"""Recurrent layers (reference nn/Recurrent.scala:27-113, nn/RNN.scala:27-90).

The reference runs an explicit Scala time-step loop with cloned cells and
truncated BPTT (Recurrent.scala:54-62, 66-107). TPU-native form: one
``lax.scan`` over the time axis — a single compiled loop whose backward is
derived by XLA, with optional gradient truncation via stop_gradient every
``bptt_truncate`` steps (the functional equivalent of bpttTruncate).

The reference snapshot has no LSTM/GRU (SURVEY.md §2.4); BASELINE.json's
"LSTM / BiRNN text classification" config makes them required, so they are
first-class cells here. Cells are fused-gate formulations: one (x,h) @ W
matmul computing all gates — the MXU-friendly layout.

Sequence layout: (B, T, F), time axis 1.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.module import Module, SimpleModule, uniform_fan_in

__all__ = ["RnnCell", "LSTMCell", "GRUCell", "Recurrent", "BiRecurrent"]


class Cell(Module):
    """A recurrent cell: ``apply(params, state, (x_t, hidden))`` returns
    ``((y_t, new_hidden), state)``. ``hidden`` is a pytree."""

    hidden_size: int

    def initial_hidden(self, batch: int, dtype=jnp.float32):
        raise NotImplementedError


class RnnCell(Cell, SimpleModule):
    """Vanilla RNN cell: act(x@Wi + h@Wh + b)
    (reference nn/RNN.scala:27-90 = ParallelTable(i2h, h2h) + CAddTable +
    activation, fused into one matmul here)."""

    def __init__(self, input_size: int, hidden_size: int, activation=jnp.tanh,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation

    def initial_hidden(self, batch: int, dtype=jnp.float32):
        return jnp.zeros((batch, self.hidden_size), dtype)

    def init(self, rng):
        k_i, k_h, k_b = jax.random.split(rng, 3)
        return {
            "w_ih": uniform_fan_in(k_i, (self.input_size, self.hidden_size),
                                   self.input_size),
            "w_hh": uniform_fan_in(k_h, (self.hidden_size, self.hidden_size),
                                   self.hidden_size),
            "bias": uniform_fan_in(k_b, (self.hidden_size,), self.hidden_size),
        }

    def _forward(self, params, x, *, training, rng):
        x_t, h = x
        h_new = self.activation(
            x_t @ params["w_ih"].astype(x_t.dtype)
            + h @ params["w_hh"].astype(x_t.dtype)
            + params["bias"].astype(x_t.dtype))
        return h_new, h_new


class LSTMCell(Cell, SimpleModule):
    """LSTM with fused 4-gate matmul and forget-gate bias 1.0.

    Natural extension of the reference's recurrent path (SURVEY.md §2.4:
    "LSTM as the natural extension"); gate order [i, f, g, o].
    """

    def __init__(self, input_size: int, hidden_size: int,
                 forget_bias: float = 1.0, name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.forget_bias = forget_bias

    def initial_hidden(self, batch: int, dtype=jnp.float32):
        z = jnp.zeros((batch, self.hidden_size), dtype)
        return (z, z)  # (h, c)

    def init(self, rng):
        k_i, k_h, k_b = jax.random.split(rng, 3)
        h4 = 4 * self.hidden_size
        return {
            "w_ih": uniform_fan_in(k_i, (self.input_size, h4), self.input_size),
            "w_hh": uniform_fan_in(k_h, (self.hidden_size, h4), self.hidden_size),
            "bias": jnp.zeros((h4,), jnp.float32),
        }

    def _forward(self, params, x, *, training, rng):
        x_t, (h, c) = x
        gates = (x_t @ params["w_ih"].astype(x_t.dtype)
                 + h @ params["w_hh"].astype(x_t.dtype)
                 + params["bias"].astype(x_t.dtype))
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f + self.forget_bias)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class GRUCell(Cell, SimpleModule):
    """GRU with fused 3-gate matmul, gate order [r, z, n]."""

    def __init__(self, input_size: int, hidden_size: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.hidden_size = input_size, hidden_size

    def initial_hidden(self, batch: int, dtype=jnp.float32):
        return jnp.zeros((batch, self.hidden_size), dtype)

    def init(self, rng):
        k_i, k_h, k_b = jax.random.split(rng, 3)
        h3 = 3 * self.hidden_size
        return {
            "w_ih": uniform_fan_in(k_i, (self.input_size, h3), self.input_size),
            "w_hh": uniform_fan_in(k_h, (self.hidden_size, h3), self.hidden_size),
            "bias": jnp.zeros((h3,), jnp.float32),
        }

    def _forward(self, params, x, *, training, rng):
        x_t, h = x
        xi = x_t @ params["w_ih"].astype(x_t.dtype) + params["bias"].astype(x_t.dtype)
        hh = h @ params["w_hh"].astype(x_t.dtype)
        xr, xz, xn = jnp.split(xi, 3, axis=-1)
        hr, hz, hn = jnp.split(hh, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        n = jnp.tanh(xn + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, h_new


class Recurrent(Module):
    """Unroll a cell over the time axis via lax.scan
    (reference nn/Recurrent.scala container).

    * ``bptt_truncate``: detach the hidden-state gradient every k steps —
      functional twin of the reference's bpttTruncate (Recurrent.scala:66-107).
      0 disables truncation (full BPTT).
    * ``return_sequences``: True -> (B, T, H) outputs (reference behavior —
      models then Select the last step); False -> last output only.
    """

    def __init__(self, cell: Cell, bptt_truncate: int = 0,
                 return_sequences: bool = True, reverse: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.cell = cell
        self.bptt_truncate = bptt_truncate
        self.return_sequences = return_sequences
        self.reverse = reverse

    def children(self):
        return (self.cell,)

    def init(self, rng):
        return {"cell": self.cell.init(rng)}

    def init_state(self):
        return {"cell": self.cell.init_state()}

    def apply(self, params, state, x, *, training=False, rng=None):
        batch = x.shape[0]
        h0 = self.cell.initial_hidden(batch, x.dtype)
        xs = jnp.swapaxes(x, 0, 1)  # (T, B, F) scan-major
        if self.reverse:
            xs = jnp.flip(xs, 0)
        cell_params = params["cell"]
        trunc = self.bptt_truncate

        def step(carry, inp):
            h, t, cell_state = carry
            x_t = inp
            if trunc > 0:
                cut = (t % trunc) == 0
                h = jax.tree_util.tree_map(
                    lambda a: lax.select(
                        jnp.broadcast_to(cut, a.shape),
                        lax.stop_gradient(a), a), h)
            step_rng = None if rng is None else jax.random.fold_in(rng, t)
            (y, h_new), cell_state = self.cell.apply(
                cell_params, cell_state, (x_t, h),
                training=training, rng=step_rng)
            return (h_new, t + 1, cell_state), y

        (_, _, final_cell_state), ys = lax.scan(
            step, (h0, jnp.asarray(0, jnp.int32), state["cell"]), xs)
        state = {"cell": final_cell_state}
        if self.reverse:
            ys = jnp.flip(ys, 0)
        if self.return_sequences:
            return jnp.swapaxes(ys, 0, 1), state  # (B, T, H)
        idx = 0 if self.reverse else -1
        return ys[idx], state


class BiRecurrent(Module):
    """Bidirectional wrapper: run two cells over opposite time directions and
    merge (concat by default, sum optional) — the BiRNN of BASELINE.json's
    text-classification config.

    ``return_sequences=False`` returns the *final state of each direction*:
    fwd output at t=T-1 concat bwd output at t=0 — each half having consumed
    the full sequence. (Slicing t=-1 of the full output would hand you a
    backward state that has seen only one token.)
    """

    def __init__(self, fwd_cell: Cell, bwd_cell: Cell, merge: str = "concat",
                 return_sequences: bool = True, name: Optional[str] = None):
        super().__init__(name)
        assert merge in ("concat", "sum")
        self.fwd = Recurrent(fwd_cell)
        self.bwd = Recurrent(bwd_cell, reverse=True)
        self.merge = merge
        self.return_sequences = return_sequences

    def children(self):
        return (self.fwd, self.bwd)

    def init(self, rng):
        k_f, k_b = jax.random.split(rng)
        return {"fwd": self.fwd.init(k_f), "bwd": self.bwd.init(k_b)}

    def init_state(self):
        return {"fwd": self.fwd.init_state(), "bwd": self.bwd.init_state()}

    def apply(self, params, state, x, *, training=False, rng=None):
        rf = None if rng is None else jax.random.fold_in(rng, 0)
        rb = None if rng is None else jax.random.fold_in(rng, 1)
        yf, sf = self.fwd.apply(params["fwd"], state["fwd"], x,
                                training=training, rng=rf)
        yb, sb = self.bwd.apply(params["bwd"], state["bwd"], x,
                                training=training, rng=rb)
        if not self.return_sequences:
            yf, yb = yf[:, -1], yb[:, 0]  # final state of each direction
        y = jnp.concatenate([yf, yb], -1) if self.merge == "concat" else yf + yb
        return y, {"fwd": sf, "bwd": sb}
