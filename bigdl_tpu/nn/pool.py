"""Pooling layers (reference nn/Spatial{Max,Average}Pooling.scala).

The reference threads each sample across the Engine pool and runs scalar
loops (SpatialMaxPooling.scala:104-196, NNPrimitive.maxPoolingForward*);
here each pooling op is one ``lax.reduce_window``, which XLA lowers to a
vectorized VPU loop with a fused backward.

NHWC layout; ``ceil_mode`` reproduces Torch's ceil output-size convention.
"""

from __future__ import annotations

import math
from typing import Optional

import jax.numpy as jnp
from jax import lax

from bigdl_tpu.core.module import SimpleModule

__all__ = ["SpatialMaxPooling", "SpatialAveragePooling",
           "TemporalMaxPooling"]


def _pool_pads(size, k, s, pad, ceil_mode):
    """Torch pooling geometry: output extent and (lo, hi) padding so that
    reduce_window reproduces floor/ceil mode exactly."""
    if ceil_mode:
        out = int(math.ceil((size + 2 * pad - k) / s)) + 1
        # Torch: last window must start inside the (padded) input
        if (out - 1) * s >= size + pad:
            out -= 1
    else:
        out = int(math.floor((size + 2 * pad - k) / s)) + 1
    needed = (out - 1) * s + k
    hi = max(0, needed - size - pad)
    return out, (pad, hi)


class _SpatialPool(SimpleModule):
    def __init__(self, kernel_w: int, kernel_h: int,
                 stride_w: Optional[int] = None, stride_h: Optional[int] = None,
                 pad_w: int = 0, pad_h: int = 0, ceil_mode: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.kernel_w, self.kernel_h = kernel_w, kernel_h
        self.stride_w = stride_w if stride_w is not None else kernel_w
        self.stride_h = stride_h if stride_h is not None else kernel_h
        self.pad_w, self.pad_h = pad_w, pad_h
        self.ceil_mode = ceil_mode
        assert self.pad_w <= self.kernel_w // 2 and self.pad_h <= self.kernel_h // 2

    def ceil(self):
        """Builder-style toggle mirroring the reference's .ceil()."""
        self.ceil_mode = True
        return self

    def _window(self, x):
        _, h, w, _ = x.shape
        _, pad_h = _pool_pads(h, self.kernel_h, self.stride_h, self.pad_h,
                              self.ceil_mode)
        _, pad_w = _pool_pads(w, self.kernel_w, self.stride_w, self.pad_w,
                              self.ceil_mode)
        dims = (1, self.kernel_h, self.kernel_w, 1)
        strides = (1, self.stride_h, self.stride_w, 1)
        pads = ((0, 0), pad_h, pad_w, (0, 0))
        return dims, strides, pads


class SpatialMaxPooling(_SpatialPool):
    """(reference nn/SpatialMaxPooling.scala, 279 LoC)"""

    def _forward(self, params, x, *, training, rng):
        dims, strides, pads = self._window(x)
        # init must be a python scalar so XLA recognizes the max-pool special
        # case (differentiable reduce_window_max primitive)
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strides, pads)


class SpatialAveragePooling(_SpatialPool):
    """(reference nn/SpatialAveragePooling.scala, 458 LoC).

    ``count_include_pad`` matches the reference default (padded zeros count
    in the divisor)."""

    def __init__(self, kernel_w, kernel_h, stride_w=None, stride_h=None,
                 pad_w=0, pad_h=0, ceil_mode=False, count_include_pad=True,
                 name=None):
        super().__init__(kernel_w, kernel_h, stride_w, stride_h, pad_w, pad_h,
                         ceil_mode, name)
        self.count_include_pad = count_include_pad

    def _forward(self, params, x, *, training, rng):
        dims, strides, pads = self._window(x)
        summed = lax.reduce_window(x, 0.0, lax.add, dims, strides, pads)
        if self.count_include_pad:
            return summed / (self.kernel_h * self.kernel_w)
        ones = jnp.ones(x.shape[1:3], x.dtype)[None, :, :, None]
        counts = lax.reduce_window(ones, 0.0, lax.add, dims, strides, pads)
        return summed / counts


class TemporalMaxPooling(SimpleModule):
    """Max-pool over the time axis of (B, T, C) sequences (Torch
    TemporalMaxPooling; the reference emulates it by reshaping through
    SpatialMaxPooling in its text-classification example)."""

    def __init__(self, k_w: int, d_w: Optional[int] = None, name=None):
        super().__init__(name)
        self.k_w = k_w
        self.d_w = d_w if d_w is not None else k_w

    def _forward(self, params, x, *, training, rng):
        return lax.reduce_window(x, -jnp.inf, lax.max,
                                 (1, self.k_w, 1), (1, self.d_w, 1),
                                 ((0, 0), (0, 0), (0, 0)))
