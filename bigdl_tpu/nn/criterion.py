"""Loss functions — parity with the reference criterion zoo
(dl/src/main/scala/com/intel/analytics/bigdl/nn/*Criterion*.scala).

Class labels are 0-based integer arrays (the reference uses Lua 1-based).
All losses are pure functions of (input, target); gradients come from
jax.grad — there are no updateGradInput implementations to maintain.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.core.criterion import Criterion

__all__ = [
    "ClassNLLCriterion", "LabelSmoothingNLLCriterion",
    "MSECriterion", "AbsCriterion", "BCECriterion",
    "CrossEntropyCriterion", "ClassSimplexCriterion", "DistKLDivCriterion",
    "CosineEmbeddingCriterion", "HingeEmbeddingCriterion",
    "L1HingeEmbeddingCriterion", "MarginCriterion", "MarginRankingCriterion",
    "MultiCriterion", "ParallelCriterion", "MultiLabelMarginCriterion",
    "MultiLabelSoftMarginCriterion", "MultiMarginCriterion",
    "SmoothL1Criterion", "SoftMarginCriterion", "L1Cost", "L1Penalty",
    "TimeDistributedCriterion",
]


def _one_hot(target, n, dtype):
    return jax.nn.one_hot(target.astype(jnp.int32), n, dtype=dtype)


class ClassNLLCriterion(Criterion):
    """Negative log-likelihood over log-probability inputs
    (reference nn/ClassNLLCriterion.scala; its per-sample threading is
    irrelevant under XLA). Input: (B, C) log-probs (e.g. from LogSoftMax);
    target: (B,) int labels. Optional per-class weights."""

    def __init__(self, weights: Optional[jnp.ndarray] = None,
                 size_average: bool = True):
        super().__init__(size_average)
        self.weights = weights

    def forward(self, input, target):
        t = target.astype(jnp.int32)
        ll = jnp.take_along_axis(input, t[:, None], axis=1)[:, 0]
        if self.weights is not None:
            w = jnp.take(self.weights.astype(input.dtype), t)
            loss = -(w * ll)
            if self.size_average:
                return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
            return jnp.sum(loss)
        return self._reduce(-ll)


class LabelSmoothingNLLCriterion(Criterion):
    """NLL over log-probs with uniform label smoothing: the target
    distribution is (1-eps) on the true class + eps/C elsewhere — the
    standard ImageNet recipe refinement (beyond the reference's
    ClassNLLCriterion; composes with LogSoftMax the same way)."""

    def __init__(self, smoothing: float = 0.1, size_average: bool = True):
        super().__init__(size_average)
        if not 0.0 <= smoothing < 1.0:
            raise ValueError(f"smoothing {smoothing} not in [0, 1)")
        self.smoothing = smoothing

    def forward(self, input, target):
        t = target.astype(jnp.int32)
        # smoothing mass eps spreads uniformly: eps * mean(logp) term
        ll_true = jnp.take_along_axis(input, t[:, None], axis=1)[:, 0]
        ll_mean = jnp.mean(input, axis=-1)
        eps = self.smoothing
        loss = -((1.0 - eps) * ll_true + eps * ll_mean)
        return self._reduce(loss)


class MSECriterion(Criterion):
    """(reference nn/MSECriterion.scala)"""

    def forward(self, input, target):
        return self._reduce(jnp.square(input - target))


class AbsCriterion(Criterion):
    """(reference nn/AbsCriterion.scala)"""

    def forward(self, input, target):
        return self._reduce(jnp.abs(input - target))


class BCECriterion(Criterion):
    """Binary cross entropy over probabilities in (0,1)
    (reference nn/BCECriterion.scala), with the standard eps clamp."""

    def __init__(self, weights: Optional[jnp.ndarray] = None,
                 size_average: bool = True, eps: float = 1e-12):
        super().__init__(size_average)
        self.weights = weights
        self.eps = eps

    def forward(self, input, target):
        p = jnp.clip(input, self.eps, 1.0 - self.eps)
        per = -(target * jnp.log(p) + (1.0 - target) * jnp.log1p(-p))
        if self.weights is not None:
            per = per * self.weights.astype(per.dtype)
        return self._reduce(per)


class CrossEntropyCriterion(Criterion):
    """LogSoftMax + ClassNLL fused (reference nn/CrossEntropyCriterion.scala).
    Input: (B, C) raw logits; target: (B,) int labels. The fused form is both
    the reference's composition and the numerically-stable XLA-friendly one."""

    def __init__(self, weights: Optional[jnp.ndarray] = None,
                 size_average: bool = True):
        super().__init__(size_average)
        self.weights = weights

    def forward(self, input, target):
        logp = jax.nn.log_softmax(input, axis=-1)
        return ClassNLLCriterion(self.weights, self.size_average).forward(
            logp, target)


class ClassSimplexCriterion(Criterion):
    """MSE against vertices of an (nClasses-1)-simplex embedding
    (reference nn/ClassSimplexCriterion.scala)."""

    def __init__(self, n_classes: int, size_average: bool = True):
        super().__init__(size_average)
        self.n_classes = n_classes
        self._simplex = self._build_simplex(n_classes)

    @staticmethod
    def _build_simplex(n: int) -> jnp.ndarray:
        # n unit-norm vertices of a regular (n-1)-simplex in R^n: center the
        # standard basis and rescale. Pairwise angles are all equal, which is
        # the property the reference's recurrence guarantees.
        import numpy as np
        v = np.eye(n) - 1.0 / n
        v /= np.linalg.norm(v[0])
        return jnp.asarray(v, jnp.float32)

    def forward(self, input, target):
        goal = jnp.take(self._simplex.astype(input.dtype),
                        target.astype(jnp.int32), axis=0)
        return self._reduce(jnp.square(input - goal))


class DistKLDivCriterion(Criterion):
    """KL(target || input) with input = log-probs
    (reference nn/DistKLDivCriterion.scala)."""

    def forward(self, input, target):
        per = jnp.where(target > 0, target * (jnp.log(jnp.maximum(target, 1e-12)) - input), 0.0)
        if self.size_average:
            # reference divides by the element count, not the batch size
            return jnp.sum(per) / input.size
        return jnp.sum(per)


class CosineEmbeddingCriterion(Criterion):
    """Table input ((x1, x2), y in {1,-1})
    (reference nn/CosineEmbeddingCriterion.scala, 195 LoC)."""

    def __init__(self, margin: float = 0.0, size_average: bool = True):
        super().__init__(size_average)
        self.margin = margin

    def forward(self, input, target):
        x1, x2 = input
        cos = jnp.sum(x1 * x2, axis=-1) / jnp.maximum(
            jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
        pos = 1.0 - cos
        neg = jnp.maximum(0.0, cos - self.margin)
        per = jnp.where(target > 0, pos, neg)
        return self._reduce(per)


class HingeEmbeddingCriterion(Criterion):
    """x if y==1 else max(0, margin - x)
    (reference nn/HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__(size_average)
        self.margin = margin

    def forward(self, input, target):
        per = jnp.where(target > 0, input,
                        jnp.maximum(0.0, self.margin - input))
        return self._reduce(per)


class L1HingeEmbeddingCriterion(Criterion):
    """Hinge embedding over L1 distance of a table (x1, x2)
    (reference nn/L1HingeEmbeddingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__(size_average)
        self.margin = margin

    def forward(self, input, target):
        x1, x2 = input
        d = jnp.sum(jnp.abs(x1 - x2), axis=-1)
        per = jnp.where(target > 0, d, jnp.maximum(0.0, self.margin - d))
        return self._reduce(per)


class MarginCriterion(Criterion):
    """Hinge loss max(0, margin - y*x) (reference nn/MarginCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__(size_average)
        self.margin = margin

    def forward(self, input, target):
        return self._reduce(jnp.maximum(0.0, self.margin - target * input))


class MarginRankingCriterion(Criterion):
    """max(0, -y*(x1-x2) + margin) over table (x1, x2)
    (reference nn/MarginRankingCriterion.scala)."""

    def __init__(self, margin: float = 1.0, size_average: bool = True):
        super().__init__(size_average)
        self.margin = margin

    def forward(self, input, target):
        x1, x2 = input
        return self._reduce(jnp.maximum(0.0, -target * (x1 - x2) + self.margin))


class MultiCriterion(Criterion):
    """Weighted sum of criterions on the same (input, target)
    (reference nn/MultiCriterion.scala)."""

    def __init__(self):
        super().__init__()
        self._items: list[tuple[Criterion, float]] = []

    def add(self, criterion: Criterion, weight: float = 1.0) -> "MultiCriterion":
        self._items.append((criterion, weight))
        return self

    def forward(self, input, target):
        return sum(w * c.forward(input, target) for c, w in self._items)


class ParallelCriterion(Criterion):
    """Weighted sum of criterions over zipped table inputs/targets
    (reference nn/ParallelCriterion.scala). repeat_target broadcasts one
    target to every branch."""

    def __init__(self, repeat_target: bool = False):
        super().__init__()
        self.repeat_target = repeat_target
        self._items: list[tuple[Criterion, float]] = []

    def add(self, criterion: Criterion, weight: float = 1.0) -> "ParallelCriterion":
        self._items.append((criterion, weight))
        return self

    def forward(self, input, target):
        total = 0.0
        for i, (c, w) in enumerate(self._items):
            t = target if self.repeat_target else target[i]
            total = total + w * c.forward(input[i], t)
        return total


class MultiLabelMarginCriterion(Criterion):
    """Multi-class multi-label margin loss
    (reference nn/MultiLabelMarginCriterion.scala, 206 LoC). Target rows list
    label indices (0-based), padded with -1 (reference pads with 0 in 1-based)."""

    def forward(self, input, target):
        b, c = input.shape
        t = target.astype(jnp.int32)
        is_label = t >= 0
        t_safe = jnp.maximum(t, 0)
        tgt_scores = jnp.take_along_axis(input, t_safe, axis=1)  # (B, L)
        # mask of classes that are targets: (B, C). Additive scatter — a
        # plain set() would let padded rows (t_safe=0) overwrite index 0.
        tgt_mask = jnp.zeros((b, c), jnp.int32).at[
            jnp.arange(b)[:, None], t_safe].add(is_label.astype(jnp.int32)) > 0
        # hinge for every (target y, non-target i): max(0, 1 - (x[y] - x[i]))
        margins = 1.0 - (tgt_scores[:, :, None] - input[:, None, :])  # (B,L,C)
        valid = is_label[:, :, None] & ~tgt_mask[:, None, :]
        per = jnp.sum(jnp.where(valid, jnp.maximum(margins, 0.0), 0.0),
                      axis=(1, 2)) / c
        return self._reduce(per)


class MultiLabelSoftMarginCriterion(Criterion):
    """Sigmoid + BCE per class (reference nn/MultiLabelSoftMarginCriterion.scala)."""

    def __init__(self, weights: Optional[jnp.ndarray] = None,
                 size_average: bool = True):
        super().__init__(size_average)
        self.weights = weights

    def forward(self, input, target):
        # numerically-stable log-sigmoid formulation
        per = -(target * jax.nn.log_sigmoid(input)
                + (1.0 - target) * jax.nn.log_sigmoid(-input))
        if self.weights is not None:
            per = per * self.weights.astype(per.dtype)
        per = jnp.mean(per, axis=-1)
        return self._reduce(per)


class MultiMarginCriterion(Criterion):
    """Multi-class margin loss (reference nn/MultiMarginCriterion.scala)."""

    def __init__(self, p: int = 1, weights: Optional[jnp.ndarray] = None,
                 margin: float = 1.0, size_average: bool = True):
        super().__init__(size_average)
        assert p in (1, 2)
        self.p, self.weights, self.margin = p, weights, margin

    def forward(self, input, target):
        b, c = input.shape
        t = target.astype(jnp.int32)
        x_y = jnp.take_along_axis(input, t[:, None], axis=1)  # (B,1)
        h = jnp.maximum(0.0, self.margin - (x_y - input))  # (B,C)
        if self.p == 2:
            h = jnp.square(h)
        if self.weights is not None:
            h = h * jnp.take(self.weights.astype(h.dtype), t)[:, None]
        not_y = jnp.arange(c)[None, :] != t[:, None]
        per = jnp.sum(jnp.where(not_y, h, 0.0), axis=1) / c
        return self._reduce(per)


class SmoothL1Criterion(Criterion):
    """Huber-style smooth L1 (reference nn/SmoothL1Criterion.scala)."""

    def forward(self, input, target):
        d = jnp.abs(input - target)
        per = jnp.where(d < 1.0, 0.5 * jnp.square(d), d - 0.5)
        return self._reduce(per)


class SoftMarginCriterion(Criterion):
    """log(1 + exp(-y*x)) (reference nn/SoftMarginCriterion.scala)."""

    def forward(self, input, target):
        return self._reduce(jax.nn.softplus(-target * input))


class TimeDistributedCriterion(Criterion):
    """Apply a per-sample criterion across a time dimension: input
    (B, T, ...) + target (B, T, ...) are flattened to (B*T, ...) and fed to
    ``base``. The sequence analog the LM/seq2seq paths need (per-token NLL
    -> perplexity); the reference's Recurrent models instead emit one
    prediction per window."""

    def __init__(self, base: Criterion):
        super().__init__()
        self.base = base

    def forward(self, input, target):
        b, t = input.shape[0], input.shape[1]
        inp = input.reshape((b * t,) + input.shape[2:])
        tgt = target.reshape((b * t,) + target.shape[2:])
        return self.base.forward(inp, tgt)


class L1Cost(Criterion):
    """sum |x| of the input, target ignored (reference nn/L1Cost.scala)."""

    def forward(self, input, target=None):
        del target
        return jnp.sum(jnp.abs(input))


class L1Penalty(Criterion):
    """L1 activation penalty (reference nn/L1Penalty.scala exists as a module
    adding a sparsity penalty to the loss; here it is expressed directly as a
    criterion term to add via MultiCriterion)."""

    def __init__(self, l1weight: float = 1.0):
        super().__init__()
        self.l1weight = l1weight

    def forward(self, input, target=None):
        del target
        return self.l1weight * jnp.sum(jnp.abs(input))
