"""Mixture-of-Experts with expert parallelism.

The reference's ``MixtureTable`` (nn/MixtureTable.scala) is a single-node
soft gating layer: every expert runs on every input and a gater blends the
outputs. :class:`MoE` keeps that dense blend available (``dense=True`` —
exact MixtureTable parity) and adds the TPU-scale sparse path the reference
never had: top-k routing with a capacity factor, einsum dispatch/combine
(one-hot matmuls — MXU-friendly, static shapes, no ragged gather), and
optional **expert parallelism**: experts' params stacked on a leading
``[E, ...]`` dim and sharded over an ``expert`` mesh axis, with tokens
moved to their experts by the all-to-all that falls out of resharding the
dispatched tensor (SURVEY.md §2.7: "Expert parallel / MoE — NO" in the
reference).

Load-balancing uses the standard auxiliary loss (mean gate fraction x mean
token fraction per expert); retrieve it from the returned state.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.core.module import Module

__all__ = ["MoE"]


class MoE(Module):
    """``MoE(expert, num_experts, d_model, top_k)``: route (batch, seq, d)
    tokens (or (batch, d)) through ``num_experts`` copies of ``expert``.

    ``dense=True`` reproduces the reference MixtureTable exactly: softmax
    gate over ALL experts, every expert computes every token, outputs
    blended. Sparse mode keeps only the top-k experts per token, bounded by
    ``capacity_factor`` (tokens above an expert's capacity are dropped —
    their residual passes through unchanged when used inside a residual
    block).
    """

    def __init__(self, expert: Module, num_experts: int, d_model: int,
                 top_k: int = 1, capacity_factor: float = 1.25,
                 dense: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self._expert_state = expert.init_state()
        if jax.tree_util.tree_leaves(self._expert_state):
            raise ValueError("MoE experts must be stateless")
        self.expert = expert
        self.num_experts = num_experts
        self.d_model = d_model
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.dense = dense

    def init(self, rng):
        ks = jax.random.split(rng, self.num_experts + 1)
        experts = [self.expert.init(k) for k in ks[1:]]
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *experts)
        gate = jax.random.normal(ks[0], (self.d_model, self.num_experts),
                                 jnp.float32) * 0.02
        return {"gate": gate, "experts": stacked}

    def init_state(self):
        # aux_loss is exposed through state so training loops can add it
        return {"aux_loss": jnp.zeros((), jnp.float32)}

    # ----------------------------------------------------------------- apply
    def _run_experts(self, p_experts, xs, training, rng):
        """vmap the expert over its stacked params: xs [E, C, d] -> [E, C, d'].
        Each expert gets its own rng stream (split per expert) so dropout
        masks are decorrelated across experts."""
        if rng is None:
            def one(pb, xb):
                y, _ = self.expert.apply(pb, self._expert_state, xb,
                                         training=training)
                return y
            return jax.vmap(one)(p_experts, xs)

        keys = jax.random.split(rng, self.num_experts)

        def one_k(pb, xb, k):
            y, _ = self.expert.apply(pb, self._expert_state, xb,
                                     training=training, rng=k)
            return y
        return jax.vmap(one_k)(p_experts, xs, keys)

    def apply(self, params, state, x, *, training=False, rng=None):
        orig_shape = x.shape
        tokens = x.reshape(-1, orig_shape[-1])  # [T, d]
        t = tokens.shape[0]
        e = self.num_experts
        logits = tokens @ params["gate"].astype(tokens.dtype)  # [T, E]
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

        if self.dense:
            # exact MixtureTable semantics: blend every expert's output
            ys = self._run_experts(params["experts"],
                                   jnp.broadcast_to(tokens, (e,) + tokens.shape),
                                   training, rng)  # [E, T, d']
            out = jnp.einsum("te,etd->td", probs.astype(ys.dtype), ys)
            new_state = {"aux_loss": jnp.zeros((), jnp.float32)}
            return out.reshape(orig_shape[:-1] + out.shape[-1:]), new_state

        # ---- sparse top-k routing with capacity ----
        cap = max(1, int(self.capacity_factor * t * self.top_k / e))
        gate_vals, gate_idx = jax.lax.top_k(probs, self.top_k)  # [T, k]
        if self.top_k > 1:
            gate_vals = gate_vals / jnp.maximum(
                gate_vals.sum(-1, keepdims=True), 1e-9)
        # top-1 keeps the RAW softmax probability (Switch): renormalizing
        # would make the combine weight identically 1, whose gradient wrt
        # the gate logits is zero — the router would never learn from the
        # task loss

        # position of each (token, k) inside its expert's capacity buffer
        onehot = jax.nn.one_hot(gate_idx, e, dtype=jnp.int32)  # [T, k, E]
        flat = onehot.reshape(t * self.top_k, e)
        pos = jnp.cumsum(flat, axis=0) - flat  # arrival order per expert
        pos = (pos * flat).sum(-1).reshape(t, self.top_k)
        keep = pos < cap

        # per-choice dispatch [T, k, E, C]: k-th choice of token t occupies
        # slot (expert gate_idx[t,k], position pos[t,k]) when kept
        disp_k = (jax.nn.one_hot(gate_idx, e, dtype=tokens.dtype)[..., None]
                  * jax.nn.one_hot(pos, cap, dtype=tokens.dtype)[:, :, None, :]
                  * keep[..., None, None].astype(tokens.dtype))
        disp = disp_k.sum(1)                               # [T, E, C] 0/1
        xs = jnp.einsum("tec,td->ecd", disp, tokens)       # [E, C, d]
        ys = self._run_experts(params["experts"], xs, training, rng)
        combine = (disp_k * gate_vals[..., None, None]).sum(1).astype(ys.dtype)
        out = jnp.einsum("tec,ecd->td", combine, ys)

        # load-balance aux loss (Switch-style): E * sum_e f_e * P_e
        frac_tokens = (jax.nn.one_hot(gate_idx[:, 0], e)
                       .mean(0))               # fraction routed (top-1)
        frac_probs = probs.mean(0)
        aux = e * jnp.sum(frac_tokens * frac_probs)
        new_state = {"aux_loss": aux}
        return out.reshape(orig_shape[:-1] + out.shape[-1:]), new_state

    # ------------------------------------------------------------- placement
    def place_expert_parallel(self, mesh: Mesh, params,
                              axis: str = "expert"):
        """Shard the stacked expert params over the expert axis; the gate
        stays replicated. Under jit, XLA inserts the all-to-all that moves
        dispatched tokens to their expert's device."""
        ex = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, NamedSharding(mesh, P(axis))),
            params["experts"])
        gate = jax.device_put(params["gate"], NamedSharding(mesh, P()))
        return {"gate": gate, "experts": ex}
