"""Activation layers — parity with the reference's activation zoo
(dl/src/main/scala/com/intel/analytics/bigdl/nn/{ReLU,Tanh,...}.scala).

Every one of these is a fused elementwise op under XLA; none of the
reference's intra-layer threading (e.g. Threshold.scala:72-336) is needed —
the compiler fuses these into neighboring matmuls/convs on the MXU/VPU path.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import ElementwiseModule, SimpleModule, Module

__all__ = [
    "ReLU", "ReLU6", "PReLU", "RReLU", "LeakyReLU", "ELU", "Threshold",
    "Tanh", "TanhShrink", "Sigmoid", "LogSigmoid", "HardTanh", "HardShrink",
    "SoftShrink", "SoftPlus", "SoftSign", "SoftMax", "SoftMin", "LogSoftMax",
    "Power", "Square", "Sqrt", "Abs", "Exp", "Log", "Clamp",
    "GradientReversal",
]


class ReLU(ElementwiseModule):
    """max(x, 0) (reference nn/ReLU.scala; ip=true has no meaning functionally)."""

    def _fn(self, x):
        return jax.nn.relu(x)


class ReLU6(ElementwiseModule):
    """min(max(x,0),6) (reference nn/ReLU6.scala)."""

    def _fn(self, x):
        return jax.nn.relu6(x)


class Threshold(ElementwiseModule):
    """x if x > th else v (reference nn/Threshold.scala:403-LoC file)."""

    def __init__(self, th: float = 1e-6, v: float = 0.0, name=None):
        super().__init__(name)
        self.th, self.v = th, v

    def _fn(self, x):
        return jnp.where(x > self.th, x, jnp.asarray(self.v, x.dtype))


class PReLU(SimpleModule):
    """Parametric ReLU with learned per-channel (or shared) slope
    (reference nn/PReLU.scala, 314 LoC). ``n_output_plane=0`` shares one
    scalar; otherwise one slope per channel, channels last (NHWC)."""

    def __init__(self, n_output_plane: int = 0, name=None):
        super().__init__(name)
        self.n_output_plane = n_output_plane

    def init(self, rng):
        n = max(1, self.n_output_plane)
        return {"weight": jnp.full((n,), 0.25, jnp.float32)}

    def _forward(self, params, x, *, training, rng):
        w = params["weight"].astype(x.dtype)
        if self.n_output_plane == 0:
            w = w[0]
        # channels-last broadcast: (..., C) * (C,)
        return jnp.where(x >= 0, x, w * x)


class RReLU(SimpleModule):
    """Randomized leaky ReLU (reference nn/RReLU.scala): slope ~ U(lower,upper)
    per element in training, fixed mean slope at inference."""

    def __init__(self, lower: float = 1.0 / 8, upper: float = 1.0 / 3, name=None):
        super().__init__(name)
        self.lower, self.upper = lower, upper

    def _forward(self, params, x, *, training, rng):
        if training:
            if rng is None:
                raise ValueError("RReLU needs an rng in training mode")
            a = jax.random.uniform(
                rng, x.shape, x.dtype, minval=self.lower, maxval=self.upper
            )
        else:
            a = jnp.asarray((self.lower + self.upper) / 2, x.dtype)
        return jnp.where(x >= 0, x, a * x)


class LeakyRelUBase(ElementwiseModule):
    negval = 0.01

    def _fn(self, x):
        return jnp.where(x >= 0, x, jnp.asarray(self.negval, x.dtype) * x)


class LeakyReLU(LeakyRelUBase):
    """(reference nn/LeakyReLU.scala)"""

    def __init__(self, negval: float = 0.01, name=None):
        super().__init__(name)
        self.negval = negval


class ELU(ElementwiseModule):
    """(reference nn/ELU.scala)"""

    def __init__(self, alpha: float = 1.0, name=None):
        super().__init__(name)
        self.alpha = alpha

    def _fn(self, x):
        safe = jnp.where(x > 0, 0.0, x)  # avoid overflow in exp for large x
        return jnp.where(x > 0, x, self.alpha * jnp.expm1(safe))


class Tanh(ElementwiseModule):
    def _fn(self, x):
        return jnp.tanh(x)


class TanhShrink(ElementwiseModule):
    """x - tanh(x) (reference nn/TanhShrink.scala)."""

    def _fn(self, x):
        return x - jnp.tanh(x)


class Sigmoid(ElementwiseModule):
    def _fn(self, x):
        return jax.nn.sigmoid(x)


class LogSigmoid(ElementwiseModule):
    def _fn(self, x):
        return jax.nn.log_sigmoid(x)


class HardTanh(ElementwiseModule):
    """clip(x, min_value, max_value) (reference nn/HardTanh.scala, 213 LoC)."""

    def __init__(self, min_value: float = -1.0, max_value: float = 1.0, name=None):
        super().__init__(name)
        assert max_value > min_value
        self.min_value, self.max_value = min_value, max_value

    def _fn(self, x):
        return jnp.clip(x, self.min_value, self.max_value)


class Clamp(HardTanh):
    """Alias of HardTanh with int bounds (reference nn/Clamp.scala)."""

    def __init__(self, min_value: int, max_value: int, name=None):
        super().__init__(float(min_value), float(max_value), name)


class HardShrink(ElementwiseModule):
    """x if |x| > lambda else 0 (reference nn/HardShrink.scala)."""

    def __init__(self, lam: float = 0.5, name=None):
        super().__init__(name)
        self.lam = lam

    def _fn(self, x):
        return jnp.where(jnp.abs(x) > self.lam, x, jnp.zeros_like(x))


class SoftShrink(ElementwiseModule):
    """sign(x)*max(|x|-lambda, 0) (reference nn/SoftShrink.scala)."""

    def __init__(self, lam: float = 0.5, name=None):
        super().__init__(name)
        self.lam = lam

    def _fn(self, x):
        return jnp.sign(x) * jnp.maximum(jnp.abs(x) - self.lam, 0.0)


class SoftPlus(ElementwiseModule):
    """log(1+exp(beta*x))/beta with linear tail (reference nn/SoftPlus.scala)."""

    def __init__(self, beta: float = 1.0, name=None):
        super().__init__(name)
        self.beta = beta

    def _fn(self, x):
        return jax.nn.softplus(self.beta * x) / self.beta


class SoftSign(ElementwiseModule):
    """x / (1+|x|) (reference nn/SoftSign.scala)."""

    def _fn(self, x):
        return jax.nn.soft_sign(x)


class SoftMax(ElementwiseModule):
    """Softmax over the last axis (reference nn/SoftMax.scala operates over
    the feature dim; here features are axis -1)."""

    def __init__(self, axis: int = -1, name=None):
        super().__init__(name)
        self.axis = axis

    def _fn(self, x):
        return jax.nn.softmax(x, axis=self.axis)


class SoftMin(ElementwiseModule):
    """softmax(-x) (reference nn/SoftMin.scala)."""

    def __init__(self, axis: int = -1, name=None):
        super().__init__(name)
        self.axis = axis

    def _fn(self, x):
        return jax.nn.softmax(-x, axis=self.axis)


class LogSoftMax(ElementwiseModule):
    """Numerically-stable log-softmax (reference nn/LogSoftMax.scala)."""

    def __init__(self, axis: int = -1, name=None):
        super().__init__(name)
        self.axis = axis

    def _fn(self, x):
        return jax.nn.log_softmax(x, axis=self.axis)


class Power(ElementwiseModule):
    """(shift + scale*x)^power (reference nn/Power.scala)."""

    def __init__(self, power: float, scale: float = 1.0, shift: float = 0.0, name=None):
        super().__init__(name)
        self.power, self.scale, self.shift = power, scale, shift

    def _fn(self, x):
        return jnp.power(self.shift + self.scale * x, self.power)


class Square(ElementwiseModule):
    def _fn(self, x):
        return jnp.square(x)


class Sqrt(ElementwiseModule):
    def _fn(self, x):
        return jnp.sqrt(x)


class Abs(ElementwiseModule):
    def _fn(self, x):
        return jnp.abs(x)


class Exp(ElementwiseModule):
    def _fn(self, x):
        return jnp.exp(x)


class Log(ElementwiseModule):
    def _fn(self, x):
        return jnp.log(x)


@jax.custom_vjp
def _grad_reverse(x, lam):
    return x


def _grad_reverse_fwd(x, lam):
    return x, lam


def _grad_reverse_bwd(lam, g):
    return (-lam * g, None)


_grad_reverse.defvjp(_grad_reverse_fwd, _grad_reverse_bwd)


class GradientReversal(SimpleModule):
    """Identity forward, -lambda * grad backward (reference
    nn/GradientReversal.scala) — implemented as a custom VJP, the JAX analog
    of overriding updateGradInput."""

    def __init__(self, lam: float = 1.0, name=None):
        super().__init__(name)
        self.lam = lam

    def _forward(self, params, x, *, training, rng):
        del params, training, rng
        return _grad_reverse(x, jnp.asarray(self.lam, x.dtype))
