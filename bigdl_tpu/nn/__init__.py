"""bigdl_tpu.nn — the layer zoo (parity inventory: SURVEY.md §2.4)."""

from bigdl_tpu.core.module import (
    Module, Container, Sequential, Identity, Lambda,
)
from bigdl_tpu.nn.activation import *  # noqa: F401,F403
from bigdl_tpu.nn.linear import *  # noqa: F401,F403
from bigdl_tpu.nn.conv import *  # noqa: F401,F403
from bigdl_tpu.nn.pool import *  # noqa: F401,F403
from bigdl_tpu.nn.norm import *  # noqa: F401,F403
from bigdl_tpu.nn.structural import *  # noqa: F401,F403
from bigdl_tpu.nn.recurrent import *  # noqa: F401,F403
from bigdl_tpu.nn.attention import *  # noqa: F401,F403
from bigdl_tpu.nn.moe import *  # noqa: F401,F403
from bigdl_tpu.nn.criterion import *  # noqa: F401,F403
