"""Attention and transformer layers.

The reference snapshot predates attention entirely (SURVEY.md §5
"Long-context / sequence parallelism: Absent" — its only sequence model is
the scanned RNN, nn/Recurrent.scala:27-113). This module is therefore
designed TPU-first rather than for parity: batched bf16-friendly matmuls
shaped for the MXU, a pluggable inner attention function so the same layer
can run

* the plain XLA path (``dot_product_attention`` below — XLA fuses the
  softmax chain),
* a Pallas flash-attention kernel (``bigdl_tpu.ops.flash_attention``), or
* ring attention over a ``seq`` mesh axis
  (``bigdl_tpu.parallel.sequence.ring_attention``) for long-context
  sequence parallelism.

Shapes: inputs are (batch, seq, d_model); heads are folded into the batch
dimension for the two attention matmuls so they are large MXU-friendly
contractions.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import (
    Module,
    SimpleModule,
    Sequential,
    xavier_uniform,
)

__all__ = [
    "dot_product_attention",
    "make_segment_mask",
    "LayerNorm",
    "MultiHeadAttention",
    "PositionalEncoding",
    "TransformerEncoderLayer",
    "TransformerEncoder",
]

AttnFn = Callable[..., jax.Array]

_NEG_INF = -1e30  # finite mask value: a fully-masked query row softmaxes to
                  # uniform-over-garbage instead of NaN, and (below) its
                  # probabilities are re-zeroed explicitly


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Scaled dot-product attention. q,k,v: (..., seq, head_dim).

    Softmax statistics are computed in fp32 regardless of input dtype
    (bf16-safe), the matmuls stay in the input dtype for the MXU.
    """
    head_dim = q.shape[-1]
    scale = 1.0 / math.sqrt(head_dim)
    # bf16 inputs: multiply on the MXU in bf16, accumulate in fp32
    logits = jnp.einsum("...qd,...kd->...qk", q, k,
                        preferred_element_type=jnp.float32) * scale
    valid = None
    if causal:
        q_len, k_len = logits.shape[-2], logits.shape[-1]
        # bottom-right aligned (flash convention): with q_len < k_len the
        # queries are the suffix of the key sequence, so query i sees keys
        # <= (k_len - q_len) + i
        offset = k_len - q_len
        valid = (jnp.arange(q_len)[:, None] + offset
                 >= jnp.arange(k_len)[None, :])
    if mask is not None:
        valid = mask if valid is None else jnp.logical_and(valid, mask)
    if valid is not None:
        logits = jnp.where(valid, logits, _NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    if valid is not None:
        # zero out fully-masked rows rather than leaving uniform noise
        weights = jnp.where(valid, weights, 0.0)
    return jnp.einsum("...qk,...kd->...qd", weights.astype(q.dtype), v)


def make_segment_mask(segments_q, segments_k=None):
    """Block-diagonal attention mask for packed sequences: several short
    documents concatenated into one training row attend only within
    their own segment (the XLA/TPU-friendly alternative to ragged
    batching — static shapes, no padding waste). ``segments``: (b, s)
    int ids, equal id = same document; id 0 marks padding and attends to
    nothing. Returns a (b, 1, s_q, s_k) bool mask (True = attend) that
    threads through ``MultiHeadAttention``/``TransformerEncoder`` as the
    mask input; combine with ``causal=True`` for packed causal LM
    training. Positions restart per document only if the model's
    position encoding is relative (RoPE applies per absolute offset —
    exact packing equivalence holds for unpositioned encoders and
    approximately for long-context relative schemes).
    """
    if segments_k is None:
        segments_k = segments_q
    same = segments_q[:, :, None] == segments_k[:, None, :]
    live = (segments_q != 0)[:, :, None] & (segments_k != 0)[:, None, :]
    return (same & live)[:, None, :, :]


class LayerNorm(SimpleModule):
    """Layer normalization over the last dimension.

    Not in the reference (its normalizations are batch/spatial —
    nn/BatchNormalization.scala); required substrate for transformers.
    Statistics in fp32, output cast back to the input dtype.
    """

    def __init__(self, dim: int, eps: float = 1e-5,
                 name: Optional[str] = None):
        super().__init__(name)
        self.dim = dim
        self.eps = eps

    def init(self, rng):
        del rng
        return {"weight": jnp.ones((self.dim,)),
                "bias": jnp.zeros((self.dim,))}

    def _forward(self, params, x, *, training, rng):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * jax.lax.rsqrt(var + self.eps)
        y = y * params["weight"] + params["bias"]
        return y.astype(x.dtype)


def rope_tables(max_len: int, dim: int, base: float = 10000.0):
    """cos/sin tables for rotary position embeddings (RoPE), NeoX-style
    half-split pairing: dims [0:dim/2] rotate with [dim/2:dim]."""
    import numpy as np

    inv = 1.0 / (base ** (np.arange(0, dim, 2).astype(np.float32) / dim))
    ang = np.arange(max_len).astype(np.float32)[:, None] * inv[None, :]
    return np.cos(ang), np.sin(ang)  # each (max_len, dim/2)


def apply_rope(x, cos, sin):
    """Rotate (..., s, d) by per-position tables (s, d/2)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    c = cos.astype(x.dtype)
    s = sin.astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


class MultiHeadAttention(SimpleModule):
    """Multi-head (self- or cross-) attention.

    ``attn_impl`` swaps the inner attention: None -> plain XLA path;
    "flash" -> Pallas flash-attention kernel; or any callable with the
    ``dot_product_attention`` signature (ring attention passes a shard_map'd
    callable here). ``rope=True`` rotates q/k by position (RoPE) instead
    of relying on an additive encoding — relative-position attention that
    extrapolates better at long context; self-attention only.
    """

    def __init__(
        self,
        d_model: int,
        num_heads: int,
        causal: bool = False,
        attn_impl: Optional[AttnFn | str] = None,
        num_kv_heads: Optional[int] = None,
        rope: bool = False,
        rope_max_len: int = 8192,
        param_dtype=jnp.float32,
        name: Optional[str] = None,
    ):
        super().__init__(name)
        if d_model % num_heads:
            raise ValueError(f"d_model {d_model} not divisible by "
                             f"num_heads {num_heads}")
        self.d_model = d_model
        self.num_heads = num_heads
        self.head_dim = d_model // num_heads
        # grouped-query attention: K/V projected to num_kv_heads heads
        # and broadcast over num_heads//num_kv_heads query groups
        # (num_kv_heads=1 is multi-query attention); shrinks the KV cache
        # and the K/V projection FLOPs by the group factor
        self.num_kv_heads = num_kv_heads or num_heads
        if num_heads % self.num_kv_heads:
            raise ValueError(f"num_heads {num_heads} not divisible by "
                             f"num_kv_heads {self.num_kv_heads}")
        self.causal = causal
        self.param_dtype = param_dtype
        self.rope = rope
        if rope:
            if self.head_dim % 2:
                raise ValueError("RoPE needs an even head_dim")
            self._rope_cos, self._rope_sin = rope_tables(
                rope_max_len, self.head_dim)
        if attn_impl == "flash":
            from bigdl_tpu.ops import flash_attention
            attn_impl = flash_attention
        elif attn_impl == "blockwise":
            from bigdl_tpu.ops import blockwise_attention
            attn_impl = blockwise_attention
        self.attn_fn: AttnFn = attn_impl or dot_product_attention
        import inspect
        try:
            self._attn_takes_segments = "segments" in inspect.signature(
                self.attn_fn).parameters
        except (TypeError, ValueError):
            self._attn_takes_segments = False

    def init(self, rng):
        ks = jax.random.split(rng, 4)
        d = self.d_model
        dkv = self.num_kv_heads * self.head_dim
        mk = lambda k, dout: xavier_uniform(k, (d, dout), d, dout,
                                            self.param_dtype)
        return {
            "wq": mk(ks[0], d), "wk": mk(ks[1], dkv), "wv": mk(ks[2], dkv),
            "wo": mk(ks[3], d),
            "bq": jnp.zeros((d,), self.param_dtype),
            "bk": jnp.zeros((dkv,), self.param_dtype),
            "bv": jnp.zeros((dkv,), self.param_dtype),
            "bo": jnp.zeros((d,), self.param_dtype),
        }

    def _split_heads(self, x, n_heads: Optional[int] = None):
        b, s, f = x.shape
        n = n_heads or self.num_heads
        return x.reshape(b, s, n, f // n).transpose(0, 2, 1, 3)

    def _expand_kv(self, kv):
        """Broadcast (b, n_kv, s, d) K/V over the query groups."""
        g = self.num_heads // self.num_kv_heads
        if g == 1:
            return kv
        return jnp.repeat(kv, g, axis=1)

    def _rope(self, x, pos0):
        """Rotate (b, h, s, d) starting at absolute position ``pos0``."""
        s = x.shape[-2]
        cos = jax.lax.dynamic_slice_in_dim(
            jnp.asarray(self._rope_cos), pos0, s, 0)
        sin = jax.lax.dynamic_slice_in_dim(
            jnp.asarray(self._rope_sin), pos0, s, 0)
        return apply_rope(x, cos, sin)

    def _merge_heads(self, x):
        b, h, s, d = x.shape
        return x.transpose(0, 2, 1, 3).reshape(b, s, h * d)

    def _forward(self, params, x, *, training, rng):
        # input forms: tensor (self-attention); (q_in, kv_in) (cross);
        # (q_in, kv_in, mask) where mask is (b, s_k) key-padding bool, a
        # broadcastable (b|1, h|1, s_q, s_k) attention mask, or — when
        # integer-dtyped — (b, s) packed-document segment ids (the flash
        # kernel applies those in-kernel; other impls get the expanded
        # block-diagonal mask)
        mask = None
        segments = None
        if isinstance(x, (tuple, list)):
            q_in, kv_in = x[0], x[1]
            mask = x[2] if len(x) > 2 else None
        else:
            q_in = kv_in = x
        if mask is not None and jnp.issubdtype(mask.dtype, jnp.integer):
            segments, mask = mask, None
            if not self._attn_takes_segments:
                mask = make_segment_mask(segments)
                segments = None
        dt = q_in.dtype
        q = q_in @ params["wq"].astype(dt) + params["bq"].astype(dt)
        k = kv_in @ params["wk"].astype(dt) + params["bk"].astype(dt)
        v = kv_in @ params["wv"].astype(dt) + params["bv"].astype(dt)
        q = self._split_heads(q)
        k = self._split_heads(k, self.num_kv_heads)
        v = self._split_heads(v, self.num_kv_heads)
        if self.rope:
            if q_in is not kv_in:
                raise ValueError("RoPE supports self-attention only")
            q = self._rope(q, 0)
            k = self._rope(k, 0)
        k, v = self._expand_kv(k), self._expand_kv(v)
        if mask is not None and mask.ndim == 2:  # (b, s_k) key-padding
            mask = mask[:, None, None, :]
        if segments is not None:
            o = self.attn_fn(q, k, v, causal=self.causal,
                             segments=segments)
        else:
            o = self.attn_fn(q, k, v, causal=self.causal, mask=mask)
        o = self._merge_heads(o)
        return o @ params["wo"].astype(dt) + params["bo"].astype(dt)

    # ----------------------------------------------- autoregressive decode
    def init_cache(self, batch: int, max_len: int, dtype=jnp.float32):
        # GQA: the cache stores only num_kv_heads heads — the memory win
        shape = (batch, self.num_kv_heads, max_len, self.head_dim)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def _qkv(self, params, x):
        dt = x.dtype
        q = x @ params["wq"].astype(dt) + params["bq"].astype(dt)
        k = x @ params["wk"].astype(dt) + params["bk"].astype(dt)
        v = x @ params["wv"].astype(dt) + params["bv"].astype(dt)
        return (self._split_heads(q),
                self._split_heads(k, self.num_kv_heads),
                self._split_heads(v, self.num_kv_heads))

    def prefill(self, params, x, cache):
        """Full-prompt forward that also writes K/V into the cache
        (positions 0..s-1; RoPE-rotated K is what gets cached, so decode
        steps never re-rotate history). Returns (out, cache)."""
        q, k, v = self._qkv(params, x)
        if self.rope:
            q, k = self._rope(q, 0), self._rope(k, 0)
        o = self.attn_fn(q, self._expand_kv(k), self._expand_kv(v),
                         causal=True, mask=None)
        cache = {
            "k": jax.lax.dynamic_update_slice(
                cache["k"], k.astype(cache["k"].dtype), (0, 0, 0, 0)),
            "v": jax.lax.dynamic_update_slice(
                cache["v"], v.astype(cache["v"].dtype), (0, 0, 0, 0)),
        }
        dt = x.dtype
        o = self._merge_heads(o)
        return o @ params["wo"].astype(dt) + params["bo"].astype(dt), cache

    def decode_step(self, params, x, cache, idx):
        """One-token step: x (b, 1, d), ``idx`` = tokens already cached.
        Appends this token's K/V at ``idx`` and attends over 0..idx."""
        return self.decode_chunk(params, x, cache, idx)

    def decode_chunk(self, params, x, cache, idx):
        """m-token step: x (b, m, d) at absolute positions idx..idx+m-1.
        Writes the chunk's K/V at those positions FIRST, then attends
        causally within the chunk (row i sees cache 0..idx+i), so the
        chunk is exactly m sequential decode_steps fused into one
        dispatch — the primitive speculative verification and
        prefix-cache suffix prefill are built on. Each query row's
        scores/softmax/weighted-sum are row-independent, so the m=1
        case IS decode_step (and per-row results match the sequential
        path bit-for-bit on the dense CPU path — pinned in tests).
        Caller must keep idx + m <= cache length: dynamic_update_slice
        clamps out-of-range starts, which would silently shift the
        write window."""
        q, k, v = self._qkv(params, x)
        if self.rope:
            q, k = self._rope(q, idx), self._rope(k, idx)
        kc = jax.lax.dynamic_update_slice(
            cache["k"], k.astype(cache["k"].dtype), (0, 0, idx, 0))
        vc = jax.lax.dynamic_update_slice(
            cache["v"], v.astype(cache["v"].dtype), (0, 0, idx, 0))
        ke = self._expand_kv(kc)
        ve = self._expand_kv(vc)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, ke.astype(q.dtype),
                       preferred_element_type=jnp.float32)
        s = s / (self.head_dim ** 0.5)
        m = x.shape[1]
        rows = idx + jnp.arange(m)[None, None, :, None]
        live = jnp.arange(ke.shape[2])[None, None, None, :] <= rows
        s = jnp.where(live, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype),
                       ve.astype(q.dtype),
                       preferred_element_type=jnp.float32).astype(x.dtype)
        dt = x.dtype
        o = self._merge_heads(o)
        return (o @ params["wo"].astype(dt) + params["bo"].astype(dt),
                {"k": kc, "v": vc})


class PositionalEncoding(SimpleModule):
    """Sinusoidal positional encoding added to (batch, seq, d_model).

    The table is precomputed once for ``max_len`` positions (a trace-time
    constant — XLA folds the slice); sequences longer than ``max_len``
    raise at trace time.
    """

    def __init__(self, d_model: int, max_len: int = 4096,
                 name: Optional[str] = None):
        super().__init__(name)
        self.d_model = d_model
        self.max_len = max_len
        import numpy as np
        pos = np.arange(max_len)[:, None].astype(np.float32)
        dim = np.arange(0, d_model, 2).astype(np.float32)
        angle = pos / np.power(10000.0, dim / d_model)  # (max_len, ceil(d/2))
        pe = np.zeros((max_len, d_model), np.float32)
        pe[:, 0::2] = np.sin(angle)
        pe[:, 1::2] = np.cos(angle)[:, : d_model // 2]
        self._table = pe

    def _forward(self, params, x, *, training, rng):
        del params, training, rng
        seq = x.shape[-2]
        if seq > self.max_len:
            raise ValueError(f"sequence length {seq} exceeds "
                             f"max_len {self.max_len}")
        return x + jnp.asarray(self._table[:seq]).astype(x.dtype)


class TransformerEncoderLayer(Module):
    """Pre-LN transformer block: x + MHA(LN(x)); x + MLP(LN(x)).

    Pre-LN (not the post-LN of the original paper) — trains stably without
    warmup, the standard choice for TPU LLM stacks.
    """

    def __init__(
        self,
        d_model: int,
        num_heads: int,
        d_ff: Optional[int] = None,
        causal: bool = False,
        dropout: float = 0.0,
        attn_impl: Optional[AttnFn | str] = None,
        num_kv_heads: Optional[int] = None,
        rope: bool = False,
        rope_max_len: int = 8192,
        name: Optional[str] = None,
    ):
        super().__init__(name)
        d_ff = d_ff or 4 * d_model
        self.d_model, self.d_ff = d_model, d_ff
        from bigdl_tpu.nn.structural import Dropout
        self.dropout = dropout
        self.drop = Dropout(dropout) if dropout > 0.0 else None
        self.ln1 = LayerNorm(d_model)
        self.ln2 = LayerNorm(d_model)
        self.mha = MultiHeadAttention(d_model, num_heads, causal=causal,
                                      attn_impl=attn_impl,
                                      num_kv_heads=num_kv_heads,
                                      rope=rope, rope_max_len=rope_max_len)
        # keep the MLP as explicit params (not a Sequential) for stable
        # checkpoint keys
        self._mlp_dims = (d_model, d_ff)

    def children(self):
        return (self.ln1, self.mha, self.ln2)

    def init(self, rng):
        ks = jax.random.split(rng, 5)
        d, f = self._mlp_dims
        return {
            "ln1": self.ln1.init(ks[0]),
            "mha": self.mha.init(ks[1]),
            "ln2": self.ln2.init(ks[2]),
            "w1": xavier_uniform(ks[3], (d, f), d, f),
            "b1": jnp.zeros((f,)),
            "w2": xavier_uniform(ks[4], (f, d), f, d),
            "b2": jnp.zeros((d,)),
        }

    def apply(self, params, state, x, *, training=False, rng=None):
        # (x, mask) threads a key-padding mask through the stack; the same
        # form is returned so Sequential/TransformerEncoder chains it
        mask = None
        if isinstance(x, (tuple, list)):
            x, mask = x[0], x[1]
        dt = x.dtype
        h = self.ln1.forward(params["ln1"], x)
        h = self.mha.forward(params["mha"],
                             h if mask is None else (h, h, mask),
                             training=training, rng=rng)
        if self.drop is not None:
            rng, k = (jax.random.split(rng) if rng is not None
                      else (None, None))
            h = self.drop.forward({}, h, training=training, rng=k)
        x = x + h
        h = self.ln2.forward(params["ln2"], x)
        h = h @ params["w1"].astype(dt) + params["b1"].astype(dt)
        h = jax.nn.gelu(h)
        h = h @ params["w2"].astype(dt) + params["b2"].astype(dt)
        if self.drop is not None:
            rng, k = (jax.random.split(rng) if rng is not None
                      else (None, None))
            h = self.drop.forward({}, h, training=training, rng=k)
        y = x + h
        return (y if mask is None else (y, mask)), state

    # ----------------------------------------------- autoregressive decode
    def init_cache(self, batch, max_len, dtype=jnp.float32):
        return self.mha.init_cache(batch, max_len, dtype)

    def _mlp(self, params, x):
        dt = x.dtype
        h = self.ln2.forward(params["ln2"], x)
        h = h @ params["w1"].astype(dt) + params["b1"].astype(dt)
        h = jax.nn.gelu(h)
        return x + (h @ params["w2"].astype(dt) + params["b2"].astype(dt))

    def prefill(self, params, x, cache):
        h = self.ln1.forward(params["ln1"], x)
        h, cache = self.mha.prefill(params["mha"], h, cache)
        return self._mlp(params, x + h), cache

    def decode_step(self, params, x, cache, idx):
        h = self.ln1.forward(params["ln1"], x)
        h, cache = self.mha.decode_step(params["mha"], h, cache, idx)
        return self._mlp(params, x + h), cache

    def decode_chunk(self, params, x, cache, idx):
        h = self.ln1.forward(params["ln1"], x)
        h, cache = self.mha.decode_chunk(params["mha"], h, cache, idx)
        return self._mlp(params, x + h), cache


class TransformerEncoder(Sequential):
    """Stack of encoder layers with optional remat.

    ``remat`` wraps each layer in ``jax.checkpoint`` — the HBM-for-FLOPs
    trade that long-context training needs. Accepts:

    * ``False`` — no remat (default);
    * ``True`` / ``"full"`` — save nothing, recompute the whole layer in
      the backward (max HBM savings, ~1/3 extra FLOPs);
    * ``"dots"`` — ``jax.checkpoint_policies.dots_with_no_batch_dims_
      saveable``: matmul outputs stay resident, only elementwise/softmax
      recompute. On TPU this is usually the better point: the MXU work
      (the expensive part) is not redone, while the bandwidth-bound
      intermediates (which XLA refuses to keep anyway once HBM is tight)
      are. The reference has no analog — its graph holds every
      intermediate by design (Scala Module.output fields).
    """

    _REMAT_POLICIES = {
        "full": None,   # jax.checkpoint default: nothing saveable
        "dots": "dots_with_no_batch_dims_saveable",
    }

    def __init__(self, num_layers: int, d_model: int, num_heads: int,
                 d_ff: Optional[int] = None, causal: bool = False,
                 dropout: float = 0.0,
                 attn_impl: Optional[AttnFn | str] = None,
                 remat: bool = False,
                 num_kv_heads: Optional[int] = None,
                 rope: bool = False, rope_max_len: int = 8192,
                 name: Optional[str] = None):
        layers = [
            TransformerEncoderLayer(d_model, num_heads, d_ff, causal,
                                    dropout, attn_impl,
                                    num_kv_heads=num_kv_heads,
                                    rope=rope, rope_max_len=rope_max_len)
            for _ in range(num_layers)
        ]
        super().__init__(*layers, name=name)
        if remat is True:
            remat = "full"
        if remat and remat not in self._REMAT_POLICIES:
            raise ValueError(f"remat must be False/True/'full'/'dots', "
                             f"got {remat!r}")
        self.remat = remat

    def apply(self, params, state, x, *, training=False, rng=None):
        if not self.remat:
            return super().apply(params, state, x, training=training, rng=rng)
        policy_name = self._REMAT_POLICIES[self.remat]
        ckpt_kw = {}
        if policy_name is not None:
            ckpt_kw["policy"] = getattr(jax.checkpoint_policies, policy_name)
        new_state = {}
        for i, m in enumerate(self._modules):
            k = str(i)
            fn = jax.checkpoint(
                lambda p, s, h, r, m=m: m.apply(p, s, h, training=training,
                                                rng=r),
                static_argnums=(), **ckpt_kw)
            r = None if rng is None else jax.random.fold_in(rng, i)
            x, s = fn(params[k], state[k], x, r)
            new_state[k] = s
        return x, new_state

    # ----------------------------------------------- autoregressive decode
    def init_cache(self, batch, max_len, dtype=jnp.float32):
        return {str(i): m.init_cache(batch, max_len, dtype)
                for i, m in enumerate(self._modules)}

    def prefill(self, params, x, cache):
        new = {}
        for i, m in enumerate(self._modules):
            k = str(i)
            x, new[k] = m.prefill(params[k], x, cache[k])
        return x, new

    def decode_step(self, params, x, cache, idx):
        new = {}
        for i, m in enumerate(self._modules):
            k = str(i)
            x, new[k] = m.decode_step(params[k], x, cache[k], idx)
        return x, new

    def decode_chunk(self, params, x, cache, idx):
        """m-token decode: x (b, m, d) at positions idx..idx+m-1 — one
        dispatch verifies a speculative draft chunk or prefills a
        prefix-cache suffix (see MultiHeadAttention.decode_chunk)."""
        new = {}
        for i, m in enumerate(self._modules):
            k = str(i)
            x, new[k] = m.decode_chunk(params[k], x, cache[k], idx)
        return x, new
