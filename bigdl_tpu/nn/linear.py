"""Linear-algebra layers (reference nn/{Linear,Bilinear,CMul,...}.scala).

TPU notes: Linear stores weight as (in, out) so the forward is a plain
``x @ w`` feeding the MXU with no transpose; the reference stores (out, in)
(Torch convention) — the difference is layout only, cited per class. Batched
table ops (MM/MV/DotProduct/...) take Python tuples as the reference takes
``Table`` inputs.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from bigdl_tpu.core.module import (
    Module,
    SimpleModule,
    uniform_fan_in,
    xavier_uniform,
)

__all__ = [
    "Linear", "Bilinear", "CMul", "CAdd", "Mul", "Add", "MulConstant",
    "AddConstant", "MM", "MV", "Cosine", "Euclidean", "DotProduct",
    "CosineDistance", "PairwiseDistance", "LookupTable",
]


class Linear(SimpleModule):
    """y = x @ W + b (reference nn/Linear.scala, 203 LoC).

    Weight shape (in_features, out_features) — transposed from the reference's
    Torch layout so the matmul hits the MXU directly. Default init is
    Torch-style U(+-1/sqrt(fanIn)) matching Linear.reset.
    """

    def __init__(
        self,
        in_features: int,
        out_features: int,
        with_bias: bool = True,
        init: str = "default",
        param_dtype=jnp.float32,
        name: Optional[str] = None,
    ):
        super().__init__(name)
        self.in_features = in_features
        self.out_features = out_features
        self.with_bias = with_bias
        self.init_method = init
        self.param_dtype = param_dtype

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        shape = (self.in_features, self.out_features)
        if self.init_method == "xavier":
            w = xavier_uniform(k_w, shape, self.in_features, self.out_features,
                               self.param_dtype)
        else:
            w = uniform_fan_in(k_w, shape, self.in_features, self.param_dtype)
        p = {"weight": w}
        if self.with_bias:
            p["bias"] = uniform_fan_in(k_b, (self.out_features,),
                                       self.in_features, self.param_dtype)
        return p

    def _forward(self, params, x, *, training, rng):
        w = params["weight"].astype(x.dtype)
        y = x @ w
        if self.with_bias:
            y = y + params["bias"].astype(x.dtype)
        return y


class Bilinear(SimpleModule):
    """y_k = x1 @ W_k @ x2 + b_k over a table input (x1, x2)
    (reference nn/Bilinear.scala, 197 LoC)."""

    def __init__(self, in1: int, in2: int, out: int, with_bias: bool = True,
                 name: Optional[str] = None):
        super().__init__(name)
        self.in1, self.in2, self.out = in1, in2, out
        self.with_bias = with_bias

    def init(self, rng):
        k_w, k_b = jax.random.split(rng)
        fan_in = self.in1 * self.in2
        p = {"weight": uniform_fan_in(k_w, (self.out, self.in1, self.in2), fan_in)}
        if self.with_bias:
            p["bias"] = uniform_fan_in(k_b, (self.out,), fan_in)
        return p

    def _forward(self, params, x, *, training, rng):
        x1, x2 = x
        w = params["weight"].astype(x1.dtype)
        # (B,in1),(out,in1,in2),(B,in2) -> (B,out)
        y = jnp.einsum("bi,oij,bj->bo", x1, w, x2)
        if self.with_bias:
            y = y + params["bias"].astype(y.dtype)
        return y


class CMul(SimpleModule):
    """Learned componentwise scale of given (broadcastable) size
    (reference nn/CMul.scala)."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def init(self, rng):
        fan_in = int(jnp.prod(jnp.asarray(self.size)))
        return {"weight": uniform_fan_in(rng, self.size, fan_in)}

    def _forward(self, params, x, *, training, rng):
        return x * params["weight"].astype(x.dtype)


class CAdd(SimpleModule):
    """Learned componentwise bias (reference nn/CAdd.scala)."""

    def __init__(self, size: Sequence[int], name: Optional[str] = None):
        super().__init__(name)
        self.size = tuple(size)

    def init(self, rng):
        fan_in = int(jnp.prod(jnp.asarray(self.size)))
        return {"bias": uniform_fan_in(rng, self.size, fan_in)}

    def _forward(self, params, x, *, training, rng):
        return x + params["bias"].astype(x.dtype)


class Mul(SimpleModule):
    """Single learned scalar gain (reference nn/Mul.scala)."""

    def init(self, rng):
        return {"weight": jax.random.uniform(rng, (), jnp.float32, -1.0, 1.0)}

    def _forward(self, params, x, *, training, rng):
        return x * params["weight"].astype(x.dtype)


class Add(SimpleModule):
    """Learned bias vector over the feature dim (reference nn/Add.scala)."""

    def __init__(self, input_size: int, name: Optional[str] = None):
        super().__init__(name)
        self.input_size = input_size

    def init(self, rng):
        return {"bias": uniform_fan_in(rng, (self.input_size,), self.input_size)}

    def _forward(self, params, x, *, training, rng):
        return x + params["bias"].astype(x.dtype)


class MulConstant(SimpleModule):
    """x * c (reference nn/MulConstant.scala)."""

    def __init__(self, constant: float, name: Optional[str] = None):
        super().__init__(name)
        self.constant = constant

    def _forward(self, params, x, *, training, rng):
        return x * jnp.asarray(self.constant, x.dtype)


class AddConstant(SimpleModule):
    """x + c (reference nn/AddConstant.scala)."""

    def __init__(self, constant: float, name: Optional[str] = None):
        super().__init__(name)
        self.constant = constant

    def _forward(self, params, x, *, training, rng):
        return x + jnp.asarray(self.constant, x.dtype)


class MM(SimpleModule):
    """Batched matrix-matrix product of a table (A, B)
    (reference nn/MM.scala) — lowers to one MXU dot_general."""

    def __init__(self, trans_a: bool = False, trans_b: bool = False,
                 name: Optional[str] = None):
        super().__init__(name)
        self.trans_a, self.trans_b = trans_a, trans_b

    def _forward(self, params, x, *, training, rng):
        a, b = x
        if self.trans_a:
            a = jnp.swapaxes(a, -1, -2)
        if self.trans_b:
            b = jnp.swapaxes(b, -1, -2)
        return a @ b


class MV(SimpleModule):
    """Batched matrix-vector product of a table (M, v) (reference nn/MV.scala)."""

    def __init__(self, trans: bool = False, name: Optional[str] = None):
        super().__init__(name)
        self.trans = trans

    def _forward(self, params, x, *, training, rng):
        m, v = x
        if self.trans:
            m = jnp.swapaxes(m, -1, -2)
        return jnp.einsum("...ij,...j->...i", m, v)


class Cosine(SimpleModule):
    """Cosine similarity against a learned weight bank: output_j =
    cos(x, w_j) (reference nn/Cosine.scala, 212 LoC)."""

    def __init__(self, input_size: int, output_size: int, eps: float = 1e-12,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.output_size, self.eps = input_size, output_size, eps

    def init(self, rng):
        return {"weight": uniform_fan_in(
            rng, (self.output_size, self.input_size), self.input_size)}

    def _forward(self, params, x, *, training, rng):
        w = params["weight"].astype(x.dtype)  # (O, I)
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), self.eps)
        wn = w / jnp.maximum(jnp.linalg.norm(w, axis=-1, keepdims=True), self.eps)
        return xn @ wn.T


class Euclidean(SimpleModule):
    """Distances to a learned set of centers: y_j = ||x - w_j||
    (reference nn/Euclidean.scala)."""

    def __init__(self, input_size: int, output_size: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self.input_size, self.output_size = input_size, output_size

    def init(self, rng):
        return {"weight": uniform_fan_in(
            rng, (self.output_size, self.input_size), self.input_size)}

    def _forward(self, params, x, *, training, rng):
        w = params["weight"].astype(x.dtype)  # (O, I)
        d = x[..., None, :] - w  # (B, O, I)
        return jnp.sqrt(jnp.sum(jnp.square(d), axis=-1) + 1e-12)


class DotProduct(SimpleModule):
    """Row-wise dot product of a table (a, b) (reference nn/DotProduct.scala)."""

    def _forward(self, params, x, *, training, rng):
        a, b = x
        return jnp.sum(a * b, axis=-1)


class CosineDistance(SimpleModule):
    """Row-wise cosine similarity of a table (a, b)
    (reference nn/CosineDistance.scala)."""

    def __init__(self, eps: float = 1e-12, name: Optional[str] = None):
        super().__init__(name)
        self.eps = eps

    def _forward(self, params, x, *, training, rng):
        a, b = x
        na = jnp.maximum(jnp.linalg.norm(a, axis=-1), self.eps)
        nb = jnp.maximum(jnp.linalg.norm(b, axis=-1), self.eps)
        return jnp.sum(a * b, axis=-1) / (na * nb)


class PairwiseDistance(SimpleModule):
    """Row-wise Lp distance of a table (a, b) (reference nn/PairwiseDistance.scala)."""

    def __init__(self, norm: int = 2, name: Optional[str] = None):
        super().__init__(name)
        self.norm = norm

    def _forward(self, params, x, *, training, rng):
        a, b = x
        d = jnp.abs(a - b)
        if self.norm == 1:
            return jnp.sum(d, axis=-1)
        return jnp.power(jnp.sum(jnp.power(d, self.norm), axis=-1), 1.0 / self.norm)


class LookupTable(SimpleModule):
    """Embedding lookup (reference nn/LookupTable.scala, 267 LoC).

    Indices are 0-based here (the reference is 1-based Lua convention).
    ``max_norm`` renormalizes *the gathered rows* at lookup time like the
    reference does; gather lowers to an efficient XLA dynamic-gather.
    """

    def __init__(self, n_index: int, n_output: int,
                 max_norm: Optional[float] = None, norm_type: float = 2.0,
                 name: Optional[str] = None):
        super().__init__(name)
        self.n_index, self.n_output = n_index, n_output
        self.max_norm, self.norm_type = max_norm, norm_type

    def init(self, rng):
        return {"weight": jax.random.normal(
            rng, (self.n_index, self.n_output), jnp.float32)}

    def _forward(self, params, x, *, training, rng):
        w = params["weight"]
        if hasattr(w, "take_rows"):
            # quantized serving weight (serving/quant.QuantizedWeight):
            # gather the 8-bit rows, scale after — same result dtype
            rows = w.take_rows(x.astype(jnp.int32))
        else:
            rows = jnp.take(w, x.astype(jnp.int32), axis=0)
        if self.max_norm is not None:
            n = jnp.linalg.norm(rows, ord=self.norm_type, axis=-1, keepdims=True)
            rows = rows * jnp.minimum(1.0, self.max_norm / jnp.maximum(n, 1e-7))
        return rows
