// bigdl-tpu native runtime: multi-threaded prefetching input pipeline +
// binary dataset readers.
//
// This is the TPU-native equivalent of the reference's multi-threaded
// ImageNet input path (image/MTLabeledBGRImgToBatch.scala:48-133): there,
// coreNumber cloned transformer pipelines race on an atomic batch-position
// counter to decode/augment into one shared batch buffer. Here, worker
// threads claim batch *tickets* from an atomic counter, run
// crop/flip/normalize over raw uint8 samples, and push finished float
// batches into a bounded queue that the host training loop pops while the
// TPU computes — classic double-buffering so the MXU never waits on the
// input pipeline (SURVEY.md §7 "Input pipeline throughput").
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <cmath>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <memory>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

#ifdef BT_WITH_JPEG
#include <csetjmp>
#include <jpeglib.h>
#endif

namespace {

struct Batch {
    long index;
    std::vector<float> images;
    std::vector<int32_t> labels;
};

struct Pipeline {
    // dataset (borrowed pointers — caller keeps them alive)
    const uint8_t* images = nullptr;
    const int32_t* labels = nullptr;
    int64_t n = 0;
    int h = 0, w = 0, c = 0;

    // batch/augment config
    int batch = 0;
    int crop_h = 0, crop_w = 0;
    bool random_crop = false;
    bool hflip = false;
    std::vector<float> mean, stdev;  // per-channel
    bool shuffle = true;
    bool loop = false;
    uint64_t seed = 0;

    // runtime
    long batches_per_epoch = 0;
    std::atomic<long> ticket{0};
    std::vector<std::thread> workers;
    size_t queue_cap = 4;
    // finished batches keyed by ticket: delivery is strictly in ticket
    // order (epoch boundaries and eval sample order must be exact even
    // though workers complete out of order)
    std::map<long, Batch> ready;
    std::mutex mu;
    std::condition_variable cv_space, cv_ready;
    bool stopping = false;
    long delivered = 0;  // == next ticket to hand to the consumer

    // per-epoch permutations (epoch -> shuffled index array); workers near
    // an epoch boundary may need two epochs' perms concurrently
    std::mutex perm_mu;
    std::map<long, std::shared_ptr<std::vector<uint32_t>>> perms;

    std::shared_ptr<std::vector<uint32_t>> perm_for(long epoch) {
        std::lock_guard<std::mutex> lk(perm_mu);
        auto it = perms.find(epoch);
        if (it != perms.end()) return it->second;
        auto p = std::make_shared<std::vector<uint32_t>>(n);
        for (int64_t i = 0; i < n; ++i) (*p)[i] = (uint32_t)i;
        if (shuffle) {
            std::mt19937_64 rng(seed * 0x9E3779B97F4A7C15ULL + (uint64_t)epoch);
            for (int64_t i = n - 1; i > 0; --i) {
                std::uniform_int_distribution<int64_t> d(0, i);
                std::swap((*p)[i], (*p)[d(rng)]);
            }
        }
        perms[epoch] = p;
        // prune stale epochs (keep a small sliding window)
        while (perms.size() > 3) perms.erase(perms.begin());
        return p;
    }
};

// Core pixel loop shared by the in-memory pipeline and the streaming
// per-sample augment: crop at (off_h, off_w) from a (src_h, src_w, c)
// uint8 HWC source, optional horizontal flip, per-channel (x - mean)/std
// normalize into float HWC dst of (ch, cw, c).
void augment_core(const uint8_t* src, int src_w, int c, float* dst, int ch,
                  int cw, int off_h, int off_w, bool flip, const float* mean,
                  const float* stdev) {
    for (int y = 0; y < ch; ++y) {
        const uint8_t* row =
            src + ((int64_t)(y + off_h) * src_w + off_w) * c;
        float* out_row = dst + (int64_t)y * cw * c;
        for (int x = 0; x < cw; ++x) {
            int sx = flip ? (cw - 1 - x) : x;
            const uint8_t* px = row + (int64_t)sx * c;
            float* out = out_row + (int64_t)x * c;
            for (int k = 0; k < c; ++k)
                out[k] = ((float)px[k] - mean[k]) / stdev[k];
        }
    }
}

// Fill one sample slot: crop (random or center), optional horizontal flip,
// per-channel (x - mean) / std normalization, uint8 HWC -> float HWC.
void fill_sample(const Pipeline* p, const uint8_t* src, float* dst,
                 std::mt19937_64& rng) {
    const int ch = p->crop_h, cw = p->crop_w, c = p->c;
    int off_h = (p->h - ch) / 2, off_w = (p->w - cw) / 2;
    if (p->random_crop && (p->h > ch || p->w > cw)) {
        if (p->h > ch) {
            std::uniform_int_distribution<int> d(0, p->h - ch);
            off_h = d(rng);
        }
        if (p->w > cw) {
            std::uniform_int_distribution<int> d(0, p->w - cw);
            off_w = d(rng);
        }
    }
    bool flip = false;
    if (p->hflip) {
        std::uniform_int_distribution<int> d(0, 1);
        flip = d(rng) == 1;
    }
    augment_core(src, p->w, c, dst, ch, cw, off_h, off_w, flip,
                 p->mean.data(), p->stdev.data());
}

void worker_main(Pipeline* p) {
    const int64_t sample_elems = (int64_t)p->crop_h * p->crop_w * p->c;
    for (;;) {
        long t = p->ticket.fetch_add(1);
        if (!p->loop && t >= p->batches_per_epoch) break;
        long epoch = t / p->batches_per_epoch;
        long b = t % p->batches_per_epoch;
        auto perm = p->perm_for(epoch);

        Batch out;
        out.index = t;
        out.images.resize((size_t)p->batch * sample_elems);
        out.labels.resize(p->batch);
        // ticket-seeded rng: augmentation is reproducible regardless of
        // which thread runs the ticket
        std::mt19937_64 rng(p->seed ^ (0xD1B54A32D192ED03ULL * (uint64_t)(t + 1)));
        for (int i = 0; i < p->batch; ++i) {
            uint32_t idx = (*perm)[(size_t)b * p->batch + i];
            const uint8_t* src =
                p->images + (int64_t)idx * p->h * p->w * p->c;
            fill_sample(p, src, out.images.data() + (int64_t)i * sample_elems,
                        rng);
            out.labels[i] = p->labels ? p->labels[idx] : 0;
        }

        std::unique_lock<std::mutex> lk(p->mu);
        // the batch the consumer is waiting for must always be insertable,
        // even when the buffer is formally full, or the pipeline deadlocks
        // (consumer waits for ticket k while k's producer waits for space)
        long my_index = out.index;
        p->cv_space.wait(lk, [p, my_index] {
            return p->stopping || p->ready.size() < p->queue_cap ||
                   my_index == p->delivered;
        });
        if (p->stopping) break;
        p->ready.emplace(my_index, std::move(out));
        p->cv_ready.notify_all();
    }
}

// Bilinear resize, uint8 HWC -> uint8 HWC (half-pixel-centered sampling).
void resize_bilinear(const uint8_t* src, int sh, int sw, int c, uint8_t* dst,
                     int th, int tw) {
    for (int y = 0; y < th; ++y) {
        float fy = ((float)y + 0.5f) * sh / th - 0.5f;
        if (fy < 0) fy = 0;
        int y0 = (int)fy;
        int y1 = y0 + 1 < sh ? y0 + 1 : sh - 1;
        float wy = fy - y0;
        for (int x = 0; x < tw; ++x) {
            float fx = ((float)x + 0.5f) * sw / tw - 0.5f;
            if (fx < 0) fx = 0;
            int x0 = (int)fx;
            int x1 = x0 + 1 < sw ? x0 + 1 : sw - 1;
            float wx = fx - x0;
            const uint8_t* p00 = src + ((int64_t)y0 * sw + x0) * c;
            const uint8_t* p01 = src + ((int64_t)y0 * sw + x1) * c;
            const uint8_t* p10 = src + ((int64_t)y1 * sw + x0) * c;
            const uint8_t* p11 = src + ((int64_t)y1 * sw + x1) * c;
            uint8_t* o = dst + ((int64_t)y * tw + x) * c;
            for (int k = 0; k < c; ++k) {
                float v = (1 - wy) * ((1 - wx) * p00[k] + wx * p01[k]) +
                          wy * ((1 - wx) * p10[k] + wx * p11[k]);
                o[k] = (uint8_t)(v + 0.5f);
            }
        }
    }
}

#ifdef BT_WITH_JPEG
struct JpegErr {
    jpeg_error_mgr pub;
    jmp_buf jb;
};

void jpeg_err_exit(j_common_ptr cinfo) {
    longjmp(((JpegErr*)cinfo->err)->jb, 1);
}
#endif

}  // namespace

extern "C" {

// 1 when the .so was built against libjpeg (bt_decode_jpeg functional).
int bt_jpeg_available(void) {
#ifdef BT_WITH_JPEG
    return 1;
#else
    return 0;
#endif
}

// Decode a JPEG and resize — the decode half of the reference's MT input
// path (image/BGRImage.scala readRawImage + MTLabeledBGRImgToBatch), kept
// native so the whole per-sample path runs without the Python interpreter:
// libjpeg DCT scaling (scale_denom in {1,2,4,8}, decode near target size —
// the "draft mode" trick) followed by an exact bilinear resize.
//
// mode 0: scale so min(h, w) == target_h (short-side convention, train);
// mode 1: scale so the image covers (target_h, target_w) (fill, eval).
// *out is malloc'd RGB HWC (caller frees with bt_free).
// Returns 0 on success, -1 on decode error / no libjpeg at build time.
int bt_decode_jpeg(const uint8_t* buf, int64_t len, int mode, int target_h,
                   int target_w, uint8_t** out, int* out_h, int* out_w) {
#ifndef BT_WITH_JPEG
    (void)buf; (void)len; (void)mode; (void)target_h; (void)target_w;
    (void)out; (void)out_h; (void)out_w;
    return -1;
#else
    if (!buf || len <= 0 || !out || !out_h || !out_w || target_h <= 0)
        return -1;
    jpeg_decompress_struct cinfo;
    JpegErr jerr;
    cinfo.err = jpeg_std_error(&jerr.pub);
    jerr.pub.error_exit = jpeg_err_exit;
    std::vector<uint8_t> decoded;  // declared before setjmp (longjmp and
    uint8_t* result = nullptr;     // non-trivial dtors don't mix)
    if (setjmp(jerr.jb)) {
        jpeg_destroy_decompress(&cinfo);
        free(result);
        return -1;
    }
    jpeg_create_decompress(&cinfo);
    jpeg_mem_src(&cinfo, buf, (unsigned long)len);
    if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    const int w0 = (int)cinfo.image_width, h0 = (int)cinfo.image_height;
    if (w0 <= 0 || h0 <= 0) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    // final dims (mirrors dataset/streaming.decode_resize arithmetic)
    int th, tw;
    if (mode == 0) {
        const int ss = target_h;
        const double scale = (double)ss / (w0 < h0 ? w0 : h0);
        tw = (int)std::lround(w0 * scale);
        th = (int)std::lround(h0 * scale);
        if (tw < ss) tw = ss;
        if (th < ss) th = ss;
    } else {
        if (target_w <= 0) {
            jpeg_destroy_decompress(&cinfo);
            return -1;
        }
        const double scale = std::fmax((double)target_h / h0,
                                       (double)target_w / w0);
        tw = (int)std::lround(w0 * scale);
        th = (int)std::lround(h0 * scale);
        if (tw < target_w) tw = target_w;
        if (th < target_h) th = target_h;
    }
    // DCT-domain downscale: largest 1/d (d in 1,2,4,8) still >= target
    int denom = 1;
    while (denom * 2 <= 8 && w0 / (denom * 2) >= tw &&
           h0 / (denom * 2) >= th)
        denom *= 2;
    cinfo.scale_num = 1;
    cinfo.scale_denom = (unsigned)denom;
    cinfo.out_color_space = JCS_RGB;  // grayscale/YCbCr sources converted
    jpeg_start_decompress(&cinfo);
    const int dw = (int)cinfo.output_width, dh = (int)cinfo.output_height;
    if (cinfo.output_components != 3) {
        jpeg_destroy_decompress(&cinfo);
        return -1;
    }
    decoded.resize((size_t)dw * dh * 3);
    while (cinfo.output_scanline < cinfo.output_height) {
        uint8_t* row = decoded.data() + (size_t)cinfo.output_scanline * dw * 3;
        jpeg_read_scanlines(&cinfo, &row, 1);
    }
    jpeg_finish_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);

    result = (uint8_t*)malloc((size_t)th * tw * 3);
    if (!result) return -1;
    if (dw == tw && dh == th)
        std::memcpy(result, decoded.data(), (size_t)th * tw * 3);
    else
        resize_bilinear(decoded.data(), dh, dw, 3, result, th, tw);
    *out = result;
    *out_h = th;
    *out_w = tw;
    return 0;
#endif
}

// Create a pipeline over an in-memory uint8 image array [n, h, w, c] and
// int32 labels [n]. Caller keeps images/labels alive until destroy.
// loop=0: exactly one epoch of batches then next() returns -1.
// loop=1: endless (train mode; reshuffles each epoch, reference
//         CachedDistriDataSet train iterator semantics).
void* bt_pipeline_create(const uint8_t* images, int64_t n, int h, int w,
                         int c, const int32_t* labels, int batch, int crop_h,
                         int crop_w, int random_crop, int hflip,
                         const float* mean, const float* stdev, int shuffle,
                         int loop, uint64_t seed, int n_threads,
                         int queue_cap) {
    if (!images || n <= 0 || batch <= 0 || crop_h <= 0 || crop_w <= 0 ||
        crop_h > h || crop_w > w || n < batch)
        return nullptr;
    auto* p = new Pipeline();
    p->images = images;
    p->labels = labels;
    p->n = n;
    p->h = h;
    p->w = w;
    p->c = c;
    p->batch = batch;
    p->crop_h = crop_h;
    p->crop_w = crop_w;
    p->random_crop = random_crop != 0;
    p->hflip = hflip != 0;
    if (mean) p->mean.assign(mean, mean + c);
    else p->mean.assign(c, 0.f);
    if (stdev) p->stdev.assign(stdev, stdev + c);
    else p->stdev.assign(c, 1.f);
    p->shuffle = shuffle != 0;
    p->loop = loop != 0;
    p->seed = seed;
    p->batches_per_epoch = n / batch;  // drop remainder: static XLA shapes
    p->queue_cap = queue_cap > 0 ? (size_t)queue_cap : 4;
    int nt = n_threads > 0 ? n_threads : 4;
    for (int i = 0; i < nt; ++i)
        p->workers.emplace_back(worker_main, p);
    return p;
}

long bt_pipeline_batches_per_epoch(void* h) {
    return h ? ((Pipeline*)h)->batches_per_epoch : 0;
}

// Pop the next finished batch into caller buffers
// (out_images: batch*crop_h*crop_w*c floats; out_labels: batch int32).
// Returns the batch ticket (>=0), or -1 when a non-loop pipeline is
// exhausted. Blocks while workers fill the queue.
long bt_pipeline_next(void* h, float* out_images, int32_t* out_labels) {
    auto* p = (Pipeline*)h;
    if (!p) return -1;
    std::unique_lock<std::mutex> lk(p->mu);
    if (!p->loop && p->delivered >= p->batches_per_epoch) return -1;
    // wait for the *in-order* next batch, not just any finished one
    p->cv_ready.wait(lk, [p] {
        return p->stopping || p->ready.count(p->delivered) > 0;
    });
    if (p->stopping && p->ready.count(p->delivered) == 0) return -1;
    auto it = p->ready.find(p->delivered);
    Batch b = std::move(it->second);
    p->ready.erase(it);
    p->delivered++;
    p->cv_space.notify_all();  // wake the producer of the new head ticket
    lk.unlock();
    std::memcpy(out_images, b.images.data(),
                b.images.size() * sizeof(float));
    if (out_labels)
        std::memcpy(out_labels, b.labels.data(),
                    b.labels.size() * sizeof(int32_t));
    return b.index;
}

void bt_pipeline_destroy(void* h) {
    auto* p = (Pipeline*)h;
    if (!p) return;
    {
        std::lock_guard<std::mutex> lk(p->mu);
        p->stopping = true;
    }
    p->cv_space.notify_all();
    p->cv_ready.notify_all();
    for (auto& t : p->workers) t.join();
    delete p;
}

// Streaming per-sample augment (the pixel half of the reference's
// MTLabeledBGRImgToBatch worker, image/MTLabeledBGRImgToBatch.scala:48-133):
// python worker threads decode JPEG via libjpeg (GIL released), then call
// this (GIL released by ctypes) for crop+flip+normalize — so the whole
// per-sample path runs parallel across the decode pool. Offsets/flip are
// chosen by the caller (per-sample seeded RNG lives host-side for
// reproducibility).
// Returns 1 on success, 0 when the crop window falls outside the source
// (caller must raise — silently leaving dst uninitialized would feed
// garbage batches to training).
int bt_augment_sample(const uint8_t* src, int src_h, int src_w, int c,
                      float* dst, int crop_h, int crop_w, int off_h,
                      int off_w, int flip, const float* mean,
                      const float* stdev) {
    if (!src || !dst || off_h < 0 || off_w < 0 || crop_h + off_h > src_h ||
        crop_w + off_w > src_w)
        return 0;
    augment_core(src, src_w, c, dst, crop_h, crop_w, off_h, off_w,
                 flip != 0, mean, stdev);
    return 1;
}

// ---------------------------------------------------------------- readers

// Read an MNIST idx file (the raw ubyte format the reference's
// models/lenet/Utils.scala parses). Returns element count and fills dims;
// data is malloc'd into *out (caller frees with bt_free).
int64_t bt_read_idx(const char* path, uint8_t** out, int64_t* dims,
                    int* ndim) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    uint8_t magic[4];
    // header: 0x00 0x00 <dtype> <ndim>; only ubyte (0x08) is supported and
    // ndim is capped at the caller's 8-slot dims buffer — both are
    // file-controlled bytes and must be validated, not trusted
    if (fread(magic, 1, 4, f) != 4 || magic[0] != 0 || magic[1] != 0 ||
        magic[2] != 0x08 || magic[3] == 0 || magic[3] > 8) {
        fclose(f);
        return -1;
    }
    int nd = magic[3];
    int64_t total = 1;
    for (int i = 0; i < nd; ++i) {
        uint8_t b[4];
        if (fread(b, 1, 4, f) != 4) {
            fclose(f);
            return -1;
        }
        dims[i] = ((int64_t)b[0] << 24) | (b[1] << 16) | (b[2] << 8) | b[3];
        if (dims[i] <= 0 || total > (int64_t)1 << 40) {
            fclose(f);
            return -1;
        }
        total *= dims[i];
    }
    *ndim = nd;
    *out = (uint8_t*)malloc((size_t)total);
    if (!*out) {
        fclose(f);
        return -1;
    }
    int64_t got = (int64_t)fread(*out, 1, (size_t)total, f);
    fclose(f);
    if (got != total) {
        free(*out);
        *out = nullptr;
        return -1;
    }
    return total;
}

// Read one CIFAR-10 .bin shard (reference dataset format: records of
// 1 label byte + 3072 CHW pixel bytes). Fills images as NHWC uint8.
int64_t bt_read_cifar10(const char* path, uint8_t* images, int32_t* labels,
                        int64_t max_records) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    const int hw = 32 * 32;
    std::vector<uint8_t> rec(1 + 3 * hw);
    int64_t count = 0;
    while (count < max_records &&
           fread(rec.data(), 1, rec.size(), f) == rec.size()) {
        labels[count] = rec[0];
        uint8_t* dst = images + count * (int64_t)(3 * hw);
        // CHW (RGB planes) -> HWC
        for (int i = 0; i < hw; ++i) {
            dst[i * 3 + 0] = rec[1 + i];
            dst[i * 3 + 1] = rec[1 + hw + i];
            dst[i * 3 + 2] = rec[1 + 2 * hw + i];
        }
        ++count;
    }
    fclose(f);
    return count;
}

void bt_free(void* p) { free(p); }

}  // extern "C"
