"""Native C++ host runtime sources (threaded prefetch pipeline, libjpeg
decode, raw dataset readers) — shipped as package data and built lazily by
``bigdl_tpu.dataset.native`` at first use. This ``__init__`` exists only so
setuptools includes the directory as a package (see pyproject.toml
``[tool.setuptools.package-data]``)."""
