"""Device-time attribution: explain every step-millisecond (ISSUE 8).

A captured xplane used to die as an opaque blob: ``utils/xplane`` could
rank op totals (PR 3) and the capture controller could verify a window
parsed (PR 7), but nothing said *where the iteration went* — the
question BigDL's parameter-manager accounting answered natively
(compute vs. parameter-sync, arxiv 1804.05839) and the one "Densifying
Assumed-sparse Tensors" (arxiv 1905.04035) shows must be measured
before collective time can be shrunk.

This module classifies every device op from a profile into a fixed
category taxonomy (:data:`CATEGORIES`), breaks the **collective**
category out per collective kind (all-reduce / all-gather /
reduce-scatter / all-to-all / collective-permute — HLO name patterns
shared with ``utils/xplane.collectives``), and joins the flops-bearing
categories against the ``utils/flops`` analytic numerators to report
achieved-vs-roofline utilization and an MFU decomposition::

    MFU(device) = compute_frac x compute_util
      compute_frac = (matmul+conv time) / total device time
      compute_util = achieved TF/s while in matmul/conv ops / peak

Surfaces: the ``bigdl-tpu explain`` CLI (``cli/explain.py``), automatic
post-capture attribution (``obs/capture.py`` stamps :func:`compact`
into every verified window and publishes ``attrib_*`` gauges), and the
``collective_s``/``collective_frac``/``attrib`` perf JSON columns
(``cli/perf.py``). No dependencies beyond the stdlib — classification
is regex-on-label, so a renamed op degrades to ``host_other``, never to
a crash.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

from bigdl_tpu.utils.xplane import (XPlane, collective_kind, device_planes,
                                    find_xplane_pb, op_totals, parse_xspace)

__all__ = ["CATEGORIES", "ATTRIB_CATEGORIES", "classify_op", "attribute",
           "attribute_profile", "compact", "publish", "render"]

# the fixed taxonomy, in display order (PERF.md §16). ``collective`` is
# the ROADMAP-item-2 breakout; ``host_other`` is the honest remainder —
# attribution that cannot name a category must not hide it.
CATEGORIES: Tuple[str, ...] = (
    "matmul", "conv", "bn_norm", "attention", "elementwise", "collective",
    "infeed", "host_other")
ATTRIB_CATEGORIES = CATEGORIES  # unambiguous name for the obs namespace

# first match wins; ordered most-specific-first so compound names land
# right: ``all-reduce`` is collective (not an elementwise ``reduce``),
# ``convert`` is elementwise (not ``conv``), ``reduce-scatter`` never
# degrades into ``scatter``. Raw HLO labels ("fusion.123",
# "convolution.4", "all-reduce-start.1") and named-scope provenance
# ("jit_train_step/.../dot_general") both match.
_RULES: Tuple[Tuple[str, "re.Pattern[str]"], ...] = (
    # collectives are matched via collective_kind() before these rules
    ("infeed", re.compile(
        r"infeed|outfeed|host[-_]?transfer|host[-_]?to[-_]?device|"
        r"device[-_]?to[-_]?host|\bsend\b|\brecv\b", re.I)),
    ("attention", re.compile(
        r"attention|flash|\bmha\b|softmax|attn", re.I)),
    ("bn_norm", re.compile(
        r"batch[-_]?norm|layer[-_]?norm|rms[-_]?norm|group[-_]?norm|"
        r"\bbn_|_bn\b|batchnorm|layernorm|rmsnorm", re.I)),
    ("conv", re.compile(
        r"convolution|conv_general|conv2d|\bconv\b|dgrad|wgrad", re.I)),
    ("matmul", re.compile(
        r"dot_general|\bdot\b|dot\.|matmul|\bgemm\b|einsum|\bmxu\b",
        re.I)),
    ("elementwise", re.compile(
        r"fusion|loop|copy|convert|transpose|reshape|broadcast|slice|"
        r"concatenate|\bpad\b|pad\.|select|compare|reduce|scatter|gather|"
        r"\badd\b|add\.|multiply|subtract|divide|\bmax\b|max\.|\bmin\b|"
        r"min\.|\bexp\b|exp\.|\blog\b|log\.|tanh|rsqrt|iota|\brng\b|"
        r"bitcast|tuple|\bsort\b|sort\.|cumsum|clamp|\babs\b|abs\.|"
        r"\bpower\b|negate|sign|floor|\band\b|\bor\b|\bnot\b|"
        r"dynamic[-_]?update|dynamic[-_]?slice|while|custom[-_]?call",
        re.I)),
)


def classify_op(name: str) -> Tuple[str, Optional[str]]:
    """``label -> (category, collective_kind|None)``; labels no rule
    claims land in ``host_other``."""
    kind = collective_kind(name)
    if kind is not None:
        return "collective", kind
    for cat, pat in _RULES:
        if pat.search(name):
            return cat, None
    return "host_other", None


def attribute(planes: Sequence[XPlane], steps: Optional[int] = None,
              step_flops: Optional[float] = None,
              flops_by_kind: Optional[dict] = None,
              peak_flops: Optional[float] = None,
              top_ops: int = 3) -> dict:
    """Classify every device op of ``planes`` into the taxonomy.

    Returns the full attribution dict: ``total_device_s`` (sum of event
    durations over all device planes — on an N-device mesh this is
    device-seconds, N x wall), per-category ``{time_s, frac, count,
    ops, top}``, the per-collective-kind breakout, and — when
    ``step_flops``/``peak_flops`` are given (per step / whole-mesh) —
    per-category FLOP share, achieved TF/s, roofline utilization, and
    the MFU decomposition above. Host-only captures (CPU test runs with
    no accelerator plane) fall back to every plane carrying events, so
    the answer degrades to host-op categories instead of emptiness."""
    planes = list(planes)
    dev = device_planes(planes)
    if not any(ln.events for p in dev for ln in p.lines):
        dev = [p for p in planes if any(ln.events for ln in p.lines)]
    totals = op_totals(dev)

    cats: Dict[str, dict] = {
        c: {"time_s": 0.0, "count": 0, "ops": 0, "top": []}
        for c in CATEGORIES}
    colls: Dict[str, dict] = {}
    total_ps = 0.0
    for name, ent in totals.items():
        ps, cnt = ent["total_ps"], int(ent["count"])
        total_ps += ps
        cat, kind = classify_op(name)
        c = cats[cat]
        c["time_s"] += ps / 1e12
        c["count"] += cnt
        c["ops"] += 1
        c["top"].append((ps, name))
        if kind is not None:
            k = colls.setdefault(kind, {"time_s": 0.0, "count": 0})
            k["time_s"] += ps / 1e12
            k["count"] += cnt

    total_s = total_ps / 1e12
    for c in cats.values():
        c["frac"] = (c["time_s"] / total_s) if total_s else 0.0
        c["top"] = [n for _, n in
                    sorted(c["top"], key=lambda t: -t[0])[:top_ops]]
    for k in colls.values():
        k["frac"] = (k["time_s"] / total_s) if total_s else 0.0

    coll_s = cats["collective"]["time_s"]
    out = {
        "total_device_s": total_s,
        "steps": steps,
        "device_planes": len(dev),
        "categories": cats,
        "collectives": colls,
        "collective_s": coll_s,
        "collective_frac": cats["collective"]["frac"],
    }
    if steps:
        out["per_step_ms"] = {c: cats[c]["time_s"] * 1e3 / steps
                              for c in CATEGORIES}
        out["collective_s_per_step"] = coll_s / steps

    # ----- flops join: share of the numerator + roofline utilization
    if step_flops:
        kinds = dict(flops_by_kind or {})
        if not kinds:
            kinds = {"matmul": float(step_flops), "conv": 0.0}
        window = float(steps or 1)
        tot_f = float(step_flops) * window
        for cat in CATEGORIES:
            f = kinds.get(cat, 0.0) * window
            c = cats[cat]
            c["flop_share"] = (f / tot_f) if tot_f else 0.0
            if f and c["time_s"]:
                c["achieved_tflops"] = f / c["time_s"] / 1e12
                if peak_flops:
                    c["roofline_util"] = f / c["time_s"] / peak_flops
        compute_s = cats["matmul"]["time_s"] + cats["conv"]["time_s"]
        mfu = {
            "step_flops": float(step_flops),
            "compute_s": compute_s,
            "compute_frac": (compute_s / total_s) if total_s else 0.0,
        }
        if compute_s:
            mfu["achieved_tflops"] = tot_f / compute_s / 1e12
        if peak_flops:
            mfu["peak_flops"] = float(peak_flops)
            if compute_s:
                mfu["compute_util"] = tot_f / compute_s / peak_flops
            if total_s:
                mfu["mfu_device"] = tot_f / total_s / peak_flops
        out["mfu"] = mfu
    return out


def attribute_profile(profile_dir: str, **kw) -> dict:
    """:func:`attribute` over the newest ``*.xplane.pb`` under a
    ``jax.profiler`` output dir; SystemExit (not a stack trace) when
    the dir has no parseable profile — this is the CLI entry."""
    pb = find_xplane_pb(profile_dir)
    if pb is None:
        raise SystemExit(f"no *.xplane.pb under {profile_dir} — is this "
                         "a jax.profiler trace / capture_<step> dir?")
    out = attribute(parse_xspace(pb), **kw)
    out["xplane"] = pb
    return out


def compact(attrib: dict, min_frac: float = 0.001) -> dict:
    """The result-JSON spelling of an attribution: categories above
    ``min_frac`` as ``{s, frac}`` (seconds rounded to 10 us), the
    collective breakout, and the MFU decomposition when present —
    small enough to ride in every perf line / capture record."""
    out = {
        "total_device_s": round(attrib["total_device_s"], 5),
        "collective_s": round(attrib["collective_s"], 6),
        "collective_frac": round(attrib["collective_frac"], 4),
        "categories": {
            c: {"s": round(d["time_s"], 5), "frac": round(d["frac"], 4)}
            for c, d in attrib["categories"].items()
            if d["time_s"] and d["frac"] >= min_frac},
        "collectives": {
            k: {"s": round(d["time_s"], 5), "frac": round(d["frac"], 4)}
            for k, d in attrib["collectives"].items()},
    }
    if attrib.get("steps"):
        out["steps"] = attrib["steps"]
    if "mfu" in attrib:
        out["mfu"] = {k: (round(v, 4) if isinstance(v, float) else v)
                      for k, v in attrib["mfu"].items()}
    return out


def publish(attrib: dict, registry=None, prefix: str = "attrib") -> None:
    """Expose one attribution on the shared registry as ``attrib_*``
    gauges (scrape surface of the latest capture window): per-category
    seconds + fraction, per-collective-kind seconds, total device time,
    and the MFU decomposition."""
    if registry is None:
        from bigdl_tpu.obs.metrics import get_registry
        registry = get_registry()
    registry.gauge(f"{prefix}_total_device_seconds",
                   "device time in the last attributed capture").set(
        attrib["total_device_s"])
    for c, d in attrib["categories"].items():
        registry.gauge(f"{prefix}_{c}_seconds",
                       f"device seconds in {c} ops").set(d["time_s"])
        registry.gauge(f"{prefix}_{c}_frac",
                       f"fraction of device time in {c} ops").set(d["frac"])
    for k, d in attrib["collectives"].items():
        registry.gauge(f"{prefix}_collective_{k}_seconds",
                       f"device seconds in {k}").set(d["time_s"])
    mfu = attrib.get("mfu", {})
    for key in ("compute_frac", "compute_util", "mfu_device"):
        if key in mfu:
            registry.gauge(f"{prefix}_{key}",
                           "attribution MFU decomposition").set(mfu[key])


def render(attrib: dict) -> str:
    """Human table (``utils/table.format_table``): one row per category
    (zero rows included — an absent collective row and a 0.0% one are
    different findings), the collective breakout, and the MFU
    decomposition footer."""
    from bigdl_tpu.utils.table import format_table

    steps = attrib.get("steps")
    have_flops = any("flop_share" in d
                     for d in attrib["categories"].values())
    heads = ["category", "time_s", "frac"]
    if steps:
        heads.append("ms/step")
    heads.append("count")
    if have_flops:
        heads += ["flop_share", "util"]
    heads.append("top ops")
    rows: List[list] = []
    for c in CATEGORIES:
        d = attrib["categories"][c]
        row = [c, f"{d['time_s']:.5f}", f"{100 * d['frac']:.1f}%"]
        if steps:
            row.append(f"{d['time_s'] * 1e3 / steps:.3f}")
        row.append(d["count"])
        if have_flops:
            fs = d.get("flop_share")
            u = d.get("roofline_util")
            row += ["-" if fs is None else f"{100 * fs:.1f}%",
                    "-" if u is None else f"{100 * u:.1f}%"]
        row.append(", ".join(d["top"]) or "-")
        rows.append(row)
    lines = [format_table(heads, rows)]
    if attrib["collectives"]:
        crows = [[k, f"{d['time_s']:.5f}", f"{100 * d['frac']:.1f}%",
                  d["count"]]
                 for k, d in sorted(attrib["collectives"].items())]
        lines += ["", "collective breakout:",
                  format_table(["kind", "time_s", "frac", "count"], crows)]
    lines += ["", f"total device time: {attrib['total_device_s']:.5f}s "
                  f"over {attrib.get('device_planes', '?')} device "
                  f"plane(s)"
                  + (f", {steps} step(s)" if steps else "")]
    mfu = attrib.get("mfu")
    if mfu:
        bits = [f"compute_frac={100 * mfu['compute_frac']:.1f}%"]
        if "compute_util" in mfu:
            bits.append(f"compute_util={100 * mfu['compute_util']:.1f}%")
        if "mfu_device" in mfu:
            bits.append(f"MFU(device)={100 * mfu['mfu_device']:.1f}%")
        lines.append("mfu decomposition: " + " x ".join(bits[:2])
                     + (" -> " + bits[2] if len(bits) > 2 else ""))
    return "\n".join(lines)
