"""Unified observability layer (ISSUE 7).

BigDL's observability story — per-module wall-time counters
(``AbstractModule.getTimes``) + cluster-wide named counters aggregated
through Spark accumulators (``optim/Metrics.scala``, paper §4) — was
reproduced in fragments: hand-rolled ``time.time()`` deltas in the
Optimizer, a serving-only metrics registry, an offline-only xplane
reader. This package is the substrate built once:

* :mod:`spans`   — structured step-phase tracing: ``span("data_wait")``
  around the real phases of training and serving, thread-safe,
  ring-buffered, near-zero cost disabled, Chrome-trace/Perfetto export;
* :mod:`metrics` — the shared process-global registry
  (Counter/Gauge/Histogram + Prometheus exposition + provenance
  stamping), promoted from ``serving/metrics.py`` and now fed by
  training (step-phase histograms), resilience (fault/retry counters),
  and serving alike;
* :mod:`capture` — on-demand ``jax.profiler`` windows mid-run
  (``--traceSteps N@M``, SIGUSR2, touch-file), verified parseable with
  ``utils/xplane`` on close;
* :mod:`http`    — a live ``/metrics`` listener for training runs,
  reusing serving's exposition format.

Wired as ``--obs``/``--traceDir``/``--traceSteps``/``--metricsPort`` on
the perf + training CLIs (``cli/common.py``), with per-step phase
columns (``data_wait_s``, ``h2d_s``, ``dispatch_s``, ``device_s``,
``ckpt_s``, ``stall_frac``) stamped into every perf JSON line next to
``bn_fused``/``lint``/``supervisor``. ROADMAP items 2 (collective time
broken out) and 4 (feed-stall metering) read from this layer.
"""

from bigdl_tpu.obs import attrib, memory
from bigdl_tpu.obs.attrib import (ATTRIB_CATEGORIES, attribute,
                                  attribute_profile, classify_op)
from bigdl_tpu.obs.capture import (CaptureController, parse_trace_steps,
                                   TOUCH_FILE_NAME)
from bigdl_tpu.obs.http import MetricsServer, start_metrics_server
from bigdl_tpu.obs.memory import (HbmSampler, build_plan,
                                  device_hbm_bytes, forecast, handle_oom,
                                  is_resource_exhausted, plan_for_model,
                                  tree_bytes, write_oom_report)
from bigdl_tpu.obs.metrics import (Counter, DEFAULT_LATENCY_BUCKETS_MS,
                                   Gauge, Histogram, MetricsRegistry,
                                   PHASE_BUCKETS_MS, TRAIN_PHASES,
                                   get_registry, phase_histograms,
                                   reset_registry, set_registry)
from bigdl_tpu.obs.spans import (NOOP_SPAN, Tracer, counter, disable,
                                 enable, enabled, get_tracer, instant,
                                 set_tracer, span)

__all__ = [
    "attrib", "ATTRIB_CATEGORIES", "attribute", "attribute_profile",
    "classify_op",
    "CaptureController", "parse_trace_steps", "TOUCH_FILE_NAME",
    "MetricsServer", "start_metrics_server",
    "memory", "HbmSampler", "build_plan", "device_hbm_bytes", "forecast",
    "handle_oom", "is_resource_exhausted", "plan_for_model", "tree_bytes",
    "write_oom_report",
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "DEFAULT_LATENCY_BUCKETS_MS", "PHASE_BUCKETS_MS", "TRAIN_PHASES",
    "get_registry", "phase_histograms", "reset_registry", "set_registry",
    "NOOP_SPAN", "Tracer", "counter", "disable", "enable", "enabled",
    "get_tracer", "instant", "set_tracer", "span",
]
