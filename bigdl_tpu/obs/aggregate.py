"""Cross-process metrics aggregation (ISSUE 20).

The fleet router scrapes each worker's ``/metrics`` page and re-exports
the fleet view from one endpoint. The in-process convention (PR 15's
dp replicas) is: unlabelled aggregate series first, then labelled
per-replica series, HELP/TYPE stated once. This module extends the same
shape across process boundaries — each worker page is re-emitted with a
``worker="i"`` label, and every summable sample is folded into an
unlabelled fleet total.

What is deliberately NOT summed:

* ``quantile=...`` samples — quantiles do not add; consumers who need
  fleet quantiles sum the ``_bucket`` series (which DO add) and
  interpolate themselves.
* ``<ns>_info`` provenance gauges — each worker's provenance is its
  own config snapshot; the per-worker relabelled line is kept, a "sum"
  would be meaningless.
* non-finite values (a gauge whose sampling fn failed renders NaN).

Pure text-in/text-out with no registry dependency, so the router can
aggregate pages from workers running ANY compatible exposition version.
"""

from __future__ import annotations

import math
import re
from typing import Dict, List, Tuple

__all__ = ["aggregate_pages", "parse_samples"]

_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")


def parse_samples(page: str) -> List[Tuple[str, str, float]]:
    """``(name, labels_str, value)`` per sample line; comments, blanks,
    and unparseable values are skipped. ``labels_str`` is the raw text
    between the braces ("" when unlabelled) — kept verbatim so
    relabelling never has to re-escape quoted label values."""
    out = []
    for line in page.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labels, raw = m.group(1), m.group(2) or "", m.group(3)
        try:
            value = float(raw)
        except ValueError:
            continue
        out.append((name, labels, value))
    return out


def _relabel(labels: str, key: str, val: str) -> str:
    tag = f'{key}="{val}"'
    return f"{tag},{labels}" if labels else tag


def aggregate_pages(pages: Dict[str, str], label: str = "worker") -> str:
    """Fold worker exposition pages into one fleet page: summed
    unlabelled series first, then every sample relabelled with
    ``label="<page key>"``. ``pages`` maps the label value (worker
    index as a string) to that worker's raw ``/metrics`` text."""
    sums: Dict[Tuple[str, str], float] = {}
    order: List[Tuple[str, str]] = []
    relabelled: List[str] = []
    for idx in sorted(pages, key=lambda k: (len(k), k)):
        for name, labels, value in parse_samples(pages[idx]):
            if f'{label}="' in labels:
                continue  # already fleet-labelled: never double-count
            relabelled.append(
                f"{name}{{{_relabel(labels, label, idx)}}} "
                f"{value:g}")
            if (name.endswith("_info") or 'quantile="' in labels
                    or not math.isfinite(value)):
                continue
            key = (name, labels)
            if key not in sums:
                sums[key] = 0.0
                order.append(key)
            sums[key] += value
    lines = [f"# fleet aggregate over {len(pages)} worker page(s)"]
    for name, labels in order:
        sfx = f"{{{labels}}}" if labels else ""
        lines.append(f"{name}{sfx} {sums[(name, labels)]:g}")
    lines.extend(relabelled)
    return "\n".join(lines) + "\n"
