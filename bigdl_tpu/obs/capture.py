"""On-demand profile capture (ISSUE 7 tentpole #3).

Chip captures used to require editing a script to pass ``--profile`` and
re-running from step 0 — useless for "the run went slow an hour in,
grab me a trace NOW". This module opens a bounded
``jax.profiler.start_trace``/``stop_trace`` window *mid-run*, triggered
three ways:

* ``--traceSteps N@M`` — capture steps M..M+N-1 (planned ahead: the
  classic "skip warmup, profile the steady state" recipe);
* ``SIGUSR2`` — ``kill -USR2 <pid>`` opens a window of ``window_steps``
  at the next step boundary (works on a run launched with no profiling
  flags at all, as long as ``--traceDir`` gave captures a home);
* touch-file — ``touch <traceDir>/CAPTURE`` does the same from a shell
  that only shares a filesystem with the run (TPU pods behind a
  bastion). The file is consumed (removed) when the window opens, so
  one touch = one capture.

Every window lands in its own ``<trace_dir>/capture_<step>`` directory
and is VERIFIED on close: the resulting ``*.xplane.pb`` must parse with
``utils/xplane.parse_xspace`` (the PR 3 reader) — a capture that
silently wrote garbage is reported as failed, not discovered a day
later on a laptop without the chip.

The controller is driven by one ``on_step(step)`` call per dispatch;
call sites hold ``None`` when no capture is configured, so the
steady-state cost is a ``None`` check. With a controller installed but
idle, the cost is an int compare plus (touch-file mode) one ``stat``.
"""

from __future__ import annotations

import logging
import os
import re
import signal as _signal
import threading
from typing import List, Optional, Tuple

logger = logging.getLogger("bigdl_tpu")

__all__ = ["CaptureController", "parse_trace_steps", "TOUCH_FILE_NAME"]

TOUCH_FILE_NAME = "CAPTURE"

_SPEC_RE = re.compile(r"^(\d+)@(\d+)$")


def parse_trace_steps(spec: str) -> Tuple[int, int]:
    """``"N@M"`` -> ``(n_steps, start_step)``; steps are 0-indexed
    dispatch counts (M=0 captures from the first timed step)."""
    m = _SPEC_RE.match(str(spec).strip())
    if not m:
        raise ValueError(
            f"--traceSteps {spec!r}: expected N@M (capture N steps "
            f"starting at step M), e.g. 5@20")
    n, start = int(m.group(1)), int(m.group(2))
    if n < 1:
        raise ValueError(f"--traceSteps {spec!r}: N must be >= 1")
    return n, start


class CaptureController:
    """Bounded mid-run ``jax.profiler`` windows with post-close
    verification.

    ``captures`` (and :meth:`annotation`) records one dict per window:
    ``{start_step, stop_step, trigger, dir, xplane, planes, ok}`` plus
    ``error`` when the profiler or the verify failed — the failure mode
    is a reported bad capture, never a crashed training run.
    """

    def __init__(self, trace_dir: str, trace_steps: Optional[str] = None,
                 window_steps: int = 5, touch_file: Optional[str] = None,
                 install_signal: bool = True):
        self.trace_dir = str(trace_dir)
        # optional attribution context (ISSUE 8): the harness that knows
        # its step FLOPs / mesh peak installs them so every verified
        # window closes with an MFU-decomposed attribution, not just
        # "parsed ok". None = attribution still runs, times only.
        self.step_flops: Optional[float] = None
        self.flops_by_kind: Optional[dict] = None
        self.peak_flops: Optional[float] = None
        # gradient-communication context (ISSUE 10): when the harness
        # compressed/bucketed the grad all-reduce, the config rides into
        # every attributed window so a captured collective_s can be read
        # against the wire bytes that produced it
        self.grad_comm: Optional[dict] = None
        os.makedirs(self.trace_dir, exist_ok=True)
        self._planned: Optional[Tuple[int, int]] = (
            parse_trace_steps(trace_steps) if trace_steps else None)
        self.window_steps = max(1, int(window_steps))
        self.touch_file = (touch_file if touch_file is not None
                           else os.path.join(self.trace_dir,
                                             TOUCH_FILE_NAME))
        self.captures: List[dict] = []
        self._active: Optional[dict] = None
        self._stop_at: int = 0
        self._signal_pending = False
        self._prev_handler = None
        if install_signal:
            self._install_signal()

    # ------------------------------------------------------------ triggers
    def _install_signal(self) -> None:
        def _handler(signum, frame):
            # flag only — start_trace from inside a signal handler could
            # land mid-dispatch; the next on_step boundary acts on it
            self._signal_pending = True

        try:
            if threading.current_thread() is threading.main_thread():
                self._prev_handler = _signal.signal(_signal.SIGUSR2,
                                                    _handler)
        except (ValueError, OSError, AttributeError):
            self._prev_handler = None  # non-main thread / platform quirk

    def request_capture(self) -> None:
        """Programmatic trigger (same path as SIGUSR2): open a
        ``window_steps`` window at the next step boundary."""
        self._signal_pending = True

    def _touch_triggered(self) -> bool:
        if not self.touch_file:
            return False
        if os.path.exists(self.touch_file):
            try:  # consume: one touch = one capture
                os.remove(self.touch_file)
            except OSError:
                pass
            return True
        return False

    # ---------------------------------------------------------------- steps
    def on_step(self, step: int) -> None:
        """One call per dispatch, BEFORE the step runs. Opens a pending
        window at its start step and closes+verifies an open window at
        its stop step."""
        if self._active is not None:
            if step >= self._stop_at:
                self._stop()
            else:
                return  # window still open; triggers wait for it
        if self._planned is not None and step >= self._planned[1]:
            n, start = self._planned
            self._planned = None
            self._start(step, step + n, trigger=f"traceSteps:{n}@{start}")
            return
        if self._signal_pending:
            self._signal_pending = False
            self._start(step, step + self.window_steps, trigger="signal")
            return
        if self._touch_triggered():
            self._start(step, step + self.window_steps, trigger="touch")

    def finish(self) -> None:
        """End-of-run drain: close a still-open window (a --traceSteps
        spec past the last step, or a trigger near the end)."""
        if self._active is not None:
            self._stop()
        if self._prev_handler is not None:
            try:
                _signal.signal(_signal.SIGUSR2, self._prev_handler)
            except (ValueError, OSError):
                pass
            self._prev_handler = None

    # --------------------------------------------------------------- window
    def _start(self, step: int, stop_at: int, trigger: str) -> None:
        d = os.path.join(self.trace_dir, f"capture_{step}")
        rec = {"start_step": step, "stop_step": stop_at,
               "trigger": trigger, "dir": d, "ok": False}
        try:
            import jax
            jax.profiler.start_trace(d)
        except Exception as e:  # a second profiler session, no backend...
            rec["error"] = f"start_trace: {type(e).__name__}: {e}"[:200]
            self.captures.append(rec)
            logger.warning("obs capture failed to open at step %d: %s",
                           step, rec["error"])
            return
        self._active = rec
        self._stop_at = stop_at
        logger.info("obs capture open at step %d (until %d, trigger=%s) "
                    "-> %s", step, stop_at, trigger, d)

    def _stop(self) -> None:
        rec, self._active = self._active, None
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            rec["error"] = f"stop_trace: {type(e).__name__}: {e}"[:200]
            self.captures.append(rec)
            logger.warning("obs capture failed to close: %s", rec["error"])
            return
        self._verify(rec)
        self.captures.append(rec)
        logger.info("obs capture closed: %s (ok=%s, %s planes)",
                    rec["dir"], rec["ok"], rec.get("planes"))

    def _verify(self, rec: dict) -> None:
        """A capture only counts if the PR 3 reader can parse it — the
        whole point of on-demand capture is a trace someone can read."""
        from bigdl_tpu.utils.xplane import find_xplane_pb, parse_xspace
        xp = find_xplane_pb(rec["dir"])
        if xp is None:
            rec["error"] = "no .xplane.pb written"
            return
        rec["xplane"] = xp
        try:
            planes = parse_xspace(xp)
        except Exception as e:
            rec["error"] = f"xplane parse: {type(e).__name__}: {e}"[:200]
            return
        rec["planes"] = len(planes)
        rec["ok"] = bool(planes)
        if not planes:
            rec["error"] = "xplane parsed but contains no planes"
            return
        self._attribute(rec, planes)

    def _attribute(self, rec: dict, planes) -> None:
        """Post-capture attribution (ISSUE 8): every verified window is
        immediately explained — per-category device time with the
        collective breakout stamped into the capture record and
        published as ``attrib_*`` gauges on the shared registry. A
        failure here is recorded, never raised: a window that parsed
        but resisted classification is still a good capture."""
        try:
            from bigdl_tpu.obs import attrib as _attrib
            from bigdl_tpu.obs.metrics import get_registry
            steps = max(1, int(rec["stop_step"]) - int(rec["start_step"]))
            summary = _attrib.attribute(
                planes, steps=steps, step_flops=self.step_flops,
                flops_by_kind=self.flops_by_kind,
                peak_flops=self.peak_flops)
            rec["attrib"] = _attrib.compact(summary)
            if self.grad_comm is not None:
                rec["grad_comm"] = dict(self.grad_comm)
            _attrib.publish(summary, get_registry())
        except Exception as e:
            rec["attrib_error"] = (
                f"attrib: {type(e).__name__}: {e}"[:200])
            logger.warning("obs capture attribution failed: %s",
                           rec["attrib_error"])

    # ----------------------------------------------------------- reporting
    def annotation(self) -> List[dict]:
        """Capture records for result-JSON stamping (paths relativized
        to the trace dir would lose the one thing the reader needs, so
        they stay absolute)."""
        return [dict(r) for r in self.captures]
