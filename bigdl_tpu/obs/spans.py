"""Low-overhead structured step-phase tracing (ISSUE 7 tentpole #1).

The reference framework answers "where did the time go" twice: per-module
wall-time counters (``AbstractModule.getTimes``) and cluster-wide named
counters aggregated through Spark accumulators (``optim/Metrics.scala``).
Both are *sums* — they can say data fetch cost 12 s total, but not that
step 847 stalled 300 ms waiting on the feed while its neighbors didn't.
This module is the timeline half: named spans around the real phases of
the training loop (data fetch, host→device transfer, dispatch, device
wait, checkpoint) and the serving request path (queue wait, batch
assembly, compute, decode step), ring-buffered and exportable as a
Chrome-trace / Perfetto JSON for ``chrome://tracing`` or ``ui.perfetto.dev``.

Design constraints, in priority order:

1. **Near-zero cost when disabled.** ``span(name)`` with no tracer
   installed is one global load, one ``None`` check, and returns a
   shared singleton no-op context manager — no allocation, no clock
   read. Instrumented hot loops pay nothing until ``--obs`` turns the
   tracer on (the same contract as ``resilience.faults.hook``).
2. **Thread-safe.** Spans from HTTP handler threads, the micro-batcher
   worker, and the training loop interleave; each thread keeps its own
   nesting stack (``threading.local``) and completed spans append into
   one lock-guarded ring buffer.
3. **Bounded memory.** The ring (default 2^16 events) drops the OLDEST
   events on overflow and counts the drops, so a week-long run can keep
   the tracer on and still export the most recent window.
4. **Deterministic under test.** The clock is injectable; tests drive a
   fake clock and assert exact timestamps/durations.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Callable, Dict, List, Optional

__all__ = ["Tracer", "span", "instant", "counter", "enable", "disable",
           "enabled", "get_tracer", "set_tracer"]


class Tracer:
    """Ring-buffered span collector with Chrome-trace export.

    Completed spans are dicts ``{name, ts, dur, tid, depth, args}`` with
    ``ts``/``dur`` in SECONDS on the tracer's clock (conversion to the
    Chrome format's microseconds happens at export). ``tid`` is a small
    stable per-thread integer, 0 for the first thread seen."""

    def __init__(self, capacity: int = 65536,
                 clock: Callable[[], float] = time.perf_counter):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.clock = clock
        self.capacity = int(capacity)
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._local = threading.local()
        self._tids: Dict[int, int] = {}
        self._recorded = 0  # total ever, to report drops

    # ---------------------------------------------------------- span stack
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = self._tids[ident] = len(self._tids)
            return tid

    def record(self, name: str, t0: float, t1: float, depth: int,
               args: Optional[dict] = None,
               cat: Optional[str] = None) -> None:
        ev = {"name": name, "ts": t0, "dur": max(t1 - t0, 0.0),
              "tid": self._tid(), "depth": depth}
        if args:
            ev["args"] = args
        if cat is not None:
            ev["cat"] = cat
        with self._lock:
            self._ring.append(ev)
            self._recorded += 1

    def instant(self, name: str, args: Optional[dict] = None) -> None:
        """A zero-duration marker on the timeline (Chrome-trace ``ph: i``
        with global scope) — fault injections, elastic reshapes, OOMs
        land as flags next to the step phases instead of only counting
        in the registry (ISSUE 12 satellite)."""
        ev = {"name": name, "ts": self.clock(), "dur": 0.0,
              "tid": self._tid(), "depth": 0, "ph": "i"}
        if args:
            ev["args"] = args
        with self._lock:
            self._ring.append(ev)
            self._recorded += 1

    def counter(self, name: str, values: dict) -> None:
        """A Chrome-trace counter sample (``ph: C``) — Perfetto renders
        a series per key, so per-step HBM bytes plot over the same
        timeline the spans live on."""
        ev = {"name": name, "ts": self.clock(), "dur": 0.0,
              "tid": self._tid(), "depth": 0, "ph": "C",
              "args": dict(values)}
        with self._lock:
            self._ring.append(ev)
            self._recorded += 1

    # ------------------------------------------------------------ snapshot
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    @property
    def dropped(self) -> int:
        with self._lock:
            return max(0, self._recorded - len(self._ring))

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._recorded = 0

    # -------------------------------------------------------------- export
    def chrome_trace(self) -> dict:
        """The Chrome Trace Event Format object (``traceEvents`` of
        ``"ph": "X"`` complete events, timestamps in microseconds).
        Loadable by chrome://tracing and Perfetto; nesting is inferred
        by the viewer from interval containment per (pid, tid)."""
        pid = os.getpid()
        evs = []
        for e in self.events():
            ph = e.get("ph", "X")
            ev = {"name": e["name"], "cat": e.get("cat", "bigdl"),
                  "ph": ph,
                  "ts": round(e["ts"] * 1e6, 3),
                  "pid": pid, "tid": e["tid"]}
            if ph == "X":
                ev["dur"] = round(e["dur"] * 1e6, 3)
            elif ph == "i":
                ev["s"] = "g"  # global scope: a full-height flag
            if "args" in e:
                ev["args"] = e["args"]
            evs.append(ev)
        # stable viewer ordering (and easier assertions): by ts, with
        # parents before their children at equal ts (larger dur first)
        evs.sort(key=lambda ev: (ev["tid"], ev["ts"], -ev.get("dur", 0.0)))
        return {"traceEvents": evs, "displayTimeUnit": "ms",
                "otherData": {"dropped_events": self.dropped}}

    def export_chrome_trace(self, path: str) -> int:
        """Write the Chrome-trace JSON to ``path``; returns the event
        count written."""
        trace = self.chrome_trace()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(trace, f)
        return len(trace["traceEvents"])


class _Span:
    """Active span context manager (only allocated when a tracer is
    installed)."""

    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: Tracer, name: str, args: Optional[dict]):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_Span":
        self._tracer._stack().append(self._name)
        self._t0 = self._tracer.clock()
        return self

    def __exit__(self, *exc) -> None:
        t1 = self._tracer.clock()
        st = self._tracer._stack()
        st.pop()
        self._tracer.record(self._name, self._t0, t1, depth=len(st),
                            args=self._args)


class _NoopSpan:
    """Shared do-nothing context manager — what ``span()`` returns when
    tracing is disabled. A singleton: the disabled path allocates
    nothing."""

    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


NOOP_SPAN = _NoopSpan()

_TRACER: Optional[Tracer] = None


def span(name: str, **args):
    """``with span("data_wait"): ...`` — time a named phase.

    Disabled (no tracer installed): one global load + ``None`` check,
    returns the shared no-op singleton. Enabled: records a completed
    span into the tracer's ring on exit, nested under any enclosing
    spans of the same thread."""
    t = _TRACER
    if t is None:
        return NOOP_SPAN
    return _Span(t, name, args or None)


def instant(name: str, **args) -> None:
    """Module-level instant marker — same disabled-cost contract as
    :func:`span` (one global load + ``None`` check, then nothing)."""
    t = _TRACER
    if t is not None:
        t.instant(name, args or None)


def counter(name: str, values: dict) -> None:
    """Module-level counter sample — no-op unless a tracer is
    installed."""
    t = _TRACER
    if t is not None:
        t.counter(name, values)


def enable(capacity: int = 65536,
           clock: Callable[[], float] = time.perf_counter) -> Tracer:
    """Install (and return) a fresh global tracer."""
    global _TRACER
    _TRACER = Tracer(capacity=capacity, clock=clock)
    return _TRACER


def disable() -> None:
    global _TRACER
    _TRACER = None


def enabled() -> bool:
    return _TRACER is not None


def get_tracer() -> Optional[Tracer]:
    return _TRACER


def set_tracer(tracer: Optional[Tracer]) -> None:
    """Install an externally constructed tracer (tests inject a fake
    clock this way)."""
    global _TRACER
    _TRACER = tracer
