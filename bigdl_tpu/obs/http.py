"""Live ``/metrics`` for training runs (ISSUE 7 tentpole #4).

Serving got scrape-based observability in PR 5; training runs still
reported nothing until the process exited. This starts the same
plaintext exposition (``obs.metrics.MetricsRegistry.render`` — one
format, one set of dashboards) on a daemon-thread HTTP listener inside
any training/perf process:

    bigdl-tpu perf -m resnet50 --obs --metricsPort 9100 &
    curl localhost:9100/metrics     # step-phase histograms, live

Deliberately minimal: GET ``/metrics`` (Prometheus text) and
``/healthz`` (liveness JSON) only, bound to localhost by default, one
thread per connection via the stdlib ``ThreadingHTTPServer``. The
listener never blocks training — scrapes read instrument snapshots
under their own short locks — and dies with the process (daemon
threads), so a crashed run can't leak a port.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

logger = logging.getLogger("bigdl_tpu")

__all__ = ["MetricsServer", "start_metrics_server"]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def do_GET(self):  # noqa: N802 (stdlib naming)
        if self.path == "/metrics":
            try:
                data = self.server.registry.render().encode()
            except Exception as e:  # a broken gauge fn must not 500-loop
                data = f"# render error: {e}\n".encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
        elif self.path == "/healthz":
            data = json.dumps({"status": "ok"}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
        else:
            data = json.dumps(
                {"error": f"unknown path {self.path}"}).encode()
            self.send_response(404)
            self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def log_message(self, fmt, *args):
        logger.debug("%s - %s", self.address_string(), fmt % args)


class MetricsServer:
    """A running training-side metrics listener; ``close()`` to stop."""

    def __init__(self, registry, host: str = "127.0.0.1", port: int = 0):
        self._srv = ThreadingHTTPServer((host, port), _Handler)
        self._srv.daemon_threads = True
        self._srv.registry = registry  # type: ignore[attr-defined]
        self.host = host
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, kwargs={"poll_interval": 0.5},
            name="obs-metrics", daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5.0)


def start_metrics_server(registry, port: int = 0,
                         host: str = "127.0.0.1", strict: bool = False
                         ) -> Optional[MetricsServer]:
    """Start the listener (port 0 = auto-pick a free port; the bound
    port is printed and rides in the ObsState annotation). Default bind
    failure is a warning returning None — observability must never kill
    the run it observes — but ``strict`` (the CLI's explicit
    ``--metricsPort N``) turns a taken port into a clean SystemExit
    instead of a mid-run socket traceback (ISSUE 12 satellite)."""
    try:
        srv = MetricsServer(registry, host=host, port=port)
    except OSError as e:
        if strict:
            raise SystemExit(
                f"--metricsPort {port}: cannot bind {host}:{port} ({e}); "
                "pick another port or use --metricsPort 0 to auto-pick "
                "a free one")
        logger.warning("obs metrics listener failed to bind %s:%d: %s",
                       host, port, e)
        return None
    logger.info("obs metrics listening on %s", srv.url)
    print(f"obs metrics listening on {srv.url}", flush=True)
    return srv
