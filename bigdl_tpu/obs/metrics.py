"""Shared metrics registry: lock-cheap counters, gauges, and latency
histograms with a plaintext exposition format (ISSUE 7 tentpole #2).

Grown out of ``serving/metrics.py`` (which now re-exports from here):
the reference monitors training through Spark's accumulator/UI machinery
and BigDL aggregates cluster-wide named counters through
``optim/Metrics.scala``; the TPU-native stack needs ONE instrument set
shared by training (step-phase histograms, cumulative phase seconds),
resilience (fault/retry counters), and serving (request counters,
latency quantiles) — all exposable over HTTP for scrape-based
collection.

Design: each instrument guards its few-word update with one short-held
``threading.Lock`` (never held across an engine call or IO), histograms
use fixed log-spaced buckets so ``observe`` is a bisect + two adds, and
quantiles are estimated at render time by linear interpolation inside
the covering bucket — the standard fixed-bucket estimator, exact at
bucket edges and monotone in between. No dependencies.

Process-global registry: :func:`get_registry` returns the one shared
:class:`MetricsRegistry` (created on first call; the first caller's
namespace wins — ``bigdl`` for training CLIs, ``bigdl_serving`` when
``bigdl-tpu serve`` boots first). Components that want isolation (unit
tests, multiple servers in one process) construct their own registry and
pass it explicitly, exactly as serving always has.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "LabelledRegistry",
           "DEFAULT_LATENCY_BUCKETS_MS", "ITL_BUCKETS_MS",
           "PHASE_BUCKETS_MS",
           "get_registry", "set_registry", "reset_registry",
           "phase_histograms", "TRAIN_PHASES"]

# log-spaced 100 us .. 60 s: covers a CPU smoke test and a loaded TPU
# server with ~2x resolution per decade
DEFAULT_LATENCY_BUCKETS_MS: tuple = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0)

# inter-token latencies cluster tightly (a healthy decode step is a few
# ms, TTFT a few tens); the serving-latency buckets above lose a whole
# p50..p99 spread inside one bucket, so TTFT/TPOT/ITL histograms
# (serving/reqtrace.py, ISSUE 15) get ~2x finer resolution below 100 ms
ITL_BUCKETS_MS: tuple = (
    0.25, 0.5, 1.0, 1.5, 2.5, 4.0, 6.0, 10.0, 15.0, 25.0, 40.0, 60.0,
    100.0, 150.0, 250.0, 400.0, 600.0, 1000.0, 2500.0, 5000.0, 10000.0,
    30000.0, 60000.0)

# training step phases are faster at the bottom (a warm h2d is tens of
# microseconds) and slower at the top (a cold compile-triggering
# dispatch is minutes) than request latencies
PHASE_BUCKETS_MS: tuple = (
    0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0, 1000.0,
    5000.0, 10000.0, 60000.0, 300000.0)

# the step-phase taxonomy (PERF.md §15): every per-step instrument and
# perf-JSON phase column derives its name from this tuple
TRAIN_PHASES = ("data_wait", "h2d", "dispatch", "device", "ckpt")


class Counter:
    """Monotone counter; ``inc`` is one lock + one add."""

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name, self.help = name, help
        self.labels = dict(labels) if labels else None
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value: either ``set()`` from the owner or backed by
    a ``fn`` sampled at render time (queue depth, occupancy)."""

    def __init__(self, name: str, help: str = "",
                 fn: Optional[Callable[[], float]] = None,
                 labels: Optional[Dict[str, str]] = None):
        self.name, self.help = name, help
        self.labels = dict(labels) if labels else None
        self._fn = fn
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:
                return float("nan")
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket histogram with quantile estimation.

    ``bounds`` are bucket upper edges (ascending); one implicit +Inf
    bucket catches overflow. ``quantile(q)`` interpolates linearly
    inside the covering bucket (lower edge = previous bound, 0 for the
    first; the +Inf bucket reports the max ever observed — a bounded
    answer instead of infinity)."""

    def __init__(self, name: str, help: str = "",
                 bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS,
                 labels: Optional[Dict[str, str]] = None):
        self.name, self.help = name, help
        self.labels = dict(labels) if labels else None
        self.bounds: List[float] = sorted(float(b) for b in bounds)
        if not self.bounds:
            raise ValueError("histogram needs at least one bucket bound")
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0
        self._max = float("-inf")

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if v > self._max:
                self._max = v

    def snapshot(self) -> dict:
        with self._lock:
            return {"counts": list(self._counts), "sum": self._sum,
                    "count": self._count, "max": self._max}

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def quantile(self, q: float, snap: Optional[dict] = None) -> float:
        """Estimated q-quantile (q in [0, 1]); NaN when empty."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile {q} outside [0, 1]")
        s = snap or self.snapshot()
        total = s["count"]
        if total == 0:
            return float("nan")
        rank = q * total
        cum = 0.0
        for i, c in enumerate(s["counts"]):
            if c == 0:
                continue
            if cum + c >= rank:
                lo = 0.0 if i == 0 else self.bounds[i - 1]
                hi = s["max"] if i == len(self.bounds) else self.bounds[i]
                frac = (rank - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return s["max"]


class MetricsRegistry:
    """Instrument factory + plaintext exposition.

    ``render()`` emits a Prometheus-compatible text page: HELP/TYPE
    lines, counter/gauge samples, histogram ``_bucket``/``_sum``/
    ``_count`` series plus estimated ``{quantile=...}`` samples. The
    config provenance (``set_provenance``) is stamped into every scrape
    twice: as an ``<ns>_info`` gauge with label pairs, and as a one-line
    ``# provenance {json}`` comment so load generators can embed the
    exact config into their bench JSON without a label parser (the
    perf-JSON contract from PRs 2-4, extended to every scrape surface).

    The default namespace stays ``bigdl_serving`` — the name every
    existing serving scrape consumer (serving_bench, the smoke jobs,
    dashboards) was built against; the training listener passes
    ``bigdl`` explicitly."""

    QUANTILES = (0.5, 0.95, 0.99)

    def __init__(self, namespace: str = "bigdl_serving",
                 clock: Callable[[], float] = time.time):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        self._provenance: dict = {}
        self._clock = clock  # injectable: uptime-derived gauges (tokens/s)
        self._t0 = clock()   # become deterministic under test

    def _register(self, name, labels, factory):
        # unlabelled instruments keep their bare name as the key, so every
        # pre-label consumer (tests poking ``reg._metrics["..."]``, scrape
        # parsers) sees an unchanged map; labelled series append the
        # rendered label set so one name can carry many series
        key = name if not labels else f"{name}{{{_labels_str(labels)}}}"
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = factory()
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        m = self._register(name, labels, lambda: Counter(name, help, labels))
        if not isinstance(m, Counter):
            raise TypeError(f"{name} already registered as {type(m).__name__}")
        return m

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None,
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        m = self._register(name, labels,
                           lambda: Gauge(name, help, fn, labels))
        if not isinstance(m, Gauge):
            raise TypeError(f"{name} already registered as {type(m).__name__}")
        return m

    def histogram(self, name: str, help: str = "",
                  bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS,
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        m = self._register(name, labels,
                           lambda: Histogram(name, help, bounds, labels))
        if not isinstance(m, Histogram):
            raise TypeError(f"{name} already registered as {type(m).__name__}")
        return m

    def labelled(self, **labels: str) -> "LabelledRegistry":
        """A view of this registry that stamps ``labels`` onto every
        instrument created through it — how each dp engine replica keeps
        calling the plain counter/gauge/histogram API while its series
        land as ``...{replica="0"}`` on the shared scrape page."""
        return LabelledRegistry(self, labels)

    def set_provenance(self, prov: dict) -> None:
        with self._lock:
            self._provenance = dict(prov)

    @property
    def provenance(self) -> dict:
        with self._lock:
            return dict(self._provenance)

    def uptime_s(self) -> float:
        return self._clock() - self._t0

    # ------------------------------------------------------------ exposition
    def render(self) -> str:
        ns = self.namespace
        with self._lock:
            metrics = list(self._metrics.values())
            prov = dict(self._provenance)
        # callable provenance values resolve at scrape time — measured
        # facts (e.g. spec accepted-tokens/step) ride next to the static
        # config in the same machine-scrapable line
        for k, v in list(prov.items()):
            if callable(v):
                try:
                    prov[k] = v()
                except Exception:
                    prov[k] = None
        lines: List[str] = []
        if prov:
            # machine-scrapable config provenance, one JSON line
            lines.append(f"# provenance {json.dumps(prov, sort_keys=True)}")
            labels = ",".join(
                f'{k}="{_label_escape(v)}"' for k, v in sorted(prov.items()))
            lines.append(f"# HELP {ns}_info serving config provenance")
            lines.append(f"# TYPE {ns}_info gauge")
            lines.append(f"{ns}_info{{{labels}}} 1")
        lines.append(f"# HELP {ns}_uptime_seconds process uptime")
        lines.append(f"# TYPE {ns}_uptime_seconds gauge")
        lines.append(f"{ns}_uptime_seconds {self.uptime_s():.3f}")
        # series of one name emit contiguously (unlabelled aggregate
        # first, then labelled replicas) with HELP/TYPE stated once
        metrics.sort(key=lambda m: (m.name, _labels_str(m.labels)))
        seen: set = set()
        for m in metrics:
            full = f"{ns}_{m.name}"
            base = m.labels or {}
            sfx = f"{{{_labels_str(base)}}}" if base else ""
            if m.name not in seen:
                seen.add(m.name)
                if m.help:
                    lines.append(f"# HELP {full} {m.help}")
                kind = ("counter" if isinstance(m, Counter) else
                        "gauge" if isinstance(m, Gauge) else "histogram")
                lines.append(f"# TYPE {full} {kind}")
            if isinstance(m, Counter):
                lines.append(f"{full}{sfx} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"{full}{sfx} {_fmt(m.value)}")
            elif isinstance(m, Histogram):
                snap = m.snapshot()
                cum = 0
                for b, c in zip(m.bounds, snap["counts"]):
                    cum += c
                    lines.append(
                        f"{full}_bucket{{"
                        f'{_labels_str(base, le=_fmt(b))}}} {cum}')
                lines.append(
                    f'{full}_bucket{{{_labels_str(base, le="+Inf")}}} '
                    f'{snap["count"]}')
                lines.append(f"{full}_sum{sfx} {_fmt(snap['sum'])}")
                lines.append(f"{full}_count{sfx} {snap['count']}")
                for q in self.QUANTILES:
                    lines.append(
                        f"{full}{{{_labels_str(base, quantile=str(q))}}} "
                        f"{_fmt(m.quantile(q, snap))}")
        return "\n".join(lines) + "\n"


class LabelledRegistry:
    """Label-stamping view over a :class:`MetricsRegistry`.

    Forwards the whole instrument-factory surface with a fixed label set
    merged in, so a component built against the plain registry API
    (engine, batcher, decoder) can be instantiated per dp replica without
    knowing it is one of N. Views nest: ``labelled()`` on a view merges
    label sets (inner wins on collision)."""

    def __init__(self, registry: MetricsRegistry, labels: Dict[str, str]):
        self._registry = registry
        self.labels = {str(k): str(v) for k, v in labels.items()}

    @property
    def namespace(self) -> str:
        return self._registry.namespace

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._registry.counter(name, help,
                                      labels={**self.labels, **(labels or {})})

    def gauge(self, name: str, help: str = "",
              fn: Optional[Callable[[], float]] = None,
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._registry.gauge(name, help, fn,
                                    labels={**self.labels, **(labels or {})})

    def histogram(self, name: str, help: str = "",
                  bounds: Sequence[float] = DEFAULT_LATENCY_BUCKETS_MS,
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        return self._registry.histogram(
            name, help, bounds, labels={**self.labels, **(labels or {})})

    def labelled(self, **labels: str) -> "LabelledRegistry":
        return LabelledRegistry(self._registry, {**self.labels, **labels})

    def uptime_s(self) -> float:
        return self._registry.uptime_s()

    def set_provenance(self, prov: dict) -> None:
        self._registry.set_provenance(prov)

    @property
    def provenance(self) -> dict:
        return self._registry.provenance

    def render(self) -> str:
        return self._registry.render()


def _fmt(v) -> str:
    f = float(v)
    if f != f:  # NaN (empty-histogram quantile, dead gauge fn)
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _label_escape(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace(
        "\n", "\\n")


def _labels_str(labels: Optional[Dict[str, str]], **extra: str) -> str:
    """Sorted ``k="v"`` label rendering; ``extra`` pairs (``le``,
    ``quantile``) merge after the instrument's own labels."""
    merged = dict(labels or {})
    merged.update(extra)
    return ",".join(
        f'{k}="{_label_escape(v)}"' for k, v in sorted(merged.items()))


# --------------------------------------------------------------- global
_GLOBAL: Optional[MetricsRegistry] = None
_GLOBAL_LOCK = threading.Lock()


def get_registry(namespace: str = "bigdl") -> MetricsRegistry:
    """The process-global registry shared by training, resilience, and
    serving (created on first call; later ``namespace`` arguments are
    ignored — one process, one exposition page)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = MetricsRegistry(namespace=namespace)
        return _GLOBAL


def set_registry(reg: Optional[MetricsRegistry]) -> None:
    """Install (or clear, with None) the process-global registry."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        _GLOBAL = reg


def reset_registry() -> None:
    """Drop the global registry so the next :func:`get_registry` starts
    fresh (tests)."""
    set_registry(None)


def phase_histograms(registry: MetricsRegistry, prefix: str = "train",
                     phases: Sequence[str] = TRAIN_PHASES
                     ) -> Dict[str, Histogram]:
    """Register (or fetch) the per-step phase histograms — one
    ``<prefix>_phase_<name>_ms`` per phase in the taxonomy — plus their
    cumulative ``<prefix>_phase_<name>_seconds_total`` counters, and
    return ``{phase: histogram}``. Idempotent (the registry dedups by
    name), so the Optimizer and the perf harness share series."""
    out = {}
    for ph in phases:
        out[ph] = registry.histogram(
            f"{prefix}_phase_{ph}_ms",
            f"per-step {ph} phase time", bounds=PHASE_BUCKETS_MS)
        registry.counter(f"{prefix}_phase_{ph}_seconds_total",
                         f"cumulative {ph} phase seconds")
    return out
