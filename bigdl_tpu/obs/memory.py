"""HBM attribution: explain every byte, forecast the fit, autopsy the
OOM (ISSUE 12 tentpole).

PR 8 made device *time* explainable (``obs/attrib.py``); this module is
the memory twin. Four surfaces:

* **static plan** — :func:`build_plan` at trace time: per-category byte
  accounting (params / optimizer state / gradients+grad-comm buckets /
  activations+temps / KV cache / input batch) from the abstract pytrees
  plus ``compiled.memory_analysis()`` of the exact step. The category
  table totals to the compiler's number BY CONSTRUCTION: the argument
  bytes are split between the known argument pytrees and an explicit
  ``unattributed`` row, the temp bytes between the gradient estimate and
  ``activations``, so the cross-check can only drift where the abstract
  estimate and the compiler genuinely disagree (and then the drift is a
  visible row, not a silent mismatch).
* **live sampling** — :class:`HbmSampler` wraps ``device.memory_stats()``
  (None on CPU backends — the sampler degrades to a no-op) and publishes
  ``hbm_bytes_in_use`` / ``hbm_peak_bytes`` / ``hbm_largest_free_block``
  gauges on the shared registry plus Chrome-trace counter events so
  Perfetto plots HBM over the same timeline the step phases live on.
* **OOM post-mortem** — the Optimizer dispatch loop and the serving
  engines call :func:`handle_oom` from their RESOURCE_EXHAUSTED catch;
  it writes a MemoryReport (last plan, live stats, top live buffers,
  headroom history) to the installed ``--traceDir`` and stamps the fault
  log like other resilience events, then the caller re-raises.
* **fit forecaster** — :func:`forecast` fits total bytes linearly over
  two plans at different batch sizes (fixed + per-sample slope) and
  predicts the max batch that still fits the device; ``bigdl-tpu
  explain --mem <model>`` renders it (:func:`plan_for_model` /
  :func:`render`).

Like ``resilience.faults``, the cross-layer channel is one module-level
install: ``install(trace_dir=..., plan=..., sampler=...)`` arms the OOM
path process-wide; call sites stay one ``handle_oom(e, ctx)`` line that
can never change the semantics of the run it observes.
"""

from __future__ import annotations

import json
import logging
import math
import os
import time
from typing import Optional, Tuple

logger = logging.getLogger("bigdl_tpu")

__all__ = [
    "HBM_BYTES", "device_hbm_bytes", "tree_bytes", "build_plan",
    "forecast", "plan_for_model", "render", "compact",
    "serving_kv_plan", "forecast_slots",
    "HbmSampler", "install", "installed_plan", "installed_trace_dir",
    "is_resource_exhausted", "handle_oom", "write_oom_report",
    "OOM_REPORT_NAME",
]

# Per-chip HBM capacity (public figures), matched like perf._PEAK_FLOPS:
# substring against the squashed device_kind, most specific first, match
# label reported alongside the number so a fallback can never hide. The
# CPU nominal keeps headroom DEFINED in CPU test runs (same contract as
# the 1e12-FLOPs CPU nominal in the MFU table).
HBM_BYTES = (
    ("v6lite", 32e9), ("v6e", 32e9), ("trillium", 32e9),
    ("v5lite", 16e9), ("v5e", 16e9),
    ("v5p", 95e9),
    ("v4lite", 16e9), ("v4", 32e9),
    ("v3", 16e9), ("v2", 8e9),
    ("cpu", 8e9),  # nominal, so headroom stays defined in CPU test runs
)

OOM_REPORT_NAME = "memory_report.json"

# what build_plan reads off CompiledMemoryStats (jaxlib names)
_MA_FIELDS = ("argument_size_in_bytes", "output_size_in_bytes",
              "temp_size_in_bytes", "alias_size_in_bytes",
              "generated_code_size_in_bytes")


def device_hbm_bytes(device=None) -> Tuple[float, str]:
    """Return ``(hbm_bytes, matched_label)`` for one chip."""
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:
            return 8e9, "cpu"
    kind = getattr(device, "device_kind", "cpu") or "cpu"
    squashed = kind.replace(" ", "").replace("-", "").lower()
    for k, v in HBM_BYTES:
        if k in squashed:
            return v, k
    return 8e9, f"UNMATCHED({kind})->8e9-nominal"


def tree_bytes(tree) -> int:
    """Total leaf bytes of a pytree — works on concrete arrays,
    ShapeDtypeStructs, and anything else exposing shape+dtype."""
    if tree is None:
        return 0
    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
            continue
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += int(np.prod(shape)) * np.dtype(dtype).itemsize
    return total


def _grad_comm_pad(grad_comm: Optional[dict]) -> int:
    """Extra bytes the bucketed grad all-reduce holds beyond the raw
    gradient tree (bucket padding + the flat staging buffer is already
    the gradient itself, so only padding counts)."""
    if not grad_comm:
        return 0
    pad = grad_comm.get("pad_bytes")
    if pad is not None:
        return int(pad)
    n = int(grad_comm.get("n_buckets") or 0)
    bb = grad_comm.get("bucket_bytes")
    total = grad_comm.get("total_bytes") or grad_comm.get("wire_bytes")
    if n and bb and total:  # worst-case: last bucket padded to the bound
        return max(0, int(n) * int(bb) - int(total))
    return 0


def build_plan(compiled=None, *, params=None, opt_state=None,
               batch=None, kv_cache=None, grad_comm: Optional[dict] = None,
               device=None, batch_size: Optional[int] = None,
               model_name: Optional[str] = None) -> dict:
    """The static memory plan: a per-category byte table that totals to
    the compiler's number.

    ``compiled`` is the exact lowered+compiled step (or any object with
    ``memory_analysis()``); without it the plan is abstract-only (the
    pre-compile lint path): argument-side categories from the pytrees, a
    params-sized gradient estimate, no activation row.
    """
    params_b = tree_bytes(params)
    opt_b = tree_bytes(opt_state)
    input_b = tree_bytes(batch)
    kv_b = tree_bytes(kv_cache)
    grads_b = params_b + _grad_comm_pad(grad_comm)

    cats = {"params": params_b, "optimizer": opt_b, "gradients": grads_b,
            "activations": 0, "kv_cache": kv_b, "input": input_b,
            "outputs": 0, "unattributed": 0}
    compiler: Optional[dict] = None
    compiler_total: Optional[int] = None
    if compiled is not None:
        ma = compiled.memory_analysis()
        compiler = {f: int(getattr(ma, f, 0) or 0) for f in _MA_FIELDS}
        arg = compiler["argument_size_in_bytes"]
        out = compiler["output_size_in_bytes"]
        tmp = compiler["temp_size_in_bytes"]
        alias = compiler["alias_size_in_bytes"]
        gen = compiler["generated_code_size_in_bytes"]
        compiler_total = arg + tmp + max(0, out - alias) + gen
        # split the argument bytes: known pytrees + explicit remainder.
        # If the abstract sum overshoots (a cast the compiler folded
        # away), scale the known rows down so the table still totals.
        known = params_b + opt_b + input_b + kv_b
        if known <= arg:
            cats["unattributed"] = arg - known
        elif known:
            scale = arg / known
            for k in ("params", "optimizer", "kv_cache", "input"):
                cats[k] = int(cats[k] * scale)
            cats["unattributed"] = arg - (cats["params"] + cats["optimizer"]
                                          + cats["kv_cache"] + cats["input"])
        # split the temp bytes: gradients live inside XLA's temps; what
        # is left over is activations + scratch. A temp smaller than the
        # gradient estimate means the compiler fused gradients away —
        # report what it kept, not the estimate.
        cats["gradients"] = min(grads_b, tmp)
        cats["activations"] = tmp - cats["gradients"]
        # non-aliased outputs: with donation the new params/opt state
        # alias the old ones (alias ~ output); without (CPU) the step
        # genuinely holds both at peak
        cats["outputs"] = max(0, out - alias)
        cats["unattributed"] += gen
        total = sum(cats.values())
    else:
        total = params_b + opt_b + grads_b + input_b + kv_b

    hbm, hbm_label = device_hbm_bytes(device)
    plan = {
        "categories": cats,
        "total_bytes": int(total),
        "compiler": compiler,
        "compiler_total_bytes": compiler_total,
        "hbm_bytes": int(hbm),
        "hbm_match": hbm_label,
        "headroom_bytes": int(hbm - total),
        "headroom_frac": round((hbm - total) / hbm, 4) if hbm else None,
        "batch": batch_size,
    }
    if model_name:
        plan["model"] = model_name
    try:
        import jax
        plan["device"] = getattr(jax.devices()[0], "device_kind", "unknown")
    except Exception:
        plan["device"] = "unknown"
    return plan


def forecast(plan_small: dict, plan_big: dict) -> dict:
    """Linear fit of total bytes over batch size from two plans:
    ``total(b) = fixed + slope * b`` — the slope is the per-sample
    activation+input cost, the intercept the model-resident state.
    Predicts the max batch that still fits the device HBM."""
    na, nb = plan_small.get("batch"), plan_big.get("batch")
    if not na or not nb or na == nb:
        raise ValueError("forecast needs two plans at distinct batch "
                         f"sizes, got {na!r} and {nb!r}")
    if na > nb:
        plan_small, plan_big, na, nb = plan_big, plan_small, nb, na
    ta = float(plan_small["total_bytes"])
    tb = float(plan_big["total_bytes"])
    slope = (tb - ta) / (nb - na)
    fixed = ta - slope * na
    cap = float(plan_big["hbm_bytes"])
    if slope > 0:
        max_batch = int(math.floor((cap - fixed) / slope))
    else:  # degenerate (constant-folded batch, or toy model): no bound
        max_batch = None
    return {
        "bytes_per_sample": int(slope),
        "fixed_bytes": int(fixed),
        "fit_batches": [na, nb],
        "hbm_bytes": int(cap),
        "predicted_max_batch": (max_batch if max_batch is None
                                else max(max_batch, 0)),
    }


def plan_for_model(model_name: str, batch: int,
                   seq_len: Optional[int] = None,
                   use_bf16: bool = False) -> dict:
    """Build, lower, and compile the single-device training step for a
    perf-zoo model at ``batch`` and return its memory plan — the
    ``explain --mem`` / forecaster entry point. Mirrors the perf
    harness's step (SGD+momentum, value_and_grad, donated state) so the
    plan describes the bytes a real run would hold."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.cli.perf import _LM_VOCAB, build_model
    from bigdl_tpu.optim import SGD

    model, in_shape = build_model(model_name, seq_len=seq_len)
    is_lm = model_name.startswith("transformer_lm")
    crit = (nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
            if is_lm else nn.ClassNLLCriterion())
    opt = SGD(learning_rate=0.01, momentum=0.9)
    dtype = (jnp.bfloat16 if (use_bf16 and jax.default_backend() == "tpu")
             else jnp.float32)

    rng = np.random.RandomState(0)
    if is_lm:
        x = jnp.asarray(rng.randint(0, _LM_VOCAB, (batch, *in_shape))
                        .astype(np.int32))
        y = jnp.asarray(rng.randint(0, _LM_VOCAB, (batch, *in_shape))
                        .astype(np.int32))
    else:
        x = jnp.asarray(np.ones((batch, *in_shape), np.float32))
        y = jnp.asarray(rng.randint(0, 1000 if in_shape[0] > 30 else 10,
                                    batch).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0))
    mod_state = model.init_state()
    opt_state = opt.init(params)

    def train_step(params, mod_state, opt_state, x, y, rng):
        def loss_fn(p):
            xc = (x.astype(dtype)
                  if jnp.issubdtype(x.dtype, jnp.floating) else x)
            out, ms = model.apply(p, mod_state, xc, training=True, rng=rng)
            return crit(out.astype(jnp.float32), y), ms

        (loss, ms), grads = jax.value_and_grad(loss_fn,
                                               has_aux=True)(params)
        new_p, new_o = opt.update(grads, opt_state, params)
        return new_p, ms, new_o, loss

    k = jax.random.PRNGKey(1)
    compiled = jax.jit(train_step, donate_argnums=(0, 1, 2)).lower(
        params, mod_state, opt_state, x, y, k).compile()
    return build_plan(compiled, params=params, opt_state=opt_state,
                      batch=(x, y), device=jax.devices()[0],
                      batch_size=batch, model_name=model_name)


def serving_kv_plan(model_name: str, *, seq_len: Optional[int] = None,
                    page_tokens: Optional[int] = None,
                    quantize: Optional[str] = None,
                    cache_dtype=None, device=None) -> dict:
    """Per-slot serving byte accounting for a transformer_lm target: the
    KV-cache cost of one decode slot (dense slab, or the kv8 page-pool
    layout — int8 rows + one f32 scale per (page, head, token), exactly
    :class:`~bigdl_tpu.serving.kv_pages.QuantPool`'s arrays) plus the
    resident weight bytes under ``--quantize``. This is the dtype-aware
    half of ``explain --mem``: quantized modes change per-slot and
    fixed bytes, and :func:`forecast_slots` re-fits the max-slot
    prediction from them."""
    import jax
    import numpy as np

    from bigdl_tpu.cli.perf import build_model
    from bigdl_tpu.serving.quant import parse_quantize, quantize_params

    if not model_name.startswith("transformer_lm"):
        raise ValueError("serving_kv_plan targets transformer_lm* models "
                         f"(decode KV slots), got {model_name!r}")
    model, _ = build_model(model_name, seq_len=seq_len)
    wfmt, kv8 = parse_quantize(quantize) if quantize else (None, False)
    L = int(model.max_len)
    pt = page_tokens
    if kv8 and pt is None:
        # same auto ladder the serve CLI uses for --quantize kv8
        for cand in (128, 64, 32, 256):
            if L % cand == 0:
                pt = cand
                break
        if pt is None:
            raise ValueError(f"no page size in (128, 64, 32, 256) "
                             f"divides max_len {L}; pass page_tokens")
    dt = np.dtype(cache_dtype) if cache_dtype is not None \
        else np.dtype(np.float32)
    cache = model.encoder.init_cache(1, L, dt)
    kv_slot = 0
    for leaf in jax.tree_util.tree_leaves(cache):
        _, kh, _, hd = leaf.shape
        if kv8:
            # QuantPool layout: q int8 (pages, kh, pt, hd) + s f32
            # (pages, kh, pt); a slot owns L/pt pages
            kv_slot += (L // pt) * (kh * pt * hd * 1 + kh * pt * 4)
        else:
            kv_slot += int(np.prod(leaf.shape)) * dt.itemsize
    params = model.init(jax.random.PRNGKey(0))
    dense_b = tree_bytes(params)
    if wfmt is not None:
        params = quantize_params(params, wfmt)
    params_b = tree_bytes(params)
    hbm, hbm_label = device_hbm_bytes(device)
    return {
        "model": model_name,
        "quantize": quantize or "off",
        "max_len": L,
        "page_tokens": pt,
        "cache_dtype": dt.name,
        "kv_bytes_per_slot": int(kv_slot),
        "params_bytes": int(params_b),
        "params_bytes_f32": int(dense_b),
        "hbm_bytes": int(hbm),
        "hbm_match": hbm_label,
    }


def forecast_slots(plan: dict, hbm_bytes=None) -> dict:
    """Max decode slots that fit the budget: ``(hbm - weights) /
    kv_bytes_per_slot`` — the serving twin of :func:`forecast`. Under
    kv8 the per-slot cost roughly quarters, so the prediction roughly
    doubles even after the weight savings are counted."""
    cap = float(hbm_bytes if hbm_bytes is not None
                else plan["hbm_bytes"])
    fixed = float(plan["params_bytes"])
    per = float(plan["kv_bytes_per_slot"])
    n = int(math.floor((cap - fixed) / per)) if per > 0 else None
    return {
        "hbm_bytes": int(cap),
        "fixed_bytes": int(fixed),
        "kv_bytes_per_slot": int(per),
        "predicted_max_slots": (max(n, 0) if n is not None else None),
    }


# ------------------------------------------------------------ rendering
def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024.0 or unit == "GiB":
            return (f"{n:.0f} {unit}" if unit == "B"
                    else f"{n:.2f} {unit}")
        n /= 1024.0
    return f"{n:.2f} GiB"


def render(plan: dict, fc: Optional[dict] = None) -> str:
    """Human table of the plan (and forecast, when given) — the memory
    twin of ``attrib.render``."""
    from bigdl_tpu.utils.table import format_table

    total = max(1, plan["total_bytes"])
    rows = []
    for cat, b in plan["categories"].items():
        if not b:
            continue
        rows.append([cat, _fmt_bytes(b), f"{100.0 * b / total:.1f}%"])
    rows.append(["TOTAL", _fmt_bytes(plan["total_bytes"]), "100.0%"])
    lines = [format_table(["category", "bytes", "frac"], rows)]
    ct = plan.get("compiler_total_bytes")
    if ct is not None:
        drift = (abs(plan["total_bytes"] - ct) / ct * 100.0) if ct else 0.0
        lines.append(f"compiler total      {_fmt_bytes(ct)}  "
                     f"(table drift {drift:.2f}%)")
    lines.append(f"device HBM          {_fmt_bytes(plan['hbm_bytes'])}  "
                 f"(match: {plan['hbm_match']})")
    hf = plan.get("headroom_frac")
    lines.append(f"headroom            "
                 f"{_fmt_bytes(plan['headroom_bytes'])}  "
                 f"({100.0 * hf:.1f}% free)" if hf is not None else
                 f"headroom            {_fmt_bytes(plan['headroom_bytes'])}")
    if fc is not None:
        lines.append("")
        lines.append(f"per-sample slope    "
                     f"{_fmt_bytes(fc['bytes_per_sample'])}/sample "
                     f"(fit over b={fc['fit_batches']})")
        lines.append(f"fixed (model state) {_fmt_bytes(fc['fixed_bytes'])}")
        mb = fc.get("predicted_max_batch")
        lines.append(f"predicted max batch "
                     f"{mb if mb is not None else 'unbounded (flat slope)'}")
    return "\n".join(lines)


def compact(plan: dict) -> dict:
    """The small spelling stamped into perf JSON lines as the ``mem``
    detail dict (schema-stable sibling of ``attrib``)."""
    return {
        "categories": {k: int(v) for k, v in plan["categories"].items()
                       if v},
        "total_bytes": plan["total_bytes"],
        "compiler_total_bytes": plan.get("compiler_total_bytes"),
        "hbm_bytes": plan["hbm_bytes"],
        "hbm_match": plan["hbm_match"],
        "headroom_frac": plan.get("headroom_frac"),
        "batch": plan.get("batch"),
    }


# --------------------------------------------------------- live sampling
class HbmSampler:
    """Live HBM stats via ``device.memory_stats()``: gauges on the
    shared registry, Chrome-trace counter events, and a bounded headroom
    history for the OOM post-mortem. On backends without memory stats
    (CPU) every sample is a cheap None and the gauges simply never
    appear."""

    def __init__(self, device=None, registry=None, history: int = 512,
                 trace_counters: bool = True):
        if device is None:
            try:
                import jax
                device = jax.devices()[0]
            except Exception:
                device = None
        self.device = device
        self.hbm_bytes, self.hbm_match = device_hbm_bytes(device)
        self.trace_counters = trace_counters
        self.history: list = []  # [(step, bytes_in_use, peak)] bounded
        self._history_cap = int(history)
        self.last: Optional[dict] = None
        self._peak_seen = 0
        self._registered = False
        self._registry = registry

    # stats keys vary slightly across backends; normalize the three the
    # plan/report read
    @staticmethod
    def _normalize(stats: dict) -> dict:
        return {
            "bytes_in_use": int(stats.get("bytes_in_use", 0) or 0),
            "peak_bytes_in_use": int(stats.get("peak_bytes_in_use", 0)
                                     or 0),
            "largest_free_block_bytes": int(
                stats.get("largest_free_block_bytes", 0) or 0),
        }

    def _ensure_gauges(self) -> None:
        if self._registered:
            return
        try:
            from bigdl_tpu.obs.metrics import get_registry
            reg = self._registry or get_registry()
            reg.gauge("hbm_bytes_in_use", "live device bytes in use",
                      fn=lambda: (self.last or {}).get("bytes_in_use", 0))
            reg.gauge("hbm_peak_bytes", "peak device bytes in use",
                      fn=lambda: self._peak_seen)
            reg.gauge("hbm_largest_free_block_bytes",
                      "largest free block on device",
                      fn=lambda: (self.last or {}).get(
                          "largest_free_block_bytes", 0))
            self._registered = True
        except Exception:  # observability must never kill the run
            pass

    def sample(self, step: Optional[int] = None) -> Optional[dict]:
        """One live reading; returns the normalized stats dict or None
        when the backend has none."""
        if self.device is None:
            return None
        try:
            stats = self.device.memory_stats()
        except Exception:
            stats = None
        if not stats:
            return None
        s = self._normalize(stats)
        self.last = s
        self._peak_seen = max(self._peak_seen,
                              s["peak_bytes_in_use"] or s["bytes_in_use"])
        self._ensure_gauges()
        if len(self.history) >= self._history_cap:
            del self.history[: self._history_cap // 2]
        self.history.append((step, s["bytes_in_use"],
                             s["peak_bytes_in_use"]))
        if self.trace_counters:
            try:
                from bigdl_tpu.obs.spans import counter as _counter
                _counter("hbm", {"bytes_in_use": s["bytes_in_use"],
                                 "largest_free_block":
                                     s["largest_free_block_bytes"]})
            except Exception:
                pass
        return s

    @property
    def peak_bytes(self) -> Optional[int]:
        return self._peak_seen or None

    def annotation(self) -> Optional[dict]:
        if self.last is None:
            return None
        return {"last": dict(self.last), "peak_bytes": self._peak_seen,
                "samples": len(self.history)}


# ------------------------------------------------------ OOM post-mortem
# process-wide context, armed once by install_observability (the same
# one-install channel resilience.faults uses)
_CONTEXT: dict = {"trace_dir": None, "plan": None, "sampler": None}


def install(trace_dir: Optional[str] = None, plan: Optional[dict] = None,
            sampler: Optional[HbmSampler] = None) -> None:
    """Arm the OOM post-mortem path process-wide. Each argument updates
    only when given, so the CLI can install the trace dir early and the
    harness the plan later (post-compile)."""
    if trace_dir is not None:
        _CONTEXT["trace_dir"] = str(trace_dir)
    if plan is not None:
        _CONTEXT["plan"] = plan
    if sampler is not None:
        _CONTEXT["sampler"] = sampler


def installed_plan() -> Optional[dict]:
    return _CONTEXT["plan"]


def installed_trace_dir() -> Optional[str]:
    return _CONTEXT["trace_dir"]


def _reset_context() -> None:  # tests
    _CONTEXT.update(trace_dir=None, plan=None, sampler=None)


def is_resource_exhausted(exc: BaseException) -> bool:
    """Does this exception smell like a device OOM? jax surfaces XLA's
    RESOURCE_EXHAUSTED through XlaRuntimeError (message carries the
    status name); match type name + message so a simulated OOM in tests
    (a RuntimeError with the status string) also qualifies."""
    msg = str(exc)
    return ("RESOURCE_EXHAUSTED" in msg
            or "Resource exhausted" in msg
            or "Out of memory" in msg)


def _top_live_buffers(n: int = 15) -> list:
    """The N largest live device arrays — who is actually holding the
    bytes at crash time."""
    try:
        import jax
        arrs = jax.live_arrays()
    except Exception:
        return []
    rows = []
    for a in arrs:
        try:
            rows.append({"shape": list(getattr(a, "shape", ())),
                         "dtype": str(getattr(a, "dtype", "?")),
                         "nbytes": int(getattr(a, "nbytes", 0))})
        except Exception:
            continue
    rows.sort(key=lambda r: -r["nbytes"])
    return rows[:n]


def write_oom_report(trace_dir: str, *, context: str,
                     exc: Optional[BaseException] = None,
                     plan: Optional[dict] = None,
                     sampler: Optional[HbmSampler] = None) -> str:
    """Write the MemoryReport JSON to ``trace_dir`` and return its path.
    Pure-function spelling (handle_oom adds the installed-context and
    never-raise wrapping)."""
    report = {
        "event": "oom",
        "context": context,
        "time": time.time(),
        "error": (f"{type(exc).__name__}: {exc}"[:500]
                  if exc is not None else None),
        "plan": plan,
        "live": sampler.annotation() if sampler is not None else None,
        "headroom_history": (list(sampler.history[-64:])
                             if sampler is not None else []),
        "top_live_buffers": _top_live_buffers(),
    }
    os.makedirs(trace_dir, exist_ok=True)
    path = os.path.join(trace_dir, OOM_REPORT_NAME)
    with open(path, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def handle_oom(exc: BaseException, context: str) -> Optional[str]:
    """Call from any RESOURCE_EXHAUSTED catch site (then re-raise).
    Writes the MemoryReport to the installed trace dir, appends the
    event to the fault log (BIGDL_FAULT_LOG, the resilience audit
    trail), drops an instant event on the span timeline, and bumps a
    registry counter. Returns the report path (or None); NEVER raises —
    the autopsy must not change how the crash propagates."""
    try:
        if not is_resource_exhausted(exc):
            return None
        path = None
        trace_dir = _CONTEXT["trace_dir"]
        if trace_dir:
            try:
                path = write_oom_report(trace_dir, context=context,
                                        exc=exc, plan=_CONTEXT["plan"],
                                        sampler=_CONTEXT["sampler"])
                logger.error("OOM in %s: memory report -> %s",
                             context, path)
            except Exception as we:
                logger.warning("OOM report write failed: %s", we)
        else:
            logger.error("OOM in %s (no --traceDir: post-mortem report "
                         "skipped): %s", context, str(exc)[:200])
        # fault-log stamp, the same JSONL + fsync contract as
        # resilience.faults._record (audit survives the crash)
        log_path = os.environ.get("BIGDL_FAULT_LOG")
        if log_path:
            try:
                with open(log_path, "a") as f:
                    f.write(json.dumps({
                        "event": "oom", "context": context,
                        "report": path,
                        "error": f"{type(exc).__name__}: {exc}"[:200],
                        "time": time.time()}) + "\n")
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:
                pass
        try:
            from bigdl_tpu.obs.spans import instant as _instant
            _instant("oom", context=context, report=path)
        except Exception:
            pass
        try:
            from bigdl_tpu.obs.metrics import get_registry
            get_registry().counter(
                "oom_total", "RESOURCE_EXHAUSTED crashes autopsied").inc()
        except Exception:
            pass
        return path
    except Exception as e:  # belt and braces: the autopsy never raises
        logger.warning("OOM handler failed: %s", e)
        return None
