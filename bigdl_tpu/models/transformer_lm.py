"""Decoder-only transformer language model — the long-context flagship.

The reference's only sequence model is SimpleRNN (truncated BPTT,
nn/Recurrent.scala); this is the modern long-context workload the brief
treats as first-class, built from the framework's own pieces: LookupTable
embedding, sinusoidal positions, causal pre-LN TransformerEncoder (flash
or ring attention via ``attn_impl``), weight-tied logits head option, and
``remat`` for HBM-bound contexts.

Scales along every axis the framework ships: dp (batch), tp (Megatron
specs apply to the blocks), sp (ring attention over `seq`), pp
(`PipelineStack` of the same TransformerEncoderLayer blocks), MoE (swap
``d_ff`` MLPs for :class:`bigdl_tpu.nn.MoE` via ``moe_experts``).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.core.module import Module

__all__ = ["TransformerLM", "transformer_lm", "packed_lm_targets",
           "PackedNLLCriterion"]


def packed_lm_targets(tokens, segments):
    """Next-token targets for a packed row (see
    ``bigdl_tpu.dataset.text.pack_sequences``): target[i] = tokens[i+1],
    with weight 0 wherever the next token belongs to a different document
    (or padding) — the boundary positions a packed causal LM must not be
    trained on. Returns (targets, weights), shapes (b, s) / (b, s) f32."""
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1)
    nxt = jnp.concatenate(
        [segments[:, 1:], jnp.zeros_like(segments[:, :1])], axis=1)
    weights = ((segments == nxt) & (segments != 0)).astype(jnp.float32)
    return targets, weights


class PackedNLLCriterion:
    """Weighted next-token NLL over (b, s, vocab) log-probs; target is the
    (targets, weights) pair from :func:`packed_lm_targets`. Mean over the
    live positions, so the loss scale matches the unpacked
    TimeDistributed(ClassNLL) path."""

    def __call__(self, logp, target):
        targets, weights = target
        nll = -jnp.take_along_axis(logp, targets[..., None],
                                   axis=-1)[..., 0]
        w = weights.astype(nll.dtype)
        return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1.0)


class TransformerLM(Module):
    """Decoder-only LM (the long-context flagship).

    TPU sizing rule, measured on chip (PERF.md §8.2): pick
    ``num_heads`` so that ``d_model // num_heads == 128`` — the MXU
    contracts over the head dim in both attention matmuls and 64-wide
    heads half-fill its 128-lane tiles (+24% tok/s at identical FLOPs
    under the shipped 512-wide flash blocks; +60% under 128-blocks).
    The 1k-context hd128 config measures 96.0k tok/s at 53.7% MFU on
    one v5e chip."""

    def __init__(self, vocab: int, d_model: int = 256, num_layers: int = 4,
                 num_heads: int = 4, d_ff: Optional[int] = None,
                 max_len: int = 2048, dropout: float = 0.0,
                 attn_impl=None, remat: bool = False,
                 tie_embeddings: bool = True, compute_dtype=None,
                 num_kv_heads: Optional[int] = None,
                 pos_encoding: str = "sinusoidal",
                 name: Optional[str] = None):
        super().__init__(name or "TransformerLM")
        if pos_encoding not in ("sinusoidal", "rope"):
            raise ValueError(f"pos_encoding {pos_encoding!r} not in "
                             f"('sinusoidal', 'rope')")
        self.vocab = vocab
        self.d_model = d_model
        self.tie = tie_embeddings
        self.max_len = max_len
        self.rope = pos_encoding == "rope"
        # token input is int, so the Optimizer-level compute_dtype cast
        # never fires for LMs; the cast belongs right after the embedding
        self.compute_dtype = compute_dtype
        self.emb = nn.LookupTable(vocab, d_model)
        # RoPE replaces the additive table (rotation happens on q/k inside
        # every attention layer — relative positions, better long-context
        # extrapolation); self.pos still carries max_len for bounds
        self.pos = nn.PositionalEncoding(d_model, max_len)
        self.encoder = nn.TransformerEncoder(
            num_layers, d_model, num_heads, d_ff, causal=True,
            dropout=dropout, attn_impl=attn_impl, remat=remat,
            num_kv_heads=num_kv_heads, rope=self.rope,
            rope_max_len=max_len)
        self.ln_f = nn.LayerNorm(d_model)
        self.head = None if tie_embeddings else nn.Linear(d_model, vocab)

    def children(self):
        out = [self.emb, self.pos, self.encoder, self.ln_f]
        if self.head is not None:
            out.append(self.head)
        return tuple(out)

    def tp_param_children(self):
        """Param-key -> child mapping so megatron_specs can shard the
        encoder blocks (and embedding) of a TP'd LM."""
        out = {"emb": self.emb, "encoder": self.encoder, "ln_f": self.ln_f}
        if self.head is not None:
            out["head"] = self.head
        return out

    def init(self, rng):
        ks = jax.random.split(rng, 3)
        p = {"emb": self.emb.init(ks[0]),
             "encoder": self.encoder.init(ks[1]),
             "ln_f": self.ln_f.init(ks[2])}
        if self.head is not None:
            p["head"] = self.head.init(jax.random.fold_in(rng, 3))
        return p

    def apply(self, params, state, x, *, training=False, rng=None):
        # x: (batch, seq) int token ids -> (batch, seq, vocab) log-probs;
        # or (tokens, segments) for packed rows (pack_sequences) — the
        # integer segment ids thread to every attention layer, which
        # confines attention per document (in-kernel for the flash impl,
        # via make_segment_mask elsewhere)
        mask = None
        if isinstance(x, (tuple, list)):
            x, segments = x
            mask = segments
        h = self.emb.forward(params["emb"], x)
        if self.compute_dtype is not None:
            h = h.astype(self.compute_dtype)
        h = h * (self.d_model ** 0.5)  # standard embedding scale
        if not self.rope:
            h = self.pos.forward({}, h)
        elif x.shape[-1] > self.max_len:
            raise ValueError(f"sequence length {x.shape[-1]} exceeds "
                             f"max_len {self.max_len}")
        h, _ = self.encoder.apply(params["encoder"],
                                  self.encoder.init_state(),
                                  h if mask is None else (h, mask),
                                  training=training, rng=rng)
        if isinstance(h, (tuple, list)):  # encoder returns (y, mask)
            h = h[0]
        h = self.ln_f.forward(params["ln_f"], h)
        if self.head is not None:
            logits = self.head.forward(params["head"], h)
        else:  # weight tying: logits = h @ E^T
            logits = h @ params["emb"]["weight"].astype(h.dtype).T
        return jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1), state


    # --------------------------------------------- autoregressive decoding
    def _embed_at(self, params, tokens, pos0):
        """Embed (b, s) tokens that sit at absolute positions pos0..pos0+s."""
        h = self.emb.forward(params["emb"], tokens)
        if self.compute_dtype is not None:
            h = h.astype(self.compute_dtype)
        h = h * (self.d_model ** 0.5)
        if self.rope:  # rotation happens inside each attention layer
            return h
        table = jnp.asarray(self.pos._table)
        pe = jax.lax.dynamic_slice_in_dim(table, pos0, tokens.shape[1], 0)
        return h + pe.astype(h.dtype)

    def _logits(self, params, h):
        h = self.ln_f.forward(params["ln_f"], h)
        if self.head is not None:
            return self.head.forward(params["head"], h)
        return h @ params["emb"]["weight"].astype(h.dtype).T

    def prefill_logits(self, params, tokens, cache, last=None):
        """Serving prefill: run the full prompt once, populate the K/V
        ``cache`` (positions 0..s-1), and return the next-token logits —
        ``(b, vocab)`` at position ``last`` (traced index; default the
        final position s-1) — plus the updated cache. With the prompt
        right-padded to a length bucket, ``last`` = true_len - 1 makes
        the result exactly the unpadded prompt's logits: causal
        attention never lets positions > last influence position last,
        and decode steps overwrite the pad K/V slots one position at a
        time before ever attending to them."""
        import jax

        h = self._embed_at(params, tokens, 0)
        h, cache = self.encoder.prefill(params["encoder"], h, cache)
        if last is None:
            h_last = h[:, -1:, :]
        else:
            h_last = jax.lax.dynamic_slice_in_dim(h, last, 1, axis=1)
        return self._logits(params, h_last)[:, 0, :], cache

    def decode_logits(self, params, tok, cache, pos):
        """One decode step: ``tok`` (b, 1) int32 at absolute position
        ``pos`` (traced) -> ((b, vocab) logits, cache). The per-token
        inner loop of :meth:`generate`, exposed for the serving engine's
        continuous-batching decoder (bigdl_tpu.serving.decode)."""
        h = self._embed_at(params, tok, pos)
        h, cache = self.encoder.decode_step(params["encoder"], h, cache,
                                            pos)
        return self._logits(params, h)[:, 0, :], cache

    def verify_logits(self, params, toks, cache, pos):
        """Chunked decode: ``toks`` (b, m) int32 at absolute positions
        ``pos``..``pos+m-1`` (pos traced) -> ((b, m, vocab) logits,
        cache). Row i is the next-token distribution after feeding
        toks[:, :i+1] — the single target dispatch that verifies m
        speculative draft tokens at once, and the suffix prefill a
        shared-prefix-cache hit runs at a page-aligned offset
        (bigdl_tpu.serving.spec_decode / prefix_cache). Row-wise
        bit-identical to m sequential :meth:`decode_logits` calls on
        the dense CPU path (pinned in tests/test_spec_decode.py).
        Caller keeps pos + m <= max_len (positional-table slice and
        cache writes both clamp rather than fail out of range)."""
        h = self._embed_at(params, toks, pos)
        h, cache = self.encoder.decode_chunk(params["encoder"], h, cache,
                                             pos)
        return self._logits(params, h), cache

    def generate(self, params, prompt, max_new_tokens: int,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 rng=None):
        """KV-cache autoregressive decoding (the inference path of the
        long-context flagship — no analog in the reference, whose only
        generative path is SimpleRNN truncated BPTT).

        ``prompt``: (b, s) int32 token ids. One full-prompt prefill builds
        the per-layer K/V cache, then each new token is one O(1)-length
        step against the cache. temperature 0 = greedy; otherwise
        softmax-temperature sampling, optionally top-k truncated.
        Returns (b, max_new_tokens) sampled ids. Jit-compiled; cache size
        is the model's max_len, so prompt+new must fit in it.
        """
        prompt = jnp.asarray(prompt, jnp.int32)
        b, s = prompt.shape
        max_len = self.pos.max_len
        if s + max_new_tokens > max_len:
            raise ValueError(f"prompt ({s}) + max_new_tokens "
                             f"({max_new_tokens}) exceeds max_len {max_len}")
        if rng is None:
            rng = jax.random.PRNGKey(0)

        def sample(logits, key):
            if temperature <= 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            logits = logits / temperature
            if top_k is not None and top_k < self.vocab:
                kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
                logits = jnp.where(logits < kth, -1e30, logits)
            return jax.random.categorical(key, logits).astype(jnp.int32)

        cache_dtype = self.compute_dtype or jnp.float32

        def run(params, prompt, rng):
            cache = self.encoder.init_cache(b, max_len, cache_dtype)
            logits, cache = self.prefill_logits(params, prompt, cache)

            def body(i, carry):
                buf, cache, logits, rng = carry
                rng, key = jax.random.split(rng)
                tok = sample(logits.astype(jnp.float32), key)
                buf = jax.lax.dynamic_update_slice_in_dim(
                    buf, tok[:, None], i, axis=1)
                logits, cache = self.decode_logits(
                    params, tok[:, None], cache, s + i)
                return buf, cache, logits, rng

            buf = jnp.zeros((b, max_new_tokens), jnp.int32)
            buf, _, _, _ = jax.lax.fori_loop(
                0, max_new_tokens, body, (buf, cache, logits, rng))
            return buf

        # one compile per (shape, sampling) config — re-jitting a fresh
        # closure every call would recompile every time
        key = (b, s, max_new_tokens, temperature, top_k)
        cache_attr = getattr(self, "_gen_jit_cache", None)
        if cache_attr is None:
            cache_attr = self._gen_jit_cache = {}
        if key not in cache_attr:
            cache_attr[key] = jax.jit(run)
        return cache_attr[key](params, prompt, rng)


def transformer_lm(vocab: int, **kw) -> TransformerLM:
    return TransformerLM(vocab, **kw)
