"""Inception/GoogLeNet models (reference models/inception/Model.scala, 395
LoC: Inception_v1_NoAuxClassifier, Inception_v1, Inception_v2).

An inception module is a 4-branch Concat along channels (reference builds it
with Concat + Sequential branches; identical structure here over NHWC, so
the channel concat is axis -1). Aux-classifier variants return a 3-tuple
(main, aux1, aux2) trained with ParallelCriterion weights (1.0, 0.3, 0.3)
as in the reference Train pipeline.
"""

from __future__ import annotations

from bigdl_tpu.core.module import Sequential, Module
from bigdl_tpu import nn

__all__ = ["inception_module", "inception_v1_no_aux", "inception_v1",
           "inception_v2"]


def inception_module(cin: int, config, with_bn: bool = False) -> Sequential:
    """config = [[c1x1], [c3x3_reduce, c3x3], [c5x5_reduce, c5x5],
    [pool_proj]] (reference Inception layer builder Model.scala)."""

    def conv(ci, co, k, pad=0):
        mods = [nn.SpatialConvolution(ci, co, k, k, 1, 1, pad, pad,
                                      init="xavier",
                                      with_bias=not with_bn)]
        if with_bn:
            mods.append(nn.SpatialBatchNormalization(co, eps=1e-3))
        mods.append(nn.ReLU())
        return mods

    b1 = Sequential(*conv(cin, config[0][0], 1))
    b2 = Sequential(*conv(cin, config[1][0], 1), *conv(config[1][0],
                                                       config[1][1], 3, 1))
    b3 = Sequential(*conv(cin, config[2][0], 1), *conv(config[2][0],
                                                       config[2][1], 5, 2))
    b4 = Sequential(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1).ceil(),
                    *conv(cin, config[3][0], 1))
    return Sequential(nn.Concat(b1, b2, b3, b4, axis=-1))


def _stem(with_bn: bool = False) -> list:
    mods = [
        nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3, init="xavier"),
        nn.ReLU(),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
        nn.SpatialConvolution(64, 64, 1, 1, init="xavier"),
        nn.ReLU(),
        nn.SpatialConvolution(64, 192, 3, 3, 1, 1, 1, 1, init="xavier"),
        nn.ReLU(),
        nn.SpatialCrossMapLRN(5, 0.0001, 0.75),
        nn.SpatialMaxPooling(3, 3, 2, 2).ceil(),
    ]
    return mods


# GoogLeNet table (Szegedy et al. 2014), as laid out in the reference's
# Inception_v1 builder: per-module [1x1, [3x3r, 3x3], [5x5r, 5x5], pool].
_V1_CFG = {
    "3a": (192, [[64], [96, 128], [16, 32], [32]]),
    "3b": (256, [[128], [128, 192], [32, 96], [64]]),
    "4a": (480, [[192], [96, 208], [16, 48], [64]]),
    "4b": (512, [[160], [112, 224], [24, 64], [64]]),
    "4c": (512, [[128], [128, 256], [24, 64], [64]]),
    "4d": (512, [[112], [144, 288], [32, 64], [64]]),
    "4e": (528, [[256], [160, 320], [32, 128], [128]]),
    "5a": (832, [[256], [160, 320], [32, 128], [128]]),
    "5b": (832, [[384], [192, 384], [48, 128], [128]]),
}


def inception_v1_no_aux(class_num: int = 1000) -> Sequential:
    """(reference Inception_v1_NoAuxClassifier) 224x224x3 -> classes."""
    m = Sequential(name="Inception_v1_NoAux")
    for mod in _stem():
        m.add(mod)
    for key in ("3a", "3b"):
        m.add(inception_module(*_V1_CFG[key]))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    for key in ("4a", "4b", "4c", "4d", "4e"):
        m.add(inception_module(*_V1_CFG[key]))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    for key in ("5a", "5b"):
        m.add(inception_module(*_V1_CFG[key]))
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    m.add(nn.Dropout(0.4))
    m.add(nn.Reshape([1024]))
    m.add(nn.Linear(1024, class_num, init="xavier"))
    m.add(nn.LogSoftMax())
    return m


def _aux_head(cin: int, class_num: int) -> Sequential:
    """(reference aux classifier: avgpool5/3 + conv1x1(128) + fc1024 +
    dropout 0.7 + fc classes)"""
    return Sequential(
        nn.SpatialAveragePooling(5, 5, 3, 3),
        nn.SpatialConvolution(cin, 128, 1, 1, init="xavier"),
        nn.ReLU(),
        nn.Reshape([128 * 4 * 4]),
        nn.Linear(128 * 4 * 4, 1024),
        nn.ReLU(),
        nn.Dropout(0.7),
        nn.Linear(1024, class_num),
        nn.LogSoftMax(),
    )


def inception_v1(class_num: int = 1000) -> Sequential:
    """Full GoogLeNet with two aux classifiers (reference Inception_v1).
    Output = (main, aux1, aux2) log-prob table; train with
    ParallelCriterion(repeat_target=True) weighted (1.0, 0.3, 0.3)."""
    trunk1 = Sequential(name="trunk1")  # up to 4a output
    for mod in _stem():
        trunk1.add(mod)
    for key in ("3a", "3b"):
        trunk1.add(inception_module(*_V1_CFG[key]))
    trunk1.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    trunk1.add(inception_module(*_V1_CFG["4a"]))

    trunk2 = Sequential(name="trunk2")  # 4b..4d
    for key in ("4b", "4c", "4d"):
        trunk2.add(inception_module(*_V1_CFG[key]))

    trunk3 = Sequential(name="trunk3")  # 4e..5b + head
    trunk3.add(inception_module(*_V1_CFG["4e"]))
    trunk3.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    for key in ("5a", "5b"):
        trunk3.add(inception_module(*_V1_CFG[key]))
    trunk3.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    trunk3.add(nn.Dropout(0.4))
    trunk3.add(nn.Reshape([1024]))
    trunk3.add(nn.Linear(1024, class_num, init="xavier"))
    trunk3.add(nn.LogSoftMax())

    # (main, aux1, aux2): trunk1 -> split(aux1 | trunk2 -> split(aux2 | trunk3))
    inner = Sequential(
        nn.ConcatTable(
            Sequential(trunk2,
                       nn.ConcatTable(trunk3, _aux_head(528, class_num))),
            _aux_head(512, class_num),
        ),
        nn.FlattenTable(),
    )
    m = Sequential(trunk1, inner,
                   nn.Lambda(lambda t: (t[0], t[2], t[1]), name="reorder"),
                   name="Inception_v1")
    return m


def inception_v2(class_num: int = 1000) -> Sequential:
    """BN-Inception (reference Inception_v2): v1 topology with
    batch-normalized inception modules and no LRN. Single output."""
    m = Sequential(name="Inception_v2")
    m.add(nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3, with_bias=False,
                                init="xavier"))
    m.add(nn.SpatialBatchNormalization(64, eps=1e-3))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    m.add(nn.SpatialConvolution(64, 64, 1, 1, with_bias=False, init="xavier"))
    m.add(nn.SpatialBatchNormalization(64, eps=1e-3))
    m.add(nn.ReLU())
    m.add(nn.SpatialConvolution(64, 192, 3, 3, 1, 1, 1, 1, with_bias=False,
                                init="xavier"))
    m.add(nn.SpatialBatchNormalization(192, eps=1e-3))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    for key in ("3a", "3b"):
        m.add(inception_module(*_V1_CFG[key], with_bn=True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    for key in ("4a", "4b", "4c", "4d", "4e"):
        m.add(inception_module(*_V1_CFG[key], with_bn=True))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2).ceil())
    for key in ("5a", "5b"):
        m.add(inception_module(*_V1_CFG[key], with_bn=True))
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    m.add(nn.Reshape([1024]))
    m.add(nn.Linear(1024, class_num, init="xavier"))
    m.add(nn.LogSoftMax())
    return m
