"""VGG models (reference models/vgg/Model.scala:25-184): VggForCifar10
(conv-BN-ReLU blocks + 512-unit classifier head), Vgg_16 and Vgg_19 for
ImageNet. NHWC; convs are bias-free where followed by BN."""

from __future__ import annotations

from bigdl_tpu.core.module import Sequential
from bigdl_tpu import nn

__all__ = ["vgg_for_cifar10", "vgg16", "vgg19"]


def _conv_bn_relu(seq: Sequential, cin: int, cout: int) -> int:
    seq.add(nn.SpatialConvolution(cin, cout, 3, 3, 1, 1, 1, 1,
                                  with_bias=False))
    seq.add(nn.SpatialBatchNormalization(cout, eps=1e-3))
    seq.add(nn.ReLU())
    return cout


def vgg_for_cifar10(class_num: int = 10, dropout: bool = True) -> Sequential:
    """(reference Model.scala VggForCifar10 :25-78) — conv stacks
    [64,64] [128,128] [256x3] [512x3] [512x3] each followed by 2x2 maxpool,
    then Linear(512,512)+BN+ReLU+Dropout(0.5)+Linear(512,classes)."""
    m = Sequential(name="VggForCifar10")
    c = 3
    for block in ([64, 64], [128, 128], [256, 256, 256],
                  [512, 512, 512], [512, 512, 512]):
        for cout in block:
            c = _conv_bn_relu(m, c, cout)
        m.add(nn.SpatialMaxPooling(2, 2, 2, 2).ceil())
    m.add(nn.Reshape([512]))
    m.add(nn.Linear(512, 512))
    m.add(nn.BatchNormalization(512))
    m.add(nn.ReLU())
    if dropout:
        m.add(nn.Dropout(0.5))
    m.add(nn.Linear(512, class_num))
    m.add(nn.LogSoftMax())
    return m


def _vgg_imagenet(cfg, class_num: int, name: str) -> Sequential:
    """(reference Vgg_16/Vgg_19 :80-184 — plain conv+ReLU, no BN, 224x224
    inputs, classifier 4096-4096-classes with dropout)"""
    m = Sequential(name=name)
    c = 3
    for block in cfg:
        for cout in block:
            m.add(nn.SpatialConvolution(c, cout, 3, 3, 1, 1, 1, 1))
            m.add(nn.ReLU())
            c = cout
        m.add(nn.SpatialMaxPooling(2, 2, 2, 2))
    m.add(nn.Reshape([512 * 7 * 7]))
    m.add(nn.Linear(512 * 7 * 7, 4096))
    m.add(nn.ReLU())
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096))
    m.add(nn.ReLU())
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num))
    m.add(nn.LogSoftMax())
    return m


def vgg16(class_num: int = 1000) -> Sequential:
    return _vgg_imagenet([[64, 64], [128, 128], [256, 256, 256],
                          [512, 512, 512], [512, 512, 512]],
                         class_num, "Vgg_16")


def vgg19(class_num: int = 1000) -> Sequential:
    return _vgg_imagenet([[64, 64], [128, 128], [256, 256, 256, 256],
                          [512, 512, 512, 512], [512, 512, 512, 512]],
                         class_num, "Vgg_19")
