"""AlexNet (reference example/loadmodel/Model.scala builds AlexNet for Caffe
import validation). Single-tower Caffe variant, NHWC."""

from __future__ import annotations

from bigdl_tpu.core.module import Sequential
from bigdl_tpu import nn

__all__ = ["alexnet"]


def alexnet(class_num: int = 1000) -> Sequential:
    m = Sequential(name="AlexNet")
    m.add(nn.SpatialConvolution(3, 96, 11, 11, 4, 4, name="conv1"))
    m.add(nn.ReLU())
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2))
    m.add(nn.SpatialConvolution(96, 256, 5, 5, 1, 1, 2, 2, n_group=2,
                                name="conv2"))
    m.add(nn.ReLU())
    m.add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2))
    m.add(nn.SpatialConvolution(256, 384, 3, 3, 1, 1, 1, 1, name="conv3"))
    m.add(nn.ReLU())
    m.add(nn.SpatialConvolution(384, 384, 3, 3, 1, 1, 1, 1, n_group=2,
                                name="conv4"))
    m.add(nn.ReLU())
    m.add(nn.SpatialConvolution(384, 256, 3, 3, 1, 1, 1, 1, n_group=2,
                                name="conv5"))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2))
    m.add(nn.Reshape([256 * 6 * 6]))
    m.add(nn.Linear(256 * 6 * 6, 4096, name="fc6"))
    m.add(nn.ReLU())
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, 4096, name="fc7"))
    m.add(nn.ReLU())
    m.add(nn.Dropout(0.5))
    m.add(nn.Linear(4096, class_num, name="fc8"))
    m.add(nn.LogSoftMax())
    return m
