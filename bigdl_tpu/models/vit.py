"""Vision Transformer — beyond-reference model family, assembled entirely
from the framework's own pieces (the reference's newest vision model is
Inception v2, models/inception/Inception_v2.scala; ViT is the modern
counterpart users expect a complete framework to ship).

Design choices, TPU-first per the measured sizing rules (PERF.md §8.2):

* patchify = ``SpatialConvolution(3, d_model, p, p, stride p)`` — one
  stride-p conv IS the per-patch linear projection, and at p=16 its
  contraction (3*16*16 = 768) fills the MXU far better than the ResNet
  stem's 3-channel 7x7 (measured at 3.6% of peak, PERF.md §3);
* ``head_dim = 128`` by default (``num_heads = d_model // 128``);
* mean pooling over patch tokens instead of a class token (keeps every
  shape static and batch-major; GAP heads match CLS within noise at
  this scale) and sinusoidal positions from the existing
  :class:`~bigdl_tpu.nn.PositionalEncoding` table;
* pre-LN encoder blocks — the framework's :class:`TransformerEncoder`
  verbatim, so flash attention, ``remat``, GQA, and the Megatron TP
  param specs all apply to ViT for free.

Output is per-class log-probabilities (``LogSoftMax`` tail), matching
every other model family and ``ClassNLLCriterion``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from bigdl_tpu import nn
from bigdl_tpu.core.module import Module

__all__ = ["ViT", "vit", "vit_b16", "vit_s16"]


class ViT(Module):
    def __init__(self, class_num: int = 1000, image_size: int = 224,
                 patch_size: int = 16, d_model: int = 768,
                 num_layers: int = 12, num_heads: Optional[int] = None,
                 d_ff: Optional[int] = None, dropout: float = 0.0,
                 attn_impl=None, remat: bool = False,
                 name: Optional[str] = None):
        super().__init__(name or "ViT")
        if image_size % patch_size:
            raise ValueError(f"image_size {image_size} not divisible by "
                             f"patch_size {patch_size}")
        if num_heads is None:
            # measured TPU rule: 128-wide heads (PERF.md §8.2)
            num_heads = max(1, d_model // 128)
        self.d_model = d_model
        self.n_patches = (image_size // patch_size) ** 2
        self.patch = nn.SpatialConvolution(
            3, d_model, patch_size, patch_size, patch_size, patch_size,
            0, 0)
        self.pos = nn.PositionalEncoding(d_model, self.n_patches)
        self.encoder = nn.TransformerEncoder(
            num_layers, d_model, num_heads, d_ff, causal=False,
            dropout=dropout, attn_impl=attn_impl, remat=remat)
        self.ln = nn.LayerNorm(d_model)
        self.head = nn.Linear(d_model, class_num)

    def children(self):
        return (self.patch, self.pos, self.encoder, self.ln, self.head)

    def init(self, rng):
        ks = jax.random.split(rng, 3)
        return {"patch": self.patch.init(ks[0]),
                "encoder": self.encoder.init(ks[1]),
                "ln": self.ln.init(jax.random.fold_in(rng, 2)),
                "head": self.head.init(ks[2])}

    def init_state(self):
        return {"encoder": self.encoder.init_state()}

    def apply(self, params, state, x, *, training=False, rng=None):
        # x: (b, h, w, 3) NHWC -> (b, n_patches, d_model) tokens
        t = self.patch.forward(params["patch"], x)
        b, gh, gw, d = t.shape
        t = t.reshape(b, gh * gw, d)
        t = self.pos.forward({}, t)
        t, enc_state = self.encoder.apply(
            params["encoder"], state["encoder"], t,
            training=training, rng=rng)
        if isinstance(t, (tuple, list)):
            t = t[0]
        t = self.ln.forward(params["ln"], t)
        t = jnp.mean(t, axis=1)  # GAP over patch tokens
        logits = self.head.forward(params["head"], t)
        return (jax.nn.log_softmax(logits.astype(jnp.float32), -1),
                {"encoder": enc_state})


def vit(class_num: int = 1000, **kw) -> ViT:
    return ViT(class_num, **kw)


def vit_b16(class_num: int = 1000, **kw) -> ViT:
    """ViT-Base/16: 12 layers, d 768, 6 heads of 128 (86M params)."""
    kw.setdefault("patch_size", 16)
    return ViT(class_num, d_model=768, num_layers=12, **kw)


def vit_s16(class_num: int = 1000, **kw) -> ViT:
    """ViT-Small/16: 12 layers, d 384, 3 heads of 128 (22M params)."""
    kw.setdefault("patch_size", 16)
    return ViT(class_num, d_model=384, num_layers=12, **kw)
