"""Recurrent text models.

* ``simple_rnn`` — (reference models/rnn/Model.scala:23-37: SimpleRNN =
  Recurrent(RnnCell+Tanh) + select last step + Linear + LogSoftMax), for
  char/word-level next-token prediction on tiny-shakespeare.
* ``lstm_classifier`` / ``birnn_classifier`` — the "LSTM / BiRNN text
  classification" BASELINE config: embedding -> (Bi)LSTM -> last state ->
  Linear -> LogSoftMax. Not in the reference snapshot (no LSTM exists
  there, SURVEY.md §2.4); built from the same recurrent path.
* ``text_cnn`` — the reference's text-classification example
  (example/textclassification/TextClassifier.scala: GloVe embeddings +
  conv/pool stack), using native TemporalConvolution.
"""

from __future__ import annotations

import jax.numpy as jnp

from bigdl_tpu.core.module import Sequential
from bigdl_tpu import nn

__all__ = ["simple_rnn", "lstm_classifier", "birnn_classifier", "text_cnn"]


def simple_rnn(input_size: int, hidden_size: int, output_size: int,
               bptt_truncate: int = 2) -> Sequential:
    """Input: one-hot (B, T, input_size); output (B, output_size) log-probs
    for the last step (reference trains next-word prediction with
    perplexity loss)."""
    return Sequential(
        nn.Recurrent(nn.RnnCell(input_size, hidden_size, jnp.tanh),
                     bptt_truncate=bptt_truncate, return_sequences=False),
        nn.Linear(hidden_size, output_size),
        nn.LogSoftMax(),
        name="SimpleRNN",
    )


def lstm_classifier(vocab_size: int, embed_dim: int, hidden_size: int,
                    class_num: int) -> Sequential:
    return Sequential(
        nn.LookupTable(vocab_size, embed_dim),
        nn.Recurrent(nn.LSTMCell(embed_dim, hidden_size),
                     return_sequences=False),
        nn.Linear(hidden_size, class_num),
        nn.LogSoftMax(),
        name="LSTMClassifier",
    )


def birnn_classifier(vocab_size: int, embed_dim: int, hidden_size: int,
                     class_num: int) -> Sequential:
    return Sequential(
        nn.LookupTable(vocab_size, embed_dim),
        # final state of each direction (fwd@T-1 ++ bwd@0) — each half has
        # consumed the whole sequence
        nn.BiRecurrent(nn.LSTMCell(embed_dim, hidden_size),
                       nn.LSTMCell(embed_dim, hidden_size),
                       return_sequences=False),
        nn.Linear(2 * hidden_size, class_num),
        nn.LogSoftMax(),
        name="BiRNNClassifier",
    )


def text_cnn(seq_len: int, embed_dim: int, class_num: int,
             filters: int = 128) -> Sequential:
    """(reference TextClassifier.scala:40-220 — three conv5/maxpool5 stages
    then a dense head; input is pre-embedded (B, T, embed_dim))."""
    m = Sequential(name="TextCNN")
    cin = embed_dim
    t = seq_len
    for _ in range(2):
        m.add(nn.TemporalConvolution(cin, filters, 5))
        m.add(nn.ReLU())
        m.add(nn.TemporalMaxPooling(5, 5))
        cin = filters
        t = (t - 4 - 5) // 5 + 1  # valid conv k=5, then pool k=s=5
    if t < 5:
        # t >= 5 after two stages requires t1 >= 29, i.e. seq_len >= 149
        raise ValueError(f"seq_len={seq_len} too short for the 3-stage "
                         f"TextCNN (needs >= 149; reference uses 500)")
    m.add(nn.TemporalConvolution(cin, filters, 5))
    m.add(nn.ReLU())
    t = t - 4
    m.add(nn.TemporalMaxPooling(t, t))
    m.add(nn.Reshape([filters]))
    m.add(nn.Linear(filters, 100))
    m.add(nn.ReLU())
    m.add(nn.Linear(100, class_num))
    m.add(nn.LogSoftMax())
    return m
