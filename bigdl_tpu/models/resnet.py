"""ResNet (reference models/resnet/ResNet.scala, 283 LoC).

Builder supports the reference's CIFAR-10 recipe (depth = 6n+2 basic blocks,
shortcutType A/B) and the ImageNet bottleneck family (ResNet-18/34/50/101/
152, shortcutType B) — ResNet-50 is the BASELINE north-star model.

The reference's "optnet" memory tricks (shareGradInput, ResNet.scala:62-100,
SpatialShareConvolution) are buffer-aliasing workarounds for the JVM; under
XLA, buffer reuse is the compiler's memory planner, and the rematerialization
analog is `jax.checkpoint` applied per residual stage (see
``bigdl_tpu.core.remat``-style usage in train configs).

Init parity: convs use the He-style/Xavier reset the reference applies in
``modelInit`` (ResNet.scala:102+); final-block BN gamma zero-init
(zero_init_residual) is exposed as an option.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax.numpy as jnp

import jax
from jax import lax

from bigdl_tpu.core.module import Sequential, SimpleModule, xavier_uniform
from bigdl_tpu import nn

__all__ = ["resnet", "resnet_cifar", "resnet50", "basic_block",
           "bottleneck_block", "SpaceToDepthStem"]


class SpaceToDepthStem(SimpleModule):
    """MXU-friendly ImageNet stem: 2x2 space-to-depth then a 4x4/stride-1
    conv on 12 channels — arithmetically equivalent to the classic
    7x7/stride-2 conv on 3 channels (the MLPerf ResNet trick).

    Why: a 3-channel 7x7 conv contracts only 147 elements and pads the
    128-lane MXU to ~4% utilization (measured 7.1 TF/s on v5e, PERF.md
    §3); packing 2x2 pixel blocks into channels gives a 192-deep
    contraction at 1/4 the spatial positions. ``weight_from_conv7``
    embeds a trained 7x7 kernel exactly (receptive fields align: output
    row i covers pixel rows 2i-3..2i+3 = blocks i-2..i+1, so tap t maps
    to (block a, parity dy) with t = 2a+dy-1; the 45 slots outside that
    window are zero — a fresh init simply trains them, an 8x8-support
    stem with the same stride).
    """

    def __init__(self, out_planes: int = 64, name=None):
        super().__init__(name)
        self.out_planes = out_planes

    def init(self, rng):
        fan_in = 7 * 7 * 3  # the classic stem's fan-in, for init parity
        fan_out = 7 * 7 * self.out_planes
        return {"weight": xavier_uniform(rng, (4, 4, 12, self.out_planes),
                                         fan_in, fan_out, jnp.float32)}

    @staticmethod
    def weight_from_conv7(w7):
        """Embed a (7,7,3,out) stem kernel into the (4,4,12,out) layout."""
        import numpy as np

        w7 = np.asarray(w7)
        out = np.zeros((4, 4, 12, w7.shape[-1]), w7.dtype)
        for a in range(4):
            for dy in range(2):
                t = 2 * a + dy - 1
                if not 0 <= t < 7:
                    continue
                for b in range(4):
                    for dx in range(2):
                        u = 2 * b + dx - 1
                        if not 0 <= u < 7:
                            continue
                        ch = dy * 6 + dx * 3
                        out[a, b, ch:ch + 3, :] = w7[t, u, :, :]
        return out

    def _forward(self, params, x, *, training, rng):
        b, h, w, c = x.shape
        xb = (x.reshape(b, h // 2, 2, w // 2, 2, c)
              .transpose(0, 1, 3, 2, 4, 5)
              .reshape(b, h // 2, w // 2, 4 * c))
        return lax.conv_general_dilated(
            xb, params["weight"].astype(x.dtype), (1, 1),
            padding=((2, 1), (2, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _conv_bn(cin, cout, k, stride=1, pad=0, relu=True, gamma_init=1.0):
    m = [nn.SpatialConvolution(cin, cout, k, k, stride, stride, pad, pad,
                               with_bias=False, init="xavier"),
         nn.SpatialBatchNormalization(cout, gamma_init=gamma_init)]
    if relu:
        m.append(nn.ReLU())
    return m


def _shortcut(cin, cout, stride, shortcut_type: str):
    """Shortcut types (reference ResNet.scala shortcutType A/B/C):
    A = zero-padded identity (parameter-free, CIFAR paper),
    B = 1x1 conv when shape changes else identity,
    C = 1x1 conv always."""
    changed = cin != cout or stride != 1
    if shortcut_type == "C" or (shortcut_type == "B" and changed):
        return Sequential(*_conv_bn(cin, cout, 1, stride, 0, relu=False))
    if changed:  # type A
        pool = []
        if stride != 1:
            pool.append(nn.SpatialAveragePooling(1, 1, stride, stride))
        pad_c = cout - cin
        pool.append(nn.Padding(-1, pad_c, value=0.0))  # pad channels (NHWC)
        return Sequential(*pool)
    return nn.Identity()


def basic_block(cin, cout, stride=1, shortcut_type="B", zero_init=False):
    """3x3 + 3x3 (reference basicBlock). ``zero_init`` zero-initializes the
    final BN gamma so the block starts as identity (zero-init-residual)."""
    main = Sequential(
        *_conv_bn(cin, cout, 3, stride, 1),
        *_conv_bn(cout, cout, 3, 1, 1, relu=False,
                  gamma_init=0.0 if zero_init else 1.0),
    )
    return Sequential(
        nn.ConcatTable(main, _shortcut(cin, cout, stride, shortcut_type)),
        nn.CAddTable(),
        nn.ReLU(),
    )


def bottleneck_block(cin, planes, stride=1, shortcut_type="B",
                     expansion=4, zero_init=False):
    """1x1 reduce, 3x3, 1x1 expand (reference bottleneck)."""
    cout = planes * expansion
    main = Sequential(
        *_conv_bn(cin, planes, 1),
        *_conv_bn(planes, planes, 3, stride, 1),
        *_conv_bn(planes, cout, 1, relu=False,
                  gamma_init=0.0 if zero_init else 1.0),
    )
    return Sequential(
        nn.ConcatTable(main, _shortcut(cin, cout, stride, shortcut_type)),
        nn.CAddTable(),
        nn.ReLU(),
    )


_IMAGENET_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def resnet(depth: int = 50, class_num: int = 1000,
           shortcut_type: str = "B", zero_init_residual: bool = False,
           s2d_stem: bool = False, fused_bn=False) -> Sequential:
    """ImageNet ResNet (reference ResNet.apply with DataSet.ImageNet).
    Input (B, 224, 224, 3) NHWC. ``s2d_stem`` swaps the 7x7/2 stem for
    the space-to-depth equivalent (see :class:`SpaceToDepthStem`).
    ``fused_bn``: "stats" or "apply" routes every BN through the Pallas
    kernels at build time (nn.set_bn_fused); "apply" also absorbs the
    conv→BN→ReLU chains' ReLUs into the fused block epilogue."""
    kind, layers = _IMAGENET_CFG[depth]
    m = Sequential(name=f"ResNet{depth}")
    if s2d_stem:
        m.add(SpaceToDepthStem(64))
    else:
        m.add(nn.SpatialConvolution(3, 64, 7, 7, 2, 2, 3, 3,
                                    with_bias=False, init="xavier"))
    m.add(nn.SpatialBatchNormalization(64))
    m.add(nn.ReLU())
    m.add(nn.SpatialMaxPooling(3, 3, 2, 2, 1, 1))
    cin = 64
    for stage, n_blocks in enumerate(layers):
        planes = 64 * (2 ** stage)
        for b in range(n_blocks):
            stride = 2 if (stage > 0 and b == 0) else 1
            if kind == "basic":
                m.add(basic_block(cin, planes, stride, shortcut_type,
                                  zero_init=zero_init_residual))
                cin = planes
            else:
                m.add(bottleneck_block(cin, planes, stride, shortcut_type,
                                       zero_init=zero_init_residual))
                cin = planes * 4
    m.add(nn.SpatialAveragePooling(7, 7, 1, 1))
    m.add(nn.Reshape([cin]))
    m.add(nn.Linear(cin, class_num, init="xavier"))
    m.add(nn.LogSoftMax())
    if fused_bn:
        nn.set_bn_fused(m, fused_bn)
    return m


def resnet_cifar(depth: int = 20, class_num: int = 10,
                 shortcut_type: str = "A", fused_bn=False) -> Sequential:
    """CIFAR-10 ResNet, depth = 6n+2 (reference ResNet.apply CIFAR path;
    recipe in models/resnet/README: depth 20, shortcut A). Input
    (B, 32, 32, 3). ``fused_bn`` as in :func:`resnet`."""
    assert (depth - 2) % 6 == 0, "CIFAR depth must be 6n+2"
    n = (depth - 2) // 6
    m = Sequential(name=f"ResNet{depth}-cifar")
    m.add(nn.SpatialConvolution(3, 16, 3, 3, 1, 1, 1, 1, with_bias=False,
                                init="xavier"))
    m.add(nn.SpatialBatchNormalization(16))
    m.add(nn.ReLU())
    cin = 16
    for stage, planes in enumerate([16, 32, 64]):
        for b in range(n):
            stride = 2 if (stage > 0 and b == 0) else 1
            m.add(basic_block(cin, planes, stride, shortcut_type))
            cin = planes
    m.add(nn.SpatialAveragePooling(8, 8, 1, 1))
    m.add(nn.Reshape([64]))
    m.add(nn.Linear(64, class_num, init="xavier"))
    m.add(nn.LogSoftMax())
    if fused_bn:
        nn.set_bn_fused(m, fused_bn)
    return m


def resnet50(class_num: int = 1000, s2d_stem: bool = False,
             fused_bn=False) -> Sequential:
    return resnet(50, class_num, s2d_stem=s2d_stem, fused_bn=fused_bn)
