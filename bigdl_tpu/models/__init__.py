"""Model zoo (reference bigdl/models/: lenet, vgg, inception, resnet, rnn,
autoencoder + example/loadmodel AlexNet)."""

from bigdl_tpu.models.lenet import lenet5
from bigdl_tpu.models.vgg import vgg_for_cifar10, vgg16, vgg19
from bigdl_tpu.models.resnet import (
    resnet, resnet_cifar, resnet50, basic_block, bottleneck_block,
)
from bigdl_tpu.models.inception import (
    inception_v1, inception_v1_no_aux, inception_v2, inception_module,
)
from bigdl_tpu.models.alexnet import alexnet
from bigdl_tpu.models.autoencoder import autoencoder
from bigdl_tpu.models.rnn import (
    simple_rnn, lstm_classifier, birnn_classifier, text_cnn,
)
from bigdl_tpu.models.vit import ViT, vit, vit_b16, vit_s16
from bigdl_tpu.models.transformer_lm import (
    TransformerLM, transformer_lm, packed_lm_targets, PackedNLLCriterion,
)
