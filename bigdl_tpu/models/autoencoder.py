"""Fully-connected autoencoder on MNIST
(reference models/autoencoder/Autoencoder.scala: 784 -> 32 -> 784 with ReLU
hidden and Sigmoid output, trained with MSE)."""

from __future__ import annotations

from bigdl_tpu.core.module import Sequential
from bigdl_tpu import nn

__all__ = ["autoencoder"]


def autoencoder(class_num: int = 32) -> Sequential:
    """class_num = bottleneck width (the reference's classNum arg)."""
    return Sequential(
        nn.Reshape([28 * 28]),
        nn.Linear(28 * 28, class_num),
        nn.ReLU(),
        nn.Linear(class_num, 28 * 28),
        nn.Sigmoid(),
        name="Autoencoder",
    )
