"""LeNet-5 (reference models/lenet/Model.scala:26-40).

Same topology as the reference: conv(1->6,5x5) tanh pool conv(6->12,5x5)
tanh pool fc(12*4*4->100) tanh fc(100->10) logsoftmax — expressed over NHWC
(28,28,1) inputs. BASELINE config 1 ("LeNet-5 on MNIST, local mode").
"""

from __future__ import annotations

from bigdl_tpu.core.module import Sequential
from bigdl_tpu import nn

__all__ = ["lenet5"]


def lenet5(class_num: int = 10) -> Sequential:
    return Sequential(
        nn.SpatialConvolution(1, 6, 5, 5),
        nn.Tanh(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Tanh(),
        nn.SpatialConvolution(6, 12, 5, 5),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.Reshape([12 * 4 * 4]),
        nn.Linear(12 * 4 * 4, 100),
        nn.Tanh(),
        nn.Linear(100, class_num),
        nn.LogSoftMax(),
        name="LeNet5",
    )
