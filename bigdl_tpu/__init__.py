"""bigdl-tpu: a TPU-native deep-learning framework with the capabilities of
early BigDL (the Scala/Spark + Intel-MKL library at /root/reference).

Nothing here is a port: the reference's MKL/JNI compute lowers to XLA HLO,
its Engine thread pools dissolve into the compiler, and its Spark
BlockManager all-reduce becomes ICI/DCN collectives (see bigdl_tpu.parallel).
"""

__version__ = "0.5.0"

from bigdl_tpu import core, nn

__all__ = ["core", "nn", "__version__"]
