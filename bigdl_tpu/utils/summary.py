"""Model summary: layer tree with parameter counts and sizes (the
reference prints module graphs via Module.toString trees,
nn/Container.scala; this adds the param accounting a TPU user needs to
reason about HBM)."""

from __future__ import annotations

import jax

__all__ = ["param_count", "param_bytes", "summary"]


def param_count(params) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(params))


def param_bytes(params) -> int:
    return sum(int(x.size) * x.dtype.itemsize
               for x in jax.tree_util.tree_leaves(params))


def _fmt(n: float) -> str:
    for unit in ("", "K", "M", "B"):
        if abs(n) < 1000 or unit == "B":
            return f"{n:.1f}{unit}" if unit else f"{int(n)}"
        n /= 1000.0
    return f"{n:.1f}B"


def summary(module, params) -> str:
    """Render an indented layer tree with per-subtree parameter counts.

    ``params`` is the tree from ``module.init(rng)``; container children
    are looked up by their positional keys (the same convention init
    uses), so the printed counts always sum to the total.
    """
    lines = []

    def walk(mod, p, indent):
        n = param_count(p) if p is not None else 0
        lines.append(f"{'  ' * indent}{mod.name} "
                     f"[{type(mod).__name__}] params={_fmt(n)}")
        children = mod.children() if hasattr(mod, "children") else ()
        if isinstance(p, dict):
            for i, c in enumerate(children):
                # containers key children "0".."n-1"; composite modules
                # (TransformerLM etc.) key by attribute-style names —
                # try both
                sub = p.get(str(i))
                if sub is None:
                    for k, v in p.items():
                        if isinstance(v, dict) and k not in map(
                                str, range(len(children))):
                            if getattr(mod, k, None) is c:
                                sub = v
                                break
                walk(c, sub, indent + 1)
        elif children:
            for c in children:
                walk(c, None, indent + 1)

    walk(module, params, 0)
    total = param_count(params)
    mb = param_bytes(params) / 1e6
    lines.append(f"total params: {_fmt(total)} ({mb:.1f} MB)")
    return "\n".join(lines)
