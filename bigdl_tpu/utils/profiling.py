"""Tracing / profiling (SURVEY.md §5 "Tracing / profiling").

The reference has three mechanisms; each maps here:

1. Per-module wall-time counters (AbstractModule.forwardTime/getTimes,
   nn/abstractnn/AbstractModule.scala:107-152, Container.scala:70-77)
   -> :func:`time_modules`: walks a module tree, times each child's
   forward eagerly (outside jit — under jit XLA fuses across module
   boundaries, so per-module wall time is only meaningful per-dispatch),
   and returns (path, seconds) rows like ``getTimes()``.
2. Named counters aggregated across the cluster (optim/Metrics.scala via
   Spark accumulators) -> :class:`bigdl_tpu.optim.Metrics` (host-side
   counters; one process per host, aggregated by the caller).
3. Perf binaries (models/utils/DistriOptimizerPerf) -> bigdl_tpu.cli.perf.

New, TPU-only: :func:`trace` wraps ``jax.profiler.trace`` so any training
loop can emit an XPlane/TensorBoard trace (the XLA-level replacement for
per-op timers), and Sequential tags each child with ``jax.named_scope`` so
modules are identifiable inside the trace.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Optional

import jax

__all__ = ["time_modules", "trace", "format_times"]


@contextlib.contextmanager
def trace(logdir: str):
    """Profile a block into ``logdir`` (open with TensorBoard or xprof):

    >>> with trace("/tmp/tb"):
    ...     step(params, ...)  # traced on device timeline
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def time_modules(module, params, x, state: Optional[Any] = None,
                 iters: int = 3, training: bool = False, rng=None):
    """Per-child forward wall time, eagerly, recursing into Sequential
    chains (reference getTimes semantics). Returns rows
    ``(path, seconds_per_call)`` ordered by execution; container rows hold
    the sum of their children.
    """
    if state is None:
        state = module.init_state()
    rows: list[tuple[str, float]] = []

    def run(mod, p, s, x, path):
        from bigdl_tpu.core.module import Sequential

        if isinstance(mod, Sequential):
            total = 0.0
            start_row = len(rows)
            rows.append((path, 0.0))  # placeholder, filled after children
            for i, child in enumerate(mod.children()):
                k = str(i)
                x, dt = run(child, p[k], s[k], x,
                            f"{path}.{i}:{child.name}")
                total += dt
            rows[start_row] = (path, total)
            return x, total

        def once():
            t0 = time.perf_counter()
            y, _ = mod.apply(p, s, x, training=training, rng=rng)
            jax.block_until_ready(y)
            return y, time.perf_counter() - t0

        y, _ = once()  # warmup/compile
        best = min(once()[1] for _ in range(max(1, iters)))
        rows.append((path, best))
        return y, best

    run(module, params, state, x, module.name)
    return rows


def format_times(rows) -> str:
    """Pretty table like the reference's getTimes log (module, time)."""
    width = max(len(p) for p, _ in rows)
    lines = [f"{p:<{width}}  {dt * 1e3:10.3f} ms" for p, dt in rows]
    return "\n".join(lines)
