"""Checkpoint IO (reference utils/File.scala:27-131, Optimizer.saveModel/
saveState :137-149).

The reference Java-serializes the module graph with transparent ``hdfs://``
support (File.scala:63-116); here checkpoints are pytrees of numpy arrays
in a ``np.savez`` archive with a pickled treedef, and any ``scheme://``
path (``gs://``, ``s3://``, ``memory://``, ...) routes through fsspec — a
v5e-pod run checkpoints straight to object storage. The two-artifact
convention (``model.<n>`` for params+state, ``state.<n>`` for optimizer
state) is preserved.

Portability note: the embedded treedef is a pickle of jax's treedef object
— stable across checkpoint/restore on the same software stack, but not a
long-term archival format (pickle + jax-internal classes). For
cross-version archival, export leaves by name instead.
"""

from __future__ import annotations

import io
import os
import pickle
from typing import Any

import jax
import numpy as np

__all__ = ["save_pytree", "load_pytree", "latest_checkpoint", "is_remote",
           "isdir", "exists"]


def is_remote(path: str) -> bool:
    """True for scheme-prefixed (fsspec) paths like gs://bucket/dir."""
    return "://" in path


def exists(path: str) -> bool:
    """Existence test that works on local paths and fsspec URIs (the
    checkpoint overwrite guard must hold on ``gs://`` pod paths too —
    reference File.scala:63-116 routes everything through one FS API)."""
    if is_remote(path):
        fs, p = _fs_for(path)
        return fs.exists(p)
    return os.path.exists(path)


def isdir(path: str) -> bool:
    """Directory test that works on local paths and fsspec URIs (orbax
    checkpoints are directories; single-blob ones are files)."""
    if is_remote(path):
        fs, p = _fs_for(path)
        return fs.isdir(p)
    return os.path.isdir(path)


def _fs_for(path: str):
    import fsspec

    return fsspec.core.url_to_fs(path)  # (fs, stripped_path)


def save_pytree(tree: Any, path: str) -> None:
    """Write a pytree of arrays to ``path`` (.npz + embedded treedef).
    Local writes are atomic (tmp + rename); remote writes are single puts
    (object stores don't expose rename, but puts are all-or-nothing)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    meta = np.frombuffer(pickle.dumps(treedef), dtype=np.uint8)
    if is_remote(path):
        # object stores want one put; buffer in RAM (getbuffer: no copy)
        fs, p = _fs_for(path)
        parent = p.rsplit("/", 1)[0]
        if parent:
            fs.makedirs(parent, exist_ok=True)
        payload = io.BytesIO()
        np.savez(payload, __treedef__=meta, **arrays)
        with fs.open(p, "wb") as f:
            f.write(payload.getbuffer())
        return
    # local: stream straight to the tmp file (no in-RAM archive copy —
    # checkpoints can be multi-GB), then atomic rename
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, __treedef__=meta, **arrays)
    os.replace(tmp, path)


def load_pytree(path: str) -> Any:
    if is_remote(path):
        fs, p = _fs_for(path)
        with fs.open(p, "rb") as f:
            buf = io.BytesIO(f.read())
    else:
        buf = path
    with np.load(buf, allow_pickle=False) as z:
        treedef = pickle.loads(z["__treedef__"].tobytes())
        leaves = [z[f"leaf_{i}"] for i in range(len(z.files) - 1)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def save_module(module, params, mod_state, path: str) -> None:
    """Whole-model file: the module DEFINITION (pickled — modules are
    plain Python descriptions with no arrays inside) plus its
    params/mod_state pytrees, in one artifact — the analog of the
    reference's ``model.save(path)`` (Java-serialized Module,
    nn/Module.scala:28-42), so a Test/predict program needs no builder
    code. Weights-only interchange stays on ``save_pytree``.
    """
    blob = {"params": params, "mod_state": mod_state,
            "__module__": np.frombuffer(pickle.dumps(module),
                                        dtype=np.uint8)}
    save_pytree(blob, path)


def load_module(path: str):
    """-> (module, params, mod_state). Trust note: like the reference's
    Java deserialization, the module definition is a pickle — load only
    files you produced."""
    blob = load_pytree(path)
    module = pickle.loads(blob.pop("__module__").tobytes())
    return module, blob["params"], blob["mod_state"]


def latest_checkpoint(directory: str, prefix: str = "model.") -> str | None:
    """Find the highest-numbered ``<prefix><n>`` entry (resume helper,
    reference models/lenet/Train.scala:55-67 --model/--state flags).
    Works on local dirs and fsspec URIs."""
    if is_remote(directory):
        fs, d = _fs_for(directory)
        if not fs.isdir(d):
            return None
        scheme = directory.split("://", 1)[0]
        names = [e.rsplit("/", 1)[-1] for e in fs.ls(d, detail=False)]
        join = lambda f: f"{scheme}://{d.rstrip('/')}/{f}"
    else:
        if not os.path.isdir(directory):
            return None
        names = os.listdir(directory)
        join = lambda f: os.path.join(directory, f)
    best, best_n = None, -1
    for f in names:
        if f.startswith(prefix):
            try:
                n = int(f[len(prefix):])
            except ValueError:
                continue
            if n > best_n:
                best, best_n = join(f), n
    return best


def latest_checkpoint_pair(directory: str):
    """Newest iteration n for which BOTH ``model.n`` and ``state.n`` exist,
    as ``(model_path, state_path)`` — ``(None, None)`` if none. An unclean
    death (kill -9) can land between the two writes; pairing the newest of
    each independently would silently mix params from iteration N with
    optimizer state from N-k."""
    if is_remote(directory):
        fs, d = _fs_for(directory)
        if not fs.isdir(d):
            return None, None
        scheme = directory.split("://", 1)[0]
        names = [e.rsplit("/", 1)[-1] for e in fs.ls(d, detail=False)]
        join = lambda f: f"{scheme}://{d.rstrip('/')}/{f}"
    else:
        if not os.path.isdir(directory):
            return None, None
        names = os.listdir(directory)
        join = lambda f: os.path.join(directory, f)

    def idxs(prefix):
        out = set()
        for f in names:
            if f.startswith(prefix):
                try:
                    out.add(int(f[len(prefix):]))
                except ValueError:
                    pass
        return out

    common = idxs("model.") & idxs("state.")
    if not common:
        return None, None
    n = max(common)
    return join(f"model.{n}"), join(f"state.{n}")


def orphaned_snapshots(directory: str, newer_than: int):
    """Snapshot paths (``model.n`` / ``state.n``) with ``n > newer_than``
    — after an unclean death these are by construction unmatched (else
    :func:`latest_checkpoint_pair` would have returned them) and a
    resumed run whose counters continue past ``newer_than`` will want to
    overwrite exactly these names."""
    if is_remote(directory):
        fs, d = _fs_for(directory)
        if not fs.isdir(d):
            return []
        scheme = directory.split("://", 1)[0]
        names = [e.rsplit("/", 1)[-1] for e in fs.ls(d, detail=False)]
        join = lambda f: f"{scheme}://{d.rstrip('/')}/{f}"
    else:
        if not os.path.isdir(directory):
            return []
        names = os.listdir(directory)
        join = lambda f: os.path.join(directory, f)
    out = []
    for f in names:
        for prefix in ("model.", "state."):
            if f.startswith(prefix):
                try:
                    if int(f[len(prefix):]) > newer_than:
                        out.append(join(f))
                except ValueError:
                    pass
    return out
