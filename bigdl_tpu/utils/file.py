"""Checkpoint IO (reference utils/File.scala:27-131, Optimizer.saveModel/
saveState :137-149).

The reference Java-serializes the module graph with transparent ``hdfs://``
support (File.scala:63-116); here checkpoints are pytrees of numpy arrays
in a ``np.savez`` archive with a pickled treedef, and any ``scheme://``
path (``gs://``, ``s3://``, ``memory://``, ...) routes through fsspec — a
v5e-pod run checkpoints straight to object storage. The two-artifact
convention (``model.<n>`` for params+state, ``state.<n>`` for optimizer
state) is preserved.

Portability note: the embedded treedef is a pickle of jax's treedef object
— stable across checkpoint/restore on the same software stack, but not a
long-term archival format (pickle + jax-internal classes). For
cross-version archival, export leaves by name instead.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

# fault-injection sites for the resilience layer (no-ops unless a
# --faultPlan is installed); ChecksumError lives with the fault taxonomy
from bigdl_tpu.resilience.faults import (ChecksumError, hook as _fault_hook,
                                         post_write_hook as _post_write_hook)

logger = logging.getLogger("bigdl_tpu")

__all__ = ["save_pytree", "load_pytree", "latest_checkpoint", "is_remote",
           "isdir", "exists", "ChecksumError", "checksum_path",
           "verify_checkpoint", "latest_valid_checkpoint_pair",
           "gc_checkpoints", "manifest_path", "read_manifest",
           "verify_manifest", "restore_resharded"]

# every save_pytree/save_module writes `<path>.sha256` next to the blob;
# load verifies it, so a torn-then-renamed or bit-rotted checkpoint is
# caught at restore (ChecksumError) instead of producing silent garbage
CHECKSUM_SUFFIX = ".sha256"

# topology manifest (ISSUE 11): `<path>.manifest.json` records the
# LOGICAL (unsharded) leaf shapes/dtypes plus the dp layout signature the
# writer ran under. Blobs already hold gathered host arrays, so the
# manifest is what lets `restore_resharded` place a checkpoint written at
# 8 devices into a 7- or 4-device mesh with shape validation instead of
# trust. Version bumps invalidate parsing, never the blob.
MANIFEST_SUFFIX = ".manifest.json"
MANIFEST_VERSION = 1


def checksum_path(path: str) -> str:
    return path + CHECKSUM_SUFFIX


def manifest_path(path: str) -> str:
    return path + MANIFEST_SUFFIX


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _write_sidecar(path: str, digest: str) -> None:
    if is_remote(path):
        fs, p = _fs_for(path)
        with fs.open(p + CHECKSUM_SUFFIX, "wb") as f:
            f.write(digest.encode())
        return
    tmp = checksum_path(path) + ".tmp"
    with open(tmp, "w") as f:
        f.write(digest)
    os.replace(tmp, checksum_path(path))


def is_remote(path: str) -> bool:
    """True for scheme-prefixed (fsspec) paths like gs://bucket/dir."""
    return "://" in path


def exists(path: str) -> bool:
    """Existence test that works on local paths and fsspec URIs (the
    checkpoint overwrite guard must hold on ``gs://`` pod paths too —
    reference File.scala:63-116 routes everything through one FS API)."""
    if is_remote(path):
        fs, p = _fs_for(path)
        return fs.exists(p)
    return os.path.exists(path)


def isdir(path: str) -> bool:
    """Directory test that works on local paths and fsspec URIs (orbax
    checkpoints are directories; single-blob ones are files)."""
    if is_remote(path):
        fs, p = _fs_for(path)
        return fs.isdir(p)
    return os.path.isdir(path)


def _fs_for(path: str):
    import fsspec

    return fsspec.core.url_to_fs(path)  # (fs, stripped_path)


def _write_manifest(path: str, arrays, layout: Optional[dict]) -> None:
    """Topology manifest sidecar: logical leaf shapes/dtypes + the
    writer's dp layout signature. Written LAST (after blob + checksum),
    so its presence implies a complete pair; like the sidecar, local
    writes go through tmp + rename so readers only ever see whole JSON
    or nothing — a torn write truncates mid-document and fails to
    parse, which the pair scan treats like a torn blob."""
    doc = {"version": MANIFEST_VERSION,
           "n_leaves": len(arrays),
           "leaves": [{"shape": list(a.shape), "dtype": str(a.dtype)}
                      for a in arrays],
           "layout": layout}
    body = json.dumps(doc, sort_keys=True)
    if is_remote(path):
        fs, p = _fs_for(path)
        with fs.open(p + MANIFEST_SUFFIX, "w") as f:
            f.write(body)
        return
    tmp = manifest_path(path) + ".tmp"
    with open(tmp, "w") as f:
        f.write(body)
    os.replace(tmp, manifest_path(path))


def save_pytree(tree: Any, path: str, layout: Optional[dict] = None) -> None:
    """Write a pytree of arrays to ``path`` (.npz + embedded treedef)
    plus a ``<path>.sha256`` checksum sidecar and a
    ``<path>.manifest.json`` topology manifest. Local writes are atomic
    (tmp + rename, sidecar written AFTER the blob so a sidecar's
    presence implies a complete blob existed); remote writes are single
    puts (object stores don't expose rename, but puts are
    all-or-nothing). ``layout`` is the writer's dp layout signature
    (``DataParallel.layout_signature()``) — recorded for provenance
    only; the blob always holds logical (gathered, unsharded) arrays, so
    ``restore_resharded`` can place it into any mesh."""
    _fault_hook("ckpt_save")
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    meta = np.frombuffer(pickle.dumps(treedef), dtype=np.uint8)
    if is_remote(path):
        # object stores want one put; buffer in RAM (getbuffer: no copy)
        fs, p = _fs_for(path)
        parent = p.rsplit("/", 1)[0]
        if parent:
            fs.makedirs(parent, exist_ok=True)
        payload = io.BytesIO()
        np.savez(payload, __treedef__=meta, **arrays)
        with fs.open(p, "wb") as f:
            f.write(payload.getbuffer())
        _write_sidecar(path, hashlib.sha256(payload.getbuffer()).hexdigest())
        _write_manifest(path, [arrays[f"leaf_{i}"]
                               for i in range(len(leaves))], layout)
        _post_write_hook("ckpt_save", path)
        return
    # local: stream straight to the tmp file (no in-RAM archive copy —
    # checkpoints can be multi-GB), then atomic rename
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, __treedef__=meta, **arrays)
    digest = _file_sha256(tmp)
    os.replace(tmp, path)
    _write_sidecar(path, digest)
    _write_manifest(path, [arrays[f"leaf_{i}"]
                           for i in range(len(leaves))], layout)
    _post_write_hook("ckpt_save", path)


def _read_sidecar(path: str):
    """The expected digest, or None when no sidecar exists (pre-ISSUE-6
    snapshots stay loadable — they just can't be *verified*)."""
    try:
        if is_remote(path):
            fs, p = _fs_for(path)
            if not fs.exists(p + CHECKSUM_SUFFIX):
                return None
            with fs.open(p + CHECKSUM_SUFFIX, "rb") as f:
                return f.read().decode().strip()
        if not os.path.exists(checksum_path(path)):
            return None
        with open(checksum_path(path)) as f:
            return f.read().strip()
    except OSError:
        return None


def load_pytree(path: str, verify: bool = True) -> Any:
    """Load a pytree; when ``verify`` and a checksum sidecar exists, the
    blob is digested first and a mismatch raises :class:`ChecksumError`
    — a torn or bit-rotted checkpoint fails loudly at restore instead of
    deserializing garbage."""
    _fault_hook("ckpt_restore")
    expected = _read_sidecar(path) if verify else None
    if is_remote(path):
        fs, p = _fs_for(path)
        with fs.open(p, "rb") as f:
            buf = io.BytesIO(f.read())
        if expected is not None:
            got = hashlib.sha256(buf.getbuffer()).hexdigest()
            if got != expected:
                raise ChecksumError(
                    f"{path}: checksum mismatch (sidecar {expected[:12]}…, "
                    f"blob {got[:12]}…) — torn write or bit-rot")
    else:
        if expected is not None:
            got = _file_sha256(path)
            if got != expected:
                raise ChecksumError(
                    f"{path}: checksum mismatch (sidecar {expected[:12]}…, "
                    f"blob {got[:12]}…) — torn write or bit-rot")
        buf = path
    with np.load(buf, allow_pickle=False) as z:
        treedef = pickle.loads(z["__treedef__"].tobytes())
        leaves = [z[f"leaf_{i}"] for i in range(len(z.files) - 1)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def verify_checkpoint(path: str) -> bool:
    """True when ``path`` is usable: its sidecar (if any) matches the
    blob. Sidecar-less artifacts (legacy snapshots, orbax directories)
    verify True — they cannot be checked, only trusted, as before."""
    try:
        if isdir(path):
            return True  # orbax sharded dirs carry no single-blob digest
        expected = _read_sidecar(path)
        if expected is None:
            return True
        if is_remote(path):
            fs, p = _fs_for(path)
            with fs.open(p, "rb") as f:
                got = hashlib.sha256(f.read()).hexdigest()
        else:
            got = _file_sha256(path)
        return got == expected
    except OSError:
        return False


def read_manifest(path: str) -> Optional[dict]:
    """The topology manifest for blob ``path``, or None when no manifest
    exists (pre-ISSUE-11 snapshots stay loadable — they just carry no
    layout provenance). A present-but-unparseable manifest raises
    :class:`ChecksumError`: a torn manifest is a torn artifact."""
    mp = manifest_path(path)
    try:
        if is_remote(path):
            fs, p = _fs_for(path)
            if not fs.exists(p + MANIFEST_SUFFIX):
                return None
            with fs.open(p + MANIFEST_SUFFIX, "r") as f:
                body = f.read()
        else:
            if not os.path.exists(mp):
                return None
            with open(mp) as f:
                body = f.read()
    except OSError as e:
        raise ChecksumError(f"{mp}: unreadable manifest: {e}") from None
    try:
        doc = json.loads(body)
        if not isinstance(doc, dict) or "version" not in doc:
            raise ValueError("not a manifest document")
    except ValueError as e:
        raise ChecksumError(
            f"{mp}: torn or corrupt topology manifest ({e})") from None
    return doc


def verify_manifest(path: str) -> bool:
    """True when blob ``path``'s manifest is absent (legacy) or parses
    cleanly — the manifest leg of pair validation, mirroring
    :func:`verify_checkpoint` for blobs."""
    try:
        read_manifest(path)
        return True
    except ChecksumError:
        return False


def restore_resharded(path: str, mesh, axis: str = "data",
                      zero1: bool = True, verify: bool = True):
    """Load the checkpoint blob at ``path`` — written under ANY dp
    topology — and place its leaves into ``mesh`` (built by
    ``parallel/mesh.make_mesh``), resharding optimizer state for the
    current device count.

    Blobs hold logical (gathered, unsharded) host arrays, so resharding
    is a placement decision, not a data transform: with ``zero1`` each
    leaf goes through the same ``_zero1_spec`` rule ``DataParallel``
    shards live optimizer state with (largest dim divisible by the axis
    size, else replicate), otherwise everything is fully replicated.
    When a manifest exists its logical shapes are validated against the
    loaded leaves first — a blob/manifest mismatch raises
    :class:`ChecksumError` rather than silently placing wrong shapes."""
    from jax.sharding import NamedSharding, PartitionSpec

    from bigdl_tpu.parallel.data_parallel import _zero1_spec

    tree = load_pytree(path, verify=verify)
    man = read_manifest(path)
    if man is not None:
        leaves = jax.tree_util.tree_leaves(tree)
        recorded = man.get("leaves") or []
        if man.get("n_leaves") != len(leaves) or len(recorded) != len(leaves):
            raise ChecksumError(
                f"{path}: manifest records {man.get('n_leaves')} leaves, "
                f"blob holds {len(leaves)}")
        for i, (leaf, rec) in enumerate(zip(leaves, recorded)):
            got = list(np.shape(leaf))
            want = list(rec.get("shape", []))
            if got != want:
                raise ChecksumError(
                    f"{path}: leaf {i} logical shape {got} != manifest "
                    f"{want} — blob and manifest disagree")

    def _place(x):
        arr = np.asarray(x)
        if zero1 and arr.ndim > 0:
            spec = _zero1_spec(arr, mesh, axis)
        else:
            spec = PartitionSpec()
        return jax.device_put(arr, NamedSharding(mesh, spec))

    return jax.tree_util.tree_map(_place, tree)


def save_module(module, params, mod_state, path: str,
                layout: Optional[dict] = None) -> None:
    """Whole-model file: the module DEFINITION (pickled — modules are
    plain Python descriptions with no arrays inside) plus its
    params/mod_state pytrees, in one artifact — the analog of the
    reference's ``model.save(path)`` (Java-serialized Module,
    nn/Module.scala:28-42), so a Test/predict program needs no builder
    code. Weights-only interchange stays on ``save_pytree``.
    """
    blob = {"params": params, "mod_state": mod_state,
            "__module__": np.frombuffer(pickle.dumps(module),
                                        dtype=np.uint8)}
    save_pytree(blob, path, layout=layout)


def load_module(path: str):
    """-> (module, params, mod_state). Trust note: like the reference's
    Java deserialization, the module definition is a pickle — load only
    files you produced."""
    blob = load_pytree(path)
    module = pickle.loads(blob.pop("__module__").tobytes())
    return module, blob["params"], blob["mod_state"]


def latest_checkpoint(directory: str, prefix: str = "model.") -> str | None:
    """Find the highest-numbered ``<prefix><n>`` entry (resume helper,
    reference models/lenet/Train.scala:55-67 --model/--state flags).
    Works on local dirs and fsspec URIs."""
    if is_remote(directory):
        fs, d = _fs_for(directory)
        if not fs.isdir(d):
            return None
        scheme = directory.split("://", 1)[0]
        names = [e.rsplit("/", 1)[-1] for e in fs.ls(d, detail=False)]
        join = lambda f: f"{scheme}://{d.rstrip('/')}/{f}"
    else:
        if not os.path.isdir(directory):
            return None
        names = os.listdir(directory)
        join = lambda f: os.path.join(directory, f)
    best, best_n = None, -1
    for f in names:
        if f.startswith(prefix):
            try:
                n = int(f[len(prefix):])
            except ValueError:
                continue
            if n > best_n:
                best, best_n = join(f), n
    return best


def latest_checkpoint_pair(directory: str):
    """Newest iteration n for which BOTH ``model.n`` and ``state.n`` exist,
    as ``(model_path, state_path)`` — ``(None, None)`` if none. An unclean
    death (kill -9) can land between the two writes; pairing the newest of
    each independently would silently mix params from iteration N with
    optimizer state from N-k."""
    if is_remote(directory):
        fs, d = _fs_for(directory)
        if not fs.isdir(d):
            return None, None
        scheme = directory.split("://", 1)[0]
        names = [e.rsplit("/", 1)[-1] for e in fs.ls(d, detail=False)]
        join = lambda f: f"{scheme}://{d.rstrip('/')}/{f}"
    else:
        if not os.path.isdir(directory):
            return None, None
        names = os.listdir(directory)
        join = lambda f: os.path.join(directory, f)

    def idxs(prefix):
        out = set()
        for f in names:
            if f.startswith(prefix):
                try:
                    out.add(int(f[len(prefix):]))
                except ValueError:
                    pass
        return out

    common = idxs("model.") & idxs("state.")
    if not common:
        return None, None
    n = max(common)
    return join(f"model.{n}"), join(f"state.{n}")


def _dir_listing(directory: str):
    """(names, join) for local dirs and fsspec URIs — None when the
    directory does not exist. The shared base of the pair/GC helpers."""
    if is_remote(directory):
        fs, d = _fs_for(directory)
        if not fs.isdir(d):
            return None
        scheme = directory.split("://", 1)[0]
        names = [e.rsplit("/", 1)[-1] for e in fs.ls(d, detail=False)]
        return names, (lambda f: f"{scheme}://{d.rstrip('/')}/{f}")
    if not os.path.isdir(directory):
        return None
    names = os.listdir(directory)
    return names, (lambda f: os.path.join(directory, f))


def _snapshot_indices(names, prefix):
    out = set()
    for f in names:
        if f.startswith(prefix):
            try:
                out.add(int(f[len(prefix):]))
            except ValueError:
                pass  # .sha256 sidecars, .tmp leftovers
    return out


def latest_valid_checkpoint_pair(directory: str):
    """Newest iteration n whose ``model.n``/``state.n`` pair BOTH verify
    against their checksum sidecars, as ``(model_path, state_path)`` —
    ``(None, None)`` if none. Corrupt (checksum-mismatched) pairs are
    skipped with a warning, falling back to the previous pair: the
    recovery contract a supervised resume relies on (a bit-rotted newest
    snapshot must cost one checkpoint interval, not the run)."""
    listing = _dir_listing(directory)
    if listing is None:
        return None, None
    names, join = listing
    common = (_snapshot_indices(names, "model.")
              & _snapshot_indices(names, "state."))
    for n in sorted(common, reverse=True):
        m, s = join(f"model.{n}"), join(f"state.{n}")
        if (verify_checkpoint(m) and verify_checkpoint(s)
                and verify_manifest(m) and verify_manifest(s)):
            return m, s
        logger.warning("checkpoint pair %d in %s fails checksum or "
                       "manifest verification — falling back to the "
                       "previous snapshot", n, directory)
    return None, None


def gc_checkpoints(directory: str, keep_last: int,
                   prefixes=("model.", "state.")):
    """Delete all but the newest ``keep_last`` snapshot iterations (blobs
    + sidecars). The newest VALID pair is never deleted, even when
    corrupt newer snapshots push it outside the keep window — the GC
    must not destroy the only recovery point. Returns deleted paths."""
    if keep_last < 1:
        raise ValueError(f"keep_last must be >= 1, got {keep_last}")
    listing = _dir_listing(directory)
    if listing is None:
        return []
    names, join = listing
    all_idx = set()
    for prefix in prefixes:
        all_idx |= _snapshot_indices(names, prefix)
    keep = set(sorted(all_idx, reverse=True)[:keep_last])
    m_valid, _ = latest_valid_checkpoint_pair(directory)
    if m_valid is not None:
        tail = str(m_valid).rstrip("/").rsplit(".", 1)[-1]
        if tail.isdigit():
            keep.add(int(tail))
    deleted = []
    remote = is_remote(directory)
    for n in sorted(all_idx - keep):
        for prefix in prefixes:
            if n not in _snapshot_indices(names, prefix):
                continue
            # a blob's sidecar AND manifest leave with it — never before
            # (a surviving pair keeps its manifest), never after (no
            # orphaned manifests describing deleted blobs)
            for path in (join(f"{prefix}{n}"),
                         join(f"{prefix}{n}") + CHECKSUM_SUFFIX,
                         join(f"{prefix}{n}") + MANIFEST_SUFFIX):
                try:
                    if remote:
                        fs, p = _fs_for(path)
                        if fs.exists(p):
                            fs.rm(p, recursive=True)
                            deleted.append(path)
                    elif os.path.isdir(path):
                        import shutil
                        shutil.rmtree(path)
                        deleted.append(path)
                    elif os.path.exists(path):
                        os.remove(path)
                        deleted.append(path)
                except OSError as e:
                    logger.warning("checkpoint GC: could not delete %s: "
                                   "%s", path, e)
    if deleted:
        logger.info("checkpoint GC: removed %d artifact(s), kept "
                    "iterations %s", len(deleted), sorted(keep))
    return deleted


def orphaned_snapshots(directory: str, newer_than: int):
    """Snapshot paths (``model.n`` / ``state.n``) with ``n > newer_than``
    — after an unclean death these are by construction unmatched (else
    :func:`latest_checkpoint_pair` would have returned them) and a
    resumed run whose counters continue past ``newer_than`` will want to
    overwrite exactly these names."""
    if is_remote(directory):
        fs, d = _fs_for(directory)
        if not fs.isdir(d):
            return []
        scheme = directory.split("://", 1)[0]
        names = [e.rsplit("/", 1)[-1] for e in fs.ls(d, detail=False)]
        join = lambda f: f"{scheme}://{d.rstrip('/')}/{f}"
    else:
        if not os.path.isdir(directory):
            return []
        names = os.listdir(directory)
        join = lambda f: os.path.join(directory, f)
    out = []
    for f in names:
        for prefix in ("model.", "state."):
            if f.startswith(prefix):
                try:
                    if int(f[len(prefix):]) > newer_than:
                        out.append(join(f))
                except ValueError:
                    pass
    return out
