"""Checkpoint IO (reference utils/File.scala:27-131, Optimizer.saveModel/
saveState :137-149).

The reference Java-serializes the module graph; here checkpoints are pytrees
of numpy arrays in a ``np.savez`` archive with a pickled treedef — portable,
no framework objects inside. The two-artifact convention (``model.<n>`` for
params+state, ``state.<n>`` for optimizer state) is preserved.
"""

from __future__ import annotations

import os
import pickle
from typing import Any

import jax
import numpy as np

__all__ = ["save_pytree", "load_pytree", "latest_checkpoint"]


def save_pytree(tree: Any, path: str) -> None:
    """Write a pytree of arrays to ``path`` (.npz + embedded treedef)."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, __treedef__=np.frombuffer(
            pickle.dumps(treedef), dtype=np.uint8), **arrays)
    os.replace(tmp, path)


def load_pytree(path: str) -> Any:
    with np.load(path, allow_pickle=False) as z:
        treedef = pickle.loads(z["__treedef__"].tobytes())
        leaves = [z[f"leaf_{i}"] for i in range(len(z.files) - 1)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def latest_checkpoint(directory: str, prefix: str = "model.") -> str | None:
    """Find the highest-numbered ``<prefix><n>`` file (resume helper,
    reference models/lenet/Train.scala:55-67 --model/--state flags)."""
    if not os.path.isdir(directory):
        return None
    best, best_n = None, -1
    for f in os.listdir(directory):
        if f.startswith(prefix):
            try:
                n = int(f[len(prefix):])
            except ValueError:
                continue
            if n > best_n:
                best, best_n = os.path.join(directory, f), n
    return best
