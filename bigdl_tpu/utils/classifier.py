"""Batched inference facade (reference utils/DLClassifier.scala:36-136 —
a Spark-ML Transformer that batches DataFrame rows, runs model.forward and
emits argmax predictions, with a per-partition cached model).

Without Spark, the equivalent surface is: wrap (module, params) once,
compile one jitted forward for a fixed batch size, stream any array /
iterable through it in fixed batches (padding the tail so XLA sees a single
static shape), return predictions. Plugs into anything that feeds numpy
arrays — the role DataFrames play in the reference.
"""

from __future__ import annotations

from typing import Any, Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Classifier"]


class Classifier:
    """``Classifier(model, params, mod_state)(x)`` -> class ids.

    ``batch_size`` fixes the compiled shape; inputs of any length are
    processed in chunks with tail padding (discarded after the forward).
    """

    def __init__(self, module, params, mod_state=None, batch_size: int = 128):
        self.module = module
        self.params = params
        self.mod_state = (mod_state if mod_state is not None
                          else module.init_state())
        self.batch_size = batch_size

        def fwd(params, mod_state, x):
            y, _ = module.apply(params, mod_state, x, training=False)
            return y

        self._fwd = jax.jit(fwd)

    def predict_scores(self, x: np.ndarray) -> np.ndarray:
        """Raw model outputs (e.g. log-probs) for every row of x."""
        n = len(x)
        if n == 0:
            feat_shape = np.asarray(x).shape[1:]
            if not feat_shape:
                # a plain empty list carries no feature dims — nothing to
                # trace a forward with; return a benign empty vector
                return np.zeros((0,), np.float32)
            # learn the output shape without compiling or executing the
            # forward: abstract evaluation of the same jitted fn
            probe = jax.ShapeDtypeStruct((self.batch_size,) + feat_shape,
                                         jnp.float32)
            y = jax.eval_shape(self._fwd, self.params, self.mod_state,
                               probe)
            return np.zeros((0,) + y.shape[1:])
        outs = []
        for i in range(0, n, self.batch_size):
            chunk = np.asarray(x[i:i + self.batch_size])
            pad = self.batch_size - len(chunk)
            if pad > 0:  # pad the tail so the jitted shape is static
                chunk = np.concatenate(
                    [chunk, np.repeat(chunk[-1:], pad, axis=0)])
            y = self._fwd(self.params, self.mod_state, jnp.asarray(chunk))
            outs.append(np.asarray(y)[:len(x[i:i + self.batch_size])])
        return np.concatenate(outs)

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Argmax class ids (reference DLClassifier's prediction column)."""
        scores = self.predict_scores(x)
        if len(scores) == 0:
            return np.zeros((0,), np.int64)
        return np.argmax(scores, axis=-1)

    def predict_iter(self, batches: Iterable[Any]) -> Iterable[np.ndarray]:
        """Stream predictions over an iterator of feature batches."""
        for b in batches:
            feats = b.input if hasattr(b, "input") else b
            yield self.predict(np.asarray(feats))

    def __call__(self, x: np.ndarray) -> np.ndarray:
        return self.predict(x)
