"""Analytic matmul/conv FLOPs from a traced jaxpr.

The reference trusts its perf harness because the metric is simple and
auditable (records/second, DistriOptimizerPerf.scala:35-150). Our MFU
metric needs a FLOPs numerator that is equally auditable: XLA's
``compiled.cost_analysis()["flops"]`` is backend-dependent and opaque, so
we count FLOPs ourselves by walking the jaxpr of the (uncompiled) train
step and summing the two primitives where essentially all deep-learning
FLOPs live:

* ``dot_general``: 2 x batch x M x N x K
* ``conv_general_dilated``: 2 x |out| x (C_in/groups) x prod(kernel spatial)

Everything else (elementwise, reductions, layout) is bandwidth-bound on
TPU and excluded by convention — this is the standard "model FLOPs"
denominator used for MFU. Control-flow bodies are recursed into
(``scan`` multiplied by trip count, ``cond`` by the most expensive
branch); ``remat`` bodies are counted once (algorithmic FLOPs, not
executed FLOPs, per the usual MFU definition).

GEMM-path accounting (ISSUE 3): when the per-geometry conv policy runs a
1x1 stride-1 conv as ``dot_general`` over ``(N*H*W, Cin) x (Cin, Cout)``,
the contraction is unchanged — ``2*N*H*W*Cin*Cout`` FLOPs either way —
so the analytic numerator is invariant under the layout/GEMM choice; the
two primitive rules above agree by construction, and
:func:`conv_unit_flops` is the closed-form spelling shared by the probe
and roofline scripts so every TF/s figure in PERF.md uses one numerator.
"""

from __future__ import annotations

import math

import jax
from jax.extend import core as jex_core


def _prod(xs) -> float:
    out = 1.0
    for x in xs:
        out *= float(x)
    return out


def _eqn_flops(eqn) -> float:
    name = eqn.primitive.name
    if name == "dot_general":
        (lc, rc), (lb, _rb) = eqn.params["dimension_numbers"]
        lhs = eqn.invars[0].aval.shape
        rhs = eqn.invars[1].aval.shape
        batch = _prod(lhs[i] for i in lb)
        contract = _prod(lhs[i] for i in lc)
        m = _prod(lhs[i] for i in range(len(lhs))
                  if i not in lb and i not in lc)
        rb, rcs = set(_rb), set(rc)
        n = _prod(rhs[i] for i in range(len(rhs))
                  if i not in rb and i not in rcs)
        return 2.0 * batch * m * n * contract
    if name == "conv_general_dilated":
        out_shape = eqn.outvars[0].aval.shape
        kernel = eqn.invars[1].aval.shape
        dn = eqn.params["dimension_numbers"]
        k_spatial = _prod(kernel[d] for d in dn.rhs_spec[2:])
        cin_per_group = float(kernel[dn.rhs_spec[1]])
        macs = _prod(out_shape) * cin_per_group * k_spatial
        # input dilation (the autodiff dgrad of a STRIDED conv) inserts
        # stride-1 zeros between input elements; only 1/prod(lhs_dilation)
        # of kernel taps hit data, the rest multiply structural zeros.
        # Without this the ViT patchify's (stride-16) backward counted
        # 256x its real MACs and inflated MFU past the physical ceiling
        # (caught by the HLO cross-check, PERF.md §8.2). The algorithmic
        # invariant this restores: dgrad MACs == wgrad MACs == fwd MACs
        # (transposes of the same linear map have identical nnz).
        ld = eqn.params.get("lhs_dilation") or ()
        d = _prod(ld)
        if d > 1:
            macs /= d
        return 2.0 * macs
    return 0.0


def _sub_jaxprs(params):
    for v in params.values():
        if isinstance(v, jex_core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jex_core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for w in v:
                if isinstance(w, jex_core.ClosedJaxpr):
                    yield w.jaxpr
                elif isinstance(w, jex_core.Jaxpr):
                    yield w


def jaxpr_flops_by_kind(jaxpr) -> dict:
    """Like :func:`jaxpr_flops` but split by primitive family:
    ``{"matmul": f, "conv": f}``. ``dot_general`` (and Pallas kernels
    with an author-declared CostEstimate — their declared FLOPs are MXU
    dot FLOPs by construction, PERF.md §5) count as matmul;
    ``conv_general_dilated`` as conv. The attribution engine
    (``obs/attrib.py``) joins these against the profiled matmul/conv
    category times to get per-category achieved-vs-roofline utilization."""
    if isinstance(jaxpr, jex_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    total = {"matmul": 0.0, "conv": 0.0}

    def add(dst, src, mult=1.0):
        dst["matmul"] += mult * src["matmul"]
        dst["conv"] += mult * src["conv"]

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        f = _eqn_flops(eqn)
        if f:
            total["conv" if name == "conv_general_dilated"
                  else "matmul"] += f
        if name == "cond":
            branches = [jaxpr_flops_by_kind(b)
                        for b in eqn.params["branches"]]
            if branches:
                add(total, max(branches,
                               key=lambda d: d["matmul"] + d["conv"]))
            continue
        mult = 1.0
        if name == "scan":
            mult = float(eqn.params.get("length", 1))
        elif name == "pallas_call":
            ce = eqn.params.get("cost_estimate")
            if ce is not None and getattr(ce, "flops", 0):
                total["matmul"] += float(ce.flops)
                continue
            gm = eqn.params.get("grid_mapping")
            grid = getattr(gm, "grid", ()) or ()
            if all(isinstance(g, int) for g in grid):
                mult = _prod(grid) if grid else 1.0
        for sub in _sub_jaxprs(eqn.params):
            add(total, jaxpr_flops_by_kind(sub), mult)
    return total


def fn_flops_by_kind(fn, *args, **kwargs) -> dict:
    """Matmul/conv FLOPs split of ``fn(*args, **kwargs)`` (abstract
    trace); same recursion rules as :func:`fn_flops`."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return jaxpr_flops_by_kind(closed)


def jaxpr_flops(jaxpr) -> float:
    """Total matmul+conv FLOPs of one evaluation of ``jaxpr``."""
    if isinstance(jaxpr, jex_core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    total = 0.0
    for eqn in jaxpr.eqns:
        total += _eqn_flops(eqn)
        name = eqn.primitive.name
        if name == "cond":
            total += max((jaxpr_flops(b) for b in eqn.params["branches"]),
                         default=0.0)
            continue
        mult = 1.0
        if name == "scan":
            mult = float(eqn.params.get("length", 1))
        elif name == "while":
            # trip count is dynamic; count the body once (lower bound)
            mult = 1.0
        elif name == "pallas_call":
            # Without special handling the kernel jaxpr is counted ONCE
            # though it runs once per grid program — flash attention's
            # seq^2 inner products were invisible and long-context MFU
            # wildly undercounted (found at seq 16k: analytic step FLOPs
            # equalled the 1k config's). Preference order:
            #  1. an author-declared CostEstimate (our flash kernels set
            #     ALGORITHMIC flops: causal-skip-aware, backward score
            #     recomputation excluded — comparable to dense autodiff);
            #  2. grid-size x kernel-body as a fallback for kernels
            #     without an estimate (counts recomputation and masked
            #     grid cells as written).
            ce = eqn.params.get("cost_estimate")
            if ce is not None and getattr(ce, "flops", 0):
                total += float(ce.flops)
                continue
            gm = eqn.params.get("grid_mapping")
            grid = getattr(gm, "grid", ()) or ()
            if all(isinstance(g, int) for g in grid):
                mult = _prod(grid) if grid else 1.0
        for sub in _sub_jaxprs(eqn.params):
            total += mult * jaxpr_flops(sub)
    return total


def conv_unit_flops(n: int, h_out: int, w_out: int, cin: int, cout: int,
                    kh: int, kw: int, groups: int = 1) -> float:
    """Closed-form 2·MAC FLOPs of ONE conv pass (fwd == dgrad == wgrad:
    transposes of the same linear map have identical nnz). The 1x1 GEMM
    spelling computes the identical contraction, so this is also its
    dot_general count — one numerator for probe/roofline TF/s."""
    return 2.0 * n * h_out * w_out * cout * (cin / max(1, groups)) * kh * kw


def fn_flops(fn, *args, **kwargs) -> float:
    """Matmul+conv FLOPs of ``fn(*args, **kwargs)`` via abstract tracing."""
    closed = jax.make_jaxpr(fn)(*args, **kwargs)
    return jaxpr_flops(closed)
