from bigdl_tpu.utils.gradcheck import check_gradients, numerical_grad
