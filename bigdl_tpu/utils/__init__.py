from bigdl_tpu.utils.gradcheck import check_gradients, numerical_grad
from bigdl_tpu.utils.table import Table, T
from bigdl_tpu.utils.classifier import Classifier
from bigdl_tpu.utils.file import save_pytree, load_pytree, latest_checkpoint
from bigdl_tpu.utils.profiling import time_modules, trace, format_times
from bigdl_tpu.utils.summary import param_bytes, param_count, summary
