"""Finite-difference gradient checking.

Parity with the reference's GradientChecker (dl/src/test/scala/.../nn/
GradientChecker.scala, used at 1e-4 perturbation / 1e-2 tolerance). Even with
JAX autodiff this stays in the framework test kit: it catches custom-VJP and
Pallas-kernel bugs that autodiff alone cannot (SURVEY.md §4 lesson (d)).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.core.pytree import flatten_params

__all__ = ["check_gradients", "numerical_grad"]


def numerical_grad(loss_fn: Callable[[jnp.ndarray], jnp.ndarray],
                   flat: jnp.ndarray, eps: float = 1e-4,
                   max_entries: int = 200) -> np.ndarray:
    """Central finite differences on a flat vector. For large vectors, checks
    a deterministic subsample of ``max_entries`` coordinates."""
    flat = np.asarray(flat, np.float64)
    n = flat.size
    idx = (np.arange(n) if n <= max_entries
           else np.linspace(0, n - 1, max_entries).astype(np.int64))
    out = np.zeros(idx.size)
    for j, i in enumerate(idx):
        d = np.zeros_like(flat)
        d[i] = eps
        lo = float(loss_fn(jnp.asarray(flat - d, jnp.float32)))
        hi = float(loss_fn(jnp.asarray(flat + d, jnp.float32)))
        out[j] = (hi - lo) / (2 * eps)
    return idx, out


def check_gradients(loss_fn: Callable, params, eps: float = 1e-3,
                    rtol: float = 2e-2, atol: float = 5e-3,
                    max_entries: int = 200) -> None:
    """Assert autodiff grads of ``loss_fn(params)`` match finite differences.

    ``loss_fn`` takes the params pytree and returns a scalar.
    """
    flat, unravel = flatten_params(params)

    def flat_loss(v):
        return loss_fn(unravel(v))

    auto = np.asarray(jax.grad(flat_loss)(flat), np.float64)
    idx, num = numerical_grad(flat_loss, flat, eps, max_entries)
    np.testing.assert_allclose(auto[idx], num, rtol=rtol, atol=atol)
