"""Sharded (orbax) checkpointing — the multi-host path.

The reference's distributed checkpoint gathers every weight slice to the
driver and Java-serializes one blob (DistriOptimizer.getModel
:472-496 + File.save). That works at Spark scale; at pod scale gathering
TB-size states to one host is the bottleneck, so the TPU-native design
writes each host's shards directly (orbax), preserving the reference's
two-artifact layout: ``model.<n>`` (params + mod_state) and ``state.<n>``
(optimizer state) under one directory.

`utils/file.py` stays the single-host default (plain msgpack-style blobs);
this module is opt-in via ``Optimizer.set_checkpoint(..., sharded=True)``
or direct calls.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax

from bigdl_tpu.utils.file import latest_checkpoint as latest_sharded  # noqa: F401
# orbax snapshots are directories, but the <prefix><n> selection logic is
# identical to the single-blob case — one helper serves both

__all__ = ["save_sharded", "restore_sharded", "latest_sharded",
           "restore_for_inference"]


def save_sharded(tree: Any, path: str, overwrite: bool = False) -> None:
    """Write a (possibly device-sharded) pytree; every process must call
    this with the same global tree (each writes only its local shards).

    Pre-existing checkpoint handling is done by process 0 only, with
    barriers on both sides, so hosts never race on the shared directory."""
    import orbax.checkpoint as ocp

    from bigdl_tpu.utils.file import is_remote

    # gs://... stays a URI (orbax handles object stores via etils.epath);
    # only local paths are absolutized
    path = path if is_remote(path) else os.path.abspath(path)

    def barrier(tag):
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(tag)

    def exists(p):
        if is_remote(p):
            from etils import epath
            return epath.Path(p).exists()
        return os.path.exists(p)

    def remove(p):
        if is_remote(p):
            from etils import epath
            epath.Path(p).rmtree()
        else:
            import shutil
            shutil.rmtree(p)

    barrier(f"ckpt-pre:{path}")
    if jax.process_index() == 0 and exists(path):
        if not overwrite:
            raise FileExistsError(path)
        remove(path)
    barrier(f"ckpt-clean:{path}")
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        ckptr.save(path, tree)


def restore_for_inference(path: str) -> tuple:
    """Inference-only restore: ``(params, mod_state)`` from a TRAINING
    checkpoint, never touching optimizer state — the serving engine
    (bigdl_tpu.serving) loads ``model.<n>`` artifacts directly, whether
    they are single-blob ``save_pytree`` files or sharded orbax
    directories. ``path`` may be:

    * a checkpoint directory — the newest ``model.<n>`` entry is used
      (``state.<n>`` is ignored by construction: no pairing needed when
      there is no optimizer to resume);
    * a single ``model.<n>`` blob file (or a whole-model ``save_module``
      file — the embedded definition is ignored, weights only);
    * one orbax snapshot directory written by :func:`save_sharded`.

    A missing or corrupt checkpoint raises a clean ``SystemExit`` with
    the path and cause — a serving launch must fail with one actionable
    line, not an np.load/orbax traceback (same contract as the CLI flag
    validation errors)."""
    from bigdl_tpu.utils.file import exists, isdir, latest_checkpoint

    if not exists(path):
        raise SystemExit(f"checkpoint {path}: does not exist")
    target = path
    if isdir(path):
        newest = latest_checkpoint(path, "model.")
        if newest is not None:
            target = newest
        # else: the directory itself may BE one orbax snapshot
    try:
        if isdir(target):
            blob = restore_sharded(target)
        else:
            from bigdl_tpu.utils.file import load_pytree
            blob = load_pytree(target)
    except SystemExit:
        raise
    except Exception as e:  # np/zip/pickle/orbax corruption all land here
        raise SystemExit(
            f"checkpoint {target}: failed to load "
            f"({type(e).__name__}: {e})")
    if not isinstance(blob, dict) or "params" not in blob:
        raise SystemExit(
            f"checkpoint {target}: not a model checkpoint (no 'params' "
            f"entry — did you point at a state.<n> optimizer blob?)")
    return blob["params"], blob.get("mod_state")


def restore_sharded(path: str, like: Optional[Any] = None) -> Any:
    """Restore a pytree; ``like`` (a pytree of arrays or ShapeDtypeStruct
    with shardings) restores directly onto those shardings — pass the
    placed training state to resume without a host gather."""
    import orbax.checkpoint as ocp

    from bigdl_tpu.utils.file import is_remote

    path = path if is_remote(path) else os.path.abspath(path)
    with ocp.Checkpointer(ocp.StandardCheckpointHandler()) as ckptr:
        if like is None:
            return ckptr.restore(path)
        target = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                           sharding=getattr(x, "sharding",
                                                            None)), like)
        return ckptr.restore(path, target)


