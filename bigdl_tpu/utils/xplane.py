"""Minimal XSpace (xplane.pb) reader — no tensorflow/tensorboard dep.

``jax.profiler.trace`` writes its device timeline as an ``XSpace``
protobuf (``plugins/profile/<run>/<host>.xplane.pb``). The only offline
consumers of that format are tensorboard plugins this container doesn't
ship, so ``scripts/backward_roofline.py`` needs a reader of its own. The
schema is tiny and stable (tsl/profiler/protobuf/xplane.proto), so this
module hand-decodes the protobuf wire format for exactly the fields the
roofline join needs: planes → lines → events, with per-plane event
metadata (op/fusion names) and durations in picoseconds.

Wire-format background: a protobuf message is a stream of
(tag, payload) pairs; ``tag = field_number << 3 | wire_type`` with
wire_type 0 = varint, 1 = fixed64, 2 = length-delimited (submessages,
strings, packed repeated), 5 = fixed32. Unknown fields are skipped, so
schema additions can't break the reader.
"""

from __future__ import annotations

import os
import re
from typing import Dict, Iterator, List, Tuple

__all__ = ["parse_xspace", "find_xplane_pb", "device_planes", "op_totals",
           "collectives", "collective_kind", "COLLECTIVE_KINDS",
           "XPlane", "XLine", "XEvent"]


def _varint(buf: bytes, i: int) -> Tuple[int, int]:
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) over one message's bytes.
    Length-delimited values come back as memoryview-sliced bytes."""
    i, n = 0, len(buf)
    while i < n:
        tag, i = _varint(buf, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            val, i = _varint(buf, i)
        elif wt == 1:
            val, i = buf[i:i + 8], i + 8
        elif wt == 2:
            ln, i = _varint(buf, i)
            val, i = buf[i:i + ln], i + ln
        elif wt == 5:
            val, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fno, wt, val


class XEvent:
    __slots__ = ("metadata_id", "offset_ps", "duration_ps",
                 "num_occurrences")

    def __init__(self):
        self.metadata_id = 0
        self.offset_ps = 0
        self.duration_ps = 0
        self.num_occurrences = 0


class XLine:
    __slots__ = ("name", "display_name", "events")

    def __init__(self):
        self.name = ""
        self.display_name = ""
        self.events: List[XEvent] = []


class XPlane:
    __slots__ = ("name", "lines", "event_names")

    def __init__(self):
        self.name = ""
        self.lines: List[XLine] = []
        # metadata id -> display_name or name (fusion/op label)
        self.event_names: Dict[int, str] = {}


def _parse_event(buf: bytes) -> XEvent:
    ev = XEvent()
    for fno, wt, val in _fields(buf):
        if fno == 1 and wt == 0:
            ev.metadata_id = val
        elif fno == 2 and wt == 0:
            ev.offset_ps = val
        elif fno == 3 and wt == 0:
            ev.duration_ps = val
        elif fno == 5 and wt == 0:
            ev.num_occurrences = val
    return ev


def _parse_line(buf: bytes) -> XLine:
    ln = XLine()
    for fno, wt, val in _fields(buf):
        if fno == 2 and wt == 2:
            ln.name = bytes(val).decode("utf-8", "replace")
        elif fno == 11 and wt == 2:
            ln.display_name = bytes(val).decode("utf-8", "replace")
        elif fno == 4 and wt == 2:
            ln.events.append(_parse_event(val))
    return ln


def _parse_event_metadata(buf: bytes) -> Tuple[int, str]:
    mid, name, display = 0, "", ""
    for fno, wt, val in _fields(buf):
        if fno == 1 and wt == 0:
            mid = val
        elif fno == 2 and wt == 2:
            name = bytes(val).decode("utf-8", "replace")
        elif fno == 4 and wt == 2:
            display = bytes(val).decode("utf-8", "replace")
    return mid, (display or name)


def _parse_plane(buf: bytes) -> XPlane:
    pl = XPlane()
    for fno, wt, val in _fields(buf):
        if fno == 2 and wt == 2:
            pl.name = bytes(val).decode("utf-8", "replace")
        elif fno == 3 and wt == 2:
            pl.lines.append(_parse_line(val))
        elif fno == 4 and wt == 2:
            # map<int64, XEventMetadata> entry: key=1, value=2
            key, meta = 0, None
            for efno, ewt, eval_ in _fields(val):
                if efno == 1 and ewt == 0:
                    key = eval_
                elif efno == 2 and ewt == 2:
                    meta = _parse_event_metadata(eval_)
            if meta is not None:
                mid, name = meta
                pl.event_names[mid or key] = name
    return pl


def parse_xspace(path: str) -> List[XPlane]:
    """Parse one ``*.xplane.pb`` file into its planes."""
    with open(path, "rb") as f:
        buf = f.read()
    planes = []
    for fno, wt, val in _fields(buf):
        if fno == 1 and wt == 2:
            planes.append(_parse_plane(val))
    return planes


def find_xplane_pb(profile_dir: str) -> "str | None":
    """Newest ``*.xplane.pb`` under a ``jax.profiler.trace`` output dir
    (the nested ``plugins/profile/<run>/`` layout), or None."""
    newest, newest_m = None, -1.0
    for root, _dirs, files in os.walk(profile_dir):
        for fn in files:
            if fn.endswith(".xplane.pb"):
                p = os.path.join(root, fn)
                m = os.path.getmtime(p)
                if m > newest_m:
                    newest, newest_m = p, m
    return newest


def device_planes(planes: List[XPlane]) -> List[XPlane]:
    """The accelerator planes (``/device:TPU:0`` etc.), host plane
    excluded; falls back to every plane carrying events when no name
    matches (so a renamed plane degrades to noise, not emptiness)."""
    dev = [p for p in planes
           if "TPU" in p.name.upper() or "GPU" in p.name.upper()]
    if dev:
        return dev
    return [p for p in planes
            if "HOST" not in p.name.upper()
            and any(ln.events for ln in p.lines)]


# HLO spellings of the cross-device collectives, most specific first
# (``reduce-scatter`` must not fall into a bare ``reduce`` bucket, and
# ``all-reduce-start``/``-done`` async halves count as all-reduce). The
# jax-level names (psum/ppermute/all_to_all) appear when the event label
# carries named-scope provenance instead of raw HLO.
COLLECTIVE_KINDS: Tuple[Tuple[str, "re.Pattern[str]"], ...] = (
    ("all_reduce", re.compile(r"all[-_]?reduce|\bpsum\b", re.I)),
    ("reduce_scatter", re.compile(r"reduce[-_]?scatter", re.I)),
    ("all_gather", re.compile(r"all[-_]?gather", re.I)),
    ("all_to_all", re.compile(r"all[-_]?to[-_]?all", re.I)),
    ("collective_permute",
     re.compile(r"collective[-_]?permute|\bppermute\b", re.I)),
    ("collective_broadcast", re.compile(r"collective[-_]?broadcast", re.I)),
)


def collective_kind(name: str) -> "str | None":
    """Collective kind of one op/fusion label, or None for non-collective
    ops. reduce-scatter is tested before all_reduce so the compound name
    never degrades into the wrong bucket."""
    if re.search(r"reduce[-_]?scatter", name, re.I):
        return "reduce_scatter"
    for kind, pat in COLLECTIVE_KINDS:
        if pat.search(name):
            return kind
    return None


def collectives(planes: List[XPlane]) -> Dict[str, Dict[str, float]]:
    """Per-collective-kind totals across the given planes:
    ``kind -> {"total_ps", "count"}`` (ROADMAP item 2's "per-step
    collective time broken out" — the raw substrate; callers divide by
    step count). Kinds with no events are absent, so an empty dict means
    a genuinely collective-free profile (single device, or a host-only
    trace)."""
    out: Dict[str, Dict[str, float]] = {}
    for name, ent in op_totals(planes).items():
        kind = collective_kind(name)
        if kind is None:
            continue
        agg = out.setdefault(kind, {"total_ps": 0.0, "count": 0})
        agg["total_ps"] += ent["total_ps"]
        agg["count"] += ent["count"]
    return out


def op_totals(planes: List[XPlane]) -> Dict[str, Dict[str, float]]:
    """Aggregate event durations by op/fusion label across the given
    planes: label -> {"total_ps", "count"}. Events whose metadata id has
    no registered name fall under "<unnamed:ID>"."""
    totals: Dict[str, Dict[str, float]] = {}
    for pl in planes:
        for ln in pl.lines:
            for ev in ln.events:
                name = pl.event_names.get(
                    ev.metadata_id, f"<unnamed:{ev.metadata_id}>")
                ent = totals.setdefault(name, {"total_ps": 0.0,
                                               "count": 0})
                ent["total_ps"] += float(ev.duration_ps)
                ent["count"] += max(1, int(ev.num_occurrences))
    return totals
