"""Lua-style Table (reference utils/Table.scala:31-137 and the ``T(...)``
constructor) — the reference's universal heterogeneous container for
optimizer state, nested activities, and hyperparameter bundles.

In the TPU framework pytrees (dicts/tuples) play that role natively, but
Table is kept for API parity: code moving over from the reference can write
``T(learningRate=0.1)`` or ``T(tensor_a, tensor_b)`` unchanged. Table is a
registered JAX pytree, so it can flow through jit/grad like a dict.
"""

from __future__ import annotations

from typing import Any, Iterator, Sequence

import jax

__all__ = ["Table", "T", "format_table"]


def format_table(headers: Sequence[str], rows: Sequence[Sequence[Any]],
                 max_col: int = 72) -> str:
    """Plain-text column-aligned table (the lint report's human output;
    also usable by any CLI that wants aligned rows without a dependency).
    Cells are str()'d and clipped at ``max_col`` chars with an ellipsis so
    one long provenance path cannot wrap the whole report."""
    def clip(s: Any) -> str:
        s = str(s)
        return s if len(s) <= max_col else s[:max_col - 1] + "…"

    srows = [[clip(c) for c in r] for r in rows]
    heads = [clip(h) for h in headers]
    widths = [max(len(heads[i]), *(len(r[i]) for r in srows))
              if srows else len(heads[i]) for i in range(len(heads))]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [fmt.format(*heads), fmt.format(*("-" * w for w in widths))]
    lines += [fmt.format(*r) for r in srows]
    return "\n".join(line.rstrip() for line in lines)


class Table:
    """Int- and string-keyed map; integer keys start at 1 (Lua convention,
    reference Table.scala array-part semantics)."""

    def __init__(self, *args: Any, **kwargs: Any):
        self._data: dict[Any, Any] = {}
        for i, v in enumerate(args):
            self._data[i + 1] = v
        self._data.update(kwargs)

    # ----------------------------------------------------------- mapping
    def __getitem__(self, k):
        return self._data[k]

    def __setitem__(self, k, v):
        self._data[k] = v

    def __contains__(self, k):
        return k in self._data

    def __len__(self):
        return len(self._data)

    def __iter__(self) -> Iterator:
        return iter(self._data)

    def get(self, k, default=None):
        return self._data.get(k, default)

    def keys(self):
        return self._data.keys()

    def values(self):
        return self._data.values()

    def items(self):
        return self._data.items()

    def update(self, other) -> "Table":
        self._data.update(dict(other.items()) if isinstance(other, Table)
                          else other)
        return self

    # ------------------------------------------------------- array part
    def insert(self, v) -> "Table":
        """Append to the integer array part (reference Table.insert)."""
        self._data[self._array_len() + 1] = v
        return self

    def remove(self) -> Any:
        """Pop the last array element."""
        n = self._array_len()
        if n == 0:
            return None
        return self._data.pop(n)

    def _array_len(self) -> int:
        n = 0
        while (n + 1) in self._data:
            n += 1
        return n

    def to_list(self) -> list:
        return [self._data[i + 1] for i in range(self._array_len())]

    def __eq__(self, other):
        return isinstance(other, Table) and self._data == other._data

    def __repr__(self):
        inner = ", ".join(f"{k!r}: {v!r}" for k, v in self._data.items())
        return f"T({{{inner}}})"


def T(*args: Any, **kwargs: Any) -> Table:
    """Constructor shorthand (reference ``T(...)``)."""
    return Table(*args, **kwargs)


def _table_flatten(t: Table):
    keys = sorted(t._data.keys(), key=lambda k: (isinstance(k, str), k))
    return [t._data[k] for k in keys], tuple(keys)


def _table_unflatten(keys, values) -> Table:
    t = Table()
    for k, v in zip(keys, values):
        t._data[k] = v
    return t


jax.tree_util.register_pytree_node(Table, _table_flatten, _table_unflatten)
