"""Criterion (loss) base class.

Functional analog of the reference's AbstractCriterion
(dl/src/main/scala/com/intel/analytics/bigdl/nn/abstractnn/AbstractCriterion.scala):
``forward(input, target) -> scalar loss``. The backward half
(``updateGradInput``) does not exist — gradients flow through ``jax.grad`` on
the composed ``loss = criterion(module.apply(...), target)``.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp

__all__ = ["Criterion"]


class Criterion:
    """Base class for losses. Subclasses implement :meth:`forward` as a pure
    function returning a scalar (mean over the batch unless
    ``size_average=False``, matching the reference's sizeAverage flag)."""

    def __init__(self, size_average: bool = True):
        self.size_average = size_average

    def forward(self, input: Any, target: Any) -> jnp.ndarray:
        raise NotImplementedError(f"{type(self).__name__}.forward")

    def __call__(self, input: Any, target: Any = None) -> jnp.ndarray:
        return self.forward(input, target)

    def _reduce(self, per_elem: jnp.ndarray) -> jnp.ndarray:
        return jnp.mean(per_elem) if self.size_average else jnp.sum(per_elem)

    def __repr__(self) -> str:
        return f"{type(self).__name__}()"
