"""Parameter-pytree utilities.

The reference compacts all parameters into one contiguous storage via
``Module.flatten`` (dl/src/main/scala/com/intel/analytics/bigdl/nn/Module.scala:42-91),
then every clone aliases that storage. In JAX the same capability — "view the
whole model as one flat vector" (used by LBFGS, gradient checking, checkpoint
size accounting) — is `ravel_pytree`, with the unravel closure replacing
storage aliasing.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree

__all__ = [
    "flatten_params",
    "tree_zeros_like",
    "tree_add",
    "tree_scale",
    "tree_size",
    "tree_global_norm",
    "tree_cast",
]


def flatten_params(params: Any) -> tuple[jnp.ndarray, Callable[[jnp.ndarray], Any]]:
    """Return ``(flat_vector, unflatten_fn)`` — the functional analog of
    ``Module.getParameters`` (AbstractModule.scala:199-202)."""
    return ravel_pytree(params)


def tree_zeros_like(tree: Any) -> Any:
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def tree_add(a: Any, b: Any) -> Any:
    return jax.tree_util.tree_map(jnp.add, a, b)


def tree_scale(tree: Any, s) -> Any:
    return jax.tree_util.tree_map(lambda x: x * s, tree)


def tree_size(tree: Any) -> int:
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(tree))


def tree_global_norm(tree: Any) -> jnp.ndarray:
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros(())
    return jnp.sqrt(sum(jnp.sum(jnp.square(x.astype(jnp.float32))) for x in leaves))


def tree_cast(tree: Any, dtype) -> Any:
    return jax.tree_util.tree_map(lambda x: x.astype(dtype), tree)
