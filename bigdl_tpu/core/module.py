"""Core module system for bigdl-tpu.

A functional, JAX-native re-design of the reference's mutable module tree
(reference: dl/src/main/scala/com/intel/analytics/bigdl/nn/abstractnn/AbstractModule.scala:40-291).

Design
------
The reference couples three things inside one mutable object: the layer's
*description* (hyperparameters), its *parameters* (weight/gradWeight storage),
and its *buffers* (cached output/gradInput, BN running stats). Under XLA that
coupling is hostile: jit-compiled functions must be pure, and parameters must
be explicit pytrees so they can be sharded with `jax.sharding` and donated
between steps.

So here a :class:`Module` is a cheap, immutable *description*. Parameters and
mutable state live outside it:

* ``params = module.init(rng)`` — a pytree (nested dicts) of ``jnp`` arrays.
* ``state = module.init_state()`` — a pytree for non-gradient buffers
  (e.g. BatchNormalization running mean/var). ``()`` when stateless.
* ``y, new_state = module.apply(params, state, x, training=..., rng=...)`` —
  the pure forward function. Under ``jax.grad`` this single function replaces
  the reference's ``updateOutput`` / ``updateGradInput`` /
  ``accGradParameters`` triple (AbstractModule.scala:161-183): XLA autodiff
  derives both gradient paths from ``apply``.

There is no ``backward`` anywhere: gradients come from ``jax.value_and_grad``
over a loss composed with ``apply``. There is no ``Engine`` thread pool
(reference utils/Engine.scala): intra-op parallelism is XLA's job.

Containers (:class:`Sequential` & friends in ``bigdl_tpu.nn``) store child
params under string keys ``"0", "1", ...`` so checkpoints are stable and
human-readable.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "Module",
    "SimpleModule",
    "ElementwiseModule",
    "Container",
    "Sequential",
    "Identity",
    "Lambda",
    "EMPTY_STATE",
]

# Canonical "no state" sentinel. An empty tuple is a valid (leaf-less) pytree,
# so it threads through jit/grad transparently.
EMPTY_STATE = ()

Params = Any
State = Any
PRNGKey = jax.Array


def _child_rng(rng: Optional[PRNGKey], index: int) -> Optional[PRNGKey]:
    """Deterministic per-child rng stream (None propagates)."""
    if rng is None:
        return None
    return jax.random.fold_in(rng, index)


class Module:
    """Base class for all layers and containers.

    Subclasses override :meth:`init`, optionally :meth:`init_state`, and
    :meth:`apply`. ``apply`` must be pure (traceable under ``jax.jit``).
    """

    def __init__(self, name: Optional[str] = None):
        self.name = name if name is not None else type(self).__name__

    # ------------------------------------------------------------------ init
    def init(self, rng: PRNGKey) -> Params:
        """Create this module's parameter pytree. Paramless modules return {}."""
        del rng
        return {}

    def init_state(self) -> State:
        """Create the non-gradient state pytree (running stats etc.)."""
        return EMPTY_STATE

    # ----------------------------------------------------------------- apply
    def apply(
        self,
        params: Params,
        state: State,
        x: Any,
        *,
        training: bool = False,
        rng: Optional[PRNGKey] = None,
    ) -> tuple[Any, State]:
        """Pure forward pass. Returns ``(output, new_state)``."""
        raise NotImplementedError(f"{type(self).__name__}.apply")

    # ----------------------------------------------------------- convenience
    def forward(
        self,
        params: Params,
        x: Any,
        state: Optional[State] = None,
        *,
        training: bool = False,
        rng: Optional[PRNGKey] = None,
    ) -> Any:
        """Forward that discards the state update (inference convenience).
        ``state=None`` uses a freshly-initialized state."""
        if state is None:
            state = self.init_state()
        y, _ = self.apply(params, state, x, training=training, rng=rng)
        return y

    def __call__(self, params: Params, x: Any, **kw: Any) -> Any:
        return self.forward(params, x, **kw)

    # ------------------------------------------------------------ reflection
    def children(self) -> Sequence["Module"]:
        return ()

    def modules(self) -> list["Module"]:
        """This module and all descendants, depth-first (reference
        Container.scala:41-90 recursion)."""
        out: list[Module] = [self]
        for c in self.children():
            out.extend(c.modules())
        return out

    def named_modules(self, prefix: str = "") -> list[tuple[str, "Module"]]:
        """(path, module) pairs; paths mirror the params-pytree keys."""
        me = prefix if prefix else self.name
        out: list[tuple[str, Module]] = [(me, self)]
        for i, c in enumerate(self.children()):
            out.extend(c.named_modules(f"{me}.{i}:{c.name}"))
        return out

    def param_count(self, params: Params) -> int:
        return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))

    def __repr__(self) -> str:
        return f"{type(self).__name__}(name={self.name!r})"


class SimpleModule(Module):
    """A module with no mutable state. Subclasses implement ``_forward``."""

    def _forward(
        self,
        params: Params,
        x: Any,
        *,
        training: bool,
        rng: Optional[PRNGKey],
    ) -> Any:
        raise NotImplementedError

    def apply(self, params, state, x, *, training=False, rng=None):
        return self._forward(params, x, training=training, rng=rng), state


class ElementwiseModule(SimpleModule):
    """Paramless elementwise op defined by a single jnp function."""

    def _fn(self, x):
        raise NotImplementedError

    def _forward(self, params, x, *, training, rng):
        del params, training, rng
        return self._fn(x)


class Identity(ElementwiseModule):
    """Pass-through (reference nn/Identity.scala)."""

    def _fn(self, x):
        return x


class Lambda(SimpleModule):
    """Wrap an arbitrary pure function as a paramless module."""

    def __init__(self, fn: Callable[[Any], Any], name: Optional[str] = None):
        super().__init__(name or getattr(fn, "__name__", "Lambda"))
        self.fn = fn

    def _forward(self, params, x, *, training, rng):
        del params, training, rng
        return self.fn(x)


class Container(Module):
    """Base container holding an ordered list of children (reference
    nn/Container.scala:28-112). Child params/state are stored in dicts keyed
    by the child's index as a string, giving stable checkpoint layouts."""

    def __init__(self, *modules: Module, name: Optional[str] = None):
        super().__init__(name)
        self._modules: list[Module] = list(modules)

    def add(self, module: Module) -> "Container":
        """Append a child (mirrors Container.add, nn/Container.scala:36).

        Mutation is allowed here because it edits the *description* before
        ``init``/``apply`` — never traced state."""
        self._modules.append(module)
        return self

    def children(self) -> Sequence[Module]:
        return tuple(self._modules)

    def __len__(self) -> int:
        return len(self._modules)

    def __getitem__(self, i: int) -> Module:
        return self._modules[i]

    def init(self, rng: PRNGKey) -> Params:
        return {
            str(i): m.init(_child_rng(rng, i))
            for i, m in enumerate(self._modules)
        }

    def init_state(self) -> State:
        return {str(i): m.init_state() for i, m in enumerate(self._modules)}

    def __repr__(self) -> str:
        inner = ", ".join(repr(m) for m in self._modules)
        return f"{type(self).__name__}({inner})"


class Sequential(Container):
    """Feed-forward chain (reference nn/Sequential.scala:26)."""

    def apply(self, params, state, x, *, training=False, rng=None):
        new_state: dict[str, State] = {}
        for i, m in enumerate(self._modules):
            k = str(i)
            # named_scope tags each child's ops in profiler traces/HLO —
            # the jit-era replacement for per-module forwardTime counters
            with jax.named_scope(f"{i}:{m.name}"):
                x, s = m.apply(
                    params[k], state[k], x,
                    training=training, rng=_child_rng(rng, i)
                )
            new_state[k] = s
        return x, new_state


# --------------------------------------------------------------------------
# Shared init helpers (used by layers' default resets; formulas match the
# reference's InitializationMethod semantics, nn/InitializationMethod.scala).
# --------------------------------------------------------------------------

def uniform_fan_in(rng: PRNGKey, shape: Sequence[int], fan_in: int, dtype=jnp.float32):
    """Torch-style default init: U(-1/sqrt(fanIn), 1/sqrt(fanIn))."""
    stdv = 1.0 / math.sqrt(max(1, fan_in))
    return jax.random.uniform(rng, tuple(shape), dtype, minval=-stdv, maxval=stdv)


def xavier_uniform(rng: PRNGKey, shape: Sequence[int], fan_in: int, fan_out: int, dtype=jnp.float32):
    """Xavier/Glorot uniform: U(+-sqrt(6/(fanIn+fanOut))) (reference
    InitializationMethod.Xavier as used by SpatialConvolution.reset,
    nn/SpatialConvolution.scala:88-103)."""
    a = math.sqrt(6.0 / max(1, fan_in + fan_out))
    return jax.random.uniform(rng, tuple(shape), dtype, minval=-a, maxval=a)
