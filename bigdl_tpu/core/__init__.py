from bigdl_tpu.core.module import (
    Module,
    SimpleModule,
    ElementwiseModule,
    Container,
    Sequential,
    Identity,
    Lambda,
    EMPTY_STATE,
    uniform_fan_in,
    xavier_uniform,
)
from bigdl_tpu.core.criterion import Criterion
from bigdl_tpu.core.pytree import (
    flatten_params,
    tree_zeros_like,
    tree_add,
    tree_scale,
    tree_size,
    tree_global_norm,
    tree_cast,
)
