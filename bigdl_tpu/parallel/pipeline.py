"""Pipeline parallelism: GPipe microbatch schedule over a ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2.7: "Pipeline
parallel — NO"); this is a new TPU-first capability. Unlike GPU frameworks
that run one process per stage with send/recv, the TPU-native shape is a
single SPMD program: the layer stack's parameters are stacked on a leading
``[L, ...]`` dim and sharded over the ``pipe`` axis, and microbatch
activations rotate between neighboring devices with ``lax.ppermute`` (one
ICI hop per tick) inside a ``lax.scan`` — compiler-visible, fully jittable,
and differentiable (the VJP of ppermute is the reverse ppermute, so the
backward pipeline falls out of ``jax.grad`` for free).

Schedule: M microbatches through P stages takes M + P - 1 ticks; bubble
fraction (P-1)/(M+P-1) — raise ``microbatches`` to amortize (GPipe).

Blocks must be homogeneous (same params structure, input shape == output
shape) and stateless — the transformer-block case. Embedding/head layers
run replicated outside the pipelined middle.

Composes with data parallelism: pass ``data_axis`` to also split each
microbatch over a ``data`` mesh axis.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.core.module import Module

__all__ = ["PipelineStack", "pipeline_forward", "place_pipeline_params",
           "make_pipeline_train_step"]


class PipelineStack(Module):
    """L homogeneous blocks with params stacked on a leading ``[L, ...]``
    dim — the layout pipeline (and remat-scan) execution wants.

    Single-device ``apply`` runs the stack as one ``lax.scan`` over layers
    (XLA compiles ONE block body regardless of L — faster compiles than an
    unrolled Sequential). :func:`pipeline_forward` runs the same params
    pipelined over a mesh axis.
    """

    def __init__(self, block: Module, num_blocks: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self._block_state = block.init_state()
        if jax.tree_util.tree_leaves(self._block_state):
            raise ValueError("PipelineStack blocks must be stateless "
                             f"({type(block).__name__} has state)")
        self.block = block
        self.num_blocks = num_blocks

    def init(self, rng):
        inits = [self.block.init(jax.random.fold_in(rng, i))
                 for i in range(self.num_blocks)]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *inits)

    def apply(self, params, state, x, *, training=False, rng=None):
        def body(carry, pb):
            i, h = carry
            r = None if rng is None else jax.random.fold_in(rng, i)
            h2, _ = self.block.apply(pb, self._block_state, h,
                                     training=training, rng=r)
            return (i + 1, h2), None

        (_, y), _ = jax.lax.scan(body, (0, x), params)
        return y, state


def place_pipeline_params(mesh: Mesh, params, axis: str = "pipe"):
    """Shard stacked ``[L, ...]`` params over the pipe axis (stage p owns
    blocks [p*L/P, (p+1)*L/P))."""
    shard = NamedSharding(mesh, P(axis))
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, shard), params)


def pipeline_forward(stack: PipelineStack, mesh: Mesh, params, x,
                     microbatches: int, axis: str = "pipe",
                     data_axis: Optional[str] = None,
                     training: bool = False, rng=None):
    """Pipelined forward of ``stack`` over the mesh: for rng-independent
    blocks this returns the same value as ``stack.apply`` (up to fp
    reassociation). With dropout the masks necessarily differ (each
    microbatch draws its own, folded by tick and layer) but stay
    decorrelated across layers. ``x`` is the full (batch, ...) input; it is
    split into ``microbatches`` equal microbatches along dim 0.
    """
    n_stage = mesh.shape[axis]
    if stack.num_blocks % n_stage:
        raise ValueError(f"{stack.num_blocks} blocks not divisible by "
                         f"{n_stage} pipeline stages")
    if x.shape[0] % microbatches:
        raise ValueError(f"batch {x.shape[0]} not divisible by "
                         f"{microbatches} microbatches")
    m = microbatches
    x_mb = x.reshape((m, x.shape[0] // m) + x.shape[1:])

    block = stack.block

    def local_fn(p_local, xs):
        # p_local: [L/P, ...] this stage's blocks; xs: [M, mb_local, ...]
        p_sz = jax.lax.psum(1, axis)
        idx = jax.lax.axis_index(axis)
        perm = [(i, (i + 1) % p_sz) for i in range(p_sz)]

        def stage(h, t):
            def body(carry, args):
                i, h = carry
                pb = args
                r = (None if rng is None
                     else jax.random.fold_in(jax.random.fold_in(rng, t), i))
                h2, _ = block.apply(pb, stack._block_state, h,
                                    training=training, rng=r)
                return (i + 1, h2), None

            (_, h), _ = jax.lax.scan(body, (idx * p_local_len, h), p_local)
            return h

        p_local_len = jax.tree_util.tree_leaves(p_local)[0].shape[0]
        zeros = jnp.zeros_like(xs[0])
        outputs = jnp.zeros_like(xs)

        def tick(carry, t):
            state_in, outputs = carry
            inj = jnp.take(xs, jnp.clip(t, 0, m - 1), axis=0)
            h_in = jnp.where(idx == 0, inj, state_in)
            h_out = stage(h_in, t)
            out_t = t - (p_sz - 1)
            start = (jnp.clip(out_t, 0, m - 1),) + (0,) * (xs.ndim - 1)
            upd = jax.lax.dynamic_update_slice(outputs, h_out[None], start)
            outputs = jnp.where((out_t >= 0) & (idx == p_sz - 1), upd,
                                outputs)
            sent = jax.lax.ppermute(h_out, axis, perm)
            return (sent, outputs), None

        (_, outputs), _ = jax.lax.scan(tick, (zeros, outputs),
                                       jnp.arange(m + p_sz - 1))
        # only the last stage holds real outputs; replicate over the pipe
        # axis (zeros elsewhere make psum a broadcast, not a sum)
        outputs = jnp.where(idx == p_sz - 1, outputs, 0.0)
        return jax.lax.psum(outputs, axis)

    p_spec = jax.tree_util.tree_map(lambda _: P(axis), params)
    x_spec = P(None, data_axis) if data_axis else P()
    y = jax.shard_map(local_fn, mesh=mesh,
                      in_specs=(p_spec, x_spec),
                      out_specs=x_spec, check_vma=False)(params, x_mb)
    return y.reshape(x.shape[0:1] + y.shape[2:])


def make_pipeline_train_step(stack: PipelineStack, mesh: Mesh, criterion,
                             optim_method, microbatches: int,
                             axis: str = "pipe",
                             data_axis: Optional[str] = None):
    """Jitted full train step (loss, grads, update) with the pipelined
    forward/backward. Params and optimizer state stay sharded over the pipe
    axis (stage-local optimizer — the pipeline analog of the reference's
    per-partition optimizer shards)."""
    p_shard = NamedSharding(mesh, P(axis))
    # x/y arrive as flat (batch, ...); the microbatch split happens inside
    # the jit, so batch-dim sharding over data is enough here
    x_shard = NamedSharding(mesh, P(data_axis) if data_axis else P())

    def train_step(params, opt_state, x, y, rng):
        def loss_fn(p):
            out = pipeline_forward(stack, mesh, p, x, microbatches,
                                   axis=axis, data_axis=data_axis,
                                   training=True, rng=rng)
            return criterion(out, y)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_params, new_opt = optim_method.update(grads, opt_state, params)
        return new_params, new_opt, loss

    def compile_for(opt_state, params):
        from bigdl_tpu.parallel.data_parallel import opt_sharding_like_params
        p_specs = jax.tree_util.tree_map(lambda _: p_shard, params)
        o_specs = opt_sharding_like_params(mesh, opt_state, params, p_specs)
        repl = NamedSharding(mesh, P())
        return jax.jit(
            train_step,
            in_shardings=(p_specs, o_specs, x_shard, x_shard, repl),
            out_shardings=(p_specs, o_specs, repl),
            donate_argnums=(0, 1))

    return compile_for
