"""Compressed, bucketed, overlap-friendly gradient communication
(ISSUE 10 tentpole — ROADMAP item 2's "shrink the all-reduce" half).

The reference BigDL owed its scaling to a ``CompressedTensor`` FP16
codec over a partitioned all-reduce (parameters/FP16CompressedTensor
.scala + AllReduceParameter.scala): gradients cross the wire truncated
to 16 bits, in fixed-size slices each node reduces independently. This
module is the JAX/TPU analogue, built from three independent pieces the
strategies compose through :meth:`DataParallel.reduce_grads`:

* **Deterministic dense bucketing** — the grad pytree flattens into
  size-bounded 1-D buckets whose layout is a pure function of the param
  tree structure (leaf order, shapes, dtypes) and the byte bound:
  ``build_bucket_plan`` is host-side, cached per (treedef, shapes,
  bound), and two processes planning the same model always agree — the
  property a multi-host reduce needs, and the reason the reference
  sliced its parameter space identically on every node. Dense buckets
  also amortize per-collective latency over many small leaves
  ("Densifying Assumed-sparse Tensors", PAPERS.md: accumulate dense,
  not per-tensor).
* **Wire compression** — each bucket is cast to bf16/fp16 before the
  cross-device reduction and back to f32 after, halving wire bytes.
  ``fp16`` clamps to the finite half range first (the codec ancestor
  truncated; an Inf would poison the psum). The ``+ec`` variants add
  the rounding residual back after decompression (error compensation):
  the value the optimizer consumes is the exact f32 gradient — only
  the wire carries 16 bits — so optimizer math stays f32 by
  construction.
* **The reduction itself** — two paths:

  - under jit-SPMD (the :class:`DataParallel` compile path, params
    replicated / batch sharded) the partitioner inserts the grad
    all-reduce; :func:`apply_grad_comm` steers it by annotating the
    COMPRESSED bucket as the replication point
    (``with_sharding_constraint``) so the collective lands on the 16-bit
    value. Buckets carry no data dependencies on each other, so XLA's
    latency-hiding scheduler is free to overlap each bucket's reduce
    with backward compute that hasn't produced later buckets yet.
    Whether a given XLA build honors the dtype steering is exactly what
    ``scripts/tpu_capture_r14.sh`` measures (PERF.md §17 result slots:
    ``collective_s`` compressed vs plain, same attribution columns).
  - an explicit shard_map path (:func:`compressed_psum`) where a
    shard_map API is importable (``jax.shard_map`` on current jax, the
    ``jax.experimental.shard_map`` spelling on this container's
    0.4.37): per-bucket ``lax.psum`` over the mesh axis on the
    compressed value — the manual-collective building block for
    strategies that hold per-device partial grads (and the autotuner's
    measurement harness).

Bucket size is autotuned per (param-bytes, n_devices, wire-dtype) under
the ``grad_comm`` namespace of the persistent tuning cache
(:func:`bigdl_tpu.tuning.grad_bucket_bytes`); ``off`` mode and
single-device meshes bypass the transform entirely — bit-identical to
the pre-grad-comm step (the ISSUE 10 acceptance bar).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

__all__ = ["COMPRESS_MODES", "DEFAULT_BUCKET_BYTES", "GradCommConfig",
           "parse_compress_spec", "make_config", "BucketPlan",
           "build_bucket_plan", "plan_wire_bytes", "apply_grad_comm",
           "compress_bucket", "decompress_bucket", "shard_map_available",
           "compressed_psum"]

# the flag surface: plain 16-bit truncation or truncation + local
# error-compensation residual (see compress/decompress below)
COMPRESS_MODES = ("off", "bf16", "fp16", "bf16+ec", "fp16+ec")

# default dense-bucket byte bound before the autotuner has a decision:
# 4 MiB rides well above per-collective launch latency while keeping
# enough buckets in flight to overlap with the backward pass (the
# bucket-size sweep candidates live in tuning.autotune.GRAD_BUCKET_BYTES)
DEFAULT_BUCKET_BYTES = 4 * 2 ** 20

_F16_MAX = 65504.0  # largest finite float16


@dataclass(frozen=True)
class GradCommConfig:
    """One run's gradient-communication configuration (the parsed
    ``--gradCompress``/``--gradBuckets`` pair). ``bucket_bytes`` None
    means "auto": the tuned decision when the autotuner is on, else
    :data:`DEFAULT_BUCKET_BYTES`."""

    compress: str = "off"
    bucket_bytes: Optional[int] = None

    @property
    def active(self) -> bool:
        return self.compress != "off"

    @property
    def wire_dtype(self) -> Optional[str]:
        if not self.active:
            return None
        return "bfloat16" if self.compress.startswith("bf16") else "float16"

    @property
    def error_comp(self) -> bool:
        return self.compress.endswith("+ec")


def parse_compress_spec(spec: Optional[str]) -> str:
    """Validate one ``--gradCompress`` spelling -> canonical mode string
    (ValueError on junk; the CLI wraps it in SystemExit)."""
    mode = (spec or "off").strip().lower()
    if mode not in COMPRESS_MODES:
        raise ValueError(
            f"gradCompress must be one of {list(COMPRESS_MODES)}, "
            f"got {spec!r}")
    return mode


def make_config(compress: Optional[str] = None,
                buckets=None) -> Optional[GradCommConfig]:
    """``(--gradCompress, --gradBuckets)`` -> config (None when the whole
    surface is off). ``buckets`` is 'auto'/None or an integer MiB bound
    (ValueError on junk)."""
    mode = parse_compress_spec(compress)
    bucket_bytes = None
    if buckets is not None and str(buckets).strip().lower() != "auto":
        try:
            mib = int(str(buckets).strip())
        except ValueError:
            raise ValueError(
                f"gradBuckets must be 'auto' or an integer MiB bound, "
                f"got {buckets!r}")
        if mib < 1:
            raise ValueError(f"gradBuckets must be >= 1 MiB, got {mib}")
        bucket_bytes = mib * 2 ** 20
    if mode == "off" and bucket_bytes is None:
        return None
    return GradCommConfig(compress=mode, bucket_bytes=bucket_bytes)


# ------------------------------------------------------------ bucket plan
@dataclass(frozen=True)
class _BucketSpec:
    """One dense bucket: which flat-tree leaves it packs, in order."""
    leaf_ids: Tuple[int, ...]
    shapes: Tuple[tuple, ...]
    sizes: Tuple[int, ...]       # element counts, leaf order
    nbytes: int                  # f32 bytes of the packed bucket


@dataclass(frozen=True)
class BucketPlan:
    """Deterministic bucket layout for one grad tree. ``signature`` is a
    content hash of (leaf order, shapes, dtypes, byte bound) — two plans
    agree iff their signatures agree, the determinism contract the
    layout test asserts."""
    buckets: Tuple[_BucketSpec, ...]
    passthrough: Tuple[int, ...]  # non-float leaves, left untouched
    n_leaves: int
    bucket_bytes: int
    total_bytes: int              # f32 bytes across all bucketed leaves
    signature: str


_PLAN_CACHE: Dict[tuple, BucketPlan] = {}


def build_bucket_plan(tree, bucket_bytes: int) -> BucketPlan:
    """Flatten ``tree``'s structure into size-bounded dense buckets.

    Layout rules (all deterministic, keyed only by tree structure):
    leaves pack in ``tree_util`` flatten order; a bucket closes when the
    next leaf would push it past ``bucket_bytes`` (a single over-bound
    leaf gets its own bucket — never split, matching the reference's
    per-slice reduce granularity); non-inexact leaves (int counters)
    bypass bucketing entirely. Cached per (treedef, shapes/dtypes,
    bound) — planning is host-side trace-time work."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    shapes = tuple(tuple(getattr(l, "shape", ())) for l in leaves)
    dtypes = tuple(str(np.dtype(getattr(l, "dtype", np.float32)))
                   for l in leaves)
    key = (treedef, shapes, dtypes, int(bucket_bytes))
    cached = _PLAN_CACHE.get(key)
    if cached is not None:
        return cached

    buckets: List[_BucketSpec] = []
    passthrough: List[int] = []
    cur_ids: List[int] = []
    cur_shapes: List[tuple] = []
    cur_sizes: List[int] = []
    cur_bytes = 0

    def close():
        nonlocal cur_ids, cur_shapes, cur_sizes, cur_bytes
        if cur_ids:
            buckets.append(_BucketSpec(tuple(cur_ids), tuple(cur_shapes),
                                       tuple(cur_sizes), cur_bytes))
        cur_ids, cur_shapes, cur_sizes, cur_bytes = [], [], [], 0

    total = 0
    for i, (shape, dtname) in enumerate(zip(shapes, dtypes)):
        if not np.issubdtype(np.dtype(dtname), np.inexact):
            passthrough.append(i)
            continue
        size = int(np.prod(shape)) if shape else 1
        nbytes = size * 4  # buckets pack in f32
        total += nbytes
        if cur_bytes and cur_bytes + nbytes > bucket_bytes:
            close()
        cur_ids.append(i)
        cur_shapes.append(shape)
        cur_sizes.append(size)
        cur_bytes += nbytes
        if cur_bytes >= bucket_bytes:
            close()
    close()

    sig = hashlib.sha256(repr(
        (shapes, dtypes, int(bucket_bytes))).encode()).hexdigest()[:16]
    plan = BucketPlan(buckets=tuple(buckets),
                      passthrough=tuple(passthrough),
                      n_leaves=len(leaves), bucket_bytes=int(bucket_bytes),
                      total_bytes=total, signature=sig)
    _PLAN_CACHE[key] = plan
    return plan


def plan_wire_bytes(plan: BucketPlan, config: GradCommConfig) -> int:
    """Per-step, per-direction wire bytes the plan's buckets put on the
    interconnect (the PERF.md §17 accounting column): f32 bytes when
    compression is off, half that for a 16-bit wire dtype."""
    if not config.active:
        return plan.total_bytes
    return plan.total_bytes // 2


# ------------------------------------------------------- compress / wire
def compress_bucket(buf, mode: str):
    """f32 bucket -> wire representation. bf16 is a straight cast
    (hardware-native, the reference codec's modern spelling); fp16
    clamps to the finite half range first — the Scala codec truncated
    mantissas and could never produce Inf, and one Inf would poison the
    whole psum."""
    import jax.numpy as jnp

    if mode.startswith("fp16"):
        return jnp.clip(buf, -_F16_MAX, _F16_MAX).astype(jnp.float16)
    return buf.astype(jnp.bfloat16)


def decompress_bucket(cbuf):
    import jax.numpy as jnp

    return cbuf.astype(jnp.float32)


def shard_map_available() -> bool:
    """True when some shard_map spelling is importable — the explicit
    per-bucket psum path (this container's jax 0.4.37 only ships the
    experimental spelling; current jax promotes it to ``jax.shard_map``)."""
    return _get_shard_map() is not None


def _get_shard_map():
    try:
        import jax
        if hasattr(jax, "shard_map"):
            return jax.shard_map
        from jax.experimental.shard_map import shard_map
        return shard_map
    except Exception:
        return None


def compressed_psum(stacked, mesh, axis: str, mode: str):
    """Explicit compressed all-reduce of per-device partial buckets:
    ``stacked`` is (n_devices, bucket_len) with row i holding device
    i's partial f32 bucket; returns the (bucket_len,) f32 sum, reduced
    over the wire in the 16-bit dtype via an explicit per-bucket
    ``lax.psum`` inside shard_map. The building block for manual
    strategies holding unreduced grads, and the autotuner's measurement
    harness; raises RuntimeError where no shard_map API exists (callers
    gate on :func:`shard_map_available`, the sp/pp refusal pattern)."""
    import jax
    from jax.sharding import PartitionSpec as P

    shard_map = _get_shard_map()
    if shard_map is None:
        raise RuntimeError(
            "compressed_psum needs a shard_map API; this jax "
            f"({jax.__version__}) ships neither jax.shard_map nor the "
            "experimental spelling")

    def local_reduce(block):
        # block: (1, L) — this device's partial bucket. Compress BEFORE
        # the wire, psum the 16-bit value, decompress after.
        c = compress_bucket(block[0], mode)
        s = jax.lax.psum(c, axis)
        return decompress_bucket(s)

    return shard_map(local_reduce, mesh=mesh, in_specs=P(axis, None),
                     out_specs=P(), check_rep=False)(stacked)


# ------------------------------------------------------- the trace path
def _resolve_bucket_bytes(config: GradCommConfig, param_bytes: int,
                          n_devices: int) -> Tuple[int, str]:
    """Effective bucket byte bound + its provenance: an explicit
    --gradBuckets N wins; else the tuned ``grad_comm`` decision when the
    autotuner is on; else the shipped default."""
    if config.bucket_bytes is not None:
        return int(config.bucket_bytes), "explicit"
    from bigdl_tpu import tuning
    tuned = tuning.grad_bucket_bytes(param_bytes, n_devices,
                                     config.wire_dtype or "bfloat16")
    if tuned is not None:
        return int(tuned), "autotune"
    return DEFAULT_BUCKET_BYTES, "default"


def apply_grad_comm(grads, config: GradCommConfig, mesh=None):
    """The reduce_grads transform under jit-SPMD: bucket, compress,
    mark the compressed bucket as the replication point, decompress,
    unbucket (+ error-compensation residual). Returns ``(new_grads,
    info)`` where ``info`` is the host-side annotation dict stamped
    into perf JSON lines (n_buckets, bucket bytes + provenance, wire
    bytes vs f32 bytes, plan signature).

    Inactive config or a 1-device mesh returns ``(grads, None)``
    untouched — the traced step is then BIT-identical to the
    pre-grad-comm step (the ``--gradCompress off`` acceptance bar).

    Numerics: ``bf16``/``fp16`` feed the optimizer the decompressed
    (rounded) gradient; ``+ec`` adds the local rounding residual
    ``g - decompress(compress(g))`` back afterwards, reconstructing the
    exact f32 gradient (bf16 round-trip keeps each element within
    2^-8 relative, so the Sterbenz condition makes the residual
    subtraction exact) — optimizer math stays f32 while only the
    compressed term is annotated for the wire."""
    import jax
    import jax.numpy as jnp

    n_dev = int(getattr(mesh, "size", 0) or 0) if mesh is not None else 0
    if config is None or not config.active or n_dev <= 1:
        return grads, None

    from jax.sharding import NamedSharding, PartitionSpec as P
    repl = NamedSharding(mesh, P())

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    param_bytes = sum(
        int(jnp.size(l)) * 4 for i, l in enumerate(leaves))
    bucket_bytes, bucket_src = _resolve_bucket_bytes(config, param_bytes,
                                                     n_dev)
    plan = build_bucket_plan(grads, bucket_bytes)

    new_leaves = list(leaves)
    for spec in plan.buckets:
        buf = jnp.concatenate(
            [jnp.ravel(leaves[i]).astype(jnp.float32)
             for i in spec.leaf_ids])
        cbuf = compress_bucket(buf, config.compress)
        # the steering annotation: tell the partitioner THIS (16-bit)
        # value is where replication happens, so the inserted
        # all-reduce rides the compressed dtype. Buckets depend only on
        # their own leaves — no cross-bucket edges — so the scheduler
        # may overlap each reduce with still-running backward compute.
        cbuf = jax.lax.with_sharding_constraint(cbuf, repl)
        dbuf = decompress_bucket(cbuf)
        if config.error_comp:
            # local error compensation: the optimizer sees the exact
            # f32 gradient; only the compressed term crossed the wire
            dbuf = dbuf + (buf - dbuf)
        offset = 0
        for leaf_id, shape, size in zip(spec.leaf_ids, spec.shapes,
                                        spec.sizes):
            piece = jax.lax.dynamic_slice_in_dim(dbuf, offset, size)
            new_leaves[leaf_id] = piece.reshape(shape).astype(
                leaves[leaf_id].dtype)
            offset += size

    info = {
        "compress": config.compress,
        "n_buckets": len(plan.buckets),
        "bucket_bytes": plan.bucket_bytes,
        "bucket_source": bucket_src,
        "wire_bytes": plan_wire_bytes(plan, config),
        "wire_bytes_f32": plan.total_bytes,
        "plan_signature": plan.signature,
        "n_devices": n_dev,
    }
    return jax.tree_util.tree_unflatten(treedef, new_leaves), info
