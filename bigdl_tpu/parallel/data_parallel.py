"""Synchronous data-parallel training over a device mesh.

This module is the TPU-native replacement for the whole of the reference's
distributed stack (SURVEY.md §2.6): ``AllReduceParameter`` (hand-rolled
scatter-reduce + all-gather over Spark's BlockManager,
parameters/AllReduceParameter.scala:54-230), FP16 gradient compression
(parameters/FP16CompressedTensor.scala), and the two-Spark-jobs-per-iteration
DistriOptimizer structure (optim/DistriOptimizer.scala:109-315).

How each reference mechanism maps:

* gradient scatter-reduce + weight all-gather  -> XLA's SPMD partitioner
  inserts reduce-scatter/all-gather collectives over ICI when the train step
  is jit-compiled with batch sharded on the ``data`` axis, params replicated,
  and optimizer state *sharded* (ZeRO-1 — exactly the reference's
  "optimizer runs on a 1/N weight shard" structure, DistriOptimizer.scala
  :225-236, but compiler-scheduled instead of blocking block exchange).
* FP16 truncated compression -> :mod:`bigdl_tpu.parallel.grad_comm`:
  gradients are bucketed into size-bounded dense buffers and cast to
  bf16/fp16 for the cross-device reduce (``--gradCompress``), with an
  error-compensation option keeping optimizer math exactly f32 — the
  codec's hardware-native spelling, applied in ``reduce_grads`` below.
* ZippedPartitionsWithLocalityRDD (host-locality of data)  ->
  per-host input pipelines + ``jax.make_array_from_process_local_data``.
* straggler dropping -> intentionally absent: SPMD collectives are bulk
  synchronous by construction (SURVEY.md §7 "hard parts").
"""

from __future__ import annotations

import logging
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger("bigdl_tpu")

__all__ = ["DataParallel", "FullyShardedDataParallel"]


def _zero1_spec(leaf, mesh: Mesh, axis: str) -> P:
    """ZeRO-1 sharding for an optimizer-state leaf: shard the largest
    dimension divisible by the data-axis size, else replicate."""
    n = mesh.shape[axis]
    if leaf.ndim == 0:
        return P()
    order = sorted(range(leaf.ndim), key=lambda i: -leaf.shape[i])
    for i in order:
        if leaf.shape[i] % n == 0 and leaf.shape[i] >= n:
            spec = [None] * leaf.ndim
            spec[i] = axis
            return P(*spec)
    return P()


def opt_sharding_like_params(mesh, opt_state, params, param_shardings,
                             zero1_axis: Optional[str] = None):
    """Shardings for an optimizer-state pytree: subtrees that mirror the
    params structure (velocity/m/v/accum) take the matching param sharding —
    except leaves whose param is fully replicated (spec ``P()``), which under
    ``zero1_axis`` get ZeRO-1 sharded instead (the momentum/m/v of non-TP-
    split params is the bulk of optimizer memory; leaving it replicated would
    defeat ZeRO-1 under TensorParallel). Everything else replicates,
    optionally ZeRO-1 sharded. Shared by the TP and pipeline strategies."""
    p_struct = jax.tree_util.tree_structure(params)

    def fallback(x):
        if zero1_axis is not None and hasattr(x, "ndim"):
            return NamedSharding(mesh, _zero1_spec(x, mesh, zero1_axis))
        return NamedSharding(mesh, P())

    def like_param(x, sh):
        # replicated param + zero1 => shard its optimizer state anyway
        if (zero1_axis is not None and hasattr(x, "ndim")
                and isinstance(sh, NamedSharding)
                and all(s is None for s in sh.spec)):
            return NamedSharding(mesh, _zero1_spec(x, mesh, zero1_axis))
        return sh

    def subtree(st):
        if jax.tree_util.tree_structure(st) == p_struct:
            return jax.tree_util.tree_map(like_param, st, param_shardings)
        return jax.tree_util.tree_map(fallback, st)

    if isinstance(opt_state, dict):
        return {k: subtree(v) for k, v in opt_state.items()}
    return subtree(opt_state)


class DataParallel:
    """Strategy object consumed by :class:`bigdl_tpu.optim.Optimizer`.

    ``zero1=True`` shards optimizer state over the data axis (reference's
    per-partition optimizer shards). ``grad_comm`` takes a
    :class:`bigdl_tpu.parallel.grad_comm.GradCommConfig` (the parsed
    ``--gradCompress``/``--gradBuckets`` pair) to bucket + compress the
    gradient all-reduce in :meth:`reduce_grads` — the reference's fp16
    codec, trace-level.
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: str = "data",
                 zero1: bool = True, donate: bool = True, grad_comm=None):
        if mesh is None:
            from bigdl_tpu.parallel.mesh import local_mesh
            mesh = local_mesh(axis)
        self.mesh = mesh
        self.axis = axis
        self.zero1 = zero1
        self.donate = donate
        self.grad_comm = grad_comm
        self._grad_comm_info = None
        self._repl = NamedSharding(mesh, P())
        self._batch = NamedSharding(mesh, P(axis))
        self._opt_shardings = None

    # ------------------------------------------------------------- identity
    def layout_signature(self) -> dict:
        """The dp layout this strategy writes checkpoints under — stamped
        into the topology manifest (``utils/file.save_pytree(layout=...)``)
        so a resharded restore knows what the writer looked like. Pure
        provenance: blobs hold gathered logical arrays regardless."""
        return {"strategy": type(self).__name__,
                "axis": self.axis,
                "zero1": bool(self.zero1),
                "n_devices": int(self.mesh.devices.size),
                "mesh": {str(name): int(self.mesh.shape[name])
                         for name in self.mesh.axis_names}}

    def lint_spec_metadata(self, params=None) -> dict:
        """What shardlint needs to reconstruct this strategy abstractly
        (ISSUE 19): declared mesh axes, the strategy's short name, the
        PartitionSpec tree it would commit for ``params`` (dp:
        replicated), and the grad-comm config steering the reduce."""
        from bigdl_tpu.parallel.tensor_parallel import replicated_specs
        return {"strategy": "dp",
                "mesh_axes": {str(name): int(self.mesh.shape[name])
                              for name in self.mesh.axis_names},
                "batch_axes": (self.axis,),
                "param_specs": (replicated_specs(params)
                                if params is not None else None),
                "grad_comm": self.grad_comm}

    # ------------------------------------------------------------- placement
    def _opt_sharding_tree(self, opt_state):
        def leaf_sharding(x):
            if not self.zero1:
                return self._repl
            return NamedSharding(self.mesh,
                                 _zero1_spec(x, self.mesh, self.axis))
        return jax.tree_util.tree_map(leaf_sharding, opt_state)

    def place(self, params, mod_state, opt_state):
        """Device-place the training pytrees: params/model-state replicated,
        optimizer state ZeRO-1 sharded."""
        params = jax.device_put(params, self._repl)
        mod_state = jax.device_put(mod_state, self._repl)
        self._opt_shardings = self._opt_sharding_tree(opt_state)
        opt_state = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), opt_state, self._opt_shardings)
        return params, mod_state, opt_state

    def shard_batch(self, x, y):
        """Global-batch placement, sharded along the data axis. Multi-host:
        each process contributes its local slice
        (make_array_from_process_local_data — the locality-aware feeding that
        replaces ZippedPartitionsWithLocalityRDD)."""
        if jax.process_count() > 1:
            mk = partial(jax.make_array_from_process_local_data, self._batch)
            return mk(np.asarray(x)), mk(np.asarray(y))
        return (jax.device_put(jnp.asarray(x), self._batch),
                jax.device_put(jnp.asarray(y), self._batch))

    # ------------------------------------------------------------- compile
    def reduce_grads(self, grads, loss):
        """The single entry point for gradient reduction: every strategy
        train step (Optimizer and perf harness) routes grads through here
        before clip/update.

        Without ``grad_comm`` the cross-device grad psum is inserted by
        the partitioner on the raw f32 values (params replicated) and
        this is the identity — the traced step is bit-identical to the
        pre-grad-comm harness. With an active config the grads are
        bucketed, compressed to the 16-bit wire dtype, annotated as the
        replication point (so the partitioner's all-reduce rides the
        compressed value), decompressed, and — under ``+ec`` — restored
        to the exact f32 gradient via the local rounding residual. The
        host-side bucket/wire accounting lands in
        :meth:`grad_comm_info` for perf JSON stamping."""
        from bigdl_tpu.parallel.grad_comm import apply_grad_comm

        grads, info = apply_grad_comm(grads, self.grad_comm, self.mesh)
        if info is not None:
            self._grad_comm_info = info
        return grads, loss

    def grad_comm_info(self):
        """Bucket/wire accounting from the last traced ``reduce_grads``
        (None when grad-comm never activated)."""
        return self._grad_comm_info

    def _hinted(self, train_step, batch_spec: Optional[P]):
        """Trace ``train_step`` under the batch-sharding hint so modules at
        reshape boundaries pin activations (and, via the constraint's
        transpose, cotangents) to the data axis — kills GSPMD's
        "Involuntary full rematerialization" on the conv→linear flatten
        backward. Only for pure dim-0 batch sharding: a composed spec
        (dp×sp etc.) must not have its seq/model layout clobbered."""
        if batch_spec is not None:
            return train_step
        from bigdl_tpu.parallel.hints import batch_sharding_hint

        def hinted(*args):
            with batch_sharding_hint(self.mesh, self.axis):
                return train_step(*args)

        return hinted

    def compile_step(self, train_step, batch_spec: Optional[P] = None):
        """``batch_spec`` overrides the x/y input sharding (e.g.
        P('data', 'seq', None) when composing with sequence parallelism)."""
        if self._opt_shardings is None:
            raise RuntimeError("DataParallel.place() must run before "
                               "compile_step()")
        batch = (self._batch if batch_spec is None
                 else NamedSharding(self.mesh, batch_spec))
        in_shardings = (self._repl, self._repl, self._opt_shardings,
                        batch, batch, self._repl)
        out_shardings = (self._repl, self._repl, self._opt_shardings,
                         self._repl)
        donate = (0, 1, 2) if self.donate else ()
        return jax.jit(self._hinted(train_step, batch_spec),
                       in_shardings=in_shardings,
                       out_shardings=out_shardings, donate_argnums=donate)

    def compile_eval(self, eval_step):
        return jax.jit(eval_step,
                       in_shardings=(self._repl, self._repl,
                                     self._batch, self._batch))

    # --------------------------------------------------------------- gather
    def gather(self, params, mod_state, opt_state):
        """Fully replicate for checkpointing (reference
        DistriOptimizer.getModel :472-496 reassembles slices on the driver)."""
        pull = lambda t: jax.device_get(t)
        return pull(params), pull(mod_state), pull(opt_state)


class FullyShardedDataParallel(DataParallel):
    """ZeRO-3 / FSDP via GSPMD: parameters themselves (not just optimizer
    state) are sharded over the data axis — per-leaf, largest divisible
    dimension — and XLA's partitioner inserts the all-gather before each
    use and the reduce-scatter on the gradients. Per-device memory for
    params+grads+opt-state drops ~Nx; the collective schedule is exactly
    the hand-written FSDP one, but compiler-derived.

    Beyond the reference (its AllReduceParameter keeps a full weight copy
    per executor, parameters/AllReduceParameter.scala:54-230); this is the
    scale path for models that don't fit replicated in HBM. Same Optimizer
    API: swap ``DataParallel(mesh)`` for ``FullyShardedDataParallel(mesh)``.

    Leaves too small to shard (dims not divisible by the axis size) stay
    replicated — same rule as ZeRO-1 state sharding, so tiny biases don't
    force padding collectives.
    """

    def __init__(self, mesh: Optional[Mesh] = None, axis: str = "data",
                 donate: bool = True):
        super().__init__(mesh, axis, zero1=True, donate=donate)
        self._param_shardings = None

    def _fsdp_sharding_tree(self, tree):
        return jax.tree_util.tree_map(
            lambda x: NamedSharding(self.mesh,
                                    _zero1_spec(x, self.mesh, self.axis)),
            tree)

    def place(self, params, mod_state, opt_state):
        self._param_shardings = self._fsdp_sharding_tree(params)
        params = jax.tree_util.tree_map(jax.device_put, params,
                                        self._param_shardings)
        # module state (BN stats etc.) is small and read every step:
        # replicate
        mod_state = jax.device_put(mod_state, self._repl)
        self._opt_shardings = opt_sharding_like_params(
            self.mesh, opt_state, params, self._param_shardings,
            zero1_axis=self.axis)
        opt_state = jax.tree_util.tree_map(jax.device_put, opt_state,
                                           self._opt_shardings)
        return params, mod_state, opt_state

    def compile_step(self, train_step, batch_spec: Optional[P] = None):
        if self._param_shardings is None:
            raise RuntimeError("FullyShardedDataParallel.place() must run "
                               "before compile_step()")
        batch = (self._batch if batch_spec is None
                 else NamedSharding(self.mesh, batch_spec))
        in_shardings = (self._param_shardings, self._repl,
                        self._opt_shardings, batch, batch, self._repl)
        out_shardings = (self._param_shardings, self._repl,
                         self._opt_shardings, self._repl)
        donate = (0, 1, 2) if self.donate else ()
        return jax.jit(self._hinted(train_step, batch_spec),
                       in_shardings=in_shardings,
                       out_shardings=out_shardings, donate_argnums=donate)

    def compile_eval(self, eval_step):
        if self._param_shardings is None:
            raise RuntimeError("FullyShardedDataParallel.place() must run "
                               "before compile_eval()")
        return jax.jit(eval_step,
                       in_shardings=(self._param_shardings, self._repl,
                                     self._batch, self._batch))

    def gather(self, params, mod_state, opt_state):
        """FSDP leaves span every process's devices; on multi-host,
        device_get would throw on non-addressable shards — allgather the
        global values instead (single-host device_get stays cheap)."""
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            ag = lambda t: multihost_utils.process_allgather(t, tiled=True)
            return ag(params), ag(mod_state), ag(opt_state)
        return super().gather(params, mod_state, opt_state)
