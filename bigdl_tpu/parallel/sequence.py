"""Sequence/context parallelism: ring attention over a mesh axis.

The reference has no attention and no sequence parallelism (SURVEY.md §2.7:
"Sequence/context parallel — NO"); long sequences there meant truncated BPTT
(nn/Recurrent.scala:66-107). This module is the TPU-native long-context
design the brief requires: the sequence dimension is sharded over a ``seq``
mesh axis, each device holds one block of Q/K/V, and K/V blocks rotate
around the ring via ``ppermute`` while each device accumulates its Q-block's
attention with an online (streaming) softmax — compute overlaps with ICI
transfer, memory per device is O(seq/N), and the result is bit-equivalent
(up to fp reassociation) to full attention.

Usage: ``attn = make_ring_attention(mesh, "seq")`` then pass it as
``attn_impl=`` to :class:`bigdl_tpu.nn.MultiHeadAttention`, with the
(batch, seq, d_model) activations sharded ``P(None, "seq", None)``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.ops.attention_kernel import online_softmax_update

__all__ = ["ring_attention", "make_ring_attention"]

_NEG_INF = -1e30  # finite mask value: keeps exp() well-defined in blocks
                  # that are entirely masked out (true -inf would NaN)


def ring_attention(q, k, v, segments=None, *, axis_name: str,
                   causal: bool = False, block_k: int = 512):
    """Blockwise ring attention. Must run inside shard_map with the seq
    dimension of q/k/v (shape ...,(b,h,s_local,d)) sharded on ``axis_name``.

    ``block_k`` bounds the score-tile width *within* each ring hop: the
    arriving K/V chunk is folded through the online softmax in sub-blocks
    (under ``jax.checkpoint``), so peak memory is O(s_local x block_k)
    instead of O(s_local^2) — at 8-way sequence parallel over a 128k
    context the local chunk is 16k and a dense per-hop tile would be
    16k x 16k per head.

    ``segments``: (b, s_local) packed-document ids (same seq sharding as
    q) — the id chunk rides the ring next to K/V, so packed training and
    sequence parallelism compose; semantics match the flash kernel
    (equal id attends, including the 0 padding id with itself).
    """
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    s_q = q.shape[-2]
    s_k = k.shape[-2]
    scale = 1.0 / (q.shape[-1] ** 0.5)

    # global positions of my q rows
    q_pos = my * s_q + jnp.arange(s_q)
    seg_q = None if segments is None else segments.astype(jnp.int32)

    bk = min(block_k, s_k)
    n_sub = s_k // bk if s_k % bk == 0 else 1
    if n_sub == 1:
        bk = s_k

    def fold_chunk(src, kb, vb, sb, m, l, o):
        """Fold one arriving (s_local, d) K/V chunk, sub-block by
        sub-block, into the streaming softmax state."""
        kbs = kb.reshape(kb.shape[:-2] + (n_sub, bk, kb.shape[-1]))
        vbs = vb.reshape(vb.shape[:-2] + (n_sub, bk, vb.shape[-1]))
        kbs = jnp.moveaxis(kbs, -3, 0)
        vbs = jnp.moveaxis(vbs, -3, 0)
        scan_in = (kbs, vbs)
        if sb is not None:
            sbs = jnp.moveaxis(
                sb.reshape(sb.shape[0], n_sub, bk), 1, 0)
            scan_in = (kbs, vbs, sbs)

        @jax.checkpoint
        def sub(carry, blk):
            m, l, o, j = carry
            if sb is not None:
                kj, vj, sj = blk
            else:
                (kj, vj), sj = blk, None
            valid = None
            if causal:
                k_pos = src * s_k + j * bk + jnp.arange(bk)
                valid = q_pos[:, None] >= k_pos[None, :]
            if sj is not None:
                # (b, 1, s_q, bk); broadcasts against (b, h, s_q, bk)
                sv = (seg_q[:, None, :, None] == sj[:, None, None, :])
                valid = sv if valid is None else (valid & sv)
            m, l, o = online_softmax_update(q, kj, vj, m, l, o, scale,
                                            valid)
            return (m, l, o, j + 1), None

        (m, l, o, _), _ = jax.lax.scan(sub, (m, l, o, 0), scan_in)
        return m, l, o

    def step(carry, t):
        if seg_q is not None:
            kb, vb, sb, m, l, o = carry
        else:
            (kb, vb, m, l, o), sb = carry, None
        # after t hops of "send to next", I hold the block born on (my - t)
        src = (my - t) % n
        m, l, o = fold_chunk(src, kb, vb, sb, m, l, o)
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        if sb is not None:
            sb = jax.lax.ppermute(sb, axis_name, perm)
            return (kb, vb, sb, m, l, o), None
        return (kb, vb, m, l, o), None

    m0 = jnp.full(q.shape[:-1] + (1,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros(q.shape[:-1] + (1,), jnp.float32)
    o0 = jnp.zeros(q.shape, jnp.float32)
    if seg_q is not None:
        carry0 = (k, v, seg_q, m0, l0, o0)
        (_, _, _, _, l, o), _ = _scan_steps(step, carry0, n)
    else:
        (_, _, _, l, o), _ = _scan_steps(step, (k, v, m0, l0, o0), n)
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _scan_steps(step, carry, n):
    return jax.lax.scan(step, carry, jnp.arange(n))


def make_ring_attention(mesh: Mesh, seq_axis: str = "seq",
                        batch_axis: Optional[str] = None,
                        block_k: int = 512):
    """Wrap :func:`ring_attention` in shard_map so it can be passed directly
    as ``attn_impl`` to MultiHeadAttention. q/k/v are (b, h, s, d); s is
    sharded on ``seq_axis`` (and b on ``batch_axis`` when given)."""
    spec = P(batch_axis, None, seq_axis, None)

    def attn(q, k, v, *, causal: bool = False, mask=None, segments=None):
        if mask is not None:
            raise NotImplementedError(
                "ring attention supports causal masking only")
        fn = functools.partial(ring_attention, axis_name=seq_axis,
                               causal=causal, block_k=block_k)
        if segments is not None:
            seg_spec = P(batch_axis, seq_axis)
            return jax.shard_map(
                fn, mesh=mesh, in_specs=(spec, spec, spec, seg_spec),
                out_specs=spec, check_vma=False)(q, k, v, segments)
        return jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec),
                             out_specs=spec, check_vma=False)(q, k, v)

    return attn
