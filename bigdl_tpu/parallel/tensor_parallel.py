"""Tensor (model) parallelism — GSPMD parameter sharding over a ``model`` axis.

The reference has no tensor parallelism (SURVEY.md §2.7: "Tensor (intra-op
model) parallel — NO"); this is a new, TPU-first capability. The design is
deliberately *not* Megatron's hand-written f/g collective layers: under XLA's
SPMD partitioner it is sufficient to annotate the **weights** with shardings —
the compiler propagates shardings through the einsums and inserts the exact
all-reduce/all-gather schedule Megatron hand-codes. The classic pairing
(column-split first matmul, row-split second, one psum at the end of the pair)
falls out automatically from the weight specs below.

``megatron_specs(module, params, axis, n_shard)`` builds a PartitionSpec
pytree that mirrors ``params``:

* ``Linear`` (weight ``(in, out)``, ``y = x @ w``): consecutive Linears
  alternate column-parallel ``P(None, axis)`` / row-parallel ``P(axis, None)``
  so activations stay sharded on the feature dim between the pair.
* ``MultiHeadAttention``: wq/wk/wv column-split (= head-parallel, the
  attention itself is embarrassingly parallel over heads), wo row-split.
* ``TransformerEncoderLayer``: attention as above; MLP w1 column / w2 row;
  LayerNorms replicated.
* ``SpatialConvolution`` (HWIO weight): output-channel split on the last dim.
* ``LookupTable``: embedding dim split (row/vocab split would need masked
  gather + psum; feature split composes with a following column Linear).
* anything else: replicated.

A dimension is only split when divisible by the axis size; otherwise that
leaf stays replicated (correctness never depends on divisibility).

:class:`TensorParallel` is the strategy object (same protocol as
:class:`~bigdl_tpu.parallel.DataParallel`: place / shard_batch /
compile_step / compile_eval / gather) for a ``data × model`` mesh — data
parallelism over ``data_axis`` (batch sharded) and tensor parallelism over
``model_axis`` (params sharded). Keep ``model`` on ICI-adjacent devices:
its collectives are per-layer, while ``data``'s is one grad reduction.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bigdl_tpu.parallel.data_parallel import (
    DataParallel, opt_sharding_like_params,
)

__all__ = ["TensorParallel", "megatron_specs", "replicated_specs"]


def replicated_specs(params):
    """All-replicated spec tree (the degenerate rule)."""
    return jax.tree_util.tree_map(lambda _: P(), params)


def _div(dim: int, n: int) -> bool:
    return n > 1 and dim % n == 0 and dim >= n


def _pointwise(mod) -> bool:
    """True for layers transparent to a feature-dim sharding — elementwise
    activations and Dropout may sit between a column-parallel and a
    row-parallel Linear without forcing a resharding. SoftMax/SoftMin/
    LogSoftMax reduce over the feature axis, so they are NOT transparent:
    pairing across them would force an all-gather per layer."""
    from bigdl_tpu import nn
    if isinstance(mod, (nn.SoftMax, nn.SoftMin, nn.LogSoftMax)):
        return False
    return (type(mod).__module__ == "bigdl_tpu.nn.activation"
            or isinstance(mod, nn.Dropout))


def megatron_specs(module, params, axis: str, n_shard: int):
    """Build the param-sharding spec pytree for ``module``'s ``params``.

    Dispatches on layer type, recursing through containers. Megatron
    pairing is **structural**, not visit-order: within an ordered container
    a Linear is column-split only when a second Linear follows it (possibly
    through pointwise activations/Dropout) to take the matching row split —
    so branchy models (Concat, lone classifier heads, odd Linear counts)
    never silently land in an all-gather-heavy layout; unpaired Linears
    replicate.
    """
    from bigdl_tpu import nn

    def linear_col_spec(p):
        spec = {"weight": P(None, axis)}
        if "bias" in p:
            spec["bias"] = P(axis)
        return spec

    def linear_row_spec(p):
        spec = {"weight": P(axis, None)}
        if "bias" in p:
            spec["bias"] = P()
        return spec

    def mha_spec(mod, p):
        if not _div(mod.num_heads, n_shard):
            return replicated_specs(p)
        return {
            "wq": P(None, axis), "wk": P(None, axis), "wv": P(None, axis),
            "bq": P(axis), "bk": P(axis), "bv": P(axis),
            "wo": P(axis, None), "bo": P(),
        }

    def block_spec(mod, p):
        d, f = mod._mlp_dims
        out = {
            "ln1": replicated_specs(p["ln1"]),
            "ln2": replicated_specs(p["ln2"]),
            "mha": mha_spec(mod.mha, p["mha"]),
            "w1": P(None, axis) if _div(f, n_shard) else P(),
            "b1": P(axis) if _div(f, n_shard) else P(),
            "w2": P(axis, None) if _div(f, n_shard) else P(),
            "b2": P(),
        }
        return out

    def conv_spec(mod, p):
        # HWIO weight; split output channels (last dim)
        w = p["weight"]
        if _div(w.shape[-1], n_shard):
            spec = {"weight": P(None, None, None, axis)}
            if "bias" in p:
                spec["bias"] = P(axis)
            return spec
        return replicated_specs(p)

    def lookup_spec(mod, p):
        w = p["weight"]
        if _div(w.shape[-1], n_shard):
            return {"weight": P(None, axis)}
        return replicated_specs(p)

    def seq_spec(children, p):
        """Ordered-container walk with structural Megatron pairing."""
        out = {}
        n_c = len(children)
        i = 0
        while i < n_c:
            k, c = str(i), children[i]
            if isinstance(c, nn.Linear) and k in p:
                # look past pointwise layers for the row-split partner
                j = i + 1
                while j < n_c and _pointwise(children[j]):
                    j += 1
                kj = str(j)
                if (j < n_c and isinstance(children[j], nn.Linear)
                        and kj in p
                        and _div(p[k]["weight"].shape[1], n_shard)
                        and _div(p[kj]["weight"].shape[0], n_shard)):
                    out[k] = linear_col_spec(p[k])
                    out[kj] = linear_row_spec(p[kj])
                    for m in range(i + 1, j):  # pointwise layers between
                        km = str(m)
                        if km in p:
                            out[km] = replicated_specs(p[km])
                    i = j + 1
                    continue
                out[k] = replicated_specs(p[k])  # unpaired: replicate
                i += 1
                continue
            if k in p:
                out[k] = rec(c, p[k])
            i += 1
        # container-level params not belonging to an indexed child
        for k in p:
            if k not in out:
                out[k] = replicated_specs(p[k])
        return out

    def rec(mod, p):
        if isinstance(mod, nn.TransformerEncoderLayer):
            return block_spec(mod, p)
        if isinstance(mod, nn.MultiHeadAttention):
            return mha_spec(mod, p)
        if isinstance(mod, nn.Linear):
            # a Linear reached outside an ordered container has no partner
            # to pair with — replicate (correct over clever)
            return replicated_specs(p)
        if isinstance(mod, nn.LookupTable):
            return lookup_spec(mod, p)
        if isinstance(mod, nn.SpatialConvolution):
            return conv_spec(mod, p)
        # custom modules that keep child params under named keys (e.g.
        # TransformerLM's "emb"/"encoder"/"ln_f") declare the mapping via
        # tp_param_children() so the walk can descend into them
        named = getattr(mod, "tp_param_children", None)
        if named is not None and isinstance(p, dict):
            mapping = named()
            out = {k: rec(c, p[k]) for k, c in mapping.items() if k in p}
            for k in p:
                if k not in out:
                    out[k] = replicated_specs(p[k])
            return out
        children = mod.children()
        if children and isinstance(p, dict):
            from bigdl_tpu.core import Sequential
            if isinstance(mod, Sequential):
                return seq_spec(children, p)
            # parallel containers (Concat/ConcatTable/ParallelTable/...):
            # each branch recurses independently — pairing never spans
            # branches that execute side by side
            out = {}
            for i, c in enumerate(children):
                k = str(i)
                if k in p:
                    out[k] = rec(c, p[k])
            for k in p:
                if k not in out:
                    out[k] = replicated_specs(p[k])
            return out
        return replicated_specs(p)

    return rec(module, params)


class TensorParallel(DataParallel):
    """dp × tp strategy over a mesh with ``data_axis`` and ``model_axis``.

    Params are sharded per ``rules`` (default :func:`megatron_specs`) over
    ``model_axis``; the batch is sharded over ``data_axis``; optimizer state
    inherits each param's sharding (so TP-sharded leaves keep their layout)
    with ZeRO-1 over ``data_axis`` for the replicated remainder.
    """

    def __init__(self, mesh: Mesh, module,
                 data_axis: str = "data", model_axis: str = "model",
                 rules: Callable = megatron_specs,
                 zero1: bool = True, donate: bool = True):
        super().__init__(mesh, axis=data_axis, zero1=zero1, donate=donate)
        self.module = module
        self.model_axis = model_axis
        self.rules = rules
        self._param_shardings = None

    def lint_spec_metadata(self, params=None) -> dict:
        """Shardlint view of this strategy (ISSUE 19): the megatron spec
        tree over ``model_axis`` for ``params`` (abstract trees from
        ``jax.eval_shape`` work — ``rules`` only reads shapes)."""
        meta = super().lint_spec_metadata(None)
        meta["strategy"] = "tp"
        meta["model_axis"] = self.model_axis
        if params is not None:
            n = self.mesh.shape[self.model_axis]
            meta["param_specs"] = self.rules(self.module, params,
                                             self.model_axis, n)
        return meta

    # ------------------------------------------------------------- placement
    def _build_param_shardings(self, params):
        n = self.mesh.shape[self.model_axis]
        specs = self.rules(self.module, params, self.model_axis, n)
        return jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), specs,
            is_leaf=lambda x: isinstance(x, P))

    def place(self, params, mod_state, opt_state):
        self._param_shardings = self._build_param_shardings(params)
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), params, self._param_shardings)
        mod_state = jax.device_put(mod_state, self._repl)
        self._opt_shardings = opt_sharding_like_params(
            self.mesh, opt_state, params, self._param_shardings,
            zero1_axis=self.axis if self.zero1 else None)
        opt_state = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, s), opt_state, self._opt_shardings)
        return params, mod_state, opt_state

    # ------------------------------------------------------------- compile
    def compile_step(self, train_step, batch_spec: Optional[P] = None):
        """``batch_spec`` overrides the x/y sharding (e.g.
        P('data', 'seq', None) when composing with ring attention)."""
        if self._param_shardings is None:
            raise RuntimeError("TensorParallel.place() must run before "
                               "compile_step()")
        batch = (self._batch if batch_spec is None
                 else NamedSharding(self.mesh, batch_spec))
        in_shardings = (self._param_shardings, self._repl, self._opt_shardings,
                        batch, batch, self._repl)
        out_shardings = (self._param_shardings, self._repl,
                         self._opt_shardings, self._repl)
        donate = (0, 1, 2) if self.donate else ()
        return jax.jit(train_step, in_shardings=in_shardings,
                       out_shardings=out_shardings, donate_argnums=donate)

    def compile_eval(self, eval_step):
        return jax.jit(eval_step,
                       in_shardings=(self._param_shardings, self._repl,
                                     self._batch, self._batch))
