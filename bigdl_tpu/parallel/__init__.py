from bigdl_tpu.parallel.mesh import (
    init_distributed, make_mesh, make_hybrid_mesh, local_mesh, P,
    NamedSharding,
)
from bigdl_tpu.parallel.data_parallel import (
    DataParallel, FullyShardedDataParallel,
)
from bigdl_tpu.parallel.grad_comm import (
    COMPRESS_MODES, DEFAULT_BUCKET_BYTES, GradCommConfig, BucketPlan,
    make_config as make_grad_comm_config, build_bucket_plan,
    apply_grad_comm, compressed_psum, shard_map_available,
)
from bigdl_tpu.parallel.tensor_parallel import (
    TensorParallel, megatron_specs, replicated_specs,
)
from bigdl_tpu.parallel.sequence import ring_attention, make_ring_attention
from bigdl_tpu.parallel.pipeline import (
    PipelineStack, pipeline_forward, place_pipeline_params,
    make_pipeline_train_step,
)
