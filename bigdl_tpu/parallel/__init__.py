from bigdl_tpu.parallel.mesh import (
    init_distributed, make_mesh, local_mesh, P, NamedSharding,
)
from bigdl_tpu.parallel.data_parallel import DataParallel
from bigdl_tpu.parallel.sequence import ring_attention, make_ring_attention
