"""Device-mesh construction and distributed bring-up.

Replaces the reference's Engine.init + Spark topology
(utils/Engine.scala:305-337): where the reference provisions executor JVMs,
env vars, and thread pools, the TPU runtime is (1) an optional
``jax.distributed.initialize`` for multi-host, and (2) a
``jax.sharding.Mesh`` whose axes name the parallelism dimensions.

Axis conventions used across the framework:
  * ``data``  — batch / data parallelism (the reference's only inter-node axis)
  * ``model`` — tensor parallelism (new capability, ICI-friendly)
  * ``seq``   — sequence/context parallelism for long sequences
  * ``pipe``  — pipeline stages
All collectives ride whichever physical links the mesh maps those axes onto;
keep ``model``/``seq`` on ICI-adjacent devices and ``data`` outermost (DCN).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["init_distributed", "make_mesh", "local_mesh", "P", "NamedSharding"]


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host control-plane bring-up (the analog of the reference's
    Engine.init(onSpark=true) executor rendezvous, Engine.scala:305-337).
    No-op when running single-process."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def make_mesh(axes: dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh, e.g. ``make_mesh({'data': 4, 'model': 2})``.

    Axis sizes must multiply to the device count; size -1 means "fill with
    the remaining devices"."""
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} "
                         f"devices, have {len(devices)}")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def local_mesh(data_axis: str = "data") -> Mesh:
    """All local devices on one data axis — the 'LocalOptimizer' topology
    (one host, batch split across chips like the reference splits across
    cores, LocalOptimizer.scala:65-105)."""
    return make_mesh({data_axis: len(jax.devices())})
