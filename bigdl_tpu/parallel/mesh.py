"""Device-mesh construction and distributed bring-up.

Replaces the reference's Engine.init + Spark topology
(utils/Engine.scala:305-337): where the reference provisions executor JVMs,
env vars, and thread pools, the TPU runtime is (1) an optional
``jax.distributed.initialize`` for multi-host, and (2) a
``jax.sharding.Mesh`` whose axes name the parallelism dimensions.

Axis conventions used across the framework:
  * ``data``  — batch / data parallelism (the reference's only inter-node axis)
  * ``model`` — tensor parallelism (new capability, ICI-friendly)
  * ``seq``   — sequence/context parallelism for long sequences
  * ``pipe``  — pipeline stages
All collectives ride whichever physical links the mesh maps those axes onto;
keep ``model``/``seq`` on ICI-adjacent devices and ``data`` outermost (DCN).
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["init_distributed", "make_mesh", "make_hybrid_mesh",
           "local_mesh", "P", "NamedSharding"]


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Multi-host control-plane bring-up (the analog of the reference's
    Engine.init(onSpark=true) executor rendezvous, Engine.scala:305-337).
    No-op when running single-process."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def make_mesh(axes: dict[str, int], devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh, e.g. ``make_mesh({'data': 4, 'model': 2})``.

    Axis sizes must multiply to the device count; size -1 means "fill with
    the remaining devices"."""
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} "
                         f"devices, have {len(devices)}")
    arr = np.asarray(devices).reshape(sizes)
    return Mesh(arr, tuple(names))


def make_hybrid_mesh(dcn_axes: dict[str, int], ici_axes: dict[str, int],
                     devices: Optional[Sequence] = None,
                     num_slices: Optional[int] = None) -> Mesh:
    """Mesh spanning multiple ICI domains (TPU slices / pods) joined by
    DCN — the multi-slice topology the reference reaches with one Spark
    cluster over many Xeon hosts (its only inter-node axis is data,
    DistriOptimizer.scala; here any named axis can be placed on either
    fabric). ``dcn_axes`` are laid out across slices (outermost, so their
    collectives ride the data-center network), ``ici_axes`` within a slice
    (inner, riding the chip interconnect) — the standard
    dp-over-DCN x tp/sp-over-ICI recipe.

    Slice membership comes from ``device.slice_index`` (real multi-slice
    TPU), falling back to ``process_index`` (multi-host CPU/test
    environments). When the runtime reports a single slice (e.g. the
    8-device virtual CPU mesh) pass ``num_slices`` to partition the
    device list into that many equal contiguous virtual slices.

    Axis sizes: the product of ``dcn_axes`` must equal the slice count;
    the product of ``ici_axes`` must equal the per-slice device count
    (one -1 wildcard allowed in each, as in :func:`make_mesh`).
    """
    devices = list(devices if devices is not None else jax.devices())

    def _group(key):
        g: dict[int, list] = {}
        for d in devices:
            g.setdefault(key(d), []).append(d)
        return g

    groups = _group(lambda d: getattr(d, "slice_index", 0))
    if len(groups) == 1:
        # non-TPU backends report one slice (CPU devices carry
        # slice_index 0 regardless of process) — host boundaries are the
        # DCN boundaries there
        by_proc = _group(lambda d: d.process_index)
        if len(by_proc) > 1:
            groups = by_proc
    if len(groups) == 1 and num_slices and num_slices > 1:
        if len(devices) % num_slices:
            raise ValueError(f"{len(devices)} devices do not split into "
                             f"{num_slices} equal virtual slices")
        per = len(devices) // num_slices
        groups = {i: devices[i * per:(i + 1) * per]
                  for i in range(num_slices)}
    slices = [groups[k] for k in sorted(groups)]
    per_slice = len(slices[0])
    if any(len(s) != per_slice for s in slices):
        raise ValueError("slices are unequal: "
                         f"{[len(s) for s in slices]} devices per slice")

    def _resolve(axes: dict[str, int], total: int, what: str):
        names, sizes = list(axes.keys()), list(axes.values())
        if -1 in sizes:
            known = int(np.prod([s for s in sizes if s != -1]))
            sizes[sizes.index(-1)] = total // max(known, 1)
        if int(np.prod(sizes)) != total:
            raise ValueError(f"{what} axes {dict(zip(names, sizes))} "
                             f"must multiply to {total}")
        return names, sizes

    dcn_names, dcn_sizes = _resolve(dcn_axes, len(slices), "dcn")
    ici_names, ici_sizes = _resolve(ici_axes, per_slice, "ici")
    arr = np.asarray([s for s in slices]).reshape(dcn_sizes + ici_sizes)
    return Mesh(arr, tuple(dcn_names + ici_names))


def local_mesh(data_axis: str = "data") -> Mesh:
    """All local devices on one data axis — the 'LocalOptimizer' topology
    (one host, batch split across chips like the reference splits across
    cores, LocalOptimizer.scala:65-105)."""
    return make_mesh({data_axis: len(jax.devices())})
