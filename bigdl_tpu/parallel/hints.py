"""Trace-time activation-sharding hints.

GSPMD propagates shardings through a program on its own, but reshapes that
collapse several dims into one (the conv→linear flatten) leave it free to
pick a spatial layout for the *cotangent* in the backward pass; it then has
to go e.g. ``{devices=[1,4,2,1]} → {devices=[8,1,1,1]}`` via full
replication ("Involuntary full rematerialization", spmd_partitioner.cc) —
correct, but a cliff at pod scale.

The fix is one well-placed :func:`jax.lax.with_sharding_constraint` on the
activation at the ambiguous boundary: the constraint's transpose rule
applies the same sharding to the cotangent, so the backward reshape keeps
the batch layout too. Modules can't see the mesh, and the strategy can't
see module internals, so the hand-off is a context variable: the strategy
sets the hint around the *trace* of the train step
(:meth:`DataParallel.compile_step` wraps ``train_step``), and
shape-changing modules (:class:`bigdl_tpu.nn.Reshape`) ask
:func:`constrain_batch` to pin dim 0 to the data axis.

The hint is only set by pure batch-sharding strategies (``batch_spec is
None``): under dp×sp or tensor-parallel layouts a dim-0-only constraint
would clobber the seq/model sharding of the activations it touches.
"""

from __future__ import annotations

from contextlib import contextmanager
from contextvars import ContextVar
from typing import Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["batch_sharding_hint", "constrain_batch"]

_BATCH_HINT: ContextVar[Optional[Tuple[Mesh, str]]] = ContextVar(
    "bigdl_tpu_batch_hint", default=None)


@contextmanager
def batch_sharding_hint(mesh: Mesh, axis: str):
    """Within this context (i.e. during the trace of a train step),
    :func:`constrain_batch` pins activations to ``P(axis, None, ...)``."""
    token = _BATCH_HINT.set((mesh, axis))
    try:
        yield
    finally:
        _BATCH_HINT.reset(token)


def constrain_batch(x):
    """Constrain dim 0 of ``x`` to the hinted data axis (no-op when no hint
    is active, outside a trace, or when dim 0 doesn't divide evenly —
    padding collectives would cost more than the reshard being avoided)."""
    hint = _BATCH_HINT.get()
    if hint is None or not hasattr(x, "ndim") or x.ndim < 1:
        return x
    mesh, axis = hint
    if x.shape[0] % mesh.shape[axis]:
        return x
    spec = P(axis, *([None] * (x.ndim - 1)))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
