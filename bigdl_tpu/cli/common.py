"""Shared CLI wiring (reference models/*/Utils.scala scopt parsers +
models/inception/Options.scala — one typed flag surface instead of the
reference's env-var / system-property / scopt triple, SURVEY.md §5
"Config / flag system")."""

from __future__ import annotations

import argparse
import json
import logging
import os
import time
from typing import Optional, Sequence

import numpy as np


def _add_platform_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument("--platform", default=None,
                   choices=["cpu", "tpu"],
                   help="force the jax backend (observed failure mode: a "
                        "down TPU tunnel hangs backend init forever — "
                        "--platform cpu keeps the CLI usable; must take "
                        "effect before first jax device use)")


def add_autotune_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument("--autotune", default="off",
                   choices=["off", "cached", "measure"],
                   help="per-shape kernel autotuner (bigdl_tpu.tuning): "
                        "conv pass layouts, flash-attention block sizes, "
                        "BN stats row block. 'cached' = read persisted "
                        "decisions (~/.cache/bigdl_tpu/autotune/"
                        "<device>.json), never measure; 'measure' = time "
                        "candidates on cache miss and persist the winner "
                        "(off-TPU this dry-records the defaults without "
                        "timing); 'off' = shipped defaults")


def add_fused_bn_arg(p: argparse.ArgumentParser) -> None:
    """--fusedBN [off|stats|apply]: Pallas BN for training-mode batch
    norm. Bare ``--fusedBN`` keeps the historical meaning (the stats
    kernel) so existing invocations/scripts are unchanged."""
    p.add_argument("--fusedBN", nargs="?", const="stats", default=None,
                   choices=["off", "stats", "apply"],
                   help="Pallas BN path (ops/bn_kernel.py; single-device "
                        "jit, auto-disabled under SPMD): 'stats' = "
                        "single-read stats kernel (measured −46%% on "
                        "chip, PERF.md §8.2 — kept for A/Bs); 'apply' = "
                        "the FULL fused block: stats+apply+absorbed-ReLU "
                        "in one kernel forward, Σdy/Σ(dy·x̂)+dx in one "
                        "kernel backward (PERF.md §10). Bare --fusedBN "
                        "means 'stats' (historical)")


def add_lint_arg(p: argparse.ArgumentParser) -> None:
    """--lint[=strict]: tpulint pre-flight (bigdl_tpu.analysis) before
    the run compiles anything — trace-time rule evaluation on CPU in
    seconds. ``strict`` refuses to launch on error-severity findings."""
    p.add_argument("--lint", nargs="?", const="on", default=None,
                   choices=["on", "strict"],
                   help="pre-flight static analysis of the model/config "
                        "(bigdl_tpu.analysis, PERF.md §12): dtype "
                        "upcasts, donation, Pallas tiling/VMEM, fusion "
                        "opportunities (unfused BN, GEMM-eligible "
                        "convs), host syncs. Bare --lint prints the "
                        "report and continues; --lint=strict exits "
                        "nonzero on error-severity findings. Findings "
                        "are stamped into perf JSON lines as 'lint'")


def add_resilience_args(p: argparse.ArgumentParser) -> None:
    """--supervise/--faultPlan (ISSUE 6): supervised recovery + the
    deterministic fault injector, shared by the training CLIs, perf,
    and serve."""
    p.add_argument("--supervise", nargs="?", const=5, type=int,
                   default=None, metavar="BUDGET",
                   help="supervised recovery (bigdl_tpu.resilience): "
                        "catch retryable faults (transient dispatch "
                        "errors, checkpoint I/O failures, checksum "
                        "mismatches, soft preemptions), retry with "
                        "exponential backoff + deterministic jitter, "
                        "auto-resume from the newest checksum-VALID "
                        "checkpoint, give up past BUDGET retries (bare "
                        "flag = 5). Fault-free overhead is one pointer "
                        "check per step")
    p.add_argument("--faultPlan", default=None, metavar="SPEC|FILE",
                   help="deterministic seeded fault injection "
                        "(bigdl_tpu.resilience.faults): ';'-separated "
                        "kind@site:VISITS[:ARG] entries or a JSON file — "
                        "e.g. 'preempt@step:7' (process-fatal kill "
                        "before step 7), 'dispatch@step:p0.01;seed=3' "
                        "(1%% transient step failures), "
                        "'corrupt@ckpt_save:2' (bit-rot the 2nd "
                        "checkpoint), 'stall@step:4:0.25', "
                        "'kill_device@step:5:1' (lose 1 device before "
                        "step 5 — recoverable only under --elastic). "
                        "Sites: data, step, ckpt_save, ckpt_restore, "
                        "infer, request. No-op when unset")
    p.add_argument("--elastic", default=None, choices=["hold", "scale"],
                   metavar="POLICY",
                   help="elastic data-parallelism "
                        "(bigdl_tpu.resilience.elastic): on device loss "
                        "(kill_device fault / DeviceLossFault) re-form "
                        "the mesh at the surviving count, re-resolve the "
                        "grad-comm bucket bound for the new n_devices, "
                        "and resume from the last valid checkpoint — "
                        "holding the global batch (hold: pad per-device "
                        "batches) or scaling it (scale: trim to "
                        "divisibility). dp strategy only")
    p.add_argument("--minDevices", type=int, default=1, metavar="N",
                   help="give up cleanly (SupervisorGaveUp) when fewer "
                        "than N healthy devices survive — elastic "
                        "reshape never thrashes below a viable mesh "
                        "(default 1)")


def add_obs_args(p: argparse.ArgumentParser) -> None:
    """--obs/--traceDir/--traceSteps/--metricsPort (ISSUE 7): the
    unified observability layer, shared by perf and every training
    CLI."""
    p.add_argument("--obs", action="store_true",
                   help="step-phase observability (bigdl_tpu.obs): span "
                        "tracing around the loop's real phases "
                        "(data_wait/h2d/dispatch/device/ckpt), per-step "
                        "phase histograms in the shared metrics "
                        "registry, and phase columns stamped into perf "
                        "JSON lines. Off: zero-cost no-ops, output "
                        "byte-identical modulo null columns")
    p.add_argument("--traceDir", default=None, metavar="DIR",
                   help="observability artifact dir: the Chrome-trace "
                        "span timeline (spans.trace.json — load in "
                        "chrome://tracing or ui.perfetto.dev) plus any "
                        "on-demand profile capture windows. Implies "
                        "--obs")
    p.add_argument("--traceSteps", default=None, metavar="N@M",
                   help="capture a jax.profiler trace of steps M..M+N-1 "
                        "mid-run into --traceDir (verified parseable "
                        "with utils/xplane on close). Independently, "
                        "SIGUSR2 or `touch DIR/CAPTURE` opens a bounded "
                        "window on a run already in flight")
    p.add_argument("--metricsPort", type=int, default=None, metavar="PORT",
                   help="start a live /metrics listener (serving's "
                        "Prometheus exposition format) for this "
                        "training/perf run; 0 = auto-pick a free port "
                        "(printed and stamped into the perf JSON obs "
                        "annotation). An explicit port that is already "
                        "taken is a clean SystemExit, not a mid-run "
                        "socket traceback")


# the --dataWorkers/--prefetchDepth/--stage surface (ISSUE 13): the
# async input-pipeline executor + host->device staging, shared by perf
# and every training CLI (must mirror dataset.pipeline.STAGE_CHOICES —
# asserted in tests, not imported here, so argparse setup never pulls
# the jax-importing dataset package)
PIPELINE_STAGE_CHOICES = ("off", "host", "device")


def add_pipeline_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--dataWorkers", type=int, default=0, metavar="N",
                   help="async input-pipeline executor "
                        "(bigdl_tpu.dataset.pipeline, the reference's "
                        "MTLabeledBGRImgToBatch model): N decode/augment "
                        "worker threads race the epoch plan's sample "
                        "tickets and reassemble batches in submission "
                        "order — the batch stream is bit-identical for "
                        "ANY worker count and under kill+resume "
                        "(per-sample (seed, epoch, index) rngs). 0 = "
                        "legacy single-threaded feed")
    p.add_argument("--prefetchDepth", type=int, default=2, metavar="D",
                   help="max batches prepared ahead of the consumer — "
                        "bounds both the executor's in-flight batch "
                        "reassembly (workers block past it) and the "
                        "staging queue (default 2: double buffering)")
    p.add_argument("--stage", default="off",
                   choices=list(PIPELINE_STAGE_CHOICES),
                   help="host->device staging thread: 'host' prepares "
                        "assembled batches ahead; 'device' additionally "
                        "jax.device_put's batch N+1 — committed to the "
                        "--strategy sharded layout — while the device "
                        "runs step N, so dispatch stops paying the h2d "
                        "copy; 'off' = feed inline (default)")


def build_feed(dataset, args, strategy=None):
    """Wrap a training DataSet in the async pipeline stack per
    ``(--dataWorkers, --prefetchDepth, --stage)``. Returns
    ``(dataset, provenance|None)`` — provenance is what perf stamps as
    the ``pipeline`` JSON column (also stashed on ``args._pipeline``)."""
    workers = int(getattr(args, "dataWorkers", 0) or 0)
    depth = int(getattr(args, "prefetchDepth", 2) or 2)
    stage = getattr(args, "stage", None) or "off"
    if workers <= 0 and stage == "off":
        args._pipeline = None
        return dataset, None
    if (stage == "device"
            and int(getattr(args, "stepsPerDispatch", 1) or 1) > 1):
        # the K-step chunk path restacks its K batches host-side, which
        # would immediately undo (and pay for) the device commit
        logging.getLogger(__name__).warning(
            "--stage device assumes one batch per dispatch; "
            "--stepsPerDispatch > 1 restacks batches host-side — "
            "downgrading to --stage host")
        stage = "host"
    from bigdl_tpu.dataset.pipeline import wrap_pipeline
    ds, prov = wrap_pipeline(dataset, workers=workers, depth=depth,
                             stage=stage, strategy=strategy,
                             seed=getattr(args, "seed", 0))
    args._pipeline = prov
    return ds, prov


class ObsState:
    """What install_observability wired up for this process: whether
    span tracing is on, the capture controller (--traceSteps/SIGUSR2/
    touch-file), the live metrics listener, and where artifacts land.
    ``finalize()`` is idempotent — the perf harness calls it before
    stamping its JSON line, the training path after optimize()."""

    def __init__(self, enabled: bool, trace_dir: Optional[str],
                 capture, server):
        self.enabled = enabled
        self.trace_dir = trace_dir
        self.capture = capture
        self.server = server
        # HBM attribution context (ISSUE 12): the harness installs its
        # static memory plan post-compile and a live sampler; the perf
        # JSON mem columns read from here
        self.mem_plan: Optional[dict] = None
        self.mem_sampler = None
        self._final: Optional[dict] = None

    def finalize(self) -> dict:
        """Close any open capture window and export the span timeline;
        returns ``{trace_json, span_events, captures}`` (present keys
        only)."""
        if self._final is not None:
            return self._final
        from bigdl_tpu import obs
        info: dict = {}
        if self.capture is not None:
            self.capture.finish()
            ann = self.capture.annotation()
            if ann:
                info["captures"] = ann
        tracer = obs.get_tracer()
        if tracer is not None and self.trace_dir:
            path = os.path.join(self.trace_dir, "spans.trace.json")
            try:
                n = tracer.export_chrome_trace(path)
            except OSError as e:
                logging.getLogger(__name__).warning(
                    "obs: span export to %s failed: %s", path, e)
            else:
                info["trace_json"] = path
                info["span_events"] = n
                print(f"obs: wrote {n} span(s) to {path}", flush=True)
        if self.server is not None:
            # the bound (possibly auto-picked) port rides in the obs
            # annotation so a log reader can find the scrape endpoint
            info["metrics_port"] = self.server.port
            info["metrics_url"] = self.server.url
        self._final = info
        return info


def install_observability(args) -> Optional[ObsState]:
    """Activate the --obs/--traceDir/--traceSteps/--metricsPort surface
    (no-op returning None when none are set). --traceDir implies span
    tracing; --traceSteps needs --traceDir (captures need a home). The
    state is also stashed on ``args`` for downstream wiring."""
    obs_flag = getattr(args, "obs", False)
    trace_dir = getattr(args, "traceDir", None)
    trace_steps = getattr(args, "traceSteps", None)
    port = getattr(args, "metricsPort", None)
    if not (obs_flag or trace_dir or trace_steps or port is not None):
        return None
    if trace_steps and not trace_dir:
        raise SystemExit("--traceSteps needs --traceDir DIR (somewhere "
                         "for the capture windows to land)")
    from bigdl_tpu import obs
    enabled = bool(obs_flag or trace_dir)
    if enabled and not obs.enabled():
        obs.enable()
    capture = None
    if trace_dir:
        try:
            capture = obs.CaptureController(trace_dir,
                                            trace_steps=trace_steps)
        except ValueError as e:
            raise SystemExit(str(e))
        # arm the OOM post-mortem (ISSUE 12): a RESOURCE_EXHAUSTED
        # anywhere in this process now has a home for its MemoryReport
        obs.memory.install(trace_dir=trace_dir)
    server = None
    if port is not None:
        # an explicit port the user asked for must bind or exit cleanly;
        # 0 auto-picks (the MetricsServer resolves the ephemeral port)
        server = obs.start_metrics_server(obs.get_registry(), port=port,
                                          strict=(port != 0))
    state = ObsState(enabled, trace_dir, capture, server)
    args._obs = state
    return state


def install_fault_plan(args) -> None:
    """Activate --faultPlan process-wide (BIGDL_FAULT_LOG names a JSONL
    file every fired fault is appended to — written before process-fatal
    kinds act, so chaos harnesses can audit post-mortem)."""
    spec = getattr(args, "faultPlan", None)
    if not spec:
        return
    from bigdl_tpu.resilience.faults import install_plan, parse_plan
    try:
        plan = parse_plan(spec)
    except ValueError as e:
        raise SystemExit(f"--faultPlan: {e}")
    install_plan(plan, log_path=os.environ.get("BIGDL_FAULT_LOG"))
    logging.getLogger(__name__).info("fault plan installed: %r", plan)


def run_optimize(make_optimizer, args):
    """``optimize()`` with optional supervision (--supervise): each
    retry builds a FRESH Optimizer (the failed one may hold torn state)
    and resumes from the newest checksum-valid snapshot in
    --checkpoint, replaying the exact rng/batch stream of an
    uninterrupted run (the PR 2 step-equivalence contract)."""
    obs_state = getattr(args, "_obs", None)

    def _make():
        opt = make_optimizer()
        if obs_state is not None and obs_state.capture is not None:
            opt.set_capture(obs_state.capture)
        return opt

    budget = getattr(args, "supervise", None)
    elastic = getattr(args, "elastic", None)
    if budget is None and elastic is None:
        try:
            return _make().optimize()
        finally:
            if obs_state is not None:
                obs_state.finalize()
    from bigdl_tpu.resilience.supervisor import RetryPolicy, Supervisor
    ckpt_dir = getattr(args, "checkpoint", None)
    policy = RetryPolicy(budget=int(budget if budget is not None else 5),
                         seed=getattr(args, "seed", 0))
    if elastic is not None:
        # device loss becomes retryable: each retry's make_optimizer()
        # re-probes healthy_devices() through build_strategy, so the
        # fresh Optimizer is born on the surviving-count mesh with its
        # grad-comm bucket bound re-resolved for the new n_devices
        from bigdl_tpu.resilience.elastic import ElasticSupervisor
        sup = ElasticSupervisor(policy, batch_policy=elastic,
                                min_devices=getattr(args, "minDevices", 1))
    else:
        sup = Supervisor(policy)

    def attempt(n):
        t0 = time.perf_counter()
        if elastic is not None:
            sup.probe()  # SupervisorGaveUp below --minDevices
        opt = _make()
        if n > 0 and ckpt_dir:
            # resume() is a no-op on an empty dir, picks the newest
            # checksum-valid pair otherwise, and falls back to a
            # model-only blob when the kill landed mid-checkpoint (its
            # orphan allowance lets the retry overwrite torn names)
            opt.resume(ckpt_dir)
        if elastic is not None:
            strat = getattr(opt, "strategy", None)
            mesh = getattr(strat, "mesh", None)
            if mesh is not None:
                n_dev = int(mesh.devices.size)
            else:
                import jax
                n_dev = len(jax.devices())
            sup.observe_topology(
                n_dev, restore_ms=((time.perf_counter() - t0) * 1000.0
                                   if n > 0 else None))
        return opt.optimize()

    try:
        result = sup.run(attempt)
    finally:
        if obs_state is not None:
            obs_state.finalize()
    ann = sup.annotation()
    if ann["retries"] or ann["events"]:
        logging.getLogger(__name__).info(
            "supervisor: %s", json.dumps(ann, sort_keys=True))
    return result


def run_preflight_lint(report, strict: bool = False):
    """Print one lint report; returns ``(exit_code, annotation)`` —
    exit_code 0 means proceed (the annotation is stamped into result
    JSON), nonzero means the caller should abort the launch (strict
    mode with error-severity findings)."""
    print(report.render(), flush=True)
    rc = report.exit_code(strict=strict)
    if rc:
        print(f"lint: {report.errors} error-severity finding(s) — "
              "refusing to launch (--lint=strict)", flush=True)
        return rc, None
    return 0, report.annotation()


def apply_fused_bn(model, mode: Optional[str]):
    """Install the --fusedBN choice on a built model (no-op for
    None/'off'). Returns the model."""
    if mode and mode != "off":
        from bigdl_tpu.nn import set_bn_fused
        set_bn_fused(model, mode)
    return model


def compile_cache_dir() -> Optional[str]:
    """Resolve the persistent compile-cache dir: BIGDL_JAX_CACHE wins;
    a user-managed JAX_COMPILATION_CACHE_DIR is left to jax itself (None
    here = don't clobber it); otherwise a per-user cache path (not a
    world-shared /tmp name another uid could pre-own or poison)."""
    explicit = os.environ.get("BIGDL_JAX_CACHE")
    if explicit:
        return explicit
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return None
    return os.path.join(os.path.expanduser("~"), ".cache", "bigdl_jax")


def enable_compile_cache() -> None:
    """Point jax at the persistent compile cache: tunnel windows are
    minutes long, so a re-run after a mid-window drop must not pay the
    multi-minute TPU compile again (window-1 evidence: the cache works
    under the axon backend)."""
    cache = compile_cache_dir()
    if cache is None:
        return
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache)
    except Exception:
        pass  # older jax or read-only fs: compile as usual


def apply_platform(args) -> None:
    """Honor --platform BEFORE any jax backend init. Uses the config API,
    not JAX_PLATFORMS (the env-var spelling hangs the axon plugin at
    import in this environment). Also enables the persistent compile
    cache for every CLI."""
    platform = getattr(args, "platform", None)
    if platform:
        import jax

        jax.config.update("jax_platforms", platform)
    enable_compile_cache()
    install_fault_plan(args)  # --faultPlan (no-op when unset)
    install_observability(args)  # --obs family (no-op when unset)
    mode = getattr(args, "autotune", None)
    if mode:
        from bigdl_tpu import tuning
        try:
            tuning.set_mode(mode)
        except ValueError as e:
            raise SystemExit(str(e))
    geom = getattr(args, "convGeom", None)
    if geom:
        # per-geometry decision file (apply_conv_probe.py --geom) — the
        # stem's wgrad can run NCHW while the 3x3 stages stay NHWC and
        # 1x1/s1 convs may run as GEMM; an explicit --convLayout below
        # still wins at lookup time
        from bigdl_tpu.ops.conv2d import install_geom_file
        try:
            n = install_geom_file(geom)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            raise SystemExit(f"--convGeom {geom}: {e}")
        logging.getLogger(__name__).info(
            "installed %d per-geometry conv layout decisions from %s",
            n, geom)
    spec = getattr(args, "convLayout", None)
    if spec:
        # explicit per-pass conv layouts (or 'auto'/'default') — wins
        # over the measured-decision auto-install the Optimizer does,
        # over --convGeom decisions and over the autotuner
        from bigdl_tpu.ops.conv2d import install_layout_spec
        try:
            install_layout_spec(spec)
        except ValueError as e:
            raise SystemExit(str(e))


def add_train_args(p: argparse.ArgumentParser) -> None:
    """The reference's common knobs (-f, -b, --learningRate, --maxEpoch,
    --checkpoint, --model/--state resume; models/lenet/Utils.scala flags)."""
    _add_platform_arg(p)
    p.add_argument("-f", "--folder", default="./", help="data folder")
    p.add_argument("-b", "--batchSize", type=int, default=128)
    p.add_argument("--learningRate", type=float, default=0.05)
    p.add_argument("--learningRateDecay", type=float, default=0.0)
    p.add_argument("--weightDecay", type=float, default=0.0)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--maxEpoch", type=int, default=5)
    p.add_argument("--checkpoint", default=None,
                   help="dir for model.<n>/state.<n> snapshots")
    p.add_argument("--stepsPerDispatch", type=int, default=1,
                   help="scan K optimizer steps over K prefetched batches "
                        "inside one jitted program — amortizes the "
                        "~2.5-3.5 ms per-dispatch overhead of the "
                        "tunneled runtime (+1.6%% ResNet-50 throughput "
                        "at K=10, PERF.md §8.2). Update math and RNG "
                        "sequence identical to K=1; iteration-counted "
                        "triggers fire at the next dispatch boundary. "
                        "Single-device only")
    p.add_argument("--convLayout", default=None,
                   metavar="FWD,DGRAD,WGRAD",
                   help="per-pass conv activation layouts (NHWC|NCHW|"
                        "GEMM each, or 'auto'/'default'; GEMM = "
                        "dot_general for eligible 1x1/stride-1 convs, "
                        "exact-parity fallback elsewhere). Unset = "
                        "'auto': the measured probe decision shipped for "
                        "this device kind (ops/conv2d.MEASURED_DECISIONS, "
                        "+1.1%% ResNet-50 train throughput on TPU v5 "
                        "lite), no-op on unmeasured devices; 'default' "
                        "forces all-NHWC. Wins over --convGeom and the "
                        "autotuner")
    p.add_argument("--convGeom", default=None, metavar="FILE",
                   help="per-conv-geometry layout decision JSON "
                        "(scripts/apply_conv_probe.py --geom): decisions "
                        "keyed by (kh, kw, stride, cin, cout, groups, "
                        "dilation, dtype), each pass independently "
                        "NHWC/NCHW/GEMM")
    p.add_argument("--model", default=None,
                   help="checkpoint dir to resume model from")
    p.add_argument("--overWriteCheckpoint", action="store_true")
    p.add_argument("--keepCheckpoints", type=int, default=None,
                   metavar="K",
                   help="keep only the newest K checkpoint snapshots "
                        "(GC after each write; the newest checksum-"
                        "VALID pair is never deleted)")
    add_resilience_args(p)
    add_obs_args(p)
    add_pipeline_args(p)
    p.add_argument("--dataParallel", action="store_true",
                   help="shard the batch over all visible devices")
    add_strategy_arg(p)
    add_grad_comm_args(p)
    add_autotune_arg(p)
    add_fused_bn_arg(p)
    add_lint_arg(p)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--logEvery", type=int, default=10)
    p.add_argument("--summary", default=None, metavar="DIR",
                   help="append train/val JSONL curves to DIR")
    p.add_argument("--optimMethod", default="sgd",
                   choices=["sgd", "adam", "adamw", "adagrad", "rmsprop",
                            "lars", "lamb"],
                   help="optimizer (sgd keeps the reference defaults; "
                        "weightDecay/momentum apply where meaningful)")


def add_test_args(p: argparse.ArgumentParser) -> None:
    _add_platform_arg(p)
    p.add_argument("-f", "--folder", default="./")
    p.add_argument("-b", "--batchSize", type=int, default=128)
    p.add_argument("--model", required=True, help="checkpoint dir or file")


def setup_logging() -> None:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s - %(message)s")


# the --strategy surface (ISSUE 8): the five parallelism families the
# MULTICHIP_r05 dryruns validate, now reachable from perf/bench/training
# instead of living only in __graft_entry__.py
STRATEGY_CHOICES = ("dp", "tp", "sp", "pp", "ep")


def add_strategy_arg(p: argparse.ArgumentParser) -> None:
    p.add_argument("--strategy", default=None, metavar="NAME[:K]",
                   help="multi-device training strategy over every "
                        "visible device (bigdl_tpu.parallel): dp = data "
                        "parallel (ZeRO-1 sharded optimizer state), tp = "
                        "dp x Megatron tensor parallel, sp = dp x ring-"
                        "attention sequence parallel (transformer_lm* "
                        "models), pp = GPipe pipeline x dp "
                        "(transformer_lm* models), ep = expert-parallel "
                        "MoE. Optional :K sizes the non-data axis (e.g. "
                        "tp:4 = 4-way model parallel, pp:2 = 2 stages); "
                        "defaults mirror the MULTICHIP_r05 dryrun "
                        "shapes. CPU-testable end to end with XLA_FLAGS="
                        "--xla_force_host_platform_device_count=8. "
                        "Replaces the deprecated --dataParallel "
                        "(still accepted as an alias for 'dp'). Mesh "
                        "topology and device count are stamped into "
                        "every result JSON line")


def parse_strategy_spec(spec: Optional[str]):
    """``"name[:K]"`` -> ``(name, k|None)``; SystemExit on junk (the
    clean-CLI-validation contract, ADVICE r5 #5)."""
    if not spec:
        return None, None
    name, _, k = str(spec).partition(":")
    if name not in STRATEGY_CHOICES:
        raise SystemExit(f"--strategy {spec!r}: unknown strategy "
                         f"{name!r}; choose from {list(STRATEGY_CHOICES)}"
                         " (optionally NAME:K to size the non-data axis)")
    if not k:
        return name, None
    try:
        kk = int(k)
    except ValueError:
        raise SystemExit(f"--strategy {spec!r}: K must be an integer")
    if kk < 1:
        raise SystemExit(f"--strategy {spec!r}: K must be >= 1")
    return name, kk


def resolve_strategy(args):
    """The run's effective ``(strategy_name, k|None)`` — ``--strategy``
    wins; the historical ``--dataParallel`` flag is kept as a deprecated
    alias for ``dp``."""
    name, k = parse_strategy_spec(getattr(args, "strategy", None))
    if name is not None:
        return name, k
    if getattr(args, "dataParallel", False):
        logging.getLogger(__name__).warning(
            "--dataParallel is deprecated; use --strategy dp")
        return "dp", None
    return None, None


def check_strategy_dispatch(steps: int, flag: str = "--stepsPerDispatch"):
    """The PR 1 validation contract: multi-step dispatch amortization is
    single-device by construction and refuses (clean SystemExit) to
    combine with a multi-device strategy — perf's old hidden
    data_parallel branch silently ignored this."""
    if steps and int(steps) > 1:
        raise SystemExit(
            f"{flag} > 1 is a single-device dispatch amortization (the "
            "stepsPerDispatch contract); it cannot be combined with a "
            "multi-device --strategy/--dataParallel (whose runtime "
            "pipelines dispatch already)")


def strategy_mesh_axes(name: str, n_devices: int, k: Optional[int] = None
                       ) -> dict:
    """Axis layout of one strategy over ``n_devices`` (the MULTICHIP_r05
    dryrun shapes). ``k`` sizes the non-data axis; defaults: tp/sp split
    devices 2-way on data (n>=4), pp uses 4 stages (n%4==0) else 2, ep
    puts every device on the expert axis."""
    n = int(n_devices)
    if name == "dp":
        return {"data": n}
    if name in ("tp", "sp"):
        axis = "model" if name == "tp" else "seq"
        kk = k or (n // 2 if n >= 4 else n)
        if n % kk:
            raise SystemExit(f"--strategy {name}:{kk} needs the {axis} "
                             f"axis to divide {n} devices")
        return {"data": n // kk, axis: kk}
    if name == "pp":
        kk = k or (4 if n % 4 == 0 and n >= 4 else 2)
        if n % kk:
            raise SystemExit(f"--strategy pp:{kk} needs the stage count "
                             f"to divide {n} devices")
        return {"pipe": kk, "data": n // kk}
    if name == "ep":
        return {"expert": k or n}
    raise SystemExit(f"unknown strategy {name!r}")


# the serving --strategy surface (ISSUE 16): two orthogonal axes behind
# one front door — ``tp[:K]`` shards the model over K chips, ``dp[:N]``
# runs N independent engine replicas, ``dp:N+tp:K`` composes them (N
# replicas, each tensor-parallel over K chips). Unlike the training
# grammar above there is no implicit data axis: serving devices are
# partitioned, not meshed globally.
SERVING_STRATEGY_CHOICES = ("dp", "tp")


def parse_serving_strategy(spec: Optional[str], n_devices: int):
    """``"tp[:K] | dp[:N] | dp:N+tp:K"`` -> ``(replicas, tp_k)``.

    Defaults when the axis size is omitted: ``tp`` -> all visible
    devices on the model axis, ``dp`` -> one single-device replica per
    visible device. Validates ``replicas * tp_k <= n_devices`` with the
    XLA_FLAGS recipe in the error (the clean-CLI-validation contract).
    ``None``/empty spec -> ``(1, 1)`` (the single-chip path)."""
    n = int(n_devices)
    if not spec:
        return 1, 1
    replicas: Optional[int] = None
    tp_k: Optional[int] = None
    seen_dp = seen_tp = False
    for part in str(spec).split("+"):
        name, _, k = part.strip().partition(":")
        if name not in SERVING_STRATEGY_CHOICES:
            raise SystemExit(
                f"serve --strategy {spec!r}: unknown axis {name!r}; the "
                f"serving grammar is tp[:K], dp[:N], or dp:N+tp:K")
        try:
            kk = int(k) if k else None
        except ValueError:
            raise SystemExit(
                f"serve --strategy {spec!r}: axis size in {part!r} must "
                "be an integer")
        if kk is not None and kk < 1:
            raise SystemExit(
                f"serve --strategy {spec!r}: axis size in {part!r} must "
                "be >= 1")
        if name == "dp":
            if seen_dp:
                raise SystemExit(
                    f"serve --strategy {spec!r}: dp given twice")
            seen_dp, replicas = True, kk
        else:
            if seen_tp:
                raise SystemExit(
                    f"serve --strategy {spec!r}: tp given twice")
            seen_tp, tp_k = True, kk
    # resolve omitted axis sizes: a lone axis claims every visible
    # device; in the composed form the omitted one takes what the
    # explicit one leaves over
    if seen_tp and tp_k is None:
        tp_k = max(n // (replicas or 1), 1) if seen_dp else max(n, 1)
    if seen_dp and replicas is None:
        replicas = max(n // (tp_k or 1), 1) if seen_tp else max(n, 1)
    replicas, tp_k = replicas or 1, tp_k or 1
    if replicas * tp_k > n:
        need = replicas * tp_k
        shape = (f"{replicas} replicas x {tp_k}-way tp"
                 if seen_dp and seen_tp else
                 f"{tp_k}-way tp" if seen_tp else
                 f"one device per replica x {replicas} replicas")
        raise SystemExit(
            f"serve --strategy {spec!r} needs {need} devices ({shape}) "
            f"but only {n} are visible; on CPU export XLA_FLAGS="
            f"--xla_force_host_platform_device_count={need} to fake "
            "them")
    return replicas, tp_k


# the --gradCompress surface (ISSUE 10): the wire dtypes of the
# compressed gradient all-reduce, optionally error-compensated (must
# mirror parallel/grad_comm.COMPRESS_MODES — asserted in tests, not
# imported here, so argparse setup never pulls the jax-importing
# parallel package)
GRAD_COMPRESS_CHOICES = ("off", "bf16", "fp16", "bf16+ec", "fp16+ec")


def add_grad_comm_args(p: argparse.ArgumentParser) -> None:
    p.add_argument("--gradCompress", default="off",
                   choices=list(GRAD_COMPRESS_CHOICES),
                   help="compress the gradient all-reduce "
                        "(bigdl_tpu.parallel.grad_comm, the reference's "
                        "FP16CompressedTensor codec): gradients flatten "
                        "into size-bounded dense buckets, cross the wire "
                        "as bf16/fp16 (half the bytes), decompress to "
                        "f32 after; '+ec' adds the local rounding "
                        "residual back so optimizer math sees the exact "
                        "f32 gradient. Active under a multi-device "
                        "--strategy (dp/tp); 'off' is bit-identical to "
                        "the uncompressed step. Stamped into result "
                        "JSON as grad_compress/grad_buckets")
    p.add_argument("--gradBuckets", default="auto", metavar="auto|N",
                   help="dense-bucket bound for --gradCompress: 'auto' = "
                        "the tuned grad_comm decision when --autotune is "
                        "on, else the shipped 4 MiB default; an integer "
                        "N pins the bound to N MiB")


def make_grad_comm(args):
    """``(--gradCompress, --gradBuckets)`` -> GradCommConfig (None when
    the surface is untouched); SystemExit on junk (the clean-CLI-
    validation contract)."""
    compress = getattr(args, "gradCompress", None)
    buckets = getattr(args, "gradBuckets", None)
    if (compress or "off") == "off" and (buckets in (None, "auto")):
        return None
    from bigdl_tpu.parallel.grad_comm import make_config
    try:
        return make_config(compress, buckets)
    except ValueError as e:
        raise SystemExit(str(e))


def build_strategy(args, model=None):
    """Resolve ``--strategy``/``--dataParallel`` into a strategy object
    consumed by the Optimizer (the reference's Engine.init(node, cores)
    + DistriOptimizer path). Owns the validation the old perf branch
    skipped: the stepsPerDispatch/innerSteps x strategy SystemExit
    contract fires here, BEFORE any mesh is built. Returns None
    single-device (the deprecated alias degrades silently, an explicit
    --strategy exits with the XLA_FLAGS recipe). dp/tp build here;
    sp/pp/ep need harness-side model composition (ring attention /
    pipeline stack / MoE) and are wired in ``cli/perf.py``."""
    name, k = resolve_strategy(args)
    elastic = getattr(args, "elastic", None)
    if name is None:
        if elastic is not None:
            raise SystemExit("--elastic needs --strategy dp (elastic "
                             "reshape is a data-parallel contract)")
        return None
    import jax

    if elastic is not None and name != "dp":
        raise SystemExit(f"--elastic composes with --strategy dp only "
                         f"(got {name}); tp/sp/pp/ep meshes cannot "
                         "re-form at arbitrary surviving counts")
    # elastic runs build their mesh from the SURVIVING roster: after a
    # kill_device fault the retry's fresh strategy lands on fewer devices
    devices = None
    if elastic is not None:
        from bigdl_tpu.resilience.faults import healthy_devices
        devices = healthy_devices()
        n = len(devices)
    else:
        n = len(jax.devices())
    if n <= 1:
        if getattr(args, "strategy", None):
            raise SystemExit(
                f"--strategy {name} needs more than one device; off-chip "
                "set XLA_FLAGS=--xla_force_host_platform_device_count=8 "
                "(the MULTICHIP dryrun recipe)")
        return None  # deprecated --dataParallel alias: historical no-op
    check_strategy_dispatch(getattr(args, "stepsPerDispatch", 1) or 1)
    check_strategy_dispatch(getattr(args, "innerSteps", 1) or 1,
                            "--innerSteps")
    from bigdl_tpu.parallel import DataParallel, TensorParallel, make_mesh

    grad_comm = make_grad_comm(args)
    axes = strategy_mesh_axes(name, n, k)
    if name == "dp":
        if elastic is not None:
            from bigdl_tpu.resilience.elastic import ElasticDataParallel
            return ElasticDataParallel(make_mesh(axes, devices),
                                       batch_policy=elastic,
                                       grad_comm=grad_comm)
        return DataParallel(make_mesh(axes), grad_comm=grad_comm)
    if name == "tp":
        if model is None:
            raise SystemExit("--strategy tp needs the model to derive "
                             "its Megatron sharding rules")
        t = TensorParallel(make_mesh(axes), model)
        # TensorParallel's ctor is (mesh, model); it inherits the
        # reduce_grads entry point, so the config rides the attribute
        t.grad_comm = grad_comm
        return t
    raise SystemExit(f"--strategy {name} composes with the model/step "
                     "structure and is wired through the perf harness "
                     "(bigdl-tpu perf --strategy {sp,pp,ep}); the "
                     "training CLIs support dp/tp")


def build_optimizer(model, dataset, criterion, args, schedule=None,
                    optim_method=None):
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.optim.schedules import Default

    # --fusedBN lever for every training CLI (the Optimizer auto-unfuses
    # with a warning under a multi-device strategy)
    apply_fused_bn(model, getattr(args, "fusedBN", None))

    if optim_method is None:
        sched = (schedule if schedule is not None
                 else Default(args.learningRateDecay))
        name = getattr(args, "optimMethod", "sgd")
        if name == "sgd":
            optim_method = SGD(
                learning_rate=args.learningRate,
                weight_decay=args.weightDecay,
                momentum=args.momentum, schedule=sched)
        else:
            from bigdl_tpu.optim import (Adagrad, Adam, AdamW, LAMB, LARS,
                                         RMSprop)
            lr = args.learningRate
            wd = args.weightDecay
            optim_method = {
                "adam": lambda: Adam(learning_rate=lr, schedule=sched),
                "adamw": lambda: AdamW(learning_rate=lr, weight_decay=wd,
                                       schedule=sched),
                # Adagrad/RMSprop carry their own decay knobs, no
                # schedule parameter (matching the reference's surface)
                "adagrad": lambda: Adagrad(
                    learning_rate=lr, weight_decay=wd,
                    lr_decay=args.learningRateDecay),
                "rmsprop": lambda: RMSprop(learning_rate=lr),
                "lars": lambda: LARS(learning_rate=lr, weight_decay=wd,
                                     momentum=args.momentum,
                                     schedule=sched),
                "lamb": lambda: LAMB(learning_rate=lr, weight_decay=wd,
                                     schedule=sched),
            }[name]()
    # build_strategy owns the stepsPerDispatch x strategy SystemExit
    # contract (ADVICE r5 #5) — one validator shared with perf (ISSUE 8)
    strategy = build_strategy(args, model=model)
    k = int(getattr(args, "stepsPerDispatch", 1) or 1)
    # --dataWorkers/--prefetchDepth/--stage: the async pipeline stack
    # wraps the dataset BEFORE the Optimizer sees it; built fresh per
    # supervised/elastic retry (run_optimize re-invokes make_optimizer),
    # so device staging always commits to the current attempt's mesh
    dataset, _ = build_feed(dataset, args, strategy=strategy)
    opt = Optimizer(model, dataset, criterion,
                    optim_method=optim_method,
                    end_when=Trigger.max_epoch(args.maxEpoch),
                    strategy=strategy, seed=args.seed,
                    log_every=args.logEvery,
                    steps_per_dispatch=k)
    if args.checkpoint:
        os.makedirs(args.checkpoint, exist_ok=True)
        opt.set_checkpoint(Trigger.every_epoch(), args.checkpoint,
                           overwrite=getattr(args, "overWriteCheckpoint",
                                             False),
                           keep_last=getattr(args, "keepCheckpoints",
                                             None))
    if args.model:
        opt.resume(args.model)
    if getattr(args, "summary", None):
        opt.set_summary(args.summary)
    lint_mode = getattr(args, "lint", None)
    if lint_mode:
        # pre-flight static analysis of the REAL step this Optimizer
        # will compile (bigdl_tpu.analysis.preflight_optimizer) —
        # module rules always, the jaxpr pass when the dataset exposes
        # its batch geometry; strict aborts before any compile
        from bigdl_tpu.analysis import preflight_optimizer
        rc, _ = run_preflight_lint(preflight_optimizer(opt),
                                   strict=(lint_mode == "strict"))
        if rc:
            raise SystemExit(rc)
    return opt


# ---------------------------------------------------------------------
# the ResolvedConfig spine (ISSUE 19 satellite, ROADMAP item 5): the
# mirrored flag families every CLI re-parses (--strategy/--gradCompress/
# --gradBuckets/--quantize/--speculate/--fusedBN/--convLayout/--convGeom/
# --autotune) resolved ONCE into a typed object that cli/lint.py and
# every --lint preflight hand to the analyzer — no per-CLI re-wiring.
import dataclasses


@dataclasses.dataclass(frozen=True)
class ResolvedConfig:
    """One run configuration, resolved from the shared flag surface.

    ``mesh_axes`` is the declared mesh (axis -> size) the strategy
    implies over ``n_devices`` — for the lint CLI with no real devices
    the virtual defaults below size it, so every multichip surface
    lints on a 1-CPU box."""

    model: str
    batch: int = 32
    seq: Optional[int] = None
    classes: int = 1000
    dtype: str = "bfloat16"
    fused_bn: Optional[str] = None
    conv_layout: Optional[str] = None
    conv_geom: Optional[str] = None
    autotune: str = "off"
    strategy: Optional[str] = None
    strategy_k: Optional[int] = None
    n_devices: int = 1
    mesh_axes: tuple = ()            # ((axis, size), ...) — hashable
    grad_compress: str = "off"
    grad_buckets: str = "auto"
    quantize: Optional[str] = None
    speculate: int = 0
    kv_page_tokens: Optional[int] = None
    slots: int = 4
    lint_mode: Optional[str] = None
    trace: bool = True
    # serve/fleet topology (ISSUE 20 satellite): the serving grammar's
    # resolution (dp replicas x tp shards) and the fleet width, owned
    # here so serve, fleet, and worker never re-mirror the parse
    serving_replicas: int = 1
    serving_tp: int = 1
    fleet_workers: int = 0

    @property
    def mesh(self) -> dict:
        return dict(self.mesh_axes)

    def make_grad_comm(self):
        """The GradCommConfig this run would build (None when the
        --gradCompress surface is untouched)."""
        if (self.grad_compress or "off") == "off" \
                and self.grad_buckets in (None, "auto"):
            return None
        from bigdl_tpu.parallel.grad_comm import make_config
        try:
            return make_config(self.grad_compress, self.grad_buckets)
        except ValueError as e:
            raise SystemExit(str(e))

    def describe(self) -> dict:
        """Provenance dict (result-JSON / lint-report annotation)."""
        out = {"model": self.model, "batch": self.batch}
        if self.strategy:
            out["strategy"] = (f"{self.strategy}:{self.strategy_k}"
                               if self.strategy_k else self.strategy)
            out["mesh"] = ",".join(f"{a}:{s}" for a, s in self.mesh_axes)
        if (self.grad_compress or "off") != "off":
            out["grad_compress"] = self.grad_compress
        if self.quantize:
            out["quantize"] = self.quantize
        if self.speculate:
            out["speculate"] = self.speculate
        if self.kv_page_tokens:
            out["kv_page_tokens"] = self.kv_page_tokens
        if self.serving_replicas > 1 or self.serving_tp > 1:
            out["serving_replicas"] = self.serving_replicas
            out["serving_tp"] = self.serving_tp
        if self.fleet_workers:
            out["fleet_workers"] = self.fleet_workers
        return out


def _virtual_mesh_devices(name: str, k: Optional[int]) -> tuple:
    """(n_devices, k) sized for an abstract lint with no real devices:
    enough virtual chips that the strategy's default shape exists."""
    if name == "dp":
        return 8, None
    if name in ("tp", "sp"):
        kk = k or 4
        return 2 * kk, kk
    if name == "pp":
        kk = k or 2
        return 2 * kk, kk
    if name == "ep":
        return (k or 8), (k or 8)
    raise SystemExit(f"unknown strategy {name!r}")


def resolve_lint_config(args, *, n_devices: Optional[int] = None
                        ) -> ResolvedConfig:
    """Resolve the shared flag families on ``args`` into one
    :class:`ResolvedConfig`. ``n_devices=None`` (the lint CLI: no real
    mesh) sizes the strategy over virtual devices —
    ``AbstractMesh``-traced, so nothing is allocated; a preflight on a
    real run passes its actual device count."""
    name, k = parse_strategy_spec(getattr(args, "strategy", None))
    mesh_axes: tuple = ()
    n = int(n_devices or 1)
    if name is not None:
        if n_devices is None:
            n, k = _virtual_mesh_devices(name, k)
        axes = strategy_mesh_axes(name, n, k)
        mesh_axes = tuple((str(a), int(s)) for a, s in axes.items())
    quantize = getattr(args, "quantize", None)
    if quantize:
        from bigdl_tpu.serving.quant import parse_quantize
        try:
            parse_quantize(quantize)  # validate the spelling up front
        except ValueError as e:
            raise SystemExit(f"--quantize {quantize!r}: {e}")
    return ResolvedConfig(
        model=getattr(args, "model", None) or "",
        batch=int(getattr(args, "batchSize", 32) or 32),
        seq=getattr(args, "seq", None),
        classes=int(getattr(args, "classes", 1000) or 1000),
        dtype=("float32" if getattr(args, "f32", False) else "bfloat16"),
        fused_bn=getattr(args, "fusedBN", None),
        conv_layout=getattr(args, "convLayout", None),
        conv_geom=getattr(args, "convGeom", None),
        autotune=getattr(args, "autotune", "off") or "off",
        strategy=name, strategy_k=k, n_devices=n, mesh_axes=mesh_axes,
        grad_compress=getattr(args, "gradCompress", "off") or "off",
        grad_buckets=getattr(args, "gradBuckets", "auto") or "auto",
        quantize=quantize,
        speculate=int(getattr(args, "speculate", 0) or 0),
        # serve spells --kvPageTokens 'auto' too; lint needs a number
        kv_page_tokens=(int(kvp) if (kvp := getattr(
            args, "kvPageTokens", None)) and str(kvp).lstrip("-").isdigit()
            else None),
        slots=int(getattr(args, "slots", 4) or 4),
        lint_mode=getattr(args, "lint", None),
        trace=not getattr(args, "no_trace", False))


def _virtual_serving_devices(spec: Optional[str]) -> int:
    """Device count for resolving a serving strategy ABSTRACTLY — in a
    process with no accelerator client (the fleet router) or no devices
    at all (lint). Big enough that any explicit ``dp:N+tp:K`` shape
    exists; omitted axis sizes then default over the same count a CPU
    smoke run would fake with XLA_FLAGS."""
    need = 1
    for part in str(spec or "").split("+"):
        _, _, k = part.strip().partition(":")
        if k and str(k).lstrip("-").isdigit():
            need *= max(int(k), 1)
    return max(8, need)


def resolve_serve_config(args, *, n_devices: Optional[int] = None
                         ) -> ResolvedConfig:
    """The serve/fleet half of the ResolvedConfig spine (ISSUE 20
    satellite): resolve the serving flag surface — topology via the
    SERVING grammar (``tp[:K] | dp[:N] | dp:N+tp:K``, not the training
    grammar), quantize/speculate modes, fleet width — ONCE, so the
    serve CLI, the fleet router, and every worker agree on one parse.

    ``n_devices=None`` resolves abstractly over virtual devices: the
    router process calls this before any worker boots (catching a bad
    --strategy/--quantize/--speculate without paying K engine compiles)
    and must never initialize jax itself."""
    spec = getattr(args, "strategy", None)
    n = int(n_devices) if n_devices is not None \
        else _virtual_serving_devices(spec)
    replicas, tp_k = parse_serving_strategy(spec, n)
    quantize = getattr(args, "quantize", None)
    if quantize == "off":  # serve spells the default as the string off
        quantize = None
    if quantize:
        from bigdl_tpu.serving.quant import parse_quantize
        try:
            parse_quantize(quantize)
        except ValueError as e:
            raise SystemExit(f"--quantize {quantize!r}: {e}")
    speculate = int(getattr(args, "speculate", 0) or 0)
    if speculate < 0:
        raise SystemExit(f"--speculate {speculate}: draft length must "
                         "be >= 0")
    fleet = int(getattr(args, "fleet", 0) or 0)
    if fleet < 0:
        raise SystemExit(f"--fleet {fleet}: worker count must be >= 0")
    mesh_axes: tuple = ()
    if tp_k > 1:
        mesh_axes = (("model", int(tp_k)),)
    return ResolvedConfig(
        model=getattr(args, "model", None) or "",
        batch=int(getattr(args, "batchSize", 32) or 32),
        seq=getattr(args, "seq", None),
        dtype=("float32" if getattr(args, "f32", False) else "bfloat16"),
        strategy=spec or None,
        n_devices=n, mesh_axes=mesh_axes,
        quantize=quantize, speculate=speculate,
        kv_page_tokens=(int(kvp) if (kvp := getattr(
            args, "kvPageTokens", None)) and str(kvp).lstrip("-").isdigit()
            else None),
        slots=int(getattr(args, "slots", 4) or 4),
        lint_mode=getattr(args, "lint", None),
        serving_replicas=int(replicas), serving_tp=int(tp_k),
        fleet_workers=fleet)


def load_trained(model, path: str):
    """Load params/mod_state from a checkpoint dir (newest model.<n>) or a
    single saved file (reference Module.load, nn/Module.scala:28)."""
    from bigdl_tpu.utils.file import load_pytree, latest_checkpoint

    if os.path.isdir(path):
        p = latest_checkpoint(path, "model.")
        if p is None:
            raise FileNotFoundError(f"no model.<n> checkpoint in {path}")
    else:
        p = path
    blob = load_pytree(p)
    return blob["params"], blob["mod_state"]


def evaluate(model, params, mod_state, dataset,
             methods: Optional[Sequence] = None):
    """Standalone evaluation (reference optim/Validator.scala +
    models/*/Test.scala)."""
    from bigdl_tpu.optim import Top1Accuracy
    from bigdl_tpu.optim.validator import build_eval_fn, run_evaluation

    methods = list(methods) if methods else [Top1Accuracy()]
    eval_fn = build_eval_fn(model, methods, None)
    results = run_evaluation(eval_fn, dataset, methods, params, mod_state,
                             None)
    for m, r in zip(methods, results):
        print(f"{m.name} is {r!r}")
    return results
