"""Imported-model validation (reference example/loadmodel/
ModelValidator.scala — loads a Caffe (.prototxt/.caffemodel), Torch (.t7)
or native checkpoint into the matching model builder and evaluates Top-1/
Top-5 on an ImageNet-style val folder).

    python -m bigdl_tpu.cli.loadmodel --modelType caffe \
        --model deploy.prototxt --weights bvlc.caffemodel \
        --modelName alexnet -f /data/imagenet
"""

from __future__ import annotations

import argparse

from bigdl_tpu.cli import common

_BUILDERS = {
    "alexnet": lambda n: _models().alexnet(n),
    "inception_v1": lambda n: _models().inception_v1_no_aux(n),
    "resnet50": lambda n: _models().resnet50(n),
    "vgg16": lambda n: _models().vgg16(n),
}


def _models():
    from bigdl_tpu import models
    return models


def load_into(model, model_type: str, model_path: str, weights: str | None):
    """Returns (params, mod_state) with imported weights copied in
    (reference Module.load/loadTorch/loadCaffe, nn/Module.scala:28-41)."""
    import jax

    params = model.init(jax.random.PRNGKey(0))
    mod_state = model.init_state()
    if model_type == "caffe":
        from bigdl_tpu.interop import load_caffe
        params = load_caffe(model, params, weights, prototxt_path=model_path)
    elif model_type == "torch":
        # whole-model import: the .t7 carries the graph; the module built
        # from --modelName is ignored (reference Module.loadTorch flow)
        raise RuntimeError("torch models are whole-model files; handled "
                           "in main() before builder construction")
    elif model_type == "bigdl":
        params, mod_state = common.load_trained(model, model_path)
    else:
        raise SystemExit(f"unknown modelType {model_type}")
    return params, mod_state


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu loadmodel")
    common._add_platform_arg(p)
    p.add_argument("--modelType", required=True,
                   choices=["caffe", "torch", "bigdl"])
    p.add_argument("--modelName", default=None, choices=sorted(_BUILDERS),
                   help="model builder (required for caffe/bigdl; torch "
                        ".t7 files carry the whole graph and ignore it)")
    p.add_argument("--model", required=True,
                   help="prototxt (caffe) / .t7 (torch) / checkpoint (bigdl)")
    p.add_argument("--weights", default=None, help=".caffemodel (caffe)")
    p.add_argument("-f", "--folder", required=True,
                   help="val folder: <class>/<imgs>")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--classNum", type=int, default=1000)
    p.add_argument("--imageSize", type=int, default=None,
                   help="val crop size (default: 227 for alexnet, else "
                        "224 — whole-model .t7 files need this when the "
                        "graph was built for another size)")
    args = p.parse_args(argv)
    common.apply_platform(args)

    from bigdl_tpu import nn  # noqa: F401  (models import side effects)
    from bigdl_tpu.dataset.folder import ImageFolderDataSet
    from bigdl_tpu.optim import Top1Accuracy, Top5Accuracy

    if args.modelType == "torch":
        # whole-model .t7: reconstruct the graph + weights directly
        # (reference Module.loadTorch, nn/Module.scala:32)
        from bigdl_tpu.interop import load_torch_module
        model, params, mod_state = load_torch_module(args.model)
    else:
        if args.modelName is None:
            raise SystemExit("--modelName is required for "
                             f"modelType={args.modelType}")
        model = _BUILDERS[args.modelName](args.classNum)
        params, mod_state = load_into(model, args.modelType, args.model,
                                      args.weights)
    # Caffe AlexNet crops to 227; the rest take 224
    if args.imageSize is not None:
        size = (args.imageSize, args.imageSize)
    else:
        size = (227, 227) if args.modelName == "alexnet" else (224, 224)
    from bigdl_tpu.dataset.folder import IMAGENET_MEAN, IMAGENET_STD
    val = ImageFolderDataSet(args.folder, args.batchSize, size=size,
                             mean=IMAGENET_MEAN, std=IMAGENET_STD)
    return common.evaluate(model, params, mod_state, val,
                           [Top1Accuracy(), Top5Accuracy()])


if __name__ == "__main__":
    main()
