"""`bigdl-tpu serve` — the online inference endpoint (ISSUE 5).

Serves a perf-zoo model (or a custom-dims transformer_lm) from a
training checkpoint over HTTP with dynamic micro-batching, bucketed
compiles, and (for LMs) continuous-batching KV-cache decode:

    bigdl-tpu serve lenet5 --model ckpt_dir --port 8000
    bigdl-tpu serve resnet50 --model ckpt_dir --fusedBN apply \
        --autotune cached --buckets 1,2,4,8,16,32
    bigdl-tpu serve transformer_lm --model ckpt_dir --slots 8 --bf16
    curl -d '{"tokens": [3, 1, 4], "max_new_tokens": 8}' \
        localhost:8000/generate

The config flags mirror the perf harness (`--fusedBN`, `--convLayout`,
`--convGeom`, `--autotune`, `--lint`) so the served program is the SAME
tuned program the benchmarks measured, and the resolved configuration is
stamped into every `/metrics` scrape (the perf-JSON provenance contract,
extended to serving).
"""

from __future__ import annotations

import argparse

from bigdl_tpu.cli import common


def _parse_buckets(spec: str):
    try:
        out = tuple(sorted({int(t) for t in spec.split(",") if t.strip()}))
        if not out or out[0] < 1:
            raise ValueError
        return out
    except ValueError:
        raise SystemExit(f"--buckets {spec!r}: expected a comma-separated "
                         f"list of positive ints, e.g. 1,2,4,8,16,32")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        "bigdl-tpu serve",
        description="online inference over HTTP (bigdl_tpu.serving): "
                    "bucketed compiles, dynamic micro-batching, KV-cache "
                    "decode for LMs, /metrics with config provenance")
    p.add_argument("model",
                   help="perf model-zoo name (see `bigdl-tpu perf`), e.g. "
                        "lenet5, resnet50, transformer_lm")
    p.add_argument("--model", dest="checkpoint", default=None,
                   metavar="CKPT",
                   help="training checkpoint to serve: dir with model.<n> "
                        "(single-blob or sharded orbax) or a single file; "
                        "optimizer state is never loaded")
    p.add_argument("--randomInit", action="store_true",
                   help="serve freshly initialized weights (benchmarks / "
                        "smoke tests; refuses to default silently)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("-p", "--port", type=int, default=8000,
                   help="0 = ephemeral (the chosen port is printed)")
    p.add_argument("--fleet", type=int, default=0, metavar="K",
                   help="serving fleet (ISSUE 20): run K engine WORKER "
                        "PROCESSES behind a router on this port — SLO-"
                        "burn-weighted least-loaded routing, supervised "
                        "restart of dead workers, rolling zero-downtime "
                        "weight swap via POST /admin/reload. 0 (default) "
                        "= today's single process")
    p.add_argument("--modelVersion", default=None, metavar="TAG",
                   help="version tag for the served weights — stamped "
                        "into provenance and echoed as x-model-version "
                        "on every response; bumped by /admin/reload "
                        "(default v0)")
    p.add_argument("--fleetHeartbeatS", type=float, default=0.5,
                   help="router -> worker heartbeat poll interval")
    p.add_argument("--fleetRestartBudget", type=int, default=8,
                   help="supervised restarts per worker before the "
                        "router gives up on that slot (exponential "
                        "backoff between attempts)")
    p.add_argument("--strategy", default=None, metavar="SPEC",
                   help="multi-chip serving (ISSUE 16): 'tp[:K]' shards "
                        "the model over K chips (Megatron layout, "
                        "bit-identical greedy output), 'dp[:N]' runs N "
                        "independent engine replicas behind one front "
                        "door (least-loaded routing, per-replica "
                        "/metrics labels), 'dp:N+tp:K' composes them. "
                        "Omitted sizes take all visible devices. "
                        "Default: single-device, exactly as before")
    p.add_argument("--buckets", default="1,2,4,8,16,32",
                   help="batch-size buckets the engine pre-compiles; "
                        "requests pad up to the nearest (bounded compile "
                        "cache, metered padding waste)")
    p.add_argument("--maxBatch", type=int, default=32,
                   help="micro-batcher flush size (throughput trigger)")
    p.add_argument("--maxWaitMs", type=float, default=5.0,
                   help="oldest-row age that forces a flush (latency "
                        "trigger)")
    p.add_argument("--maxQueue", type=int, default=256,
                   help="admission control: queued rows beyond this are "
                        "fast-rejected with HTTP 429")
    p.add_argument("--slots", type=int, default=4,
                   help="continuous-batching decode slots (LM models): "
                        "concurrent generations sharing one decode batch")
    p.add_argument("--maxWaiting", type=int, default=64,
                   help="generate requests allowed to wait for a slot "
                        "before 429")
    p.add_argument("--seq", type=int, default=None,
                   help="override the LM sequence length / max context")
    p.add_argument("--speculate", type=int, default=0, metavar="K",
                   help="speculative decoding: a draft LM proposes K "
                        "tokens per round, the target verifies them in "
                        "one chunked dispatch (exact acceptance — greedy "
                        "output is bit-identical to --speculate 0). "
                        "Default draft is the target itself (self-draft); "
                        "pass --draftDims for a smaller proposer")
    p.add_argument("--draftDims", default=None,
                   metavar="DMODEL,LAYERS,HEADS",
                   help="draft-model dims for --speculate (randomly "
                        "initialized; acceptance stays exact, only the "
                        "accept RATE depends on draft quality)")
    p.add_argument("--kvPageTokens", default=None, metavar="N|auto",
                   help="paged KV cache: fixed pages of N tokens with "
                        "per-slot page tables — kv_cache_bytes then "
                        "tracks ALLOCATED pages, not slots x max_len. "
                        "'auto' consults the kv_pages autotune namespace "
                        "(falls back to 128 where it divides max_len)")
    p.add_argument("--prefixCache", action="store_true",
                   help="share page-aligned prompt-prefix K/V across "
                        "requests (needs --kvPageTokens): hits copy "
                        "resident pages and prefill only the suffix")
    p.add_argument("--quantize", default="off",
                   choices=("off", "int8", "fp8", "kv8", "int8+kv8",
                            "fp8+kv8"),
                   help="quantized serving (ISSUE 17): int8/fp8 weights "
                        "(per-channel symmetric, dequant fused into the "
                        "matmul epilogue), kv8 stores the paged KV pools "
                        "8-bit with per-row scales (~2x the slots at "
                        "equal HBM; implies --kvPageTokens, auto-picked "
                        "if unset). Greedy-agreement + logit-error vs "
                        "f32 are measured at startup and stamped into "
                        "provenance. 'off' is byte-identical to today")
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--bf16", action="store_true",
                   help="bf16 activations (vision: input cast; LM: "
                        "post-embedding cast + bf16 KV cache)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip pre-compiling every bucket at startup "
                        "(first requests then pay the compiles)")
    p.add_argument("--timeout", type=float, default=120.0,
                   help="per-request wall timeout (503 past it)")
    p.add_argument("--deadlineMs", type=float, default=None,
                   help="default per-request deadline: rows/requests "
                        "still queued past it are dropped BEFORE "
                        "compute and answered 504 (a request-body "
                        "'deadline_ms' overrides per request)")
    p.add_argument("--shedAt", type=float, default=0.75,
                   help="tiered overload degradation: past this "
                        "fraction of queue capacity /generate sheds "
                        "with 429 while /predict keeps admitting")
    p.add_argument("--watchdogStallS", type=float, default=30.0,
                   help="watchdog verdict threshold: a worker busy with "
                        "no heartbeat this long is declared wedged — "
                        "pending requests fail fast (503) and /readyz "
                        "goes 503 while /healthz stays 200")
    p.add_argument("--faultPlan", default=None, metavar="SPEC|FILE",
                   help="deterministic fault injection on the serving "
                        "path (bigdl_tpu.resilience.faults), e.g. "
                        "'worker_kill@infer:3' kills the batcher worker "
                        "on its 3rd flush — the watchdog/fast-fail "
                        "drill. No-op unless set")
    p.add_argument("--reqTrace", choices=("on", "off"), default="off",
                   help="per-request lifecycle tracing (ISSUE 15): "
                        "request IDs minted at admission and threaded "
                        "through batcher/engine/decoder, server-side "
                        "TTFT/TPOT/ITL + queue/prefill/decode "
                        "histograms, a bounded flight recorder behind "
                        "/debug/requests + /debug/slots, and request "
                        "spans joined onto the --obs Chrome trace. "
                        "Off: the hot loop is byte-identical (same "
                        "None-check contract as --obs)")
    p.add_argument("--reqTraceCapacity", type=int, default=1024,
                   metavar="N",
                   help="completed-request records the flight recorder "
                        "retains (oldest dropped and counted past it)")
    p.add_argument("--slo", default=None, metavar="SPEC",
                   help="server-side latency SLOs, e.g. "
                        "'ttft=200,tpot=30' (ms; optional "
                        "burn=FRAC,window=N): per-dimension violation "
                        "counters, goodput, and tiered shed consults "
                        "the SLO burn rate. Implies --reqTrace on")
    p.add_argument("--accessLog", default=None, metavar="FILE",
                   help="append one JSONL access-log line per "
                        "completed request (rid, endpoint, state, "
                        "status, ttft/tpot/queue/prefill/decode ms, "
                        "tokens). Implies --reqTrace on")
    p.add_argument("--logSample", type=float, default=1.0, metavar="P",
                   help="access-log sampling probability in [0,1] — "
                        "deterministic per request id (hash-based), so "
                        "reruns sample the same rids")
    # custom-dims LM (matches cli/transformerlm.py checkpoints)
    p.add_argument("--vocabSize", type=int, default=None,
                   help="build a custom transformer_lm (with --dModel/"
                        "--numLayers/--numHeads/--seq) instead of the "
                        "32k-vocab perf-zoo config — the shape "
                        "`bigdl-tpu transformerlm train` checkpoints")
    p.add_argument("--dModel", type=int, default=128)
    p.add_argument("--numLayers", type=int, default=2)
    p.add_argument("--numHeads", type=int, default=4)
    common._add_platform_arg(p)
    common.add_autotune_arg(p)
    common.add_fused_bn_arg(p)
    common.add_lint_arg(p)
    p.add_argument("--convLayout", default=None, metavar="FWD,DGRAD,WGRAD",
                   help="per-pass conv activation layouts "
                        "(NHWC|NCHW|GEMM each, or 'auto'/'default') — "
                        "same semantics as the perf harness")
    p.add_argument("--convGeom", default=None, metavar="FILE",
                   help="per-conv-geometry layout decision JSON "
                        "(scripts/apply_conv_probe.py --geom)")
    return p


def _resolve_page_tokens(args, model, compute_dtype):
    """``--kvPageTokens``: explicit int, 'auto' (tuned via the kv_pages
    autotune namespace with a 128-where-it-divides fallback), or None
    (dense cache)."""
    spec = getattr(args, "kvPageTokens", None)
    if not spec:
        return None
    max_len = args.seq or model.max_len
    if str(spec).lower() == "auto":
        import jax.numpy as jnp

        from bigdl_tpu import tuning
        head_dim = getattr(model.encoder._modules[0].mha, "head_dim",
                           model.d_model // 4)
        kv_heads = getattr(model.encoder._modules[0].mha, "num_kv_heads",
                           args.numHeads)
        pt = tuning.kv_page_tokens(max_len, kv_heads, head_dim,
                                   compute_dtype or jnp.float32)
        if pt is None:  # autotune off: shipped ladder default
            for cand in (128, 64, 32, 256):
                if cand <= max_len and max_len % cand == 0:
                    return cand
            return None  # ragged max_len: stay dense
        return pt
    try:
        pt = int(spec)
    except ValueError:
        raise SystemExit(f"--kvPageTokens {spec!r}: expected an int or "
                         "'auto'")
    if pt < 1 or max_len % pt:
        raise SystemExit(f"--kvPageTokens {pt} must divide the context "
                         f"length {max_len}")
    return pt


def build_app(args):
    """Construct (app, engine, in_shape, in_dtype) from parsed args —
    separated from main() so tests and the load generator can run the
    server in-process on an ephemeral port."""
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu.serving import (DecodeEngine, InferenceEngine,
                                   MetricsRegistry, MicroBatcher,
                                   ServingApp, Watchdog)

    name = args.model
    is_lm = name.startswith("transformer_lm")
    if is_lm and args.vocabSize is not None:
        from bigdl_tpu import models
        seq = args.seq or 128
        model = models.transformer_lm(
            args.vocabSize, d_model=args.dModel,
            num_layers=args.numLayers, num_heads=args.numHeads,
            max_len=seq)
        in_shape = (seq,)
    else:
        from bigdl_tpu.cli.perf import build_model
        model, in_shape = build_model(name, class_num=args.classes,
                                      seq_len=args.seq)
    common.apply_fused_bn(model, getattr(args, "fusedBN", None))
    compute_dtype = jnp.bfloat16 if args.bf16 else None
    if is_lm and compute_dtype is not None:
        model.compute_dtype = compute_dtype  # post-embedding cast

    # --strategy (ISSUE 16): tp shards each engine over K chips, dp
    # runs N independent replicas on disjoint device groups; composed,
    # each replica is a K-chip tp engine. The parse itself lives on the
    # ResolvedConfig spine (ISSUE 20 satellite) so serve, fleet, and
    # lint resolve the serving flag surface identically.
    strategy = getattr(args, "strategy", None)
    n_replicas, tp_k, groups, mesh0 = 1, 1, None, None
    if strategy:
        import jax

        from bigdl_tpu.serving import replica_device_groups, serving_mesh
        cfg = common.resolve_serve_config(args,
                                          n_devices=len(jax.devices()))
        n_replicas, tp_k = cfg.serving_replicas, cfg.serving_tp
        groups = replica_device_groups(n_replicas, tp_k)
        mesh0 = serving_mesh(groups[0])

    if args.checkpoint:
        if mesh0 is not None:
            # any training topology -> this serving topology (PR 10's
            # resharded restore; engines re-place per replica/mesh)
            from bigdl_tpu.serving import restore_for_serving
            params, mod_state = restore_for_serving(args.checkpoint,
                                                    mesh0)
        else:
            from bigdl_tpu.utils.orbax_ckpt import restore_for_inference
            params, mod_state = restore_for_inference(args.checkpoint)
    elif args.randomInit:
        import jax
        params, mod_state = model.init(jax.random.PRNGKey(0)), None
    else:
        raise SystemExit(
            "serve needs weights: pass --model CKPT (a training "
            "checkpoint dir or file) or --randomInit for smoke/bench "
            "runs")

    # --quantize (ISSUE 17): quantize the weight tree ONCE up front (the
    # engines re-apply idempotently, so dp replicas share the 8-bit
    # tree) and measure the quality guardrail against the full-precision
    # tree while it is still around. 'off' never touches params.
    quantize = getattr(args, "quantize", None) or "off"
    q_wfmt, q_kv8, quant_info = None, False, None
    if quantize != "off":
        from bigdl_tpu.serving.quant import (parse_quantize, quant_report,
                                             quantize_params)
        q_wfmt, q_kv8 = parse_quantize(quantize)
        if q_kv8 and not is_lm:
            raise SystemExit("--quantize kv8 quantizes the decode KV "
                             "cache — transformer_lm models only")
        qparams = quantize_params(params, q_wfmt)
        if is_lm:
            probe = list(range(1, min(9, model.vocab)))
            quant_info = quant_report(model, params, qparams,
                                      prompt=probe, max_new_tokens=8,
                                      kv8=q_kv8,
                                      cache_dtype=compute_dtype)
        params = qparams

    metrics = MetricsRegistry()
    # install as the process-global registry (ISSUE 7): resilience
    # fault/retry counters and any training-side phase publishes in this
    # process land on the SAME /metrics page the server exposes
    from bigdl_tpu.obs.metrics import set_registry
    set_registry(metrics)

    # --reqTrace (ISSUE 15): the per-request lifecycle tracer. --slo and
    # --accessLog imply it — asking for SLOs or an access log without
    # the recorder they read from would silently do nothing.
    reqtrace_on = (args.reqTrace == "on" or args.slo is not None
                   or args.accessLog is not None)
    reqtracer = None
    if reqtrace_on:
        from bigdl_tpu.serving import reqtrace as _reqtrace
        slo = None
        if args.slo is not None:
            try:
                slo = _reqtrace.SloPolicy.parse(args.slo)
            except ValueError as e:
                raise SystemExit(f"--slo {args.slo!r}: {e}")
        access_log = None
        if args.accessLog is not None:
            if not 0.0 <= args.logSample <= 1.0:
                raise SystemExit(f"--logSample {args.logSample} must be "
                                 "in [0, 1]")
            access_log = _reqtrace.AccessLog(args.accessLog,
                                             sample=args.logSample)
        reqtracer = _reqtrace.RequestTracer(
            capacity=args.reqTraceCapacity, metrics=metrics, slo=slo,
            access_log=access_log)
        _reqtrace.set_request_tracer(reqtracer)
    in_dtype = np.int32 if is_lm else np.float32
    lint_mode = getattr(args, "lint", None)
    page_tokens = None
    draft_model = draft_params = None
    if is_lm:
        page_tokens = _resolve_page_tokens(args, model, compute_dtype)
        if q_kv8 and page_tokens is None:
            # kv8 is a page-pool layout; pick a page size automatically
            # rather than bounce the operator to --kvPageTokens
            for cand in (128, 64, 32, 256):
                if model.max_len % cand == 0:
                    page_tokens = cand
                    break
            if page_tokens is None:
                raise SystemExit(
                    f"--quantize {quantize}: no page size in "
                    f"(128, 64, 32, 256) divides max_len "
                    f"{model.max_len}; pass --kvPageTokens explicitly")
        if args.prefixCache and page_tokens is None:
            raise SystemExit("--prefixCache needs --kvPageTokens (prefix "
                             "sharing is a page copy)")
        if args.speculate > 0 and args.draftDims:
            import jax

            from bigdl_tpu import models
            from bigdl_tpu.serving import parse_draft_dims
            dims = parse_draft_dims(args.draftDims)
            draft_model = models.transformer_lm(
                model.vocab, max_len=model.max_len,
                compute_dtype=compute_dtype, **dims)
            draft_params = draft_model.init(jax.random.PRNGKey(1))

    # what mesh the first-stack lint pass actually vetted (stamped into
    # provenance as lint_mesh — ISSUE 19 satellite)
    lint_prov: dict = {}

    def _build_stack(mesh, m, first):
        """One replica's full serving stack. ``m`` is its metrics view
        (labelled per replica under dp); pre-flight lints run for the
        FIRST stack only — replicas compile the identical graph."""
        engine = InferenceEngine(
            model, params, mod_state,
            buckets=_parse_buckets(args.buckets),
            compute_dtype=compute_dtype, lint=lint_mode,
            metrics=m, mesh=mesh, quantize=quantize)
        if first:
            # lint pre-flight over the exact serving graph BEFORE first
            # compile (strict refuses to serve, same contract as the
            # perf/training CLIs)
            rc = engine.preflight_lint(in_shape, in_dtype)
            if rc:
                raise SystemExit(rc)
            if lint_mode is not None and tp_k > 1:
                # tp placement rule (ISSUE 16): a big matmul weight the
                # Megatron pairing left replicated defeats the strategy
                from bigdl_tpu.analysis import run_serving_tp_rules
                report = run_serving_tp_rules(engine.params, tp_k)
                rc, _ = common.run_preflight_lint(
                    report, strict=(lint_mode == "strict"))
                if rc:
                    raise SystemExit(rc)
        batcher = MicroBatcher(engine.predict_scores,
                               max_batch=args.maxBatch,
                               max_wait_ms=args.maxWaitMs,
                               max_queue=args.maxQueue, metrics=m)
        decoder = None
        if is_lm:
            decoder = DecodeEngine(model, params, slots=args.slots,
                                   cache_dtype=compute_dtype,
                                   max_waiting=args.maxWaiting,
                                   metrics=m,
                                   kv_page_tokens=page_tokens,
                                   speculate=args.speculate,
                                   draft_model=draft_model,
                                   draft_params=draft_params,
                                   prefix_cache=args.prefixCache,
                                   mesh=mesh, quantize=quantize)
            # decode-path lint pre-flight (ISSUE 14): sampling-sort /
            # host-sync rules over the traced decode step + the
            # page-layout fit, same strict contract as the forward's.
            # Under dp:N+tp:K every replica compiles the IDENTICAL
            # graph on an isomorphic tp group, so linting the first
            # stack covers the fleet (ISSUE 19 bugfix) — the mesh the
            # pass actually checked is stamped into provenance as
            # lint_mesh so "which graph was vetted" is auditable
            if first and lint_mode is not None:
                from bigdl_tpu.analysis import (run_decode_rules,
                                                run_kv_sharding_rules,
                                                run_sharding_rules)
                head_dim = getattr(model.encoder._modules[0].mha,
                                   "head_dim", model.d_model // 4)
                step_jaxpr = decoder.trace_step_jaxpr()
                report = run_decode_rules(
                    step_jaxpr, page_tokens=page_tokens,
                    max_len=decoder.max_len, head_dim=head_dim,
                    dtype=decoder.cache_dtype)
                if tp_k > 1:
                    # shardlint over the SHARDED decode step (ISSUE
                    # 19): annotation consistency on the tp group +
                    # the KV head-split fit of the page pools
                    run_sharding_rules(
                        step_jaxpr, mesh_axes={"model": tp_k},
                        strategy=None, context="serving",
                        report=report)
                    run_kv_sharding_rules(
                        decoder._kv.pools if decoder.paged
                        else decoder._cache,
                        tp_k, page_tokens=page_tokens, report=report)
                    lint_prov["lint_mesh"] = (
                        f"model:{tp_k} x {n_replicas} replica(s)"
                        if n_replicas > 1 else f"model:{tp_k}")
                else:
                    lint_prov["lint_mesh"] = (
                        f"replicated x {n_replicas} replica(s)"
                        if n_replicas > 1 else "single-device")
                rc, _ = common.run_preflight_lint(
                    report, strict=(lint_mode == "strict"))
                if rc:
                    raise SystemExit(rc)
            decoder.start()
        # watchdog over every worker thread: dead/wedged -> pending
        # futures fail fast, /readyz flips 503, /healthz stays (ISSUE 6)
        watchdog = Watchdog(stall_timeout_s=args.watchdogStallS,
                            metrics=m)
        watchdog.watch("batcher", batcher)
        if decoder is not None:
            watchdog.watch("decoder", decoder)
        watchdog.start()
        return engine, batcher, decoder, watchdog

    replica_set = None
    if n_replicas > 1:
        from bigdl_tpu.serving import Replica, ReplicaSet, serving_mesh
        reps = []
        for r in range(n_replicas):
            mesh_r = serving_mesh(groups[r])
            m = metrics.labelled(replica=str(r))
            eng_r, bat_r, dec_r, wd_r = _build_stack(mesh_r, m,
                                                     first=(r == 0))
            reps.append(Replica(r, devices=groups[r], mesh=mesh_r,
                                engine=eng_r, batcher=bat_r,
                                decoder=dec_r, watchdog=wd_r,
                                metrics=m))
        replica_set = ReplicaSet(reps, metrics=metrics)
        engine, batcher = reps[0].engine, None
        decoder, watchdog = reps[0].decoder, None
    else:
        engine, batcher, decoder, watchdog = _build_stack(
            mesh0, metrics, first=True)

    prov = engine.provenance()
    if lint_prov:
        prov.update(lint_prov)
    prov.update({
        "model": name,
        "max_batch": args.maxBatch,
        "max_wait_ms": args.maxWaitMs,
        "max_queue": args.maxQueue,
        "deadline_ms": args.deadlineMs if args.deadlineMs else "none",
        "shed_at": args.shedAt,
        "reqtrace": "on" if reqtracer is not None else "off",
    })
    if quant_info is not None:
        # measured quality guardrail (ISSUE 17): greedy agreement vs the
        # f32 tree and worst-case logit error, pinned into every scrape
        prov["quant_agreement"] = round(float(quant_info["agreement"]), 4)
        prov["quant_logit_max_err"] = round(
            float(quant_info["logit_max_err"]), 6)
    if strategy:
        import jax
        # multi-chip topology provenance (ISSUE 16): every /metrics
        # scrape and bench record names the serving shape it measured
        prov["strategy"] = strategy
        prov["serving_replicas"] = n_replicas
        prov["serving_tp"] = tp_k
        prov["n_devices"] = len(jax.devices())
    if reqtracer is not None:
        prov["slo"] = args.slo if args.slo else "none"
        if reqtracer.access_log is not None:
            prov["access_log"] = reqtracer.access_log.path
            prov["access_log_sample"] = args.logSample
    if decoder is not None:
        prov["decode_slots"] = args.slots
        prov["prompt_buckets"] = ",".join(
            str(b) for b in decoder.prompt_buckets)
        prov["speculate"] = args.speculate
        prov["draft_dims"] = args.draftDims or (
            "self" if args.speculate > 0 else "none")
        prov["kv_page_tokens"] = decoder.page_tokens or "dense"
        prov["prefix_cache"] = int(bool(args.prefixCache))
        if args.speculate > 0:
            # measured, resolved per scrape: tokens emitted per target
            # verify dispatch (the ISSUE 14 acceptance number; replica
            # 0's labelled series under dp)
            g = metrics.gauge("spec_accepted_tokens_per_step",
                              "tokens emitted per target verify step",
                              labels=({"replica": "0"}
                                      if replica_set is not None
                                      else None))
            prov["spec_accepted_tokens_per_step"] = \
                lambda: round(g.value, 4)
    if getattr(args, "faultPlan", None):
        prov["fault_plan"] = args.faultPlan
    metrics.set_provenance(prov)

    version = getattr(args, "modelVersion", None) or "v0"
    if replica_set is not None:
        app = ServingApp(name=name, metrics=metrics,
                         replicas=replica_set,
                         request_timeout_s=args.timeout,
                         default_deadline_ms=args.deadlineMs,
                         shed_generate_frac=args.shedAt,
                         version=version)
    else:
        app = ServingApp(name=name, metrics=metrics, engine=engine,
                         batcher=batcher, decoder=decoder,
                         request_timeout_s=args.timeout,
                         default_deadline_ms=args.deadlineMs,
                         shed_generate_frac=args.shedAt,
                         watchdog=watchdog, version=version)
    # resolved per scrape: a rolling weight swap (ISSUE 20) bumps
    # app.model_version and every later scrape names the NEW weights
    prov["model_version"] = lambda: app.model_version
    metrics.set_provenance(prov)
    return app, engine, in_shape, in_dtype


def main(argv=None):
    common.setup_logging()
    import sys
    raw_argv = list(sys.argv[1:]) if argv is None else list(argv)
    args = build_parser().parse_args(raw_argv)
    if getattr(args, "fleet", 0):
        # --fleet K (ISSUE 20): this process becomes the ROUTER — it
        # never initializes jax; each worker re-enters the serve stack
        # in its own process with the router-owned flags stripped
        from bigdl_tpu.serving.fleet.router import run_fleet
        return run_fleet(args, raw_argv)
    common.apply_platform(args)  # --convLayout/--convGeom/--autotune

    from bigdl_tpu.serving import run_server

    app, engine, in_shape, in_dtype = build_app(args)
    if not args.no_warmup:
        engines = ([r.engine for r in app.replicas.replicas]
                   if app.replicas is not None else [engine])
        print(f"warmup: compiling buckets {engine.buckets} at "
              f"{tuple(in_shape)} {in_dtype.__name__}"
              + (f" x{len(engines)} replicas" if len(engines) > 1
                 else ""), flush=True)
        for e in engines:
            e.warmup(in_shape, in_dtype)
    return run_server(app, args.host, args.port)


if __name__ == "__main__":
    raise SystemExit(main())
