"""Inception-v1/v2 on ImageNet-style image folders (reference
models/inception/{Train,Test,Options}.scala: SeqFile pipeline + Poly LR
schedule Train.scala:77-83; here the input is a label-by-folder image tree
streamed through ImageFolderDataSet)."""

from __future__ import annotations

import argparse

from bigdl_tpu.cli import common

from bigdl_tpu.dataset.folder import IMAGENET_MEAN as _MEAN
from bigdl_tpu.dataset.folder import IMAGENET_STD as _STD


def _train_dataset(folder: str, batch: int):
    import os

    from bigdl_tpu.dataset.folder import ImageFolderDataSet

    return ImageFolderDataSet(os.path.join(folder, "train"), batch,
                              size=(224, 224), train=True,
                              mean=_MEAN, std=_STD)


def _val_dataset(folder: str, batch: int):
    import os

    from bigdl_tpu.dataset.folder import ImageFolderDataSet

    vdir = os.path.join(folder, "val")
    return (ImageFolderDataSet(vdir, batch, size=(224, 224),
                               mean=_MEAN, std=_STD)
            if os.path.isdir(vdir) else None)


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu inception")
    sub = p.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("train")
    common.add_train_args(tr)
    tr.add_argument("--modelName", choices=["inception_v1", "inception_v2"],
                    default="inception_v1")
    tr.add_argument("--classNum", type=int, default=1000)
    tr.add_argument("--maxIteration", type=int, default=62000)
    te = sub.add_parser("test")
    common.add_test_args(te)
    te.add_argument("--modelName", choices=["inception_v1", "inception_v2"],
                    default="inception_v1")
    te.add_argument("--classNum", type=int, default=1000)
    args = p.parse_args(argv)
    common.apply_platform(args)

    from bigdl_tpu import nn
    from bigdl_tpu.models import inception_v1_no_aux, inception_v2
    from bigdl_tpu.optim import SGD, Top1Accuracy, Top5Accuracy, Trigger
    from bigdl_tpu.optim.schedules import Poly

    build = (inception_v1_no_aux if args.modelName == "inception_v1"
             else inception_v2)
    model = build(args.classNum)

    if args.cmd == "train":
        train = _train_dataset(args.folder, args.batchSize)
        val = _val_dataset(args.folder, args.batchSize)
        # reference hyperparams: lr 0.0898, Poly(0.5, 62000)
        def _make():
            method = SGD(learning_rate=args.learningRate,
                         schedule=Poly(0.5, args.maxIteration))
            opt = common.build_optimizer(model, train,
                                         nn.ClassNLLCriterion(), args,
                                         optim_method=method)
            if val is not None:
                opt.set_validation(Trigger.every_epoch(), val,
                                   [Top1Accuracy(), Top5Accuracy()])
            return opt
        return common.run_optimize(_make, args)
    params, mod_state = common.load_trained(model, args.model)
    val = _val_dataset(args.folder, args.batchSize)
    if val is None:
        raise FileNotFoundError(
            f"no val/ directory under {args.folder} — `inception test` "
            f"needs {args.folder}/val/<class>/*.jpg")
    return common.evaluate(model, params, mod_state, val,
                           [Top1Accuracy(), Top5Accuracy()])


if __name__ == "__main__":
    main()
