"""`bigdl-tpu explain` — turn a profile into an explanation (ISSUE 8).

    bigdl-tpu explain /tmp/obs/capture_4            # a capture window
    bigdl-tpu explain /tmp/xp --steps 5 --gflops 94 # any profiler dir
    bigdl-tpu explain resnet50 -b 32 -i 5           # run + explain

The target is either a ``jax.profiler`` output directory (a perf
``--profile`` dir or an obs ``capture_<step>`` window) or a perf-zoo
model name — the latter runs a short profiled throughput loop first
(``cli/perf.py``), then attributes its own trace with the run's analytic
FLOPs numerator and mesh peak, so the table carries FLOP share and
roofline utilization, not just times. Output: the per-category table
(``utils/table``) with the collective breakout and MFU decomposition,
or ``--json`` (one line, printed last — ``tail -1`` safe).

``--mem`` (ISSUE 12) is the memory twin for a model target: no run —
the training step is lowered+compiled at two batch sizes, the
per-category byte plan of the exact step is rendered (totalling to
``compiled.memory_analysis()``), and the linear per-sample fit predicts
the max batch that still fits the device HBM:

    bigdl-tpu explain --mem resnet50 -b 32
"""

from __future__ import annotations

import argparse
import json
import os


def main(argv=None):
    p = argparse.ArgumentParser(
        "bigdl-tpu explain",
        description="classify every device op of a profile into the "
                    "PERF.md §16 taxonomy (matmul/conv/bn_norm/"
                    "attention/elementwise/collective/infeed/host_other)"
                    " with per-collective subtotals and an MFU "
                    "decomposition")
    p.add_argument("target",
                   help="jax.profiler trace dir (e.g. an obs "
                        "capture_<step> window) OR a perf model name "
                        "(runs a short profiled loop first)")
    p.add_argument("--json", action="store_true",
                   help="machine output (one JSON line, printed last)")
    p.add_argument("--mem", action="store_true",
                   help="memory mode (model target only): per-category "
                        "HBM plan of the compiled training step, "
                        "headroom against the device capacity, and the "
                        "predicted max batch from a two-point "
                        "per-sample fit — no training run")
    p.add_argument("-b", "--batchSize", type=int, default=16,
                   help="batch for model-mode runs")
    p.add_argument("-i", "--iteration", type=int, default=5,
                   help="timed steps for model-mode runs (= the step "
                        "count the attribution divides by)")
    p.add_argument("--steps", type=int, default=None,
                   help="step count of a profile-dir target (enables "
                        "ms/step and the per-step collective column)")
    p.add_argument("--gflops", type=float, default=None,
                   help="analytic step GFLOPs of the profiled run "
                        "(perf JSON's step_gflops_analytic) — enables "
                        "FLOP share / utilization for a profile-dir "
                        "target")
    p.add_argument("--gflopsConv", type=float, default=None,
                   help="conv share of --gflops (perf JSON's "
                        "step_gflops_by_kind.conv); rest is matmul")
    p.add_argument("--peak", type=float, default=None,
                   help="whole-mesh peak FLOP/s for the roofline join "
                        "(perf JSON's peak_flops_assumed x n_devices)")
    p.add_argument("--top", type=int, default=3,
                   help="top ops listed per category")
    p.add_argument("--seq", type=int, default=None,
                   help="transformer_lm* sequence override (model mode)")
    p.add_argument("--quantize", default=None,
                   choices=("off", "int8", "fp8", "kv8", "int8+kv8",
                            "fp8+kv8"),
                   help="with --mem on a transformer_lm target: account "
                        "the serving KV cache and weights under this "
                        "quantize mode (ISSUE 17) and re-fit the "
                        "max-slot forecast — kv8 roughly quarters the "
                        "per-slot bytes, so ~2x the slots fit")
    from bigdl_tpu.cli.common import (_add_platform_arg, add_strategy_arg,
                                      apply_platform)
    _add_platform_arg(p)
    add_strategy_arg(p)
    args = p.parse_args(argv)
    apply_platform(args)

    from bigdl_tpu.obs import attrib

    if args.mem:
        # memory mode (ISSUE 12): two abstract plans -> category table
        # + headroom + predicted max batch; no timed run
        if os.path.isdir(args.target):
            raise SystemExit(
                "--mem explains a MODEL's memory plan (it compiles the "
                "step); pass a perf model name, not a profile dir")
        from bigdl_tpu.obs import memory
        b = args.batchSize
        plan = memory.plan_for_model(args.target, b, seq_len=args.seq)
        plan2 = memory.plan_for_model(args.target, 2 * b,
                                      seq_len=args.seq)
        fc = memory.forecast(plan, plan2)
        kvp = fcs = None
        if args.target.startswith("transformer_lm"):
            # serving-side companion (ISSUE 17): per-slot KV bytes and
            # the max-slot fit, dtype-aware under --quantize
            kvp = memory.serving_kv_plan(args.target, seq_len=args.seq,
                                         quantize=args.quantize)
            fcs = memory.forecast_slots(kvp)
        if args.json:
            out = memory.compact(plan)
            out["model"] = args.target
            out["forecast"] = fc
            out["plan_2x"] = memory.compact(plan2)
            if kvp is not None:
                out["serving_kv"] = kvp
                out["forecast_slots"] = fcs
            print(json.dumps(out))
        else:
            print(f"memory plan: {args.target} b={b} "
                  f"({plan.get('device')})")
            print(memory.render(plan, fc))
            if kvp is not None:
                print(f"\nserving (quantize={kvp['quantize']}): "
                      f"kv/slot {kvp['kv_bytes_per_slot']} B "
                      f"(L={kvp['max_len']}, "
                      f"dtype={kvp['cache_dtype']}"
                      + (f", page={kvp['page_tokens']}"
                         if kvp['page_tokens'] else "")
                      + f"), weights {kvp['params_bytes']} B"
                      f" -> predicted max slots "
                      f"{fcs['predicted_max_slots']}")
        return 0

    if os.path.isdir(args.target):
        step_flops = args.gflops * 1e9 if args.gflops else None
        by_kind = None
        if step_flops and args.gflopsConv is not None:
            conv = args.gflopsConv * 1e9
            by_kind = {"matmul": max(0.0, step_flops - conv),
                       "conv": conv}
        summary = attrib.attribute_profile(
            args.target, steps=args.steps, step_flops=step_flops,
            flops_by_kind=by_kind, peak_flops=args.peak,
            top_ops=args.top)
    else:
        # model mode: short profiled perf run, then attribute its trace
        # with the run's own numerators (perf prints its JSON line
        # first; ours is last)
        import tempfile

        from bigdl_tpu.cli import perf

        tmp = tempfile.mkdtemp(prefix="bigdl_explain_")
        out = perf.run(args.target, args.batchSize, args.iteration,
                       "random", profile_dir=tmp,
                       strategy=args.strategy, seq_len=args.seq)
        gf = out.get("step_gflops_analytic") or 0.0
        kinds = out.get("step_gflops_by_kind") or {}
        summary = attrib.attribute_profile(
            tmp, steps=args.iteration * out.get("inner_steps", 1),
            step_flops=gf * 1e9 or None,
            flops_by_kind={k: v * 1e9 for k, v in kinds.items()} or None,
            peak_flops=(out.get("peak_flops_assumed") or 0)
            * out.get("n_devices", 1) or None,
            top_ops=args.top)
        summary["perf"] = {k: out.get(k) for k in (
            "model", "batch", "strategy", "n_devices", "mesh",
            "records_per_second", "mfu_pct", "device")}

    if args.json:
        c = attrib.compact(summary)
        c["xplane"] = summary.get("xplane")
        if "perf" in summary:
            c["perf"] = summary["perf"]
        print(json.dumps(c))
    else:
        print(attrib.render(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
