"""Shared program-configuration provenance (ISSUE 18 satellite; first
bite of ROADMAP item 5).

Four surfaces used to hand-assemble the same "which program config
produced this number" fields — the perf JSON line
(``cli/perf.py`` annotators), the ``/metrics`` ``_info`` gauge
(``serving/engine.provenance`` + ``cli/serve``), the ``bench.py``
companion rows, and the bench-script capture records — and a fifth
consumer (``bigdl-tpu batch-predict``) was about to appear. This module
is the single assembly point:

* :func:`provenance_dict` builds the shared core — BN fusion mode,
  autotune decisions, conv layout policy, per-geometry conv decisions —
  in either of the two shapes the callers historically used:
  ``flat=False`` keeps structured dicts and omits defaults (the perf
  JSON idiom: absent key == default config), ``flat=True`` renders
  scrape-safe scalars and always emits every key (the ``/metrics``
  ``_info`` idiom: a stable label set).
* :data:`PROVENANCE_COMPANION_KEYS` is the canonical key list record
  assemblies copy from a result dict (``bench.py`` companions, capture
  records) — one list to extend when a new provenance column lands.

Every field is read from the live process state at call time, exactly
as the four hand-rolled copies did, so routing through here changes no
output — it only removes the copies that could drift.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["provenance_dict", "PROVENANCE_COMPANION_KEYS"]

# provenance columns a record assembly copies verbatim from a result
# dict (bench companions, capture records): the config core plus the
# feed-attribution columns that make perf rows self-describing
PROVENANCE_COMPANION_KEYS = ("conv_layouts", "conv_geom", "autotune",
                             "bn_fused", "pipeline", "stall_frac",
                             "data_wait_s")


def provenance_dict(model=None, flat: bool = False) -> dict:
    """The shared provenance core, assembled from live process state.

    ``model`` supplies the BN-fusion verdict (``bn_fused`` is omitted
    when None and ``flat=False``; reported as ``"none"`` when None and
    ``flat=True`` so the scrape label set stays stable).

    ``flat=False`` (perf-JSON shape): structured values, defaults
    omitted —

    * ``conv_layouts``: the non-default layout triple dict, absent when
      default;
    * ``conv_geom``: installed per-geometry decisions dict, absent when
      none;
    * ``autotune``: the tuning annotation (mode + per-key decisions),
      absent when tuning is off with no ledger;
    * ``bn_fused``: ``off``/``stats``/``apply``.

    ``flat=True`` (``_info``-gauge shape): every key present, scalar
    values —

    * ``conv_layouts``: ``"k=v/..."`` joined string or ``"default"``;
    * ``conv_geom_decisions``: decision count (0 when none);
    * ``autotune``: the tuning MODE string;
    * ``bn_fused``: as above.
    """
    from bigdl_tpu import tuning
    from bigdl_tpu.nn.norm import bn_fused_mode
    from bigdl_tpu.ops.conv2d import (conv_layouts_if_nondefault,
                                      geom_policy_if_any)

    out: dict = {}
    cl = conv_layouts_if_nondefault()
    gp = geom_policy_if_any()
    if flat:
        out["bn_fused"] = (bn_fused_mode(model) if model is not None
                           else "none")
        out["autotune"] = tuning.get_mode()
        out["conv_layouts"] = ("/".join(f"{k}={v}" for k, v in
                                        sorted(cl.items()))
                               if cl else "default")
        out["conv_geom_decisions"] = len(gp) if gp else 0
        return out
    if model is not None:
        out["bn_fused"] = bn_fused_mode(model)
    ann = tuning.annotation()
    if ann is not None:
        out["autotune"] = ann
    if cl:
        out["conv_layouts"] = cl
    if gp:
        out["conv_geom"] = gp
    return out
