"""Transformer language model training (the long-context successor to the
reference's SimpleRNN/tiny-shakespeare pipeline, models/rnn/Train.scala —
same input.txt corpus format and perplexity metric, modern model).

    python -m bigdl_tpu.cli.transformerlm train -f data/ --seqLength 256 \
        --dModel 256 --numLayers 4 --flash --remat
"""

from __future__ import annotations

import argparse
import math
import os

from bigdl_tpu.cli import common


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu transformerlm")
    sub = p.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("train")
    common.add_train_args(tr)
    tr.add_argument("--vocabSize", type=int, default=4000)
    tr.add_argument("--seqLength", type=int, default=128)
    tr.add_argument("--dModel", type=int, default=128)
    tr.add_argument("--numLayers", type=int, default=2)
    tr.add_argument("--numHeads", type=int, default=4)
    tr.add_argument("--dropout", type=float, default=0.0)
    tr.add_argument("--flash", action="store_true",
                    help="use the Pallas flash-attention kernel")
    tr.add_argument("--remat", action="store_true",
                    help="jax.checkpoint each block (HBM for FLOPs)")
    tr.add_argument("--bf16", action="store_true")
    tr.add_argument("--accumSteps", type=int, default=1)
    ge = sub.add_parser("generate",
                        help="sample from a trained checkpoint (KV-cache "
                             "decode)")
    common.add_test_args(ge)
    for flag, typ, dv in (("--vocabSize", int, 4000), ("--seqLength", int,
                          128), ("--dModel", int, 128), ("--numLayers",
                          int, 2), ("--numHeads", int, 4)):
        ge.add_argument(flag, type=typ, default=dv)
    ge.add_argument("--prompt", default="the ",
                    help="prompt text (tokenized with the corpus dict)")
    ge.add_argument("--numTokens", type=int, default=64)
    ge.add_argument("--temperature", type=float, default=0.8)
    ge.add_argument("--topK", type=int, default=40)
    ge.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    common.apply_platform(args)

    if args.cmd == "generate":
        return _generate(args)

    import numpy as np
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import BatchDataSet
    from bigdl_tpu.dataset.text import Dictionary, tokenize
    from bigdl_tpu.models import transformer_lm

    path = os.path.join(args.folder, "input.txt")
    with open(path) as f:
        tokens = tokenize(f.read())
    d = Dictionary([tokens], vocab_size=args.vocabSize)
    ids = np.asarray(d.ids(tokens), np.int32)

    # non-overlapping next-token windows: x = w[:-1], y = w[1:]
    s = args.seqLength + 1
    n_win = len(ids) // s
    if n_win < 2:
        raise SystemExit(f"corpus too small: {len(ids)} tokens for "
                         f"seqLength {args.seqLength}")
    w = ids[: n_win * s].reshape(n_win, s)
    x, y = w[:, :-1], w[:, 1:]
    n_held = max(1, n_win // 10)
    x, y, x_val, y_val = x[:-n_held], y[:-n_held], x[-n_held:], y[-n_held:]

    if len(x) < args.batchSize:
        # BatchDataSet drops the short remainder; without this clamp a
        # small corpus would train for zero steps and report garbage
        print(f"warning: only {len(x)} training windows < batchSize "
              f"{args.batchSize}; clamping batchSize to {len(x)}")
        args.batchSize = len(x)

    model = transformer_lm(
        len(d), d_model=args.dModel, num_layers=args.numLayers,
        num_heads=args.numHeads, max_len=args.seqLength,
        dropout=args.dropout, attn_impl="flash" if args.flash else None,
        remat=args.remat,
        # cast right after the embedding — the Optimizer-level cast only
        # applies to float inputs, and LM input is int tokens
        compute_dtype=jnp.bfloat16 if args.bf16 else None)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    train = BatchDataSet(x, y, args.batchSize, shuffle=True)
    opt = common.build_optimizer(model, train, crit, args)
    opt.accum_steps = max(1, args.accumSteps)
    trained = opt.optimize()

    logp = trained.module.forward(trained.params, jnp.asarray(x_val))
    lp = np.asarray(logp)
    nll = -np.mean(np.take_along_axis(lp, y_val[..., None], axis=-1))
    print(f"perplexity is {math.exp(nll):.2f}")
    return trained


def _generate(args):
    """Sample continuations from a trained LM (reference rnn/Test.scala
    samples from the trained SimpleRNN the same way: seed text -> ids ->
    iterative next-token -> words)."""
    import jax
    import numpy as np

    from bigdl_tpu.cli import common
    from bigdl_tpu.dataset.text import Dictionary, tokenize
    from bigdl_tpu.models import transformer_lm

    path = os.path.join(args.folder, "input.txt")
    with open(path) as f:
        tokens = tokenize(f.read())
    d = Dictionary([tokens], vocab_size=args.vocabSize)

    model = transformer_lm(
        len(d), d_model=args.dModel, num_layers=args.numLayers,
        num_heads=args.numHeads, max_len=args.seqLength)
    params, _ = common.load_trained(model, args.model)

    prompt_ids = np.asarray([d.ids(tokenize(args.prompt))], np.int32)
    if prompt_ids.shape[1] == 0:
        raise SystemExit("empty prompt after tokenization")
    out = model.generate(params, prompt_ids, args.numTokens,
                         temperature=args.temperature, top_k=args.topK,
                         rng=jax.random.PRNGKey(args.seed))
    words = [d.id2word.get(int(i), "<unk>") for i in np.asarray(out)[0]]
    print(args.prompt + " ".join(words))
    return words


if __name__ == "__main__":
    main()
