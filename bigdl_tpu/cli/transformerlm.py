"""Transformer language model training (the long-context successor to the
reference's SimpleRNN/tiny-shakespeare pipeline, models/rnn/Train.scala —
same input.txt corpus format and perplexity metric, modern model).

    python -m bigdl_tpu.cli.transformerlm train -f data/ --seqLength 256 \
        --dModel 256 --numLayers 4 --flash --remat
"""

from __future__ import annotations

import argparse
import math
import os

from bigdl_tpu.cli import common


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu transformerlm")
    sub = p.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("train")
    common.add_train_args(tr)
    tr.add_argument("--vocabSize", type=int, default=4000)
    tr.add_argument("--seqLength", type=int, default=128)
    tr.add_argument("--dModel", type=int, default=128)
    tr.add_argument("--numLayers", type=int, default=2)
    tr.add_argument("--numHeads", type=int, default=4)
    tr.add_argument("--dropout", type=float, default=0.0)
    tr.add_argument("--flash", action="store_true",
                    help="use the Pallas flash-attention kernel")
    tr.add_argument("--remat", nargs="?", const="full", default=False,
                    choices=["full", "dots"],
                    help="jax.checkpoint each block (HBM for FLOPs): "
                         "'full' recomputes everything; 'dots' keeps "
                         "matmul outputs resident and recomputes only "
                         "bandwidth-bound intermediates (usually the "
                         "better TPU point)")
    tr.add_argument("--bf16", action="store_true")
    tr.add_argument("--accumSteps", type=int, default=1)
    tr.add_argument("--packed", action="store_true",
                    help="sentence-split the corpus and pack documents "
                         "into rows (segment-masked attention, boundary-"
                         "masked loss) instead of fixed windows")
    ge = sub.add_parser("generate",
                        help="sample from a trained checkpoint (KV-cache "
                             "decode)")
    common.add_test_args(ge)
    for flag, typ, dv in (("--vocabSize", int, 4000), ("--seqLength", int,
                          128), ("--dModel", int, 128), ("--numLayers",
                          int, 2), ("--numHeads", int, 4)):
        ge.add_argument(flag, type=typ, default=dv)
    ge.add_argument("--prompt", default="the ",
                    help="prompt text (tokenized with the corpus dict)")
    ge.add_argument("--numTokens", type=int, default=64)
    ge.add_argument("--temperature", type=float, default=0.8)
    ge.add_argument("--topK", type=int, default=40)
    ge.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)
    common.apply_platform(args)

    if args.cmd == "generate":
        return _generate(args)

    import numpy as np
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import BatchDataSet
    from bigdl_tpu.dataset.text import Dictionary, tokenize
    from bigdl_tpu.models import transformer_lm

    path = os.path.join(args.folder, "input.txt")
    with open(path) as f:
        tokens = tokenize(f.read())
    d = Dictionary([tokens], vocab_size=args.vocabSize)
    ids = np.asarray(d.ids(tokens), np.int32)

    if args.packed:
        return _train_packed(args, d, tokens)

    # non-overlapping next-token windows: x = w[:-1], y = w[1:]
    s = args.seqLength + 1
    n_win = len(ids) // s
    if n_win < 2:
        raise SystemExit(f"corpus too small: {len(ids)} tokens for "
                         f"seqLength {args.seqLength}")
    w = ids[: n_win * s].reshape(n_win, s)
    x, y = w[:, :-1], w[:, 1:]
    n_held = max(1, n_win // 10)
    x, y, x_val, y_val = x[:-n_held], y[:-n_held], x[-n_held:], y[-n_held:]

    if len(x) < args.batchSize:
        # BatchDataSet drops the short remainder; without this clamp a
        # small corpus would train for zero steps and report garbage
        print(f"warning: only {len(x)} training windows < batchSize "
              f"{args.batchSize}; clamping batchSize to {len(x)}")
        args.batchSize = len(x)

    model = transformer_lm(
        len(d), d_model=args.dModel, num_layers=args.numLayers,
        num_heads=args.numHeads, max_len=args.seqLength,
        dropout=args.dropout, attn_impl="flash" if args.flash else None,
        remat=args.remat,
        # cast right after the embedding — the Optimizer-level cast only
        # applies to float inputs, and LM input is int tokens
        compute_dtype=jnp.bfloat16 if args.bf16 else None)
    crit = nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
    train = BatchDataSet(x, y, args.batchSize, shuffle=True)

    def _make():
        opt = common.build_optimizer(model, train, crit, args)
        opt.accum_steps = max(1, args.accumSteps)
        return opt
    trained = common.run_optimize(_make, args)

    logp = trained.module.forward(trained.params, jnp.asarray(x_val))
    lp = np.asarray(logp)
    nll = -np.mean(np.take_along_axis(lp, y_val[..., None], axis=-1))
    print(f"perplexity is {math.exp(nll):.2f}")
    return trained


def _train_packed(args, d, tokens):
    """Packed-document training: sentences become documents, documents
    pack into fixed rows (dataset.text.pack_sequences), attention is
    segment-masked and the loss skips document boundaries. The Optimizer
    sees plain arrays: features/labels stack (tokens|segments) and
    (targets|weights) along axis 1, unstacked by thin adapters."""
    import numpy as np
    import jax.numpy as jnp

    from bigdl_tpu.core.module import Module
    from bigdl_tpu.dataset import BatchDataSet
    from bigdl_tpu.dataset.text import pack_sequences
    from bigdl_tpu.models import (PackedNLLCriterion, packed_lm_targets,
                                  transformer_lm)

    # sentence-split on the period token the tokenizer keeps; if the
    # corpus has no ".", chunk fixed-size pseudo-documents instead
    ids = d.ids(tokens)
    period = d.word2id.get(".")
    docs, cur = [], []
    if period is not None:
        for t in ids:
            cur.append(t)
            if t == period:
                docs.append(cur)
                cur = []
        if cur:
            docs.append(cur)
    else:
        step = max(args.seqLength // 4, 8)
        docs = [ids[i:i + step] for i in range(0, len(ids), step)]
    toks, segs = pack_sequences(docs, args.seqLength)
    if len(toks) < 2:
        raise SystemExit(f"corpus too small to pack: {len(docs)} docs "
                         f"-> {len(toks)} rows")
    tgt, w = packed_lm_targets(jnp.asarray(toks), jnp.asarray(segs))
    feats = np.stack([toks, segs], axis=1)                  # (n, 2, s)
    labels = np.stack([np.asarray(tgt), np.asarray(w)], axis=1)
    n_held = max(1, len(feats) // 10)
    f_tr, f_val = feats[:-n_held], feats[-n_held:]
    l_tr, l_val = labels[:-n_held], labels[-n_held:]
    if len(f_tr) < args.batchSize:
        print(f"warning: only {len(f_tr)} packed rows < batchSize "
              f"{args.batchSize}; clamping")
        args.batchSize = len(f_tr)

    lm = transformer_lm(
        len(d), d_model=args.dModel, num_layers=args.numLayers,
        num_heads=args.numHeads, max_len=args.seqLength,
        dropout=args.dropout, attn_impl="flash" if args.flash else None,
        remat=args.remat,
        compute_dtype=jnp.bfloat16 if args.bf16 else None)

    class _PackedLM(Module):
        """Unstacks (n, 2, s) -> ((tokens, segments)) for the LM."""

        def children(self):
            return (lm,)

        def init(self, rng):
            return lm.init(rng)

        def init_state(self):
            return lm.init_state()

        def apply(self, params, state, x, *, training=False, rng=None):
            return lm.apply(params, state, (x[:, 0], x[:, 1]),
                            training=training, rng=rng)

    base = PackedNLLCriterion()
    crit = lambda logp, y: base(logp, (y[:, 0].astype(jnp.int32),
                                       y[:, 1]))
    train = BatchDataSet(f_tr, l_tr, args.batchSize, shuffle=True)

    def _make():
        opt = common.build_optimizer(_PackedLM(), train, crit, args)
        opt.accum_steps = max(1, args.accumSteps)
        return opt
    trained = common.run_optimize(_make, args)

    logp = trained.module.forward(trained.params, jnp.asarray(f_val))
    lp = np.asarray(logp)
    tv, wv = l_val[:, 0].astype(np.int64), l_val[:, 1]
    nll = -(np.take_along_axis(lp, tv[..., None], axis=-1)[..., 0] * wv
            ).sum() / max(wv.sum(), 1.0)
    print(f"packed perplexity is {math.exp(nll):.2f} "
          f"({int(wv.sum())} live targets)")
    return trained


def _generate(args):
    """Sample continuations from a trained LM (reference rnn/Test.scala
    samples from the trained SimpleRNN the same way: seed text -> ids ->
    iterative next-token -> words)."""
    import jax
    import numpy as np

    from bigdl_tpu.cli import common
    from bigdl_tpu.dataset.text import Dictionary, tokenize
    from bigdl_tpu.models import transformer_lm

    path = os.path.join(args.folder, "input.txt")
    with open(path) as f:
        tokens = tokenize(f.read())
    d = Dictionary([tokens], vocab_size=args.vocabSize)

    model = transformer_lm(
        len(d), d_model=args.dModel, num_layers=args.numLayers,
        num_heads=args.numHeads, max_len=args.seqLength)
    params, _ = common.load_trained(model, args.model)

    prompt_ids = np.asarray([d.ids(tokenize(args.prompt))], np.int32)
    if prompt_ids.shape[1] == 0:
        raise SystemExit("empty prompt after tokenization")
    out = model.generate(params, prompt_ids, args.numTokens,
                         temperature=args.temperature, top_k=args.topK,
                         rng=jax.random.PRNGKey(args.seed))
    words = [d.id2word.get(int(i), "<unk>") for i in np.asarray(out)[0]]
    print(args.prompt + " ".join(words))
    return words


if __name__ == "__main__":
    main()
