"""`bigdl-tpu lint` — the tpulint static-analysis CLI (ISSUE 4 + 19).

Trace a perf-zoo model's full train step on CPU in seconds (abstract
inputs, no compile, no device) and report TPU perf/correctness
anti-patterns with rule-level provenance and fix hints:

    python -m bigdl_tpu.cli.main lint resnet50 -b 128
    bigdl-tpu lint resnet50 --fusedBN apply --convLayout GEMM,GEMM,GEMM
    bigdl-tpu lint transformer_lm --seq 600 --strict   # ragged seq -> rc 2
    bigdl-tpu lint lenet5 --json report.json

shardlint (ISSUE 19) extends the same command to every multichip
surface — the strategy's SHARDED train step is traced over an
``AbstractMesh`` (virtual devices, nothing allocated), and the serving
decode step is traced when ``--quantize``/``--speculate``/
``--kvPageTokens`` ask for one, so a laptop CPU lints the exact graph a
pod would compile:

    bigdl-tpu lint transformer_lm --strategy tp:4 --gradCompress bf16+ec \\
        --quantize int8+kv8 --speculate 4 --strict

Configuration flags mirror the perf/training/serve harnesses
(--fusedBN / --convLayout / --convGeom / --autotune / --strategy /
--gradCompress / --gradBuckets / --quantize / --speculate /
--kvPageTokens) so the exact run configuration you are about to launch
is what gets analyzed; ``--strict`` exits nonzero on any error-severity
finding (the CI gate). Rule catalog: PERF.md §12 and §26.
"""

from __future__ import annotations

import argparse
import json


def main(argv=None):
    p = argparse.ArgumentParser(
        "bigdl-tpu lint",
        description="trace-time TPU anti-pattern lint "
                    "(bigdl_tpu.analysis; PERF.md §12, shardlint §26)")
    p.add_argument("model",
                   help="perf model-zoo name (see `bigdl-tpu perf`), "
                        "e.g. resnet50, lenet5, transformer_lm")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--seq", type=int, default=None,
                   help="override the LM sequence length (transformer_lm* "
                        "models) — e.g. 600 demonstrates the ragged-seq "
                        "flash fallback finding")
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--f32", action="store_true",
                   help="analyze the f32 path instead of the bf16 "
                        "TPU-projected default")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero on error-severity findings (what "
                        "--lint=strict does on the perf/training CLIs)")
    p.add_argument("--json", default=None, metavar="FILE",
                   help="also write the full finding list as JSON "
                        "('-' = stdout)")
    p.add_argument("--no-trace", action="store_true",
                   help="module-level rules only (skip the jaxpr pass)")
    from bigdl_tpu.cli.common import (_add_platform_arg, add_autotune_arg,
                                      add_fused_bn_arg, add_grad_comm_args,
                                      add_strategy_arg, apply_platform,
                                      resolve_lint_config)
    _add_platform_arg(p)
    add_autotune_arg(p)
    add_fused_bn_arg(p)
    p.add_argument("--convLayout", default=None, metavar="FWD,DGRAD,WGRAD",
                   help="analyze under this explicit per-pass conv layout "
                        "policy (NHWC|NCHW|GEMM each, or "
                        "'auto'/'default')")
    p.add_argument("--convGeom", default=None, metavar="FILE",
                   help="analyze under this per-geometry conv decision "
                        "JSON (scripts/apply_conv_probe.py --geom)")
    # shardlint (ISSUE 19): the multichip flag families, spelled exactly
    # like the perf/serve CLIs — the mesh is virtual (AbstractMesh), so
    # tp:4 lints on a 1-CPU box in seconds
    add_strategy_arg(p)
    add_grad_comm_args(p)
    p.add_argument("--quantize", default=None,
                   metavar="int8|fp8|int8+kv8|fp8+kv8",
                   help="lint the quantized serving decode step for this "
                        "weight/KV format (mirrors serve --quantize)")
    p.add_argument("--speculate", type=int, default=0, metavar="K",
                   help="lint the speculative decode surface (mirrors "
                        "serve --speculate)")
    p.add_argument("--kvPageTokens", default=None, metavar="N",
                   help="lint the paged-KV decode step with N-token pages "
                        "(mirrors serve --kvPageTokens)")
    p.add_argument("--slots", type=int, default=4,
                   help="decode slots for the serving-surface lint")
    args = p.parse_args(argv)
    apply_platform(args)  # installs --convLayout/--convGeom/--autotune

    cfg = resolve_lint_config(args)

    from bigdl_tpu.analysis import lint_config
    from bigdl_tpu.ops.conv2d import policy_snapshot, restore_policy

    snap = policy_snapshot()
    try:
        report = lint_config(cfg)
    finally:
        restore_policy(snap)

    print(report.render(), flush=True)
    if args.json == "-":
        print(json.dumps(report.to_json(), indent=2), flush=True)
    elif args.json:
        report.dump_json(args.json)
        print(f"lint: wrote {args.json}", flush=True)
    return report.exit_code(strict=args.strict)


if __name__ == "__main__":
    raise SystemExit(main())
