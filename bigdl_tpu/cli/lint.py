"""`bigdl-tpu lint` — the tpulint static-analysis CLI (ISSUE 4).

Trace a perf-zoo model's full train step on CPU in seconds (abstract
inputs, no compile, no device) and report TPU perf/correctness
anti-patterns with rule-level provenance and fix hints:

    python -m bigdl_tpu.cli.main lint resnet50 -b 128
    bigdl-tpu lint resnet50 --fusedBN apply --convLayout GEMM,GEMM,GEMM
    bigdl-tpu lint transformer_lm --seq 600 --strict   # ragged seq -> rc 2
    bigdl-tpu lint lenet5 --json report.json

Configuration flags mirror the perf harness (--fusedBN / --convLayout /
--convGeom / --autotune) so the exact run configuration you are about to
launch is what gets analyzed; ``--strict`` exits nonzero on any
error-severity finding (the CI gate). Rule catalog: PERF.md §12.
"""

from __future__ import annotations

import argparse
import json


def main(argv=None):
    p = argparse.ArgumentParser(
        "bigdl-tpu lint",
        description="trace-time TPU anti-pattern lint "
                    "(bigdl_tpu.analysis; PERF.md §12)")
    p.add_argument("model",
                   help="perf model-zoo name (see `bigdl-tpu perf`), "
                        "e.g. resnet50, lenet5, transformer_lm")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--seq", type=int, default=None,
                   help="override the LM sequence length (transformer_lm* "
                        "models) — e.g. 600 demonstrates the ragged-seq "
                        "flash fallback finding")
    p.add_argument("--classes", type=int, default=1000)
    p.add_argument("--f32", action="store_true",
                   help="analyze the f32 path instead of the bf16 "
                        "TPU-projected default")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero on error-severity findings (what "
                        "--lint=strict does on the perf/training CLIs)")
    p.add_argument("--json", default=None, metavar="FILE",
                   help="also write the full finding list as JSON "
                        "('-' = stdout)")
    p.add_argument("--no-trace", action="store_true",
                   help="module-level rules only (skip the jaxpr pass)")
    from bigdl_tpu.cli.common import (_add_platform_arg, add_autotune_arg,
                                      add_fused_bn_arg, apply_platform)
    _add_platform_arg(p)
    add_autotune_arg(p)
    add_fused_bn_arg(p)
    p.add_argument("--convLayout", default=None, metavar="FWD,DGRAD,WGRAD",
                   help="analyze under this explicit per-pass conv layout "
                        "policy (NHWC|NCHW|GEMM each, or "
                        "'auto'/'default')")
    p.add_argument("--convGeom", default=None, metavar="FILE",
                   help="analyze under this per-geometry conv decision "
                        "JSON (scripts/apply_conv_probe.py --geom)")
    args = p.parse_args(argv)
    apply_platform(args)  # installs --convLayout/--convGeom/--autotune

    import jax.numpy as jnp

    from bigdl_tpu.analysis import lint_perf_model
    from bigdl_tpu.ops.conv2d import policy_snapshot, restore_policy

    snap = policy_snapshot()
    try:
        report = lint_perf_model(
            args.model, args.batchSize, seq_len=args.seq,
            dtype=jnp.float32 if args.f32 else None,
            fused_bn=args.fusedBN, classes=args.classes,
            trace=not getattr(args, "no_trace", False))
    finally:
        restore_policy(snap)

    print(report.render(), flush=True)
    if args.json == "-":
        print(json.dumps(report.to_json(), indent=2), flush=True)
    elif args.json:
        report.dump_json(args.json)
        print(f"lint: wrote {args.json}", flush=True)
    return report.exit_code(strict=args.strict)


if __name__ == "__main__":
    raise SystemExit(main())
