"""Record-shard generator CLI (reference
models/utils/ImageNetSeqFileGenerator.scala — parallel workers packing
ImageNet folders into 512-image Hadoop SequenceFiles; here: .btr record
shards, bigdl_tpu/dataset/recordfile.py).

    python -m bigdl_tpu.cli.record_gen -f /data/imagenet -o /data/records \
        -b 512 -p 8

Expects ``<folder>/train`` and/or ``<folder>/val`` label-by-folder trees
(falls back to treating ``<folder>`` itself as one split). Training then
reads the shards with ``RecordImageDataSet(out_dir/train, ...)``.
"""

from __future__ import annotations

import argparse
import os


def main(argv=None):
    p = argparse.ArgumentParser("bigdl-tpu record-gen")
    p.add_argument("-f", "--folder", required=True,
                   help="imagenet-style root (train/ and val/ subfolders)")
    p.add_argument("-o", "--output", required=True)
    p.add_argument("-b", "--blockSize", type=int, default=512,
                   help="images per shard (reference default 512)")
    p.add_argument("-p", "--parallel", type=int, default=8)
    p.add_argument("--limit", type=int, default=None,
                   help="cap images per split (debug)")
    args = p.parse_args(argv)

    from bigdl_tpu.dataset.recordfile import write_image_shards

    splits = [s for s in ("train", "val")
              if os.path.isdir(os.path.join(args.folder, s))]
    if not splits:
        splits = [""]
    for s in splits:
        src = os.path.join(args.folder, s) if s else args.folder
        dst = os.path.join(args.output, s) if s else args.output
        shards = write_image_shards(
            src, dst, prefix=s or "data",
            images_per_shard=args.blockSize, workers=args.parallel,
            limit=args.limit)
        print(f"{src}: wrote {len(shards)} shards to {dst}")


if __name__ == "__main__":
    main()
