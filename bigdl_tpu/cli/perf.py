"""Synthetic-data training throughput harness (reference
models/utils/DistriOptimizerPerf.scala:35-150 / LocalOptimizerPerf.scala —
constant/random synthetic input, models inception/vgg/resnet, reports the
canonical records/second; extended with MFU, which the reference lacks but
the BASELINE north-star requires).

    python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType constant
"""

from __future__ import annotations

import argparse
import json
import os
import time


# bf16 peak per chip (public figures); used for the MFU estimate. Matched
# by substring against the *squashed* (space-stripped, lowered) device_kind,
# most specific first, so real-world kinds like "TPU v5 lite" (v5e), "TPU
# v5p slice", "TPU v4 lite" all resolve. Round-2 bug: the old table missed
# "TPU v5 lite" and fell back silently to the 1e12 nominal, inflating MFU
# ~197x; the match label is now reported alongside the number so a fallback
# can never hide again.
_PEAK_FLOPS = (
    ("v6lite", 918e12), ("v6e", 918e12), ("trillium", 918e12),
    ("v5lite", 197e12), ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4lite", 138e12), ("v4", 275e12),
    ("v3", 123e12), ("v2", 46e12),
    ("cpu", 1e12),  # nominal, so MFU stays defined in CPU test runs
)


def _peak_flops(device):
    """Return (peak_bf16_flops, matched_label) for one chip."""
    kind = getattr(device, "device_kind", "cpu") or "cpu"
    squashed = kind.replace(" ", "").replace("-", "").lower()
    for k, v in _PEAK_FLOPS:
        if k in squashed:
            return v, k
    return 1e12, f"UNMATCHED({kind})->1e12-nominal"


_LM_VOCAB = 32000  # shared by the model head and the synthetic token data


def _bn_subset(m, k: int = 32):
    from bigdl_tpu.nn import set_bn_stat_sample
    return set_bn_stat_sample(m, k)


def _bn_fused(m, mode=True):
    from bigdl_tpu.nn import set_bn_fused
    return set_bn_fused(m, mode)


# build_model(seq_len=..., lm_attn_impl=...) installs overrides here for
# the duration of one table call — tpulint builds LMs with the flash
# kernel forced on (TPU-projected trace off-chip) and a custom seq
_LM_OVERRIDE: dict = {}


def _lm(*, num_kv_heads=2, pos_encoding="rope", **kw):
    """Shared LM-config plumbing for the perf model zoo (vocab + the
    backend-conditional flash selection live in ONE place)."""
    import jax

    from bigdl_tpu import models

    kw = dict(kw)
    kw.setdefault("attn_impl",
                  "flash" if jax.default_backend() == "tpu" else None)
    kw.update(_LM_OVERRIDE)
    return models.transformer_lm(
        _LM_VOCAB, pos_encoding=pos_encoding, num_kv_heads=num_kv_heads,
        **kw)


def build_model(name: str, class_num: int = 1000, seq_len=None,
                lm_attn_impl=None):
    import jax

    from bigdl_tpu import models

    table = {
        "inception_v1": lambda: models.inception_v1_no_aux(class_num),
        "inception_v2": lambda: models.inception_v2(class_num),
        "vgg16": lambda: models.vgg16(class_num),
        "vgg19": lambda: models.vgg19(class_num),
        "alexnet": lambda: models.alexnet(class_num),
        "resnet50": lambda: models.resnet50(class_num),
        "resnet50_s2d": lambda: models.resnet50(class_num, s2d_stem=True),
        # BN stats from 32 batch rows: cuts the stats-pass HBM re-read of
        # every activation (the dominant BN cost, PERF.md §2) by b/32
        "resnet50_bnss": lambda: _bn_subset(models.resnet50(class_num)),
        # single-read Pallas BN stats (ops/bn_kernel.py): the stats pass
        # is the #1 sync op category (PERF.md §2); exact semantics,
        # unlike the bnss subset sampling. Measured −46% on chip (§8.2)
        # — kept as the A/B middle leg against _fba below
        "resnet50_fbn": lambda: _bn_fused(models.resnet50(class_num)),
        # the FULL fused BN block (ISSUE 2): stats+apply+absorbed-ReLU
        # one kernel forward, Σdy/Σ(dy·x̂)+dx one kernel backward —
        # attacks the 34 ms backward (PERF.md §10) where the stats-only
        # kernel above LOST 46% by unfusing its elementwise neighbors
        "resnet50_fba": lambda: _bn_fused(models.resnet50(class_num),
                                          "apply"),
        # CIFAR-shaped depth-20 resnet (reference models/resnet/README
        # recipe) — the fast time-to-accuracy config
        "resnet20_cifar": lambda: models.resnet_cifar(
            20, class_num if class_num != 1000 else 10),
        "lenet5": lambda: models.lenet5(10),
        # beyond-reference vision family: patchify conv (3*16*16 = 768
        # contraction vs the resnet stem's MXU-starved 3-channel 7x7),
        # 128-wide heads, flash on TPU — see models/vit.py
        "vit_b16": lambda: models.vit_b16(
            class_num, attn_impl=("flash" if jax.default_backend() ==
                                  "tpu" else None)),
        "vit_s16": lambda: models.vit_s16(
            class_num, attn_impl=("flash" if jax.default_backend() ==
                                  "tpu" else None)),
        # causal LMs, 32k vocab. _lm fills the shared plumbing: the
        # Pallas flash kernel only off-interpret on TPU; elsewhere the
        # dense path keeps CPU benchmark runs fast.
        "transformer_lm": lambda: _lm(
            d_model=512, num_layers=8, num_heads=8, max_len=512,
            pos_encoding="sinusoidal", num_kv_heads=None),
        # modern-config A/B: RoPE + grouped-query (2 kv heads)
        "transformer_lm_rope": lambda: _lm(
            d_model=512, num_layers=8, num_heads=8, max_len=512),
        # larger config at 1k context: matmuls big enough that MFU
        # reflects the MXU, not dispatch/embedding overhead
        "transformer_lm_1k": lambda: _lm(
            d_model=1024, num_layers=12, num_heads=16, max_len=1024,
            num_kv_heads=4),
        # head-dim A/B: same d_model/layers/FLOPs, 8 heads of 128 instead
        # of 16 of 64 — the MXU contracts over the head dim in both
        # attention matmuls, and 64 lanes half-fills its 128-wide tiles.
        # Measured +24% tok/s on chip at 512-wide flash blocks; 53.7%
        # MFU — past the 50% north star (PERF.md §8.2).
        "transformer_lm_1k_hd128": lambda: _lm(
            d_model=1024, num_layers=12, num_heads=8, max_len=1024),
        # long-context flagship: 16k tokens END-TO-END through the
        # training step on one chip — flash-only territory (dense
        # attention needs a 17 GB score matrix from seq 8k up and
        # OOM-fails, PERF.md §8.2); remat='dots' keeps the MXU outputs
        # resident and recomputes the bandwidth-bound intermediates
        "transformer_lm_16k": lambda: _lm(
            d_model=1024, num_layers=12, num_heads=8, max_len=16384,
            remat="dots"),
        # 32k: double the 16k flagship — the flash kernel is
        # compiled-verified at this length (flash_bench; dense needs a
        # 68 GB score matrix), full-recompute remat for the activations
        "transformer_lm_32k": lambda: _lm(
            d_model=1024, num_layers=12, num_heads=8, max_len=32768,
            remat="full"),
    }
    if name not in table:
        raise SystemExit(f"unknown model {name}; choose from {list(table)}")
    size = {"lenet5": (28, 28, 1),
            "resnet20_cifar": (32, 32, 3),
            "transformer_lm": (512,),
            "transformer_lm_rope": (512,),
            "transformer_lm_1k": (1024,),
            "transformer_lm_1k_hd128": (1024,),
            "transformer_lm_16k": (16384,),
            "transformer_lm_32k": (32768,)}.get(name, (224, 224, 3))
    # LM build overrides (tpulint): forced attn_impl and/or seq length
    # apply only to transformer_lm* names and only for this one call
    global _LM_OVERRIDE
    over = {}
    if name.startswith("transformer_lm"):
        if lm_attn_impl is not None:
            over["attn_impl"] = lm_attn_impl
        if seq_len is not None:
            over["max_len"] = int(seq_len)
            size = (int(seq_len),)
    prev = _LM_OVERRIDE
    _LM_OVERRIDE = over
    try:
        model = table[name]()
    finally:
        _LM_OVERRIDE = prev
    return model, size


def _short_side(crop) -> int:
    """The resize target feeding a random crop: the standard 256-for-224
    headroom ratio, generalized so non-224 image models (resnet20_cifar)
    can train from record shards too."""
    if tuple(crop) == (224, 224):
        return 256
    return max(8, (max(crop) * 8) // 7)


def _record_batches(source: str, batch: int, n_threads: int = 0,
                    crop=(224, 224)):
    """Endless MiniBatch iterator over ``record:<shard-dir>`` — the
    train-from-storage bench path (decode + per-sample augment + batch +
    host->device all inside the timed loop; round-2 weak #2: the synthetic
    bench can't see an input-bound regime)."""
    import os

    from bigdl_tpu.dataset.streaming import RecordImageDataSet

    ds = RecordImageDataSet(
        source, batch_size=batch, crop=crop, train=True,
        short_side=_short_side(crop),
        mean=[123.68, 116.779, 103.939],
        std=[58.4, 57.1, 57.4],
        n_threads=n_threads or min(32, (os.cpu_count() or 4) * 2),
        window=4)
    while True:
        for mb in ds:
            yield mb


def _executor_record_batches(source: str, batch: int, workers: int,
                             depth: int = 2, stage: str = "off",
                             strategy=None, crop=(224, 224)):
    """Endless executor-fed record feed (ISSUE 13): the SAME decode/
    augment recipe as :func:`_record_batches` (so A/B rows compare the
    feed machinery, not the pipeline params), driven by the
    ``dataset/pipeline/`` executor + optional host->device staging.
    Returns ``(iterator, provenance dict)``."""
    from bigdl_tpu.dataset.pipeline import (EpochPlan, ExecutorDataSet,
                                            StagedDataSet,
                                            StreamingSampleSource)
    from bigdl_tpu.dataset.streaming import RecordImageDataSet

    # worker parallelism lives in the executor now — the inner dataset
    # only contributes its per-sample decode path (_load_sample)
    rds = RecordImageDataSet(
        source, batch_size=batch, crop=crop, train=True,
        short_side=_short_side(crop), mean=[123.68, 116.779, 103.939],
        std=[58.4, 57.1, 57.4], n_threads=1, window=1)
    src = StreamingSampleSource(rds)
    plan = EpochPlan(len(src), batch, seed=rds.seed, shuffle=True,
                     process_index=0, process_count=1)
    ds = ExecutorDataSet(src, workers=workers, depth=depth, plan=plan)
    prov_ds = ds
    if stage != "off":
        ds = StagedDataSet(ds, stage=stage, depth=depth, strategy=strategy)
        prov_ds = ds

    def endless():
        while True:
            for mb in ds:
                yield mb
            ds.shuffle()  # advance the plan epoch (legacy feed parity)

    return endless(), prov_ds.signature()


def _annotate_conv_layouts(out: dict) -> None:
    """Stamp the active non-default conv layout policy — global triple
    AND installed per-geometry decisions — into a result dict; shared by
    run() and run_time_to_acc() so their JSON provenance cannot drift
    apart. Delegates to the shared assembly (ISSUE 18 satellite) —
    perf JSON, /metrics _info, bench companions, and batch-predict all
    read the same code now."""
    from bigdl_tpu.cli.provenance import provenance_dict
    core = provenance_dict()
    for k in ("conv_layouts", "conv_geom"):
        if k in core:
            out[k] = core[k]


def _annotate_autotune(out: dict) -> None:
    """Stamp the run's tuning provenance (mode + per-key decision or
    'default') into a result dict — ISSUE 1 acceptance: every perf JSON
    line says which decisions it ran under."""
    from bigdl_tpu.cli.provenance import provenance_dict
    core = provenance_dict()
    if "autotune" in core:
        out["autotune"] = core["autotune"]


def _annotate_bn_fused(out: dict, model) -> None:
    """Stamp the model's effective BN fusion mode (off/stats/apply) the
    same way the autotune decisions are stamped, so fused-vs-stats-vs-
    default A/B rows are self-describing (ISSUE 2 satellite)."""
    from bigdl_tpu.cli.provenance import provenance_dict
    out["bn_fused"] = provenance_dict(model)["bn_fused"]


_PHASE_COLUMNS = ("data_wait_s", "h2d_s", "dispatch_s", "device_s",
                  "ckpt_s", "stall_frac")
# ISSUE 8: attribution columns, schema-stable like the phase columns —
# null until a capture window closed (no capture = no device timeline to
# attribute), then the per-step collective seconds, the collective share
# of device time, and the compact per-category attribution of the run's
# LAST verified window.
_ATTRIB_COLUMNS = ("collective_s", "collective_frac", "attrib")
# ISSUE 12: the memory columns, schema-stable like the attrib columns —
# null obs-off; under --obs the peak HBM bytes (live device.memory_stats
# when the backend has them, else the static plan's modeled total), the
# headroom fraction against the matched per-chip capacity, and the
# compact per-category plan as the `mem` detail dict.
_MEM_COLUMNS = ("hbm_peak_bytes", "hbm_headroom_frac", "mem")


def _annotate_obs_phases(out: dict, obs_state, phase: dict | None = None,
                         wall_s: float | None = None) -> None:
    """Stamp the step-phase columns into a result dict (ISSUE 7). The
    columns are ALWAYS present so the JSON schema is stable: null in an
    obs-off run (whose output stays byte-identical to pre-obs output
    modulo exactly these nulls), measured cumulative seconds under
    --obs. ``stall_frac`` is the feed-stall fraction of wall time — the
    number PERF.md §4 could previously only infer. Under --obs the
    trace/capture artifacts ride along as ``obs``, and a closed capture
    window additionally fills the attribution columns (ISSUE 8)."""
    for c in _ATTRIB_COLUMNS:
        out[c] = None
    for c in _MEM_COLUMNS:
        out[c] = None
    on = (obs_state is not None and obs_state.enabled
          and phase is not None)
    if not on:
        for c in _PHASE_COLUMNS:
            out[c] = None
        return
    out["data_wait_s"] = round(phase.get("data_wait", 0.0), 4)
    out["h2d_s"] = round(phase.get("h2d", 0.0), 4)
    out["dispatch_s"] = round(phase.get("dispatch", 0.0), 4)
    out["device_s"] = round(phase.get("device", 0.0), 4)
    out["ckpt_s"] = round(phase.get("ckpt", 0.0), 4)
    out["stall_frac"] = (round(phase.get("data_wait", 0.0) / wall_s, 4)
                         if wall_s else None)
    plan = getattr(obs_state, "mem_plan", None)
    sampler = getattr(obs_state, "mem_sampler", None)
    if plan is not None:
        from bigdl_tpu.obs import memory as _mem
        live_peak = (sampler.peak_bytes if sampler is not None else None)
        peak = live_peak or plan["total_bytes"]
        cap = plan["hbm_bytes"]
        out["hbm_peak_bytes"] = int(peak)
        out["hbm_headroom_frac"] = (round((cap - peak) / cap, 4)
                                    if cap else None)
        m = _mem.compact(plan)
        m["source"] = "live" if live_peak else "plan"
        live = (sampler.annotation() if sampler is not None else None)
        if live:
            m["live"] = live
        out["mem"] = m
    info = obs_state.finalize()
    o: dict = {}
    if "trace_json" in info:
        o["trace_json"] = info["trace_json"]
        o["span_events"] = info["span_events"]
    if "metrics_port" in info:  # the bound (or auto-picked) listener
        o["metrics_port"] = info["metrics_port"]
        o["metrics_url"] = info["metrics_url"]
    if "captures" in info:
        o["captures"] = [
            {k: c[k] for k in ("start_step", "stop_step", "trigger",
                               "ok", "dir", "error", "attrib",
                               "attrib_error") if k in c}
            for c in info["captures"]]
        for c in reversed(info["captures"]):
            a = c.get("attrib")
            if a:  # newest attributed window wins
                steps = max(1, int(a.get("steps") or 1))
                out["attrib"] = a
                out["collective_s"] = round(
                    a["collective_s"] / steps, 6)
                out["collective_frac"] = a["collective_frac"]
                break
    if o:
        out["obs"] = o


def _annotate_supervisor(out: dict, supervisor) -> None:
    """Stamp the structured fault/recovery log next to bn_fused/lint
    (ISSUE 6): under --supervise the full supervisor annotation
    (attempts/retries/events incl. injected faults); with only a
    --faultPlan active, the raw injected-fault events — either way a
    perf row produced under faults says so."""
    if supervisor is not None:
        out["supervisor"] = supervisor.annotation()
        return
    from bigdl_tpu.resilience.faults import injected_events
    ev = injected_events()
    if ev:
        out["faults"] = ev


# (d_model, layers, heads, seq) of the LM zoo configs — the pp/ep
# harness builders below size their pipeline stack / MoE block from the
# requested model name so an A/B against the dp/tp/sp legs compares the
# same transformer geometry
_LM_GEOM = {
    "transformer_lm": (512, 8, 8, 512),
    "transformer_lm_rope": (512, 8, 8, 512),
    "transformer_lm_1k": (1024, 12, 16, 1024),
    "transformer_lm_1k_hd128": (1024, 12, 8, 1024),
    "transformer_lm_16k": (1024, 12, 8, 16384),
    "transformer_lm_32k": (1024, 12, 8, 32768),
}


def _setup_strategy_harness(strat_name: str, model_name: str, batch: int,
                            mesh, mesh_axes: dict, dtype,
                            seq_len: int | None):
    """Build the pp/ep timed-loop pieces (ISSUE 8). These strategies
    compose with the STEP structure, not just parameter placement — a
    GPipe pipeline schedules microbatches through ppermute hops, an
    expert-parallel MoE routes tokens — so they get dedicated builders
    that return a step with the harness's uniform
    ``(params, mod_state, opt_state, x, y, rng) -> 4-tuple`` signature.
    Geometry comes from the requested transformer_lm* config
    (:data:`_LM_GEOM`, seq overridable via --seq); the criterion is MSE
    over the block stack (embedding/head run replicated outside a real
    pipeline and are excluded, exactly like the MULTICHIP_r05 dryrun)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.optim import SGD

    geom = _LM_GEOM.get(model_name)
    if geom is None:
        raise SystemExit(
            f"--strategy {strat_name} sizes its transformer stack from "
            f"the model name; choose one of {sorted(_LM_GEOM)}")
    d_model, layers, heads, seq = geom
    if seq_len is not None:
        seq = int(seq_len)
    crit = nn.MSECriterion()
    opt = SGD(learning_rate=0.01, momentum=0.9)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(batch, seq, d_model), dtype)
    y = jnp.asarray(rs.randn(batch, seq, d_model), dtype)

    if strat_name == "pp":
        from bigdl_tpu.parallel import (PipelineStack,
                                        make_pipeline_train_step,
                                        place_pipeline_params)

        stages = mesh_axes["pipe"]
        if layers % stages:
            raise SystemExit(
                f"--strategy pp: {layers} layers must divide over "
                f"{stages} pipeline stages (try pp:{layers // 2} or a "
                "deeper model)")
        micro = stages  # GPipe bubble (P-1)/(M+P-1); M=P keeps it <50%
        data_ax = mesh_axes.get("data", 1)
        if batch % micro or (batch // micro) % data_ax:
            raise SystemExit(
                f"--strategy pp: batch {batch} must split into {micro} "
                f"microbatches of a multiple of the data axis "
                f"({data_ax})")
        stack = PipelineStack(
            nn.TransformerEncoderLayer(d_model=d_model, num_heads=heads,
                                       d_ff=4 * d_model), layers)
        params = place_pipeline_params(mesh,
                                       stack.init(jax.random.PRNGKey(0)),
                                       "pipe")
        opt_state = opt.init(jax.device_get(params))
        compile_for = make_pipeline_train_step(
            stack, mesh, crit, opt, microbatches=micro, axis="pipe",
            data_axis="data")
        raw = compile_for(opt_state, params)

        def step(params, mod_state, opt_state, x, y, rng):
            p, o, loss = raw(params, opt_state, x, y, rng)
            return p, mod_state, o, loss

        return {"step": step, "single_step": step, "params": params,
                "opt_state": opt_state, "x": x, "y": y,
                "in_shape": (seq, d_model)}

    # ep: expert-parallel MoE — experts sharded over the expert axis,
    # the top-2 router's dispatch/combine einsums become the measured
    # all-to-all-shaped traffic
    from bigdl_tpu.core import Sequential

    n_exp = mesh_axes["expert"]
    moe = nn.MoE(Sequential(nn.Linear(d_model, 2 * d_model), nn.ReLU(),
                            nn.Linear(2 * d_model, d_model)),
                 num_experts=n_exp, d_model=d_model, top_k=2,
                 capacity_factor=2.0)
    params = moe.place_expert_parallel(mesh,
                                       moe.init(jax.random.PRNGKey(0)))
    opt_state = opt.init(params)

    def train_step(params, mod_state, opt_state, x, y, rng):
        def loss_fn(p):
            out, st = moe.apply(p, moe.init_state(), x, training=True)
            return (crit(out.astype(jnp.float32),
                         y.astype(jnp.float32))
                    + 0.01 * st["aux_loss"])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        new_p, new_o = opt.update(grads, opt_state, params)
        return new_p, mod_state, new_o, loss

    step = jax.jit(train_step, donate_argnums=(0, 2))
    return {"step": step, "single_step": train_step, "params": params,
            "opt_state": opt_state, "x": x, "y": y,
            "in_shape": (seq, d_model)}


def run(model_name: str, batch: int, iterations: int, data_type: str,
        use_bf16: bool = True, data_parallel: bool = False,
        data_source: str | None = None, inner_steps: int = 1,
        profile_dir: str | None = None, autotune: str | None = None,
        fused_bn: str | None = None, lint: dict | None = None,
        supervisor=None, obs_state=None, strategy: str | None = None,
        seq_len: int | None = None, grad_compress: str | None = None,
        grad_buckets: str | None = None, elastic=None,
        data_workers: int = 0, prefetch_depth: int = 2,
        stage: str = "off"):
    """Throughput harness entry. ``autotune`` optionally installs the
    tuning mode (the CLI does it via --autotune/apply_platform; bench.py
    children pass it directly). ``fused_bn`` ('off'/'stats'/'apply')
    installs the Pallas BN path on the built model — the flag spelling of
    the resnet50_fbn/_fba model names. ``strategy`` ('dp'/'tp'/'sp'/
    'pp'/'ep', optionally NAME:K) runs the timed loop over every visible
    device via the ``parallel/`` API (ISSUE 8); ``data_parallel`` is the
    deprecated alias for 'dp'. ``grad_compress``/``grad_buckets`` are the
    --gradCompress/--gradBuckets pair (ISSUE 10): bucketed 16-bit
    gradient all-reduce under a multi-device dp/tp strategy. The conv
    layout policy is snapshotted and restored so back-to-back runs in
    one process stay independent (ADVICE r5 #1)."""
    from bigdl_tpu import tuning
    from bigdl_tpu.ops import conv2d

    if autotune is not None:
        tuning.set_mode(autotune)
    tuning.reset_decisions()
    snap = conv2d.policy_snapshot()
    try:
        return _run_timed(model_name, batch, iterations, data_type,
                          use_bf16=use_bf16, data_parallel=data_parallel,
                          data_source=data_source, inner_steps=inner_steps,
                          profile_dir=profile_dir, fused_bn=fused_bn,
                          lint=lint, supervisor=supervisor,
                          obs_state=obs_state, strategy=strategy,
                          seq_len=seq_len, grad_compress=grad_compress,
                          grad_buckets=grad_buckets, elastic=elastic,
                          data_workers=data_workers,
                          prefetch_depth=prefetch_depth, stage=stage)
    finally:
        conv2d.restore_policy(snap)


def _run_timed(model_name: str, batch: int, iterations: int, data_type: str,
               use_bf16: bool = True, data_parallel: bool = False,
               data_source: str | None = None, inner_steps: int = 1,
               profile_dir: str | None = None,
               fused_bn: str | None = None, lint: dict | None = None,
               supervisor=None, obs_state=None,
               strategy: str | None = None, seq_len: int | None = None,
               grad_compress: str | None = None,
               grad_buckets: str | None = None, elastic=None,
               data_workers: int = 0, prefetch_depth: int = 2,
               stage: str = "off"):
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    # elastic attempt wall-clock starts here: on a post-loss retry the
    # mesh re-formation + rebuild + recompile up to warmup IS restore_ms
    t_attempt0 = time.perf_counter()

    # persistent compile cache: repeat benchmark runs (the capture
    # sweeps re-measure the same configs) skip the 20-40s TPU compile
    from bigdl_tpu.cli import common as _common
    _common.enable_compile_cache()

    # ----- strategy resolution (ISSUE 8): the hidden data_parallel-only
    # branch is gone — all five MULTICHIP-validated families resolve
    # through the shared cli/common machinery (mesh shapes, the
    # innerSteps x strategy SystemExit contract), with --dataParallel
    # kept as a deprecated alias for dp that still degrades silently on
    # one device (its historical behavior)
    strat_spec = strategy if strategy is not None else (
        "dp" if data_parallel else None)
    strat_name, strat_k = _common.parse_strategy_spec(strat_spec)
    mesh = None
    mesh_axes = None
    elastic_devices = None
    if elastic is not None:
        if strat_name != "dp":
            raise SystemExit(
                "--elastic composes with --strategy dp only (elastic "
                "reshape is a data-parallel contract)")
        # the surviving-device roster for THIS attempt; below
        # --minDevices this raises SupervisorGaveUp (clean give-up,
        # never a retry)
        elastic_devices = elastic.probe()
    if strat_name is not None:
        n_all = (len(elastic_devices) if elastic_devices is not None
                 else len(jax.devices()))
        if n_all <= 1:
            if strategy is not None:
                raise SystemExit(
                    f"--strategy {strat_name} needs more than one "
                    "device; off-chip set XLA_FLAGS="
                    "--xla_force_host_platform_device_count=8 (the "
                    "MULTICHIP dryrun recipe)")
            strat_name = None  # deprecated alias: historical no-op
        else:
            _common.check_strategy_dispatch(inner_steps, "--innerSteps")
            if (strat_name == "sp"
                    and not model_name.startswith("transformer_lm")):
                # usage error regardless of the jax build — report it
                # before the capability guard below
                raise SystemExit(
                    "--strategy sp shards the sequence axis via ring "
                    "attention; it needs a transformer_lm* model")
            if strat_name in ("sp", "pp") and not hasattr(jax,
                                                          "shard_map"):
                # ring attention / the pipeline schedule run inside
                # jax.shard_map (the API the MULTICHIP dryruns
                # validate); older jax only ships the experimental
                # spelling with different kwargs — refuse cleanly
                # instead of crashing mid-build
                raise SystemExit(
                    f"--strategy {strat_name} needs jax.shard_map; "
                    f"this jax ({jax.__version__}) predates it — "
                    "dp/tp/ep still run")
            mesh_axes = _common.strategy_mesh_axes(strat_name, n_all,
                                                   strat_k)
            from bigdl_tpu.parallel import make_mesh
            mesh = make_mesh(mesh_axes, elastic_devices)
            data_ax = mesh_axes.get("data", 1)
            if batch % data_ax and elastic is None:
                # elastic runs pad/trim to divisibility instead
                # (ElasticDataParallel.shard_batch, --elastic policy)
                raise SystemExit(
                    f"batch {batch} must be divisible by the data axis "
                    f"({data_ax}) of --strategy {strat_name} "
                    f"(mesh {mesh_axes})")

    # ----- gradient-communication config (ISSUE 10): bucketed 16-bit
    # all-reduce through DataParallel.reduce_grads — so it composes with
    # the strategies that route grads there (dp/tp/sp); pp/ep build
    # their own step structure and refuse cleanly rather than silently
    # running uncompressed
    from bigdl_tpu.parallel.grad_comm import make_config as _mk_grad_comm
    try:
        grad_comm_cfg = _mk_grad_comm(grad_compress, grad_buckets)
    except ValueError as e:
        raise SystemExit(str(e))
    if grad_comm_cfg is not None and grad_comm_cfg.active:
        if strat_name is None:
            raise SystemExit(
                "--gradCompress compresses the cross-device gradient "
                "all-reduce; it needs a multi-device --strategy (dp/tp)")
        if strat_name in ("pp", "ep"):
            raise SystemExit(
                f"--gradCompress rides DataParallel.reduce_grads; "
                f"--strategy {strat_name} builds its own step structure "
                "and has no replicated-grad all-reduce to compress")

    # conv-layout decision for this device AND run configuration. The
    # window-2 combination matrix (PERF.md §8.2) measured the shipped
    # decision POSITIVE alone (+1.1%) but NEGATIVE chained with
    # inner-stepping (2,630 vs 2,678 img/s) or the s2d stem (2,579 vs
    # 2,674) — so those configurations resolve their own autotune keys
    # (default all-NHWC until measured) instead of skipping installation
    # and inheriting whatever an earlier run left behind. inner_steps is
    # normalized to 1 further down for data_source runs — mirror that
    # here so those (plain-dispatch) runs still get the decision
    _eff_inner = (1 if (data_source is not None or strat_name is not None)
                  else inner_steps)
    from bigdl_tpu import tuning
    tuning.install_conv_layouts(
        "s2d" if model_name.endswith("_s2d")
        else ("inner" if _eff_inner > 1 else "plain"))

    from bigdl_tpu import nn
    from bigdl_tpu.optim import SGD

    on_tpu = jax.default_backend() == "tpu"
    dtype = jnp.bfloat16 if (use_bf16 and on_tpu) else jnp.float32

    if strat_name in ("pp", "ep"):
        # pipeline/expert parallelism compose with the STEP structure,
        # not just the placement — dedicated harness builders below
        setup = _setup_strategy_harness(strat_name, model_name, batch,
                                        mesh, mesh_axes, dtype, seq_len)
        model, in_shape, is_lm = None, setup["in_shape"], False
        params, mod_state, opt_state = (setup["params"], {},
                                        setup["opt_state"])
        x, y = setup["x"], setup["y"]
        step, single_step = setup["step"], setup["single_step"]
        strat = None
    else:
        lm_attn = None
        if strat_name == "sp":
            if not model_name.startswith("transformer_lm"):
                raise SystemExit(
                    "--strategy sp shards the sequence axis via ring "
                    "attention; it needs a transformer_lm* model")
            from bigdl_tpu.parallel import make_ring_attention
            lm_attn = make_ring_attention(mesh, "seq", batch_axis="data")

        model, in_shape = build_model(model_name, seq_len=seq_len,
                                      lm_attn_impl=lm_attn)
        _common.apply_fused_bn(model, fused_bn)
        is_lm = model_name.startswith("transformer_lm")
        if strat_name == "sp" and in_shape[0] % mesh_axes["seq"]:
            raise SystemExit(
                f"--strategy sp: sequence length {in_shape[0]} must be "
                f"divisible by the seq axis ({mesh_axes['seq']}); "
                "shrink/resize with --seq")
        crit = (nn.TimeDistributedCriterion(nn.ClassNLLCriterion())
                if is_lm else nn.ClassNLLCriterion())
        opt = SGD(learning_rate=0.01, momentum=0.9)

        rng = np.random.RandomState(0)
        if is_lm:  # token ids in, per-token targets
            if dtype == jnp.bfloat16:
                model.compute_dtype = dtype  # cast lives after the
                # embedding
            x_host = rng.randint(0, _LM_VOCAB,
                                 (batch, *in_shape)).astype(np.int32)
            y_host = rng.randint(0, _LM_VOCAB,
                                 (batch, *in_shape)).astype(np.int32)
        elif data_type == "constant":
            x_host = np.ones((batch, *in_shape), np.float32)
            y_host = rng.randint(0, 1000 if in_shape[0] > 30 else 10,
                                 batch).astype(np.int32)
        else:
            x_host = rng.randn(batch, *in_shape).astype(np.float32)
            y_host = rng.randint(0, 1000 if in_shape[0] > 30 else 10,
                                 batch).astype(np.int32)

        params = model.init(jax.random.PRNGKey(0))
        mod_state = model.init_state()
        opt_state = opt.init(params)

        strat = None
        if strat_name == "dp" and elastic is not None:
            # elastic dp: batch placement pads (hold) or trims (scale)
            # to the surviving data-axis size; everything else is plain
            # DataParallel, so at full topology this is bit-identical
            from bigdl_tpu.resilience.elastic import ElasticDataParallel

            strat = ElasticDataParallel(mesh,
                                        batch_policy=elastic.batch_policy,
                                        grad_comm=grad_comm_cfg)
        elif strat_name == "dp" or strat_name == "sp":
            from bigdl_tpu.parallel import DataParallel

            strat = DataParallel(mesh, grad_comm=grad_comm_cfg)
        elif strat_name == "tp":
            from bigdl_tpu.parallel import TensorParallel

            strat = TensorParallel(mesh, model)
            strat.grad_comm = grad_comm_cfg
        if strat is not None:
            params, mod_state, opt_state = strat.place(
                params, mod_state, opt_state)

        def train_step(params, mod_state, opt_state, x, y, rng):
            def loss_fn(p):
                xc = x.astype(dtype) if jnp.issubdtype(x.dtype,
                                                       jnp.floating) else x
                out, ms = model.apply(p, mod_state, xc, training=True,
                                      rng=rng)
                return crit(out.astype(jnp.float32), y), ms

            (loss, ms), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if strat is not None:
                grads, loss = strat.reduce_grads(grads, loss)
            new_p, new_o = opt.update(grads, opt_state, params)
            return new_p, ms, new_o, loss

        single_step = train_step  # FLOPs are counted per single step

        if strat is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            if strat_name == "sp":
                # token ids sharded batch x seq so ring attention's
                # shard_map sees its home layout without a reshard
                spec = P("data", "seq")
                step = strat.compile_step(train_step, batch_spec=spec)
                sh = NamedSharding(mesh, spec)
                x = jax.device_put(jnp.asarray(x_host), sh)
                y = jax.device_put(jnp.asarray(y_host), sh)
            else:
                step = strat.compile_step(train_step)
                x, y = strat.shard_batch(x_host, y_host)
            inner_steps = 1
        else:
            if data_source is not None:
                inner_steps = 1  # fresh host batch every step by
                # definition
            if inner_steps > 1:
                # amortize per-dispatch overhead (measured ~2.5-3.5ms
                # through the tunneled runtime) by chaining steps inside
                # one program; same resident batch, per-step folded rng
                # — the pure-compute meter the reference's
                # LocalOptimizerPerf is
                def train_step(params, mod_state, opt_state, x, y, rng):  # noqa: F811
                    def body(i, c):
                        p, ms, o, _ = c
                        return single_step(p, ms, o, x, y,
                                           jax.random.fold_in(rng, i))
                    init = (params, mod_state, opt_state,
                            jnp.zeros((), jnp.float32))
                    return jax.lax.fori_loop(0, inner_steps, body, init)

            step = jax.jit(train_step, donate_argnums=(0, 1, 2))
            x, y = jnp.asarray(x_host), jnp.asarray(y_host)

    k = jax.random.PRNGKey(1)
    # Two independent FLOPs estimates for the MFU numerator:
    #  * analytic — walk the train-step jaxpr and sum 2*MAC for every
    #    dot_general / conv (utils/flops.py); auditable, backend-free;
    #  * HLO — compiled.cost_analysis()["flops"], XLA's own count.
    # MFU is reported from the analytic number; both appear in the JSON
    # and a >2x disagreement is flagged rather than silently trusted.
    flops_analytic, flops_error = 0.0, None
    flops_kinds = {"matmul": 0.0, "conv": 0.0}
    try:
        from bigdl_tpu.utils.flops import fn_flops_by_kind

        flops_kinds = fn_flops_by_kind(single_step, params, mod_state,
                                       opt_state, x, y, k)
        flops_analytic = flops_kinds["matmul"] + flops_kinds["conv"]
    except Exception as e:  # record, never hide — the basis field (below)
        flops_error = f"{type(e).__name__}: {e}"[:200]
    flops_hlo = 0.0
    n_dev = (int(np.prod(list(mesh_axes.values())))
             if mesh_axes is not None else 1)
    compiled = None
    try:
        compiled = step.lower(params, mod_state, opt_state, x, y, k).compile()
        if inner_steps == 1:  # multi-step: while-body cost attribution is
            # backend-dependent, so the HLO cross-check only runs plain
            cost = compiled.cost_analysis()
            if isinstance(cost, (list, tuple)):  # older jax returns [dict]
                cost = cost[0] if cost else {}
            flops_hlo = float(cost.get("flops", 0.0) or 0.0)
            # under SPMD cost_analysis reports the per-device partitioned
            # module; scale to global so both numerators share a basis
            if strat_name is not None:
                flops_hlo *= n_dev
        step = compiled
    except Exception:
        pass
    step_flops = flops_analytic or flops_hlo
    mfu_basis = ("analytic" if flops_analytic
                 else ("hlo" if flops_hlo else None))

    peak_per_chip, peak_label = _peak_flops(jax.devices()[0])
    peak = peak_per_chip * n_dev
    if obs_state is not None and obs_state.capture is not None:
        # attribution context (ISSUE 8): every capture window this run
        # closes gets the run's own FLOPs numerator and mesh peak, so
        # the post-capture attribution can decompose MFU instead of
        # reporting bare times
        cap = obs_state.capture
        if step_flops:
            cap.step_flops = step_flops * inner_steps
            cap.flops_by_kind = {kk: v * inner_steps
                                 for kk, v in flops_kinds.items()}
        cap.peak_flops = peak
        if strat is not None and strat.grad_comm_info() is not None:
            # the captured window's collective times belong to a
            # compressed wire — attribution records say so
            cap.grad_comm = strat.grad_comm_info()

    if obs_state is not None and obs_state.enabled:
        # HBM attribution context (ISSUE 12): the static per-category
        # plan of the exact compiled step + a live sampler, installed
        # BEFORE the first execution so an OOM autopsy carries the plan
        from bigdl_tpu.obs import memory as _mem
        try:
            mem_plan = _mem.build_plan(
                compiled, params=params, opt_state=opt_state,
                batch=(x, y),
                grad_comm=(strat.grad_comm_info() if strat is not None
                           else None),
                device=jax.devices()[0], batch_size=batch,
                model_name=model_name)
            mem_sampler = _mem.HbmSampler()
            obs_state.mem_plan = mem_plan
            obs_state.mem_sampler = mem_sampler
            _mem.install(plan=mem_plan, sampler=mem_sampler)
        except Exception:  # the plan must never break the run it plans
            pass

    try:
        params, mod_state, opt_state, loss = step(params, mod_state,
                                                  opt_state, x, y, k)
        # scalar host transfer = true sync; on the tunneled (axon)
        # platform block_until_ready was observed returning before
        # execution finished
        float(loss)  # compile + warmup
    except Exception as e:
        # first execution is where a genuinely-too-big step dies —
        # autopsy RESOURCE_EXHAUSTED (plan + live stats + top buffers
        # to --traceDir) before re-raising, like any other crash
        from bigdl_tpu.obs import memory as _mem
        _mem.handle_oom(e, "perf_warmup")
        raise

    if elastic is not None:
        # topology is live (mesh formed, step compiled, bucket bound
        # re-resolved in the fresh trace): report it — the call after a
        # caught DeviceLossFault closes out the reshape event with the
        # from/to counts, restore_ms, and bucket bound before/after
        info = strat.grad_comm_info() if strat is not None else None
        elastic.observe_topology(
            n_dev, bucket_bytes=(info or {}).get("bucket_bytes"),
            restore_ms=(time.perf_counter() - t_attempt0) * 1000.0)

    feed = None
    pipeline_prov = None
    if data_source is not None:
        if not data_source.startswith("record:"):
            raise SystemExit(f"unknown --data source {data_source!r}")
        src_path = data_source[len("record:"):]
        # image models: crop records to the model's own spatial dims
        # (224 for the ImageNet family, 32 for resnet20_cifar, ...)
        crop = (tuple(in_shape[:2])
                if len(in_shape) == 3 and in_shape[2] == 3 else (224, 224))
        if data_workers > 0 or stage != "off":
            # ISSUE 13: the executor pipeline replaces the legacy
            # windowed thread-pool feed; --stage device commits the
            # batch to the strategy's sharded layout off-thread
            feed, sig = _executor_record_batches(
                src_path, batch, workers=max(1, data_workers),
                depth=prefetch_depth, stage=stage, strategy=strat,
                crop=crop)
            pipeline_prov = {"workers": max(1, data_workers),
                             "depth": prefetch_depth, "stage": stage,
                             "signature": sig}
        else:
            feed = _record_batches(src_path, batch, crop=crop)
        next(feed)  # warm the decode pool outside the timed region

    import contextlib
    trace_cm = contextlib.nullcontext()
    if profile_dir:
        # xplane trace of the timed region (feeds scripts/mfu_experiment
        # style analysis; view with tensorboard or xprof tooling)
        trace_cm = jax.profiler.trace(profile_dir)

    from bigdl_tpu.resilience.faults import hook as _fault_hook

    # --obs: per-step phase metering (ISSUE 7). The obs-on loop is a
    # separate branch so the obs-off loop stays UNTOUCHED — obs-off
    # output must be byte-identical to pre-obs output (modulo the null
    # phase columns), and the per-step block_until_ready that makes
    # device time exact costs dispatch pipelining (that delta IS the
    # obs overhead, measured by scripts/tpu_capture_r12.sh's A/B).
    obs_on = obs_state is not None and obs_state.enabled
    capture = obs_state.capture if obs_state is not None else None
    phase = None
    t0 = time.perf_counter()
    with trace_cm:
        if obs_on:
            from bigdl_tpu.obs import (get_registry, phase_histograms,
                                       span)
            phase = {p: 0.0 for p in ("data_wait", "h2d", "dispatch",
                                      "device", "ckpt")}
            hists = phase_histograms(get_registry(), "train")
            mem_sampler = getattr(obs_state, "mem_sampler", None)
            pc = time.perf_counter

            def _meter(name, t_start):
                d = pc() - t_start
                phase[name] += d
                hists[name].observe(d * 1000.0)

            for i in range(iterations):
                if capture is not None:
                    capture.on_step(i)
                if feed is not None:
                    t = pc()
                    with span("data_wait"):
                        mb = next(feed)
                    _meter("data_wait", t)
                    t = pc()
                    with span("h2d"):
                        # staged feeds already committed the batch to
                        # device (producer thread recorded the h2d span);
                        # the asarray here would be a no-op aliasing
                        x, y = mb.input, mb.target
                        if not isinstance(x, jax.Array):
                            x = jnp.asarray(x)
                            y = jnp.asarray(y)
                    _meter("h2d", t)
                _fault_hook("step")
                t = pc()
                with span("dispatch"):
                    params, mod_state, opt_state, loss = step(
                        params, mod_state, opt_state, x, y, k)
                _meter("dispatch", t)
                t = pc()
                with span("device"):
                    jax.block_until_ready(loss)
                _meter("device", t)
                if mem_sampler is not None:
                    # live HBM gauges + Chrome-trace counter series (a
                    # cheap None on backends without memory_stats)
                    mem_sampler.sample(step=i)
            float(loss)
            reg = get_registry()
            for p_name, secs in phase.items():
                if secs > 0.0:
                    reg.counter(
                        f"train_phase_{p_name}_seconds_total",
                        f"cumulative {p_name} phase seconds").inc(secs)
        else:
            for _ in range(iterations):
                if feed is not None:
                    mb = next(feed)
                    x, y = mb.input, mb.target
                    if not isinstance(x, jax.Array):
                        x = jnp.asarray(x)   # host->device each step,
                        y = jnp.asarray(y)   # as in a real epoch
                # fault site (one pointer check when no --faultPlan):
                # the supervised-overhead A/B in tpu_capture_r11.sh
                # bounds its cost
                _fault_hook("step")
                params, mod_state, opt_state, loss = step(
                    params, mod_state, opt_state, x, y, k)
            float(loss)  # scalar host read = true device sync (above)
    dt = time.perf_counter() - t0

    total_steps = iterations * inner_steps
    ips = batch * total_steps / dt
    mfu = (step_flops * total_steps / dt) / peak if step_flops else None
    out = {
        "model": model_name,
        "batch": batch,
        "iterations": iterations,
        "inner_steps": inner_steps,
        # ISSUE 8: strategy + mesh topology in EVERY line — a multichip
        # row must say which axes its collectives rode (null/1/null on
        # a single-device run, schema stable)
        "strategy": strat_name,
        "n_devices": n_dev,
        "mesh": mesh_axes,
        # ISSUE 10: what the gradient wire carried — every line says so
        # ("off"/null single-device or uncompressed, so compressed-vs-
        # plain A/Bs join on schema-stable columns next to collective_s)
        "grad_compress": (grad_comm_cfg.compress
                          if (grad_comm_cfg is not None
                              and grad_comm_cfg.active
                              and strat is not None) else "off"),
        "grad_buckets": (strat.grad_comm_info()["n_buckets"]
                         if (strat is not None
                             and strat.grad_comm_info() is not None)
                         else None),
        "seconds": round(dt, 4),
        "records_per_second": round(ips, 2),
        "images_per_second_per_chip": round(ips / n_dev, 2),
        "dtype": str(dtype.__name__ if hasattr(dtype, "__name__")
                     else dtype),
        # MFU is a FRACTION in [0,1]; mfu_pct is the human-facing percent
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_pct": round(mfu * 100, 2) if mfu is not None else None,
        "mfu_basis": mfu_basis,
        "peak_flops_assumed": peak_per_chip,
        "peak_flops_device_match": peak_label,
        "step_gflops_analytic": round(flops_analytic / 1e9, 3),
        # the matmul/conv split of the analytic numerator — what the
        # attribution engine joins category times against (ISSUE 8)
        "step_gflops_by_kind": {
            "matmul": round(flops_kinds["matmul"] / 1e9, 3),
            "conv": round(flops_kinds["conv"] / 1e9, 3)},
        "step_gflops_hlo": round(flops_hlo / 1e9, 3),
        # loss parity anchor: a --strategy run must land where the
        # single-device run lands (the DistriOptimizerSpec bar)
        "final_loss": round(float(loss), 6),
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        # ISSUE 13: which feed machinery produced the batches — null on
        # the legacy window feed / synthetic data, so executor-vs-legacy
        # A/Bs join on a schema-stable column next to stall_frac
        "pipeline": pipeline_prov,
    }
    if strat is not None and strat.grad_comm_info() is not None:
        # the full wire accounting (bucket bound + provenance, wire
        # bytes vs f32 bytes, plan signature) for PERF.md §17 tables
        out["grad_comm"] = dict(strat.grad_comm_info())
    if elastic is not None:
        # ISSUE 11: the elastic columns. `reshape` is the most recent
        # mesh re-formation (from/to device counts, restore_ms, bucket
        # bound before/after + total count) or null when the topology
        # never changed; effective_batch exposes hold-padding/scale-
        # trimming (== batch at full topology)
        out["elastic"] = {"policy": elastic.batch_policy,
                          "min_devices": elastic.min_devices,
                          "effective_batch": int(x.shape[0])}
        out["reshape"] = elastic.reshape_annotation()
    _annotate_obs_phases(out, obs_state, phase, dt)
    _annotate_conv_layouts(out)
    _annotate_autotune(out)
    if model is not None:
        _annotate_bn_fused(out, model)
    else:
        out["bn_fused"] = "off"  # pp/ep harnesses carry no BN
    if lint is not None:  # --lint pre-flight summary rides in the JSON
        out["lint"] = lint  # line like bn_fused/autotune decisions do
    _annotate_supervisor(out, supervisor)
    if flops_error is not None:
        out["flops_analytic_error"] = flops_error
    if flops_analytic and flops_hlo:
        ratio = flops_hlo / flops_analytic
        if ratio > 2.0 or ratio < 0.5:
            out["flops_disagreement"] = round(ratio, 3)
    if is_lm:
        out["tokens_per_second"] = round(ips * in_shape[0], 1)
    print(json.dumps(out))
    return out


# synthetic-grade defaults: (band/chroma contrast, pixel-noise sigma).
# hard was tuned so resnet20 at CIFAR scale crosses 0.91 over multiple
# epochs; easy saturates in under an epoch (color cue)
HARD_GRADE = (8.0, 35.0)
EASY_GRADE = (55.0, 30.0)


def resolve_grade(hard: bool, lift: float | None,
                  noise: float | None) -> tuple[float, float]:
    """Effective (lift, noise) after applying grade defaults — also used
    to RECORD the effective values in result JSON (a null there could
    not tell which defaults generated archived data)."""
    g_lift, g_noise = HARD_GRADE if hard else EASY_GRADE
    return (g_lift if lift is None else lift,
            g_noise if noise is None else noise)


def _make_class_image_tree(root: str, classes: int, per_class: int,
                           size: int, seed: int = 0,
                           hard: bool = False,
                           lift: float | None = None,
                           noise: float | None = None) -> None:
    """Synthetic LEARNABLE image tree (zero-egress stand-in for ImageNet):
    easy grade gives each class a distinct mean color + a bright band at
    a class-specific height under pixel noise — decodable by a conv net
    but not linearly trivial. JPEG-encoded so the full decode+augment
    path runs.

    ``hard=True`` encodes the class as a SUBTLE MEAN-CHROMA DIRECTION:
    every class shares the same gray luminance; class c tints the image
    toward hue angle 2*pi*c/classes with per-pixel amplitude ``lift``
    (default 8) under noise sigma ``noise`` (default 35) — per-pixel SNR
    ~0.2, so the net must learn to pool chroma over the whole image.

    Why mean chroma: it is the only signal family that survives the
    training pipeline's standard augmentation unchanged. Two earlier
    hard grades failed measurably at 50k scale: (1) band *position* —
    the 8/7-headroom random crop translates train images by up to ~5 px,
    more than the 3.2 px between band positions, so train labels become
    inconsistent while val center-crops stay clean (train loss ~0, val
    plateau 0.46); (2) stripe *period* — train's 8/7 resize rescales
    every period by 1.156x relative to val's scale-to-fill, so the
    train-learned frequency classes systematically miss the val
    frequencies (val collapses to chance). Mean chroma is invariant to
    resize, crop, hflip, and JPEG 4:2:0 chroma subsampling.
    ``lift``/``noise`` override the grade's contrast and noise sigma."""
    import numpy as np
    from PIL import Image

    lift, noise = resolve_grade(hard, lift, noise)
    # chroma basis exactly orthogonal to Rec.601 luma (0.299,0.587,0.114)
    # so the full-resolution JPEG Y channel carries ZERO class signal for
    # every angle — otherwise classes near ang=+-90 deg would be partly
    # readable from luminance and per-class difficulty would be skewed
    _luma = np.array([0.299, 0.587, 0.114], np.float32)
    _v1 = np.array([0.587, -0.299, 0.0], np.float32)
    _v1 /= np.linalg.norm(_v1)
    _v2 = np.cross(_luma, _v1)
    _v2 /= np.linalg.norm(_v2)

    rs = np.random.RandomState(seed)
    for c in range(classes):
        d = os.path.join(root, f"class{c:03d}")
        os.makedirs(d, exist_ok=True)
        if hard:
            ang = 2.0 * np.pi * c / classes
            # 1.22 ~= sqrt(1.5): keeps total chroma power at the level
            # the grade's lift default was tuned at
            chroma = 1.22 * (np.cos(ang) * _v1 + np.sin(ang) * _v2)
            hue = np.full(3, 110.0, np.float32)
        else:
            chroma = None
            hue = np.array([(40 + c * 53) % 200, (60 + c * 97) % 200,
                            (80 + c * 151) % 200], np.float32)
        band = (c * size) // classes
        bh = max(2, size // classes)
        for i in range(per_class):
            img = np.broadcast_to(hue, (size, size, 3)).copy()
            if hard:
                img += chroma * lift
            else:
                img[band:band + bh] += lift
            img += rs.randn(size, size, 3) * noise
            Image.fromarray(
                np.clip(img, 0, 255).astype(np.uint8)).save(
                os.path.join(d, f"{i:04d}.jpg"), quality=85)


def run_time_to_acc(model_name: str, batch: int, target: float,
                    max_epochs: int = 40, image_size: int = 64,
                    classes: int = 10, train_per_class: int = 200,
                    val_per_class: int = 40, learning_rate: float = 0.1,
                    use_bf16: bool = True, data_dir: str | None = None,
                    hard: bool = False, val_every_iters: int | None = None,
                    lift: float | None = None, noise: float | None = None,
                    weight_decay: float = 1e-4,
                    fused_bn: str | None = None,
                    lint: dict | None = None,
                    supervisor=None, obs_state=None,
                    grad_compress: str | None = None,
                    grad_buckets: str | None = None):
    """Time-to-accuracy harness (BASELINE.json metric: images/sec/chip
    **+ time-to-76%-top1**; reference recipe models/inception/Train.scala
    :77-83 + scripts/run.example.sh:54). Trains ``model_name`` from
    RECORD SHARDS (decode+augment in the timed path, like the reference's
    SequenceFile flow), validates top-1 each epoch against wall clock,
    stops at ``target`` via Trigger.max_score, and reports the first
    crossing time from the val curve. Zero-egress sandbox ⇒ the dataset
    is synthetic-but-learnable (_make_class_image_tree); on real ImageNet
    shards pass ``data_dir`` with train/ and val/ record subdirs plus
    ``classes=1000`` and target=0.76."""
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp

    from bigdl_tpu import tuning
    tuning.reset_decisions()  # annotate only THIS run's consulted keys

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import RecordImageDataSet, write_image_shards
    from bigdl_tpu.optim import (Optimizer, SGD, Top1Accuracy, Trigger)
    from bigdl_tpu.parallel import DataParallel, local_mesh

    t_setup = time.time()
    td = None
    summary_dir = tempfile.mkdtemp(prefix="tta_summary_")
    try:
        if data_dir is None:
            td = tempfile.mkdtemp(prefix="tta_")
            for split, per in (("train", train_per_class),
                               ("val", val_per_class)):
                tree = os.path.join(td, "imgs", split)
                _make_class_image_tree(tree, classes, per, image_size,
                                       seed=0 if split == "train" else 1,
                                       hard=hard, lift=lift, noise=noise)
                write_image_shards(tree, os.path.join(td, "shards", split),
                                   prefix=split, images_per_shard=256,
                                   workers=4)
            data_dir = os.path.join(td, "shards")

        mean, std = [127.0] * 3, [60.0] * 3
        crop = (image_size, image_size)
        train_ds = RecordImageDataSet(os.path.join(data_dir, "train"),
                                      batch, crop=crop, train=True,
                                      mean=mean, std=std)
        val_ds = RecordImageDataSet(os.path.join(data_dir, "val"), batch,
                                    crop=crop, train=False, mean=mean,
                                    std=std)

        model, _ = build_model(model_name, class_num=classes)
        from bigdl_tpu.cli.common import apply_fused_bn
        apply_fused_bn(model, fused_bn)
        from bigdl_tpu.parallel.grad_comm import make_config as _mk_gc
        try:
            gc_cfg = _mk_gc(grad_compress, grad_buckets)
        except ValueError as e:
            raise SystemExit(str(e))
        strat = DataParallel(local_mesh(), grad_comm=gc_cfg)
        opt = Optimizer(
            model, train_ds, nn.ClassNLLCriterion(),
            # wd matches the reference CIFAR recipe (models/resnet/README.md
            # Training: lr 0.1, momentum 0.9, weight decay 1e-4) — without
            # it the 50k-scale hard grade memorizes its pixel noise
            optim_method=SGD(learning_rate=learning_rate, momentum=0.9,
                             weight_decay=weight_decay),
            end_when=Trigger.or_(Trigger.max_epoch(max_epochs),
                                 Trigger.max_score(target)),
            strategy=strat,
            compute_dtype=(jnp.bfloat16 if use_bf16 else None))
        val_trig = (Trigger.several_iteration(val_every_iters)
                    if val_every_iters else Trigger.every_epoch())
        opt.set_validation(val_trig, val_ds, [Top1Accuracy()])
        opt.set_summary(summary_dir)
        if obs_state is not None and obs_state.capture is not None:
            opt.set_capture(obs_state.capture)

        t_train = time.time()
        opt.optimize()
        wall = time.time() - t_train

        curve = []
        with open(os.path.join(summary_dir, "val.jsonl")) as f:
            for line in f:
                curve.append(json.loads(line))
    finally:
        if td is not None:
            shutil.rmtree(td, ignore_errors=True)
        shutil.rmtree(summary_dir, ignore_errors=True)
    reached = [r for r in curve if r.get("top1_accuracy", 0.0) >= target]
    out = {
        "model": model_name,
        "metric": "time_to_acc",
        "target_top1": target,
        "reached": bool(reached),
        "time_to_acc_s": (round(reached[0]["wall_s"], 2) if reached
                          else None),
        "train_wall_s": round(wall, 2),
        "setup_s": round(t_train - t_setup, 2),
        "final_top1": curve[-1]["top1_accuracy"] if curve else None,
        # distinct epoch stamps across val points: equals the epoch count
        # under every-epoch validation, and "epochs touched" under
        # --valEvery (the val row's epoch field is post-rollover)
        "epochs_run": len({r.get("epoch") for r in curve}),
        "val_points": len(curve),
        # schema-stable grad-comm columns (ISSUE 10) — the tta line
        # carries them like every perf line does
        "grad_compress": (gc_cfg.compress
                          if (gc_cfg is not None and gc_cfg.active
                              and strat.grad_comm_info() is not None)
                          else "off"),
        "grad_buckets": (strat.grad_comm_info()["n_buckets"]
                         if strat.grad_comm_info() is not None else None),
        "hard_data": hard,
        "grade_lift": resolve_grade(hard, lift, noise)[0],
        "grade_noise": resolve_grade(hard, lift, noise)[1],
        "weight_decay": weight_decay,
        "batch": batch,
        "image_size": image_size,
        "classes": classes,
        "device": jax.devices()[0].device_kind,
        "curve": [{"wall_s": r.get("wall_s"),
                   "top1": r.get("top1_accuracy")} for r in curve],
    }
    _annotate_obs_phases(out, obs_state, opt.phase_totals(), wall)
    _annotate_conv_layouts(out)
    _annotate_autotune(out)
    _annotate_bn_fused(out, model)
    if lint is not None:
        out["lint"] = lint
    _annotate_supervisor(out, supervisor)
    print(json.dumps(out))
    return out


def main(argv=None):
    p = argparse.ArgumentParser("bigdl-tpu perf")
    p.add_argument("-m", "--model", default="inception_v1")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("-i", "--iteration", type=int, default=10)
    p.add_argument("--dataType", choices=["constant", "random"],
                   default="constant")
    p.add_argument("--f32", action="store_true",
                   help="disable bf16 compute")
    p.add_argument("--dataParallel", action="store_true",
                   help="deprecated alias for --strategy dp")
    p.add_argument("--seq", type=int, default=None,
                   help="override the transformer_lm* sequence length "
                        "(mirrors lint's --seq; shrinks CPU --strategy "
                        "smokes to seconds)")
    p.add_argument("--data", default=None,
                   help="feed from storage instead of a resident batch, "
                        "e.g. record:/path/to/shards (timed loop then "
                        "includes decode+augment+host->device)")
    p.add_argument("--innerSteps", type=int, default=1,
                   help="steps chained inside one compiled program "
                        "(amortizes dispatch overhead)")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler xplane trace of the timed "
                        "loop to DIR")
    p.add_argument("--timeToAcc", type=float, default=None, metavar="T",
                   help="run the time-to-accuracy harness instead of the "
                        "throughput loop: train from record shards to "
                        "val top1 >= T (BASELINE metric "
                        "'time-to-76%%-top1'; synthetic learnable data "
                        "unless --data record:DIR points at real shards)")
    p.add_argument("--maxEpoch", type=int, default=40,
                   help="epoch cap for --timeToAcc")
    p.add_argument("--imageSize", type=int, default=64,
                   help="image side for --timeToAcc synthetic data")
    p.add_argument("--classes", type=int, default=10,
                   help="class count for --timeToAcc (pass 1000 with real "
                        "ImageNet shards via --data record:DIR)")
    p.add_argument("--trainPerClass", type=int, default=200,
                   help="synthetic train images per class for --timeToAcc "
                        "(5000 = CIFAR-10 scale, the reference recipe "
                        "models/resnet/README.md Training section)")
    p.add_argument("--valPerClass", type=int, default=40,
                   help="synthetic val images per class for --timeToAcc "
                        "(1000 = CIFAR-10 scale)")
    p.add_argument("--ttaHard", action="store_true",
                   help="harder synthetic classes (band position only, "
                        "no color cue) so the accuracy curve spans "
                        "multiple epochs")
    p.add_argument("--valEvery", type=int, default=None, metavar="ITERS",
                   help="validate every N iterations instead of every "
                        "epoch (denser accuracy-vs-wall-clock curve)")
    p.add_argument("--ttaLift", type=float, default=None,
                   help="override the synthetic grade's contrast: chroma "
                        "amplitude for --ttaHard (default 8), band "
                        "contrast for easy (default 55)")
    p.add_argument("--ttaNoise", type=float, default=None,
                   help="override the synthetic grade's pixel-noise sigma "
                        "(hard default 35, easy 30)")
    p.add_argument("--ttaWd", type=float, default=1e-4,
                   help="weight decay for --timeToAcc (reference CIFAR "
                        "recipe value 1e-4)")
    p.add_argument("--convLayout", default=None, metavar="FWD,DGRAD,WGRAD",
                   help="per-pass conv activation layouts (NHWC|NCHW|GEMM "
                        "each, or 'auto'/'default') — e.g. a "
                        "scripts/conv_bwd_probe.py decision via "
                        "scripts/apply_conv_probe.py. GEMM runs eligible "
                        "1x1/stride-1 convs as dot_general (exact-parity "
                        "NHWC fallback elsewhere). Unset = 'auto': the "
                        "measured decision shipped for this device kind "
                        "(ops/conv2d.MEASURED_DECISIONS), no-op on "
                        "unmeasured devices; 'default' forces all-NHWC. "
                        "An explicit spec wins over --convGeom and the "
                        "autotuner")
    p.add_argument("--convGeom", default=None, metavar="FILE",
                   help="per-conv-geometry layout decision JSON "
                        "(scripts/apply_conv_probe.py --geom output): "
                        "keys decisions by (kh, kw, stride, cin, cout, "
                        "groups, dilation, dtype) so e.g. the stem's "
                        "wgrad runs NCHW while 3x3 stages stay NHWC and "
                        "1x1/s1 convs may run as GEMM; stamped as "
                        "conv_geom in the result JSON")
    from bigdl_tpu.cli.common import (_add_platform_arg, add_autotune_arg,
                                      add_fused_bn_arg, add_grad_comm_args,
                                      add_lint_arg, add_obs_args,
                                      add_pipeline_args,
                                      add_resilience_args,
                                      add_strategy_arg, apply_platform,
                                      run_preflight_lint)
    _add_platform_arg(p)
    add_strategy_arg(p)
    add_grad_comm_args(p)
    add_autotune_arg(p)
    add_fused_bn_arg(p)
    add_lint_arg(p)
    add_resilience_args(p)
    add_obs_args(p)
    add_pipeline_args(p)
    args = p.parse_args(argv)
    apply_platform(args)  # also installs --faultPlan and --obs
    if args.convLayout:
        # apply_platform already installed the spec (SystemExit on a bad
        # one); just surface what's active for the capture logs
        from bigdl_tpu.ops.conv2d import get_conv_pass_layouts
        print("conv pass layouts:", get_conv_pass_layouts(), flush=True)
    lint_ann = None
    if args.lint:
        # pre-flight static analysis of THIS run's model/config
        # (bigdl_tpu.analysis; PERF.md §12 + §26) — the ResolvedConfig
        # spine resolves the mirrored flag families once, shardlint
        # traces the sharded step over this run's REAL device count,
        # strict refuses to launch on error-severity findings, and the
        # summary is stamped into the result JSON either way
        import jax

        from bigdl_tpu.analysis import lint_config
        from bigdl_tpu.cli.common import resolve_lint_config
        cfg = resolve_lint_config(args, n_devices=len(jax.devices()))
        report = lint_config(cfg)
        rc, lint_ann = run_preflight_lint(
            report, strict=(args.lint == "strict"))
        if lint_ann is not None and cfg.mesh:
            lint_ann["mesh"] = ",".join(
                f"{a}:{s}" for a, s in cfg.mesh_axes)
        if rc:
            return rc
    obs_state = getattr(args, "_obs", None)

    def _go(supervisor=None, elastic=None):
        if args.timeToAcc is not None:
            if args.strategy and args.strategy != "dp":
                raise SystemExit(
                    "--timeToAcc trains through the Optimizer, which is "
                    "data-parallel by construction — --strategy only "
                    "composes with the throughput loop (dp is implied "
                    "here)")
            data_dir = None
            if args.data and args.data.startswith("record:"):
                data_dir = args.data[len("record:"):]
            run_time_to_acc(args.model, args.batchSize, args.timeToAcc,
                            max_epochs=args.maxEpoch,
                            image_size=args.imageSize,
                            classes=args.classes,
                            train_per_class=args.trainPerClass,
                            val_per_class=args.valPerClass,
                            use_bf16=not args.f32, data_dir=data_dir,
                            hard=args.ttaHard,
                            val_every_iters=args.valEvery,
                            lift=args.ttaLift, noise=args.ttaNoise,
                            weight_decay=args.ttaWd, fused_bn=args.fusedBN,
                            lint=lint_ann, supervisor=supervisor,
                            obs_state=obs_state,
                            grad_compress=args.gradCompress,
                            grad_buckets=args.gradBuckets)
            return
        run(args.model, args.batchSize, args.iteration, args.dataType,
            use_bf16=not args.f32, data_parallel=args.dataParallel,
            data_source=args.data, inner_steps=args.innerSteps,
            profile_dir=args.profile, fused_bn=args.fusedBN,
            lint=lint_ann, supervisor=supervisor, obs_state=obs_state,
            strategy=args.strategy, seq_len=args.seq,
            grad_compress=args.gradCompress, grad_buckets=args.gradBuckets,
            elastic=elastic, data_workers=args.dataWorkers,
            prefetch_depth=args.prefetchDepth, stage=args.stage)

    if args.elastic is not None:
        # elastic perf (ISSUE 11): a kill_device fault mid-loop marks
        # the victims lost and raises DeviceLossFault; the retry probes
        # the survivors, re-forms the mesh at the smaller count, pads or
        # trims the batch per --elastic, and the JSON line carries the
        # reshape dict. Below --minDevices the run gives up cleanly.
        if args.timeToAcc is not None:
            raise SystemExit(
                "--elastic + --timeToAcc: use the training CLIs (their "
                "run_optimize path reshapes through checkpoint resume); "
                "the perf throughput loop is the elastic harness here")
        from bigdl_tpu.resilience.elastic import ElasticSupervisor
        from bigdl_tpu.resilience.supervisor import (RetryPolicy,
                                                     SupervisorGaveUp)
        sup = ElasticSupervisor(
            RetryPolicy(budget=(args.supervise if args.supervise is not None
                                else 5)),
            min_devices=args.minDevices, batch_policy=args.elastic,
            name="perf")
        try:
            sup.run(lambda _n: _go(sup, elastic=sup))
        except SupervisorGaveUp as e:
            raise SystemExit(f"elastic: {e}")
        return

    if args.supervise is not None:
        # supervised perf: transient injected faults retry with backoff
        # and the fault/recovery log rides in the JSON line; fault-free,
        # the timed loop is unchanged (one pointer check per step)
        from bigdl_tpu.resilience.supervisor import RetryPolicy, Supervisor
        sup = Supervisor(RetryPolicy(budget=args.supervise), name="perf")
        sup.run(lambda _n: _go(sup))
        return
    _go()


if __name__ == "__main__":
    main()
