"""`bigdl-tpu fleet` — the serving fleet front door (ISSUE 20).

A spelling of ``serve --fleet K`` with the fleet as the DEFAULT: the
same flag surface as serve, but this process is always the router and
``--fleet`` defaults to 2 workers instead of 0.

    bigdl-tpu fleet transformer_lm --model ckpt_dir --fleet 4 -p 8000
    curl -d '{"checkpoint": "ckpt_v2", "version": "v2"}' \\
        localhost:8000/admin/reload
"""

from __future__ import annotations

from bigdl_tpu.cli import common


def build_parser():
    from bigdl_tpu.cli import serve as serve_cli
    p = serve_cli.build_parser()
    p.prog = "bigdl-tpu fleet"
    p.set_defaults(fleet=2)
    return p


def main(argv=None) -> int:
    common.setup_logging()
    import sys
    raw_argv = list(sys.argv[1:]) if argv is None else list(argv)
    args = build_parser().parse_args(raw_argv)
    if int(args.fleet) < 1:
        raise SystemExit("bigdl-tpu fleet: --fleet must be >= 1 (use "
                         "`bigdl-tpu serve` for the single-process "
                         "stack)")
    from bigdl_tpu.serving.fleet.router import run_fleet
    return run_fleet(args, raw_argv)


if __name__ == "__main__":
    raise SystemExit(main())
