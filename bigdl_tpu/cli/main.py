"""`bigdl-tpu` console entry point — one launcher for every example and
tool, the analog of the reference's dispatch script
(/root/reference/scripts/run.example.sh:21-47, which maps a model name to
its Spark-submit class) and its per-model `...models.<name>.Train` mains.

    bigdl-tpu lenet train -f /data/mnist -b 128
    bigdl-tpu perf -m resnet50 -b 128 -i 20
    bigdl-tpu predict --model model.bin -f images/

Each subcommand forwards to the matching ``bigdl_tpu.cli.<module>.main``,
so `bigdl-tpu lenet ...` and `python -m bigdl_tpu.cli.lenet ...` are the
same surface.
"""

from __future__ import annotations

import importlib
import sys
from typing import List, Optional

# subcommand -> cli module name (all expose main(argv))
_COMMANDS = {
    "lenet": "lenet",
    "vgg": "vgg",
    "resnet": "resnet",
    "inception": "inception",
    "rnn": "rnn",
    "autoencoder": "autoencoder",
    "transformerlm": "transformerlm",
    "textclassification": "textclassification",
    "perf": "perf",
    "explain": "explain",
    "lint": "lint",
    "serve": "serve",
    "fleet": "fleet",
    "predict": "predict",
    "batch-predict": "batch_predict",
    "loadmodel": "loadmodel",
    "record-gen": "record_gen",
}


def _usage() -> str:
    from bigdl_tpu import __version__

    cmds = "\n".join(f"  {name}" for name in _COMMANDS)
    return (f"bigdl-tpu {__version__} — TPU-native deep-learning "
            f"framework\n\nusage: bigdl-tpu <command> [args...]\n\n"
            f"commands:\n{cmds}\n\n"
            f"run `bigdl-tpu <command> --help` for per-command flags\n")


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help"):
        print(_usage())
        return 0
    if argv[0] == "--version":
        from bigdl_tpu import __version__

        print(__version__)
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd not in _COMMANDS:
        print(f"bigdl-tpu: unknown command {cmd!r}\n\n{_usage()}",
              file=sys.stderr)
        return 2
    mod = importlib.import_module(f"bigdl_tpu.cli.{_COMMANDS[cmd]}")
    rc = mod.main(rest)
    # subcommand mains return rich values (optimize() results, arrays) —
    # only a genuine int is an exit code (bool True must not become 1)
    return rc if isinstance(rc, int) and not isinstance(rc, bool) else 0


if __name__ == "__main__":
    raise SystemExit(main())
