"""SimpleRNN word-level language model (reference models/rnn/{Train,Test,
Utils}.scala: WordTokenizer dictionary over input.txt, one-hot windows,
next-word prediction, perplexity loss)."""

from __future__ import annotations

import argparse
import math
import os

from bigdl_tpu.cli import common


def _windows(ids, seq_len: int):
    import numpy as np

    xs, ys = [], []
    for i in range(0, len(ids) - seq_len):
        xs.append(ids[i:i + seq_len])
        ys.append(ids[i + seq_len])
    return np.asarray(xs, np.int32), np.asarray(ys, np.int32)


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu rnn")
    sub = p.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("train")
    common.add_train_args(tr)
    tr.add_argument("--vocabSize", type=int, default=4000)
    tr.add_argument("--seqLength", type=int, default=20)
    tr.add_argument("--hiddenSize", type=int, default=40)
    args = p.parse_args(argv)
    common.apply_platform(args)

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.core import Sequential
    from bigdl_tpu.dataset import BatchDataSet
    from bigdl_tpu.dataset.text import Dictionary, tokenize
    from bigdl_tpu.models import simple_rnn
    from bigdl_tpu.nn import LookupTable

    path = os.path.join(args.folder, "input.txt")
    with open(path) as f:
        tokens = tokenize(f.read())
    d = Dictionary([tokens], vocab_size=args.vocabSize)
    ids = np.asarray(d.ids(tokens), np.int32)
    x, y = _windows(ids, args.seqLength)
    # hold out the tail windows for the perplexity report
    n_held = min(512, max(1, len(x) // 10))
    x, y, x_val, y_val = (x[:-n_held], y[:-n_held],
                          x[-n_held:], y[-n_held:])
    train = BatchDataSet(x, y, args.batchSize, shuffle=True)

    vocab = len(d)
    # embedding front-end instead of the reference's explicit one-hot
    # expansion — same math (one-hot @ W == row lookup), MXU-friendly
    model = Sequential(
        LookupTable(vocab, vocab),
        *simple_rnn(vocab, args.hiddenSize, vocab).children(),
        name="SimpleRNN-LM",
    )
    trained = common.run_optimize(
        lambda: common.build_optimizer(model, train, nn.ClassNLLCriterion(),
                                       args), args)
    # report perplexity on the held-out tail (reference loss = perplexity)
    import jax.numpy as jnp
    logp = trained.module.forward(trained.params, jnp.asarray(x_val))
    nll = -np.mean(np.asarray(logp)[np.arange(len(y_val)), y_val])
    print(f"perplexity is {math.exp(nll):.2f}")
    return trained


if __name__ == "__main__":
    main()
