"""Batch image prediction (reference example/imageclassification/
ImagePredictor.scala:34-82 — DLClassifier over a folder of images; here the
Spark DataFrame becomes a plain file stream through
:class:`bigdl_tpu.utils.Classifier`).

    python -m bigdl_tpu.cli.predict --model ckpt_dir --modelName lenet \
        -f /path/to/images [--topN 5]
"""

from __future__ import annotations

import argparse
import logging
import os

from bigdl_tpu.cli import common


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu predict")
    p.add_argument("--model", required=True,
                   help="checkpoint dir or file. Whole-model files embed "
                        "their definition as a pickle — only load files "
                        "you produced (same trust model as the "
                        "reference's Java deserialization)")
    p.add_argument("--modelName", default="lenet",
                   choices=["lenet", "alexnet", "inception_v1", "resnet50",
                            "vgg16"])
    p.add_argument("-f", "--folder", required=True,
                   help="folder of images (flat or class subdirs)")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--classNum", type=int, default=1000)
    p.add_argument("--topN", type=int, default=1)
    p.add_argument("--imageSize", type=int, default=None,
                   help="input side for whole-model files (defaults per "
                        "--modelName otherwise)")
    args = p.parse_args(argv)
    common.apply_platform(args)

    import numpy as np

    from bigdl_tpu import models
    from bigdl_tpu.dataset.folder import _decode, list_image_folder
    from bigdl_tpu.serving import InferenceEngine, power_of_two_buckets

    model = None
    if os.path.isfile(args.model):
        # a save_module artifact carries its own definition — no
        # --modelName rebuild needed (reference Module.load semantics)
        try:
            from bigdl_tpu.utils.file import load_module
            model, params, mod_state = load_module(args.model)
            side = args.imageSize or 224
            size = (side, side)
        except KeyError:
            # no __module__ marker: a weights-only file — rebuild from
            # --modelName below
            model = None
        except Exception as e:
            # a corrupt/incompatible whole-model file would otherwise
            # surface as a confusing pytree mismatch far from here
            logging.getLogger("bigdl_tpu").warning(
                "load_module(%s) failed (%s: %s); falling back to "
                "--modelName rebuild", args.model, type(e).__name__, e)
            model = None
    if model is None:
        if args.modelName == "lenet":
            model, size = models.lenet5(max(args.classNum, 10)), (28, 28)
        else:
            build = {"alexnet": models.alexnet,
                     "inception_v1": models.inception_v1_no_aux,
                     "resnet50": models.resnet50,
                     "vgg16": models.vgg16}[args.modelName]
            model, size = build(args.classNum), (
                (227, 227) if args.modelName == "alexnet" else (224, 224))
        if args.imageSize:
            size = (args.imageSize, args.imageSize)
        params, mod_state = common.load_trained(model, args.model)
    # the serving engine's bucketed compile cache (power-of-two ladder up
    # to --batchSize): the tail batch pads to an existing bucket instead
    # of compiling its own odd shape — same scores row-for-row as the
    # old full-batch-padded Classifier path
    clf = InferenceEngine(model, params, mod_state,
                          buckets=power_of_two_buckets(args.batchSize))

    # accept both a class-subdir tree and a flat folder of images
    try:
        paths, _, _ = list_image_folder(args.folder)
    except (FileNotFoundError, ValueError):
        paths = []
    if not paths:
        exts = (".jpg", ".jpeg", ".png", ".bmp")
        paths = [os.path.join(args.folder, f)
                 for f in sorted(os.listdir(args.folder))
                 if f.lower().endswith(exts)]
    if not paths:
        raise SystemExit(f"no images under {args.folder}")

    for i in range(0, len(paths), args.batchSize):
        chunk = paths[i:i + args.batchSize]
        imgs = np.stack([_decode(p_, size) for p_ in chunk])
        if args.modelName == "lenet":
            # match cli/lenet.py training normalization
            from bigdl_tpu.dataset.mnist import TRAIN_MEAN, TRAIN_STD
            if imgs.shape[-1] == 3:
                imgs = imgs.mean(-1, keepdims=True)
            x = ((imgs.astype(np.float32) / 255.0) - TRAIN_MEAN) / TRAIN_STD
        else:
            # match the ImageFolderDataSet stats the imagenet CLIs train with
            from bigdl_tpu.dataset.folder import IMAGENET_MEAN, IMAGENET_STD
            x = ((imgs.astype(np.float32)
                  - np.asarray(IMAGENET_MEAN, np.float32))
                 / np.asarray(IMAGENET_STD, np.float32))
        scores = clf.predict_scores(x)
        top = np.argsort(-scores, axis=-1)[:, : args.topN]
        for path, classes in zip(chunk, top):
            print(f"{path}\t{' '.join(map(str, classes))}")


if __name__ == "__main__":
    main()
