"""Command-line Train/Test entry points per model family
(reference bigdl/models/*/{Train,Test}.scala scopt CLIs + the perf harness
models/utils/DistriOptimizerPerf.scala). Run as, e.g.::

    python -m bigdl_tpu.cli.lenet train -f /data/mnist -b 128 --maxEpoch 5
    python -m bigdl_tpu.cli.lenet test -f /data/mnist --model ckpt_dir
    python -m bigdl_tpu.cli.perf -m inception_v1 -b 32 -i 10
"""
