"""ResNet on CIFAR-10 (reference models/resnet/{Train,Utils}.scala:
depth-20/32/44/56/110 with basic blocks, momentum 0.9, weight decay 1e-4,
nesterov; reference default optnet memory sharing is XLA's job here)."""

from __future__ import annotations

import argparse

from bigdl_tpu.cli import common
from bigdl_tpu.cli.vgg import _datasets, _one_split


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu resnet")
    sub = p.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("train")
    common.add_train_args(tr)
    tr.add_argument("--depth", type=int, default=20)
    tr.add_argument("--bnStatSample", type=int, default=None,
                    help="BN training stats from this many batch rows "
                         "(throughput lever; see nn.set_bn_stat_sample)")
    # reference resnet recipe defaults (an explicit --weightDecay 0 still
    # disables decay; only the *default* changes here)
    tr.set_defaults(weightDecay=1e-4)
    te = sub.add_parser("test")
    common.add_test_args(te)
    te.add_argument("--depth", type=int, default=20)
    args = p.parse_args(argv)
    common.apply_platform(args)

    from bigdl_tpu import nn
    from bigdl_tpu.models import resnet_cifar
    from bigdl_tpu.optim import SGD, Top1Accuracy, Trigger
    from bigdl_tpu.optim.schedules import EpochSchedule, Regime

    model = resnet_cifar(args.depth, 10)
    if getattr(args, "bnStatSample", None):
        from bigdl_tpu.nn import set_bn_stat_sample
        set_bn_stat_sample(model, args.bnStatSample)
    if args.cmd == "train":
        train, test = _datasets(args.folder, args.batchSize, train_aug=True)
        # reference resnet training regime: lr drops at epochs 81/122
        sched = EpochSchedule([
            Regime(1, 80, {"learning_rate": args.learningRate}),
            Regime(81, 121, {"learning_rate": args.learningRate * 0.1}),
            Regime(122, 10**9, {"learning_rate": args.learningRate * 0.01}),
        ])
        method = SGD(learning_rate=args.learningRate,
                     weight_decay=args.weightDecay,
                     momentum=args.momentum, dampening=0.0,
                     nesterov=args.momentum > 0, schedule=sched)
        opt = common.build_optimizer(model, train, nn.ClassNLLCriterion(),
                                     args, optim_method=method)
        opt.set_validation(Trigger.every_epoch(), test, [Top1Accuracy()])
        return opt.optimize()
    params, mod_state = common.load_trained(model, args.model)
    test = _one_split(args.folder, args.batchSize, False, False)
    return common.evaluate(model, params, mod_state, test)


if __name__ == "__main__":
    main()
