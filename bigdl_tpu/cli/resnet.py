"""ResNet training CLI (reference models/resnet/{Train,TrainImageNet,
Utils}.scala).

Two dataset families, as in the reference:

* ``--dataset cifar10`` (default) — depth 6n+2 basic-block nets on CIFAR
  folders, the reference Train.scala recipe (momentum 0.9, wd 1e-4,
  nesterov, lr drops at epochs 81/122).
* ``--dataset imagenet`` — depth 18/34/50/101/152 on an ImageNet-style
  label-by-folder tree at 224x224 (reference TrainImageNet.scala).

TPU perf levers are first-class flags here, not perf-harness-only
(VERDICT r4 item 3; the reference exposes its perf knobs on the CLI the
same way, models/inception/Options.scala:134):

* ``--s2d`` (imagenet-only) — space-to-depth stem: the 7x7/2 conv on
  224x224x3 runs at ~3.6% of MXU peak (PERF.md §3); the s2d rewrite is
  the same math with MXU-sized channel dims.
* ``--fusedBN [off|stats|apply]`` — Pallas BN (ops/bn_kernel.py):
  ``stats`` is the single-read stats kernel (round-4 lever, measured
  −46% on chip — PERF.md §8.2); ``apply`` is the full fused BN block
  (stats+apply+absorbed-ReLU forward, reductions+dx backward — PERF.md
  §10), attacking the 34 ms backward. Single-device jit path; the
  Optimizer falls back automatically (with a warning) under multi-device
  SPMD, where pallas_call has no partitioning rule.
"""

from __future__ import annotations

import argparse

from bigdl_tpu.cli import common
from bigdl_tpu.cli.vgg import _datasets, _one_split


def _add_lever_args(tr):
    tr.add_argument("--bnStatSample", type=int, default=None,
                    help="BN training stats from this many batch rows "
                         "(throughput lever; see nn.set_bn_stat_sample)")
    # --fusedBN [off|stats|apply] comes in via common.add_train_args
    tr.add_argument("--s2d", action="store_true",
                    help="space-to-depth stem (imagenet models only): "
                         "MXU-friendly rewrite of the 7x7/2 stem conv")


def _imagenet_datasets(folder: str, batch: int):
    import os

    from bigdl_tpu.dataset.folder import (IMAGENET_MEAN, IMAGENET_STD,
                                          ImageFolderDataSet)

    train = ImageFolderDataSet(os.path.join(folder, "train"), batch,
                               size=(224, 224), train=True,
                               mean=IMAGENET_MEAN, std=IMAGENET_STD)
    vdir = os.path.join(folder, "val")
    val = (ImageFolderDataSet(vdir, batch, size=(224, 224),
                              mean=IMAGENET_MEAN, std=IMAGENET_STD)
           if os.path.isdir(vdir) else None)
    return train, val


def _build_model(args):
    from bigdl_tpu.models import resnet, resnet_cifar
    from bigdl_tpu.models.resnet import _IMAGENET_CFG

    if args.dataset == "imagenet":
        if args.depth not in _IMAGENET_CFG:
            raise SystemExit(
                f"--depth {args.depth} invalid for imagenet; pick one of "
                f"{sorted(_IMAGENET_CFG)}")
        if getattr(args, "s2d", False) and not getattr(args, "convLayout",
                                                       None):
            # s2d + the shipped layout decision interfere (2,579 vs
            # 2,674 img/s, PERF.md §8.2 combination matrix): pin the
            # all-NHWC default unless the user chose layouts explicitly
            from bigdl_tpu.ops.conv2d import install_layout_spec
            install_layout_spec("default")
        return resnet(args.depth, args.classNum,
                      s2d_stem=getattr(args, "s2d", False))
    if getattr(args, "s2d", False):
        raise SystemExit("--s2d applies to --dataset imagenet models only "
                         "(the CIFAR stem is already a 3x3/1 conv)")
    if (args.depth - 2) % 6:
        raise SystemExit(f"--depth {args.depth} invalid for cifar10; "
                         "depth must be 6n+2 (20/32/44/56/110)")
    return resnet_cifar(args.depth, args.classNum)


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu resnet")
    sub = p.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("train")
    common.add_train_args(tr)
    tr.add_argument("--dataset", choices=["cifar10", "imagenet"],
                    default="cifar10")
    tr.add_argument("--depth", type=int, default=None,
                    help="6n+2 for cifar10 (default 20); 18/34/50/101/152 "
                         "for imagenet (default 50)")
    tr.add_argument("--classNum", type=int, default=None)
    _add_lever_args(tr)
    # reference resnet recipe defaults (an explicit --weightDecay 0 still
    # disables decay; only the *default* changes here)
    tr.set_defaults(weightDecay=1e-4)
    te = sub.add_parser("test")
    common.add_test_args(te)
    te.add_argument("--dataset", choices=["cifar10", "imagenet"],
                    default="cifar10")
    te.add_argument("--depth", type=int, default=None)
    te.add_argument("--classNum", type=int, default=None)
    te.add_argument("--s2d", action="store_true",
                    help="evaluate a checkpoint trained with --s2d "
                         "(the stem param tree differs)")
    args = p.parse_args(argv)
    common.apply_platform(args)
    if args.classNum is None:
        args.classNum = 1000 if args.dataset == "imagenet" else 10
    if args.depth is None:
        args.depth = 50 if args.dataset == "imagenet" else 20

    from bigdl_tpu import nn
    from bigdl_tpu.optim import SGD, Top1Accuracy, Top5Accuracy, Trigger
    from bigdl_tpu.optim.schedules import EpochSchedule, Regime

    model = _build_model(args)
    if getattr(args, "bnStatSample", None):
        from bigdl_tpu.nn import set_bn_stat_sample
        set_bn_stat_sample(model, args.bnStatSample)
    # --fusedBN (off/stats/apply) is installed by common.build_optimizer
    # for train; the test path has no BN-fusion lever (eval-mode BN is a
    # plain elementwise op)
    if args.cmd == "train":
        if args.dataset == "imagenet":
            train, test = _imagenet_datasets(args.folder, args.batchSize)
            # reference TrainImageNet regime: warmup-free step decay /10
            # at epochs 30/60/80
            sched = EpochSchedule([
                Regime(1, 29, args.learningRate),
                Regime(30, 59, args.learningRate * 0.1),
                Regime(60, 79, args.learningRate * 0.01),
                Regime(80, 10**9, args.learningRate * 0.001),
            ])
        else:
            train, test = _datasets(args.folder, args.batchSize,
                                    train_aug=True)
            # reference resnet training regime: lr drops at epochs 81/122
            sched = EpochSchedule([
                Regime(1, 80, args.learningRate),
                Regime(81, 121, args.learningRate * 0.1),
                Regime(122, 10**9, args.learningRate * 0.01),
            ])
        method = SGD(learning_rate=args.learningRate,
                     weight_decay=args.weightDecay,
                     momentum=args.momentum, dampening=0.0,
                     nesterov=args.momentum > 0, schedule=sched)
        def _make():
            opt = common.build_optimizer(model, train,
                                         nn.ClassNLLCriterion(), args,
                                         optim_method=method)
            if test is not None:
                metrics = [Top1Accuracy()]
                if args.dataset == "imagenet":
                    metrics.append(Top5Accuracy())
                opt.set_validation(Trigger.every_epoch(), test, metrics)
            return opt
        return common.run_optimize(_make, args)
    params, mod_state = common.load_trained(model, args.model)
    if args.dataset == "imagenet":
        _, test = _imagenet_datasets(args.folder, args.batchSize)
        if test is None:
            raise FileNotFoundError(
                f"no val/ directory under {args.folder}")
        return common.evaluate(model, params, mod_state, test,
                               [Top1Accuracy(), Top5Accuracy()])
    test = _one_split(args.folder, args.batchSize, False, False)
    return common.evaluate(model, params, mod_state, test)


if __name__ == "__main__":
    main()
