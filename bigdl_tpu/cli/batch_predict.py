"""Offline bulk inference over record shards (ISSUE 18 tentpole a) —
the TPU-native analog of BigDL's RDD batch scoring (the
``model.predict(rdd)`` workhorse of arxiv 1804.05839 §3), built by
composition: the ``dataset/pipeline`` executor feeds the serving
engine's bucketed forwards, and a cursor checkpoint makes kill+resume
byte-identical.

    bigdl-tpu batch-predict --modelName resnet50 --model ckpt_dir \\
        -f record:/data/shards --out /data/scores -b 128 \\
        --dataWorkers 8 --stage device --strategy dp

* the record feed is the training input pipeline in eval mode
  (``shuffle=False``, deterministic center-crop transforms): N decode
  workers race the :class:`EpochPlan`'s tickets, batches reassemble in
  plan order, ``--stage device`` overlaps the h2d copy with scoring;
* ``--strategy dp[:N]`` shards the batch stream round-robin across N
  engine replicas on disjoint device groups
  (:func:`replica_device_groups`), ``tp:K`` runs each replica
  tensor-parallel over K chips — the same spellings ``serve`` takes;
* outputs append to sharded JSONL (``scores-XXXXX-of-NNNNN.jsonl``,
  global order reconstructible by sorting on ``"i"``), with a cursor
  checkpoint every ``--checkpointEvery`` batches (serving/bulk.py) so a
  killed job resumes with no re-scored and no dropped records;
* the report line carries the training-perf phase/provenance columns
  (``stall_frac``, ``data_wait_s``, ``pipeline``, hbm/mem columns under
  ``--obs``) plus ``images_per_second_per_chip``.

The tail remainder (``n % global_batch`` records the EpochPlan drops by
design for training) is scored as one final partial batch — the engine
pads it to a compiled bucket — so bulk scoring covers every record.
"""

from __future__ import annotations

import argparse
import json
import time

from bigdl_tpu.cli import common


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser("bigdl-tpu batch-predict")
    p.add_argument("--modelName", default="resnet50",
                   choices=["alexnet", "inception_v1", "inception_v2",
                            "vgg16", "vgg19", "resnet50", "resnet20_cifar",
                            "vit_b16", "vit_s16"],
                   help="image model (cli/perf.py build table); sets the "
                        "eval crop geometry")
    p.add_argument("--model", default=None,
                   help="trained checkpoint dir (newest model.<n>) or "
                        "single saved file")
    p.add_argument("--randomInit", action="store_true",
                   help="random weights instead of --model (throughput "
                        "smoke / perf capture)")
    p.add_argument("-f", "--folder", required=True,
                   help="record:<dir> (or plain dir/glob) of .btr record "
                        "shards to score")
    p.add_argument("--out", required=True,
                   help="output dir: scores-*.jsonl shards + cursor.json "
                        "(an existing cursor resumes the job)")
    p.add_argument("-b", "--batchSize", type=int, default=32)
    p.add_argument("--classNum", type=int, default=1000)
    p.add_argument("--limit", type=int, default=None, metavar="N",
                   help="score only the first N records of the plan order")
    p.add_argument("--scores", action="store_true",
                   help="emit full score vectors per record, not just the "
                        "argmax pred")
    p.add_argument("--checkpointEvery", type=int, default=32, metavar="K",
                   help="drain + fsync + cursor write every K dispatched "
                        "batches (the resume granularity)")
    p.add_argument("--strategy", default=None, metavar="dp[:N]|tp[:K]",
                   help="device fan-out, serve spellings: dp[:N] = N "
                        "engine replicas on disjoint device groups fed "
                        "round-robin; tp[:K] = each replica "
                        "tensor-parallel over K chips; dp:N+tp:K "
                        "combines. Default: one single-group engine")
    common.add_pipeline_args(p)
    common._add_platform_arg(p)
    common.add_autotune_arg(p)
    common.add_fused_bn_arg(p)
    common.add_obs_args(p)
    return p


def _build_feed(args, crop):
    """The eval-mode executor feed + its provenance: record source ->
    StreamingSampleSource -> EpochPlan(shuffle=False) -> ExecutorDataSet
    [-> StagedDataSet]. Returns ``(feed_iter, plan, n, sig, pipeline)``
    where ``feed_iter`` yields ``(ordinal, indices, x)`` including the
    final tail-remainder partial batch."""
    import numpy as np

    from bigdl_tpu.cli.perf import _short_side
    from bigdl_tpu.dataset.pipeline import (EpochPlan, ExecutorDataSet,
                                            StagedDataSet,
                                            StreamingSampleSource)
    from bigdl_tpu.dataset.streaming import RecordImageDataSet

    source = args.folder
    if source.startswith("record:"):
        source = source[len("record:"):]
    batch = args.batchSize
    # the perf-harness record recipe, eval mode: deterministic resize +
    # center crop (train=False), so scores are reproducible run-to-run
    rds = RecordImageDataSet(
        source, batch_size=batch, crop=crop, train=False,
        short_side=_short_side(crop), mean=[123.68, 116.779, 103.939],
        std=[58.4, 57.1, 57.4], n_threads=1, window=1)
    src = StreamingSampleSource(rds)
    n = len(src)
    if args.limit is not None:
        n = min(n, int(args.limit))
    if n <= 0:
        raise SystemExit(f"no records to score under {source}")
    plan = EpochPlan(n, batch, seed=0, shuffle=False,
                     process_index=0, process_count=1)
    workers = max(1, int(args.dataWorkers or 0))
    depth = max(1, int(args.prefetchDepth or 2))
    ds = ExecutorDataSet(src, workers=workers, depth=depth, plan=plan)
    staged = ds
    if args.stage != "off":
        staged = StagedDataSet(ds, stage=args.stage, depth=depth)
    pipeline_sig = staged.signature()

    batch_rows = plan.batch_indices(0)  # (steps, batch) plan-order rows

    def feed():
        s = 0
        for mb in staged:
            if s >= plan.steps:
                break
            yield s, batch_rows[s], mb.input
            s += 1
        # the EpochPlan drops n % global_batch for training lockstep;
        # bulk scoring must cover every record — score the tail as one
        # partial batch (the engine pads it to a compiled bucket)
        tail = np.arange(plan.steps * plan.global_batch, n)
        if len(tail):
            mb = src.collate([src.load(int(i), 0) for i in tail])
            yield plan.steps, tail, mb.input

    return feed(), plan, n, src.signature(), pipeline_sig


def main(argv=None):
    common.setup_logging()
    args = build_parser().parse_args(argv)
    if not args.randomInit and not args.model:
        raise SystemExit("need --model CKPT (or --randomInit for a "
                         "throughput smoke)")
    common.apply_platform(args)

    import jax
    import numpy as np  # noqa: F401  (feed helpers)

    from bigdl_tpu.cli.perf import _annotate_obs_phases, build_model
    from bigdl_tpu.cli.provenance import provenance_dict
    from bigdl_tpu.serving import (InferenceEngine, bulk,
                                   power_of_two_buckets)
    from bigdl_tpu.serving.sharding import (replica_device_groups,
                                            serving_mesh)

    model, size = build_model(args.modelName, class_num=args.classNum)
    common.apply_fused_bn(model, getattr(args, "fusedBN", None))
    crop = tuple(size[:2])
    if args.randomInit:
        params, mod_state = model.init(jax.random.PRNGKey(0)), None
    else:
        params, mod_state = common.load_trained(model, args.model)

    devices = jax.devices()
    replicas, tp_k = common.parse_serving_strategy(args.strategy,
                                                   len(devices))
    groups = replica_device_groups(replicas, tp_k)
    # one engine per device group, mirroring serve's replica stacks —
    # batch ordinal s scores on engine s % len(groups)
    engines = [InferenceEngine(model, params, mod_state,
                               buckets=power_of_two_buckets(args.batchSize),
                               mesh=serving_mesh(g))
               for g in groups]

    feed, plan, n, src_sig, pipeline_sig = _build_feed(args, crop)
    # the resume identity: the exact plan + source + scoring config —
    # any drift refuses to resume instead of silently rescoring
    signature = {"plan": plan.signature(), "src": src_sig,
                 "model": args.modelName, "class_num": int(args.classNum),
                 "scores": bool(args.scores), "groups": len(engines),
                 "tp": int(tp_k)}

    prior = bulk.load_cursor(args.out)
    records_prior = int(prior.get("records_done", 0)) if prior else 0

    obs_state = getattr(args, "_obs", None)
    phase: dict = {}
    t0 = time.perf_counter()
    rep = bulk.run_bulk(engines, feed, signature, args.out,
                        scores=args.scores,
                        checkpoint_every=args.checkpointEvery,
                        phase=phase)
    wall = time.perf_counter() - t0

    n_chips = len(groups) * max(1, tp_k)
    scored = max(0, rep["records"] - records_prior)
    out = {"bench": "batch_predict", "model": args.modelName,
           "batch": args.batchSize, "records": rep["records"],
           "records_scored_this_run": scored,
           "batches": rep["batches"],
           "resumed_from_batch": rep["resumed_from_batch"],
           "groups": rep["groups"], "tp": tp_k, "chips": n_chips,
           "shards": rep["shards"], "seconds": round(wall, 3),
           "images_per_second": (round(scored / wall, 2) if wall else None),
           "images_per_second_per_chip": (round(scored / wall / n_chips, 2)
                                          if wall else None),
           "pipeline": pipeline_sig}
    # same schema-stable phase/provenance columns as the training perf
    # JSON — stall_frac is the acceptance number (ISSUE 18: <= 0.02 at
    # --dataWorkers 8 --stage device)
    _annotate_obs_phases(out, obs_state, phase, wall)
    out.update(provenance_dict(model))
    print(json.dumps(out))
    return out


if __name__ == "__main__":
    main()
