"""VGG on CIFAR-10 (reference models/vgg/{Train,Test}.scala: BGR
normalize -> random crop/flip augment -> SGD)."""

from __future__ import annotations

import argparse

from bigdl_tpu.cli import common


def _one_split(folder: str, batch: int, train_split: bool, augment: bool):
    from bigdl_tpu.dataset.cifar import load_cifar10, TRAIN_MEAN, TRAIN_STD
    from bigdl_tpu.dataset.native import NativePrefetchDataSet, available
    import numpy as np

    mean = [m * 255 for m in TRAIN_MEAN]
    std = [s * 255 for s in TRAIN_STD]
    x, y = load_cifar10(folder, train=train_split)
    if available():
        return NativePrefetchDataSet(x, y, batch, train=augment,
                                     mean=mean, std=std)
    # pure-python fallback
    from bigdl_tpu.dataset import BatchDataSet

    xn = ((x.astype(np.float32) - np.asarray(mean, np.float32))
          / np.asarray(std, np.float32))
    return BatchDataSet(xn, y, batch, shuffle=augment)


def _datasets(folder: str, batch: int, train_aug: bool):
    return (_one_split(folder, batch, True, train_aug),
            _one_split(folder, batch, False, False))


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu vgg")
    sub = p.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("train")
    common.add_train_args(tr)
    te = sub.add_parser("test")
    common.add_test_args(te)
    args = p.parse_args(argv)
    common.apply_platform(args)

    from bigdl_tpu import nn
    from bigdl_tpu.models import vgg_for_cifar10
    from bigdl_tpu.optim import Top1Accuracy, Trigger

    model = vgg_for_cifar10(10)
    if args.cmd == "train":
        train, test = _datasets(args.folder, args.batchSize, train_aug=True)

        def _make():
            opt = common.build_optimizer(model, train,
                                         nn.ClassNLLCriterion(), args)
            opt.set_validation(Trigger.every_epoch(), test,
                               [Top1Accuracy()])
            return opt
        return common.run_optimize(_make, args)
    params, mod_state = common.load_trained(model, args.model)
    test = _one_split(args.folder, args.batchSize, False, False)
    return common.evaluate(model, params, mod_state, test)


if __name__ == "__main__":
    main()
