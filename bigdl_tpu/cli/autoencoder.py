"""Autoencoder on MNIST (reference models/autoencoder/Train.scala: MSE
reconstruction of normalized grey images, Adagrad in the reference's
example config; SGD+momentum default here with --adagrad to match)."""

from __future__ import annotations

import argparse

from bigdl_tpu.cli import common


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu autoencoder")
    sub = p.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("train")
    common.add_train_args(tr)
    tr.add_argument("--adagrad", action="store_true")
    args = p.parse_args(argv)
    common.apply_platform(args)

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import BatchDataSet
    from bigdl_tpu.dataset.mnist import load_mnist
    from bigdl_tpu.models import autoencoder
    from bigdl_tpu.optim import Adagrad

    xtr, _ = load_mnist(args.folder, train=True)
    x = xtr.astype(np.float32) / 255.0
    # target = flattened input (reconstruction); BatchDataSet keeps the
    # feature/target rows aligned under shuffling
    train = BatchDataSet(x, x.reshape(len(x), -1), args.batchSize,
                         shuffle=True)

    model = autoencoder(32)

    def _make():
        method = (Adagrad(learning_rate=args.learningRate)
                  if args.adagrad else None)
        return common.build_optimizer(model, train, nn.MSECriterion(),
                                      args, optim_method=method)
    return common.run_optimize(_make, args)


if __name__ == "__main__":
    main()
