"""Text classification example (reference example/textclassification/
TextClassifier.scala:40-220 — GloVe embeddings + a 1D-conv-as-
SpatialConvolution text CNN over 20 Newsgroups; scaladoc claims ~90% after
2 epochs).

Input layout mirrors the reference's baseDir:

    baseDir/
      20news-18828/<group-name>/<doc files>     (label-by-folder corpus)
      glove.6B/glove.6B.<dim>d.txt              (optional pretrained vectors)

When GloVe vectors are absent the embedding is trained from scratch
(LookupTable init); when the corpus is absent a synthetic two-class corpus
is generated so the pipeline is runnable end-to-end anywhere.

The model is the reference's text CNN re-expressed TPU-first: embeddings
(batch, seq, dim) -> TemporalConvolution/ReLU/TemporalMaxPooling x2 ->
Linear -> LogSoftMax, all static shapes so XLA tiles the convs on the MXU.
"""

from __future__ import annotations

import argparse
import logging
import os

from bigdl_tpu.cli import common

logger = logging.getLogger("bigdl_tpu")


def load_glove(path: str, dictionary, dim: int):
    """Rows for words in the dictionary; missing words keep random init."""
    import numpy as np

    table = np.random.RandomState(0).normal(
        0, 0.05, (len(dictionary), dim)).astype(np.float32)
    hits = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            idx = dictionary.word2id.get(parts[0])  # skip OOV (UNK id is 1)
            if idx is not None and len(parts) == dim + 1:
                table[idx] = np.asarray(parts[1:], np.float32)
                hits += 1
    logger.info("GloVe: %d/%d dictionary words covered", hits,
                len(dictionary))
    return table


def read_corpus(base: str):
    """(texts, labels, class_names) from a 20news-style folder tree."""
    root = None
    for cand in ("20news-18828", "20_newsgroup", "corpus"):
        p = os.path.join(base, cand)
        if os.path.isdir(p):
            root = p
            break
    if root is None:
        return None
    texts, labels, names = [], [], []
    for cls in sorted(os.listdir(root)):
        cdir = os.path.join(root, cls)
        if not os.path.isdir(cdir):  # stray files must not shift label ids
            continue
        ci = len(names)
        names.append(cls)
        for fn in sorted(os.listdir(cdir)):
            try:
                with open(os.path.join(cdir, fn), errors="ignore") as f:
                    texts.append(f.read())
                labels.append(ci)
            except OSError:
                continue
    return texts, labels, names


def synthetic_corpus(n_per_class: int = 200, seed: int = 0):
    """Two topics with disjoint-ish vocabularies — learnable by any text
    model, used when no corpus directory exists."""
    import numpy as np

    rs = np.random.RandomState(seed)
    topics = [
        ["game", "team", "score", "play", "season", "win", "coach",
         "league", "player", "ball"],
        ["code", "kernel", "memory", "compile", "driver", "linux",
         "system", "program", "software", "bug"],
    ]
    filler = ["the", "a", "of", "and", "to", "in", "is", "it", "for", "on"]
    texts, labels = [], []
    for ci, vocab in enumerate(topics):
        for _ in range(n_per_class):
            words = [
                (vocab if rs.rand() < 0.4 else filler)[
                    rs.randint(0, 10)] for _ in range(60)
            ]
            texts.append(" ".join(words))
            labels.append(ci)
    return texts, labels, ["sports", "computing"]


def build_model(vocab: int, emb_dim: int, seq_len: int, n_class: int,
                emb_table=None):
    import jax.numpy as jnp

    from bigdl_tpu import nn
    from bigdl_tpu.core import Sequential

    lut = nn.LookupTable(vocab, emb_dim)
    if emb_table is not None:
        base_init = lut.init

        def init_with_glove(rng):
            p = base_init(rng)
            p["weight"] = jnp.asarray(emb_table)
            return p

        lut.init = init_with_glove
    # reference: 128 filters, kernel 5, pool 5, twice, then dense
    return Sequential(
        lut,
        nn.TemporalConvolution(emb_dim, 128, 5), nn.ReLU(),
        nn.TemporalMaxPooling(5, 5),
        nn.TemporalConvolution(128, 128, 5), nn.ReLU(),
        nn.TemporalMaxPooling(5, 5),
        nn.Lambda(lambda x: x.reshape(x.shape[0], -1), name="Flatten"),
        nn.Linear(128 * (((seq_len - 4) // 5 - 4) // 5), 128), nn.ReLU(),
        nn.Linear(128, n_class), nn.LogSoftMax(),
        name="TextCNN",
    )


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu textclassification")
    common.add_train_args(p)
    p.add_argument("--embeddingDim", type=int, default=100)
    p.add_argument("--sequenceLength", type=int, default=500)
    p.add_argument("--maxWordsNum", type=int, default=5000)
    p.add_argument("--trainingSplit", type=float, default=0.8)
    args = p.parse_args(argv)
    common.apply_platform(args)

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.dataset import BatchDataSet
    from bigdl_tpu.dataset.text import Dictionary, pad_sequences, tokenize
    from bigdl_tpu.optim import Top1Accuracy, Trigger

    corpus = read_corpus(args.folder)
    if corpus is None:
        logger.warning("no corpus under %s — using the synthetic two-class "
                       "corpus", args.folder)
        corpus = synthetic_corpus()
    texts, labels, names = corpus
    toks = [tokenize(t)[: args.sequenceLength] for t in texts]
    d = Dictionary(toks, vocab_size=args.maxWordsNum)
    ids = pad_sequences([d.ids(t) for t in toks], args.sequenceLength)
    x = np.asarray(ids, np.int32)
    y = np.asarray(labels, np.int32)

    rs = np.random.RandomState(args.seed)
    order = rs.permutation(len(x))
    x, y = x[order], y[order]
    n_train = int(len(x) * args.trainingSplit)

    emb = None
    glove = os.path.join(args.folder, "glove.6B",
                         f"glove.6B.{args.embeddingDim}d.txt")
    if os.path.isfile(glove):
        emb = load_glove(glove, d, args.embeddingDim)

    model = build_model(len(d), args.embeddingDim, args.sequenceLength,
                        len(names), emb)
    train = BatchDataSet(x[:n_train], y[:n_train], args.batchSize,
                         shuffle=True)
    val = BatchDataSet(x[n_train:], y[n_train:], args.batchSize)
    def _make():
        opt = common.build_optimizer(model, train, nn.ClassNLLCriterion(),
                                     args)
        opt.set_validation(Trigger.every_epoch(), val, [Top1Accuracy()])
        return opt
    return common.run_optimize(_make, args)


if __name__ == "__main__":
    main()
