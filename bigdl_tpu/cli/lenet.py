"""LeNet-5 on MNIST (reference models/lenet/{Train,Test}.scala:
GreyImgNormalizer(trainMean, trainStd) -> GreyImgToBatch -> SGD ->
Top1 validation)."""

from __future__ import annotations

import argparse

from bigdl_tpu.cli import common


def _one_split(folder: str, batch: int, train_split: bool):
    import numpy as np

    from bigdl_tpu.dataset import BatchDataSet
    from bigdl_tpu.dataset.mnist import load_mnist, TRAIN_MEAN, TRAIN_STD

    x, y = load_mnist(folder, train=train_split)
    xn = ((x.astype(np.float32) / 255.0) - TRAIN_MEAN) / TRAIN_STD
    return BatchDataSet(xn, y, batch, shuffle=train_split)


def _datasets(folder: str, batch: int):
    return _one_split(folder, batch, True), _one_split(folder, batch, False)


def main(argv=None):
    common.setup_logging()
    p = argparse.ArgumentParser("bigdl-tpu lenet")
    sub = p.add_subparsers(dest="cmd", required=True)
    tr = sub.add_parser("train")
    common.add_train_args(tr)
    te = sub.add_parser("test")
    common.add_test_args(te)
    args = p.parse_args(argv)
    common.apply_platform(args)

    from bigdl_tpu import nn
    from bigdl_tpu.models import lenet5
    from bigdl_tpu.optim import Top1Accuracy, Trigger

    model = lenet5(10)
    if args.cmd == "train":
        train, test = _datasets(args.folder, args.batchSize)

        def _make():
            opt = common.build_optimizer(model, train,
                                         nn.ClassNLLCriterion(), args)
            opt.set_validation(Trigger.every_epoch(), test,
                               [Top1Accuracy()])
            return opt
        return common.run_optimize(_make, args)
    params, mod_state = common.load_trained(model, args.model)
    test = _one_split(args.folder, args.batchSize, False)
    return common.evaluate(model, params, mod_state, test)


if __name__ == "__main__":
    main()
