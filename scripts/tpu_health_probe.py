"""Shared TPU tunnel health probe (exit 0 = healthy) — the ONE copy of
the gate both `tpu_poll_and_capture.sh` and the capture sweeps run.

Health means more than backend-up: time one RESIDENT-input chained
matmul synced by a host VALUE FETCH. The tunnel's two measurement traps
(PERF.md §8.2): ``block_until_ready`` acks before device completion
(async timings read impossibly fast), and fresh-input timing is
dominated by the tunnel's tens-of-MB/s upload bandwidth. A resident
chained compute + scalar fetch measures the device; more than 2 s for
a 2048^3 (healthy: milliseconds + fetch latency) means the link is
unusable for capture work.
"""

import sys
import time

import jax
import jax.numpy as jnp


def main() -> int:
    if jax.default_backend() != "tpu":
        print(f"backend={jax.default_backend()}", file=sys.stderr)
        return 1
    f = jax.jit(lambda a: a @ a)
    a = jnp.full((2048, 2048), 0.5, jnp.float32)
    cur = f(a)
    float(jnp.sum(cur))  # warmup incl. compile
    t0 = time.perf_counter()
    cur = f(cur)
    float(jnp.sum(cur))
    dt = time.perf_counter() - t0
    if dt >= 2.0:
        print(f"unhealthy: {dt:.2f}s resident 2048^3 + fetch",
              file=sys.stderr)
        return 1
    print(f"tpu up (healthy, {dt * 1e3:.0f} ms):",
          jax.devices()[0].device_kind)
    return 0


if __name__ == "__main__":
    sys.exit(main())
