"""Input-pipeline executor smoke (ISSUE 13 CI): the tier1.yml
``pipeline-smoke`` job — record-fed training on CPU, asserted end to end.

What it proves:

1. the executor feed is BIT-IDENTICAL to the legacy window feed over the
   same record shards (same epoch permutation, same (seed, epoch, index)
   per-sample augment, same collate) — and invariant in the worker count;
2. record-fed lenet5 trained through the Optimizer lands bit-identical
   params under --dataWorkers 1 and 8 (the end-to-end spelling of the
   determinism contract);
3. a record-fed --obs perf run with the executor feed stamps a filled
   ``stall_frac``/``data_wait_s`` and the ``pipeline`` provenance column
   into its JSON line; obs-off stamps the nulls but keeps provenance;
4. SIGTERM mid-epoch shuts the worker pool down cleanly (no leaked
   ``bigdl-pipe-*`` threads, clean rc=0).

Usage:  python scripts/pipeline_smoke.py
Exit 0 = all assertions held.
"""

import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _fail(msg):
    print(f"pipeline_smoke: FAIL: {msg}", flush=True)
    sys.exit(1)


def _make_shards(root, n_per_class=24, classes=("a", "b")):
    from PIL import Image

    from bigdl_tpu.dataset.recordfile import write_image_shards

    rng = np.random.RandomState(0)
    img_root = os.path.join(root, "imgs")
    for cls in classes:
        d = os.path.join(img_root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(n_per_class):
            arr = rng.randint(0, 255, (40, 48, 3)).astype(np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i}.png"))
    out = os.path.join(root, "shards")
    write_image_shards(img_root, out, images_per_shard=16)
    return out


def _stream(ds, epochs):
    out = []
    for _ in range(epochs):
        for mb in ds:
            out.append((np.asarray(mb.input).copy(),
                        np.asarray(mb.target).copy()))
        ds.shuffle()
    return out


def check_bit_identity(shards):
    """(1) executor == legacy window feed, and worker-count invariant."""
    from bigdl_tpu.dataset.pipeline import as_executor
    from bigdl_tpu.dataset.streaming import RecordImageDataSet

    def mk():
        return RecordImageDataSet(shards, batch_size=8, crop=(28, 28),
                                  train=True, seed=11, n_threads=2,
                                  window=2)

    legacy = []
    ds = mk()
    for _ in range(2):  # legacy __iter__ advances its own epoch
        for mb in ds:
            legacy.append((np.asarray(mb.input).copy(),
                           np.asarray(mb.target).copy()))

    streams = {}
    for w in (1, 2, 8):
        streams[w] = _stream(as_executor(mk(), workers=w), 2)
    for w, s in streams.items():
        if len(s) != len(legacy):
            _fail(f"workers={w}: {len(s)} batches vs legacy {len(legacy)}")
        for i, ((xa, ya), (xb, yb)) in enumerate(zip(legacy, s)):
            if not (np.array_equal(xa, xb) and np.array_equal(ya, yb)):
                _fail(f"workers={w}: batch {i} differs from legacy feed")
    print("pipeline_smoke: executor == legacy feed, bit-identical for "
          "workers {1,2,8}", flush=True)


def check_train_invariance(shards):
    """(2) record-fed lenet5: trained params identical for 1 vs 8
    workers (grayscale adapter keeps the 1-channel stem)."""
    import jax

    from bigdl_tpu import nn
    from bigdl_tpu.dataset.pipeline import (EpochPlan, ExecutorDataSet,
                                            StreamingSampleSource)
    from bigdl_tpu.dataset.streaming import RecordImageDataSet
    from bigdl_tpu.models import lenet5
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    class GraySource(StreamingSampleSource):
        def load(self, index, epoch):
            x, y = super().load(index, epoch)
            return x.mean(-1, keepdims=True), y

    def train(workers):
        rds = RecordImageDataSet(shards, batch_size=8, crop=(28, 28),
                                 train=True, seed=11, n_threads=1,
                                 window=1)
        src = GraySource(rds)
        plan = EpochPlan(len(src), 8, seed=rds.seed, shuffle=True,
                         process_index=0, process_count=1)
        ds = ExecutorDataSet(src, workers=workers, depth=2, plan=plan)
        opt = Optimizer(lenet5(10), ds, nn.ClassNLLCriterion(),
                        optim_method=SGD(learning_rate=0.05),
                        end_when=Trigger.max_iteration(8), seed=7,
                        log_every=100)
        return opt.optimize()

    p1 = jax.tree_util.tree_leaves(train(1).params)
    p8 = jax.tree_util.tree_leaves(train(8).params)
    for a, b in zip(p1, p8):
        if not np.array_equal(np.asarray(a), np.asarray(b)):
            _fail("record-fed lenet5 params differ between 1 and 8 workers")
    print("pipeline_smoke: record-fed lenet5 params bit-identical for "
          "1 vs 8 workers", flush=True)


def check_perf_columns(shards):
    """(3) stall_frac/data_wait filled under --obs; provenance always."""
    from bigdl_tpu import obs
    from bigdl_tpu.cli import common, perf

    obs.enable()
    st = common.ObsState(True, None, None, None)
    out = perf.run("resnet20_cifar", 8, 4, "random", use_bf16=False,
                   data_source=f"record:{shards}", data_workers=4,
                   prefetch_depth=2, stage="device", obs_state=st)
    if out["stall_frac"] is None or out["data_wait_s"] is None:
        _fail(f"obs-on executor run left stall columns null: {out}")
    prov = out["pipeline"]
    if not prov or prov["workers"] != 4 or prov["stage"] != "device":
        _fail(f"pipeline provenance wrong: {prov}")
    if prov["signature"]["plan"]["batch"] != 8:
        _fail(f"plan signature wrong: {prov}")
    obs.disable()
    out2 = perf.run("resnet20_cifar", 8, 2, "random", use_bf16=False,
                    data_source=f"record:{shards}", data_workers=4,
                    stage="host")
    if out2["stall_frac"] is not None:
        _fail("obs-off run filled stall_frac (schema must stay null)")
    if not out2["pipeline"]:
        _fail("obs-off run dropped pipeline provenance")
    print(f"pipeline_smoke: perf columns ok (stall_frac="
          f"{out['stall_frac']}, data_wait_s={out['data_wait_s']})",
          flush=True)


_SIGTERM_CHILD = r"""
import os, signal, sys, threading, time
sys.path.insert(0, os.getcwd())
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from bigdl_tpu.dataset.pipeline import ArraySampleSource, ExecutorDataSet

stop = []
signal.signal(signal.SIGTERM, lambda s, f: stop.append(1))
rs = np.random.RandomState(0)
src = ArraySampleSource(rs.randn(512, 4).astype(np.float32),
                        rs.randint(0, 3, 512).astype(np.int32))
ds = ExecutorDataSet(src, batch_size=8, workers=4, depth=2, seed=0)
for i, mb in enumerate(ds):
    print(f"STEP {i}", flush=True)
    time.sleep(0.05)
    if stop:
        break  # mid-epoch abandon: the executor's finally joins the pool
leaked = [t.name for t in threading.enumerate()
          if t.name.startswith("bigdl-pipe-")]
if leaked:
    print("LEAKED", leaked, flush=True)
    sys.exit(1)
print("CLEAN_EXIT", flush=True)
"""


def check_sigterm():
    """(4) SIGTERM mid-epoch: worker pool joins, no leaked threads."""
    proc = subprocess.Popen([sys.executable, "-c", _SIGTERM_CHILD],
                            stdout=subprocess.PIPE, text=True,
                            cwd=os.path.dirname(os.path.dirname(
                                os.path.abspath(__file__))))
    # wait until it is demonstrably mid-epoch
    for line in proc.stdout:
        if line.startswith("STEP 3"):
            break
    proc.send_signal(signal.SIGTERM)
    try:
        rest = proc.stdout.read()
        rc = proc.wait(timeout=30)
    except subprocess.TimeoutExpired:
        proc.kill()
        _fail("SIGTERM child hung past 30s (worker pool not joining)")
    if rc != 0 or "CLEAN_EXIT" not in rest:
        _fail(f"SIGTERM exit not clean: rc={rc} tail={rest[-300:]!r}")
    print("pipeline_smoke: SIGTERM mid-epoch shut down cleanly", flush=True)


def main():
    t0 = time.time()
    with tempfile.TemporaryDirectory(prefix="pipe_smoke_") as td:
        shards = _make_shards(td)
        check_bit_identity(shards)
        check_train_invariance(shards)
        check_perf_columns(shards)
    check_sigterm()
    print(f"pipeline_smoke: OK ({time.time() - t0:.1f}s)", flush=True)


if __name__ == "__main__":
    main()
