#!/usr/bin/env bash
# Round-10 capture: ISSUE 5 (serving) chip evidence. The serving path is
# CPU-verified end-to-end (tests/test_serving.py, tier1 smoke); what only
# a chip can tell us is the LATENCY/THROUGHPUT shape of the tuned program
# under load — p50/p95/p99 vs batch size through the micro-batcher,
# decode tokens/s vs slot count, and whether the tuned config
# (--fusedBN apply / --autotune cached / probe conv layouts) moves
# serving latency the way it moved training MFU. Every bench JSON line
# carries the server's /metrics provenance, so tuned-vs-default rows are
# self-describing (PERF.md §13 slots). Appends to $OUT, mirrored into
# the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r10.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r10.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 0. compiled-path tests first (serving reuses the Pallas kernels; a
#    broken flash/BN path would poison every number below)
step "pytest_tpu_marked" 1200 env BIGDL_TPU_TESTS=1 python -m pytest tests/ -m tpu -q
step "pytest_serving" 600 python -m pytest tests/test_serving.py -q

# 1. lenet5 sanity leg: the smallest model isolates the HTTP + batcher
#    overhead floor from model compute (compare its p50 against the
#    resnet legs below)
step "serve_lenet5_b1" 900 python scripts/serving_bench.py \
  --model lenet5 --requests 256 --concurrency 8 --batch 1
step "serve_lenet5_b8" 900 python scripts/serving_bench.py \
  --model lenet5 --requests 256 --concurrency 8 --batch 8

# 2. resnet50 A/B: default config vs the tuned program the training
#    benchmarks measured (fused BN apply + cached autotune decisions).
#    Same bucket ladder both legs; provenance in each JSON line is the
#    diff. b1 = latency-bound, b8 = bucket-throughput-bound.
for B in 1 8; do
  step "serve_resnet50_default_b${B}" 1800 python scripts/serving_bench.py \
    --model resnet50 --requests 128 --concurrency 8 --batch "$B"
  step "serve_resnet50_tuned_b${B}" 1800 python scripts/serving_bench.py \
    --model resnet50 --requests 128 --concurrency 8 --batch "$B" \
    --serveArg=--fusedBN --serveArg=apply --serveArg=--autotune \
    --serveArg=cached
done

# 3. transformer_lm decode: tokens/s vs continuous-batching slot count
#    (1 slot = sequential baseline; 4/8 = shared decode batches), then
#    the tuned-config A/B at the production 512-seq config.
for S in 1 4 8; do
  step "serve_lm_slots${S}" 1800 python scripts/serving_bench.py \
    --model transformer_lm --endpoint generate --requests 64 \
    --concurrency "$S" --promptLen 64 --maxNewTokens 64 \
    --serveArg=--slots --serveArg="$S"
done
step "serve_lm_tuned" 1800 python scripts/serving_bench.py \
  --model transformer_lm --endpoint generate --requests 64 \
  --concurrency 4 --promptLen 64 --maxNewTokens 64 \
  --serveArg=--slots --serveArg=4 --serveArg=--bf16 \
  --serveArg=--autotune --serveArg=cached

# 4. padding-waste + admission behavior under overload: concurrency far
#    above maxBatch exercises the 429 fast-reject path; the final
#    /metrics scrape (inside each bench line's provenance) records the
#    padding_waste_fraction the bucket ladder produced.
step "serve_resnet50_overload" 1800 python scripts/serving_bench.py \
  --model resnet50 --requests 256 --concurrency 32 --batch 4 \
  --serveArg=--maxQueue --serveArg=64

echo "capture r10 complete -> $REPO_LOG" | tee -a "$OUT"
