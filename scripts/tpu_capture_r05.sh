#!/usr/bin/env bash
# Round-5 capture: chip evidence for VERDICT r4 item 1 — compiled kernels,
# clean b128 + transformer_lm_1k MFU, flash rows, and the lever A/Bs
# (s2d, innerSteps, bnss, and the new fused-BN Pallas stats kernel).
# Appends to $OUT, mirrored into the repo per step.


set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r05.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r05.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# Ordered by evidentiary value so a short tunnel window still captures
# the essentials (every step mirrors the log into the repo).

# 1. compiled flash kernel: proves the lse-layout fix lowers on Mosaic
step "pytest_tpu_marked" 1200 env BIGDL_TPU_TESTS=1 python -m pytest tests/ -m tpu -q

# 2. clean headline number + the transformer datapoints
step "perf_resnet50_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random
step "perf_transformer_lm_b32" 900 python -m bigdl_tpu.cli.perf -m transformer_lm -b 32 -i 10 --dataType random
step "perf_transformer_lm_1k_b16" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_1k -b 16 -i 10 --dataType random

# 3. flash vs dense microbenchmark (incl. 16k/32k flash-only rows)
step "flash_bench" 1800 python scripts/flash_bench.py 4 8 64

# 4. lever A/Bs + the rest of the trajectory
step "perf_resnet50_inner10_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 4 --innerSteps 10 --dataType random
step "perf_resnet50_bnss_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50_bnss -b 128 -i 20 --dataType random
# round-4 lever: single-read Pallas BN stats (ops/bn_kernel.py) — exact
# semantics, targets the 15.6 ms/step BN stat category head-on
step "perf_resnet50_fbn_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50_fbn -b 128 -i 20 --dataType random
step "perf_resnet50_fbn_s2d_inner10" 900 python -m bigdl_tpu.cli.perf -m resnet50_fbn -b 128 -i 4 --innerSteps 10 --dataType random
step "perf_resnet50_s2d_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50_s2d -b 128 -i 20 --dataType random
for B in 64 256 512; do
  step "perf_resnet50_b$B" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b "$B" -i 20 --dataType random
done
step "perf_transformer_lm_rope_b32" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_rope -b 32 -i 10 --dataType random

# train-from-storage: first capture's TPU attempt breached the default 900s
# (JPEG generation shared the core with a pytest run); give it headroom
step "bench_pipe" 2400 env BENCH_TPU_TIMEOUT=2000 BENCH_COMPANIONS=0 python bench.py resnet50_pipe 128 20

# convergence on the chip (first capture lost it to the tunnel dropping)
if [ ! -f /tmp/synth_mnist_full/train-images-idx3-ubyte ]; then
  step "make_synth_mnist" 1200 python scripts/make_synth_mnist.py /tmp/synth_mnist_full 20000 4000
fi
step "lenet_convergence" 1800 ./scripts/run_example.sh lenet /tmp/synth_mnist_full -b 128 --maxEpoch 20 --learningRate 0.1

# where does the backward lose its 8 MFU points: per-pass conv layout probe,
# then DECIDE and A/B the decision on the real model (VERDICT r4 weak #4)
step "conv_bwd_probe" 1500 bash -c "python scripts/conv_bwd_probe.py 30 | tee /tmp/conv_probe_r05.jsonl"
step "conv_probe_apply" 900 bash -c 'L=$(python scripts/apply_conv_probe.py /tmp/conv_probe_r05.jsonl) && echo "decision: $L" && python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --convLayout "$L"'

# accuracy-vs-wall-clock on the chip (BASELINE's second metric) — r05:
# CIFAR-10 scale (50k/10k @ 32x32, batch 128), the reference recipe
# (models/resnet/README.md Training section)
step "time_to_acc_cifar_scale" 3600 python -m bigdl_tpu.cli.perf -m resnet20_cifar --timeToAcc 0.91 -b 128 --imageSize 32 --maxEpoch 156 --trainPerClass 5000 --valPerClass 1000 --ttaHard --valEvery 195
step "time_to_acc_resnet50" 2400 python -m bigdl_tpu.cli.perf -m resnet50 --timeToAcc 0.85 -b 64 --imageSize 224 --maxEpoch 15

# the official bench line last
step "bench_main" 2400 python bench.py

echo "capture2 complete -> $OUT"
