#!/usr/bin/env bash
# Round-23 capture: ISSUE 20 (serving fleet tier) chip evidence. The
# correctness contracts are CPU-verified (tests/test_fleet.py, the
# tier1 fleet-smoke job): rolling-swap atomicity (in-flight decodes
# finish on OLD weights, zero 5xx window), kill -9 -> supervised
# restart + rejoin at the CURRENT weights, rid echo through the proxy
# hop, /readyz 200 while >=1 worker lives. What only hardware can tell
# us: (a) the rolling-swap 5xx window + p99 inflation at REAL reload
# cost — a multi-GiB restore + re-place + re-quantize takes seconds on
# chip, not the CPU smoke's milliseconds, so the drain window finally
# means something; (b) the worker-kill goodput floor — tokens/s the
# fleet holds with K-1 workers while the backoff ladder runs; (c) the
# router proxy overhead vs PR 15's in-process dp:N at equal chip count
# (the process hop must cost p50 noise, not a tier). Appends to $OUT,
# mirrored into the repo per step. Results -> PERF.md §27 slots.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r23.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r23.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 0. the r23 test file + fleet smoke on this env (CPU backends first —
#    proves the harness before burning chip time)
step "pytest_r23" 1200 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_fleet.py -q
step "fleet_smoke_cpu" 1200 env JAX_PLATFORMS=cpu \
  python scripts/serving_bench.py --fleetSmoke --model transformer_lm

# 1. two REAL checkpoints for the A/B swap: same arch, different
#    training seeds -> observably different weights. Point CKPT_A /
#    CKPT_B at production checkpoints to override.
CKPT_A="${CKPT_A:-/tmp/r23_ckpt_a}"
CKPT_B="${CKPT_B:-/tmp/r23_ckpt_b}"
LM_DIMS="--vocabSize 32000 --dModel 1024 --numLayers 8 --numHeads 16"
if [ ! -d "$CKPT_A" ]; then
  # shellcheck disable=SC2086
  step "train_ckpt_a" 3600 python -m bigdl_tpu.cli.main train \
    transformer_lm $LM_DIMS --seq 1024 -b 8 -i 50 --seed 1 \
    --checkpoint "$CKPT_A" --dataType constant || true
  # shellcheck disable=SC2086
  step "train_ckpt_b" 3600 python -m bigdl_tpu.cli.main train \
    transformer_lm $LM_DIMS --seq 1024 -b 8 -i 50 --seed 2 \
    --checkpoint "$CKPT_B" --dataType constant || true
fi

# shared serving geometry — matches tpu_capture_r18..r22 so latency
# reads against those logs
LM="--serveArg=--vocabSize --serveArg=32000 \
    --serveArg=--dModel --serveArg=1024 \
    --serveArg=--numLayers --serveArg=8 \
    --serveArg=--numHeads --serveArg=16 \
    --serveArg=--seq --serveArg=1024 \
    --serveArg=--slots --serveArg=8"
GEN="--model transformer_lm --endpoint generate \
     --requests 64 --promptLen 128 --maxNewTokens 128"

# 2. THE r23 headline — rolling swap under sustained load at real
#    reload cost. The fleet smoke drives its own kill + swap legs; on
#    chip the interesting numbers are the swap-window 5xx count (must
#    stay 0) and how long each worker's drain->restore->rejoin takes
#    (the per-worker capacity dip). x3 reps.
for REP in 1 2 3; do
  step "fleet_swap_rep${REP}" 3600 env \
    BIGDL_FLEET_CKPT_A="$CKPT_A" BIGDL_FLEET_CKPT_B="$CKPT_B" \
    python scripts/serving_bench.py --fleetSmoke --model transformer_lm
done

# 3. proxy-overhead A/B at equal chip count: in-process dp:2 (PR 15)
#    vs fleet --fleet 2 (this round), same bench geometry. Acceptance:
#    fleet p50 within noise of dp:2; the delta IS the process hop.
for REP in 1 2 3; do
  # shellcheck disable=SC2086
  step "dp2_rep${REP}" 1800 python scripts/serving_bench.py \
    $GEN $LM --concurrency 8 \
    --serveArg=--strategy --serveArg=dp:2 \
    --serveArg=--reqTrace --serveArg=on || true
  # shellcheck disable=SC2086
  step "fleet2_rep${REP}" 1800 python scripts/serving_bench.py \
    $GEN $LM --concurrency 8 \
    --serveArg=--fleet --serveArg=2 \
    --serveArg=--reqTrace --serveArg=on || true
done

# 4. worker-kill goodput floor: run the closed-loop bench against a
#    2-worker fleet, kill -9 one worker a third of the way through
#    (pid from /debug/fleet), let the supervisor restart it. The bench
#    error count + the router's slo goodput gauges give the floor; the
#    fleet must never 503 the whole window (readyz stays 200).
step "fleet_kill_goodput" 3600 bash -c '
  set -u
  python scripts/serving_bench.py '"$GEN $LM"' --concurrency 8 \
    --serveArg=--fleet --serveArg=2 \
    --serveArg=--slo --serveArg=ttft=2000,tpot=100 &
  BENCH=$!
  sleep 45
  PORT=$(ss -ltnp 2>/dev/null | grep -o ":80[0-9][0-9]" | head -1 | tr -d :)
  PORT="${PORT:-8000}"
  WPID=$(python -c "import json,urllib.request as u; \
    d=json.load(u.urlopen(\"http://127.0.0.1:${PORT}/debug/fleet\")); \
    print(d[\"workers\"][0][\"pid\"])" 2>/dev/null || echo "")
  [ -n "$WPID" ] && kill -9 "$WPID" && echo "killed worker pid=$WPID"
  wait "$BENCH"
' || true

# 5. composed production stack through the fleet: quantized weights +
#    paged KV + speculation behind the router — the swap must
#    re-quantize on reload and the proxy must not tax the decode.
# shellcheck disable=SC2086
step "fleet_quant_spec" 1800 python scripts/serving_bench.py \
  $GEN $LM --concurrency 8 \
  --serveArg=--fleet --serveArg=2 \
  --serveArg=--quantize --serveArg=int8+kv8 \
  --serveArg=--speculate --serveArg=4 \
  --serveArg=--kvPageTokens --serveArg=128 || true

# 6. summarize every JSON line in this log for PERF.md §27
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
