"""Flash-vs-dense attention microbenchmark (VERDICT r2 missing #3).

Times forward and forward+backward of the Pallas flash kernel against the
dense XLA path at seq 1k-32k, causal, bf16, d=64. Dense materializes the
(s, s) score matrix, so at 16k+ it is expected to fail allocation and
print an error row — that contrast (flash rows keep going) is the point.

    python scripts/flash_bench.py [batch] [heads] [dim]

One JSON line per (seq, impl, pass). Meaningful numbers need the TPU
(interpret-mode Pallas is not timed — on non-TPU backends flash rows are
skipped, and the long-seq dense attempts may be OOM-killed by the OS).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.attention import dot_product_attention
from bigdl_tpu.ops import flash_attention


def _sync(x):
    leaf = jax.tree_util.tree_leaves(x)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))


def timeit(fn, args, iters=10):
    c = jax.jit(fn).lower(*args).compile()
    out = c(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = c(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters * 1000  # ms


def run(b=4, h=8, d=64):
    on_tpu = jax.default_backend() == "tpu"
    rs = np.random.RandomState(0)
    # 16k/32k: dense needs the (s,s) score matrix (68 GB bf16 at 32k —
    # records an OOM error row); flash streams it in O(block) VMEM
    for s in (1024, 2048, 4096, 8192, 16384, 32768):
        q = jnp.asarray(rs.randn(b, h, s, d), jnp.bfloat16)
        k = jnp.asarray(rs.randn(b, h, s, d), jnp.bfloat16)
        v = jnp.asarray(rs.randn(b, h, s, d), jnp.bfloat16)
        # causal attention FLOPs: 2 matmuls, ~half the s^2 under the mask
        flops = 2 * 2.0 * b * h * s * s * d / 2
        impls = {"dense": lambda q, k, v: dot_product_attention(
            q, k, v, causal=True)}
        if on_tpu:
            impls["flash"] = lambda q, k, v: flash_attention(
                q, k, v, causal=True)
        for name, f in impls.items():
            if name == "dense" and s > 8192 and not on_tpu:
                # off-TPU there is no flash row to contrast with, and the
                # (s,s) dense attempt can draw the OS OOM killer
                continue
            try:
                t_f = timeit(f, (q, k, v))
                loss = (lambda f_: lambda q, k, v: f_(
                    q, k, v).astype(jnp.float32).sum())(f)
                t_b = timeit(jax.grad(loss, argnums=(0, 1, 2)), (q, k, v))
            except Exception as e:  # dense OOMs first at long seq
                # full repr: an expected dense RESOURCE_EXHAUSTED must be
                # distinguishable from a flash lowering regression
                print(json.dumps({"seq": s, "impl": name,
                                  "error": repr(e)[:200]}),
                      flush=True)
                continue
            print(json.dumps({
                "seq": s, "impl": name,
                "fwd_ms": round(t_f, 3), "fwdbwd_ms": round(t_b, 3),
                "fwd_tflops": round(flops / t_f / 1e9, 1),
                "fwdbwd_tflops": round(3.5 * flops / t_b / 1e9, 1),
                "backend": jax.default_backend(),
            }), flush=True)


if __name__ == "__main__":
    b = int(sys.argv[1]) if len(sys.argv) > 1 else 4
    h = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    d = int(sys.argv[3]) if len(sys.argv) > 3 else 64
    run(b, h, d)
