#!/usr/bin/env bash
# Round-5 resume sweep: the first window (22:44-22:46Z) lasted ~2.5 min —
# long enough for `pytest -m tpu` to PASS compiled (flash + fused-BN on
# Mosaic, TPU_CAPTURE_r05.log) and nothing else. This sweep re-runs the
# remaining steps, ordered by evidentiary value, and GATES each step on a
# 90 s device probe: when the tunnel dies mid-sweep the sweep aborts fast
# (instead of burning 900 s per dead step) and re-arms the poller.
set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r05.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r05.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

probe() {
  timeout 90 python - <<'EOF' >/dev/null 2>&1
import jax, jax.numpy as jnp
assert jax.default_backend() == "tpu"
(jnp.ones((128, 128)) @ jnp.ones((128, 128))).block_until_ready()
EOF
}

step() {
  local name="$1" tmo="$2"; shift 2
  if ! probe; then
    echo "=== ABORT before $name: tunnel dead ($(date -u +%H:%M:%SZ)); re-arming poller" | tee -a "$OUT"
    cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
    exec bash scripts/tpu_poll_and_capture.sh scripts/tpu_capture_r05b.sh
  fi
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 1. headline + official bench line first (BENCH_PARTIAL.jsonl streams rows)
step "perf_resnet50_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random
step "bench_main" 2400 python bench.py

# 2. transformer datapoints (flash kernel e2e on chip)
step "perf_transformer_lm_b32" 900 python -m bigdl_tpu.cli.perf -m transformer_lm -b 32 -i 10 --dataType random
step "perf_transformer_lm_1k_b16" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_1k -b 16 -i 10 --dataType random

# 3. lever A/Bs in profiled-impact order (VERDICT r4 item 2)
step "perf_resnet50_fbn_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50_fbn -b 128 -i 20 --dataType random
step "conv_bwd_probe" 1500 bash -c "python scripts/conv_bwd_probe.py 30 | tee /tmp/conv_probe_r05.jsonl"
step "conv_probe_apply" 900 bash -c 'L=$(python scripts/apply_conv_probe.py /tmp/conv_probe_r05.jsonl) && echo "decision: $L" && python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --convLayout "$L"'
step "perf_resnet50_s2d_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50_s2d -b 128 -i 20 --dataType random
step "perf_resnet50_inner10_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 4 --innerSteps 10 --dataType random
step "perf_resnet50_fbn_s2d_inner10" 900 python -m bigdl_tpu.cli.perf -m resnet50_fbn -b 128 -i 4 --innerSteps 10 --dataType random
step "perf_resnet50_bnss_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50_bnss -b 128 -i 20 --dataType random

# 4. flash vs dense microbenchmark (incl. 16k/32k flash-only rows)
step "flash_bench" 1800 python scripts/flash_bench.py 4 8 64

# 5. batch sweep + rope
for B in 64 256 512; do
  step "perf_resnet50_b$B" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b "$B" -i 20 --dataType random
done
step "perf_transformer_lm_rope_b32" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_rope -b 32 -i 10 --dataType random

# 6. train-from-storage pipeline bench
step "bench_pipe" 2400 env BENCH_TPU_TIMEOUT=2000 BENCH_COMPANIONS=0 python bench.py resnet50_pipe 128 20

# 7. convergence + TTA at scale (the long tail; only reached in a long window)
if [ ! -f /tmp/synth_mnist_full/train-images-idx3-ubyte ]; then
  step "make_synth_mnist" 1200 python scripts/make_synth_mnist.py /tmp/synth_mnist_full 20000 4000
fi
step "lenet_convergence" 1800 ./scripts/run_example.sh lenet /tmp/synth_mnist_full -b 128 --maxEpoch 20 --learningRate 0.1
step "time_to_acc_cifar_scale" 3600 python -m bigdl_tpu.cli.perf -m resnet20_cifar --timeToAcc 0.91 -b 128 --imageSize 32 --maxEpoch 156 --trainPerClass 5000 --valPerClass 1000 --ttaHard --ttaLift 7 --valEvery 65
step "time_to_acc_resnet50" 2400 python -m bigdl_tpu.cli.perf -m resnet50 --timeToAcc 0.85 -b 64 --imageSize 224 --maxEpoch 15

# 8. sustained-training soak on chip (VERDICT r4 stretch item 9):
# kill -9 mid-run + resume + steady-state verdict. Dataset generation
# (20k JPEGs + shards) is its own host-side step so the soak slot is not
# burned on IO; the 3300 s timeout then has real headroom over
# phase1+phase2+wait slack (1500+480+600) + two compiles (phase-2 resume
# loads from the persistent cache). orchestrate reaps its training child
# on SIGTERM/timeout so nothing can orphan a device-lock-holding
# grandchild.
step "soak_data_prep" 1500 python -c "import sys; sys.path.insert(0, '.'); from scripts.soak import _ensure_data; print(_ensure_data('/tmp/soak_chip'))"
step "soak_chip" 3300 python scripts/soak.py orchestrate --dir /tmp/soak_chip --batch 128 --ckpt-every 50 --phase1 1500 --phase2 480

echo "r05b sweep complete -> $OUT" | tee -a "$OUT"
