#!/usr/bin/env bash
# Round-8 capture: ISSUE 3 (per-conv-geometry layout policy + 1x1-as-GEMM)
# chip evidence. Core contract: the ResNet-50 b128 tuned-vs-global A/B —
# per-geometry decisions (stem wgrad NCHW, 3x3 stages NHWC, 1x1/s1 convs
# optionally GEMM; ops/conv2d.py + tuning conv_geom namespace) against
# the single global triple that round 5 shipped — plus the per-op
# backward roofline capture (xplane profile joined against same-shape
# isolated microbenches, scripts/backward_roofline.py -> PERF.md §11).
# resnet50_pipe is gone from the sweep (VERDICT r5 weak #5) — its ~32 s
# funds the A/B legs here. Appends to $OUT, mirrored into the repo per
# step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r08.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r08.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 1. compiled-path tests incl. the per-geometry/GEMM conv smoke
step "pytest_tpu_marked" 1200 env BIGDL_TPU_TESTS=1 python -m pytest tests/ -m tpu -q

# 2. the per-shape probe, now with geometry fields + the GEMM leg on the
#    1x1/s1 shapes (~half of ResNet-50's FLOPs are GEMMs in conv
#    clothes) — the decision source AND the roofline microbench side
step "conv_probe_geom" 1200 sh -c 'python scripts/conv_bwd_probe.py 30 | tee /tmp/conv_probe_r08.jsonl; cp -f /tmp/conv_probe_r08.jsonl CONV_PROBE_r08.jsonl'

# 3. probe -> per-geometry decisions: JSON for --convGeom AND persisted
#    into the autotune conv_geom namespace for --autotune cached replay
step "apply_probe_geom" 120 sh -c 'python scripts/apply_conv_probe.py --geom --cache /tmp/conv_probe_r08.jsonl | tee /tmp/conv_geom_r08.json; cp -f /tmp/conv_geom_r08.json CONV_GEOM_r08.json'

# 4. THE A/B contract — resnet50 b128, same window:
#    (a) global policy baseline (the round-5 shipped decision),
#    (b) per-geometry decisions from the probe (--convGeom),
#    (c) cached autotune replay (conv_geom namespace; also re-tunes
#        flash/BN keys it already holds),
#    (d) the explicit all-GEMM-wgrad spelling as a single-lever probe.
step "perf_resnet50_b128_global" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random
step "perf_resnet50_b128_geom" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --convGeom /tmp/conv_geom_r08.json
step "perf_resnet50_b128_geom_cached" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --autotune cached
step "perf_resnet50_b128_gemm_wgrad" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --convLayout NHWC,NHWC,GEMM

# 5. measure-mode autotune now resolves per-geometry conv_geom keys live
#    at trace time (plus the flash/BN keys) — the fully-automatic leg
step "perf_resnet50_b128_geom_measure" 1800 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --autotune measure

# 6. per-geometry composed with the best single lever (innerSteps=10):
#    the §8.2 lesson — levers interact, measure the composition
step "perf_resnet50_geom_inner10" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 4 --innerSteps 10 --dataType random --convGeom /tmp/conv_geom_r08.json

# 7. ROOFLINE capture: xplane trace of the tuned b128 run, joined against
#    the same-window isolated microbenches -> the PERF.md §11 table
step "perf_profile_roofline" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 5 --dataType random --convGeom /tmp/conv_geom_r08.json --profile /tmp/xprof_r08
step "roofline_join" 300 sh -c 'python scripts/backward_roofline.py --probe /tmp/conv_probe_r08.jsonl --profile /tmp/xprof_r08 --steps 5 --top 12 --out ROOFLINE_r08.md --json ROOFLINE_r08.json; cat ROOFLINE_r08.md'

# 8. the populated cache is part of the evidence — archive it
step "autotune_cache_dump" 60 sh -c 'for f in ~/.cache/bigdl_tpu/autotune/*.json; do echo "--- $f"; cat "$f"; done'

# 9. full bench line: resnet50_geom companion (cached replay) rides next
#    to resnet50_tuned and the headline; hard-grade TTA curve included;
#    pipe row gone
step "bench_headline" 5400 env BENCH_TPU_TIMEOUT=2000 python bench.py resnet50 128 20
