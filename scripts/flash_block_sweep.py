"""Flash-attention block-size sweep (chip): the kernel's absolute TF/s
bounds the LM path (PERF.md §8.2 — 16k e2e is attention-bound at the
kernel's ~12 TF/s fwd+bwd vs the chip's ~92 TF/s conv ceiling). Each
(block_q, block_k) changes per-program matmul size and grid overhead;
this times fwd and fwd+bwd per combo and prints one JSON line each.

Usage: python scripts/flash_block_sweep.py [seq] [b] [h] [d]
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp


def main():
    seq = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    b = int(sys.argv[2]) if len(sys.argv) > 2 else 4
    h = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    d = int(sys.argv[4]) if len(sys.argv) > 4 else 128

    from bigdl_tpu.cli.common import enable_compile_cache
    from bigdl_tpu.ops import flash_attention
    enable_compile_cache()

    key = jax.random.PRNGKey(0)
    q = jax.random.normal(key, (b, h, seq, d), jnp.bfloat16)
    # causal algorithmic flops (live-pair basis, matching the kernels'
    # declared CostEstimate): fwd 2 units, fwd+bwd 6 units over ~s^2/2
    unit = 2.0 * b * h * (seq * seq / 2) * d

    for bq in (128, 256, 512):
        for bk in (128, 256, 512):
            if bq > seq or bk > seq:
                continue
            fn = functools.partial(flash_attention, causal=True,
                                   block_q=bq, block_k=bk)

            def loss(q):
                return jnp.sum(fn(q, q, q).astype(jnp.float32))

            try:
                # Timing rules for this tunnel (PERF.md §8.2, learned
                # the hard way): (a) chain each call on the previous
                # result so executions cannot be elided/pipelined;
                # (b) sync by FETCHING a value to host — through axon,
                # block_until_ready acks before device completion and
                # "timed" impossible >1000 TF/s. float(sum(...)) is the
                # only trustworthy barrier (flash_bench's pattern).
                def _sync(x):
                    leaf = jax.tree_util.tree_leaves(x)[0]
                    return float(jnp.sum(leaf.astype(jnp.float32)))

                fwd = jax.jit(fn)
                cur = fwd(q, q, q)
                _sync(cur)
                t0 = time.perf_counter()
                for _ in range(5):
                    cur = fwd(cur, q, q)
                _sync(cur)
                f_ms = (time.perf_counter() - t0) / 5 * 1e3

                g = jax.jit(jax.value_and_grad(loss))
                _, gq = g(q)
                _sync(gq)
                t0 = time.perf_counter()
                for _ in range(5):
                    _, gq = g(gq)
                _sync(gq)
                fb_ms = (time.perf_counter() - t0) / 5 * 1e3
                print(json.dumps({
                    "seq": seq, "bq": bq, "bk": bk,
                    "fwd_ms": round(f_ms, 3),
                    "fwd_tflops": round(2 * unit / f_ms / 1e9, 2),
                    "fwdbwd_ms": round(fb_ms, 3),
                    "fwdbwd_tflops": round(6 * unit / fb_ms / 1e9, 2),
                }), flush=True)
            except Exception as e:  # lowering failure is a result too
                print(json.dumps({"seq": seq, "bq": bq, "bk": bk,
                                  "error": str(e)[:160]}), flush=True)


if __name__ == "__main__":
    main()
