#!/usr/bin/env bash
# Round-6 capture: ISSUE 1 (per-shape autotuner) chip evidence.
# Core contract: a tuned-vs-default A/B on resnet50 b128 and
# transformer_lm_1k so the window records the MFU delta of the measured
# decisions (conv pass layouts per run-config, flash block sizes per
# shape, BN stats row block). Order: populate the cache once with
# --autotune measure, then time clean runs under --autotune cached
# against --autotune off baselines — the measure run itself pays the
# candidate-sweep compiles and must not be the timed half.
# Appends to $OUT, mirrored into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r06.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r06.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 1. compiled-path autotune + kernel tests (includes the -m tpu autotune
#    round-trip: measure populates a real measured entry, cached rereads it)
step "pytest_tpu_marked" 1200 env BIGDL_TPU_TESTS=1 python -m pytest tests/ -m tpu -q

# 2. the A/B contract — resnet50 b128 (conv layouts + BN row block)
step "perf_resnet50_b128_default" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --autotune off
step "autotune_measure_resnet50" 1800 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --autotune measure
step "perf_resnet50_b128_tuned" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --autotune cached

# 3. the A/B contract — transformer_lm_1k (flash block sizes at seq 1024)
step "perf_transformer_lm_1k_default" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_1k -b 16 -i 10 --dataType random --autotune off
step "autotune_measure_transformer_lm_1k" 1800 python -m bigdl_tpu.cli.perf -m transformer_lm_1k -b 16 -i 10 --dataType random --autotune measure
step "perf_transformer_lm_1k_tuned" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_1k -b 16 -i 10 --dataType random --autotune cached

# 4. guarded-config composition: the tuner resolves per-variant keys
#    (inner/s2d) instead of skipping installation — measure + A/B them
step "autotune_measure_resnet50_inner10" 1800 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 4 --innerSteps 10 --dataType random --autotune measure
step "perf_resnet50_inner10_tuned" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 4 --innerSteps 10 --dataType random --autotune cached
step "perf_resnet50_inner10_default" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 4 --innerSteps 10 --dataType random --autotune off

# 5. long-context flash shapes: per-shape block decisions at 16k
step "autotune_measure_lm_16k" 1800 python -m bigdl_tpu.cli.perf -m transformer_lm_16k -b 1 -i 3 --dataType random --autotune measure
step "perf_lm_16k_tuned" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_16k -b 1 -i 3 --dataType random --autotune cached
step "perf_lm_16k_default" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_16k -b 1 -i 3 --dataType random --autotune off

# 6. the fused-BN model under a tuned row block (r5 measured fbn −46%
#    at the fixed 512 block; does a tuned block change the verdict?)
step "autotune_measure_resnet50_fbn" 1800 python -m bigdl_tpu.cli.perf -m resnet50_fbn -b 128 -i 20 --dataType random --autotune measure
step "perf_resnet50_fbn_tuned" 900 python -m bigdl_tpu.cli.perf -m resnet50_fbn -b 128 -i 20 --dataType random --autotune cached
step "perf_resnet50_fbn_default" 900 python -m bigdl_tpu.cli.perf -m resnet50_fbn -b 128 -i 20 --dataType random --autotune off

# 7. the populated cache is part of the evidence — archive it
step "autotune_cache_dump" 60 sh -c 'for f in ~/.cache/bigdl_tpu/autotune/*.json; do echo "--- $f"; cat "$f"; done'

# 8. full bench line (includes the resnet50_tuned / transformer_lm_tuned
#    companions riding next to their untuned halves)
step "bench_headline" 5400 env BENCH_TPU_TIMEOUT=2000 python bench.py resnet50 128 20
