#!/usr/bin/env bash
# Round-13 capture: ISSUE 8 (device-time attribution + --strategy) chip
# evidence. The attribution loop is CPU-verified end to end
# (tests/test_attrib.py, tests/test_strategy_perf.py, the attrib-smoke
# CI job); what only hardware can tell us is (a) what the per-category
# split of a REAL tuned step looks like — the §16 result slots: does
# conv+matmul time match the §2 profile, how much rides in elementwise
# fusions, (b) whether single-chip collective time is truly ~0 (the
# baseline the multichip rows get compared against), and (c) the
# per-strategy attribution A/Bs on any multi-chip slice this tunnel
# exposes: dp vs tp vs ep with collective_s broken out per window —
# ROADMAP item 2's "measure the all-reduce before shrinking it".
# Appends to $OUT, mirrored into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r13.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r13.log}"
TRACE_ROOT="${TRACE_ROOT:-/tmp/attrib_r13}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 0. the attribution/strategy tests on the bench env first
step "pytest_attrib" 600 python -m pytest tests/test_attrib.py \
  tests/test_strategy_perf.py tests/test_roofline.py -q

# 1. single-chip attribution of the tuned flagships: capture a 4-step
#    window mid-run, attribution lands in the JSON line (attrib +
#    collective_s columns — expect collective_s ~0 on one chip; that
#    number IS the baseline for the multichip A/Bs below)
step "attrib_resnet50_fba" 1800 python -m bigdl_tpu.cli.main perf \
  -m resnet50_fba -b 128 -i 40 --autotune cached \
  --obs --traceDir "$TRACE_ROOT/resnet50_fba" --traceSteps 4@20
step "attrib_lm_hd128" 1800 python -m bigdl_tpu.cli.main perf \
  -m transformer_lm_1k_hd128 -b 8 -i 40 --autotune cached \
  --obs --traceDir "$TRACE_ROOT/lm_hd128" --traceSteps 4@20

# 2. the explain CLI over those windows (human table -> log, JSON ->
#    artifacts) — the §16 "explain recipe" exercised on real profiles
step "explain_resnet50" 600 python -m bigdl_tpu.cli.main explain \
  "$TRACE_ROOT/resnet50_fba/capture_20" --steps 4
step "explain_resnet50_json" 600 bash -c \
  "python -m bigdl_tpu.cli.main explain \
   '$TRACE_ROOT/resnet50_fba/capture_20' --steps 4 --json \
   > '$TRACE_ROOT/resnet50_fba_attrib.json' && \
   tail -c 400 '$TRACE_ROOT/resnet50_fba_attrib.json'"
step "explain_lm" 600 python -m bigdl_tpu.cli.main explain \
  "$TRACE_ROOT/lm_hd128/capture_20" --steps 4

# 3. model-mode explain: one command from nothing to a table (runs a
#    short profiled loop itself; numerators/peak wired automatically)
step "explain_model_mode" 1800 python -m bigdl_tpu.cli.main explain \
  resnet50 -b 128 -i 10

# 4. per-strategy attribution A/Bs. On a single-chip tunnel these exit
#    cleanly ("needs more than one device") and cost seconds; on a
#    multi-chip slice each leg stamps mesh topology + per-window
#    collective_s/collective_frac — dp's one grad all-reduce vs tp's
#    per-layer collectives vs ep's routed dispatch is THE r13 table.
for STRAT in dp tp ep; do
  step "strategy_${STRAT}_resnet50" 1800 python -m bigdl_tpu.cli.main \
    perf -m resnet50 -b 128 -i 30 --strategy "$STRAT" \
    --obs --traceDir "$TRACE_ROOT/strat_${STRAT}" --traceSteps 4@15 \
    || true
done
step "strategy_dp_lm" 1800 python -m bigdl_tpu.cli.main perf \
  -m transformer_lm_1k_hd128 -b 8 -i 30 --strategy dp \
  --obs --traceDir "$TRACE_ROOT/strat_dp_lm" --traceSteps 4@15 || true

# 5. bench.py with the strategy plumbed through (the multichip bench
#    row with collective_s in the line)
step "bench_strategy_dp" 2400 python bench.py resnet50 128 20 \
  --strategy dp

# 6. summarize every JSON line in this log for PERF.md §16
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
