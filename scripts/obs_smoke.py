"""Observability smoke (ISSUE 7 CI): one CPU perf run with the full
--obs surface, asserted end to end.

What it proves (the tier1.yml ``obs-smoke`` job):

1. an obs-ON lenet5 perf run stamps the phase columns
   (``data_wait_s``/``h2d_s``/``dispatch_s``/``device_s``/``ckpt_s``/
   ``stall_frac``) into its perf JSON, and their sum is sane against
   the measured wall time;
2. the Chrome-trace span timeline json-loads and contains the step
   phases;
3. a LIVE ``/metrics`` scrape from the training listener (taken while
   the run is still stepping when the box is fast enough, from the
   still-running listener right after otherwise) carries the step-phase
   histograms in serving's exposition format;
4. an obs-OFF run of the same config emits exactly the null phase
   columns and leaves the span API as compiled no-ops.

Usage:  python scripts/obs_smoke.py [--model lenet5 -b 16 -i 40]
Exit 0 = all assertions held.
"""

import argparse
import json
import os
import sys
import tempfile
import threading
import time
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _fail(msg):
    print(f"obs_smoke: FAIL: {msg}", flush=True)
    sys.exit(1)


def main():
    ap = argparse.ArgumentParser("obs_smoke")
    ap.add_argument("--model", default="lenet5")
    ap.add_argument("-b", "--batch", type=int, default=16)
    ap.add_argument("-i", "--iters", type=int, default=40)
    args = ap.parse_args()

    from bigdl_tpu import obs
    from bigdl_tpu.cli import common, perf

    td = tempfile.mkdtemp(prefix="obs_smoke_")
    obs.enable()
    srv = obs.start_metrics_server(obs.get_registry(), port=0)
    if srv is None:
        _fail("metrics listener failed to bind")
    capture = obs.CaptureController(td, install_signal=False)
    st = common.ObsState(True, td, capture, srv)

    result = {}

    def _run():
        result["out"] = perf.run(args.model, args.batch, args.iters,
                                 "constant", use_bf16=False, obs_state=st)

    t = threading.Thread(target=_run, daemon=True)
    t.start()

    # (3) live scrape: poll while the run steps; the histograms appear
    # in the registry at the first timed iteration. If the run outraces
    # the poll (tiny model, fast box) the listener is still up — the
    # final scrape below is equally live.
    page, live = "", False
    deadline = time.time() + 300
    while t.is_alive() and time.time() < deadline:
        try:
            with urllib.request.urlopen(srv.url, timeout=5) as r:
                page = r.read().decode()
            if "train_phase_dispatch_ms_bucket" in page:
                live = True
                break
        except Exception:
            pass
        time.sleep(0.2)
    t.join(300)
    if t.is_alive():
        _fail("perf run did not finish in time")
    if "out" not in result:
        _fail("perf run raised (see traceback above)")
    if not live:
        with urllib.request.urlopen(srv.url, timeout=10) as r:
            page = r.read().decode()
    if "train_phase_dispatch_ms_bucket" not in page:
        _fail("/metrics scrape has no step-phase histograms")
    if "train_phase_device_ms_count" not in page:
        _fail("/metrics scrape has no device-phase histogram")
    print(f"obs_smoke: /metrics scrape ok (live={live}, "
          f"{len(page.splitlines())} lines)", flush=True)

    # (1) phase columns present and coherent
    out = result["out"]
    cols = ("data_wait_s", "h2d_s", "dispatch_s", "device_s", "ckpt_s",
            "stall_frac")
    for c in cols:
        if out.get(c) is None:
            _fail(f"obs-on perf JSON missing phase column {c}")
    phase_sum = (out["data_wait_s"] + out["h2d_s"] + out["dispatch_s"]
                 + out["device_s"] + out["ckpt_s"])
    ratio = phase_sum / max(out["seconds"], 1e-9)
    if not 0.5 <= ratio <= 1.05:  # CI boxes are noisy; tests pin 10%
        _fail(f"phase sum {phase_sum:.4f}s vs wall {out['seconds']}s "
              f"(ratio {ratio:.3f}) is incoherent")
    print(f"obs_smoke: phase columns ok (sum/wall = {ratio:.3f})",
          flush=True)

    # (2) the span timeline json-loads and carries the step phases
    trace_path = out.get("obs", {}).get("trace_json")
    if not trace_path:
        _fail("no trace_json in the obs annotation")
    with open(trace_path) as f:
        doc = json.load(f)
    names = {e["name"] for e in doc["traceEvents"]}
    if not {"dispatch", "device"} <= names:
        _fail(f"trace is missing step-phase spans (has {sorted(names)})")
    print(f"obs_smoke: chrome trace ok ({len(doc['traceEvents'])} "
          f"events)", flush=True)

    # (4) obs-off leg: null columns, no-op spans, no obs annotation
    srv.close()
    obs.disable()
    off = perf.run(args.model, args.batch, max(4, args.iters // 10),
                   "constant", use_bf16=False)
    for c in cols:
        if c not in off or off[c] is not None:
            _fail(f"obs-off perf JSON column {c} should be null, got "
                  f"{off.get(c)!r}")
    if "obs" in off:
        _fail("obs-off perf JSON must not carry an obs annotation")
    if obs.span("x") is not obs.NOOP_SPAN:
        _fail("disabled span() is not the shared no-op singleton")
    print("obs_smoke: obs-off null columns ok", flush=True)
    print("obs_smoke: PASS", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
