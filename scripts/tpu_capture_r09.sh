#!/usr/bin/env bash
# Round-9 capture: ISSUE 4 (tpulint) chip correlation. The lint pass is
# CPU-static by construction; what only a chip can tell us is which
# findings CORRELATE with measured MFU gaps — so this window records the
# lint report for each A/B leg right next to the measured numbers
# (PERF.md §12 "next chip window" contract), then re-runs the r08-style
# tuned-vs-default A/Bs with --lint so every perf JSON line carries its
# finding summary inline. Appends to $OUT, mirrored into the repo per
# step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r09.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r09.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 1. compiled-path tests incl. the lint suite (CPU rules must agree with
#    what actually lowers on the chip backend)
step "pytest_tpu_marked" 1200 env BIGDL_TPU_TESTS=1 python -m pytest tests/ -m tpu -q
step "pytest_lint" 300 python -m pytest tests/test_lint.py -q

# 2. lint reports for the A/B legs, archived as JSON — the artifact the
#    correlation table in PERF.md §12 is built from. Default config
#    (expected: fusion-bn-unfused error, conv-gemm + upcast warnings)
#    vs the tuned config (expected: zero fusion findings).
step "lint_resnet50_default" 300 sh -c 'python -m bigdl_tpu.cli.main lint resnet50 -b 128 --json LINT_r09_resnet50_default.json'
step "lint_resnet50_tuned" 300 sh -c 'python -m bigdl_tpu.cli.main lint resnet50 -b 128 --fusedBN apply --convLayout GEMM,GEMM,GEMM --json LINT_r09_resnet50_tuned.json'
step "lint_resnet50_fba_b128" 300 sh -c 'python -m bigdl_tpu.cli.main lint resnet50_fba -b 128 --json LINT_r09_resnet50_fba.json'
step "lint_transformer_lm_1k" 300 sh -c 'python -m bigdl_tpu.cli.main lint transformer_lm_1k -b 8 --json LINT_r09_lm1k.json'
step "lint_transformer_lm_1k_hd128" 300 sh -c 'python -m bigdl_tpu.cli.main lint transformer_lm_1k_hd128 -b 8 --json LINT_r09_lm1k_hd128.json'

# 3. the correlation legs: same model, lint-flagged config vs lint-clean
#    config, measured in one window — does the error/warning delta
#    predict the MFU delta? --lint stamps the summary into each JSON
#    line so the pairing is self-describing.
step "perf_resnet50_default_lint" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --lint
step "perf_resnet50_fba_lint" 900 python -m bigdl_tpu.cli.perf -m resnet50_fba -b 128 -i 20 --dataType random --lint
step "perf_resnet50_tuned_lint" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --fusedBN apply --autotune cached --lint
step "perf_lm1k_lint" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_1k -b 8 -i 20 --dataType random --lint
step "perf_lm1k_hd128_lint" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_1k_hd128 -b 8 -i 20 --dataType random --lint

# 4. strict gate smoke ON the chip environment (exit codes are the CI
#    contract; rc=2 expected for the first, rc=0 for the second)
step "lint_strict_misconfig" 300 sh -c 'python -m bigdl_tpu.cli.main lint resnet50 -b 128 --strict; echo "strict-misconfig rc=$?"'
step "lint_strict_tuned" 300 sh -c 'python -m bigdl_tpu.cli.main lint resnet50 -b 128 --fusedBN apply --convLayout GEMM,GEMM,GEMM --strict; echo "strict-tuned rc=$?"'

# 5. full bench line rides along as usual so the window also refreshes
#    the headline numbers next to the lint artifacts
step "bench_headline" 5400 env BENCH_TPU_TIMEOUT=2000 python bench.py resnet50 128 20
