#!/usr/bin/env bash
# Round-17 capture: ISSUE 13 (executor input pipeline) chip evidence.
# The executor's determinism/backpressure/resume contracts are
# CPU-verified end to end (tests/test_pipeline_exec.py, the
# pipeline-smoke CI job) — what only hardware can tell us is whether the
# N-worker executor + double-buffered device staging actually closes the
# feed gap the legacy single-window pipe leg measured (0.99% MFU,
# PERF.md §4): (a) the before/after leg trains resnet50 from the SAME
# record shards under the legacy feed and under the executor at matched
# batch/iterations, with --obs so every line carries data_wait_s /
# stall_frac; (b) the sweep leg grids dataWorkers x prefetchDepth x
# stage to find the knee on real decode + real h2d; (c) the staging A/B
# isolates --stage device (producer-thread jax.device_put) vs host.
# Appends to $OUT, mirrored into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r17.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r17.log}"
SHARDS="${SHARDS:-/tmp/pipe_r17_shards}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 0. the pipeline tests on the bench env first
step "pytest_pipeline" 600 python -m pytest tests/test_pipeline_exec.py \
  tests/test_record_pipeline.py -q

# 1. shared shard set for every leg (1024 ImageNet-shape JPEGs) — the
#    A/B must compare feed machinery, not datasets
step "make_shards" 900 python - "$SHARDS" <<'EOF'
import os, sys
sys.path.insert(0, "scripts")
from input_pipeline_bench import make_jpegs
from bigdl_tpu.dataset.recordfile import write_image_shards
root = sys.argv[1]
img = os.path.join(root, "imgs")
if not os.path.isdir(os.path.join(root, "shards")):
    make_jpegs(img, 1024)
    write_image_shards(img, os.path.join(root, "shards"),
                       images_per_shard=256)
print("shards ready:", os.listdir(os.path.join(root, "shards")))
EOF

# 2. THE r17 leg — before/after at matched config. Legacy window feed
#    (the re-admitted resnet50_pipe shape) vs executor + device staging
#    (resnet50_pipe_exec shape). stall_frac and data_wait_s in the two
#    JSON lines are the whole story; images_per_second_per_chip is the
#    headline delta for PERF.md §20.
for LEG in "legacy:--dataWorkers 0 --stage off" \
           "exec:--dataWorkers 8 --prefetchDepth 2 --stage device"; do
  NAME="${LEG%%:*}"; FLAGS="${LEG#*:}"
  # shellcheck disable=SC2086
  step "ab_resnet50_${NAME}" 1800 python -m bigdl_tpu.cli.main perf \
    -m resnet50 -b 128 -i 30 --data "record:$SHARDS/shards" \
    --obs $FLAGS || true
done

# 3. sweep leg: dataWorkers x prefetchDepth x stage on real decode +
#    real h2d — one perf JSON line per config (the knee feeds the §20
#    table and the shipped default)
for W in 1 2 4 8 16; do
  for D in 2 4; do
    step "sweep_w${W}_d${D}_device" 1200 python -m bigdl_tpu.cli.main \
      perf -m resnet50 -b 128 -i 20 --data "record:$SHARDS/shards" \
      --obs --dataWorkers "$W" --prefetchDepth "$D" --stage device \
      || true
  done
done

# 4. staging A/B at the knee: host-staged (consumer-thread h2d) vs
#    device-staged (producer-thread h2d overlapped with the step)
for S in host device; do
  step "stage_${S}_w8_d2" 1200 python -m bigdl_tpu.cli.main perf \
    -m resnet50 -b 128 -i 30 --data "record:$SHARDS/shards" \
    --obs --dataWorkers 8 --prefetchDepth 2 --stage "$S" || true
done

# 5. multichip composition: the executor feed under --strategy dp —
#    device staging commits straight to the NamedSharding layout
step "dp_exec_w8" 1800 python -m bigdl_tpu.cli.main perf \
  -m resnet50 -b 256 -i 20 --data "record:$SHARDS/shards" \
  --obs --strategy dp --dataWorkers 8 --prefetchDepth 2 \
  --stage device || true

# 6. host-side offline sweep (no chip in the loop): simulated-step
#    stall_frac grid for the PERF.md §20 sidebar
step "offline_sweep" 1800 python scripts/input_pipeline_bench.py \
  --sweep --images 512 --batch 128 --stepMs 45 \
  --workers 1,2,4,8,16 --depths 1,2,4 --stages off,host,device || true

# 7. summarize every JSON line in this log for PERF.md §20
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
