#!/usr/bin/env bash
# Round-7 capture: ISSUE 2 (fused BN apply+backward epilogue) chip
# evidence. Core contract: the ResNet-50 b128 fused-vs-stats-vs-default
# A/B — resnet50 (default jnp BN) vs resnet50_fbn (round-4 stats-only
# kernel, the measured −46% leg) vs resnet50_fba (the FULL fused block:
# stats+apply+absorbed-ReLU one kernel forward, Σdy/Σ(dy·x̂)+dx one
# kernel backward — PERF.md §10), attacking the 34 ms backward where the
# stats-only kernel lost by unfusing its elementwise neighbors. Plus the
# bn_fba row-block autotune populate/replay and the flag-spelled run so
# the bn_fused JSON stamp lands in the log. Appends to $OUT, mirrored
# into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r07.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r07.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 1. compiled-path kernel tests (includes test_fba_compiled_on_tpu: the
#    two-phase grid + ri*ph output index map verified under Mosaic, not
#    interpret — the round-3 lesson)
step "pytest_tpu_marked" 1200 env BIGDL_TPU_TESTS=1 python -m pytest tests/ -m tpu -q

# 2. THE A/B contract — resnet50 b128 fused-vs-stats-vs-default, same
#    window, bn_fused stamped in every JSON line
step "perf_resnet50_b128_default" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random
step "perf_resnet50_b128_fbn_stats" 900 python -m bigdl_tpu.cli.perf -m resnet50_fbn -b 128 -i 20 --dataType random
step "perf_resnet50_b128_fba_apply" 900 python -m bigdl_tpu.cli.perf -m resnet50_fba -b 128 -i 20 --dataType random

# 3. flag spelling of the same lever (reaches every model, stamps
#    bn_fused=apply without the _fba model alias)
step "perf_resnet50_b128_fusedBN_apply_flag" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random --fusedBN apply

# 4. bn_fba row-block autotune: populate the cache (measure pays the
#    candidate sweep), then the timed replay under cached — does a tuned
#    row block move the fused-block verdict?
step "autotune_measure_resnet50_fba" 1800 python -m bigdl_tpu.cli.perf -m resnet50_fba -b 128 -i 20 --dataType random --autotune measure
step "perf_resnet50_fba_tuned" 900 python -m bigdl_tpu.cli.perf -m resnet50_fba -b 128 -i 20 --dataType random --autotune cached

# 5. fused block composed with the best measured single lever
#    (innerSteps=10, the 2,677.7 img/s config) — the §8.2 lesson is that
#    levers interact; measure the composition, don't assume it
step "perf_resnet50_fba_inner10" 900 python -m bigdl_tpu.cli.perf -m resnet50_fba -b 128 -i 4 --innerSteps 10 --dataType random

# 6. the populated cache is part of the evidence — archive it
step "autotune_cache_dump" 60 sh -c 'for f in ~/.cache/bigdl_tpu/autotune/*.json; do echo "--- $f"; cat "$f"; done'

# 7. full bench line (resnet50_fba companion rides next to resnet50_fbn
#    and the headline — the A/B inside one JSON line)
step "bench_headline" 5400 env BENCH_TPU_TIMEOUT=2000 python bench.py resnet50 128 20
