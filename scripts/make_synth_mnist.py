"""Generate an MNIST-analog dataset as real idx-ubyte files.

This sandbox has zero egress and ships no datasets, so the end-to-end
convergence run (reference scripts/run.example.sh downloading MNIST and
training LeNet) uses a procedurally rendered stand-in: PIL's built-in
bitmap font draws digits 0-9 at 28x28 with per-sample random shift,
rotation, scale jitter, and pixel noise — a real (non-linearly-separable)
10-class problem with the exact MNIST file format, so
``scripts/run_example.sh lenet <dir>`` runs unchanged.

    python scripts/make_synth_mnist.py <out_dir> [n_train] [n_test]
"""

import os
import struct
import sys

import numpy as np


def render_digit(digit: int, rs: np.random.RandomState) -> np.ndarray:
    from PIL import Image, ImageDraw, ImageFont

    # draw large, then rotate/scale/shift into the 28x28 frame
    canvas = Image.new("L", (40, 40), 0)
    d = ImageDraw.Draw(canvas)
    font = ImageFont.load_default()
    d.text((14, 10), str(digit), fill=255, font=font)
    angle = rs.uniform(-20, 20)
    scale = rs.uniform(1.4, 2.2)
    canvas = canvas.rotate(angle, resample=Image.BILINEAR, center=(17, 14))
    nw = max(8, int(40 * scale))
    canvas = canvas.resize((nw, nw), Image.BILINEAR)
    arr = np.asarray(canvas, np.float32)
    ys, xs = np.nonzero(arr > 32)
    if len(ys) == 0:  # degenerate render; retry with fresh params
        return render_digit(digit, rs)
    cy, cx = int(ys.mean()), int(xs.mean())
    oy = cy - 14 + rs.randint(-3, 4)
    ox = cx - 14 + rs.randint(-3, 4)
    out = np.zeros((28, 28), np.float32)
    for y in range(28):
        sy = y + oy
        if 0 <= sy < arr.shape[0]:
            sx0, sx1 = max(0, ox), min(arr.shape[1], ox + 28)
            if sx1 > sx0:
                out[y, max(0, -ox):max(0, -ox) + (sx1 - sx0)] = \
                    arr[sy, sx0:sx1]
    out += rs.randn(28, 28) * 12 + rs.uniform(0, 20)
    return out.clip(0, 255).astype(np.uint8)


def write_idx_images(path: str, images: np.ndarray) -> None:
    n, h, w = images.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">BBBB", 0, 0, 0x08, 3))
        f.write(struct.pack(">III", n, h, w))
        f.write(images.tobytes())


def write_idx_labels(path: str, labels: np.ndarray) -> None:
    with open(path, "wb") as f:
        f.write(struct.pack(">BBBB", 0, 0, 0x08, 1))
        f.write(struct.pack(">I", len(labels)))
        f.write(labels.astype(np.uint8).tobytes())


def make_split(n: int, seed: int):
    rs = np.random.RandomState(seed)
    labels = rs.randint(0, 10, n).astype(np.uint8)
    images = np.stack([render_digit(int(l), rs) for l in labels])
    return images, labels


def main(out_dir: str, n_train: int = 20000, n_test: int = 4000) -> None:
    os.makedirs(out_dir, exist_ok=True)
    xtr, ytr = make_split(n_train, seed=1)
    xte, yte = make_split(n_test, seed=2)
    write_idx_images(os.path.join(out_dir, "train-images-idx3-ubyte"), xtr)
    write_idx_labels(os.path.join(out_dir, "train-labels-idx1-ubyte"), ytr)
    write_idx_images(os.path.join(out_dir, "t10k-images-idx3-ubyte"), xte)
    write_idx_labels(os.path.join(out_dir, "t10k-labels-idx1-ubyte"), yte)
    print(f"wrote {n_train} train / {n_test} test to {out_dir}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "./data/synth_mnist",
         int(sys.argv[2]) if len(sys.argv) > 2 else 20000,
         int(sys.argv[3]) if len(sys.argv) > 3 else 4000)
