#!/usr/bin/env bash
# Round-11 capture: ISSUE 6 (resilience) chip evidence. The recovery
# machinery is CPU-verified end-to-end (tests/test_resilience.py, the
# chaos-smoke CI job); what only a chip can tell us is (a) that the
# fault-free --supervise hook costs NOTHING measurable on the real hot
# path (the acceptance bound: within noise of baseline img/s), and
# (b) what a preempt-mid-run + supervised restart actually costs in
# wall clock on hardware, with the structured fault log captured from
# the perf JSON / fault-log file. Appends to $OUT, mirrored into the
# repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r11.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r11.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 0. compiled-path + resilience tests first (a broken kernel path would
#    poison every number below; the chaos property must hold on-chip
#    exactly as it does on CPU)
step "pytest_tpu_marked" 1200 env BIGDL_TPU_TESTS=1 python -m pytest tests/ -m tpu -q
step "pytest_resilience" 900 python -m pytest tests/test_resilience.py -q

# 1. supervised-vs-plain overhead A/B (the acceptance bound): identical
#    tuned resnet50 config, 3 interleaved reps each, fault-free. The
#    --supervise leg stamps {"supervisor": {...retries: 0...}} into its
#    JSON line; img/s must be within run-to-run noise of the plain leg
#    (the hook is one pointer check per step).
for REP in 1 2 3; do
  step "perf_plain_rep${REP}" 1800 python -m bigdl_tpu.cli.main perf \
    -m resnet50 -b 128 -i 40 --fusedBN apply --autotune cached
  step "perf_supervised_rep${REP}" 1800 python -m bigdl_tpu.cli.main perf \
    -m resnet50 -b 128 -i 40 --fusedBN apply --autotune cached --supervise
done

# 2. same A/B at the transformer_lm config (different dispatch cadence,
#    tokens/s slot in PERF.md §14)
step "perf_lm_plain" 1800 python -m bigdl_tpu.cli.main perf \
  -m transformer_lm -b 8 -i 40 --autotune cached
step "perf_lm_supervised" 1800 python -m bigdl_tpu.cli.main perf \
  -m transformer_lm -b 8 -i 40 --autotune cached --supervise

# 3. transient-fault recovery ON CHIP: inject 2 retryable dispatch
#    faults into a supervised perf run; the JSON line must show
#    attempts=3/retries=2 with the full event log, and the final
#    throughput row is still a clean measurement (the faulted attempts
#    never print).
step "perf_supervised_faults" 2400 python -m bigdl_tpu.cli.main perf \
  -m resnet50 -b 128 -i 40 --fusedBN apply --autotune cached \
  --supervise 4 --faultPlan "dispatch@step:10;dispatch@step:55"

# 4. preempt-mid-run recovery leg: the chaos harness (hard os._exit
#    kills + supervised restarts + bit-identical assert) on the chip
#    backend, fault log captured into the step output.
step "chaos_kill_resume" 2400 python scripts/chaos_run.py --kills 2
step "chaos_kill_in_ckpt" 2400 python scripts/chaos_run.py \
  --kills 1 --kill-in-ckpt

# 5. serving hardening on chip: deadline-expiry 504 + worker-kill
#    watchdog drill against a real served model, then a loaded A/B with
#    --deadlineMs to measure how many rows the deadline actually sheds
#    at saturation (expired counters land in /metrics provenance).
step "serving_chaos_smoke" 1800 python scripts/serving_bench.py \
  --chaosSmoke --model lenet5
step "serving_deadline_load" 1800 python scripts/serving_bench.py \
  --model resnet50 --requests 128 --concurrency 16 --batch 8 \
  --serveArg=--deadlineMs --serveArg=250 \
  --serveArg=--fusedBN --serveArg=apply

echo "=== r11 capture complete ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
