#!/usr/bin/env bash
# Round-22 capture: ISSUE 18 (offline batch-predict + streaming
# /generate) chip evidence. The correctness contracts are CPU-verified
# (tests/test_batch_predict.py, tests/test_streaming.py, the tier1
# throughput-smoke job): executor->engine score parity, kill+resume
# byte-identity, dp coverage, streamed == buffered bit-identity,
# disconnect cleanup. What only hardware can tell us: (a) whether
# batch-predict's offline throughput actually reaches the training
# harness's forward-only ceiling for the same model/batch (the ISSUE's
# headline claim — the gap IS the serving overhead); (b) where the
# --dataWorkers x --stage knee sits when the forward is fast, i.e. the
# stall_frac story off-chip CPUs can't reproduce; (c) the streamed vs
# buffered TTFT/TPOT A/B under concurrent load — streaming must buy
# first-token latency without taxing steady-state decode. Appends to
# $OUT, mirrored into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r22.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r22.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 0. the r22 test files + both smokes on this env (CPU backends)
step "pytest_r22" 900 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_batch_predict.py tests/test_streaming.py -q
step "stream_smoke" 900 env JAX_PLATFORMS=cpu \
  python scripts/serving_bench.py --streamSmoke --model transformer_lm

# 1. a synthetic record set big enough that scoring is steady-state
#    (~50k 224x224 records; point RECORDS at real shards to override)
RECORDS="${RECORDS:-/tmp/r22_records}"
if [ ! -d "$RECORDS" ]; then
  step "gen_records" 1800 python - <<'EOF'
import numpy as np, os
from PIL import Image
rng = np.random.RandomState(0)
root = "/tmp/r22_imgs"
for cls in range(10):
    d = f"{root}/c{cls}"; os.makedirs(d, exist_ok=True)
    for i in range(64):
        Image.fromarray(rng.randint(0, 255, (256, 256, 3))
                        .astype(np.uint8)).save(f"{d}/{i}.jpg")
print("640 source images (record-gen oversamples via shard repeat)")
EOF
  step "pack_records" 1800 python -m bigdl_tpu.cli.main record-gen \
    -f /tmp/r22_imgs -o "$RECORDS" -b 512 -p 8
fi

# 2. THE r22 headline — batch-predict images/s vs the training
#    harness's forward-only ceiling, resnet50 b128, x3 reps each.
#    Acceptance (ISSUE 18): per-chip batch-predict throughput within
#    noise of `perf --forwardOnly` b128; the residual gap is the
#    engine's pad/dispatch overhead and goes in PERF.md §25.
for REP in 1 2 3; do
  step "fwd_ceiling_rep${REP}" 1800 python -m bigdl_tpu.cli.perf \
    -m resnet50 -b 128 -i 40 --forwardOnly --dataType constant
  step "bp_rep${REP}" 3600 python -m bigdl_tpu.cli.main batch-predict \
    --modelName resnet50 --randomInit -f "record:$RECORDS" \
    --out /tmp/r22_bp_rep${REP} -b 128 --dataWorkers 8 --stage device \
    --obs
done

# 3. the worker x stage knee: where does the input pipeline stop
#    hiding behind a fast chip forward? stall_frac <= 0.02 at
#    --dataWorkers 8 --stage device is the ISSUE acceptance line; the
#    sweep shows the knee (1 worker must starve, the staged legs must
#    beat host staging).
for W in 1 2 4 8 16; do
  for STAGE in host device; do
    step "knee_w${W}_${STAGE}" 1800 python -m bigdl_tpu.cli.main \
      batch-predict --modelName resnet50 --randomInit \
      -f "record:$RECORDS" --out /tmp/r22_knee_w${W}_${STAGE} \
      -b 128 --dataWorkers "$W" --stage "$STAGE" --obs || true
  done
done

# 4. dp scale-out: all chips, one feed — per-chip images/s should hold
#    flat vs the single-chip rep (the executor feed is the only shared
#    resource; its stall_frac column says whether it kept up).
step "bp_dp" 3600 python -m bigdl_tpu.cli.main batch-predict \
  --modelName resnet50 --randomInit -f "record:$RECORDS" \
  --out /tmp/r22_bp_dp -b 128 --dataWorkers 16 --stage device \
  --strategy dp --obs || true

# 5. streamed vs buffered A/B under load — same serving geometry as
#    tpu_capture_r18..r21 so TTFT/TPOT read against those logs.
#    Acceptance: streamed first-byte TTFT well under the buffered
#    full-response latency at c8, TPOT within noise (streaming must
#    not tax steady-state decode).
LM="--serveArg=--vocabSize --serveArg=32000 \
    --serveArg=--dModel --serveArg=1024 \
    --serveArg=--numLayers --serveArg=8 \
    --serveArg=--numHeads --serveArg=16 \
    --serveArg=--seq --serveArg=1024 \
    --serveArg=--slots --serveArg=8"
GEN="--model transformer_lm --endpoint generate \
     --requests 32 --promptLen 128 --maxNewTokens 128"
for REP in 1 2 3; do
  # shellcheck disable=SC2086
  step "buffered_c8_rep${REP}" 1800 python scripts/serving_bench.py \
    $GEN $LM --concurrency 8 \
    --serveArg=--reqTrace --serveArg=on || true
  # shellcheck disable=SC2086
  step "stream_c8_rep${REP}" 1800 python scripts/serving_bench.py \
    $GEN $LM --concurrency 8 --stream \
    --serveArg=--reqTrace --serveArg=on || true
done
# composed leg: streaming + speculative + paged KV (the production
# stack) — accepted-token chunks only, TTFT from the first verify
# shellcheck disable=SC2086
step "stream_spec_c8" 1800 python scripts/serving_bench.py \
  $GEN $LM --concurrency 8 --stream \
  --serveArg=--speculate --serveArg=4 \
  --serveArg=--kvPageTokens --serveArg=128 \
  --serveArg=--reqTrace --serveArg=on || true

# 6. summarize every JSON line in this log for PERF.md §25
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
