#!/usr/bin/env bash
# Poll the axon TPU tunnel; the moment a backend probe succeeds, run the
# capture sweep given as $1 (default scripts/tpu_capture2.sh). The probe is
# a subprocess with a hard timeout because backend init HANGS (not errors)
# while the tunnel is down.
set -u
cd "$(dirname "$0")/.."
SWEEP="${1:-scripts/tpu_capture2.sh}"
while true; do
  if timeout 120 python -c "
import jax
assert jax.default_backend() == 'tpu', jax.default_backend()
print('tpu up:', jax.devices()[0].device_kind)
" 2>/dev/null; then
    exec bash "$SWEEP"
  fi
  sleep 180
done
