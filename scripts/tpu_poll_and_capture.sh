#!/usr/bin/env bash
# Poll the axon TPU tunnel; the moment a backend probe succeeds, run the
# capture sweep given as $1 (default scripts/tpu_capture2.sh). The probe is
# a subprocess with a hard timeout because backend init HANGS (not errors)
# while the tunnel is down.
set -u
cd "$(dirname "$0")/.."
SWEEP="${1:-scripts/tpu_capture2.sh}"
# Window 1 of round 5 lasted ~2.5 min: with the old 180 s sleep + 120 s
# probe the worst-case detection latency (~5 min) could miss a whole
# window. A warm tunnel answers backend init in ~10-15 s, but a COLD
# libtpu init can take ~60 s — keep a 90 s probe timeout (so a cold
# window is never misread as down) with a 45 s sleep: worst-case
# detection ~135 s. A hung probe is killed by timeout — polling is free.
# The probe also rejects the DEGRADED half-alive tunnel mode (07:00Z,
# window 2): backend init succeeds but a fresh-input matmul round trip
# takes seconds and completions resolve without executing — firing a
# sweep there burns the steps on garbage timing. Second iteration timed
# so compile/cold-start doesn't count.
while true; do
  if timeout 120 python -c "
import time
import jax, jax.numpy as jnp, numpy as np
assert jax.default_backend() == 'tpu', jax.default_backend()
f = jax.jit(lambda a: a @ a)
for i in range(2):
    a = jnp.asarray(np.full((2048, 2048), 1.0 + i, np.float32))
    jax.block_until_ready(a)
    t0 = time.perf_counter()
    jax.block_until_ready(f(a))
    dt = time.perf_counter() - t0
assert dt < 1.0, f'degraded: {dt:.2f}s round trip'
print('tpu up (healthy):', jax.devices()[0].device_kind)
" 2>/dev/null; then
    exec bash "$SWEEP"
  fi
  sleep 45
done
