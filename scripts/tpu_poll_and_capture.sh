#!/usr/bin/env bash
# Poll the axon TPU tunnel; the moment a backend probe succeeds, run the
# capture sweep given as $1 (default scripts/tpu_capture2.sh). The probe is
# a subprocess with a hard timeout because backend init HANGS (not errors)
# while the tunnel is down.
set -u
cd "$(dirname "$0")/.."
SWEEP="${1:-scripts/tpu_capture2.sh}"
# Window 1 of round 5 lasted ~2.5 min: with the old 180 s sleep + 120 s
# probe the worst-case detection latency (~5 min) could miss a whole
# window. A warm tunnel answers backend init in ~10-15 s, but a COLD
# libtpu init can take ~60 s — keep a 90 s probe timeout (so a cold
# window is never misread as down) with a 45 s sleep: worst-case
# detection ~135 s. A hung probe is killed by timeout — polling is free.
# Health semantics live in scripts/tpu_health_probe.py (the ONE copy,
# shared with the sweeps' per-step gate): resident-input chained matmul
# + host value fetch, rejecting both tunnel measurement traps
# (early-acking block_until_ready, upload-bandwidth-bound fresh inputs).
while true; do
  if timeout 120 python scripts/tpu_health_probe.py 2>/dev/null; then
    exec bash "$SWEEP"
  fi
  sleep 45
done
