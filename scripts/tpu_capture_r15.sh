#!/usr/bin/env bash
# Round-15 capture: ISSUE 11 (elastic data-parallel training) chip
# evidence. The reshape mechanism is CPU-verified end to end
# (tests/test_elastic.py, the elastic-smoke CI job, chaos_run.py
# --kill-device); what only hardware can tell us is (a) the real
# restore_ms of a resharded resume — how long the 8->7 / 8->4 re-form
# actually stalls the job on a slice where device_put crosses ICI,
# (b) whether the reshaped run keeps useful throughput (the hold
# policy's padded batch vs the scale policy's smaller one, against the
# uninterrupted baseline), and (c) the grad-comm bucket bound the
# autotuner re-resolves for the surviving count (per-n_devices cache
# key — the 7-device decision is NOT the 8-device one). Each A/B leg
# runs x3 reps so the §18.4 slots get medians. On a single-chip tunnel
# every --strategy leg exits cleanly ("needs more than one device")
# and the round costs minutes, not hours. Appends to $OUT, mirrored
# into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r15.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r15.log}"
TRACE_ROOT="${TRACE_ROOT:-/tmp/elastic_r15}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 0. the elastic + resilience tests on the bench env first
step "pytest_elastic" 600 python -m pytest tests/test_elastic.py \
  tests/test_resilience.py -q

# 1. THE r15 table: uninterrupted baseline vs elastic kill/reshape A/B
#    on dp, x3 reps each so PERF.md §18.4 gets medians. Every elastic
#    line stamps the reshape dict (from/to devices, restore_ms, bucket
#    bound before/after) next to throughput.
for REP in 1 2 3; do
  step "baseline_dp_r${REP}" 1800 python -m bigdl_tpu.cli.main perf \
    -m resnet50 -b 128 -i 40 --strategy dp || true
  for POL in hold scale; do
    step "elastic_${POL}_8to7_r${REP}" 1800 python -m bigdl_tpu.cli.main \
      perf -m resnet50 -b 128 -i 40 --strategy dp --elastic "$POL" \
      --minDevices 4 --faultPlan "kill_device@step:20:1" || true
  done
  # the halved-slice leg: zero1 shards stay divisible at 4, so this
  # exercises the reshard-to-shards path (7 degrades to replication)
  step "elastic_hold_8to4_r${REP}" 1800 python -m bigdl_tpu.cli.main \
    perf -m resnet50 -b 128 -i 40 --strategy dp --elastic hold \
    --minDevices 4 --faultPlan "kill_device@step:20:4" || true
done

# 2. the LM leg (big-leaf gradient tree: the resharded restore moves a
#    few large arrays instead of many small ones — opposite restore_ms
#    economics)
for REP in 1 2 3; do
  step "elastic_lm_r${REP}" 1800 python -m bigdl_tpu.cli.main perf \
    -m transformer_lm_1k_hd128 -b 8 -i 40 --strategy dp \
    --elastic hold --minDevices 4 \
    --faultPlan "kill_device@step:20:1" || true
done

# 3. per-n_devices bucket re-resolution on chip: measure at 8, then a
#    reshaped run at 7 must consult the 7-device cache key (a miss ->
#    its own measured pick, never the 8-device bound; the reshape dict's
#    bucket_bytes_before/after makes the re-resolution visible)
step "buckets_measure_8dev" 2400 python -m bigdl_tpu.cli.main perf \
  -m resnet50 -b 128 -i 30 --strategy dp --gradCompress bf16 \
  --gradBuckets auto --autotune measure || true
step "elastic_buckets_reresolve" 2400 python -m bigdl_tpu.cli.main perf \
  -m resnet50 -b 128 -i 40 --strategy dp --gradCompress bf16 \
  --gradBuckets auto --autotune measure --elastic hold --minDevices 4 \
  --faultPlan "kill_device@step:20:1" || true

# 4. the still-unrun r14 multichip row folded in (§17.4's first two
#    slots): compressed-vs-plain gradient all-reduce with attribution
#    windows — one session captures both rounds' tables
for REP in 1 2 3; do
  for GC in off bf16; do
    step "r14_ab_dp_${GC}_r${REP}" 1800 python -m bigdl_tpu.cli.main \
      perf -m resnet50 -b 128 -i 30 --strategy dp --gradCompress "$GC" \
      --obs --traceDir "$TRACE_ROOT/r14_dp_${GC}_r${REP}" \
      --traceSteps 4@15 || true
  done
done
step "r14_explain_dp_off" 600 python -m bigdl_tpu.cli.main explain \
  "$TRACE_ROOT/r14_dp_off_r1/capture_15" --steps 4 || true
step "r14_explain_dp_bf16" 600 python -m bigdl_tpu.cli.main explain \
  "$TRACE_ROOT/r14_dp_bf16_r1/capture_15" --steps 4 || true

# 5. summarize every JSON line in this log for PERF.md §18.4 / §17.4
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
