#!/usr/bin/env bash
# Round-5 window-2 follow-on sweep. Window 2 (03:47Z+) measured every
# individual lever on chip: conv-layout decision +1.1%, s2d +1.5%,
# innerSteps=10 +1.6%, fused-BN -46% (negative, twice). This sweep
# captures what r05b cannot: the COMBINED best config (r05b's only
# combined step uses the now-known-negative fbn), then finishes any
# long-tail step r05b hasn't already banked. Steps are probe-gated like
# r05b and additionally skip-if-banked: a step whose "=== end NAME rc=0"
# already appears in the repo log is not re-run, so a tunnel drop +
# re-fire resumes instead of restarting.
set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r05.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r05.log}"
LAYOUT="NHWC,NHWC,NCHW"   # decision from conv_probe_apply, window 2
# seed OUT from the banked repo log when /tmp was cleaned (reboot), so
# the per-step cp back to REPO_LOG never clobbers banked results and
# skip-if-banked keeps working
if [ -f "$REPO_LOG" ] && { [ ! -f "$OUT" ] || [ "$(wc -c <"$REPO_LOG")" -gt "$(wc -c <"$OUT")" ]; }; then
  cp -f "$REPO_LOG" "$OUT"
fi
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

probe() {
  # shared health gate — see scripts/tpu_health_probe.py
  timeout 120 python scripts/tpu_health_probe.py >/dev/null 2>&1
}

step() {
  local name="$1" tmo="$2"; shift 2
  if grep -q "=== end $name rc=0" "$REPO_LOG" "$OUT" 2>/dev/null; then
    echo "=== skip $name: already banked" ; return 0
  fi
  if ! probe; then
    echo "=== ABORT before $name: tunnel dead ($(date -u +%H:%M:%SZ)); re-arming poller" | tee -a "$OUT"
    cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
    exec bash scripts/tpu_poll_and_capture.sh scripts/tpu_capture_r05c.sh
  fi
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 1. combined-lever A/Bs (all individually positive in window 2).
# NOTE: perf.run now AUTO-INSTALLS the measured decision on v5lite when
# --convLayout is omitted — layout-free control arms must pin
# '--convLayout default' explicitly or they silently run with $LAYOUT.
step "perf_rn50_s2d_layout" 900 python -m bigdl_tpu.cli.perf -m resnet50_s2d -b 128 -i 20 --dataType random --convLayout "$LAYOUT"
step "perf_rn50_layout_inner10" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 4 --innerSteps 10 --dataType random --convLayout "$LAYOUT"
step "perf_rn50_s2d_inner10" 900 python -m bigdl_tpu.cli.perf -m resnet50_s2d -b 128 -i 4 --innerSteps 10 --dataType random --convLayout default
step "perf_rn50_best_combo" 900 python -m bigdl_tpu.cli.perf -m resnet50_s2d -b 128 -i 4 --innerSteps 10 --dataType random --convLayout "$LAYOUT"
step "perf_rn50_best_combo_b256" 900 python -m bigdl_tpu.cli.perf -m resnet50_s2d -b 256 -i 4 --innerSteps 10 --dataType random --convLayout "$LAYOUT"

# 1b. real-training dispatch amortization A/B: same lenet config the
# banked lenet_convergence step ran at K=1 (119 s to 99.90% on chip),
# now with Optimizer steps_per_dispatch=8 — the tiny model is dispatch-
# dominated through the tunnel, so the wall-clock delta isolates the
# lever through the ACTUAL Optimizer loop users run (not the perf
# harness's --innerSteps analog). Data prep is host-side and keyed on
# the files (a banked rc=0 must not skip regeneration after a /tmp wipe)
if [ ! -f /tmp/synth_mnist_full/train-images-idx3-ubyte ]; then
  echo "=== make_synth_mnist host-side ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout 1200 python scripts/make_synth_mnist.py /tmp/synth_mnist_full 20000 4000 2>&1 | tail -5 | tee -a "$OUT"
fi
step "lenet_convergence_spd8" 1800 ./scripts/run_example.sh lenet /tmp/synth_mnist_full -b 128 --maxEpoch 20 --learningRate 0.1 --stepsPerDispatch 8

# 1c. flash block-size sweep: the kernel's absolute TF/s bounds the LM
# path. v2: the first attempts timed with block_until_ready, which acks
# early through axon (perf.py:344 documents the trap) — rows were
# impossible and discarded; the sweep now syncs by host value fetch
step "flash_block_sweep_4k_v2" 1500 bash -c "python scripts/flash_block_sweep.py 4096 4 8 128 | tee /tmp/flash_blocks_r05.jsonl"

# 2. long tail, exactly r05b's set, skipped when already banked
step "perf_resnet50_bnss_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50_bnss -b 128 -i 20 --dataType random
step "flash_bench" 1800 python scripts/flash_bench.py 4 8 64
for B in 64 256 512; do
  step "perf_resnet50_b$B" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b "$B" -i 20 --dataType random
done
step "perf_transformer_lm_rope_b32" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_rope -b 32 -i 10 --dataType random
step "bench_pipe" 2400 env BENCH_TPU_TIMEOUT=2000 BENCH_COMPANIONS=0 python bench.py resnet50_pipe 128 20
# data prep is HOST-side (no device, no probe) and must key on the data
# files, not the banked log — after a /tmp wipe the banked "rc=0" would
# otherwise skip regeneration and starve the training steps
if [ ! -f /tmp/synth_mnist_full/train-images-idx3-ubyte ]; then
  echo "=== make_synth_mnist host-side ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout 1200 python scripts/make_synth_mnist.py /tmp/synth_mnist_full 20000 4000 2>&1 | tail -5 | tee -a "$OUT"
fi
step "lenet_convergence" 1800 ./scripts/run_example.sh lenet /tmp/synth_mnist_full -b 128 --maxEpoch 20 --learningRate 0.1
step "time_to_acc_cifar_scale" 3600 python -m bigdl_tpu.cli.perf -m resnet20_cifar --timeToAcc 0.91 -b 128 --imageSize 32 --maxEpoch 156 --trainPerClass 5000 --valPerClass 1000 --ttaHard --ttaLift 7 --valEvery 65
step "time_to_acc_resnet50" 2400 python -m bigdl_tpu.cli.perf -m resnet50 --timeToAcc 0.85 -b 64 --imageSize 224 --maxEpoch 15
# _ensure_data is idempotent (returns fast when the shards exist) — run
# it unconditionally host-side for the same /tmp-wipe reason
echo "=== soak_data_prep host-side ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
timeout 1500 python -c "import sys; sys.path.insert(0, '.'); from scripts.soak import _ensure_data; print(_ensure_data('/tmp/soak_chip'))" 2>&1 | tail -3 | tee -a "$OUT"
step "soak_chip" 3300 python scripts/soak.py orchestrate --dir /tmp/soak_chip --batch 128 --ckpt-every 50 --phase1 1500 --phase2 480

# 3. LM rows under the shipped 512-wide flash blocks (the step names
# above banked the 128-block values; these are the durable-log copies
# of the post-block-change measurements in PERF.md §8.2)
step "perf_lm_b32_512blk" 900 python -m bigdl_tpu.cli.perf -m transformer_lm -b 32 -i 5 --dataType random
step "perf_lm_1k_512blk" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_1k -b 16 -i 5 --dataType random
step "perf_lm_1k_hd128_512blk" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_1k_hd128 -b 16 -i 5 --dataType random
step "perf_lm_16k_512blk" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_16k -b 1 -i 5 --dataType random
step "perf_lm_32k_512blk" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_32k -b 1 -i 3 --dataType random
step "bench_main_512blk" 2400 python bench.py
# 4. ViT family (landed late round 5) + corrected-numerator headline
step "perf_vit_b16_b64" 900 python -m bigdl_tpu.cli.perf -m vit_b16 -b 64 -i 10 --dataType random
step "perf_resnet50_corrected_basis" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --dataType random

echo "r05c sweep complete -> $OUT" | tee -a "$OUT"
