#!/usr/bin/env bash
# Round-18 capture: ISSUE 14 (decode raw speed) chip evidence. The
# exactness contracts are CPU-verified (tests/test_spec_decode.py,
# tests/test_kv_pages.py, the tier1 spec-smoke leg) — what only hardware
# can tell us is the actual tokens/s: (a) the spec A/B runs the SAME
# greedy /generate workload with --speculate 0 vs 4 three times each
# (client tokens/s + the accepted-tokens/step column in every JSON
# line); (b) the page sweep grids --kvPageTokens over the tuned ladder
# plus the dense layout at matched workload — gather/scatter overhead vs
# residency is the trade the kv_pages autotune namespace prices; (c) the
# prefix leg fires a shared-prefix prompt set cold then warm and scrapes
# hit counters + latency quantiles. Appends to $OUT, mirrored into the
# repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r18.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r18.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# serving-bench geometry for every leg: a mid-size LM (big enough that
# the chip, not Python, is the bottleneck) at a matched workload
LM="--serveArg=--vocabSize --serveArg=32000 \
    --serveArg=--dModel --serveArg=1024 \
    --serveArg=--numLayers --serveArg=8 \
    --serveArg=--numHeads --serveArg=16 \
    --serveArg=--seq --serveArg=1024 \
    --serveArg=--slots --serveArg=8"
GEN="--model transformer_lm --endpoint generate \
     --requests 32 --concurrency 4 --promptLen 128 --maxNewTokens 128"

# 0. the decode test files + exactness smoke on the bench env first
step "pytest_decode" 900 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_spec_decode.py tests/test_kv_pages.py -q
step "spec_exactness" 600 python scripts/serving_bench.py \
  --specSmoke --model transformer_lm

# 1. THE r18 leg — speculative A/B x3: same greedy workload, draft =
#    target (self-draft ships as the default). tokens_per_second and
#    spec.accepted_tokens_per_step in each JSON line are the story.
for REP in 1 2 3; do
  for K in 0 4; do
    # shellcheck disable=SC2086
    step "spec_ab_k${K}_rep${REP}" 1800 python scripts/serving_bench.py \
      $GEN $LM --serveArg=--speculate --serveArg="$K" || true
  done
done

# 2. separate small draft (4x shallower): acceptance drops below 1 but
#    each verify amortizes K draft steps that cost ~1/8 the target's
for REP in 1 2 3; do
  # shellcheck disable=SC2086
  step "spec_draft_rep${REP}" 1800 python scripts/serving_bench.py \
    $GEN $LM --serveArg=--speculate --serveArg=4 \
    --serveArg=--draftDims --serveArg=256,2,4 || true
done

# 3. page-size sweep at matched workload: dense baseline then the tuned
#    ladder — the gather/scatter cost each page size pays on real HBM
#    (feeds the kv_pages autotune default and PERF.md §21)
# shellcheck disable=SC2086
step "pages_dense" 1800 python scripts/serving_bench.py $GEN $LM || true
for PT in 32 64 128 256; do
  # shellcheck disable=SC2086
  step "pages_pt${PT}" 1800 python scripts/serving_bench.py $GEN $LM \
    --serveArg=--kvPageTokens --serveArg="$PT" || true
done
# measured (not dry) kv_pages autotune decision on the chip
# shellcheck disable=SC2086
step "pages_auto_measured" 1800 python scripts/serving_bench.py $GEN $LM \
  --serveArg=--kvPageTokens --serveArg=auto \
  --serveArg=--autotune --serveArg=measure || true

# 4. shared-prefix warm/cold: same 512-token system prefix, distinct
#    tails — cold pass populates, warm pass must show hits and a
#    latency drop proportional to prefix/(prefix+tail) prefill work
step "prefix_warm_cold" 1800 python - <<'EOF'
import json, sys
sys.path.insert(0, "scripts")
import serving_bench as sb

class A:  # minimal spawn_server surface
    model = "transformer_lm"; ckpt = None; platform = None; smoke = False
args = A()
proc, url, logs = sb.spawn_server(args, [
    "--vocabSize", "32000", "--dModel", "1024", "--numLayers", "8",
    "--numHeads", "16", "--seq", "1024", "--slots", "8",
    "--kvPageTokens", "128", "--prefixCache"])
try:
    import numpy as np, time
    rng = np.random.RandomState(0)
    prefix = rng.randint(1, 31000, 512).tolist()
    def fire(tag):
        lats = []
        for i in range(8):
            tail = rng.randint(1, 31000, 16).tolist()
            t0 = time.perf_counter()
            sb._post(url + "/generate",
                     {"tokens": prefix + tail, "max_new_tokens": 32})
            lats.append((time.perf_counter() - t0) * 1000)
        _, page = sb._get(url + "/metrics")
        print(json.dumps({
            "leg": f"prefix_{tag}",
            "p50_ms": sorted(lats)[len(lats) // 2],
            "hits": sb.scrape_value(page, "prefix_cache_hits_total"),
            "misses": sb.scrape_value(page,
                                      "prefix_cache_misses_total")}))
    fire("cold_then_warm")   # first request populates, rest hit
    fire("warm")
finally:
    sb._shutdown_clean(proc, logs)
EOF

# 5. summarize every JSON line in this log for PERF.md §21
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
