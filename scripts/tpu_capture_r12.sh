#!/usr/bin/env bash
# Round-12 capture: ISSUE 7 (observability) chip evidence. The obs layer
# is CPU-verified end-to-end (tests/test_obs.py, the obs-smoke CI job);
# what only a chip can tell us is (a) what --obs actually COSTS on the
# real hot path — the per-step block_until_ready that makes device time
# exact trades dispatch pipelining for truth, and the A/B below puts a
# number on that trade (PERF.md §15 overhead slot), (b) what the phase
# split says about the tuned configs (device_s should dominate; any
# data_wait on synthetic data is dispatch-loop overhead), and (c) that a
# mid-run --traceSteps window on hardware produces an xplane the PR 3
# reader parses (the capture leg stamps ok:true into its JSON line).
# Appends to $OUT, mirrored into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r12.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r12.log}"
TRACE_ROOT="${TRACE_ROOT:-/tmp/obs_r12}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 0. compiled-path + obs tests first (a broken kernel path would poison
#    every number below; the span/capture contracts must hold on-chip)
step "pytest_tpu_marked" 1200 env BIGDL_TPU_TESTS=1 python -m pytest tests/ -m tpu -q
step "pytest_obs" 600 python -m pytest tests/test_obs.py -q

# 1. obs-on vs obs-off overhead A/B (the §15 overhead slot): identical
#    tuned resnet50 config, 3 interleaved reps each. The obs leg stamps
#    the phase columns + stall_frac into its JSON line; the img/s delta
#    between legs IS the cost of exact per-step phase attribution
#    (expected: the block_until_ready sync serializes dispatch — same
#    class of cost as log_every=1).
for REP in 1 2 3; do
  step "perf_obsoff_rep${REP}" 1800 python -m bigdl_tpu.cli.main perf \
    -m resnet50 -b 128 -i 40 --fusedBN apply --autotune cached
  step "perf_obson_rep${REP}" 1800 python -m bigdl_tpu.cli.main perf \
    -m resnet50 -b 128 -i 40 --fusedBN apply --autotune cached \
    --obs --traceDir "$TRACE_ROOT/resnet50_rep${REP}"
done

# 2. same A/B at the transformer_lm flagship (different dispatch
#    cadence; tokens/s + phase split land in §15)
step "perf_lm_obsoff" 1800 python -m bigdl_tpu.cli.main perf \
  -m transformer_lm_1k_hd128 -b 8 -i 40 --autotune cached
step "perf_lm_obson" 1800 python -m bigdl_tpu.cli.main perf \
  -m transformer_lm_1k_hd128 -b 8 -i 40 --autotune cached \
  --obs --traceDir "$TRACE_ROOT/lm"

# 3. mid-run capture window ON CHIP: --traceSteps 4@20 opens a bounded
#    jax.profiler window at step 20 of a 60-step run and verifies the
#    xplane parses (ok:true in the JSON obs.captures annotation); the
#    resulting profile feeds scripts/backward_roofline.py exactly like
#    a --profile run would, but without profiling the warmup.
step "perf_tracesteps_window" 2400 python -m bigdl_tpu.cli.main perf \
  -m resnet50 -b 128 -i 60 --fusedBN apply --autotune cached \
  --obs --traceDir "$TRACE_ROOT/window" --traceSteps 4@20

# 4. input-pipeline phase split: the record-fed bench is the config the
#    feed-stall columns were built for (resnet50_pipe measured 0.99%
#    MFU, PERF.md §4 — data_wait_s/stall_frac now say exactly how much
#    of every wall-second the chip spent starved). Shards are built on
#    the fly if the probe dir is absent.
if [ -d "${SHARDS:-/tmp/r12_shards}" ]; then
  step "perf_pipe_obs" 2400 python -m bigdl_tpu.cli.main perf \
    -m resnet50 -b 128 -i 30 --data "record:${SHARDS:-/tmp/r12_shards}" \
    --obs --traceDir "$TRACE_ROOT/pipe"
else
  echo "=== perf_pipe_obs skipped (no \$SHARDS dir)" | tee -a "$OUT"
fi

# 5. training-loop phase split + live scrape: a short supervised TTA
#    run with the metrics listener up; the scrape is taken mid-run by
#    the smoke harness (same assertions as CI, now against chip phase
#    numbers), and the epoch log lines carry data_wait/dispatch/stall.
step "obs_smoke_chip" 1800 python scripts/obs_smoke.py -b 64 -i 60

echo "=== r12 capture complete ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
