#!/usr/bin/env bash
# Round-16 capture: ISSUE 12 (HBM attribution) chip evidence. The plan/
# forecast/autopsy machinery is CPU-verified end to end
# (tests/test_memory.py, the mem-smoke CI job) — but on CPU the plan is
# modeled (source: plan) and HBM is a nominal 8 GB. What only hardware
# can tell us is (a) how close the static plan lands to the LIVE
# device.memory_stats() peak (source: live) across batch sizes, (b)
# whether the two-point linear forecaster's predicted_max_batch is real
# — the forecast leg runs the predicted batch and the batch above it,
# expecting the latter to OOM, (c) the KV-cache accounting of a serving
# LM against live stats, and (d) a deliberate OOM's MemoryReport
# post-mortem on a real RESOURCE_EXHAUSTED (top live buffers, headroom
# history — artifacts CPU cannot produce). Appends to $OUT, mirrored
# into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r16.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r16.log}"
TRACE_ROOT="${TRACE_ROOT:-/tmp/mem_r16}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 0. the memory + obs tests on the bench env first
step "pytest_memory" 600 python -m pytest tests/test_memory.py \
  tests/test_obs.py -q

# 1. plan-vs-live calibration: explain --mem forecasts, then --obs runs
#    at the same batches read the real device.memory_stats() peak. The
#    perf JSON's mem.source must be "live" on chip and
#    hbm_peak_bytes/plan total is the §19 calibration ratio.
step "mem_plan_resnet50_b128" 1200 python -m bigdl_tpu.cli.main explain \
  --mem resnet50 -b 128 --json || true
for B in 32 64 128; do
  step "mem_live_resnet50_b${B}" 1800 python -m bigdl_tpu.cli.main perf \
    -m resnet50 -b "$B" -i 30 --obs \
    --traceDir "$TRACE_ROOT/resnet50_b${B}" || true
done

# 2. THE r16 leg: does the forecaster's predicted max batch hold? Run
#    explain --mem, extract predicted_max_batch P, then run perf at the
#    largest power-of-two <= P (expected: fits, mem columns near 100%
#    utilization) and at 2x that (expected: RESOURCE_EXHAUSTED with a
#    MemoryReport in the trace dir — the deliberate-OOM autopsy leg).
step "forecast_probe" 3600 bash -c '
  set -u
  P=$(python -m bigdl_tpu.cli.main explain --mem resnet50 -b 64 --json \
      | tail -1 | python -c "
import json, sys
print(json.loads(sys.stdin.read())[\"forecast\"][\"predicted_max_batch\"])")
  echo "predicted_max_batch=$P"
  FIT=1; while [ $((FIT * 2)) -le "$P" ]; do FIT=$((FIT * 2)); done
  echo "fit_batch=$FIT oom_batch=$((FIT * 2))"
  python -m bigdl_tpu.cli.main perf -m resnet50 -b "$FIT" -i 10 --obs \
    --traceDir '"$TRACE_ROOT"'/fit
  python -m bigdl_tpu.cli.main perf -m resnet50 -b $((FIT * 2)) -i 10 \
    --obs --traceDir '"$TRACE_ROOT"'/oom
  echo "oom leg rc=$? (nonzero expected)"
  python -c "
import json
rep = json.load(open(\"'"$TRACE_ROOT"'/oom/memory_report.json\"))
print(\"MemoryReport ok:\", rep[\"context\"],
      [b[\"nbytes\"] for b in rep[\"top_live_buffers\"][:3]])"' || true

# 3. KV-cache accounting on a serving LM: the decode engine's
#    kv_cache_bytes gauges + per-bucket compile-time memory in
#    provenance, against the live /metrics scrape
step "mem_kv_serving" 1800 python scripts/serving_bench.py \
  --smoke --model transformer_lm || true
step "mem_lm_train" 1800 python -m bigdl_tpu.cli.main perf \
  -m transformer_lm_1k_hd128 -b 8 -i 30 --obs \
  --traceDir "$TRACE_ROOT/lm" || true

# 4. summarize every JSON line in this log for PERF.md §19
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
