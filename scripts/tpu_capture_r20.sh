#!/usr/bin/env bash
# Round-20 capture: ISSUE 16 (multi-chip serving) chip evidence.
# The correctness contracts are CPU-verified on virtual devices
# (tests/test_serving_tp.py, the tier1 serving-tp-smoke job) — what
# only hardware can tell us is the WIN: (a) tp A/B — single-chip vs
# --strategy tp:K per-token latency on one stream (tp spends chips on
# latency: the row-split psum must cost less than the per-chip matmul
# time it saves); (b) the dp sweep — aggregate QPS over dp:1,2,4 with
# the ≥0.8x-linear acceptance floor ASSERTED (replicas share nothing
# on real chips, so the floor is enforceable here and only here);
# (c) the composed dp+tp leg. Appends to $OUT, mirrored into the repo
# per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r20.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r20.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# identical serving geometry + workload to tpu_capture_r18/r19.sh so
# the r20 topology numbers read directly against those slots
LM="--serveArg=--vocabSize --serveArg=32000 \
    --serveArg=--dModel --serveArg=1024 \
    --serveArg=--numLayers --serveArg=8 \
    --serveArg=--numHeads --serveArg=16 \
    --serveArg=--seq --serveArg=1024 \
    --serveArg=--slots --serveArg=8"
GEN="--model transformer_lm --endpoint generate \
     --requests 32 --promptLen 128 --maxNewTokens 128"
TPK="${TPK:-4}"   # tp width for the A/B; set to the slice's chip count

# 0. the multi-chip test file + the full assertion pass on this env
step "pytest_serving_tp" 900 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_serving_tp.py -q
step "tp_smoke" 900 python scripts/serving_bench.py \
  --tpSmoke --model transformer_lm

# 1. tp A/B x3 — one stream (c1), per-token latency. The tp:K legs'
#    JSON lines carry the strategy provenance; acceptance for PERF.md
#    §23 is tokens_per_second up (or p50 down) vs single-chip on the
#    SAME workload, with greedy output already bit-identity-checked by
#    the smoke above.
for REP in 1 2 3; do
  # shellcheck disable=SC2086
  step "tp_single_rep${REP}" 1800 python scripts/serving_bench.py \
    $GEN $LM --concurrency 1 || true
  # shellcheck disable=SC2086
  step "tp_tp${TPK}_rep${REP}" 1800 python scripts/serving_bench.py \
    $GEN $LM --concurrency 1 --strategy "tp:${TPK}" || true
done

# 2. THE r20 leg — dp aggregate-QPS sweep with the acceptance floor
#    asserted: dp:N must land ≥0.8x linear in N (concurrency scales
#    4xN inside the sweep so every replica stays fed). Per-replica
#    generated-token splits ride each record — the routing spread is
#    part of the evidence.
# shellcheck disable=SC2086
step "dp_sweep" 3600 python scripts/serving_bench.py $GEN $LM \
  --dpSweep 1,2,4 --assertScaling 0.8 || true

# 3. composed dp:2+tp:2 (4 chips): replicated tensor-parallel engines
#    behind one port — the full --smoke pass through the serving tp
#    lint gate, then a measured leg for the §23 composed slot.
step "dp_tp_smoke" 1800 python scripts/serving_bench.py \
  --smoke --model transformer_lm --strategy dp:2+tp:2 \
  --serveArg=--lint --serveArg=on || true
# shellcheck disable=SC2086
step "dp_tp_bench" 1800 python scripts/serving_bench.py $GEN $LM \
  --concurrency 8 --strategy dp:2+tp:2 || true

# 4. summarize every JSON line in this log for PERF.md §23
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
