#!/usr/bin/env bash
# Round-21 capture: ISSUE 17 (quantized serving) chip evidence.
# The correctness contracts are CPU-verified (tests/test_quant.py, the
# tier1 quant-smoke job): greedy-token identity off vs int8+kv8, kv8
# bitwise pool parity, the quant_report guardrail, and the >= 2x
# slots-at-equal-HBM count through the real allocator. What only
# hardware can tell us is the WIN: (a) weight A/B — off vs int8 vs fp8
# per-token latency + HBM on the SAME one-stream workload (dequant rides
# the matmul epilogue; fp8 additionally exercises the native fp8 path on
# chips that have it); (b) the kv8 slot sweep — --slots pushed past the
# f32 HBM ceiling under --quantize int8+kv8 at fixed geometry, the
# measured counterpart of the forecaster's ~2x; (c) composed legs —
# quantize under tp:2 and under --speculate (accept-rate delta is part
# of the evidence). Appends to $OUT, mirrored into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r21.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r21.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# identical serving geometry + workload to tpu_capture_r18..r20.sh so
# the r21 quantization numbers read directly against those slots
LM="--serveArg=--vocabSize --serveArg=32000 \
    --serveArg=--dModel --serveArg=1024 \
    --serveArg=--numLayers --serveArg=8 \
    --serveArg=--numHeads --serveArg=16 \
    --serveArg=--seq --serveArg=1024 \
    --serveArg=--slots --serveArg=8"
GEN="--model transformer_lm --endpoint generate \
     --requests 32 --promptLen 128 --maxNewTokens 128"
# kv8 needs page-aligned pools; 128 divides seq 1024 on every leg
PAGED="--serveArg=--kvPageTokens --serveArg=128"

# 0. the quant test file + the full A/B assertion pass on this env
step "pytest_quant" 900 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_quant.py -q
step "quant_smoke" 900 python scripts/serving_bench.py \
  --quantSmoke --model transformer_lm

# 1. weight-format A/B x3 — one stream (c1), per-token latency. Every
#    quantized JSON line carries quantize= + quant_agreement +
#    quant_logit_max_err in provenance (the guardrail numbers PERF.md
#    §24 records next to the speed). Acceptance: int8/fp8 p50 at or
#    under off on the SAME workload, agreement >= 0.98.
for REP in 1 2 3; do
  for MODE in off int8 fp8; do
    # shellcheck disable=SC2086
    step "w_${MODE}_rep${REP}" 1800 python scripts/serving_bench.py \
      $GEN $LM --concurrency 1 \
      --serveArg=--quantize --serveArg="$MODE" || true
  done
done

# 2. THE r21 leg — kv8 slot sweep at fixed HBM. f32 pools OOM-bound
#    the slot count; int8+kv8 at the same geometry must serve >= 2x
#    the slots (forecaster prediction: explain --mem --quantize). Walk
#    slots up under both modes; the last slot count that serves without
#    RESOURCE_EXHAUSTED is the measured ceiling for §24.
for SLOTS in 8 16 24 32 48 64; do
  # shellcheck disable=SC2086
  step "kv_f32_s${SLOTS}" 1800 python scripts/serving_bench.py \
    $GEN --concurrency 8 $PAGED \
    --serveArg=--vocabSize --serveArg=32000 \
    --serveArg=--dModel --serveArg=1024 \
    --serveArg=--numLayers --serveArg=8 \
    --serveArg=--numHeads --serveArg=16 \
    --serveArg=--seq --serveArg=1024 \
    --serveArg=--slots --serveArg="$SLOTS" || true
  # shellcheck disable=SC2086
  step "kv_kv8_s${SLOTS}" 1800 python scripts/serving_bench.py \
    $GEN --concurrency 8 $PAGED \
    --serveArg=--quantize --serveArg=int8+kv8 \
    --serveArg=--vocabSize --serveArg=32000 \
    --serveArg=--dModel --serveArg=1024 \
    --serveArg=--numLayers --serveArg=8 \
    --serveArg=--numHeads --serveArg=16 \
    --serveArg=--seq --serveArg=1024 \
    --serveArg=--slots --serveArg="$SLOTS" || true
done

# 3. composed legs: quantize under tp:2 (scale placement on real
#    chips) and under speculative decode (accept-rate delta vs the
#    unquantized speculative run is part of the §24 evidence).
# shellcheck disable=SC2086
step "q_tp2" 1800 python scripts/serving_bench.py $GEN $LM \
  --concurrency 1 --strategy tp:2 \
  --serveArg=--quantize --serveArg=int8+kv8 $PAGED || true
# shellcheck disable=SC2086
step "q_spec" 1800 python scripts/serving_bench.py $GEN $LM \
  --concurrency 1 $PAGED \
  --serveArg=--speculate --serveArg=4 || true
# shellcheck disable=SC2086
step "q_spec_int8kv8" 1800 python scripts/serving_bench.py $GEN $LM \
  --concurrency 1 $PAGED \
  --serveArg=--quantize --serveArg=int8+kv8 \
  --serveArg=--speculate --serveArg=4 || true

# 4. the forecaster's prediction for this geometry, for the §24 table
step "forecast" 300 env JAX_PLATFORMS=cpu python -m bigdl_tpu.cli.main \
  explain --mem transformer_lm --json --quantize int8+kv8

# 5. summarize every JSON line in this log for PERF.md §24
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
