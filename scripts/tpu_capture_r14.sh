#!/usr/bin/env bash
# Round-14 capture: ISSUE 10 (compressed, bucketed, overlapped gradient
# all-reduce) chip evidence. The mechanism is CPU-verified end to end
# (tests/test_grad_comm.py, the gradcomm-smoke CI job); what only
# hardware can tell us is (a) the compressed-vs-plain collective_s /
# collective_frac delta on a real mesh — halving wire bytes only pays
# when the all-reduce is actually bandwidth-bound, (b) whether the
# dependency-free bucket launches overlap with backward under the real
# XLA scheduler (step time delta beyond the collective delta), and
# (c) what bucket bound the measure-mode autotuner picks per
# (param-bytes, n_devices, dtype) on chip. Each A/B leg runs x3 reps so
# the §17 slots get medians, with explain legs attributing the windows.
# On a single-chip tunnel every --strategy leg exits cleanly ("needs
# more than one device") and the round costs minutes, not hours.
# Appends to $OUT, mirrored into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r14.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r14.log}"
TRACE_ROOT="${TRACE_ROOT:-/tmp/gradcomm_r14}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# 0. the grad-comm tests on the bench env first
step "pytest_grad_comm" 600 python -m pytest tests/test_grad_comm.py \
  tests/test_strategy_perf.py -q

# 1. THE r14 table: compressed-vs-plain gradient all-reduce A/B on dp,
#    x3 reps each so PERF.md §17 gets medians. Every line stamps
#    grad_compress/grad_buckets next to collective_s/collective_frac;
#    the capture window attributes the collective bucket per leg.
for REP in 1 2 3; do
  for GC in off bf16 bf16+ec fp16; do
    step "ab_dp_${GC}_r${REP}" 1800 python -m bigdl_tpu.cli.main perf \
      -m resnet50 -b 128 -i 30 --strategy dp --gradCompress "$GC" \
      --obs --traceDir "$TRACE_ROOT/dp_${GC}_r${REP}" \
      --traceSteps 4@15 || true
  done
done

# 2. the LM leg (gradient tree dominated by a few big matmul leaves —
#    the bucket layout stress case opposite resnet's many small ones)
for REP in 1 2 3; do
  for GC in off bf16; do
    step "ab_lm_${GC}_r${REP}" 1800 python -m bigdl_tpu.cli.main perf \
      -m transformer_lm_1k_hd128 -b 8 -i 30 --strategy dp \
      --gradCompress "$GC" \
      --obs --traceDir "$TRACE_ROOT/lm_${GC}_r${REP}" \
      --traceSteps 4@15 || true
  done
done

# 3. bucket-bound sweep at fixed compression: explicit 1/4/16 MiB vs
#    the measure-mode autotuned pick (persisted under the grad_comm
#    cache namespace; the cached leg replays it with zero overhead)
for BK in 1 4 16; do
  step "buckets_${BK}mib" 1800 python -m bigdl_tpu.cli.main perf \
    -m resnet50 -b 128 -i 30 --strategy dp --gradCompress bf16 \
    --gradBuckets "$BK" || true
done
step "buckets_autotune_measure" 2400 python -m bigdl_tpu.cli.main perf \
  -m resnet50 -b 128 -i 30 --strategy dp --gradCompress bf16 \
  --gradBuckets auto --autotune measure || true
step "buckets_autotune_cached" 1800 python -m bigdl_tpu.cli.main perf \
  -m resnet50 -b 128 -i 30 --strategy dp --gradCompress bf16 \
  --gradBuckets auto --autotune cached || true

# 4. explain the compressed vs plain windows — the collective row of
#    the attribution table is the wire-byte halving made visible
step "explain_dp_off" 600 python -m bigdl_tpu.cli.main explain \
  "$TRACE_ROOT/dp_off_r1/capture_15" --steps 4 || true
step "explain_dp_bf16" 600 python -m bigdl_tpu.cli.main explain \
  "$TRACE_ROOT/dp_bf16_r1/capture_15" --steps 4 || true

# 5. bench.py with compression plumbed through (the multichip bench row
#    with grad_compress/grad_buckets columns in the line)
step "bench_dp_bf16" 2400 env BENCH_COMPANIONS=0 python bench.py \
  resnet50 128 20 --strategy dp --gradCompress bf16

# 6. summarize every JSON line in this log for PERF.md §17
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
