#!/usr/bin/env bash
# Round-19 capture: ISSUE 15 (per-request observability) chip evidence.
# The correctness contracts are CPU-verified (tests/test_reqtrace.py,
# the tier1 slo-smoke leg) — what only hardware can tell us is the
# OVERHEAD: (a) --reqTrace off vs on A/B x3 on the r18 spec leg (same
# greedy workload; acceptance is tokens/s and client p50 inside the
# rep-to-rep noise band, while the on-legs' JSON lines also carry the
# server-side ttft/tpot quantiles next to the client's); (b) the same
# A/B over the full r18 stack (speculate + paged KV + prefix cache) —
# the round-log bookkeeping must stay invisible under the fastest
# decode path; (c) an SLO burn drill with targets set from the off-leg
# p50s, tight enough that overload sheds instead of queueing. Appends
# to $OUT, mirrored into the repo per step.

set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture_r19.log}"
REPO_LOG="${REPO_LOG:-TPU_CAPTURE_r19.log}"
trap 'cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -40 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" "$REPO_LOG" 2>/dev/null || true
}

# identical serving geometry + workload to tpu_capture_r18.sh so the
# r19 overhead numbers read directly against the r18 slots
LM="--serveArg=--vocabSize --serveArg=32000 \
    --serveArg=--dModel --serveArg=1024 \
    --serveArg=--numLayers --serveArg=8 \
    --serveArg=--numHeads --serveArg=16 \
    --serveArg=--seq --serveArg=1024 \
    --serveArg=--slots --serveArg=8"
GEN="--model transformer_lm --endpoint generate \
     --requests 32 --concurrency 4 --promptLen 128 --maxNewTokens 128"
SPEC="--serveArg=--speculate --serveArg=4"
PAGED="--serveArg=--kvPageTokens --serveArg=128 --serveArg=--prefixCache"

# 0. the reqtrace test file + the full CPU assertion pass on this env
step "pytest_reqtrace" 900 env JAX_PLATFORMS=cpu python -m pytest \
  tests/test_reqtrace.py -q
step "slo_smoke" 900 python scripts/serving_bench.py \
  --sloSmoke --model transformer_lm

# 1. THE r19 leg — tracing overhead A/B x3 on the r18 spec config:
#    same greedy workload with --reqTrace off vs on. tokens_per_second
#    and latency_ms.p50 must match within noise; the on-legs' JSON
#    lines add server_latency_ms (ttft/tpot p50-p99) for PERF.md §22.
for REP in 1 2 3; do
  for RT in off on; do
    # shellcheck disable=SC2086
    step "reqtrace_${RT}_rep${REP}" 1800 python scripts/serving_bench.py \
      $GEN $LM $SPEC --serveArg=--reqTrace --serveArg="$RT" || true
  done
done

# 2. the full r18 stack traced: speculate + paged KV + prefix cache
#    with --reqTrace on — per-round bookkeeping (accepted tokens, pages
#    held) must not tax the fastest decode path
for REP in 1 2 3; do
  # shellcheck disable=SC2086
  step "reqtrace_full_rep${REP}" 1800 python scripts/serving_bench.py \
    $GEN $LM $SPEC $PAGED --serveArg=--reqTrace --serveArg=on || true
done

# 3. SLO burn drill: targets tight enough that the c8 overload misses
#    them — goodput, per-dim violation counters, and the tiered shed
#    (generate 429s, predict spared) under real chip latencies. The
#    access log prices itself at full sampling.
# shellcheck disable=SC2086
step "slo_burn" 1800 python scripts/serving_bench.py $GEN $LM \
  --concurrency 8 \
  --serveArg=--slo --serveArg=ttft=250,tpot=20,burn=0.75,window=32 \
  --serveArg=--accessLog --serveArg=/tmp/r19_access.jsonl || true
step "slo_burn_accesslog" 60 bash -c \
  'wc -l /tmp/r19_access.jsonl && tail -3 /tmp/r19_access.jsonl'

# 4. summarize every JSON line in this log for PERF.md §22
step "summarize" 300 python scripts/update_perf_from_capture.py "$OUT"
