"""Sustained-training soak: steady-state input pipeline + async checkpoint
+ kill -9 mid-run + resume (VERDICT r4 next-round item 9; the analog of the
reference's long ImageNet runs, scripts/run.example.sh:54, whose durability
story is Spark re-execution — ours is the two-artifact checkpoint
convention surviving an unclean death).

Two modes:

* ``run`` — the inner training loop: resnet20-CIFAR-shape net training
  from generated record shards (libjpeg decode + augment in the loop),
  async checkpoint every N iterations, JSONL summary. Resumes from the
  newest checkpoint if one exists. Runs until killed or --minutes.
* ``orchestrate`` — spawns ``run``, SIGKILLs it mid-step after phase1
  seconds, re-spawns it (which must resume from the last complete
  snapshot), lets phase2 run, then verifies: training advanced past the
  kill point, every logged loss is finite, loss after resume is no worse
  than ~the loss before the kill (params actually restored, not
  re-initialized), and throughput is steady (no leak-driven decay).
  Prints one JSON verdict line.

Usage:
    python scripts/soak.py orchestrate --dir /tmp/soak --phase1 1800 --phase2 600
"""

import argparse
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _ensure_data(root: str, per_class: int = 2000, classes: int = 10,
                 size: int = 32):
    shards = os.path.join(root, "shards")
    if os.path.isdir(shards) and os.listdir(shards):
        return shards
    from bigdl_tpu.cli.perf import _make_class_image_tree
    from bigdl_tpu.dataset import write_image_shards

    tree = os.path.join(root, "imgs")
    # hard grade: loss decays over epochs, so the post-resume loss level
    # actually discriminates restored-params from re-initialized
    _make_class_image_tree(tree, classes, per_class, size, seed=0,
                           hard=True)
    write_image_shards(tree, shards, images_per_shard=512, workers=4)
    return shards


def run(args):
    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    # the whole point of this harness is kill -9 + re-spawn: the phase-2
    # resume must load its TPU executable from the persistent cache, not
    # re-pay the multi-minute compile out of the phase-2 budget
    from bigdl_tpu.cli.common import enable_compile_cache
    enable_compile_cache()
    from bigdl_tpu import nn
    from bigdl_tpu.dataset import RecordImageDataSet
    from bigdl_tpu.models import resnet_cifar
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    shards = _ensure_data(args.dir)
    ds = RecordImageDataSet(shards, args.batch, crop=(32, 32), train=True,
                            mean=[127.0] * 3, std=[60.0] * 3)
    model = resnet_cifar(20, 10)
    t0 = time.time()
    deadline = Trigger(lambda s: time.time() - t0 > args.minutes * 60,
                       f"wallClock({args.minutes}m)")
    ck = os.path.join(args.dir, "ckpt")
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(),
                    optim_method=SGD(learning_rate=0.05, momentum=0.9),
                    end_when=deadline, log_every=10)
    opt.set_checkpoint(Trigger.several_iteration(args.ckpt_every), ck,
                       overwrite=True, async_save=True)
    opt.set_summary(os.path.join(args.dir, "summary"))
    if os.path.isdir(ck) and os.listdir(ck):
        opt.resume(ck)
        print(f"soak: resumed from {ck}", flush=True)
    opt.optimize()
    print("soak run: clean exit", flush=True)


def _read_train_rows(root: str):
    path = os.path.join(root, "summary", "train.jsonl")
    rows = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn tail line from the kill — expected
    return rows


def orchestrate(args):
    import math

    base = [sys.executable, os.path.abspath(__file__), "run",
            "--dir", args.dir, "--batch", str(args.batch),
            "--ckpt-every", str(args.ckpt_every),
            "--minutes", str(max(1.0, (args.phase1 + args.phase2) / 60.0))]
    if args.cpu:
        base.append("--cpu")

    # If the sweep's step timeout SIGTERMs this orchestrator, the live
    # training child must die too — an orphaned child would wedge the
    # TPU device lock and block every later sweep step.
    children = []

    def _reap(signum, frame):
        for c in children:
            try:
                c.kill()
            except OSError:
                pass
        sys.exit(128 + signum)

    signal.signal(signal.SIGTERM, _reap)
    signal.signal(signal.SIGINT, _reap)

    os.makedirs(args.dir, exist_ok=True)
    _ensure_data(args.dir)        # dataset generation outside phase timing
    log1 = open(os.path.join(args.dir, "phase1.log"), "w")
    p = subprocess.Popen(base, stdout=log1, stderr=subprocess.STDOUT)
    children.append(p)
    time.sleep(args.phase1)
    p.send_signal(signal.SIGKILL)      # uncleanly, mid-step by design
    p.wait()
    rows1 = _read_train_rows(args.dir)
    kill_iter = rows1[-1]["iteration"] if rows1 else 0

    log2 = open(os.path.join(args.dir, "phase2.log"), "w")
    base[base.index("--minutes") + 1] = str(max(1.0, args.phase2 / 60.0))
    p2 = subprocess.Popen(base, stdout=log2, stderr=subprocess.STDOUT)
    children.append(p2)
    try:
        p2.wait(timeout=args.phase2 + 600)
    except subprocess.TimeoutExpired:
        # a wedged child (tunnel drop mid-step) must not outlive us and
        # hold the TPU device lock; kill it and still emit the verdict
        # from whatever rows landed
        p2.kill()
        p2.wait()
    rows2 = _read_train_rows(args.dir)
    new_rows = rows2[len(rows1):]

    losses = [r["loss"] for r in rows2]
    rps = [r["records_per_second"] for r in rows2]
    # loss continuity: first post-resume losses should sit near the last
    # pre-kill ones (window medians), not back at the from-scratch level
    def _median(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2] if xs else float("nan")

    pre = _median([r["loss"] for r in rows1[-5:]])
    post = _median([r["loss"] for r in new_rows[:5]])
    first = _median([r["loss"] for r in rows1[:3]])
    resumed_line = ""
    with open(os.path.join(args.dir, "phase2.log")) as f:
        for line in f:
            if line.startswith("soak: resumed"):
                resumed_line = line.strip()
    verdict = {
        "metric": "soak",
        "phase1_s": args.phase1, "phase2_s": args.phase2,
        "kill_iteration": kill_iter,
        "final_iteration": rows2[-1]["iteration"] if rows2 else 0,
        "advanced_past_kill": bool(new_rows) and
            rows2[-1]["iteration"] > kill_iter,
        "resumed_from_checkpoint": bool(resumed_line),
        "all_losses_finite": all(math.isfinite(l) for l in losses),
        "loss_pre_kill": round(pre, 4), "loss_post_resume": round(post, 4),
        "loss_at_start": round(first, 4),
        "resume_continuity": bool(post == post and pre == pre and
                                  post < (pre + first) / 2),
        "throughput_median_rps": round(_median(rps), 1),
        "throughput_last10_rps": round(_median(rps[-10:]), 1),
        "throughput_steady": bool(
            rps and _median(rps[-10:]) > 0.7 * _median(rps)),
    }
    verdict["ok"] = all(verdict[k] for k in (
        "advanced_past_kill", "resumed_from_checkpoint",
        "all_losses_finite", "resume_continuity", "throughput_steady"))
    print(json.dumps(verdict), flush=True)
    return 0 if verdict["ok"] else 1


def main():
    p = argparse.ArgumentParser("soak")
    sub = p.add_subparsers(dest="cmd", required=True)
    r = sub.add_parser("run")
    o = sub.add_parser("orchestrate")
    for q in (r, o):
        q.add_argument("--dir", required=True)
        q.add_argument("--batch", type=int, default=128)
        q.add_argument("--ckpt-every", type=int, default=50)
        q.add_argument("--cpu", action="store_true")
    r.add_argument("--minutes", type=float, default=30.0)
    o.add_argument("--phase1", type=int, default=1800)
    o.add_argument("--phase2", type=int, default=600)
    args = p.parse_args()
    if args.cmd == "run":
        run(args)
    else:
        sys.exit(orchestrate(args))


if __name__ == "__main__":
    main()
