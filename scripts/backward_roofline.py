"""Per-op backward roofline: achieved-vs-ceiling TF/s for the conv
passes, joined against an xplane profile's top fusions (PERF.md §11).

Two inputs:

* ``--probe FILE`` — conv_bwd_probe.py JSONL (geometry fields + per-pass
  isolated ms/TF/s under each layout). Required. From it alone the
  script emits the **isolated roofline**: for every (geometry, pass),
  the default-NHWC time, the best layout's time, and the ceiling ratio —
  i.e. how much of each pass's attainable rate the shipped default
  reaches, and what the per-geometry policy should buy.
* ``--profile DIR`` — a ``jax.profiler.trace`` directory (e.g. from
  ``perf ... --profile DIR``). Optional. The script parses the xplane
  protobuf with ``bigdl_tpu.utils.xplane`` (no tensorboard dep), takes
  the top ``--top`` device ops by total time, scales to per-step ms via
  ``--steps``, and joins each against the same-shape isolated
  microbenches by duration proximity: a fusion whose per-step time is
  within ``--tol`` of an isolated pass time gets that label and an
  achieved-vs-ceiling percentage. Unmatched fusions are listed honestly
  — the point of the table is to either land ≥40% b128 MFU or bound the
  model on this chip, not to flatter it.

Usage:
    python scripts/conv_bwd_probe.py 30 | tee /tmp/probe.jsonl
    python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 5 --profile /tmp/xp
    python scripts/backward_roofline.py --probe /tmp/probe.jsonl \
        --profile /tmp/xp --steps 5 --out ROOFLINE_r08.md
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bigdl_tpu.ops.conv2d import _PASSES, _row_geom  # noqa: E402

_BWD = ("dgrad", "wgrad")


def load_probe(path: str):
    """Probe JSONL -> {(geom, pass): {layout: {"ms", "tfs"}}} plus a
    display name per geometry."""
    cells, names = {}, {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            g = _row_geom(row)
            lay = row.get("layout")
            if g is None or lay is None:
                continue
            names.setdefault(g, row.get("shape", "conv"))
            gf = float(row.get("gflops") or 0.0)
            for p in _PASSES:
                ms = row.get(f"{p}_ms")
                if ms is None:
                    continue
                ms = float(ms)
                tfs = row.get(f"{p}_tfs")
                tfs = (float(tfs) if tfs is not None
                       else (gf / ms if ms else 0.0))
                cells.setdefault((g, p), {})[lay] = {"ms": ms, "tfs": tfs,
                                                     "gflops": gf}
    if not cells:
        raise SystemExit(f"no usable probe rows in {path}")
    return cells, names


def isolated_table(cells, names):
    """Rows: per (geometry, backward pass) — NHWC vs best layout vs
    ceiling fraction. The 'ceiling' of a pass is its best measured
    layout; achieved-under-default is the NHWC cell."""
    rows = []
    for (g, p), per in sorted(cells.items(),
                              key=lambda kv: (names[kv[0][0]], kv[0][1])):
        if p not in _BWD:
            continue
        best_lay = min(per, key=lambda l: per[l]["ms"])
        best = per[best_lay]
        nhwc = per.get("NHWC", best)
        rows.append({
            "shape": names[g], "pass": p,
            "nhwc_ms": round(nhwc["ms"], 3),
            "nhwc_tfs": round(nhwc["tfs"], 1),
            "best_layout": best_lay,
            "best_ms": round(best["ms"], 3),
            "best_tfs": round(best["tfs"], 1),
            "pct_of_ceiling_default": round(
                100.0 * best["ms"] / nhwc["ms"], 1) if nhwc["ms"] else None,
        })
    return rows


def join_profile(profile_dir, cells, names, top, steps, tol):
    """Top device fusions by total time, each matched (by per-step
    duration proximity) against the isolated microbench cells."""
    from bigdl_tpu.utils.xplane import (device_planes, find_xplane_pb,
                                        op_totals, parse_xspace)

    from bigdl_tpu.obs.attrib import attribute, classify_op

    pb = find_xplane_pb(profile_dir)
    if pb is None:
        raise SystemExit(f"no *.xplane.pb under {profile_dir}")
    planes = parse_xspace(pb)
    totals = op_totals(device_planes(planes))
    # the ISSUE 8 attribution of the same profile rides along so the
    # roofline table and the category/collective breakout come from ONE
    # parse — consumers stop re-deriving it (and unknown keys like
    # collective_s/attrib in perf JSON lines are now first-class here)
    summary = attribute(planes, steps=max(1, steps))
    ranked = sorted(totals.items(), key=lambda kv: -kv[1]["total_ps"])
    rows = []
    for name, ent in ranked[:top]:
        ms_step = ent["total_ps"] / 1e9 / max(1, steps)
        row = {"op": name, "ms_per_step": round(ms_step, 3),
               "count": ent["count"],
               "category": classify_op(name)[0], "match": None}
        # nearest isolated cell by relative duration distance
        best_key, best_d = None, tol
        for (g, p), per in cells.items():
            for lay, cell in per.items():
                if not cell["ms"]:
                    continue
                d = abs(ms_step - cell["ms"]) / cell["ms"]
                if d < best_d:
                    best_key, best_d = (g, p, lay), d
        if best_key is not None:
            g, p, lay = best_key
            per = cells[(g, p)]
            cell = per[lay]
            ceil = max(c["tfs"] for c in per.values())
            ach = cell["gflops"] / ms_step / 1e3 if ms_step else 0.0
            row["match"] = {
                "shape": names[g], "pass": p, "layout": lay,
                "isolated_ms": round(cell["ms"], 3),
                "rel_duration_gap": round(best_d, 3),
                "achieved_tfs": round(ach, 1),
                "ceiling_tfs": round(ceil, 1),
                "pct_of_ceiling": round(100.0 * ach / ceil, 1)
                if ceil else None,
            }
        rows.append(row)
    return pb, rows, summary


def load_perf_mem(path):
    """Last perf JSON line of ``path`` that carries the ISSUE 12 memory
    columns -> (hbm_peak_bytes, hbm_headroom_frac, mem-dict) or None."""
    found = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if "hbm_peak_bytes" in row or isinstance(row.get("mem"), dict):
                found = row
    if found is None:
        return None
    return {"hbm_peak_bytes": found.get("hbm_peak_bytes"),
            "hbm_headroom_frac": found.get("hbm_headroom_frac"),
            "mem": found.get("mem"),
            "model": found.get("model"),
            "batch": found.get("batch")}


def markdown(iso_rows, prof_rows, pb, attrib_summary=None, mem=None):
    out = ["### Isolated backward roofline (probe microbenches)", "",
           "| shape | pass | NHWC ms | NHWC TF/s | best | best ms | "
           "best TF/s | best/NHWC time |",
           "|---|---|---|---|---|---|---|---|"]
    for r in iso_rows:
        out.append(
            f"| {r['shape']} | {r['pass']} | {r['nhwc_ms']} | "
            f"{r['nhwc_tfs']} | {r['best_layout']} | {r['best_ms']} | "
            f"{r['best_tfs']} | {r['pct_of_ceiling_default']}% |")
    if prof_rows is not None:
        out += ["", f"### Profile join (top fusions, {pb})", "",
                "| op | category | ms/step | matched bench | "
                "achieved TF/s | ceiling TF/s | % of ceiling |",
                "|---|---|---|---|---|---|---|"]
        for r in prof_rows:
            m = r["match"]
            cat = r.get("category", "-")
            if m:
                out.append(
                    f"| {r['op']} | {cat} | {r['ms_per_step']} | "
                    f"{m['shape']}/{m['pass']}/{m['layout']} "
                    f"(±{m['rel_duration_gap']}) | {m['achieved_tfs']} | "
                    f"{m['ceiling_tfs']} | {m['pct_of_ceiling']}% |")
            else:
                out.append(f"| {r['op']} | {cat} | {r['ms_per_step']} | "
                           "unmatched | — | — | — |")
    if attrib_summary is not None:
        out += ["", "### Device-time attribution (PERF.md §16 taxonomy)",
                "", "| category | time_s | frac % | ms/step |",
                "|---|---|---|---|"]
        steps = max(1, attrib_summary.get("steps") or 1)
        for cat, d in attrib_summary["categories"].items():
            out.append(f"| {cat} | {d['time_s']:.5f} "
                       f"| {100 * d['frac']:.1f} "
                       f"| {d['time_s'] * 1e3 / steps:.3f} |")
        for kind, d in attrib_summary["collectives"].items():
            out.append(f"| coll:{kind} | {d['time_s']:.5f} "
                       f"| {100 * d['frac']:.1f} "
                       f"| {d['time_s'] * 1e3 / steps:.3f} |")
    if mem is not None:
        pk, hr = mem.get("hbm_peak_bytes"), mem.get("hbm_headroom_frac")
        out += ["", "### HBM attribution (ISSUE 12, from --perfJson)", "",
                f"run: {mem.get('model')} b={mem.get('batch')} — "
                f"hbm peak "
                f"{round(pk / 2**30, 2) if pk is not None else '-'} GiB, "
                f"headroom "
                f"{round(hr * 100, 1) if hr is not None else '-'}%", "",
                "| category | MiB | frac % |", "|---|---|---|"]
        m = mem.get("mem") or {}
        total = max(1, m.get("total_bytes") or 1)
        for cat, b in (m.get("categories") or {}).items():
            out.append(f"| {cat} | {b / 2**20:.1f} "
                       f"| {100.0 * b / total:.1f} |")
    return "\n".join(out) + "\n"


def main(argv=None):
    ap = argparse.ArgumentParser("backward roofline join")
    ap.add_argument("--probe", required=True,
                    help="conv_bwd_probe.py JSONL")
    ap.add_argument("--profile", default=None,
                    help="jax.profiler.trace dir (optional)")
    ap.add_argument("--top", type=int, default=10)
    ap.add_argument("--steps", type=int, default=1,
                    help="training steps covered by the trace (per-step "
                         "scaling)")
    ap.add_argument("--tol", type=float, default=0.35,
                    help="max relative duration gap for a bench match")
    ap.add_argument("--perfJson", default=None,
                    help="perf JSON log of the same run (an --obs line "
                         "with the ISSUE 12 memory columns) — adds the "
                         "HBM peak/headroom + category section")
    ap.add_argument("--out", default=None,
                    help="write the markdown table here (stdout default)")
    ap.add_argument("--json", default=None,
                    help="also dump the raw rows as JSON here")
    args = ap.parse_args(argv)

    cells, names = load_probe(args.probe)
    iso = isolated_table(cells, names)
    pb, prof, summary = (None, None, None)
    if args.profile:
        pb, prof, summary = join_profile(args.profile, cells, names,
                                         args.top, args.steps, args.tol)
    mem = load_perf_mem(args.perfJson) if args.perfJson else None
    md = markdown(iso, prof, pb, summary, mem)
    if args.out:
        with open(args.out, "w") as f:
            f.write(md)
        print(f"wrote {args.out}", file=sys.stderr)
    else:
        sys.stdout.write(md)
    if args.json:
        attrib_compact = None
        if summary is not None:
            from bigdl_tpu.obs.attrib import compact
            attrib_compact = compact(summary)
        with open(args.json, "w") as f:
            json.dump({"isolated": iso, "profile": prof,
                       "attrib": attrib_compact, "mem": mem,
                       "xplane": pb}, f, indent=1, sort_keys=True)
            f.write("\n")


if __name__ == "__main__":
    main()
