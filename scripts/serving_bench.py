#!/usr/bin/env python
"""Closed-loop load generator for the `bigdl-tpu serve` endpoint
(ISSUE 5 satellite) — the serving analog of the perf harness: drive
/predict or /generate at a fixed concurrency, report client-side latency
quantiles (p50/p95/p99) and throughput, and stamp the SERVER's config
provenance (scraped from /metrics) into the emitted JSON line so every
result is attributable to an exact program — the perf-JSON contract from
PRs 2-4 extended to serving.

    # spawn a server on an ephemeral port, bench, shut down
    python scripts/serving_bench.py --model lenet5 --randomInit \
        --requests 64 --concurrency 4 --platform cpu

    # bench an already-running server
    python scripts/serving_bench.py --url http://127.0.0.1:8000 \
        --model resnet50 --endpoint predict --batch 4

    # CI smoke: tiny config, asserts endpoints + metrics + clean shutdown
    python scripts/serving_bench.py --smoke --model transformer_lm \
        --platform cpu

    # CI slo-smoke: ISSUE 15 per-request observability assertions
    # (SLO goodput/burn/shed, access log, /debug/*, x-request-id)
    python scripts/serving_bench.py --sloSmoke --model transformer_lm \
        --platform cpu

    # CI serving-tp-smoke: ISSUE 16 multi-chip assertions (tp:2
    # bit-identity vs single chip, dp:2 replica-labelled metrics)
    python scripts/serving_bench.py --tpSmoke --model transformer_lm \
        --platform cpu

    # dp QPS scaling sweep (the ISSUE 16 perf headline; on chips add
    # --assertScaling 0.8)
    python scripts/serving_bench.py --dpSweep 1,2,4 \
        --model transformer_lm --endpoint generate

    # CI fleet-smoke: ISSUE 20 multi-process fleet assertions (router
    # proxy, kill/restart/rejoin, zero-5xx rolling weight swap)
    python scripts/serving_bench.py --fleetSmoke --model transformer_lm \
        --platform cpu
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# input geometry per perf-zoo family (serving payload synthesis); LMs
# take their length from --seq
_SHAPES = {"lenet5": (28, 28, 1), "resnet20_cifar": (32, 32, 3)}
_DEFAULT_SHAPE = (224, 224, 3)

# tiny-LM dims for --smoke / --randomInit LM runs: CPU-fast, same code
# path as the 32k-vocab production config
_SMOKE_LM = ["--vocabSize", "64", "--dModel", "32", "--numLayers", "2",
             "--numHeads", "2", "--seq", "64", "--slots", "2",
             "--buckets", "1,2,4", "--maxWaitMs", "2"]


def _post(url, body, timeout=120.0):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _post_status(url, body, timeout=120.0):
    """Like _post but 4xx/5xx return (status, body) instead of raising
    — the chaos smoke asserts exact error codes."""
    try:
        return _post(url, body, timeout)
    except urllib.error.HTTPError as e:
        try:
            return e.code, json.loads(e.read())
        except (ValueError, json.JSONDecodeError):
            return e.code, {}


def _post_h(url, body, headers=None, timeout=120.0):
    """POST returning (status, json_body, lowercased response headers)
    — the ISSUE 15 legs assert on the ``x-request-id`` echo, and 4xx/5xx
    return instead of raising (the shed leg asserts exact 429s)."""
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return (r.status, json.loads(r.read()),
                    {k.lower(): v for k, v in r.headers.items()})
    except urllib.error.HTTPError as e:
        try:
            out = json.loads(e.read())
        except (ValueError, json.JSONDecodeError):
            out = {}
        return e.code, out, {k.lower(): v for k, v in e.headers.items()}


def _get_status(url, timeout=30.0):
    try:
        return _get(url, timeout)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _get(url, timeout=30.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read().decode()


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def spawn_server(args, extra):
    """Launch `bigdl-tpu serve` as a child on an ephemeral port; parse
    the bound port from its stdout. Returns (proc, base_url, log_lines).
    """
    cmd = [sys.executable, "-m", "bigdl_tpu.cli.main", "serve",
           args.model, "--port", "0"]
    if args.ckpt:
        cmd += ["--model", args.ckpt]
    else:
        cmd += ["--randomInit"]
    if args.platform:
        cmd += ["--platform", args.platform]
    if args.model.startswith("transformer_lm") and (args.smoke
                                                    or not args.ckpt):
        cmd += _SMOKE_LM
    cmd += extra
    env = None
    if "--strategy" in cmd:
        # multi-chip strategies need devices to place replicas/shards on;
        # on the CPU host platform that means virtual devices (the same
        # trick tests/conftest.py uses). No-op on real accelerators.
        env = dict(os.environ)
        if "xla_force_host_platform_device_count" not in \
                env.get("XLA_FLAGS", ""):
            env["XLA_FLAGS"] = (
                env.get("XLA_FLAGS", "")
                + " --xla_force_host_platform_device_count=8").strip()
    proc = subprocess.Popen(cmd, cwd=REPO, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    lines, port = [], None
    port_re = re.compile(r"serving .+ on http://[^:]+:(\d+)")
    ready = threading.Event()

    def _reader():
        nonlocal port
        for line in proc.stdout:
            lines.append(line.rstrip())
            m = port_re.search(line)
            if m:
                port = int(m.group(1))
                ready.set()
        ready.set()  # EOF: unblock the waiter even on startup failure

    threading.Thread(target=_reader, daemon=True).start()
    if not ready.wait(timeout=300) or port is None:
        proc.kill()
        raise SystemExit("server never reported its port; log tail:\n"
                         + "\n".join(lines[-20:]))
    url = f"http://127.0.0.1:{port}"
    deadline = time.time() + 120
    while time.time() < deadline:
        try:
            if _get(url + "/healthz", timeout=5)[0] == 200:
                return proc, url, lines
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.2)
    proc.kill()
    raise SystemExit("server bound but /healthz never answered")


def make_payload(args):
    import numpy as np
    rng = np.random.RandomState(0)
    if args.endpoint == "generate":
        seq = args.promptLen
        return {"tokens": rng.randint(1, 50, seq).tolist(),
                "max_new_tokens": args.maxNewTokens}
    if args.model.startswith("transformer_lm"):
        seq = 64 if (args.smoke or not args.ckpt) else (args.seq or 512)
        x = rng.randint(0, 50, (args.batch, seq)).tolist()
    else:
        shape = _SHAPES.get(args.model, _DEFAULT_SHAPE)
        x = rng.randn(args.batch, *shape).astype("float32").tolist()
    return {"inputs": x}


def closed_loop(url, args):
    """N workers, each fire-wait-fire until the shared budget drains.
    With ``--stream`` (generate only) each request rides the chunked
    SSE path instead, recording client-side first-byte latency — the
    streamed half of the r22 TTFT/TPOT A/B."""
    payload = make_payload(args)
    path = f"{url}/{args.endpoint}"
    stream = bool(getattr(args, "stream", False)) \
        and args.endpoint == "generate"
    lat, ttfb, errors, lock = [], [], [0], threading.Lock()
    budget = [args.requests]
    new_tokens = [0]

    def worker():
        while True:
            with lock:
                if budget[0] <= 0:
                    return
                budget[0] -= 1
            t0 = time.perf_counter()
            try:
                if stream:
                    st, frames, t_first, t_done, _ = _stream_generate(
                        url, payload)
                    assert st == 200, frames
                    toks = sum(len(f["tokens"]) for f in frames
                               if "tokens" in f)
                    with lock:
                        lat.append(t_done * 1000.0)
                        ttfb.append(t_first * 1000.0)
                        new_tokens[0] += toks
                    continue
                _, out = _post(path, payload)
                dt = (time.perf_counter() - t0) * 1000.0
                with lock:
                    lat.append(dt)
                    if args.endpoint == "generate":
                        new_tokens[0] += len(out.get("tokens", []))
            except Exception:
                with lock:
                    errors[0] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker)
               for _ in range(args.concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    lat.sort()
    out = {
        "bench": "serving",
        "model": args.model,
        "endpoint": args.endpoint,
        "requests": args.requests,
        "concurrency": args.concurrency,
        "batch": args.batch if args.endpoint == "predict" else None,
        "wall_s": round(wall, 4),
        "rps": round(len(lat) / wall, 2) if wall else None,
        "errors": errors[0],
        "latency_ms": {
            "p50": round(_percentile(lat, 0.50), 3) if lat else None,
            "p95": round(_percentile(lat, 0.95), 3) if lat else None,
            "p99": round(_percentile(lat, 0.99), 3) if lat else None,
            "mean": round(sum(lat) / len(lat), 3) if lat else None,
            "max": round(lat[-1], 3) if lat else None,
        },
    }
    if args.endpoint == "generate":
        out["tokens_per_second"] = (round(new_tokens[0] / wall, 1)
                                    if wall else None)
    if stream:
        ttfb.sort()
        out["stream"] = True
        out["first_byte_ms"] = {
            "p50": round(_percentile(ttfb, 0.50), 3) if ttfb else None,
            "p95": round(_percentile(ttfb, 0.95), 3) if ttfb else None,
        }
    return out


def scrape_provenance(url):
    _, page = _get(url + "/metrics")
    for line in page.splitlines():
        if line.startswith("# provenance "):
            return json.loads(line[len("# provenance "):]), page
    return None, page


def scrape_value(page, name):
    """Last sample of a counter/gauge on the exposition page (with or
    without the bigdl_serving_ prefix), or None if absent."""
    for line in page.splitlines():
        parts = line.split()
        if len(parts) == 2 and parts[0] in (name,
                                            "bigdl_serving_" + name):
            try:
                return float(parts[1])
            except ValueError:
                return None
    return None


def scrape_quantile(page, name, q):
    """One quantile sample of a registry histogram, e.g.
    ``bigdl_serving_ttft_ms{quantile="0.5"} 12.3`` -> 12.3 (None when
    the line is absent or the histogram is empty/NaN)."""
    needle = f'{name}{{quantile="{q}"}}'
    for line in page.splitlines():
        parts = line.split()
        if len(parts) == 2 and parts[0] in (needle,
                                            "bigdl_serving_" + needle):
            try:
                v = float(parts[1])
            except ValueError:
                return None
            return None if v != v else v  # NaN = empty histogram
    return None


def scrape_server_latency(page):
    """The ISSUE 15 server-side request-latency columns (reqtrace
    histograms): TTFT / TPOT / ITL p50-p99 plus the p50 decomposition
    (queue wait, prefill, decode). All None when the server ran
    --reqTrace off."""
    out = {}
    for name in ("ttft_ms", "tpot_ms", "itl_ms"):
        out[name] = {p: scrape_quantile(page, name, q)
                     for p, q in (("p50", "0.5"), ("p95", "0.95"),
                                  ("p99", "0.99"))}
    for name in ("request_queue_wait_ms", "request_prefill_ms",
                 "request_decode_ms"):
        out[name.replace("request_", "") + "_p50"] = \
            scrape_quantile(page, name, "0.5")
    return out


def scrape_spec_columns(page):
    """The ISSUE 14 speculative-decoding columns: accept rate and tokens
    emitted per target verify step (the dispatch-count win the bench
    reports alongside tokens/s). None-valued when serving --speculate 0.
    """
    return {
        "spec_accept_rate": scrape_value(page, "spec_accept_rate"),
        "accepted_tokens_per_step": scrape_value(
            page, "spec_accepted_tokens_per_step"),
        "decode_steps_total": scrape_value(page, "decode_steps_total"),
        "generated_tokens_total": scrape_value(
            page, "generated_tokens_total"),
    }


def _smoke_latency_agreement(url, args):
    """ISSUE 15 satellite: the server-side TTFT/TPOT histograms
    (reqtrace) must agree with what a client measures from outside.

    Client-side TTFT ~ the round trip of a ``max_new_tokens=1`` generate
    at concurrency 1 (queue wait ~0, one decode round); client-side TPOT
    ~ the per-extra-token slope between a K-token and a 1-token request.
    Tolerances are CPU-CI generous — this catches unit mistakes (s vs
    ms), double counting, and misattributed phases, not microseconds."""
    K = 17
    prompt = list(range(1, 9))
    one, many = [], []
    for _ in range(6):
        t0 = time.perf_counter()
        _post(url + "/generate", {"tokens": prompt, "max_new_tokens": 1})
        one.append((time.perf_counter() - t0) * 1000.0)
        t0 = time.perf_counter()
        _post(url + "/generate", {"tokens": prompt, "max_new_tokens": K})
        many.append((time.perf_counter() - t0) * 1000.0)
    one.sort()
    many.sort()
    ttft_c = _percentile(one, 0.50)
    tpot_c = max((_percentile(many, 0.50) - ttft_c) / (K - 1), 0.0)
    _, page = _get(url + "/metrics")
    ttft_s = scrape_quantile(page, "ttft_ms", "0.5")
    tpot_s = scrape_quantile(page, "tpot_ms", "0.5")
    assert ttft_s is not None and ttft_s > 0, "ttft_ms histogram empty"
    assert tpot_s is not None and tpot_s > 0, "tpot_ms histogram empty"
    assert abs(ttft_s - ttft_c) <= max(100.0, 0.6 * max(ttft_c, ttft_s)), \
        f"TTFT p50 disagree: server {ttft_s:.2f} ms vs client " \
        f"{ttft_c:.2f} ms"
    assert abs(tpot_s - tpot_c) <= max(25.0, 0.6 * max(tpot_c, tpot_s)), \
        f"TPOT p50 disagree: server {tpot_s:.2f} ms vs client " \
        f"{tpot_c:.2f} ms"
    print(f"smoke: server-side p50 agrees with client-side "
          f"(TTFT {ttft_s:.1f}~{ttft_c:.1f} ms, "
          f"TPOT {tpot_s:.2f}~{tpot_c:.2f} ms) OK", flush=True)


def run_smoke(url, args, page_checks=True):
    """Tiny assertion pass: every endpoint answers, metrics count."""
    st, _ = _get(url + "/healthz")
    assert st == 200, f"/healthz -> {st}"
    args.endpoint, args.batch, args.requests = "predict", 2, 4
    args.concurrency = 2
    res = closed_loop(url, args)
    assert res["errors"] == 0, f"predict errors: {res}"
    if args.model.startswith("transformer_lm"):
        args.endpoint = "generate"
        args.promptLen, args.maxNewTokens = 5, 4
        gen = closed_loop(url, args)
        assert gen["errors"] == 0, f"generate errors: {gen}"
        assert gen["tokens_per_second"], gen
    prov, page = scrape_provenance(url)
    assert prov is not None, "metrics page lost its provenance line"
    assert "requests_predict_total" in page
    for needle in ("bn_fused", "autotune", "buckets", "conv_layouts"):
        assert needle in prov, f"provenance missing {needle}: {prov}"
    count = [l for l in page.splitlines()
             if l.startswith("bigdl_serving_requests_predict_total ")]
    assert count and float(count[0].split()[-1]) >= 4, count
    print("smoke: endpoints + metrics provenance OK", flush=True)
    if (args.model.startswith("transformer_lm")
            and prov.get("reqtrace") == "on"):
        _smoke_latency_agreement(url, args)


def run_spec_smoke(args):
    """ISSUE 14 speculative-decoding assertion pass (CI):

    spawn the same tiny LM twice — --speculate 0 and --speculate 4 —
    fire one fixed greedy /generate prompt at each, and assert the
    speculative tokens are BIT-IDENTICAL to the plain ones (the exact-
    acceptance contract), that spec_accept_rate lands non-zero, and
    that the accepted-tokens/step gauge shows >1 token per target
    dispatch (the raw-speed win, observable without a chip as a
    dispatch-count proxy: fewer verify steps than emitted tokens)."""
    prompt = list(range(1, 13))
    body = {"tokens": prompt, "max_new_tokens": 16}
    results = {}
    for k in (0, 4):
        extra = list(args.serveArg) + ["--speculate", str(k)]
        proc, url, log_lines = spawn_server(args, extra)
        try:
            st, out = _post(url + "/generate", body)
            assert st == 200, f"--speculate {k} /generate -> {st}"
            prov, page = scrape_provenance(url)
            assert prov["speculate"] == k, prov
            results[k] = (out["tokens"], scrape_spec_columns(page), prov)
        finally:
            _shutdown_clean(proc, log_lines)
    plain, spec = results[0][0], results[4][0]
    assert spec == plain, (
        f"speculative greedy output diverged:\n  plain {plain}\n"
        f"  spec  {spec}")
    cols = results[4][1]
    assert cols["spec_accept_rate"] and cols["spec_accept_rate"] > 0, cols
    assert cols["accepted_tokens_per_step"] > 1.0, cols
    assert cols["decode_steps_total"] < cols["generated_tokens_total"], \
        cols
    # the measured number also rides the provenance line (scrape-time
    # resolved), next to the static --speculate config
    prov = results[4][2]
    assert prov["spec_accepted_tokens_per_step"] > 1.0, prov
    record = {"bench": "serving_spec_smoke", "prompt_len": len(prompt),
              "max_new_tokens": 16, "bit_identical": True, **cols}
    print(json.dumps(record), flush=True)
    print(f"spec-smoke: --speculate 4 bit-identical, accept_rate="
          f"{cols['spec_accept_rate']:.2f}, accepted-tokens/step="
          f"{cols['accepted_tokens_per_step']:.2f} OK", flush=True)
    return 0


_QUANT_AGREE_MIN = 0.9       # client-side A/B token agreement floor
_QUANT_GUARDRAIL_MIN = 0.98  # server-measured quant_report floor
_QUANT_SLOT_FACTOR = 2.0     # kv8 must admit >= 2x slots at equal HBM


def _kv_slot_capacity(page_tokens=16, max_len=64, dense_slots=8):
    """In-process PagedKvCache A/B at EQUAL pool bytes: size both pools
    to the HBM budget ``dense_slots`` full-length slots cost in f32,
    then count how many slots each variant actually admits via
    ``reserve()`` — the real allocator, not arithmetic."""
    if REPO not in sys.path:  # the spawned servers get cwd=REPO; we
        sys.path.insert(0, REPO)  # import in-process for the allocator
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from bigdl_tpu import models
    from bigdl_tpu.serving.kv_pages import PagedKvCache, pages_needed

    model = models.transformer_lm(64, d_model=32, num_layers=2,
                                  num_heads=2, max_len=max_len)
    per_slot = pages_needed(max_len, page_tokens)

    def probe_bpp(quantized):
        return PagedKvCache(model.encoder, slots=1, max_len=max_len,
                            page_tokens=page_tokens, dtype=jnp.float32,
                            pool_pages=2,
                            quantized=quantized).bytes_per_page

    budget = probe_bpp(False) * per_slot * dense_slots
    out = {}
    for name, quantized in (("off", False), ("int8+kv8", True)):
        bpp = probe_bpp(quantized)
        kv = PagedKvCache(model.encoder, slots=budget // bpp,
                          max_len=max_len, page_tokens=page_tokens,
                          dtype=jnp.float32, pool_pages=budget // bpp,
                          quantized=quantized)
        admitted = 0
        while admitted < kv.slots and kv.reserve(admitted, max_len):
            admitted += 1
        out[name] = {"slots": admitted, "bytes_per_page": int(bpp),
                     "pool_bytes": int(bpp * kv.pool_pages)}
    out["budget_bytes"] = int(budget)
    return out


def run_quant_smoke(args):
    """ISSUE 17 quantized-serving assertion pass (CI quant-smoke leg):

    A/B the same tiny LM under --quantize off and --quantize int8+kv8
    with one fixed greedy /generate prompt. Asserts: the quantized
    output agrees with the full-precision one position-wise at >=
    _QUANT_AGREE_MIN; every server's provenance stamps its quantize
    mode; the quantized server carries the measured quant_report
    guardrail (agreement >= _QUANT_GUARDRAIL_MIN, finite logit error);
    and — the capacity headline — an in-process PagedKvCache A/B at
    EQUAL pool bytes admits >= 2x the slots with 8-bit pools."""
    prompt = list(range(1, 13))
    body = {"tokens": prompt, "max_new_tokens": 16}
    results = {}
    for mode in ("off", "int8+kv8"):
        extra = list(args.serveArg) + ["--quantize", mode]
        proc, url, log_lines = spawn_server(args, extra)
        try:
            st, out = _post(url + "/generate", body)
            assert st == 200, f"--quantize {mode} /generate -> {st}"
            prov, _page = scrape_provenance(url)
            assert prov is not None, "metrics page lost its provenance"
            assert prov.get("quantize") == mode, \
                f"provenance quantize missing/wrong under {mode}: {prov}"
            results[mode] = (out["tokens"], prov)
        finally:
            _shutdown_clean(proc, log_lines)
    base, quant = results["off"][0], results["int8+kv8"][0]
    assert len(base) == len(quant) > 0, (base, quant)
    agree = sum(a == b for a, b in zip(base, quant)) / len(base)
    assert agree >= _QUANT_AGREE_MIN, (
        f"int8+kv8 greedy agreement {agree:.2f} < {_QUANT_AGREE_MIN}:\n"
        f"  off  {base}\n  int8 {quant}")
    qprov = results["int8+kv8"][1]
    assert qprov.get("quant_agreement", 0) >= _QUANT_GUARDRAIL_MIN, qprov
    assert qprov.get("quant_logit_max_err") is not None, qprov
    assert results["off"][1].get("quant_agreement") is None, \
        "off must not pay (or stamp) the quant_report guardrail"
    cap = _kv_slot_capacity()
    factor = cap["int8+kv8"]["slots"] / max(1, cap["off"]["slots"])
    assert factor >= _QUANT_SLOT_FACTOR, (
        f"kv8 admitted only {factor:.2f}x slots at equal HBM: {cap}")
    record = {"bench": "serving_quant_smoke", "prompt_len": len(prompt),
              "max_new_tokens": 16, "agreement": round(agree, 4),
              "quant_agreement": qprov.get("quant_agreement"),
              "quant_logit_max_err": qprov.get("quant_logit_max_err"),
              "slots_off": cap["off"]["slots"],
              "slots_int8_kv8": cap["int8+kv8"]["slots"],
              "slot_factor": round(factor, 2),
              "kv_budget_bytes": cap["budget_bytes"]}
    print(json.dumps(record), flush=True)
    print(f"quant-smoke: int8+kv8 agreement={agree:.2f}, guardrail="
          f"{qprov.get('quant_agreement')}, slots "
          f"{cap['off']['slots']} -> {cap['int8+kv8']['slots']} "
          f"({factor:.1f}x) at equal HBM OK", flush=True)
    return 0


def run_slo_smoke(args):
    """ISSUE 15 assertion pass (CI slo-smoke leg), two servers:

    leg 1 — generous SLO + access log: every request meets the SLO, so
    goodput is 1.0 and violations stay 0; the ttft/tpot histograms
    populate; every response (with and without a client-supplied id)
    echoes ``x-request-id``; a long generation is OBSERVED mid-decode
    through /debug/requests and /debug/slots; after clean shutdown the
    JSONL access log holds exactly one line per completed request;

    leg 2 — unmeetable SLO: every finished request violates, so the
    per-dim violation counters move, burn rate hits 1.0, and once the
    burn window has MIN_BURN_SAMPLES the tiered shedder 429s /generate
    while /predict keeps answering 200."""
    import tempfile
    if not args.model.startswith("transformer_lm"):
        raise SystemExit("--sloSmoke needs --model transformer_lm "
                         "(exercises the decode path)")
    access = os.path.join(tempfile.mkdtemp(prefix="slo_smoke_"),
                          "access.jsonl")

    # ---- leg 1: generous SLO, everything good, in-flight visibility
    proc, url, log_lines = spawn_server(
        args, list(args.serveArg)
        + ["--reqTrace", "on", "--slo", "ttft=60000,tpot=60000",
           "--accessLog", access])
    n_done = 0
    try:
        st, _, hdr = _post_h(url + "/generate",
                             {"tokens": [1, 2, 3, 4],
                              "max_new_tokens": 4},
                             headers={"x-request-id": "slo-smoke-00"})
        assert st == 200, f"/generate -> {st}"
        assert hdr.get("x-request-id") == "slo-smoke-00", \
            f"client request id not echoed: {hdr}"
        n_done += 1
        for _ in range(9):
            st, _, hdr = _post_h(url + "/generate",
                                 {"tokens": [5, 6, 7, 8],
                                  "max_new_tokens": 6})
            assert st == 200, f"/generate -> {st}"
            assert hdr.get("x-request-id"), f"no minted rid echoed: {hdr}"
            n_done += 1

        # in-flight visibility: long generations polled mid-decode
        fired, seen_decode, seen_slots = [0], False, False
        def _long():
            fired[0] += 1
            _post_status(url + "/generate",
                         {"tokens": [9, 10, 11, 12],
                          "max_new_tokens": 48}, timeout=120)
        deadline = time.time() + 60
        while time.time() < deadline and not (seen_decode and seen_slots):
            threads = [threading.Thread(target=_long) for _ in range(2)]
            for t in threads:
                t.start()
            while any(t.is_alive() for t in threads):
                st, txt = _get_status(url + "/debug/requests")
                assert st == 200, f"/debug/requests -> {st}"
                snap = json.loads(txt)
                assert snap.get("enabled") is True, snap
                for r in snap.get("in_flight", []):
                    if (r.get("state") == "decode"
                            and r.get("tokens_out", 0) > 0):
                        seen_decode = True
                st, txt = _get_status(url + "/debug/slots")
                assert st == 200, f"/debug/slots -> {st}"
                slots = json.loads(txt)
                for k in ("slots", "slots_total", "slots_active",
                          "waiting", "kv"):
                    assert k in slots, f"/debug/slots missing {k}: {slots}"
                if slots.get("slots_active", 0) >= 1:
                    seen_slots = True
            for t in threads:
                t.join()
        assert seen_decode, "/debug/requests never showed a request " \
                            "mid-decode (state=decode, tokens_out>0)"
        assert seen_slots, "/debug/slots never showed an active slot"
        n_done += fired[0]

        _, page = _get(url + "/metrics")
        for name in ("ttft_ms", "tpot_ms", "itl_ms"):
            q = scrape_quantile(page, name, "0.5")
            assert q is not None and q > 0, \
                f"{name} histogram not populated"
        total = scrape_value(page, "slo_requests_total")
        good = scrape_value(page, "slo_good_total")
        viol = scrape_value(page, "slo_violations_total")
        assert total == n_done, (total, n_done)
        assert good == total and viol == 0, (good, viol, total)
        assert scrape_value(page, "slo_goodput_frac") == 1.0
        assert scrape_value(page, "requests_state_finished_total") \
            == n_done
        print(f"slo-smoke leg 1: {n_done} requests all good, goodput "
              f"1.0, mid-decode visible via /debug/* OK", flush=True)
    finally:
        _shutdown_clean(proc, log_lines)

    with open(access) as f:
        recs = [json.loads(l) for l in f if l.strip()]
    assert len(recs) == n_done, \
        f"access log has {len(recs)} lines, expected {n_done}"
    rids = [r["rid"] for r in recs]
    assert len(set(rids)) == len(rids), "duplicate rids in access log"
    assert "slo-smoke-00" in rids, rids
    for r in recs:
        for k in ("rid", "endpoint", "state", "status", "ttft_ms",
                  "tpot_ms", "queue_wait_ms", "prefill_ms", "decode_ms",
                  "total_ms", "tokens_out"):
            assert k in r, f"access-log line missing {k}: {r}"
        assert r["state"] == "finished" and r["status"] == 200, r
    print(f"slo-smoke: access log {len(recs)}/{n_done} lines, "
          f"unique rids OK", flush=True)

    # ---- leg 2: unmeetable SLO -> violations, burn, tiered shed
    proc, url, log_lines = spawn_server(
        args, list(args.serveArg)
        + ["--slo", "ttft=0.001,tpot=0.001,burn=0.5,window=16"])
    try:
        statuses = []
        for _ in range(14):
            st, _, hdr = _post_h(url + "/generate",
                                 {"tokens": [1, 2, 3],
                                  "max_new_tokens": 4})
            assert hdr.get("x-request-id"), hdr
            statuses.append(st)
        # burn gate: no shedding below MIN_BURN_SAMPLES finished requests
        assert all(s == 200 for s in statuses[:8]), statuses
        assert 429 in statuses, \
            f"SLO burn never tripped the shedder: {statuses}"
        assert statuses[-1] == 429, statuses
        args.endpoint, args.batch = "predict", 1
        st, _, _ = _post_h(url + "/predict", make_payload(args))
        assert st == 200, f"/predict under SLO shed -> {st} (tiered " \
                          "shed must spare predict)"
        _, page = _get(url + "/metrics")
        assert scrape_value(page, "slo_violations_total") >= 8
        assert scrape_value(page, "slo_ttft_violations_total") >= 8
        assert scrape_value(page, "slo_burn_rate") == 1.0
        assert scrape_value(page, "requests_state_shed_total") >= 1
        st, txt = _get_status(url + "/debug/requests")
        assert st == 200 and json.loads(txt)["slo"]["shedding"] is True
        print(f"slo-smoke leg 2: {statuses.count(429)} shed by SLO "
              f"burn, predict spared OK", flush=True)
    finally:
        _shutdown_clean(proc, log_lines)
    print("slo-smoke: all ISSUE 15 assertions OK", flush=True)
    return 0


def scrape_labelled(page, name, label="replica"):
    """All samples of a replica-labelled gauge/counter on the exposition
    page, keyed by label value — e.g. ``decode_worker_up{replica="1"} 1``
    -> {"1": 1.0}. Tolerates the bigdl_serving_ namespace prefix."""
    pat = re.compile(r'^(?:bigdl_serving_)?%s\{%s="([^"]+)"\} (\S+)$'
                     % (re.escape(name), re.escape(label)))
    out = {}
    for line in page.splitlines():
        m = pat.match(line)
        if m:
            try:
                out[m.group(1)] = float(m.group(2))
            except ValueError:
                pass
    return out


def run_tp_smoke(args):
    """ISSUE 16 multi-chip serving assertion pass (CI serving-tp-smoke):

    leg 1 — tensor parallel: the same tiny LM is served single-chip and
    --strategy tp:2 (virtual devices), both with speculative decoding,
    paged KV, and the prefix cache ON; a fixed greedy prompt, an exact
    repeat of it (prefix-cache page-copy hit), and a second prompt
    sharing its prefix must all come back BIT-IDENTICAL across the two
    topologies — sharding must never change which token argmax wins;

    leg 2 — data parallel: --strategy dp:2 brings two full engine
    stacks up behind one port; /readyz counts both live, /metrics
    carries per-replica labelled worker gauges AND the unlabelled fleet
    aggregates, routed requests come back replica-stamped in
    /debug/requests, and one SIGTERM takes the whole fleet down rc=0."""
    shared = list(range(1, 17))  # one full page at --kvPageTokens 16
    bodies = [
        {"tokens": shared + [21, 22], "max_new_tokens": 12},
        {"tokens": shared + [21, 22], "max_new_tokens": 12},  # prefix hit
        {"tokens": shared + [33, 34, 35], "max_new_tokens": 12},
    ]
    tp_extra = ["--kvPageTokens", "16", "--prefixCache",
                "--speculate", "3"]
    results = {}
    for strat in (None, "tp:2"):
        extra = list(args.serveArg) + tp_extra
        if strat:
            extra += ["--strategy", strat]
        proc, url, log_lines = spawn_server(args, extra)
        try:
            outs = []
            for body in bodies:
                st, out = _post(url + "/generate", body)
                assert st == 200, f"{strat or 'single'} /generate -> {st}"
                outs.append(out["tokens"])
            prov, page = scrape_provenance(url)
            results[strat] = (outs, prov)
        finally:
            _shutdown_clean(proc, log_lines)
    single, tp = results[None][0], results["tp:2"][0]
    for i, (a, b) in enumerate(zip(single, tp)):
        assert a == b, (f"tp:2 output diverged from single-chip on "
                        f"prompt {i}:\n  single {a}\n  tp:2   {b}")
    prov = results["tp:2"][1]
    assert prov.get("strategy") == "tp:2", prov
    assert prov.get("serving_tp") == 2, prov
    assert prov.get("serving_replicas") == 1, prov
    assert prov.get("n_devices", 0) >= 2, prov
    print(f"tp-smoke: tp:2 bit-identical to single-chip on "
          f"{len(bodies)} prompts (spec+paged+prefix-cache on) OK",
          flush=True)

    # ---- leg 2: dp:2 — fleet readiness, labelled + aggregate metrics
    proc, url, log_lines = spawn_server(
        args, list(args.serveArg)
        + ["--strategy", "dp:2", "--reqTrace", "on"])
    try:
        st, txt = _get(url + "/readyz")
        assert st == 200, f"/readyz -> {st}"
        ready = json.loads(txt)
        assert ready.get("replicas") == 2, ready
        assert ready.get("replicas_live") == 2, ready
        errs = [0]

        def _fire():
            st, _ = _post_status(url + "/generate",
                                 {"tokens": [1, 2, 3, 4, 5],
                                  "max_new_tokens": 6}, timeout=120)
            if st != 200:
                errs[0] += 1
        threads = [threading.Thread(target=_fire) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert errs[0] == 0, f"{errs[0]}/6 dp:2 generates failed"
        prov, page = scrape_provenance(url)
        assert prov.get("strategy") == "dp:2", prov
        assert prov.get("serving_replicas") == 2, prov
        up = scrape_labelled(page, "decode_worker_up")
        assert up.get("0") == 1.0 and up.get("1") == 1.0, \
            f"per-replica decode_worker_up gauges missing/down: {up}"
        assert scrape_value(page, "replicas") == 2, "no fleet gauge"
        assert scrape_value(page, "replicas_live") == 2, page[:200]
        for agg in ("kv_cache_bytes", "kv_pages_in_use",
                    "fleet_generated_tokens_total"):
            assert scrape_value(page, agg) is not None, \
                f"aggregate {agg} gauge missing"
        per_rep_tokens = scrape_labelled(page, "generated_tokens_total")
        assert sum(per_rep_tokens.values()) >= 6, per_rep_tokens
        st, txt = _get(url + "/debug/requests")
        assert st == 200, st
        recent = json.loads(txt).get("recent", [])
        stamped = [r for r in recent if "replica" in r]
        assert stamped, f"no replica-stamped records: {recent}"
        assert all(r["replica"] in (0, 1) for r in stamped), stamped
        print(f"tp-smoke: dp:2 fleet live, labelled+aggregate metrics, "
              f"{len(stamped)} replica-stamped records OK", flush=True)
    finally:
        _shutdown_clean(proc, log_lines)
    record = {"bench": "serving_tp_smoke", "bit_identical": True,
              "tp": 2, "dp_replicas": 2, "prompts": len(bodies)}
    print(json.dumps(record), flush=True)
    print("tp-smoke: all ISSUE 16 multi-chip assertions OK", flush=True)
    return 0


def run_dp_sweep(args):
    """dp QPS scaling sweep (the ISSUE 16 perf headline): run the same
    closed-loop /generate load against ``--strategy dp:N`` for each N
    in --dpSweep and report aggregate client-side QPS against the
    linear ideal (N x the per-replica rate of the first point). Each
    record carries the server's provenance and the per-replica
    generated-token split so the routing spread is visible.

    --assertScaling F turns the floor into a hard assertion
    (aggregate QPS >= F x linear at every N). Use that on real chips;
    virtual CPU devices share the same host cores, so CPU CI reports
    the curve without asserting it."""
    counts = [int(x) for x in args.dpSweep.split(",") if x]
    assert counts, "--dpSweep needs at least one replica count"
    args.endpoint = "generate"
    base_conc = args.concurrency
    records = []
    for n in counts:
        extra = list(args.serveArg) + ["--strategy", f"dp:{n}"]
        proc, url, log_lines = spawn_server(args, extra)
        # keep every replica busy: concurrency scales with the fleet
        args.concurrency = max(base_conc, 4 * n)
        try:
            res = closed_loop(url, args)
            assert res["errors"] == 0, f"dp:{n} bench errors: {res}"
            prov, page = scrape_provenance(url)
            assert prov.get("serving_replicas") == n, prov
            rec = {"bench": "serving_dp_sweep", "replicas": n,
                   "qps": res["rps"],
                   "tokens_per_second": res["tokens_per_second"],
                   "concurrency": args.concurrency,
                   "requests": args.requests,
                   "latency_ms": res["latency_ms"],
                   "per_replica_tokens": scrape_labelled(
                       page, "generated_tokens_total"),
                   "provenance": prov}
        finally:
            args.concurrency = base_conc
            _shutdown_clean(proc, log_lines)
        records.append(rec)
        print(json.dumps(rec), flush=True)
    per_replica0 = records[0]["qps"] / records[0]["replicas"]
    summary = {"bench": "serving_dp_sweep_summary",
               "counts": counts,
               "qps": [r["qps"] for r in records],
               "scaling_vs_linear": [
                   round(r["qps"] / (per_replica0 * r["replicas"]), 3)
                   for r in records]}
    print(json.dumps(summary), flush=True)
    if args.assertScaling is not None:
        floor = args.assertScaling
        for n, frac in zip(counts, summary["scaling_vs_linear"]):
            assert frac >= floor, \
                (f"dp:{n} aggregate QPS is {frac:.2f}x linear, below "
                 f"the {floor}x floor")
        print(f"dp-sweep: all points >= {floor}x linear OK", flush=True)
    return 0


def _companion_keys():
    """The shared provenance companion-key list (cli/provenance.py),
    loaded by file path so the bench parent never imports the bigdl_tpu
    package (whose import pulls jax; see bench.py for the failure mode).
    """
    import importlib.util
    path = os.path.join(REPO, "bigdl_tpu", "cli", "provenance.py")
    try:
        spec = importlib.util.spec_from_file_location("_sb_prov", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return tuple(mod.PROVENANCE_COMPANION_KEYS)
    except Exception:
        return ("conv_layouts", "conv_geom", "autotune", "bn_fused",
                "pipeline", "stall_frac", "data_wait_s")


def _stream_generate(url, body, read_frames=None, timeout=120.0):
    """POST /generate with ``stream: true`` and parse the SSE frames off
    the chunked response. Returns ``(status, frames, t_first_byte_s,
    t_done_s, conn)`` — when ``read_frames`` is set, returns after that
    many token frames WITHOUT closing the connection (``conn`` is live;
    the disconnect leg closes it mid-decode)."""
    import http.client
    from urllib.parse import urlparse
    u = urlparse(url)
    conn = http.client.HTTPConnection(u.hostname, u.port, timeout=timeout)
    t0 = time.perf_counter()
    conn.request("POST", "/generate",
                 json.dumps({**body, "stream": True}).encode(),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    if resp.status != 200:
        try:
            out = json.loads(resp.read() or b"{}")
        except ValueError:
            out = {}
        conn.close()
        return resp.status, out, None, None, None
    assert resp.getheader("Content-Type", "").startswith(
        "text/event-stream"), resp.getheader("Content-Type")
    frames, t_first, buf = [], None, b""
    while True:
        b1 = resp.read(1)  # http.client undoes the chunked framing
        if not b1:
            break
        if t_first is None:
            t_first = time.perf_counter() - t0
        buf += b1
        while b"\n\n" in buf:
            raw, buf = buf.split(b"\n\n", 1)
            if raw.startswith(b"data: "):
                frames.append(json.loads(raw[len(b"data: "):]))
        if read_frames is not None and len(
                [f for f in frames if "tokens" in f]) >= read_frames:
            return resp.status, frames, t_first, None, conn
        if frames and frames[-1].get("done"):
            break
    t_done = time.perf_counter() - t0
    conn.close()
    return resp.status, frames, t_first, t_done, None


def run_stream_smoke(args):
    """ISSUE 18 streaming assertion pass (CI throughput-smoke leg), one
    server with the full composition on — speculative decoding, paged
    KV, lifecycle tracing, SLOs:

    leg 1 — bit-identity: for >= 3 fixed greedy prompts the streamed
    token frames, concatenated, equal the buffered /generate response
    exactly (speculative path included: only ACCEPTED tokens are ever
    emitted), and the final frame carries done/prompt_len/tokens_out;

    leg 2 — felt TTFT: the first SSE byte lands well before the
    buffered response for the same prompt completes, and the
    server-side ttft_ms histogram (stamped at first-byte-out, feeding
    --slo) is populated;

    leg 3 — disconnect: a client that walks away mid-stream gets its
    slot cancelled — decode_cancelled_total moves, kv_pages_in_use
    returns to the pre-request baseline (no leaked page reservations),
    and the request lands terminal state ``closed`` in /debug/requests.
    """
    extra = (list(args.serveArg)
             + ["--kvPageTokens", "16", "--speculate", "3",
                "--reqTrace", "on", "--slo", "ttft=60000,tpot=60000"])
    prompts = [list(range(1, 9)), list(range(5, 21)),
               [2, 3, 5, 7, 11, 13]]
    proc, url, log_lines = spawn_server(args, extra)
    try:
        # ---- leg 1: streamed == buffered, per prompt, bit for bit
        first_ms = full_ms = None
        for i, prompt in enumerate(prompts):
            body = {"tokens": prompt, "max_new_tokens": 24,
                    "temperature": 0.0}
            t0 = time.perf_counter()
            st, ref = _post(url + "/generate", body)
            buffered_s = time.perf_counter() - t0
            assert st == 200, f"buffered /generate -> {st}"
            st, frames, t_first, t_done, _ = _stream_generate(url, body)
            assert st == 200, f"streamed /generate -> {st}"
            toks = [t for f in frames if "tokens" in f
                    for t in f["tokens"]]
            assert toks == ref["tokens"], (
                f"streamed output diverged on prompt {i}:\n"
                f"  buffered {ref['tokens']}\n  streamed {toks}")
            final = frames[-1]
            assert final.get("done") is True, final
            assert final.get("prompt_len") == len(prompt), final
            assert final.get("tokens_out") == len(toks), final
            if i == 0:
                # ---- leg 2: first byte beats the full round trip
                assert t_first < t_done, (t_first, t_done)
                assert t_first < buffered_s, (
                    f"first SSE byte ({t_first * 1000:.1f} ms) not ahead "
                    f"of the buffered response ({buffered_s * 1000:.1f} "
                    f"ms)")
                first_ms = round(t_first * 1000, 2)
                full_ms = round(buffered_s * 1000, 2)
        _, page = _get(url + "/metrics")
        ttft = scrape_quantile(page, "ttft_ms", "0.5")
        assert ttft is not None and ttft > 0, \
            "ttft_ms histogram empty — first-byte stamp not feeding SLOs"
        print(f"stream-smoke: {len(prompts)} prompts bit-identical "
              f"(speculate on), first byte {first_ms} ms vs buffered "
              f"{full_ms} ms, ttft_ms populated OK", flush=True)

        # ---- leg 3: mid-stream disconnect frees the slot + pages
        _, page = _get(url + "/metrics")
        base_pages = scrape_value(page, "kv_pages_in_use") or 0
        st, frames, _, _, conn = _stream_generate(
            url, {"tokens": list(range(1, 9)), "max_new_tokens": 48,
                  "temperature": 0.0}, read_frames=1)
        assert st == 200 and conn is not None, (st, frames)
        conn.close()  # walk away mid-decode
        deadline = time.time() + 60
        cancelled = pages_ok = False
        while time.time() < deadline:
            _, page = _get(url + "/metrics")
            cancelled = (scrape_value(page,
                                      "decode_cancelled_total") or 0) >= 1
            pages_ok = (scrape_value(page, "kv_pages_in_use")
                        or 0) <= base_pages
            if cancelled and pages_ok:
                break
            time.sleep(0.2)
        assert cancelled, "decode_cancelled_total never moved after " \
                          "client disconnect"
        assert pages_ok, "kv_pages_in_use never returned to baseline " \
                         "(leaked page reservations)"
        st, txt = _get_status(url + "/debug/requests")
        assert st == 200, st
        recent = json.loads(txt).get("recent", [])
        closed = [r for r in recent if r.get("state") == "closed"]
        assert closed, f"no terminal-state closed record: {recent}"
        # a fresh request still runs on the freed slot
        st, out = _post(url + "/generate",
                        {"tokens": [1, 2, 3], "max_new_tokens": 4})
        assert st == 200 and out["tokens"], (st, out)
        print("stream-smoke: disconnect cancelled mid-decode, pages "
              "freed, state=closed, slot reusable OK", flush=True)

        prov, _ = scrape_provenance(url)
        record = {"bench": "serving_stream_smoke",
                  "prompts": len(prompts), "bit_identical": True,
                  "first_byte_ms": first_ms, "buffered_ms": full_ms,
                  "server_ttft_p50_ms": ttft,
                  "disconnect_freed_pages": True,
                  **{k: prov[k] for k in _companion_keys()
                     if k in (prov or {})}}
        print(json.dumps(record), flush=True)
    finally:
        _shutdown_clean(proc, log_lines)
    print("stream-smoke: all ISSUE 18 streaming assertions OK",
          flush=True)
    return 0


def _shutdown_clean(proc, log_lines):
    proc.send_signal(signal.SIGTERM)
    try:
        rc = proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise SystemExit("server ignored SIGTERM")
    assert rc == 0, f"server exit code {rc} after SIGTERM"
    assert any("serving shutdown clean" in l for l in log_lines), \
        "missing clean-shutdown marker in server log"


def run_chaos_smoke(args):
    """ISSUE 6 serving-hardening assertions (CI chaos-smoke job):

    leg 1 — deadline expiry: a request with deadline_ms=0 is dropped
    BEFORE compute and answered 504 (distinct from admission 429),
    while normal requests keep answering 200;

    leg 2 — worker kill: a --faultPlan kills the batcher worker on its
    2nd flush; the in-flight request errors (500), the NEXT submit
    fast-fails 503 in well under a second (no hanging until client
    timeout), /readyz flips 503 while /healthz stays 200, the fault
    counters land in /metrics, and SIGTERM still shuts down rc=0."""
    rng_payload = make_payload(args)

    # ---- leg 1: deadline expiry -> 504, healthy path unaffected
    proc, url, log_lines = spawn_server(args, list(args.serveArg))
    try:
        st, _ = _post_status(url + "/predict", rng_payload)
        assert st == 200, f"healthy predict -> {st}"
        st, body = _post_status(url + "/predict",
                                {**rng_payload, "deadline_ms": 0})
        assert st == 504, f"expired-deadline predict -> {st} ({body})"
        assert "deadline" in body.get("error", ""), body
        st, _ = _post_status(url + "/predict", rng_payload)
        assert st == 200, f"predict after 504 -> {st}"
        st, _ = _get_status(url + "/readyz")
        assert st == 200, f"/readyz (healthy) -> {st}"
        print("chaos-smoke: deadline expiry -> 504, healthy path OK",
              flush=True)
    finally:
        _shutdown_clean(proc, log_lines)

    # ---- leg 2: worker kill -> fast 503 + readiness flip
    proc, url, log_lines = spawn_server(
        args, list(args.serveArg)
        + ["--faultPlan", "worker_kill@infer:2", "--watchdogStallS", "5"])
    try:
        st, _ = _post_status(url + "/predict", rng_payload)
        assert st == 200, f"predict before kill -> {st}"
        st, body = _post_status(url + "/predict", rng_payload)
        assert st == 500, f"killed-flush predict -> {st} ({body})"
        t0 = time.perf_counter()
        st, body = _post_status(url + "/predict", rng_payload)
        dt = time.perf_counter() - t0
        assert st == 503, f"post-kill predict -> {st} ({body})"
        assert dt < 2.0, f"dead-worker 503 took {dt:.2f}s (not fast)"
        st, _ = _get_status(url + "/readyz")
        assert st == 503, f"/readyz (dead worker) -> {st}"
        st, _ = _get_status(url + "/healthz")
        assert st == 200, f"/healthz must stay live, got {st}"
        _, page = _get(url + "/metrics")
        for needle in ("batcher_worker_up 0",
                       "requests_worker_dead_total"):
            assert needle in page, f"metrics missing {needle!r}"
        print(f"chaos-smoke: worker kill -> 500 then fast 503 "
              f"({dt * 1000:.0f} ms), /readyz 503, /healthz 200 OK",
              flush=True)
    finally:
        _shutdown_clean(proc, log_lines)

    # ---- leg 3 (ISSUE 20 satellite): rids must survive the router —
    # 5xx responses produced BEHIND a proxy hop (and by the router
    # itself once every worker is gone) still echo x-request-id
    proc, url, log_lines = spawn_fleet(
        args, list(args.serveArg)
        + ["--faultPlan", "worker_kill@infer:2", "--watchdogStallS", "5",
           "--fleetRestartBudget", "0"], k=1)
    try:
        st, _, hdr = _post_h(url + "/predict", rng_payload,
                             headers={"x-request-id": "chaos-hop-00"})
        assert st == 200, f"fleet predict -> {st}"
        assert hdr.get("x-request-id") == "chaos-hop-00", hdr
        # deadline expiry 504 answered by the WORKER, relayed by the
        # router (dropped before compute, so no infer flush is spent)
        st, body, hdr = _post_h(url + "/predict",
                                {**rng_payload, "deadline_ms": 0},
                                headers={"x-request-id": "chaos-hop-04"})
        assert st == 504, f"proxied expired-deadline -> {st} ({body})"
        assert hdr.get("x-request-id") == "chaos-hop-04", \
            f"rid lost on proxied 504: {hdr}"
        # 2nd infer flush kills the batcher worker thread: 500 then a
        # fast 503, both proxied, both rid-stamped
        st, body, hdr = _post_h(url + "/predict", rng_payload,
                                headers={"x-request-id": "chaos-hop-05"})
        assert st == 500, f"proxied killed-flush -> {st} ({body})"
        assert hdr.get("x-request-id") == "chaos-hop-05", \
            f"rid lost on proxied 500: {hdr}"
        st, body, hdr = _post_h(url + "/predict", rng_payload,
                                headers={"x-request-id": "chaos-hop-03"})
        assert st == 503, f"proxied dead-worker -> {st} ({body})"
        assert hdr.get("x-request-id") == "chaos-hop-03", \
            f"rid lost on proxied 503: {hdr}"
        # now remove the PROCESS: restart budget 0 means the router
        # gives the slot up, and its OWN no-live-worker 503 (and the
        # /readyz flip) must still carry the rid
        st, body = _get(url + "/debug/fleet")
        pid = json.loads(body)["workers"][0]["pid"]
        os.kill(pid, signal.SIGKILL)
        deadline = time.time() + 30
        while time.time() < deadline:
            st, body, hdr = _post_h(url + "/predict", rng_payload,
                                    headers={"x-request-id":
                                             "chaos-hop-99"})
            assert hdr.get("x-request-id") == "chaos-hop-99", \
                f"rid lost on router {st}: {hdr}"
            if st == 503 and "no live fleet worker" in \
                    body.get("error", ""):
                break
            time.sleep(0.5)
        else:
            raise AssertionError("router never originated its own 503")
        st, _ = _get_status(url + "/readyz")
        assert st == 503, f"/readyz with zero workers -> {st}"
        print("chaos-smoke: x-request-id survives the proxy hop on "
              "504/500/503 + router-originated 503 OK", flush=True)
    finally:
        _shutdown_clean(proc, log_lines)
    print("chaos-smoke: all serving-hardening assertions OK", flush=True)
    return 0


def spawn_fleet(args, extra, k=2):
    """Launch `bigdl-tpu serve --fleet K` (the ISSUE 20 router + K
    worker processes) on an ephemeral port. Same contract as
    spawn_server, but the port is parsed from the ROUTER's banner —
    worker banners arrive first, prefixed ``[worker N]``, and must not
    win."""
    cmd = [sys.executable, "-m", "bigdl_tpu.cli.main", "serve",
           args.model, "--port", "0", "--fleet", str(k)]
    if args.ckpt:
        cmd += ["--model", args.ckpt]
    else:
        cmd += ["--randomInit"]
    if args.platform:
        cmd += ["--platform", args.platform]
    if args.model.startswith("transformer_lm") and (args.smoke
                                                    or not args.ckpt):
        cmd += _SMOKE_LM
    cmd += extra
    proc = subprocess.Popen(cmd, cwd=REPO, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    lines, port = [], None
    port_re = re.compile(r"^serving .+ fleet on http://[^:]+:(\d+)")
    ready = threading.Event()

    def _reader():
        nonlocal port
        for line in proc.stdout:
            lines.append(line.rstrip())
            m = port_re.match(lines[-1])
            if m:
                port = int(m.group(1))
                ready.set()
        ready.set()

    threading.Thread(target=_reader, daemon=True).start()
    if not ready.wait(timeout=600) or port is None:
        proc.kill()
        raise SystemExit("fleet router never reported its port; log "
                         "tail:\n" + "\n".join(lines[-30:]))
    return proc, f"http://127.0.0.1:{port}", lines


def _make_lm_ckpt(path, seed=42):
    """A version-stamped smoke-LM checkpoint (same dims as _SMOKE_LM)
    for the rolling-swap leg — different seed, visibly different
    weights."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import jax

    from bigdl_tpu import models
    from bigdl_tpu.utils.file import save_pytree
    m = models.transformer_lm(64, d_model=32, num_layers=2, num_heads=2,
                              max_len=64)
    save_pytree({"params": m.init(jax.random.PRNGKey(seed)),
                 "mod_state": m.init_state()},
                os.path.join(path, "model.1"))
    return path


def run_fleet_smoke(args):
    """ISSUE 20 fleet assertions (CI fleet-smoke job), one K=2 fleet:

    leg 1 — the router front door: /generate proxied with the client
    rid echoed and x-model-version stamped; /metrics carries the
    router's bigdl_fleet series plus worker-labelled re-exports and
    summed aggregates; /readyz 200.

    leg 2 — elasticity: kill -9 one worker; /readyz stays 200 and
    /generate keeps answering on the survivor throughout; the killed
    worker is restarted within the supervisor budget and rejoins
    rotation (restarts >= 1, routable again).

    leg 3 — zero-downtime rolling swap: under continuous traffic, POST
    /admin/reload to a version-B checkpoint; every response during the
    swap is 200 (no 5xx window), both versions are observed across the
    window, and afterwards every response reports vB."""
    import tempfile

    ckpt_b = _make_lm_ckpt(os.path.join(
        tempfile.mkdtemp(prefix="fleet_smoke_"), "ck_vB"))
    proc, url, log_lines = spawn_fleet(
        args, list(args.serveArg) + ["--modelVersion", "vA"], k=2)
    gen = {"tokens": [3, 1, 4], "max_new_tokens": 4}
    try:
        # ---- leg 1: router basics
        st, _, hdr = _post_h(url + "/generate", gen,
                             headers={"x-request-id": "fleet-smoke-00"})
        assert st == 200, f"proxied generate -> {st}"
        assert hdr.get("x-request-id") == "fleet-smoke-00", hdr
        assert hdr.get("x-model-version") == "vA", hdr
        st, _ = _get_status(url + "/readyz")
        assert st == 200, f"/readyz -> {st}"
        _, page = _get(url + "/metrics")
        for needle in ("bigdl_fleet_workers 2",
                       "bigdl_fleet_requests_generate_total",
                       "# fleet aggregate", 'worker="0"', 'worker="1"'):
            assert needle in page, f"fleet metrics missing {needle!r}"
        print("fleet-smoke: router proxy + rid/version echo + "
              "aggregated metrics OK", flush=True)

        # ---- leg 2: kill one worker; serve through it, expect rejoin
        _, body = _get(url + "/debug/fleet")
        pid = json.loads(body)["workers"][0]["pid"]
        os.kill(pid, signal.SIGKILL)
        deadline = time.time() + 120
        rejoined = False
        while time.time() < deadline:
            st, _ = _get_status(url + "/readyz")
            assert st == 200, "/readyz flipped 503 with a live survivor"
            st, _, _ = _post_h(url + "/generate", gen, timeout=60)
            assert st == 200, f"generate during restart -> {st}"
            _, body = _get(url + "/debug/fleet")
            w0 = json.loads(body)["workers"][0]
            if w0["routable"] and w0["restarts"] >= 1:
                rejoined = True
                break
            time.sleep(1.0)
        assert rejoined, "killed worker never rejoined rotation"
        print("fleet-smoke: kill -9 -> restart + rejoin, /readyz 200 "
              "throughout OK", flush=True)

        # ---- leg 3: rolling swap under traffic, zero 5xx window
        results = []
        stop = threading.Event()

        def _traffic():
            while not stop.is_set():
                s, _, h = _post_h(url + "/generate", gen, timeout=60)
                results.append((s, h.get("x-model-version")))
                time.sleep(0.05)

        t = threading.Thread(target=_traffic, daemon=True)
        t.start()
        time.sleep(1.0)
        st, body, _ = _post_h(url + "/admin/reload",
                              {"checkpoint": ckpt_b, "version": "vB"},
                              timeout=600)
        assert st == 200, f"/admin/reload -> {st} ({body})"
        assert all(r["status"] == "reloaded" for r in body["workers"]), \
            body
        time.sleep(1.0)
        stop.set()
        t.join(60)
        statuses = sorted({s for s, _ in results})
        versions = sorted({v for _, v in results})
        assert statuses == [200], \
            f"5xx window during rolling swap: {statuses}"
        assert versions == ["vA", "vB"], \
            f"expected both versions across the swap, saw {versions}"
        st, _, hdr = _post_h(url + "/generate", gen)
        assert st == 200 and hdr.get("x-model-version") == "vB", hdr
        record = {"bench": "serving_fleet_smoke", "workers": 2,
                  "swap_requests": len(results), "swap_5xx": 0,
                  "versions_observed": versions}
        print(json.dumps(record), flush=True)
        print(f"fleet-smoke: rolling swap vA->vB with zero 5xx over "
              f"{len(results)} in-flight requests OK", flush=True)
    finally:
        _shutdown_clean(proc, log_lines)
    print("fleet-smoke: all ISSUE 20 fleet assertions OK", flush=True)
    return 0


def main(argv=None):
    p = argparse.ArgumentParser("serving_bench")
    p.add_argument("--model", default="lenet5",
                   help="perf-zoo name (payload geometry + spawn target)")
    p.add_argument("--url", default=None,
                   help="bench an already-running server instead of "
                        "spawning one")
    p.add_argument("--ckpt", default=None,
                   help="checkpoint for the spawned server (default "
                        "--randomInit)")
    p.add_argument("--endpoint", default="predict",
                   choices=["predict", "generate"])
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--concurrency", type=int, default=4)
    p.add_argument("--batch", type=int, default=1,
                   help="rows per /predict request")
    p.add_argument("--promptLen", type=int, default=16)
    p.add_argument("--maxNewTokens", type=int, default=16)
    p.add_argument("--stream", action="store_true",
                   help="drive the load through the chunked-SSE "
                        "/generate path instead of buffered responses; "
                        "adds client-side first_byte_ms percentiles to "
                        "the JSON line (the streamed half of the "
                        "streamed-vs-buffered TTFT/TPOT A/B)")
    p.add_argument("--seq", type=int, default=None)
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    p.add_argument("--smoke", action="store_true",
                   help="assertion pass + clean-shutdown check (CI)")
    p.add_argument("--specSmoke", action="store_true",
                   help="speculative-decoding assertion pass (ISSUE 14):"
                        " --speculate 4 /generate bit-identical to "
                        "--speculate 0, non-zero accept rate, >1 "
                        "accepted-tokens/step (spawns its own servers)")
    p.add_argument("--quantSmoke", action="store_true",
                   help="quantized-serving assertion pass (ISSUE 17): "
                        "--quantize int8+kv8 /generate agrees with "
                        "--quantize off, quantize + measured guardrail "
                        "stamped in provenance, and 8-bit KV pools "
                        "admit >= 2x the slots at equal pool bytes "
                        "(spawns its own servers)")
    p.add_argument("--sloSmoke", action="store_true",
                   help="per-request observability assertion pass "
                        "(ISSUE 15): TTFT/TPOT histograms populate, "
                        "goodput/violation counters move, SLO burn "
                        "trips the tiered shedder (generate 429s, "
                        "predict spared), one access-log line per "
                        "request, x-request-id echoed, /debug/requests "
                        "shows requests mid-decode (spawns its own "
                        "servers)")
    p.add_argument("--chaosSmoke", action="store_true",
                   help="serving-hardening assertion pass (ISSUE 6): "
                        "deadline-expiry 504, worker-kill fast 503 + "
                        "watchdog readiness flip, and x-request-id "
                        "echo on 503/504s routed through a fleet "
                        "proxy hop (spawns its own servers)")
    p.add_argument("--fleetSmoke", action="store_true",
                   help="serving-fleet assertion pass (ISSUE 20): "
                        "2-worker fleet behind the router — proxied "
                        "rid/version echo, worker-labelled + summed "
                        "/metrics, kill -9 restart/rejoin with /readyz "
                        "200 throughout, and a rolling /admin/reload "
                        "with zero 5xx while both x-model-versions are "
                        "observed (spawns its own fleet)")
    p.add_argument("--streamSmoke", action="store_true",
                   help="streaming /generate assertion pass (ISSUE 18): "
                        "streamed SSE tokens bit-identical to buffered "
                        "(speculate+paged KV on), first byte ahead of "
                        "the buffered round trip with ttft_ms fed at "
                        "first-byte-out, and a mid-stream disconnect "
                        "cancels the slot + frees KV pages with "
                        "terminal state closed (spawns its own server)")
    p.add_argument("--tpSmoke", action="store_true",
                   help="multi-chip serving assertion pass (ISSUE 16): "
                        "--strategy tp:2 /generate bit-identical to "
                        "single-chip (speculate + paged KV + prefix "
                        "cache on), dp:2 fleet readiness + per-replica "
                        "labelled metrics + aggregates + replica-"
                        "stamped traces (spawns its own servers on "
                        "virtual devices)")
    p.add_argument("--dpSweep", default=None, metavar="N,N,...",
                   help="QPS scaling sweep over --strategy dp:N replica"
                        " counts, e.g. 1,2,4 (ISSUE 16 perf headline); "
                        "emits one record per point + a summary with "
                        "scaling_vs_linear")
    p.add_argument("--assertScaling", type=float, default=None,
                   metavar="FRAC",
                   help="with --dpSweep: assert aggregate QPS >= FRAC x"
                        " linear at every point (use on real chips; "
                        "CPU replicas share host cores)")
    p.add_argument("--strategy", default=None, metavar="SPEC",
                   help="forwarded to the spawned serve CLI: tp[:K], "
                        "dp[:N], or dp:N+tp:K (ISSUE 16); spawns with "
                        "virtual devices on the CPU platform")
    p.add_argument("--serveArg", action="append", default=[],
                   metavar="ARG",
                   help="extra flag forwarded to the spawned serve CLI "
                        "(repeatable), e.g. --serveArg=--fusedBN "
                        "--serveArg=apply")
    args = p.parse_args(argv)

    if args.chaosSmoke:
        args.endpoint, args.batch = "predict", 2
        return run_chaos_smoke(args)
    if args.fleetSmoke:
        args.endpoint = "generate"
        return run_fleet_smoke(args)
    if args.specSmoke:
        return run_spec_smoke(args)
    if args.quantSmoke:
        return run_quant_smoke(args)
    if args.sloSmoke:
        return run_slo_smoke(args)
    if args.streamSmoke:
        return run_stream_smoke(args)
    if args.tpSmoke:
        return run_tp_smoke(args)
    if args.dpSweep:
        return run_dp_sweep(args)

    proc = None
    if args.url:
        url = args.url.rstrip("/")
    else:
        extra = list(args.serveArg)
        if args.strategy:
            extra += ["--strategy", args.strategy]
        # --smoke also asserts server-vs-client TTFT/TPOT agreement
        # (ISSUE 15 satellite), which needs the lifecycle tracer on the
        # spawned server; an explicit --serveArg=--reqTrace wins
        if args.smoke and "--reqTrace" not in extra:
            extra += ["--reqTrace", "on"]
        proc, url, log_lines = spawn_server(args, extra)
    try:
        if args.smoke:
            run_smoke(url, args)
        else:
            res = closed_loop(url, args)
            prov, page = scrape_provenance(url)
            res["provenance"] = prov
            if args.endpoint == "generate":
                res["spec"] = scrape_spec_columns(page)
                # server-side request-latency columns next to the
                # client-side quantiles (None when --reqTrace off)
                res["server_latency_ms"] = scrape_server_latency(page)
                # quant columns (ISSUE 17): mode + measured guardrail
                # ride every /generate record, "off" included, so A/B
                # lines are self-describing
                res["quant"] = {
                    "quantize": (prov or {}).get("quantize", "off"),
                    "agreement": (prov or {}).get("quant_agreement"),
                    "logit_max_err":
                        (prov or {}).get("quant_logit_max_err"),
                }
            print(json.dumps(res), flush=True)
    finally:
        if proc is not None:
            proc.send_signal(signal.SIGTERM)
            try:
                rc = proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                raise SystemExit("server ignored SIGTERM")
            if args.smoke:
                assert rc == 0, f"server exit code {rc} after SIGTERM"
                assert any("serving shutdown clean" in l
                           for l in log_lines), \
                    "missing clean-shutdown marker in server log"
                print("smoke: clean shutdown OK (rc=0)", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
