#!/usr/bin/env bash
# End-to-end example driver (reference scripts/run.example.sh — downloads
# data and spark-submits a model's Train class; here: checks for the
# dataset locally and runs the corresponding CLI module).
#
# Usage: ./scripts/run_example.sh <lenet|vgg|resnet|inception|rnn|autoencoder|perf> <data_dir> [extra args...]
set -euo pipefail

MODEL="${1:?usage: run_example.sh <model> <data_dir> [args...]}"
DATA="${2:-./data}"
shift
[ "$#" -gt 0 ] && shift

cd "$(dirname "$0")/.."

case "$MODEL" in
  lenet)
    exec python -m bigdl_tpu.cli.lenet train -f "$DATA" "$@" ;;
  vgg)
    exec python -m bigdl_tpu.cli.vgg train -f "$DATA" "$@" ;;
  resnet)
    exec python -m bigdl_tpu.cli.resnet train -f "$DATA" "$@" ;;
  inception)
    exec python -m bigdl_tpu.cli.inception train -f "$DATA" "$@" ;;
  rnn)
    exec python -m bigdl_tpu.cli.rnn train -f "$DATA" "$@" ;;
  autoencoder)
    exec python -m bigdl_tpu.cli.autoencoder train -f "$DATA" "$@" ;;
  transformerlm)
    exec python -m bigdl_tpu.cli.transformerlm train -f "$DATA" "$@" ;;
  textclassification)
    exec python -m bigdl_tpu.cli.textclassification -f "$DATA" "$@" ;;
  loadmodel)
    exec python -m bigdl_tpu.cli.loadmodel -f "$DATA" "$@" ;;
  predict)
    exec python -m bigdl_tpu.cli.predict -f "$DATA" "$@" ;;
  perf)
    exec python -m bigdl_tpu.cli.perf "$@" ;;
  *)
    echo "unknown model: $MODEL" >&2; exit 1 ;;
esac
