#!/usr/bin/env bash
# Round-3 TPU measurement sweep: runs every chip-dependent datapoint and
# appends JSON/log lines to $OUT (default /tmp/tpu_capture.log). Each step
# has its own timeout so one hang doesn't lose the rest.
set -uo pipefail
cd "$(dirname "$0")/.."
OUT="${OUT:-/tmp/tpu_capture.log}"
# mirror into the repo on every step so a capture that fires after the
# builder's last turn still gets committed by the round driver
trap 'cp -f "$OUT" TPU_CAPTURE_r03.log 2>/dev/null || true' EXIT

step() {
  local name="$1" tmo="$2"; shift 2
  echo "=== $name ($(date -u +%H:%M:%SZ))" | tee -a "$OUT"
  timeout "$tmo" "$@" 2>&1 | tail -30 | tee -a "$OUT"
  echo "=== end $name rc=$?" | tee -a "$OUT"
  cp -f "$OUT" TPU_CAPTURE_r03.log 2>/dev/null || true
}

# MFU trajectory (b64..b512) + variants
for B in 64 128 256 512; do
  step "perf_resnet50_b$B" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b "$B" -i 20 --dataType random
done
step "perf_resnet50_s2d_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50_s2d -b 128 -i 20 --dataType random
step "perf_resnet50_inner10_b128" 900 python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 4 --innerSteps 10 --dataType random
step "perf_transformer_lm_b32" 900 python -m bigdl_tpu.cli.perf -m transformer_lm -b 32 -i 10 --dataType random
step "perf_transformer_lm_rope_b32" 900 python -m bigdl_tpu.cli.perf -m transformer_lm_rope -b 32 -i 10 --dataType random

# flash kernel: compiled tests + microbench
step "pytest_tpu_marked" 1200 env BIGDL_TPU_TESTS=1 python -m pytest tests/ -m tpu -q
step "flash_bench" 1800 python scripts/flash_bench.py 4 8 64

# train-from-storage (decode+augment+transfer in the loop)
step "bench_pipe" 1800 python bench.py resnet50_pipe 128 20

# convergence: LeNet on the MNIST-analog through the user-facing script
if [ ! -f /tmp/synth_mnist_full/train-images-idx3-ubyte ]; then
  step "make_synth_mnist" 1200 python scripts/make_synth_mnist.py /tmp/synth_mnist_full 20000 4000
fi
step "lenet_convergence" 1800 ./scripts/run_example.sh lenet /tmp/synth_mnist_full -b 128 --maxEpoch 20 --learningRate 0.1

# the official bench line last (resnet50 + companions)
step "bench_main" 2400 python bench.py

echo "capture complete -> $OUT"
