"""Conv backward layout probe (PERF.md §2: the backward runs at ~38% MFU
vs the forward's 46% — this isolates WHERE).

For each representative ResNet-50 conv shape, times the three conv passes
separately (forward, input-grad, filter-grad) in bf16, for both NHWC and
NCHW activation layouts — and, where the shape is exactly a matmul (1x1,
stride 1), for the GEMM spelling (``dot_general`` over flattened pixels,
the ops/conv2d.py round-8 layout choice). XLA picks internal layouts per
op; what the framework controls is the activation layout (or the matmul
spelling) it hands XLA — if NCHW or GEMM wins some pass for some shape
class, the per-geometry policy (ops/conv2d.py, ISSUE 3) is the lever.

Every row carries its geometry fields (kh/kw/stride/cin/cout/groups/
dilation/dtype), so ``scripts/apply_conv_probe.py --geom`` can turn the
JSONL directly into per-geometry decisions; rows from older probes
(name-only) are mapped through ops/conv2d.LEGACY_PROBE_SHAPES.

Usage: python scripts/conv_bwd_probe.py [iters]   # one JSON line per cell
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from bigdl_tpu.utils.flops import conv_unit_flops  # noqa: E402

# (name, batch, h, w, cin, cout, k, stride)
SHAPES = [
    ("stem7x7s2", 128, 224, 224, 3, 64, 7, 2),
    ("s1_3x3", 128, 56, 56, 64, 64, 3, 1),
    ("s2_3x3", 128, 28, 28, 128, 128, 3, 1),
    ("s3_3x3", 128, 14, 14, 256, 256, 3, 1),
    ("s4_3x3", 128, 7, 7, 512, 512, 3, 1),
    ("s2_1x1", 128, 28, 28, 512, 128, 1, 1),
    # the two remaining 1x1 families (~half of ResNet-50's FLOPs are
    # 1x1 GEMMs): bottleneck expand and reduce at stage-1 width
    ("s1_1x1_expand", 128, 56, 56, 64, 256, 1, 1),
    ("s1_1x1_reduce", 128, 56, 56, 256, 64, 1, 1),
]

_DIMSPEC = {"NHWC": ("NHWC", "HWIO", "NHWC"),
            "NCHW": ("NCHW", "OIHW", "NCHW")}


def _conv(x, w, stride, layout):
    if layout == "GEMM":
        # 1x1/s1 only: the conv IS a matmul over flattened pixels
        b, h, w_, cin = x.shape
        cout = w.shape[-1]
        y = lax.dot_general(x.reshape(b * h * w_, cin),
                            w.reshape(cin, cout), (((1,), (0,)), ((), ())))
        return y.reshape(b, h, w_, cout)
    k = w.shape[0] if layout == "NHWC" else w.shape[2]
    pad = (k - 1) // 2
    # bf16 in/out (MXU accumulates f32 internally); an explicit f32
    # preferred_element_type would hand the backward a mixed-dtype conv
    return lax.conv_general_dilated(
        x, w, (stride, stride), ((pad, pad), (pad, pad)),
        dimension_numbers=_DIMSPEC[layout])


def _sync(x):
    # host value fetch = the only true device barrier through the axon
    # tunnel; block_until_ready acks before completion there and timed
    # impossible >1000 TF/s (PERF.md §8.2 measurement contract). This is
    # why the probe's historical ABSOLUTE TF/s rows read above physical
    # peak — only the NHWC-vs-NCHW relatives were meaningful (and those
    # were validated end-to-end by the same-window perf A/B).
    leaf = jax.tree_util.tree_leaves(x)[0]
    float(jnp.sum(leaf.astype(jnp.float32)))


def _time(fn, args, iters):
    """Per-op device time with dispatch amortized: `iters` copies of the
    op run INSIDE one jitted program (inputs perturbed per copy so XLA
    cannot CSE them into one), one value-fetch sync at the end. A
    per-call loop would measure the tunnel's ~2.5-3 ms dispatch floor,
    not the sub-millisecond convs (PERF.md §3 measures ceilings the
    same way)."""
    x, w = args

    def repeated(x, w):
        acc = None
        for i in range(iters):
            eps = jnp.asarray(i * 1e-6, x.dtype)  # keep the conv dtype
            y = fn(x + eps, w)
            acc = y if acc is None else acc + y
        return acc

    r = jax.jit(repeated)
    _sync(r(x, w))  # compile + warmup
    t0 = time.perf_counter()
    _sync(r(x, w))
    return (time.perf_counter() - t0) / iters


def probe(iters: int = 30):
    dev = jax.devices()[0]
    for name, b, h, w_, cin, cout, k, stride in SHAPES:
        flops = conv_unit_flops(b, h // stride, w_ // stride, cin, cout,
                                k, k)
        rs = np.random.RandomState(0)
        layouts = ["NHWC", "NCHW"]
        if k == 1 and stride == 1:
            layouts.append("GEMM")  # matmul spelling of the same conv
        for layout in layouts:
            if layout == "NCHW":
                x = jnp.asarray(rs.randn(b, cin, h, w_), jnp.bfloat16)
                kern = jnp.asarray(rs.randn(cout, cin, k, k), jnp.bfloat16)
            else:  # NHWC and GEMM share the NHWC operand layout
                x = jnp.asarray(rs.randn(b, h, w_, cin), jnp.bfloat16)
                kern = jnp.asarray(rs.randn(k, k, cin, cout), jnp.bfloat16)

            fwd = jax.jit(lambda a, c: _conv(a, c, stride, layout))
            loss = lambda a, c: jnp.sum(
                _conv(a, c, stride, layout).astype(jnp.float32))
            dgrad = jax.jit(jax.grad(loss, argnums=0))
            wgrad = jax.jit(jax.grad(loss, argnums=1))

            row = {"shape": name, "layout": layout,
                   "gflops": round(flops / 1e9, 1),
                   # geometry fields: apply_conv_probe.py --geom turns
                   # rows into per-geometry decisions (ops/conv2d.py)
                   "kh": k, "kw": k, "stride": [stride, stride],
                   "cin": cin, "cout": cout, "groups": 1,
                   "dilation": [1, 1], "dtype": "bfloat16",
                   "device": dev.device_kind}
            for pname, fn in (("fwd", fwd), ("dgrad", dgrad),
                              ("wgrad", wgrad)):
                dt = _time(fn, (x, kern), iters)
                row[f"{pname}_ms"] = round(dt * 1e3, 3)
                row[f"{pname}_tfs"] = round(flops / dt / 1e12, 2)
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    probe(int(sys.argv[1]) if len(sys.argv) > 1 else 30)
