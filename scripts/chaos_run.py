#!/usr/bin/env python
"""Chaos harness (ISSUE 6): kill a real training run at injected steps,
restart it under the supervisor, and assert the final params are
BIT-IDENTICAL to an uninterrupted run — PR 2's resume-equivalence test
turned into an end-to-end CI property that covers the whole stack:
fault injector -> process death (os._exit, nothing flushes) ->
checksum-verified newest-valid-pair resume -> step-equivalent replay.

    # CI: 2 preemptions, equivalence asserted, rc 0/1
    python scripts/chaos_run.py --kills 2 --platform cpu

    # kill mid-checkpoint too (torn pair -> fallback to previous)
    python scripts/chaos_run.py --kills 1 --kill-in-ckpt --platform cpu

    # ISSUE 11: elastic reshape — lose 1 of 8 virtual CPU devices
    # before step 5, assert the run FINISHES AT 7 with loss within
    # tolerance of the uninterrupted 8-device run and the reshape
    # event stamped in the child's perf JSON + fault log
    python scripts/chaos_run.py --kill-device 1@5 --platform cpu

The trainee (``--worker`` mode, same file) is a deterministic tiny
model with Dropout — rng-SENSITIVE on purpose, so a resume that
replayed the wrong key stream would diverge measurably, not silently.
Checkpoints land every ``--ckpt-every`` iterations; each restart
resumes from the newest checksum-valid pair. The parent is
``resilience.supervise_command``: restart while the child dies with
``PREEMPT_RC`` (75), bounded budget, deterministic backoff.

Emits one JSON line: {"equal": bool, "kills": [...], "restarts": N,
"fault_events": [...]} and exits nonzero on any mismatch or missing
fault-log entry.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


# ------------------------------------------------------------------ worker
def worker_main(args) -> int:
    """One training attempt: resume from the newest valid pair (if any),
    train to --max-it, write final params to --out."""
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    if args.faultPlan:
        from bigdl_tpu.resilience.faults import install_plan, parse_plan
        install_plan(parse_plan(args.faultPlan),
                     log_path=args.faultLog or None)

    import numpy as np

    from bigdl_tpu import nn
    from bigdl_tpu.dataset.dataset import BatchDataSet
    from bigdl_tpu.optim import Optimizer, SGD, Trigger
    from bigdl_tpu.utils.file import save_pytree

    # deterministic data + a Dropout layer: the same trainee as
    # tests/test_resume_equivalence.py — rng-sensitive, so a wrong
    # resume diverges instead of passing by luck
    rs = np.random.RandomState(0)
    x = rs.randn(64, 8).astype(np.float32)
    y = rs.randint(0, 3, 64).astype(np.int32)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Dropout(0.5),
                          nn.Linear(16, 3), nn.LogSoftMax())
    ds = BatchDataSet(x, y, 16)  # 4 iterations/epoch
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(),
                    optim_method=SGD(learning_rate=0.1),
                    end_when=Trigger.max_iteration(args.maxIt), seed=7,
                    log_every=100)
    opt.set_checkpoint(Trigger.several_iteration(args.ckptEvery),
                       args.ckpt)
    # resume() is a no-op on an empty dir, picks the newest checksum-
    # VALID pair otherwise (falling back past torn/corrupt snapshots),
    # and accepts a model-only blob when a kill landed between the
    # model.<n> and state.<n> writes
    opt.resume(args.ckpt)
    trained = opt.optimize()
    save_pytree({"params": trained.params}, args.out)
    return 0


# ------------------------------------------------- elastic kill-device mode
def _kill_device_mode(args, wd: str) -> int:
    """``--kill-device N@STEP``: run the perf harness under --elastic on
    8 virtual CPU devices, fire the kill_device fault at STEP, and assert
    the run finishes on the surviving count with loss within --tolerance
    of an uninterrupted 8-device run, the reshape dict in its JSON line,
    and the kill_device event in the fault log."""
    import subprocess

    try:
        n_kill_s, _, step_s = args.killDevice.partition("@")
        n_kill, step = int(n_kill_s), int(step_s)
        if n_kill < 1 or step < 1:
            raise ValueError
    except ValueError:
        print(f"chaos: bad --kill-device {args.killDevice!r} "
              "(expected N@STEP, e.g. 1@5)", flush=True)
        return 2

    n_devices = 8
    fault_log = os.path.join(wd, "faults.jsonl")
    env = dict(os.environ)
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count="
                        + str(n_devices)).strip()
    env["BIGDL_FAULT_LOG"] = fault_log
    base = [sys.executable, "-m", "bigdl_tpu.cli.perf", "-m", "lenet5",
            "-b", "16", "-i", str(args.maxIt), "--strategy", "dp",
            # constant data + f32: hold-padding duplicates identical
            # rows, so the post-reshape loss stays comparable to the
            # uninterrupted run within a tight tolerance
            "--dataType", "constant", "--f32"]
    if args.platform:
        base += ["--platform", args.platform]
        env["JAX_PLATFORMS"] = args.platform

    def _perf(cmd, tag):
        out_path = os.path.join(wd, f"{tag}.json")
        with open(out_path, "w") as f:
            rc = subprocess.call(cmd, env=env, stdout=f)
        if rc != 0:
            print(f"chaos: {tag} perf run failed rc={rc}", flush=True)
            return rc, None
        with open(out_path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        return 0, json.loads(lines[-1])

    print(f"chaos: kill-device mode — lose {n_kill} of {n_devices} "
          f"device(s) before step {step}, max_it={args.maxIt}, "
          f"workdir={wd}", flush=True)
    rc, ref = _perf(base, "ref")
    if rc != 0:
        return 2
    elastic_cmd = base + ["--elastic", "hold",
                          "--minDevices", str(args.minDevices),
                          "--faultPlan",
                          f"kill_device@step:{step}:{n_kill}"]
    rc, el = _perf(elastic_cmd, "elastic")
    if rc != 0:
        return 2

    kill_events = []
    if os.path.exists(fault_log):
        with open(fault_log) as f:
            kill_events = [e for e in (json.loads(ln) for ln in f
                                       if ln.strip())
                           if e.get("fault") == "kill_device"]

    surviving = n_devices - n_kill
    reshape = el.get("reshape")
    rel = (abs(el["final_loss"] - ref["final_loss"])
           / max(abs(ref["final_loss"]), 1e-9))
    checks = {
        "finished_at_surviving_count": el["n_devices"] == surviving,
        "reshape_stamped": bool(
            reshape and reshape.get("from_devices") == n_devices
            and reshape.get("to_devices") == surviving
            and reshape.get("restore_ms") is not None),
        "kill_logged": len(kill_events) >= 1,
        "loss_within_tolerance": rel <= args.tolerance,
        "supervised_retry_recorded": bool(
            el.get("supervisor", {}).get("retries", 0) >= 1),
    }
    out = {
        "chaos": "kill_device_reshape",
        "kill": f"{n_kill}@{step}",
        "devices": {"before": n_devices, "after": el["n_devices"]},
        "reshape": reshape,
        "ref_loss": ref["final_loss"],
        "elastic_loss": el["final_loss"],
        "rel_loss_delta": round(rel, 6),
        "tolerance": args.tolerance,
        "fault_events": kill_events,
        "checks": checks,
    }
    print(json.dumps(out), flush=True)
    if not all(checks.values()):
        failed = sorted(k for k, v in checks.items() if not v)
        print(f"chaos: FAILED ({', '.join(failed)})", flush=True)
        return 1
    print(f"chaos: OK — lost {n_kill} device(s) at step {step}, run "
          f"finished at {surviving} devices, loss delta "
          f"{rel * 100:.2f}% <= {args.tolerance * 100:.0f}%, reshape "
          "stamped in perf JSON + fault log", flush=True)
    return 0


# ------------------------------------------------------------------ parent
def _resumed_iteration(ckpt_dir: str) -> int:
    """Mirror Optimizer.resume's selection exactly (valid pair, else a
    checksum-valid model-only blob) so the parent's local-visit math
    targets the same global step the worker will actually resume at."""
    from bigdl_tpu.utils.file import (latest_checkpoint,
                                      latest_valid_checkpoint_pair,
                                      verify_checkpoint)
    m, _s = latest_valid_checkpoint_pair(ckpt_dir)
    if m is None:
        m = latest_checkpoint(ckpt_dir, "model.")
        if m is None or not verify_checkpoint(m):
            return 0
    tail = str(m).rstrip("/").rsplit(".", 1)[-1]
    return int(tail) if tail.isdigit() else 0


def _worker_argv(args, ckpt: str, out: str, plan: str = "",
                 fault_log: str = "") -> list:
    argv = [sys.executable, os.path.abspath(__file__), "--worker",
            "--max-it", str(args.maxIt), "--ckpt-every",
            str(args.ckptEvery), "--ckpt", ckpt, "--out", out]
    if args.platform:
        argv += ["--platform", args.platform]
    if plan:
        argv += ["--faultPlan", plan]
    if fault_log:
        argv += ["--faultLog", fault_log]
    return argv


def _load_params(path: str):
    from bigdl_tpu.utils.file import load_pytree
    return load_pytree(path)["params"]


def main(argv=None) -> int:
    p = argparse.ArgumentParser("chaos_run")
    p.add_argument("--worker", action="store_true",
                   help="internal: run one training attempt")
    p.add_argument("--kills", type=int, default=2,
                   help="process-fatal preemptions to inject at evenly "
                        "spaced steps")
    p.add_argument("--kill-steps", default=None,
                   help="explicit comma-separated global kill steps "
                        "(overrides --kills spacing)")
    p.add_argument("--kill-in-ckpt", action="store_true",
                   help="also preempt INSIDE a checkpoint write on the "
                        "first attempt (torn pair -> previous-pair "
                        "fallback)")
    p.add_argument("--max-it", dest="maxIt", type=int, default=12)
    p.add_argument("--ckpt-every", dest="ckptEvery", type=int, default=3)
    p.add_argument("--budget", type=int, default=8,
                   help="restart budget for the supervising parent")
    p.add_argument("--kill-device", dest="killDevice", nargs="?",
                   const="1@5", default=None, metavar="N@STEP",
                   help="elastic mode: lose N of 8 virtual devices at "
                        "STEP and assert the run finishes on the "
                        "survivors with the reshape stamped (default "
                        "1@5)")
    p.add_argument("--tolerance", type=float, default=0.05,
                   help="kill-device mode: max relative final-loss "
                        "delta vs the uninterrupted run")
    p.add_argument("--min-devices", dest="minDevices", type=int,
                   default=4,
                   help="kill-device mode: --minDevices for the "
                        "elastic child")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu"])
    p.add_argument("--workdir", default=None,
                   help="keep artifacts here instead of a fresh tempdir")
    # worker-only flags
    p.add_argument("--ckpt", default=None)
    p.add_argument("--out", default=None)
    p.add_argument("--faultPlan", default=None)
    p.add_argument("--faultLog", default=None)
    args = p.parse_args(argv)

    if args.worker:
        return worker_main(args)

    import numpy as np

    from bigdl_tpu.resilience.supervisor import (RetryPolicy,
                                                 supervise_command)

    wd = args.workdir or tempfile.mkdtemp(prefix="chaos_")
    os.makedirs(wd, exist_ok=True)
    if args.killDevice:
        return _kill_device_mode(args, wd)
    if args.kill_steps:
        kills = sorted(int(t) for t in args.kill_steps.split(",") if t)
    else:
        n = max(args.kills, 0)
        kills = sorted({max(1, round(args.maxIt * (i + 1) / (n + 1)))
                        for i in range(n)})
    print(f"chaos: max_it={args.maxIt} ckpt_every={args.ckptEvery} "
          f"kills_at={kills} kill_in_ckpt={args.kill_in_ckpt} "
          f"workdir={wd}", flush=True)

    # 1. the uninterrupted reference run
    base_out = os.path.join(wd, "base.npz")
    rc = __import__("subprocess").call(
        _worker_argv(args, os.path.join(wd, "ck_base"), base_out))
    if rc != 0:
        print(f"chaos: baseline run failed rc={rc}", flush=True)
        return 2

    # 2. the chaos run: inject preemptions, restart + resume each time
    chaos_ck = os.path.join(wd, "ck_chaos")
    chaos_out = os.path.join(wd, "chaos.npz")
    fault_log = os.path.join(wd, "faults.jsonl")

    def _fired() -> tuple:
        """(step_kills_fired, ckpt_kill_fired) read from the fault log —
        the dying child's own record, so accounting survives any
        fire-order interleaving of the step and ckpt rules."""
        steps, ckpt = 0, False
        if os.path.exists(fault_log):
            with open(fault_log) as f:
                for line in f:
                    if not line.strip():
                        continue
                    e = json.loads(line)
                    if e.get("fault") != "preempt":
                        continue
                    if e.get("site") == "step":
                        steps += 1
                    elif e.get("site") == "ckpt_save":
                        ckpt = True
        return steps, ckpt

    def make_argv(attempt: int) -> list:
        resumed = _resumed_iteration(chaos_ck)
        step_fired, ckpt_fired = _fired()
        entries = []
        remaining = kills[step_fired:]
        if remaining:
            # the injector counts per-process step visits; after a
            # resume at iteration r, global step k is local visit k - r
            local = remaining[0] - resumed
            if local >= 1:
                entries.append(f"preempt@step:{local}")
        if args.kill_in_ckpt and not ckpt_fired:
            # visit 2 = the state.<n> write of this attempt's FIRST
            # snapshot: the pair is torn mid-write, resume must fall
            # back (model-only or previous pair)
            entries.append("preempt@ckpt_save:2")
        plan = ";".join(entries)
        print(f"chaos: attempt {attempt + 1} resumed_at={resumed} "
              f"plan={plan or '(none)'}", flush=True)
        return _worker_argv(args, chaos_ck, chaos_out, plan, fault_log)

    expected_kills = len(kills) + (1 if args.kill_in_ckpt else 0)
    rc, events = supervise_command(
        make_argv,
        policy=RetryPolicy(budget=args.budget, base_s=0.05, max_s=0.5),
    )
    if rc != 0:
        print(f"chaos: supervised run did not converge rc={rc} "
              f"events={json.dumps(events)}", flush=True)
        return 2

    # 3. every injected fault must appear in the fault log (written by
    #    the dying child BEFORE os._exit)
    fault_events = []
    if os.path.exists(fault_log):
        with open(fault_log) as f:
            fault_events = [json.loads(line) for line in f if line.strip()]
    restarts = sum(1 for e in events if e.get("event") == "restart")

    # 4. the acceptance bit: params identical to the uninterrupted run
    import jax
    a = jax.tree_util.tree_leaves(_load_params(base_out))
    b = jax.tree_util.tree_leaves(_load_params(chaos_out))
    equal = (len(a) == len(b)
             and all(np.array_equal(np.asarray(x), np.asarray(y))
                     for x, y in zip(a, b)))

    out = {
        "chaos": "kill_resume_equivalence",
        "max_it": args.maxIt,
        "ckpt_every": args.ckptEvery,
        "kills": kills,
        "kill_in_ckpt": args.kill_in_ckpt,
        "restarts": restarts,
        "equal": equal,
        "fault_events": fault_events,
        "supervisor_events": events,
    }
    print(json.dumps(out), flush=True)
    ok = (equal and restarts == expected_kills
          and len(fault_events) == expected_kills
          and all(e.get("fault") == "preempt" for e in fault_events))
    if not ok:
        print(f"chaos: FAILED (equal={equal}, restarts={restarts}/"
              f"{expected_kills}, logged_faults={len(fault_events)}/"
              f"{expected_kills})", flush=True)
        return 1
    print(f"chaos: OK — {expected_kills} preemption(s), {restarts} "
          f"supervised restart(s), final params bit-identical",
          flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
