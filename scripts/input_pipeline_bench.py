"""ImageNet-shape input-pipeline throughput bench (VERDICT r2 missing #2:
prove decode+augment can feed the chip at its measured img/s).

Generates realistic synthetic JPEGs (~100-200KB, short side ~375, the
ImageNet file-size regime), packs them into .btr shards, then measures
RecordImageDataSet streaming throughput (decode + per-sample random
crop/flip + normalize + batch assembly) in train mode at 224x224.

    python scripts/input_pipeline_bench.py [n_images] [n_threads] [batch]

Prints one JSON line: images/sec plus the decode backend in use.
Reference bar: MTLabeledBGRImgToBatch.scala:48-133 kept Xeon clusters
saturated; our bar is >= the measured model img/s (BENCH_r03).
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def make_jpegs(root: str, n: int, seed: int = 0) -> None:
    from PIL import Image

    rs = np.random.RandomState(seed)
    d = os.path.join(root, "class0")
    os.makedirs(d, exist_ok=True)
    for i in range(n):
        # smooth gradients + mild noise compress to ~the ImageNet size
        # regime at q87; pure noise would be unrealistically large
        h = int(rs.randint(375, 500))
        w = int(rs.randint(480, 640))
        yy = np.linspace(0, 255, h)[:, None]
        xx = np.linspace(0, 255, w)[None, :]
        base = np.stack([yy + 0 * xx, 0 * yy + xx, (yy + xx) / 2], -1)
        img = (base + rs.randn(h, w, 3) * 28).clip(0, 255).astype(np.uint8)
        Image.fromarray(img).save(os.path.join(d, f"{i}.jpg"), quality=87)


def run(n_images: int = 512, n_threads: int = 16, batch: int = 128,
        epochs: int = 2):
    from bigdl_tpu.dataset import native
    from bigdl_tpu.dataset.recordfile import write_image_shards
    from bigdl_tpu.dataset.streaming import RecordImageDataSet

    with tempfile.TemporaryDirectory() as td:
        img_root = os.path.join(td, "imgs")
        make_jpegs(img_root, n_images)
        sizes = [os.path.getsize(os.path.join(img_root, "class0", f))
                 for f in os.listdir(os.path.join(img_root, "class0"))]
        shard_dir = os.path.join(td, "shards")
        write_image_shards(img_root, shard_dir, images_per_shard=256)

        ds = RecordImageDataSet(
            shard_dir, batch_size=batch, crop=(224, 224), train=True,
            short_side=256,
            mean=[123.68, 116.779, 103.939], std=[58.4, 57.1, 57.4],
            n_threads=n_threads, window=4)
        # warmup epoch fragment: imports, thread pool, reader handles
        next(iter(ds))
        t0 = time.perf_counter()
        n_done = 0
        for _ in range(epochs):
            for b in ds:
                n_done += b.input.shape[0]
        dt = time.perf_counter() - t0
        out = {
            "metric": "input_pipeline_imagenet_shape",
            "images_per_second": round(n_done / dt, 1),
            "n_images": n_images, "batch": batch,
            "n_threads": n_threads,
            "mean_jpeg_kb": round(float(np.mean(sizes)) / 1024, 1),
            "native_jpeg_decode": native.jpeg_available(),
            "seconds": round(dt, 2),
        }
        print(json.dumps(out), flush=True)
        return out


if __name__ == "__main__":
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    t = int(sys.argv[2]) if len(sys.argv) > 2 else 16
    b = int(sys.argv[3]) if len(sys.argv) > 3 else 128
    run(n, t, b)
