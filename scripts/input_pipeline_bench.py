"""ImageNet-shape input-pipeline throughput bench (VERDICT r2 missing #2:
prove decode+augment can feed the chip at its measured img/s).

Generates realistic synthetic JPEGs (~100-200KB, short side ~375, the
ImageNet file-size regime), packs them into .btr shards, then measures
RecordImageDataSet streaming throughput (decode + per-sample random
crop/flip + normalize + batch assembly) in train mode at 224x224.

    python scripts/input_pipeline_bench.py [n_images] [n_threads] [batch]

Prints one JSON line: images/sec plus the decode backend in use.
Reference bar: MTLabeledBGRImgToBatch.scala:48-133 kept Xeon clusters
saturated; our bar is >= the measured model img/s (BENCH_r03).

ISSUE 13 sweep mode — grid the executor pipeline and report per-config
stall fraction against a simulated device step:

    python scripts/input_pipeline_bench.py --sweep \
        --workers 1,2,4,8 --depths 1,2,4 --stages off,host,device \
        --stepMs 50 [--images N] [--batch B]

Each config prints one JSON line ({"metric": "pipeline_sweep", ...,
"stall_frac": ...}): the consumer "trains" for --stepMs per batch, and
stall_frac is the fraction of wall-clock it spent waiting on the feed —
0.0 means the executor kept the (simulated) chip fed.
"""

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def make_jpegs(root: str, n: int, seed: int = 0) -> None:
    from PIL import Image

    rs = np.random.RandomState(seed)
    d = os.path.join(root, "class0")
    os.makedirs(d, exist_ok=True)
    for i in range(n):
        # smooth gradients + mild noise compress to ~the ImageNet size
        # regime at q87; pure noise would be unrealistically large
        h = int(rs.randint(375, 500))
        w = int(rs.randint(480, 640))
        yy = np.linspace(0, 255, h)[:, None]
        xx = np.linspace(0, 255, w)[None, :]
        base = np.stack([yy + 0 * xx, 0 * yy + xx, (yy + xx) / 2], -1)
        img = (base + rs.randn(h, w, 3) * 28).clip(0, 255).astype(np.uint8)
        Image.fromarray(img).save(os.path.join(d, f"{i}.jpg"), quality=87)


def run(n_images: int = 512, n_threads: int = 16, batch: int = 128,
        epochs: int = 2):
    from bigdl_tpu.dataset import native
    from bigdl_tpu.dataset.recordfile import write_image_shards
    from bigdl_tpu.dataset.streaming import RecordImageDataSet

    with tempfile.TemporaryDirectory() as td:
        img_root = os.path.join(td, "imgs")
        make_jpegs(img_root, n_images)
        sizes = [os.path.getsize(os.path.join(img_root, "class0", f))
                 for f in os.listdir(os.path.join(img_root, "class0"))]
        shard_dir = os.path.join(td, "shards")
        write_image_shards(img_root, shard_dir, images_per_shard=256)

        ds = RecordImageDataSet(
            shard_dir, batch_size=batch, crop=(224, 224), train=True,
            short_side=256,
            mean=[123.68, 116.779, 103.939], std=[58.4, 57.1, 57.4],
            n_threads=n_threads, window=4)
        # warmup epoch fragment: imports, thread pool, reader handles
        next(iter(ds))
        t0 = time.perf_counter()
        n_done = 0
        for _ in range(epochs):
            for b in ds:
                n_done += b.input.shape[0]
        dt = time.perf_counter() - t0
        out = {
            "metric": "input_pipeline_imagenet_shape",
            "images_per_second": round(n_done / dt, 1),
            "n_images": n_images, "batch": batch,
            "n_threads": n_threads,
            "mean_jpeg_kb": round(float(np.mean(sizes)) / 1024, 1),
            "native_jpeg_decode": native.jpeg_available(),
            "seconds": round(dt, 2),
        }
        print(json.dumps(out), flush=True)
        return out


def sweep(n_images: int = 256, batch: int = 64, step_ms: float = 50.0,
          workers_list=(1, 2, 4, 8), depths=(1, 2, 4),
          stages=("off", "host", "device"), epochs: int = 2):
    """Grid dataWorkers x prefetchDepth x stage over the SAME shard set
    and report stall_frac against a simulated --stepMs device step.
    One JSON line per config (ISSUE 13 satellite)."""
    from bigdl_tpu.dataset import native
    from bigdl_tpu.dataset.pipeline import (EpochPlan, ExecutorDataSet,
                                            StagedDataSet,
                                            StreamingSampleSource)
    from bigdl_tpu.dataset.recordfile import write_image_shards
    from bigdl_tpu.dataset.streaming import RecordImageDataSet

    results = []
    with tempfile.TemporaryDirectory() as td:
        img_root = os.path.join(td, "imgs")
        make_jpegs(img_root, n_images)
        shard_dir = os.path.join(td, "shards")
        write_image_shards(img_root, shard_dir, images_per_shard=256)

        for stage in stages:
            for workers in workers_list:
                for depth in depths:
                    rds = RecordImageDataSet(
                        shard_dir, batch_size=batch, crop=(224, 224),
                        train=True, short_side=256,
                        mean=[123.68, 116.779, 103.939],
                        std=[58.4, 57.1, 57.4], n_threads=1, window=1)
                    src = StreamingSampleSource(rds)
                    plan = EpochPlan(len(src), batch, seed=rds.seed,
                                     shuffle=True, process_index=0,
                                     process_count=1)
                    ds = ExecutorDataSet(src, workers=workers,
                                         depth=depth, plan=plan)
                    if stage != "off":
                        ds = StagedDataSet(ds, stage=stage, depth=depth)
                    step_s = step_ms / 1000.0
                    # warm: thread spawn + first decode outside the clock
                    it = iter(ds)
                    next(it)
                    n_done = batch  # the warm batch still trains below
                    t0 = time.perf_counter()
                    time.sleep(step_s)  # "device step" for the warm batch
                    for _ in range(epochs):
                        for mb in it:
                            n_done += batch
                            time.sleep(step_s)  # simulated device step
                        ds.shuffle()
                        it = iter(ds)
                    dt = time.perf_counter() - t0
                    steps = n_done // batch
                    # the sleeps total steps*step_s; everything else in
                    # the wall clock is the feed making the consumer wait
                    wait_s = max(0.0, dt - steps * step_s)
                    out = {
                        "metric": "pipeline_sweep",
                        "workers": workers, "depth": depth, "stage": stage,
                        "batch": batch, "step_ms": step_ms,
                        "images_per_second": round(n_done / dt, 1),
                        "stall_frac": round(wait_s / dt, 4),
                        "seconds": round(dt, 2),
                        "native_jpeg_decode": native.jpeg_available(),
                    }
                    print(json.dumps(out), flush=True)
                    results.append(out)
    return results


def _parse_csv(s, cast):
    return tuple(cast(v) for v in s.split(",") if v)


if __name__ == "__main__":
    import argparse

    p = argparse.ArgumentParser(__doc__.splitlines()[0])
    p.add_argument("n_images", nargs="?", type=int, default=512)
    p.add_argument("n_threads", nargs="?", type=int, default=16)
    p.add_argument("batch_pos", nargs="?", type=int, default=None)
    p.add_argument("--sweep", action="store_true",
                   help="grid dataWorkers x prefetchDepth x stage "
                        "(executor pipeline) instead of the legacy "
                        "single-config window-feed bench")
    p.add_argument("--images", type=int, default=None)
    p.add_argument("--batch", type=int, default=None)
    p.add_argument("--stepMs", type=float, default=50.0,
                   help="simulated device step per batch for --sweep")
    p.add_argument("--workers", default="1,2,4,8")
    p.add_argument("--depths", default="1,2,4")
    p.add_argument("--stages", default="off,host,device")
    p.add_argument("--epochs", type=int, default=2)
    a = p.parse_args()
    if a.sweep:
        sweep(a.images or a.n_images or 256,
              a.batch or a.batch_pos or 64, a.stepMs,
              _parse_csv(a.workers, int), _parse_csv(a.depths, int),
              _parse_csv(a.stages, str), a.epochs)
    else:
        run(a.images or a.n_images, a.n_threads,
            a.batch or a.batch_pos or 128)
