"""Summarize a tpu_capture.sh log into markdown for PERF.md.

    python scripts/update_perf_from_capture.py /tmp/tpu_capture.log

Parses every JSON line in the capture log (perf runs, flash bench rows,
pipeline bench, bench.py line) and prints ready-to-paste markdown
tables; leaves PERF.md itself untouched (human merges the story).
"""

import json
import re
import sys


def parse(path: str):
    rows = []
    section = None
    for line in open(path, errors="replace"):
        m = re.match(r"^=== (\S+)", line)
        if m and not line.startswith("=== end"):
            section = m.group(1)
        line = line.strip()
        if line.startswith("{") and line.endswith("}"):
            try:
                rows.append((section, json.loads(line)))
            except json.JSONDecodeError:
                pass
        if "passed" in line and ("failed" in line or "skipped" in line
                                 or "error" in line or " in " in line):
            rows.append((section, {"pytest": line}))
    return rows


def main(path: str) -> None:
    rows = parse(path)
    perf = [(s, r) for s, r in rows if "images_per_second_per_chip" in r]
    flash = [(s, r) for s, r in rows if "impl" in r and "seq" in r]
    pipe = [(s, r) for s, r in rows if r.get("metric") ==
            "input_pipeline_imagenet_shape"]
    tests = [(s, r) for s, r in rows if "pytest" in r]
    tta = [(s, r) for s, r in rows if r.get("metric") == "time_to_acc"]
    convp = [(s, r) for s, r in rows if "dgrad_tfs" in r]

    if perf:
        # ISSUE 8 columns: strategy/mesh stamping + the per-step
        # collective breakout (null until a capture window fired);
        # ISSUE 12 columns: HBM peak + headroom (null obs-off)
        print("### Training throughput / MFU\n")
        print("| run | model | strategy | devs | batch | img/s/chip "
              "| MFU % | basis | coll ms/step | coll % "
              "| hbm peak GiB | headroom % | device |")
        print("|---|---|---|---|---|---|---|---|---|---|---|---|---|")
        for s, r in perf:
            cs = r.get("collective_s")
            cf = r.get("collective_frac")
            pk = r.get("hbm_peak_bytes")
            hr = r.get("hbm_headroom_frac")
            print(f"| {s} | {r.get('model')} "
                  f"| {r.get('strategy') or '-'} "
                  f"| {r.get('n_devices', 1)} | {r.get('batch')} "
                  f"| {r.get('images_per_second_per_chip')} "
                  f"| {r.get('mfu_pct')} | {r.get('mfu_basis')} "
                  f"| {round(cs * 1e3, 3) if cs is not None else '-'} "
                  f"| {round(cf * 100, 2) if cf is not None else '-'} "
                  f"| {round(pk / 2**30, 2) if pk is not None else '-'} "
                  f"| {round(hr * 100, 1) if hr is not None else '-'} "
                  f"| {r.get('device')} |")
        print()
        memmed = [(s, r) for s, r in perf
                  if isinstance(r.get("mem"), dict)]
        if memmed:
            print("### HBM attribution (per run)\n")
            print("| run | model | category | MiB | frac % |")
            print("|---|---|---|---|---|")
            for s, r in memmed:
                m = r["mem"]
                total = max(1, m.get("total_bytes") or 1)
                for cat, b in (m.get("categories") or {}).items():
                    print(f"| {s} | {r.get('model')} | {cat} "
                          f"| {round(b / 2**20, 1)} "
                          f"| {round(100.0 * b / total, 1)} |")
            print()
        attribbed = [(s, r) for s, r in perf if r.get("attrib")]
        if attribbed:
            print("### Device-time attribution (per capture window)\n")
            print("| run | model | category | time_s | frac % |")
            print("|---|---|---|---|---|")
            for s, r in attribbed:
                a = r["attrib"]
                for cat, d in a.get("categories", {}).items():
                    print(f"| {s} | {r.get('model')} | {cat} "
                          f"| {d.get('s')} "
                          f"| {round(d.get('frac', 0) * 100, 2)} |")
                for kind, d in a.get("collectives", {}).items():
                    print(f"| {s} | {r.get('model')} | coll:{kind} "
                          f"| {d.get('s')} "
                          f"| {round(d.get('frac', 0) * 100, 2)} |")
            print()
    if flash:
        print("### Flash vs dense attention (causal bf16)\n")
        print("| seq | impl | fwd ms | fwd+bwd ms | fwd TF/s | "
              "fwd+bwd TF/s |")
        print("|---|---|---|---|---|---|")
        for _, r in flash:
            print(f"| {r.get('seq')} | {r.get('impl')} "
                  f"| {r.get('fwd_ms', r.get('error', '-'))} "
                  f"| {r.get('fwdbwd_ms', '-')} | {r.get('fwd_tflops', '-')} "
                  f"| {r.get('fwdbwd_tflops', '-')} |")
        print()
    if pipe:
        print("### Input pipeline\n")
        for _, r in pipe:
            print(f"- {r}")
        print()
    if tta:
        print("### Time to accuracy\n")
        print("| run | model | target | reached | t (s) | final top1 | "
              "epochs | device |")
        print("|---|---|---|---|---|---|---|---|")
        for s, r in tta:
            print(f"| {s} | {r.get('model')} | {r.get('target_top1')} "
                  f"| {r.get('reached')} | {r.get('time_to_acc_s')} "
                  f"| {r.get('final_top1')} | {r.get('epochs_run')} "
                  f"| {r.get('device')} |")
        print()
    if convp:
        print("### Conv backward layout probe (TF/s)\n")
        print("| shape | layout | fwd | dgrad | wgrad |")
        print("|---|---|---|---|---|")
        for _, r in convp:
            print(f"| {r.get('shape')} | {r.get('layout')} "
                  f"| {r.get('fwd_tfs')} | {r.get('dgrad_tfs')} "
                  f"| {r.get('wgrad_tfs')} |")
        print()
    if tests:
        print("### Test runs\n")
        for s, r in tests:
            print(f"- {s}: {r['pytest']}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_capture.log")
