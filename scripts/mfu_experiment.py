"""One-off MFU experiment driver for PERF.md: variants x batch sizes.

Usage: python scripts/mfu_experiment.py [variant] [batch]
variant in {f32params, bf16params}; prints one JSON line per run.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import models, nn
from bigdl_tpu.optim import SGD
from bigdl_tpu.cli.perf import _peak_flops
from bigdl_tpu.utils.flops import fn_flops


def run(variant: str, batch: int, iters: int = 20):
    model = models.resnet50(1000)
    crit = nn.ClassNLLCriterion()
    opt = SGD(learning_rate=0.01, momentum=0.9)

    rng = np.random.RandomState(0)
    x_host = rng.randn(batch, 224, 224, 3).astype(np.float32)
    y_host = rng.randint(0, 1000, batch).astype(np.int32)

    params = model.init(jax.random.PRNGKey(0))
    mod_state = model.init_state()
    opt_state = opt.init(params)
    cast_params = variant == "bf16params"

    def train_step(params, mod_state, opt_state, x, y, rng):
        def loss_fn(p):
            pc = (jax.tree.map(
                lambda a: a.astype(jnp.bfloat16)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, p)
                if cast_params else p)
            out, ms = model.apply(pc, mod_state, x.astype(jnp.bfloat16),
                                  training=True, rng=rng)
            return crit(out.astype(jnp.float32), y), ms

        (loss, ms), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_p, new_o = opt.update(grads, opt_state, params)
        return new_p, ms, new_o, loss

    x, y = jnp.asarray(x_host), jnp.asarray(y_host)
    k = jax.random.PRNGKey(1)
    flops = fn_flops(train_step, params, mod_state, opt_state, x, y, k)
    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    compiled = step.lower(params, mod_state, opt_state, x, y, k).compile()
    params, mod_state, opt_state, loss = compiled(
        params, mod_state, opt_state, x, y, k)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        params, mod_state, opt_state, loss = compiled(
            params, mod_state, opt_state, x, y, k)
    float(loss)
    dt = time.perf_counter() - t0
    peak, label = _peak_flops(jax.devices()[0])
    print(json.dumps({
        "variant": variant, "batch": batch,
        "img_s": round(batch * iters / dt, 1),
        "ms_step": round(dt / iters * 1000, 2),
        "mfu_pct": round(100 * flops * iters / dt / peak, 2),
        "gflops_step": round(flops / 1e9, 1), "peak": label,
    }), flush=True)


if __name__ == "__main__":
    variant = sys.argv[1] if len(sys.argv) > 1 else "bf16params"
    batch = int(sys.argv[2]) if len(sys.argv) > 2 else 128
    run(variant, batch)
