"""Turn conv_bwd_probe output into a conv layout decision.

Reads probe JSONL rows (file args or stdin), aggregates per-pass totals
via ops.conv2d, prints the winning ``FWD,DGRAD,WGRAD`` string on stdout
(consumable by ``perf --convLayout $(...)``) and the per-pass totals on
stderr.

Usage:
    python scripts/conv_bwd_probe.py 30 | tee /tmp/probe.jsonl
    python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 \
        --convLayout "$(python scripts/apply_conv_probe.py /tmp/probe.jsonl)"
"""

import fileinput
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bigdl_tpu.ops.conv2d import (_PASSES, decide_from_probe,  # noqa: E402
                                  probe_totals)


def main():
    lines = list(fileinput.input())
    totals = probe_totals(lines)
    decision = decide_from_probe(lines)
    for p in _PASSES:
        t = totals[p]
        print(f"{p}: NHWC {t['NHWC']:.1f} ms vs NCHW {t['NCHW']:.1f} ms "
              f"-> {decision[p]}", file=sys.stderr)
    print(",".join(decision[p] for p in _PASSES))


if __name__ == "__main__":
    main()
