"""Turn conv_bwd_probe output into conv layout decisions.

Default mode (back-compat): aggregates per-pass totals via ops.conv2d,
prints the winning global ``FWD,DGRAD,WGRAD`` string on stdout
(consumable by ``perf --convLayout $(...)``) and per-pass totals on
stderr.

``--geom`` (ISSUE 3): emits PER-GEOMETRY decisions instead — one entry
per (kh, kw, stride, cin, cout, groups, dilation, dtype), each pass
independently NHWC/NCHW/GEMM — as deterministic JSON on stdout,
consumable by ``perf --convGeom FILE`` and by
``ops.conv2d.install_geom_decisions``. Rows from probes predating the
geometry fields are mapped through ``ops.conv2d.LEGACY_PROBE_SHAPES``.

``--cache`` additionally writes the per-geometry decisions into the
autotune cache's ``conv_geom`` namespace (source "probe") for the
current device kind, so ``--autotune cached`` replays them with zero
measurement cost on every later run.

Usage:
    python scripts/conv_bwd_probe.py 30 | tee /tmp/probe.jsonl
    # global triple (historical):
    python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 \
        --convLayout "$(python scripts/apply_conv_probe.py /tmp/probe.jsonl)"
    # per-geometry decisions:
    python scripts/apply_conv_probe.py --geom /tmp/probe.jsonl > geom.json
    python -m bigdl_tpu.cli.perf -m resnet50 -b 128 -i 20 --convGeom geom.json
    # persist into the autotune cache for --autotune cached replay:
    python scripts/apply_conv_probe.py --geom --cache /tmp/probe.jsonl
"""

import argparse
import fileinput
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from bigdl_tpu.ops.conv2d import (_PASSES, decide_from_probe,  # noqa: E402
                                  decide_geom_from_probe, probe_totals)


def main(argv=None):
    ap = argparse.ArgumentParser("apply conv probe")
    ap.add_argument("--geom", action="store_true",
                    help="emit per-geometry decision JSON instead of the "
                         "global FWD,DGRAD,WGRAD triple")
    ap.add_argument("--cache", action="store_true",
                    help="also persist the per-geometry decisions into "
                         "the autotune cache (conv_geom namespace, "
                         "source 'probe') for --autotune cached replay")
    ap.add_argument("files", nargs="*",
                    help="probe JSONL files (stdin when omitted)")
    args = ap.parse_args(argv)

    lines = list(fileinput.input(args.files))
    if args.geom or args.cache:
        decisions = decide_geom_from_probe(lines)
        for d in decisions:
            g = d["geom"]
            print(f"{g['kh']}x{g['kw']}/s{g['stride'][0]} "
                  f"{g['cin']}->{g['cout']} {g['dtype']}: "
                  f"{d['layouts']}", file=sys.stderr)
        if args.cache:
            from bigdl_tpu import tuning
            n = tuning.put_geom_decisions(decisions)
            print(f"wrote {n} conv_geom entries to "
                  f"{tuning.get_cache().path}", file=sys.stderr)
        json.dump({"decisions": decisions}, sys.stdout, indent=1,
                  sort_keys=True)
        sys.stdout.write("\n")
        return

    totals = probe_totals(lines)
    decision = decide_from_probe(lines)
    for p in _PASSES:
        t = totals[p]
        print(f"{p}: NHWC {t['NHWC']:.1f} ms vs NCHW {t['NCHW']:.1f} ms "
              f"-> {decision[p]}", file=sys.stderr)
    print(",".join(decision[p] for p in _PASSES))


if __name__ == "__main__":
    main()
