"""Serving subsystem tests (ISSUE 5): bucket-padding parity, KV-cache
decode parity vs the full-sequence forward, micro-batcher flush/admission
semantics under an injected clock, metrics histogram correctness,
inference-only checkpoint restore, and an end-to-end CPU smoke of the
`serve` HTTP surface (the acceptance contract: /generate tokens
bit-identical to an offline full-sequence argmax decode, /metrics
non-zero counters)."""

import json
import os
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import models, nn
from bigdl_tpu.serving import (AdmissionError, DecodeEngine,
                               InferenceEngine, MetricsRegistry,
                               MicroBatcher, power_of_two_buckets)
from bigdl_tpu.serving.metrics import Histogram


# --------------------------------------------------------------- fixtures
@pytest.fixture(scope="module")
def tiny_net():
    m = nn.Sequential(nn.Linear(12, 16), nn.ReLU(), nn.Linear(16, 7),
                      nn.LogSoftMax())
    return m, m.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def tiny_lm():
    m = models.transformer_lm(50, d_model=32, num_layers=2, num_heads=2,
                              max_len=64)
    return m, m.init(jax.random.PRNGKey(1))


def _offline_greedy(model, params, prompt, n):
    """The reference decode: full-sequence forward, argmax the last
    position, append, repeat — no cache, no padding."""
    seq = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        logp, _ = model.apply(params, model.init_state(),
                              np.asarray([seq], np.int32))
        tok = int(np.argmax(np.asarray(logp)[0, -1]))
        out.append(tok)
        seq.append(tok)
    return out


# ------------------------------------------------- engine: bucket padding
def test_bucket_padding_parity_f32(tiny_net):
    model, params = tiny_net
    eng = InferenceEngine(model, params, buckets=(8,))
    x = np.random.RandomState(0).randn(5, 12).astype(np.float32)
    got = eng.predict_scores(x)
    ref, _ = model.apply(params, model.init_state(), jnp.asarray(x),
                         training=False)
    assert got.shape == (5, 7)
    assert np.array_equal(got, np.asarray(ref))


def test_bucket_padding_parity_bf16(tiny_net):
    model, params = tiny_net
    eng = InferenceEngine(model, params, buckets=(8,),
                          compute_dtype=jnp.bfloat16)
    x = np.random.RandomState(1).randn(3, 12).astype(np.float32)
    got = eng.predict_scores(x)

    def ref_fwd(x):
        y, _ = model.apply(params, model.init_state(),
                           jnp.asarray(x).astype(jnp.bfloat16),
                           training=False)
        return np.asarray(y)

    # padding rows must not perturb real rows even in bf16 (rows are
    # independent through Linear/ReLU/LogSoftMax)
    assert np.array_equal(got, ref_fwd(x))


def test_engine_chunks_past_largest_bucket(tiny_net):
    model, params = tiny_net
    reg = MetricsRegistry()
    eng = InferenceEngine(model, params, buckets=(2, 4), metrics=reg)
    x = np.random.RandomState(2).randn(9, 12).astype(np.float32)
    got = eng.predict_scores(x)  # 4 + 4 + 1->bucket2 (1 pad row)
    ref, _ = model.apply(params, model.init_state(), jnp.asarray(x),
                         training=False)
    assert np.array_equal(got, np.asarray(ref))
    assert reg._metrics["rows_total"].value == 9
    assert reg._metrics["padded_rows_total"].value == 1
    waste = reg._metrics["padding_waste_fraction"].value
    assert abs(waste - 1 / 10) < 1e-9


def test_engine_compile_cache_bounded(tiny_net):
    model, params = tiny_net
    reg = MetricsRegistry()
    eng = InferenceEngine(model, params, buckets=(2, 8), metrics=reg)
    for n in (1, 2, 5, 7, 8, 2, 1, 6):  # many row counts, two buckets
        eng.predict_scores(
            np.random.RandomState(n).randn(n, 12).astype(np.float32))
    assert reg._metrics["compiles_total"].value == 2


def test_power_of_two_buckets():
    assert power_of_two_buckets(13) == (1, 2, 4, 8, 13)
    assert power_of_two_buckets(32) == (1, 2, 4, 8, 16, 32)
    assert power_of_two_buckets(1) == (1,)
    with pytest.raises(ValueError):
        power_of_two_buckets(0)


def test_engine_matches_classifier_path(tiny_net):
    """cli/predict.py satellite: the bucketed engine must be score-level
    identical to the old full-batch-padded Classifier."""
    from bigdl_tpu.utils import Classifier
    model, params = tiny_net
    x = np.random.RandomState(3).randn(11, 12).astype(np.float32)
    old = Classifier(model, params, batch_size=8).predict_scores(x)
    new = InferenceEngine(model, params,
                          buckets=power_of_two_buckets(8)
                          ).predict_scores(x)
    assert np.array_equal(old, new)


# ----------------------------------------------------- KV-cache decode
def test_decode_parity_per_token(tiny_lm):
    """Per-token: bucketed prefill + slot decode == full-sequence
    forward argmax at every step (the acceptance bit-identity)."""
    model, params = tiny_lm
    de = DecodeEngine(model, params, slots=2)
    prompt = [3, 1, 4, 1, 5]
    got = de.generate(prompt, 8)
    ref = _offline_greedy(model, params, prompt, 8)
    assert got == ref


def test_decode_continuous_batching_parity(tiny_lm):
    """Two concurrent requests of DIFFERENT prompt lengths share the
    decode batch and still match their individual offline decodes."""
    model, params = tiny_lm
    de = DecodeEngine(model, params, slots=2)
    f1 = de.submit([7, 8], 6)
    f2 = de.submit([1, 2, 3, 4, 5, 6, 7], 6)
    steps = 0
    while not (f1.done() and f2.done()):
        assert de.step() > 0
        steps += 1
        assert steps < 50
    assert f1.result() == _offline_greedy(model, params, [7, 8], 6)
    assert f2.result() == _offline_greedy(model, params,
                                          [1, 2, 3, 4, 5, 6, 7], 6)


def test_decode_slot_reuse_after_finish(tiny_lm):
    """A finishing request frees its slot for a waiting one (continuous
    batching); the late request still decodes exactly."""
    model, params = tiny_lm
    de = DecodeEngine(model, params, slots=1)
    f1 = de.submit([9, 9], 3)
    f2 = de.submit([2, 3, 4], 3)  # waits for the single slot
    while not f2.done():
        de.step()
    assert f1.result() == _offline_greedy(model, params, [9, 9], 3)
    assert f2.result() == _offline_greedy(model, params, [2, 3, 4], 3)


def test_decode_validates_length_budget(tiny_lm):
    model, params = tiny_lm
    de = DecodeEngine(model, params, slots=1)
    with pytest.raises(ValueError):
        de.submit(list(range(60)), 10)  # 60 + 10 > max_len 64
    with pytest.raises(ValueError):
        de.submit([], 4)


def test_decode_admission_fast_reject(tiny_lm):
    model, params = tiny_lm
    reg = MetricsRegistry()
    de = DecodeEngine(model, params, slots=1, max_waiting=0, metrics=reg)
    de.submit([1, 2], 2)  # occupies the only slot
    with pytest.raises(AdmissionError):
        de.submit([3, 4], 2)
    assert reg._metrics["decode_rejected_total"].value == 1


def test_serving_prefill_buckets():
    from bigdl_tpu.ops.attention_kernel import serving_prefill_buckets
    b = serving_prefill_buckets(512, 64, True, jnp.float32)
    assert b[-1] == 512 and b[0] >= 16
    assert list(b) == sorted(set(b))
    assert serving_prefill_buckets(64, 64)[-1] == 64


# ------------------------------------------------------------ batcher
def _sum_predict(batch):
    return batch.sum(axis=tuple(range(1, batch.ndim)))[:, None]


def test_batcher_max_wait_trigger_injected_clock():
    t = [0.0]
    b = MicroBatcher(_sum_predict, max_batch=4, max_wait_ms=10,
                     clock=lambda: t[0], start=False)
    futs = [b.submit(np.full(3, i, np.float32)) for i in range(3)]
    assert b.pump(0.0) == 0          # neither trigger fired
    assert b.pump(0.0099) == 0       # just under max_wait
    assert b.pump(0.0101) == 3       # oldest aged past max_wait
    assert [f.result(0) [0] for f in futs] == [0.0, 3.0, 6.0]


def test_batcher_max_batch_trigger_injected_clock():
    t = [0.0]
    b = MicroBatcher(_sum_predict, max_batch=2, max_wait_ms=1000,
                     clock=lambda: t[0], start=False)
    f1 = b.submit(np.ones(3, np.float32))
    assert b.pump(0.0) == 0
    f2 = b.submit(np.ones(3, np.float32))
    assert b.pump(0.0) == 2          # full batch flushes with zero age
    f3 = b.submit(np.ones(3, np.float32))
    assert b.pump(0.0) == 0          # the straggler waits again
    assert f1.result(0)[0] == 3.0 and f2.result(0)[0] == 3.0
    assert not f3.done()


def test_batcher_admission_fast_reject():
    reg = MetricsRegistry()
    b = MicroBatcher(_sum_predict, max_batch=4, max_queue=2,
                     clock=lambda: 0.0, start=False, metrics=reg)
    b.submit(np.ones(3))
    b.submit(np.ones(3))
    with pytest.raises(AdmissionError):
        b.submit(np.ones(3))
    assert reg._metrics["batcher_rows_rejected_total"].value == 1
    assert reg._metrics["batcher_rows_submitted_total"].value == 2
    assert b.queue_depth == 2


def test_batcher_propagates_engine_errors():
    def boom(batch):
        raise RuntimeError("engine down")
    t = [1.0]
    b = MicroBatcher(boom, max_batch=1, max_wait_ms=0,
                     clock=lambda: t[0], start=False)
    fut = b.submit(np.ones(2))
    b.pump(2.0)
    with pytest.raises(RuntimeError, match="engine down"):
        fut.result(0)


def test_batcher_threaded_end_to_end(tiny_net):
    """Real worker thread + real clock: concurrent submits coalesce into
    engine batches and every future resolves."""
    model, params = tiny_net
    eng = InferenceEngine(model, params, buckets=(1, 2, 4, 8))
    reg = MetricsRegistry()
    b = MicroBatcher(eng.predict_scores, max_batch=8, max_wait_ms=20,
                     metrics=reg)
    try:
        x = np.random.RandomState(4).randn(6, 12).astype(np.float32)
        futs = [b.submit(row) for row in x]
        got = np.stack([f.result(30.0) for f in futs])
        ref, _ = model.apply(params, model.init_state(), jnp.asarray(x),
                             training=False)
        assert np.array_equal(got, np.asarray(ref))
        assert reg._metrics["batcher_flushes_total"].value >= 1
    finally:
        b.close()


# ------------------------------------------------------------- metrics
def test_histogram_quantiles():
    h = Histogram("lat", bounds=(1.0, 2.0, 4.0, 8.0))
    for v in (0.5, 1.5, 3.0, 7.0):
        h.observe(v)
    assert h.count == 4 and abs(h.sum - 12.0) < 1e-9
    # rank interpolation: p50 lands at the (1,2] bucket's upper edge
    assert abs(h.quantile(0.5) - 2.0) < 1e-9
    assert abs(h.quantile(0.99) - 7.84) < 1e-6
    assert abs(h.quantile(0.0) - 0.0) < 1e-9
    # overflow bucket reports the observed max, not +Inf
    h.observe(20.0)
    assert h.quantile(1.0) == 20.0
    assert np.isnan(Histogram("e", bounds=(1,)).quantile(0.5))


def test_metrics_render_exposition():
    reg = MetricsRegistry(namespace="t")
    reg.counter("reqs", "requests").inc(3)
    reg.gauge("depth", fn=lambda: 7).value
    h = reg.histogram("lat_ms", bounds=(1.0, 10.0))
    h.observe(0.5)
    h.observe(5.0)
    reg.set_provenance({"model": "x", "buckets": "1,2"})
    page = reg.render()
    assert "# TYPE t_reqs counter" in page
    assert "t_reqs 3" in page
    assert "t_depth 7" in page
    assert 't_lat_ms_bucket{le="1"} 1' in page
    assert 't_lat_ms_bucket{le="+Inf"} 2' in page
    assert "t_lat_ms_count 2" in page
    assert 't_lat_ms{quantile="0.5"}' in page
    prov_lines = [l for l in page.splitlines()
                  if l.startswith("# provenance ")]
    assert len(prov_lines) == 1
    assert json.loads(prov_lines[0][len("# provenance "):]) == {
        "model": "x", "buckets": "1,2"}
    assert 't_info{buckets="1,2",model="x"} 1' in page


def test_metrics_render_with_empty_histogram():
    """An endpoint nobody hit yet must not break the scrape: empty
    histogram quantiles render as NaN, not a handler crash (the lenet5
    smoke regression — /metrics after /predict only, generate empty)."""
    reg = MetricsRegistry(namespace="t")
    reg.histogram("never_hit_ms")
    page = reg.render()
    assert 't_never_hit_ms{quantile="0.5"} NaN' in page


def test_metrics_type_clash_rejected():
    reg = MetricsRegistry()
    reg.counter("a")
    with pytest.raises(TypeError):
        reg.histogram("a")


# ------------------------------------------- inference-only restore
def test_restore_for_inference_from_dir(tmp_path, tiny_net):
    from bigdl_tpu.utils.file import save_pytree
    from bigdl_tpu.utils.orbax_ckpt import restore_for_inference
    model, params = tiny_net
    ck = tmp_path / "ckpt"
    ck.mkdir()
    save_pytree({"params": params, "mod_state": model.init_state(),
                 "driver": {"epoch": 1, "iteration": 3}},
                str(ck / "model.3"))
    save_pytree({"params": params, "mod_state": model.init_state(),
                 "driver": {"epoch": 2, "iteration": 9}},
                str(ck / "model.9"))
    save_pytree({"momentum": params}, str(ck / "state.9"))
    p, ms = restore_for_inference(str(ck))  # picks model.9, ignores state
    ref = jax.tree_util.tree_leaves(params)
    got = jax.tree_util.tree_leaves(p)
    assert all(np.array_equal(a, b) for a, b in zip(ref, got))


def test_restore_for_inference_missing_and_corrupt(tmp_path):
    from bigdl_tpu.utils.orbax_ckpt import restore_for_inference
    with pytest.raises(SystemExit, match="does not exist"):
        restore_for_inference(str(tmp_path / "nope"))
    bad = tmp_path / "model.1"
    bad.write_bytes(b"not a checkpoint at all")
    with pytest.raises(SystemExit, match="failed to load"):
        restore_for_inference(str(bad))
    from bigdl_tpu.utils.file import save_pytree
    state_only = tmp_path / "state.1"
    save_pytree({"momentum": {"w": np.ones(3)}}, str(state_only))
    with pytest.raises(SystemExit, match="no 'params'"):
        restore_for_inference(str(state_only))


# --------------------------------------------------- end-to-end HTTP smoke
def _post(port, path, body, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _get(port, path, timeout=30):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=timeout) as r:
        return r.status, r.read().decode()


def test_serve_http_end_to_end(tmp_path, tiny_lm):
    """The acceptance contract on CPU: `serve transformer_lm` answers a
    /generate whose tokens are bit-identical to the offline
    full-sequence argmax decode of the same checkpoint, /predict works
    through the micro-batcher, and /metrics reports non-zero request and
    latency counters with config provenance."""
    from bigdl_tpu.cli import common, serve as serve_cli
    from bigdl_tpu.serving import make_server
    from bigdl_tpu.utils.file import save_pytree

    model, params = tiny_lm
    ck = tmp_path / "ckpt"
    ck.mkdir()
    save_pytree({"params": params, "mod_state": model.init_state(),
                 "driver": {"epoch": 1, "iteration": 7}},
                str(ck / "model.7"))

    args = serve_cli.build_parser().parse_args(
        ["transformer_lm", "--model", str(ck), "--vocabSize", "50",
         "--dModel", "32", "--numLayers", "2", "--numHeads", "2",
         "--seq", "64", "--slots", "2", "--buckets", "1,2,4",
         "--maxWaitMs", "2", "--lint"])
    common.apply_platform(args)
    app, eng, in_shape, in_dtype = serve_cli.build_app(args)
    assert in_shape == (64,) and in_dtype == np.int32
    srv = make_server(app, "127.0.0.1", 0)
    port = srv.server_address[1]
    thr = threading.Thread(target=srv.serve_forever, daemon=True)
    thr.start()
    try:
        st, body = _get(port, "/healthz")
        assert st == 200 and body == (
            '{"status": "ok", "model": "transformer_lm"}')

        prompt = [3, 1, 4, 1, 5]
        st, out = _post(port, "/generate",
                        {"tokens": prompt, "max_new_tokens": 6})
        assert st == 200
        assert out["tokens"] == _offline_greedy(model, params, prompt, 6)

        toks = np.random.RandomState(0).randint(
            0, 50, (3, 64)).tolist()
        st, out = _post(port, "/predict", {"inputs": toks})
        assert st == 200
        assert np.asarray(out["predictions"]).shape == (3, 64)

        st, out = _post(port, "/generate",
                        {"tokens": [1] * 70, "max_new_tokens": 4})
        assert st == 400 and "exceeds" in out["error"]
        st, out = _post(port, "/predict", {"inputs": "garbage"})
        assert st == 400

        st, page = _get(port, "/metrics")
        assert st == 200
        prov = json.loads(
            [l for l in page.splitlines()
             if l.startswith("# provenance ")][0][len("# provenance "):])
        assert prov["model"] == "transformer_lm"
        assert prov["buckets"] == "1,2,4"
        assert prov["decode_slots"] == 2
        assert prov["bn_fused"] == "off"
        assert prov["autotune"] == "off"
        assert prov["lint"] == "0e/0w/0i"

        def metric(name):
            for line in page.splitlines():
                if line.startswith(name + " "):
                    return float(line.split()[-1])
            return None

        assert metric("bigdl_serving_requests_generate_total") == 1
        assert metric("bigdl_serving_requests_predict_total") == 1
        assert metric("bigdl_serving_latency_generate_ms_count") == 1
        assert metric("bigdl_serving_latency_predict_ms_count") == 1
        assert metric("bigdl_serving_generated_tokens_total") == 6
        assert metric("bigdl_serving_rows_total") == 3
    finally:
        srv.shutdown()
        srv.server_close()
        app.close()
    thr.join(10.0)
    assert not thr.is_alive()


def test_serve_requires_weights():
    from bigdl_tpu.cli import serve as serve_cli
    args = serve_cli.build_parser().parse_args(["lenet5"])
    with pytest.raises(SystemExit, match="needs weights"):
        serve_cli.build_app(args)
