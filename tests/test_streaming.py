"""Streaming /generate + mid-decode cancellation (ISSUE 18 tentpole b):
the emit sink contract on DecodeEngine (plain and speculative — only
ACCEPTED tokens ever reach a stream), first-class ``cancel(rid)``
including the slot/page cleanup and the verify-dispatch interleave, and
the HTTP layer end-to-end — chunked-transfer SSE framing, streamed
output bit-identical to buffered, first-byte TTFT feeding the --slo
histograms, and client-disconnect cancellation with no leaked KV
pages."""

import http.client
import json
import socket
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from bigdl_tpu import models
from bigdl_tpu.serving import DecodeEngine, MetricsRegistry


@pytest.fixture(scope="module")
def tiny_lm():
    m = models.transformer_lm(50, d_model=32, num_layers=2, num_heads=2,
                              max_len=64)
    return m, m.init(jax.random.PRNGKey(1))


def _offline_greedy(model, params, prompt, n):
    seq = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        logp, _ = model.apply(params, model.init_state(),
                              np.asarray([seq], np.int32))
        tok = int(np.argmax(np.asarray(logp)[0, -1]))
        out.append(tok)
        seq.append(tok)
    return out


def _drive(de, *futs):
    steps = 0
    while not all(f.done() for f in futs):
        de.step()
        steps += 1
        assert steps < 200
    return steps


# ------------------------------------------------ emit sink (engine level)
def test_decode_emit_streams_every_token(tiny_lm):
    """The emit sink sees every generated token exactly once, in order,
    with done=True on the last call — and the request's buffered result
    is unchanged by having a sink attached (bit-identity is structural:
    the same _emit feeds both)."""
    model, params = tiny_lm
    de = DecodeEngine(model, params, slots=2)
    chunks = []
    fut = de.submit([3, 1, 4, 1, 5], 6,
                    emit=lambda toks, done: chunks.append(
                        (list(toks), done)))
    _drive(de, fut)
    got = fut.result()
    assert got == _offline_greedy(model, params, [3, 1, 4, 1, 5], 6)
    assert [t for toks, _ in chunks for t in toks] == got
    assert all(toks for toks, _ in chunks)
    assert [d for _, d in chunks] == [False] * (len(chunks) - 1) + [True]


@pytest.mark.slow
def test_decode_emit_speculative_accepted_only(tiny_lm):
    """Under speculative decoding the sink must only ever see ACCEPTED
    tokens — the streamed concatenation equals the plain engine's
    output bit for bit, never a speculated-then-rejected draft."""
    model, params = tiny_lm
    prompt = [7, 8, 9, 10]
    plain = DecodeEngine(model, params, slots=2).generate(prompt, 8)
    de = DecodeEngine(model, params, slots=2, speculate=4)
    chunks = []
    fut = de.submit(prompt, 8,
                    emit=lambda toks, done: chunks.append(list(toks)))
    _drive(de, fut)
    assert fut.result() == plain
    assert [t for toks in chunks for t in toks] == plain


# --------------------------------------------------- cancel (engine level)
def test_cancel_waiting_request(tiny_lm):
    model, params = tiny_lm
    de = DecodeEngine(model, params, slots=1)
    f1 = de.submit([9, 9], 3, rid="keep")
    f2 = de.submit([2, 3, 4], 3, rid="drop")  # waits for the one slot
    assert de.cancel("drop") is True
    with pytest.raises(RuntimeError, match="cancelled"):
        f2.result(timeout=0)
    _drive(de, f1)
    assert f1.result() == _offline_greedy(model, params, [9, 9], 3)
    # cancelling the same rid again (or a finished one) is a no-op
    assert de.cancel("drop") is False
    assert de.cancel("keep") is False
    assert de.cancel(None) is False


def test_cancel_active_frees_slot_and_pages(tiny_lm):
    """Cancelling a request mid-decode releases its slot AND its paged-KV
    reservation (kv_pages_in_use back to zero), and the freed slot
    decodes a fresh request exactly — the stale pending sampled token
    from the cancelled occupant must not leak into the next install."""
    model, params = tiny_lm
    reg = MetricsRegistry()
    de = DecodeEngine(model, params, slots=2, kv_page_tokens=8,
                      metrics=reg)
    fut = de.submit([5, 6, 7], 40, rid="gone")
    for _ in range(3):
        de.step()
    assert de.kv_pages_in_use() > 0
    assert de.cancel("gone") is True
    with pytest.raises(RuntimeError, match="cancelled"):
        fut.result(timeout=0)
    assert de.kv_pages_in_use() == 0
    assert reg._metrics["decode_cancelled_total"].value == 1
    f2 = de.submit([1, 2, 3], 4)
    _drive(de, f2)
    assert f2.result() == _offline_greedy(model, params, [1, 2, 3], 4)
    assert de.kv_pages_in_use() == 0


@pytest.mark.slow
def test_cancel_speculative_interleaved_with_steps(tiny_lm):
    """The verify-dispatch/accept race: cancel() fired from another
    thread while a speculative engine is stepping. The lock discipline
    (step holds the engine lock for the whole draft/verify/accept
    round) means the cancel lands between rounds — the cancelled future
    fails, the survivor stays bit-identical, nothing deadlocks."""
    model, params = tiny_lm
    prompt = [1, 2, 3, 4, 5]
    plain = DecodeEngine(model, params, slots=2).generate(prompt, 10)
    de = DecodeEngine(model, params, slots=2, speculate=3)
    keep = de.submit(prompt, 10, rid="keep")
    drop = de.submit([6, 7, 8], 30, rid="drop")
    stop = threading.Event()

    def _stepper():
        while not stop.is_set() and not keep.done():
            de.step()

    thr = threading.Thread(target=_stepper)
    thr.start()
    try:
        time.sleep(0.05)  # let both requests get in flight
        assert de.cancel("drop") is True
        keep.result(timeout=60)
    finally:
        stop.set()
        thr.join(30)
    assert not thr.is_alive()
    with pytest.raises(RuntimeError, match="cancelled"):
        drop.result(timeout=0)
    assert keep.result() == plain


# -------------------------------------------------------- HTTP streaming
# The HTTP tier spins a full in-process server (bucketed compiles) —
# `slow`-marked out of the tier-1 sweep; the tier1.yml
# throughput-smoke job runs this file unfiltered on every push.
@pytest.fixture(scope="module")
def stream_server():
    """One in-process server with the full composition on: speculative
    decoding, paged KV, lifecycle tracing, SLOs."""
    from bigdl_tpu.cli import common, serve as serve_cli
    from bigdl_tpu.serving import make_server

    args = serve_cli.build_parser().parse_args(
        ["transformer_lm", "--randomInit", "--vocabSize", "50",
         "--dModel", "32", "--numLayers", "2", "--numHeads", "2",
         "--seq", "64", "--slots", "2", "--buckets", "1,2,4",
         "--maxWaitMs", "2", "--speculate", "3", "--kvPageTokens", "16",
         "--reqTrace", "on", "--slo", "ttft=60000,tpot=60000"])
    common.apply_platform(args)
    app, eng, in_shape, in_dtype = serve_cli.build_app(args)
    srv = make_server(app, "127.0.0.1", 0)
    port = srv.server_address[1]
    thr = threading.Thread(target=srv.serve_forever, daemon=True)
    thr.start()
    try:
        yield port
    finally:
        srv.shutdown()
        srv.server_close()
        app.close()


def _post(port, path, body, timeout=120):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=30) as r:
        return r.status, r.read().decode()


def _metric(page, name):
    for line in page.splitlines():
        parts = line.split()
        if len(parts) == 2 and parts[0] in (name,
                                            "bigdl_serving_" + name):
            try:
                return float(parts[1])
            except ValueError:
                return None
    return None


def _stream(port, body, read_frames=None, timeout=120):
    """Streamed /generate via http.client (which undoes the chunked
    framing). Returns (status, frames, t_first_s, conn_or_None)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    t0 = time.perf_counter()
    conn.request("POST", "/generate",
                 json.dumps({**body, "stream": True}).encode(),
                 {"Content-Type": "application/json"})
    resp = conn.getresponse()
    if resp.status != 200:
        out = json.loads(resp.read() or b"{}")
        conn.close()
        return resp.status, out, None, None
    frames, t_first, buf = [], None, b""
    while True:
        b1 = resp.read(1)
        if not b1:
            break
        if t_first is None:
            t_first = time.perf_counter() - t0
        buf += b1
        while b"\n\n" in buf:
            raw, buf = buf.split(b"\n\n", 1)
            if raw.startswith(b"data: "):
                frames.append(json.loads(raw[len(b"data: "):]))
        if read_frames is not None and len(
                [f for f in frames if "tokens" in f]) >= read_frames:
            return resp.status, frames, t_first, conn
        if frames and frames[-1].get("done"):
            break
    conn.close()
    return resp.status, frames, t_first, None


@pytest.mark.slow
def test_stream_chunked_sse_wire_framing(stream_server):
    """Raw-socket check of the wire format: chunked transfer encoding
    (hex-length frames, 0-terminator), text/event-stream content type,
    and every chunk decoding to ``data: {json}`` SSE frames."""
    port = stream_server
    body = json.dumps({"tokens": [3, 1, 4], "max_new_tokens": 4,
                       "stream": True}).encode()
    with socket.create_connection(("127.0.0.1", port), timeout=60) as s:
        s.sendall(b"POST /generate HTTP/1.1\r\n"
                  b"Host: 127.0.0.1\r\n"
                  b"Content-Type: application/json\r\n"
                  + b"Content-Length: %d\r\n\r\n" % len(body) + body)
        s.settimeout(60)
        raw = b""
        while b"0\r\n\r\n" not in raw:
            got = s.recv(4096)
            if not got:
                break
            raw += got
    head, _, payload = raw.partition(b"\r\n\r\n")
    headers = head.decode().lower()
    assert "http/1.1 200" in headers
    assert "transfer-encoding: chunked" in headers
    assert "content-type: text/event-stream" in headers
    assert "x-request-id:" in headers
    # undo the chunked framing by hand: <hex>\r\n<data>\r\n ... 0\r\n\r\n
    frames_raw, rest = b"", payload
    while rest:
        size_s, _, rest = rest.partition(b"\r\n")
        size = int(size_s, 16)
        if size == 0:
            break
        frames_raw += rest[:size]
        assert rest[size:size + 2] == b"\r\n"
        rest = rest[size + 2:]
    frames = [json.loads(f[len(b"data: "):])
              for f in frames_raw.split(b"\n\n") if f]
    assert all(("tokens" in f) or f.get("done") for f in frames)
    final = frames[-1]
    assert final["done"] is True and final["prompt_len"] == 3
    assert final["tokens_out"] == sum(
        len(f["tokens"]) for f in frames if "tokens" in f) == 4


@pytest.mark.slow
def test_stream_bit_identical_to_buffered(stream_server):
    """Streamed tokens, concatenated, equal the buffered response for
    the same prompt — with the speculative path ON, so only accepted
    tokens ever reached the stream."""
    port = stream_server
    for prompt in ([3, 1, 4, 1, 5], list(range(5, 21)), [2, 2, 2]):
        body = {"tokens": prompt, "max_new_tokens": 12,
                "temperature": 0.0}
        st, ref = _post(port, "/generate", body)
        assert st == 200
        st, frames, _, _ = _stream(port, body)
        assert st == 200
        toks = [t for f in frames if "tokens" in f for t in f["tokens"]]
        assert toks == ref["tokens"]
        assert frames[-1]["tokens_out"] == len(toks)
        assert frames[-1]["prompt_len"] == len(prompt)


@pytest.mark.slow
def test_stream_first_byte_ttft_feeds_slo(stream_server):
    """TTFT is measured at first-byte-out for streamed requests and
    feeds the same --slo histograms/goodput accounting as buffered
    ones."""
    port = stream_server
    _, page = _get(port, "/metrics")
    done0 = _metric(page, "slo_requests_total") or 0
    st, frames, t_first, _ = _stream(
        port, {"tokens": [1, 2, 3, 4], "max_new_tokens": 8})
    assert st == 200 and frames[-1].get("done")
    assert t_first is not None
    _, page = _get(port, "/metrics")
    assert (_metric(page, "slo_requests_total") or 0) == done0 + 1
    assert (_metric(page, "slo_good_total") or 0) >= done0 + 1
    # the server-side ttft histogram populated from the stream
    count = _metric(page, "ttft_ms_count")
    assert count is not None and count >= 1


@pytest.mark.slow
def test_stream_disconnect_cancels_and_frees(stream_server):
    """A client that walks away mid-stream: the slot is cancelled
    (decode_cancelled_total moves), its KV pages return to the pool,
    the request lands terminal state ``closed`` in /debug/requests, and
    the freed slot serves the next request."""
    port = stream_server
    _, page = _get(port, "/metrics")
    base_pages = _metric(page, "kv_pages_in_use") or 0
    base_cancel = _metric(page, "decode_cancelled_total") or 0
    st, frames, _, conn = _stream(
        port, {"tokens": [1, 2, 3, 4, 5, 6, 7, 8],
               "max_new_tokens": 48}, read_frames=1)
    assert st == 200 and conn is not None
    conn.close()  # mid-decode disconnect
    deadline = time.time() + 60
    while time.time() < deadline:
        _, page = _get(port, "/metrics")
        if ((_metric(page, "decode_cancelled_total") or 0) > base_cancel
                and (_metric(page, "kv_pages_in_use")
                     or 0) <= base_pages):
            break
        time.sleep(0.1)
    assert (_metric(page, "decode_cancelled_total") or 0) == \
        base_cancel + 1, "disconnect never cancelled the slot"
    assert (_metric(page, "kv_pages_in_use") or 0) <= base_pages, \
        "leaked KV page reservations after disconnect"
    st, txt = _get(port, "/debug/requests")
    assert st == 200
    recent = json.loads(txt).get("recent", [])
    assert any(r.get("state") == "closed" for r in recent), recent
    st, out = _post(port, "/generate",
                    {"tokens": [4, 5, 6], "max_new_tokens": 4})
    assert st == 200 and len(out["tokens"]) == 4


@pytest.mark.slow
def test_stream_bad_request_is_plain_json(stream_server):
    """Pre-stream failures (validation) come back as ordinary JSON
    errors, not as a 200 SSE stream."""
    port = stream_server
    st, out, _, _ = _stream(stream_server,
                            {"tokens": [1] * 70, "max_new_tokens": 4})
    assert st == 400 and "exceeds" in out["error"]
    st, out, _, _ = _stream(port, {"tokens": [], "max_new_tokens": 4})
    assert st == 400
