"""shardlint (ISSUE 19): positive + negative cases per rule group on
hand-built sharded jaxprs (AbstractMesh — zero devices committed) AND
real perf-zoo models over virtual meshes, plus the flagship zero-error
regression pin, the serving-unsharded-matmul alias contract, the
ResolvedConfig spine, and CLI smoke for the composed `lint` command."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import (AbstractMesh, Mesh, NamedSharding,
                          PartitionSpec as P)

from bigdl_tpu.analysis import (CATALOG, SHARD_CATALOG,
                                run_kv_sharding_rules,
                                run_replicated_operand_rules,
                                run_sharding_rules,
                                trace_sharded_train_step)
from bigdl_tpu.parallel.grad_comm import make_config

AM = AbstractMesh((("data", 2), ("model", 4)))
BIG = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)  # 4 MiB


def _trace(fn, *args, in_shardings=None):
    f = jax.jit(fn, in_shardings=in_shardings)
    return jax.make_jaxpr(f)(*args)


def errors(rep, rule=None):
    return [f for f in rep.findings if f.severity == "error"
            and (rule is None or f.rule == rule)]


# ------------------------------------------------------------- catalog
def test_shard_catalog_merged_into_main_catalog():
    for rule, (fam, sev, desc) in SHARD_CATALOG.items():
        assert rule in CATALOG, rule
        assert fam == "sharding", rule
        assert sev in ("error", "warning"), rule
        assert desc, rule


# ============================== group 1: strategy/collective consistency
def test_undeclared_axis_in_constraint_is_error():
    def f(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(AM, P("model", None))) * 2.0
    rep = run_sharding_rules(_trace(f, BIG), mesh_axes={"data": 2},
                             strategy="dp")
    hits = errors(rep, "shard-collective-axis")
    assert hits and "model" in str(hits[0].detail["axes"])


def test_declared_axis_in_constraint_is_clean():
    def f(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(AM, P("model", None))) * 2.0
    rep = run_sharding_rules(_trace(f, BIG),
                             mesh_axes={"data": 2, "model": 4},
                             strategy="tp")
    assert not errors(rep, "shard-collective-axis")


def test_unreferenced_mesh_axis_is_missing_signature():
    def f(x):
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(AM, P("data", None))) * 2.0
    rep = run_sharding_rules(_trace(f, BIG),
                             mesh_axes={"data": 2, "model": 4},
                             strategy="tp")
    hits = errors(rep, "shard-collective-missing")
    assert hits and any(h.detail.get("axis") == "model" for h in hits)


def test_grad_compress_with_no_16bit_bucket_is_missing():
    gc = make_config("bf16", "auto")
    assert gc.active

    def f(x):  # f32 constraint only — the compressed path never engaged
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(AM, P("data", "model"))) * 2.0
    rep = run_sharding_rules(_trace(f, BIG),
                             mesh_axes={"data": 2, "model": 4},
                             strategy="dp", grad_comm=gc)
    hits = errors(rep, "shard-collective-missing")
    assert any(h.where == "grad_comm" for h in hits)


def test_grad_compress_with_bf16_bucket_is_clean():
    gc = make_config("bf16", "auto")

    def f(x):
        b = jax.lax.with_sharding_constraint(
            x.astype(jnp.bfloat16), NamedSharding(AM, P()))
        return jax.lax.with_sharding_constraint(
            x * 1.5, NamedSharding(AM, P("data", "model"))) \
            + b.astype(jnp.float32)
    rep = run_sharding_rules(_trace(f, BIG),
                             mesh_axes={"data": 2, "model": 4},
                             strategy="dp", grad_comm=gc)
    assert not any(h.where == "grad_comm"
                   for h in errors(rep, "shard-collective-missing"))


def test_explicit_collective_outside_strategy_is_extra():
    # shard_map graphs are the only place explicit collectives appear;
    # conftest pins 8 host devices so a real 2x4 mesh exists
    from jax.experimental.shard_map import shard_map
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(2, 4),
                ("data", "model"))
    g = shard_map(lambda x: jax.lax.psum(x, "model"), mesh=mesh,
                  in_specs=P("data", None), out_specs=P("data", None))
    closed = jax.make_jaxpr(g)(jax.ShapeDtypeStruct((8, 512),
                                                    jnp.float32))
    rep = run_sharding_rules(closed, mesh_axes={"data": 2, "model": 4},
                             strategy="dp")
    assert errors(rep, "shard-collective-extra")
    # the same psum is legitimate under tp (model is an expected axis)
    rep2 = run_sharding_rules(closed, mesh_axes={"data": 2, "model": 4},
                              strategy="tp")
    assert not errors(rep2, "shard-collective-extra")


# ======================================= group 3: wire dtype and remat
def test_f32_replication_point_under_grad_compress_is_error():
    gc = make_config("bf16", "auto")

    def f(x):
        b = jax.lax.with_sharding_constraint(  # satisfies signature (b)
            x[:1].astype(jnp.bfloat16), NamedSharding(AM, P()))
        big = jax.lax.with_sharding_constraint(  # 4 MiB f32 on the wire
            x * 2.0, NamedSharding(AM, P()))
        return big + b.astype(jnp.float32)
    rep = run_sharding_rules(_trace(f, BIG),
                             mesh_axes={"data": 2, "model": 4},
                             strategy="dp", grad_comm=gc)
    hits = errors(rep, "shard-wire-dtype")
    assert hits and hits[0].detail["compress"] == "bf16"


def test_wire_dtype_silent_without_grad_compress():
    def f(x):
        return jax.lax.with_sharding_constraint(
            x * 2.0, NamedSharding(AM, P()))
    rep = run_sharding_rules(_trace(f, BIG),
                             mesh_axes={"data": 2, "model": 4},
                             strategy="dp", grad_comm=None)
    assert not rep.by_rule("shard-wire-dtype")


def test_quant_remat_before_boundary_is_warning():
    q = jax.ShapeDtypeStruct((1024, 1024), jnp.int8)

    def f(w):
        dense = w.astype(jnp.float32) * 0.02  # 4 MiB rematerialized
        return jax.lax.with_sharding_constraint(
            dense, NamedSharding(AM, P()))
    rep = run_sharding_rules(_trace(f, q),
                             mesh_axes={"data": 2, "model": 4})
    hits = rep.by_rule("shard-quant-remat-wire")
    assert hits and hits[0].severity == "warning"
    assert hits[0].detail["src_dtype"] == "int8"


def test_quant_kept_8bit_across_boundary_is_clean():
    q = jax.ShapeDtypeStruct((1024, 1024), jnp.int8)

    def f(w):
        w8 = jax.lax.with_sharding_constraint(
            w, NamedSharding(AM, P(None, "model")))
        return w8.astype(jnp.float32) * 0.02  # dequant AFTER the wire
    rep = run_sharding_rules(_trace(f, q),
                             mesh_axes={"data": 2, "model": 4})
    assert not rep.by_rule("shard-quant-remat-wire")


# ============================================== group 4: reshard churn
def test_conflicting_consecutive_constraints_are_churn():
    def f(x):
        a = jax.lax.with_sharding_constraint(
            x, NamedSharding(AM, P("model", None)))
        return jax.lax.with_sharding_constraint(
            a, NamedSharding(AM, P(None, "model")))
    rep = run_sharding_rules(_trace(f, BIG),
                             mesh_axes={"data": 2, "model": 4})
    hits = rep.by_rule("shard-reshard-churn")
    assert hits and hits[0].severity == "warning"
    assert hits[0].detail["wasted_bytes"] > 0


def test_stable_layout_is_not_churn():
    def f(x):
        a = jax.lax.with_sharding_constraint(
            x, NamedSharding(AM, P("model", None)))
        return jax.lax.with_sharding_constraint(
            a * 2.0, NamedSharding(AM, P("model", None)))
    rep = run_sharding_rules(_trace(f, BIG),
                             mesh_axes={"data": 2, "model": 4})
    assert not rep.by_rule("shard-reshard-churn")


# ======================================= group 2: replicated operands
def _abstract_params():
    return {"emb": {"w": jax.ShapeDtypeStruct((4096, 512), jnp.float32)},
            "bias": jax.ShapeDtypeStruct((512,), jnp.float32)}


def test_replicated_big_operand_under_model_axis_is_error():
    specs = {"emb": {"w": P()}, "bias": P()}
    rep = run_replicated_operand_rules(_abstract_params(),
                                       {"data": 2, "model": 4},
                                       specs=specs)
    hits = errors(rep, "shard-replicated-operand")
    assert len(hits) == 1  # the 1-D bias never fires
    assert "emb" in hits[0].where
    assert "model" in hits[0].detail["splittable_axes"]


def test_split_spec_is_clean_and_data_axis_never_fires():
    specs = {"emb": {"w": P(None, "model")}, "bias": P()}
    rep = run_replicated_operand_rules(_abstract_params(),
                                       {"data": 2, "model": 4},
                                       specs=specs)
    assert not rep.findings
    # a pure-dp mesh replicates params BY DESIGN
    rep2 = run_replicated_operand_rules(
        _abstract_params(), {"data": 8},
        specs={"emb": {"w": P()}, "bias": P()})
    assert not rep2.findings


def test_unknown_placement_never_fires():
    # abstract leaves with no spec tree and no committed sharding:
    # placement is unknown, not replicated
    rep = run_replicated_operand_rules(_abstract_params(),
                                       {"data": 2, "model": 4})
    assert not rep.findings


def test_legacy_alias_keeps_pr15_serving_output():
    # the serving-unsharded-matmul spelling only reads PLACED trees and
    # emits the PR 15 finding shape (family serving, tp in detail)
    rep = run_replicated_operand_rules(
        _abstract_params(), {"model": 4}, split_axes=("model",),
        rule_id="serving-unsharded-matmul")
    assert not rep.findings  # abstract tree: placed-only semantics
    placed = {"w": jnp.zeros((1024, 512), jnp.float32)}  # 2 MiB, 1 dev
    rep2 = run_replicated_operand_rules(
        placed, {"model": 4}, split_axes=("model",),
        rule_id="serving-unsharded-matmul")
    hits = rep2.by_rule("serving-unsharded-matmul")
    assert hits and hits[0].family == "serving"
    assert hits[0].detail["tp"] == 4


# ============================================ group 5: KV pool misfit
def _kv_leaf(kv_heads, dtype=jnp.bfloat16):
    # (pool_pages, kv_heads, page_tokens, head_dim) ~ several MiB
    return jax.ShapeDtypeStruct((33, kv_heads, 128, 64), dtype)


def test_kv_heads_not_divisible_by_tp_is_misfit():
    rep = run_kv_sharding_rules({"k": _kv_leaf(6), "v": _kv_leaf(6)},
                                4, page_tokens=128)
    hits = errors(rep, "kv-shard-misfit")
    assert len(hits) == 2
    assert hits[0].detail["kv_heads"] == 6 and hits[0].detail["tp"] == 4


def test_kv_heads_divisible_is_clean_and_tp1_silent():
    rep = run_kv_sharding_rules({"k": _kv_leaf(8), "v": _kv_leaf(8)}, 4)
    assert not rep.findings
    rep2 = run_kv_sharding_rules({"k": _kv_leaf(6)}, 1)
    assert not rep2.findings


# =============================== real models over virtual meshes
def _lm():
    from bigdl_tpu.cli.perf import build_model
    return build_model("transformer_lm", class_num=1000,
                       lm_attn_impl="flash")


def test_flagship_tp_grad_compress_is_zero_errors():
    # the regression pin: transformer_lm tp:2 + bf16 compression is the
    # blessed multichip config and must stay shardlint-clean
    model, in_shape = _lm()
    closed, meta = trace_sharded_train_step(
        model, in_shape, 8, mesh_axes={"data": 2, "model": 2},
        is_lm=True, grad_comm=make_config("bf16", "auto"))
    rep = run_sharding_rules(closed, mesh_axes=meta["mesh_axes"],
                             strategy="tp",
                             grad_comm=make_config("bf16", "auto"),
                             param_specs=meta["param_specs"],
                             params=meta["params"])
    assert not errors(rep), [f.render() for f in errors(rep)[:3]]


def test_missharded_tp3_fires_multiple_groups():
    # 512 % 3 != 0: megatron falls back to full replication — the
    # strategy is a silent no-op AND every big weight replicates
    model, in_shape = _lm()
    closed, meta = trace_sharded_train_step(
        model, in_shape, 8, mesh_axes={"data": 2, "model": 3},
        is_lm=True)
    rep = run_sharding_rules(closed, mesh_axes=meta["mesh_axes"],
                             strategy="tp",
                             param_specs=meta["param_specs"],
                             params=meta["params"])
    rules = {f.rule for f in errors(rep)}
    assert "shard-collective-missing" in rules
    assert "shard-replicated-operand" in rules


# ------------------------------------------------- ResolvedConfig spine
def test_resolve_lint_config_virtual_mesh_and_grad_comm():
    import argparse

    from bigdl_tpu.cli.common import resolve_lint_config
    args = argparse.Namespace(model="transformer_lm", batchSize=8,
                              strategy="tp:4", gradCompress="bf16+ec",
                              gradBuckets="auto", quantize="int8+kv8",
                              speculate=4, kvPageTokens="auto")
    cfg = resolve_lint_config(args)
    assert cfg.mesh == {"data": 2, "model": 4}
    assert cfg.strategy == "tp" and cfg.strategy_k == 4
    assert cfg.make_grad_comm().active
    assert cfg.kv_page_tokens is None  # 'auto' is serve-side only
    assert cfg.describe()["mesh"] == "data:2,model:4"


def test_strategy_lint_spec_metadata():
    from bigdl_tpu.parallel import DataParallel, TensorParallel
    from bigdl_tpu.parallel.mesh import local_mesh
    dp = DataParallel(local_mesh("data"))
    meta = dp.lint_spec_metadata()
    assert meta["strategy"] == "dp" and "data" in meta["mesh_axes"]

    model, _ = _lm()
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "model"))
    tp = TensorParallel(mesh, model)
    params = jax.eval_shape(model.init, jax.random.PRNGKey(0))
    meta = tp.lint_spec_metadata(params)
    leaves = jax.tree_util.tree_leaves(
        meta["param_specs"], is_leaf=lambda x: isinstance(x, P))
    assert any(not all(a is None for a in tuple(sp))
               for sp in leaves if isinstance(sp, P))


# ------------------------------------------------------------ CLI smoke
@pytest.mark.slow
def test_cli_flagship_composed_config_is_clean():
    from bigdl_tpu.cli.lint import main
    rc = main(["transformer_lm", "--strategy", "tp:2",
               "--gradCompress", "bf16", "--quantize", "int8+kv8",
               "--strict"])
    assert rc == 0


@pytest.mark.slow
def test_cli_missharded_config_exits_2_under_strict(capsys):
    from bigdl_tpu.cli.lint import main
    rc = main(["transformer_lm", "--strategy", "tp:3", "--strict"])
    assert rc == 2
    out = capsys.readouterr().out
    assert "shard-" in out


def test_serve_lint_strict_dp_tp_stamps_lint_mesh():
    # ISSUE 19 satellite bugfix: `serve --lint=strict` under dp:N+tp:K
    # lints ONCE on the first replica's tp group (every replica compiles
    # the identical graph) and records the mesh it vetted in provenance
    import json as _json

    from bigdl_tpu.cli import common, serve as serve_cli
    args = serve_cli.build_parser().parse_args(
        ["transformer_lm", "--randomInit", "--vocabSize", "50",
         "--dModel", "32", "--numLayers", "2", "--numHeads", "2",
         "--seq", "64", "--slots", "2", "--buckets", "1,2",
         "--maxWaitMs", "2", "--strategy", "dp:2+tp:2",
         "--lint=strict"])
    common.apply_platform(args)
    app, eng, in_shape, in_dtype = serve_cli.build_app(args)
    try:
        page = app.metrics.render()
        prov = _json.loads(
            [l for l in page.splitlines()
             if l.startswith("# provenance ")][0][len("# provenance "):])
        assert prov["lint_mesh"] == "model:2 x 2 replica(s)"
        assert prov["strategy"] == "dp:2+tp:2"
    finally:
        app.close()
