"""MoE: dense mode = reference MixtureTable parity; sparse top-k routing
and expert parallelism are new TPU-first capabilities (SURVEY.md §2.7:
"Expert parallel / MoE — NO" in the reference)."""

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import nn
from bigdl_tpu.core import Sequential
from bigdl_tpu.parallel import make_mesh


def _expert(d=8, h=16):
    return Sequential(nn.Linear(d, h), nn.ReLU(), nn.Linear(h, d))


def test_dense_mode_matches_manual_blend(rng):
    """dense=True == softmax-gated blend of every expert (MixtureTable)."""
    moe = nn.MoE(_expert(), num_experts=4, d_model=8, dense=True)
    params = moe.init(rng)
    x = jnp.asarray(np.random.RandomState(0).randn(6, 8), jnp.float32)
    y, _ = moe.apply(params, moe.init_state(), x)

    probs = jax.nn.softmax(x @ params["gate"], axis=-1)
    outs = []
    for i in range(4):
        pb = jax.tree_util.tree_map(lambda a: a[i], params["experts"])
        outs.append(moe.expert.forward(pb, x))
    manual = sum(probs[:, i:i + 1] * outs[i] for i in range(4))
    np.testing.assert_allclose(np.asarray(y), np.asarray(manual), atol=1e-5)


def test_sparse_top1_matches_selected_expert(rng):
    """Top-1 with ample capacity: each token's output is its argmax
    expert's output scaled by the RAW gate probability (Switch style —
    keeps the router differentiable wrt the task loss)."""
    moe = nn.MoE(_expert(), num_experts=4, d_model=8, top_k=1,
                 capacity_factor=4.0)
    params = moe.init(rng)
    x = jnp.asarray(np.random.RandomState(1).randn(10, 8), jnp.float32)
    y, st = moe.apply(params, moe.init_state(), x)

    probs = jax.nn.softmax(x @ params["gate"], axis=-1)
    pick = np.asarray(jnp.argmax(probs, -1))
    for t in range(10):
        pb = jax.tree_util.tree_map(lambda a: a[pick[t]], params["experts"])
        want = probs[t, pick[t]] * moe.expert.forward(pb, x[t:t + 1])[0]
        np.testing.assert_allclose(np.asarray(y[t]), np.asarray(want),
                                   atol=1e-5)
    assert float(st["aux_loss"]) > 0.0
    # router must get task-loss gradient through the raw probability
    g = jax.grad(lambda p: moe.apply(p, moe.init_state(), x)[0].sum())(params)
    assert float(jnp.abs(g["gate"]).max()) > 0.0


def test_capacity_drops_overflow_tokens(rng):
    """cap=1 per expert: overflowing tokens come out as zeros (residual
    passthrough is the enclosing block's job)."""
    moe = nn.MoE(_expert(), num_experts=2, d_model=8, top_k=1,
                 capacity_factor=0.125)  # cap = 16*1/2*0.125 = 1
    params = moe.init(rng)
    x = jnp.asarray(np.random.RandomState(2).randn(16, 8), jnp.float32)
    y, _ = moe.apply(params, moe.init_state(), x)
    zero_rows = np.sum(np.all(np.abs(np.asarray(y)) < 1e-12, axis=-1))
    assert zero_rows >= 14, f"expected >=14 dropped tokens, got {zero_rows}"


def test_moe_3d_input_shape(rng):
    moe = nn.MoE(_expert(), num_experts=4, d_model=8, top_k=2,
                 capacity_factor=2.0)
    params = moe.init(rng)
    x = jnp.asarray(np.random.RandomState(3).randn(2, 5, 8), jnp.float32)
    y, _ = moe.apply(params, moe.init_state(), x)
    assert y.shape == (2, 5, 8)


def test_optimizer_applies_aux_loss(rng):
    """Training an MoE model through Optimizer includes the load-balance
    aux loss (ADVICE r1: previously only hand-written steps added it) —
    the gate must receive a gradient contribution from balancing."""
    from bigdl_tpu.dataset import BatchDataSet
    from bigdl_tpu.optim import Optimizer, SGD, Trigger

    rs = np.random.RandomState(0)
    x = rs.randn(32, 8).astype(np.float32)
    y = rs.randint(0, 4, 32).astype(np.int32)
    model = Sequential(
        nn.MoE(_expert(), num_experts=4, d_model=8, top_k=1,
               capacity_factor=4.0),
        nn.Linear(8, 4), nn.LogSoftMax())
    crit = nn.ClassNLLCriterion()

    def run(aux_w):
        ds = BatchDataSet(x, y, batch_size=32, shuffle=False)
        opt = Optimizer(model, ds, crit,
                        optim_method=SGD(learning_rate=0.1),
                        end_when=Trigger.max_iteration(3), seed=3,
                        aux_loss_weight=aux_w)
        return jax.device_get(opt.optimize().params)

    p_on, p_off = run(1.0), run(0.0)
    gate_on = np.asarray(p_on["0"]["gate"])
    # weights must differ when the aux loss participates
    diffs = [np.abs(np.asarray(a) - np.asarray(b)).max()
             for a, b in zip(jax.tree_util.tree_leaves(p_on),
                             jax.tree_util.tree_leaves(p_off))]
    assert max(diffs) > 1e-7
    assert np.all(np.isfinite(gate_on))


def test_expert_parallel_matches_unsharded(rng):
    """Experts sharded over an `expert` mesh axis under jit == unsharded
    (XLA inserts the dispatch all-to-all)."""
    mesh = make_mesh({"expert": 8})
    moe = nn.MoE(_expert(), num_experts=8, d_model=8, top_k=2,
                 capacity_factor=2.0)
    params = moe.init(rng)
    x = jnp.asarray(np.random.RandomState(4).randn(4, 6, 8), jnp.float32)
    y_ref, _ = moe.apply(params, moe.init_state(), x)

    sharded = moe.place_expert_parallel(mesh, params)

    @jax.jit
    def fwd(p, xs):
        y, st = moe.apply(p, moe.init_state(), xs)
        return y, st["aux_loss"]

    y_ep, aux = fwd(sharded, x)
    np.testing.assert_allclose(np.asarray(y_ep), np.asarray(y_ref),
                               atol=1e-4)
    # grads flow through routing to the sharded experts
    g = jax.grad(lambda p: fwd(p, x)[0].sum())(sharded)
    assert all(np.all(np.isfinite(np.asarray(l)))
               for l in jax.tree_util.tree_leaves(g))
