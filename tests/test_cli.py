"""CLI smoke tests: the Train/Test mains run end-to-end on tiny synthetic
datasets written in the reference's on-disk formats (idx-ubyte MNIST,
CIFAR bins, input.txt — reference models/*/Train.scala pipelines)."""

import gzip
import json
import os
import struct
import sys

import numpy as np
import pytest


def _write_mnist(folder, n=64, seed=0):
    os.makedirs(folder, exist_ok=True)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, n).astype(np.uint8)
    images = (labels[:, None, None] * 20
              + rng.randint(0, 30, (n, 28, 28))).astype(np.uint8)
    for stem, count in [("train", n), ("t10k", n)]:
        with open(os.path.join(folder, f"{stem}-images-idx3-ubyte"),
                  "wb") as f:
            f.write(struct.pack(">IIII", 2051, count, 28, 28))
            f.write(images[:count].tobytes())
        with open(os.path.join(folder, f"{stem}-labels-idx1-ubyte"),
                  "wb") as f:
            f.write(struct.pack(">II", 2049, count))
            f.write(labels[:count].tobytes())
    return images, labels


def _write_cifar(folder, n_train=48, n_test=16, seed=0):
    os.makedirs(folder, exist_ok=True)
    rng = np.random.RandomState(seed)

    def write(path, count):
        with open(path, "wb") as f:
            for _ in range(count):
                lab = rng.randint(0, 10)
                img = (np.full((3, 32, 32), lab * 20, np.uint8)
                       + rng.randint(0, 20, (3, 32, 32)).astype(np.uint8))
                f.write(bytes([lab]))
                f.write(img.tobytes())

    per = max(1, n_train // 5)
    for i in range(1, 6):
        write(os.path.join(folder, f"data_batch_{i}.bin"), per)
    write(os.path.join(folder, "test_batch.bin"), n_test)


def test_lenet_train_and_test(tmp_path, capsys):
    from bigdl_tpu.cli import lenet

    data = str(tmp_path / "mnist")
    ckpt = str(tmp_path / "ckpt")
    _write_mnist(data)
    trained = lenet.main(["train", "-f", data, "-b", "16", "--maxEpoch", "6",
                          "--learningRate", "0.1", "--checkpoint", ckpt,
                          "--logEvery", "100"])
    assert trained is not None
    assert any(f.startswith("model.") for f in os.listdir(ckpt))
    results = lenet.main(["test", "-f", data, "-b", "16", "--model", ckpt])
    acc, _count = results[0].result()
    assert acc > 0.3  # tiny synthetic set, 2 epochs — just needs learning


def test_vgg_cli_parses_and_runs_one_epoch(tmp_path):
    from bigdl_tpu.cli import vgg

    data = str(tmp_path / "cifar")
    _write_cifar(data)
    trained = vgg.main(["train", "-f", data, "-b", "8", "--maxEpoch", "1",
                        "--logEvery", "100"])
    assert trained is not None


def test_autoencoder_cli(tmp_path):
    from bigdl_tpu.cli import autoencoder

    data = str(tmp_path / "mnist")
    _write_mnist(data)
    trained = autoencoder.main(["train", "-f", data, "-b", "16",
                                "--maxEpoch", "1", "--adagrad",
                                "--learningRate", "0.01",
                                "--logEvery", "100"])
    assert trained is not None


def test_rnn_cli(tmp_path, capsys):
    from bigdl_tpu.cli import rnn

    data = tmp_path / "text"
    data.mkdir()
    words = ["the", "quick", "brown", "fox", "jumps", "over", "lazy", "dog"]
    (data / "input.txt").write_text(" ".join(words * 50))
    trained = rnn.main(["train", "-f", str(data), "-b", "16",
                        "--maxEpoch", "2", "--seqLength", "5",
                        "--hiddenSize", "16", "--learningRate", "0.5",
                        "--logEvery", "100"])
    assert trained is not None
    out = capsys.readouterr().out
    assert "perplexity is" in out


def test_perf_harness_lenet(capsys):
    from bigdl_tpu.cli import perf

    out = perf.run("lenet5", batch=8, iterations=2, data_type="random",
                   use_bf16=False)
    assert out["records_per_second"] > 0
    printed = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(printed)
    assert parsed["model"] == "lenet5"
    assert parsed["images_per_second_per_chip"] > 0


def test_capture_scripts_reference_valid_perf_models():
    """A typo'd -m in the capture sweeps would waste a tunnel window; pin
    every referenced model to the perf build table."""
    import re

    from bigdl_tpu.cli.perf import build_model

    import glob as _glob

    names = set()
    scripts = sorted(_glob.glob(os.path.join(
        os.path.dirname(__file__), "..", "scripts", "tpu_capture*.sh")))
    assert len(scripts) >= 2
    for script in scripts:
        for line in open(script):
            m = re.search(r"cli\.perf -m (\S+)", line)
            if m:
                names.add(m.group(1))
    assert names, "no perf invocations found in capture scripts"
    for n in names:
        build_model(n, 10)  # raises SystemExit on unknown names


def test_resnet_cli_cifar_fused_bn(tmp_path):
    """--fusedBN on the real training CLI (VERDICT r4 item 3): one epoch
    on synthetic CIFAR runs end-to-end with the Pallas BN stats path."""
    from bigdl_tpu.cli import resnet

    data = str(tmp_path / "cifar")
    _write_cifar(data)
    trained = resnet.main(["train", "-f", data, "-b", "8", "--maxEpoch",
                           "1", "--depth", "8", "--fusedBN",
                           "--logEvery", "100"])
    assert trained is not None


def test_resnet_cli_cifar_fused_bn_apply(tmp_path):
    """--fusedBN apply (ISSUE 2): the FULL fused BN block (stats+apply+
    absorbed-ReLU fwd, reductions+dx bwd) reachable end-to-end on the
    real training CLI."""
    from bigdl_tpu.cli import resnet

    data = str(tmp_path / "cifar")
    _write_cifar(data)
    trained = resnet.main(["train", "-f", data, "-b", "8", "--maxEpoch",
                           "1", "--depth", "8", "--fusedBN", "apply",
                           "--logEvery", "100"])
    assert trained is not None
    from bigdl_tpu.nn.norm import bn_fused_mode
    assert bn_fused_mode(trained.module) == "apply"


def test_resnet_cli_imagenet_s2d(tmp_path):
    """--dataset imagenet --s2d: space-to-depth stem on the training CLI,
    one epoch over a tiny label-by-folder image tree."""
    from PIL import Image

    from bigdl_tpu.cli import resnet

    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        d = tmp_path / "train" / cls
        d.mkdir(parents=True)
        for i in range(4):
            Image.fromarray(rng.randint(0, 255, (64, 64, 3), np.uint8)
                            ).save(d / f"{i}.jpg")
    trained = resnet.main(["train", "-f", str(tmp_path), "-b", "4",
                           "--dataset", "imagenet", "--depth", "18",
                           "--classNum", "2", "--maxEpoch", "1",
                           "--s2d", "--fusedBN", "--logEvery", "100"])
    assert trained is not None


def test_resnet_cli_s2d_rejected_on_cifar(tmp_path):
    from bigdl_tpu.cli import resnet

    with pytest.raises(SystemExit, match="imagenet"):
        resnet.main(["train", "-f", str(tmp_path), "--s2d"])


def test_resnet_cli_depth_validation(tmp_path):
    from bigdl_tpu.cli import resnet

    with pytest.raises(SystemExit, match="invalid for imagenet"):
        resnet.main(["train", "-f", str(tmp_path), "--dataset", "imagenet",
                     "--depth", "20"])
    with pytest.raises(SystemExit, match="invalid for cifar10"):
        resnet.main(["train", "-f", str(tmp_path), "--depth", "21"])
