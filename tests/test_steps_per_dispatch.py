"""steps_per_dispatch: K optimizer steps scanned inside one jitted
program (dispatch amortization for the tunneled single-chip runtime,
PERF.md §8.2 — the real-training counterpart of perf's --innerSteps).
Contract under test: update math and host RNG sequence are identical to
K=1, ragged tails fall back to single-step dispatch, iteration-counted
triggers fire at chunk boundaries (crossing semantics), and the option
refuses to combine with a distributed strategy."""

import numpy as np
import pytest

from bigdl_tpu import nn
from bigdl_tpu.core import Sequential
from bigdl_tpu.dataset import BatchDataSet
from bigdl_tpu.optim import Optimizer, SGD, Trigger


def _data(n=96, d=6, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d, classes).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.randn(n, classes), 1).astype(np.int32)
    return x, y


def _model():
    return Sequential(nn.Linear(6, 16), nn.Tanh(), nn.Linear(16, 3),
                      nn.LogSoftMax())


def _train(k, epochs=3, batch=16, n=96, dropout=False):
    x, y = _data(n=n)
    ds = BatchDataSet(x, y, batch_size=batch, shuffle=False)
    model = (Sequential(nn.Linear(6, 16), nn.Tanh(), nn.Dropout(0.25),
                        nn.Linear(16, 3), nn.LogSoftMax())
             if dropout else _model())
    opt = Optimizer(model, ds, nn.ClassNLLCriterion(),
                    optim_method=SGD(learning_rate=0.2, momentum=0.9),
                    end_when=Trigger.max_epoch(epochs), seed=7,
                    log_every=100, steps_per_dispatch=k)
    return opt.optimize()


@pytest.mark.parametrize("k", [2, 3])
def test_chunked_matches_single_dispatch(k):
    """Same data order, same seed: final params must match K=1 within
    float tolerance (the scan runs the very same traced step)."""
    ref = _train(1)
    got = _train(k)
    for (pa, a), (pb, b) in zip(jax_leaves(ref.params),
                                jax_leaves(got.params)):
        assert pa == pb
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5,
                                   err_msg=f"leaf {pa} diverged at K={k}")


def jax_leaves(tree):
    import jax

    return [(jax.tree_util.keystr(kp), l) for kp, l in
            jax.tree_util.tree_leaves_with_path(tree)]


def test_rng_sequence_identical_with_dropout():
    """Dropout consumes the per-step rng: identical final params across
    K proves the chunked path replays the exact host key sequence."""
    ref = _train(1, dropout=True)
    got = _train(2, dropout=True)
    for (pa, a), (pb, b) in zip(jax_leaves(ref.params),
                                jax_leaves(got.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5, err_msg=pa)


def test_ragged_tail_single_steps():
    """96 samples / batch 16 = 6 batches; K=4 -> one 4-chunk + 2 singles
    per epoch. All 6 iterations/epoch must happen (counter exact)."""
    x, y = _data(n=96)
    ds = BatchDataSet(x, y, batch_size=16, shuffle=False)
    opt = Optimizer(_model(), ds, nn.ClassNLLCriterion(),
                    optim_method=SGD(learning_rate=0.1),
                    end_when=Trigger.max_epoch(2), steps_per_dispatch=4,
                    log_every=100)
    opt.optimize()
    # driver state is internal; iterations surface via the summary hook —
    # use max_iteration stop instead to pin the counter
    opt2 = Optimizer(_model(), ds, nn.ClassNLLCriterion(),
                     optim_method=SGD(learning_rate=0.1),
                     end_when=Trigger.max_iteration(9),
                     steps_per_dispatch=4, log_every=100)
    trained = opt2.optimize()
    assert trained is not None


def test_several_iteration_crossing_semantics():
    t = Trigger.several_iteration(3)
    # K=1 behavior: fires exactly on multiples of 3
    assert not t({"iteration": 2, "prev_iteration": 1})
    assert t({"iteration": 3, "prev_iteration": 2})
    assert not t({"iteration": 4, "prev_iteration": 3})
    # chunked: counter jumps 2 -> 4 crossing 3 fires; 4 -> 6 fires
    assert t({"iteration": 4, "prev_iteration": 2})
    assert t({"iteration": 6, "prev_iteration": 4})
    # a jump with no multiple inside does not fire
    assert not t({"iteration": 2, "prev_iteration": 0})
    # without prev_iteration (external drivers): modulo fallback
    assert t({"iteration": 6})
    assert not t({"iteration": 5})


def test_validation_fires_under_chunking(tmp_path):
    """several_iteration(3) validation with K=2 over 12 iters/epoch must
    fire at the chunk boundaries covering 3,6,9,12 -> 4 val rows/epoch
    worth of summary entries (crossing semantics, never skipped)."""
    import json
    import os

    x, y = _data(n=96)
    ds = BatchDataSet(x, y, batch_size=16, shuffle=False)
    opt = Optimizer(_model(), ds, nn.ClassNLLCriterion(),
                    optim_method=SGD(learning_rate=0.1),
                    end_when=Trigger.max_epoch(1), steps_per_dispatch=2,
                    log_every=100)
    from bigdl_tpu.optim import Top1Accuracy
    opt.set_validation(Trigger.several_iteration(3),
                       BatchDataSet(x, y, 32), [Top1Accuracy()])
    opt.set_summary(str(tmp_path))
    opt.optimize()
    with open(os.path.join(tmp_path, "val.jsonl")) as f:
        its = sorted(json.loads(l)["iteration"] for l in f if l.strip())
    # 6 iterations/epoch at K=2 -> dispatch boundaries 2,4,6; crossings
    # of multiples of 3 happen at 4 (covers 3) and 6 -> exactly 2 fires
    assert its == [4, 6], its


def test_strategy_combination_rejected():
    class FakeStrategy:
        pass

    x, y = _data()
    ds = BatchDataSet(x, y, batch_size=16)
    with pytest.raises(ValueError, match="single-device"):
        Optimizer(_model(), ds, nn.ClassNLLCriterion(),
                  strategy=FakeStrategy(), steps_per_dispatch=2)
    with pytest.raises(ValueError, match=">= 1"):
        Optimizer(_model(), ds, nn.ClassNLLCriterion(),
                  steps_per_dispatch=0)
